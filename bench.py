"""Headline benchmark: LoRA SFT training throughput, tokens/sec/chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": N, ...}

The reference (`acceleratedscience/finetune-controller`) publishes **no**
performance numbers (BASELINE.json: "published": {}) — it is a k8s control
plane whose training throughput belongs to user containers.  The baseline is
therefore self-established: ``vs_baseline`` is measured throughput divided by
a roofline-derived target for the benchmark hardware (40% MFU on the model's
6*N FLOPs/token), so >1.0 means we beat the target, and the number stays
comparable across rounds.

Measurement discipline (round-2 rework):
  * the timed window is bounded by ``jax.block_until_ready`` on the FULL
    final state (not just a loss scalar), so async dispatch / lazy runtimes
    cannot make steps appear free — every step's device work must complete
    inside the window.  Steps are NOT individually blocked: per-step blocking
    would serialize host dispatch against the device and undercount the
    host/device overlap real training gets (measured ~87 ms/step on the
    TinyLlama config).  Per-step spread is still reported from a separate
    individually-blocked probe window so stragglers stay visible;
  * achieved MFU is computed and the bench REFUSES to print a number when
    MFU > 1.0 — an impossible figure is a measurement bug, not a result;
  * the timed window's losses must be finite and must not regress above the
    warmup loss (the step must be doing real optimization work);
  * throughput is the timed window's token count over its wall time; the
    probe window's p10/p90 per-step times are reported alongside.

Env knobs: BENCH_PRESET, BENCH_STEPS, BENCH_BATCH, BENCH_SEQ, BENCH_TINY=1
(CI-sized run), BENCH_MODE=qlora (int4 config #3), BENCH_REMAT_POLICY,
BENCH_ATTN_IMPL, BENCH_FROZEN_DTYPE, BENCH_LOGITS_DTYPE (perf experiments),
BENCH_RECOMPILE_BUDGET (distinct jit signatures allowed before the run is
declared a measurement bug and aborted — analysis/recompile_guard.py; 0 off),
BENCH_TRANSFER_GUARD (default on: the trainer step and serve decode hot
windows run under FTC_TRANSFER_GUARD=raise — analysis/transfer_guard.py — so
a reintroduced device<->host sync ABORTS the timed window; 0 disables).

Input-pipeline knobs (round 6): BENCH_PREFETCH (background prefetch depth
for the batch stream, default 2; 0 = synchronous host build on the timing
thread) and BENCH_PREFETCH_AB (default on in BENCH_MODE=mm: run a prefetch
off/on A/B over REAL decoded images — a generated on-disk jsonl of PNGs fed
through data/mm_loader.py with the pixel cache disabled — and attach the
per-leg step time + input_fraction under "prefetch_ab"). Every bench JSON now
carries "input_fraction": the share of the timed window the training thread
spent WAITING on its next batch — the number that catches an input-bound
config that raw tokens/sec would hide.

Serving knobs (BENCH_MODE=serve): BENCH_SERVE_REQUESTS, BENCH_SERVE_NEW_TOKENS,
BENCH_SERVE_SLOTS, and — for the prefix-reuse A/B (ISSUE 6, gated) —
BENCH_SERVE_PREFIX_LEN (shared system-prompt length, default 240) and
BENCH_SERVE_PREFIX_CACHE_MB (snapshot budget, default 64).  Paged-KV +
multi-tenant gates (ISSUE 11): BENCH_SERVE_PAGED (1 = run the paged A/B;
default on), BENCH_SERVE_PAGE_TOKENS (page size, default 16) and
BENCH_SERVE_ADAPTERS (multiplexed tenants, default 4) — gated on >= 2x
concurrent lanes at a fixed KV byte budget, >= 0.9x mixed-workload tok/s at
equal concurrency (bit-identical outputs), and multiplexed-vs-dedicated
bit-identity across adapters.  Cross-process transport gates (ISSUE 12):
BENCH_SERVE_TRANSPORT (1 = run the process-mode A/B; default on),
BENCH_SERVE_CONC (concurrent mixed-length requests, floor 64),
BENCH_SERVE_TRANSPORT_WORKERS / _SLOTS — gated (multi-core hosts) on
process-mode N-worker throughput >= 1.5x one worker, beating the
in-process contention baseline, and the 64+-concurrent p95 latency
fair-share bound.

Observability knobs (BENCH_MODE=obs, gated <2% overhead): BENCH_OBS_STEPS,
BENCH_OBS_ROUNDS, BENCH_BATCH, BENCH_SEQ (docs/observability.md).
"""

from __future__ import annotations

import json
import os
import sys
import time


# Peak bf16 TFLOP/s per chip, by jax device_kind substring (public specs).
PEAK_TFLOPS = [
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]
TARGET_MFU = 0.40
CPU_FALLBACK_TARGET_TOKENS_PER_SEC = 2000.0  # tiny model on one CPU host


def _peak_tflops(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for key, tflops in PEAK_TFLOPS:
        if key in kind:
            return tflops
    return None


BEST_KNOWN_PEAK_TFLOPS = max(t for _, t in PEAK_TFLOPS)


def _jsonable(x):
    """Make a diagnostic value RFC-JSON safe (NaN/Inf become strings)."""
    import math

    if isinstance(x, float) and not math.isfinite(x):
        return repr(x)
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    return x


def fail(reason: str, **diag) -> None:
    """Refuse to emit a benchmark number; print a diagnostic and exit 1."""
    safe = {k: _jsonable(v) for k, v in diag.items()}
    print(json.dumps({"bench_error": reason, **safe}), file=sys.stderr)
    sys.exit(1)


PROBE_CACHE = f"/tmp/ftc_tpu_probe_verdict_{os.getuid()}.json"  # per-user
PROBE_CACHE_TTL_S = 900.0  # one driver/bench session, not forever

# Committed raw-measurement log (scripts/tpu_session.py appends here too).
SESSION_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tpu_session.jsonl")


def _latest_session_tpu_record(kind_prefix: str) -> dict | None:
    """Latest committed real-TPU bench record from tpu_session.jsonl.

    Used when the live probe fails (tunnel outage): the round artifact then
    carries the most recent chip-measured headline alongside the honest CPU
    fallback instead of looking like a perf regression.  Prefers the newest
    record whose metric matches the requested bench kind (``lora_``,
    ``qlora_`` …); returns None when no same-kind record exists — a cached
    headline of a DIFFERENT kind would misattribute the number to automated
    consumers reading only value/vs_baseline.
    """
    def is_default_config(rec: dict) -> bool:
        # the session script's headline steps, or an ad-hoc run with no
        # shape/preset overrides — i.e. the config a plain `python bench.py`
        # (what the driver runs) would measure, as opposed to supplementary
        # rows like long-context seq-8192
        if "headline" in str(rec.get("step", "")):
            return True
        env = rec.get("env") or {}
        return not any(k in env for k in
                       ("BENCH_PRESET", "BENCH_SEQ", "BENCH_BATCH"))

    best_kind = best_default = None
    try:
        with open(SESSION_LOG) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (rec.get("error") or rec.get("fallback")
                        or not rec.get("metric")
                        or "tpu" not in str(rec.get("device_kind", "")).lower()):
                    continue
                # file is append-ordered: last matching record wins
                if str(rec["metric"]).startswith(kind_prefix):
                    best_kind = rec
                    if is_default_config(rec):
                        best_default = rec
    except OSError:
        return None
    rec = best_default or best_kind
    if rec is None:
        return None
    keep = ("ts", "step", "metric", "value", "unit", "vs_baseline", "mfu",
            "step_time_avg_s", "n_chips", "device_kind", "env")
    return {k: rec[k] for k in keep if k in rec}


def _session_log_append(record: dict) -> None:
    """Append a real-TPU measurement to the committed session log.

    Every chip-measured bench number must exist as a raw record, however the
    bench was invoked (driver, scripts/tpu_session.py, or an ad-hoc
    ``BENCH_MODE=... python bench.py``) — numbers living only in BASELINE.md
    prose have no provenance.  Disable with BENCH_SESSION_LOG=0 (the session
    script does: it writes its own step-named records).
    """
    from finetune_controller_tpu.platform import env_flag

    if not env_flag("BENCH_SESSION_LOG", default=True):
        return
    env = {k: v for k, v in os.environ.items()
           if k.startswith(("BENCH_", "FTC_")) and k != "BENCH_SESSION_LOG"}
    rec = {"ts": round(time.time(), 1), "step": "adhoc_bench", "env": env,
           **record}
    try:
        with open(SESSION_LOG, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError as e:
        print(f"session-log append failed: {e}", file=sys.stderr)


def _cached_probe_failure() -> bool:
    """Only FAILURE verdicts are cached: a cached success would let the
    in-process backend init run unprobed and hang if the tunnel died in the
    meantime — the exact hang the bounded probe exists to prevent."""
    try:
        with open(PROBE_CACHE) as f:
            rec = json.load(f)
        return (
            rec["ok"] is False
            and time.time() - float(rec["ts"]) < PROBE_CACHE_TTL_S
        )
    except Exception:
        return False


def _store_probe_failure() -> None:
    try:
        with open(PROBE_CACHE, "w") as f:
            json.dump({"ok": False, "ts": time.time()}, f)
    except OSError:
        pass


def _init_backend_with_fallback() -> None:
    """Initialise JAX; if the TPU backend is unreachable (e.g. a remote-TPU
    tunnel outage), re-exec onto the CPU backend so the bench still emits an
    honest (clearly ``"fallback": true``-labelled) number instead of crashing
    the harness.  One bounded probe attempt, verdict cached on disk for the
    session — round 2 burned 12+ minutes on 3×240 s retries before falling
    back, which is worse for the harness than an immediate honest fallback."""
    if os.environ.get("BENCH_NO_CPU_FALLBACK"):
        return  # fallback leg (or probing disabled): init happens in main()
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return  # already pinned to CPU — nothing to probe
    if not _cached_probe_failure():
        import subprocess

        probe = (
            "import os, jax\n"
            "if os.environ.get('JAX_PLATFORMS'):\n"
            "    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])\n"
            "assert jax.devices()[0].platform == 'tpu'\n"
        )
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "75"))
        try:
            subprocess.run(
                [sys.executable, "-c", probe],
                timeout=timeout_s, check=True,
                stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
            )
            return  # backend reachable; init in-process will succeed too
        except (subprocess.TimeoutExpired, subprocess.CalledProcessError) as e:
            # a dead remote-TPU tunnel can HANG init, not just fail it — the
            # subprocess probe bounds that. Surface the probe's stderr so a
            # genuine install error (version mismatch etc.) isn't masked by
            # the CPU fallback's success-looking output.
            detail = (e.stderr or b"") if hasattr(e, "stderr") else ""
            if isinstance(detail, bytes):
                detail = detail.decode(errors="replace")
            tail = "\n".join(str(detail).strip().splitlines()[-5:])
            print(f"backend probe failed: {e}\n{tail}", file=sys.stderr)
            _store_probe_failure()
    print("TPU backend unavailable; re-exec on CPU fallback", file=sys.stderr)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_TINY"] = "1"
    env["BENCH_NO_CPU_FALLBACK"] = "1"
    env["BENCH_IS_FALLBACK"] = "1"
    # the fallback leg always runs the tiny lora config, but the session-cache
    # comparator should match the bench the user ASKED for — carry the
    # requested kind across the re-exec before BENCH_MODE is popped
    mode = os.environ.get("BENCH_MODE", "lora").strip().lower()
    env["BENCH_FALLBACK_KIND"] = {
        "qlora": "qlora", "mm": "mm_lora", "moe": "moe_lora"
    }.get(mode, "lora")
    # TPU-sized knobs must not leak into the tiny CPU leg
    for knob in (
        "BENCH_PRESET", "BENCH_SEQ", "BENCH_BATCH", "BENCH_STEPS",
        "BENCH_MODE", "BENCH_REMAT_POLICY", "BENCH_FROZEN_DTYPE",
        "BENCH_ATTN_IMPL", "BENCH_LOGITS_DTYPE",
    ):
        env.pop(knob, None)
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def _write_mm_bench_dataset(dir_path: str, n_rows: int, src_px: int) -> str:
    """Write an image-bearing jsonl of REAL encoded images (PNG via PIL when
    available, ``.npy`` otherwise) so the mm input A/B measures genuine
    per-batch decode+resize host work, not synthetic in-memory arrays."""
    import numpy as np

    rng = np.random.default_rng(0)
    path = os.path.join(dir_path, "mm_bench.jsonl")
    with open(path, "w") as f:
        for i in range(n_rows):
            arr = rng.integers(0, 256, (src_px, src_px, 3)).astype("uint8")
            try:
                from PIL import Image

                name = f"img_{i:03d}.png"
                Image.fromarray(arr).save(os.path.join(dir_path, name))
            except ImportError:
                name = f"img_{i:03d}.npy"
                np.save(os.path.join(dir_path, name), arr)
            f.write(json.dumps({
                "image": name,
                "prompt": f"describe image {i}: ",
                "completion": "a square of colored noise",
            }) + "\n")
    return path


def measure_mm_prefetch_ab(
    trainer, state, dataset_path: str, *,
    image_size: int, batch: int, seq: int,
    steps: int = 8, depth: int = 2, warmup: int = 2,
):
    """Prefetch off/on A/B over the real multimodal loader (pixel cache
    disabled, so every batch pays its decode+resize — the steady-state cost
    of any epoch past the cache cap).

    Steps are individually blocked so each leg's step time is deterministic;
    the device wait releases the GIL, which is exactly the window the
    prefetch producer uses to build (and device_put) the next batch.
    Per-leg step time is the MEDIAN over the timed steps (host-side decode
    timing on a shared box is long-tailed; a mean would let one scheduler
    hiccup decide the A/B), while input_fraction keeps the honest totals.
    Returns ``(state, legs)`` where legs carries per-leg step time,
    input wait, and input_fraction, plus the off/on speedup.
    """
    import jax
    import numpy as np

    from finetune_controller_tpu.data.mm_loader import mm_jsonl_batches
    from finetune_controller_tpu.data.prefetch import prefetch_batches

    legs: dict = {}
    for leg, leg_depth in (("off", 0), ("on", depth)):
        raw = mm_jsonl_batches(
            dataset_path, batch_size=batch, seq_len=seq,
            image_size=image_size, pixel_cache_size=0,
        )
        it = prefetch_batches(
            raw, depth=leg_depth,
            transfer=trainer._shard_batch if leg_depth else None,
        )
        try:
            for _ in range(warmup):
                state, _ = trainer.step(state, next(it))
                state = jax.block_until_ready(state)
            input_s = 0.0
            step_times = []
            t0 = time.perf_counter()
            for _ in range(steps):
                ts = time.perf_counter()
                b = next(it)
                input_s += time.perf_counter() - ts
                state, _ = trainer.step(state, b)
                state = jax.block_until_ready(state)
                step_times.append(time.perf_counter() - ts)
            total_s = time.perf_counter() - t0
        finally:
            if hasattr(it, "close"):
                it.close()
        legs[leg] = {
            "step_time_avg_s": round(float(np.median(step_times)), 4),
            "input_ms_avg": round(input_s / steps * 1000, 2),
            "input_fraction": round(input_s / total_s, 4),
        }
    legs["speedup"] = round(
        legs["off"]["step_time_avg_s"]
        / max(legs["on"]["step_time_avg_s"], 1e-9), 3,
    )
    return state, legs


def _measure_obs() -> dict:
    """BENCH_MODE=obs: the tracing-overhead gate (docs/observability.md).

    Runs the SAME tiny fit repeatedly over identical synthetic batches,
    alternating the obs layer off (``FTC_TRACE=0``) and on within each
    round — the phase clock, event log, span recorder, AND the
    histogram-observation path the monitor runs on every synced row (fed
    here through ``on_metrics``).  The gate: the FASTEST window step time
    with tracing on must stay within 2% of tracing off — external load
    only ever ADDS time, so the two floors compare the true per-step cost
    while means/medians would gate on the box's noise (a whole leg landing
    in a slow phase shifts every mid-distribution statistic).  Rounds
    alternate on/off order to cancel slow drift; one untimed warmup fit
    pays the jit compile for both legs (the trainer instance — and so the
    jit cache — is shared).

    Knobs: BENCH_OBS_STEPS (per leg, default 30), BENCH_OBS_ROUNDS
    (default 8), BENCH_BATCH, BENCH_SEQ.  Legs are SHORT and alternated so
    both arms sample every phase of the box's seconds-scale load drift —
    one long leg per arm lets a busy phase land entirely on one side.
    """
    import gc
    import shutil
    import tempfile

    import jax
    import numpy as np

    from finetune_controller_tpu.data.synthetic import synthetic_batches
    from finetune_controller_tpu.models.llama import PRESETS
    from finetune_controller_tpu.models.lora import LoRAConfig
    from finetune_controller_tpu.obs.prom import ObsHub
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    preset = os.environ.get("BENCH_PRESET", "tiny-test")
    steps = int(os.environ.get("BENCH_OBS_STEPS", "30"))
    rounds = int(os.environ.get("BENCH_OBS_ROUNDS", "8"))
    # steps sized to tens of ms: the obs layer's per-step cost is FIXED
    # (a few perf_counter calls + a throttled stat), so measuring against
    # a representative step length is both honest — real jobs' steps are
    # far longer than tiny-test's 3ms — and resolvable on a noisy shared
    # box, where scheduler jitter swamps a 2% effect at small steps
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    seq = int(os.environ.get("BENCH_SEQ", "128"))

    model_cfg = PRESETS[preset].replace(lora=LoRAConfig(rank=4))
    train_cfg = TrainConfig(
        mode="lora", learning_rate=1e-3, warmup_steps=2, total_steps=steps,
        batch_size=batch, seq_len=seq, log_every=10, checkpoint_every=10**9,
        prefetch=0, heartbeat_interval_s=0,
    )
    trainer = Trainer(model_cfg, train_cfg)
    hub = ObsHub()

    tokens_per_batch = batch * seq

    def leg(trace_on: bool) -> list:
        """One fit; returns the PER-WINDOW mean step seconds derived from
        each logged row's ``tokens_per_sec`` — measured inside the step
        loop, so the final blocking save and state init stay out of the
        sample, and a load spike poisons one window, not the whole leg.
        The on-leg also pays the monitor-side histogram observation per
        logged row, exactly like a live monitor would."""
        os.environ["FTC_TRACE"] = "1" if trace_on else "0"
        if trace_on:
            os.environ["FTC_TRACE_ID"] = "b" * 32
        windows: list = []

        def on_metrics(step, m):
            windows.append(tokens_per_batch / max(m["tokens_per_sec"], 1e-9))
            if trace_on:
                hub.observe_step_phases(m)

        d = tempfile.mkdtemp(prefix="ftc_obs_bench_")
        # even the GC slate between legs, then keep the collector out of
        # the timed windows: a cycle collection landing mid-window is
        # millisecond noise that hits whichever arm happens to cross the
        # allocation threshold — the allocations themselves (the real,
        # recurring cost of the obs layer) are still fully timed
        gc.collect()
        gc.disable()
        try:
            batches = synthetic_batches(
                batch, seq, model_cfg.vocab_size, task="increment"
            )
            trainer.fit(batches, d, resume=False, on_metrics=on_metrics)
            return windows
        finally:
            gc.enable()
            shutil.rmtree(d, ignore_errors=True)

    def measure() -> tuple:
        offs, ons = [], []
        for i in range(rounds):
            order = (False, True) if i % 2 == 0 else (True, False)
            for trace_on in order:
                (ons if trace_on else offs).extend(leg(trace_on))
        off_floor = float(np.min(offs))
        on_floor = float(np.min(ons))
        pct = (on_floor / max(off_floor, 1e-12) - 1.0) * 100.0
        return pct, off_floor, on_floor, len(offs)

    saved = {k: os.environ.get(k) for k in ("FTC_TRACE", "FTC_TRACE_ID")}
    attempts = []
    try:
        leg(False)  # untimed warmup: jit compile + state init caches
        # noise on a shared box only INFLATES a measurement, never deflates
        # it — so any attempt under the gate proves the true overhead is
        # under it, and best-of-3 keeps a load spike from failing the gate
        for _ in range(3):
            result = measure()
            attempts.append(round(result[0], 3))
            if result[0] < 2.0:
                break
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    overhead_pct, off_floor, on_floor, n_windows = result
    if overhead_pct >= 2.0:
        fail(
            "obs bench: tracing overhead breached the 2% step-time gate "
            "on all attempts",
            attempts=attempts,
            step_time_off_ms=round(off_floor * 1000, 4),
            step_time_on_ms=round(on_floor * 1000, 4),
            windows=n_windows,
        )
    if hub.step_phase_ms.count(phase="compute") == 0:
        fail("obs bench: the on-leg produced no phase histogram samples")
    return {
        "metric": f"obs_overhead_pct[{preset},bs{batch},seq{seq},"
                  f"steps{steps}x{rounds}]",
        "value": round(overhead_pct, 3),
        "unit": "% fastest window step time (tracing on vs FTC_TRACE=0)",
        "gate_pct": 2.0,
        "step_time_off_ms": round(off_floor * 1000, 4),
        "step_time_on_ms": round(on_floor * 1000, 4),
        "windows": n_windows,
        "attempts": attempts,
        "phase_samples": hub.step_phase_ms.count(phase="compute"),
        "device_kind": jax.devices()[0].device_kind,
    }


def _measure_chaos_recovery() -> dict:
    """BENCH_MODE=chaos: time the supervised-retry loop end to end.

    Runs a tiny job on the local backend, SIGTERM-kills it after its first
    committed checkpoint (backend restart budget zeroed so the CONTROLLER
    half — classify → backoff → resubmit-with-resume, docs/resilience.md —
    does the recovery), and reports the operator-facing latencies:

      detect_s    kill → the monitor classifies the failure (RETRYING)
      requeue_s   kill → the supervisor's resubmission hits the backend
      recover_s   kill → the respawned attempt reaches RUNNING
      total_s     submit → SUCCEEDED, both attempts included

    These are the production SLO numbers for a preemptible pool: how much
    wall clock one revocation costs beyond the backoff delay itself.
    """
    import asyncio
    import tempfile
    import time as _time
    from pathlib import Path

    from finetune_controller_tpu.controller.backends.local import LocalProcessBackend
    from finetune_controller_tpu.controller.examples import (
        LoRASFTArguments, TinyTestLoRA,
    )
    from finetune_controller_tpu.controller.monitor import JobMonitor
    from finetune_controller_tpu.controller.objectstore import LocalObjectStore
    from finetune_controller_tpu.controller.schemas import DatabaseStatus, JobInput
    from finetune_controller_tpu.controller.statestore import StateStore
    from finetune_controller_tpu.controller.task_builder import (
        DatasetInput, task_builder,
    )
    from finetune_controller_tpu.controller.devices import (
        DeviceCatalog, DeviceFlavor, FlavorQuota,
    )
    from finetune_controller_tpu.controller.registry import load_builtin_models
    from finetune_controller_tpu.resilience.policy import RetryPolicy
    from finetune_controller_tpu.resilience.supervisor import RetrySupervisor

    load_builtin_models()  # the supervisor rebuilds the spec from the registry

    steps = int(os.environ.get("BENCH_STEPS", "400"))
    ckpt_every = int(os.environ.get("BENCH_CKPT_EVERY", "50"))
    backoff_s = float(os.environ.get("BENCH_RETRY_BACKOFF", "0.2"))

    async def run(tmp: Path) -> dict:
        state = StateStore(tmp / "state")
        store = LocalObjectStore(tmp / "objects")
        catalog = DeviceCatalog(
            flavors=[DeviceFlavor(name="chip-1", generation="cpu", hosts=1,
                                  chips_per_host=1, runtime="cpu", queue="q")],
            quotas=[FlavorQuota(flavor="chip-1", nominal_chips=2)],
            default_flavor="chip-1",
        )
        backend = LocalProcessBackend(
            tmp / "sandboxes", store, catalog,
            sync_interval_s=0.2, backoff_limit=0,
        )
        supervisor = RetrySupervisor(
            state, backend, catalog,
            policy=RetryPolicy(max_attempts=3, base_delay_s=backoff_s,
                               max_delay_s=backoff_s, seed=0),
        )
        monitor = JobMonitor(state, store, backend, interval_s=0.1,
                             supervisor=supervisor)
        await state.connect()
        spec = TinyTestLoRA(training_arguments=LoRASFTArguments(
            total_steps=steps, warmup_steps=1, batch_size=2, seq_len=16,
            lora_rank=2, log_every=ckpt_every, checkpoint_every=ckpt_every,
        ))
        job = JobInput(job_id="chaos-bench-1", user_id="bench",
                       model_name="tiny-test-lora", device="chip-1",
                       arguments=spec.training_arguments.model_dump())
        t_submit = _time.perf_counter()
        await task_builder(
            job, spec, DatasetInput(),
            state=state, store=store, backend=backend, catalog=catalog,
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        import re as _re

        handle = backend._handles["chaos-bench-1"]
        ckpt_dir = handle.artifacts_dir / "checkpoints"
        deadline = _time.monotonic() + 300
        committed = _re.compile(r"^step_\d+$")  # NOT in-flight *-tmp staging

        def has_committed() -> bool:
            return ckpt_dir.is_dir() and any(
                committed.match(p.name) for p in ckpt_dir.iterdir()
            )

        while not has_committed():
            if _time.monotonic() > deadline:
                fail("chaos bench: no checkpoint appeared within 300s")
            await asyncio.sleep(0.1)
        assert await backend.inject_fault("chaos-bench-1", signum=15)
        t_kill = _time.perf_counter()
        t_detect = t_requeue = t_recover = None
        while True:
            await monitor.tick()
            now = _time.perf_counter()
            rec = await state.get_job("chaos-bench-1")
            if t_detect is None and rec.status is DatabaseStatus.RETRYING:
                t_detect = now
            if t_requeue is None and supervisor.resubmits > 0:
                t_requeue = now
            report = await backend.get_job("chaos-bench-1")
            if (t_recover is None and t_requeue is not None
                    and report is not None and report.state.value == "Running"):
                t_recover = now
            if rec.status.is_final:
                break
            if _time.monotonic() > deadline:
                fail("chaos bench: job not final within 300s", status=str(rec.status))
            await asyncio.sleep(0.05)
        t_done = _time.perf_counter()
        attempts = rec.metadata.get("attempt_history") or []
        if rec.status is not DatabaseStatus.SUCCEEDED:
            fail("chaos bench: job did not recover to SUCCEEDED",
                 status=str(rec.status), attempts=attempts)
        if len(attempts) != 1:
            fail("chaos bench: expected exactly one recorded kill",
                 attempts=attempts)
        out = {
            "metric": f"chaos_recovery[tiny-test,steps{steps},ckpt{ckpt_every}]",
            "value": round(t_recover - t_kill, 3) if t_recover else None,
            "unit": "s (kill -> respawned attempt RUNNING)",
            "detect_s": round(t_detect - t_kill, 3) if t_detect else None,
            "requeue_s": round(t_requeue - t_kill, 3) if t_requeue else None,
            "recover_s": round(t_recover - t_kill, 3) if t_recover else None,
            "total_s": round(t_done - t_submit, 3),
            "backoff_s": backoff_s,
            "failure_class": attempts[0]["failure_class"],
            "restored_checkpoints": (await state.get_job("chaos-bench-1"))
                .metadata.get("restored_checkpoints"),
        }
        await backend.close()
        await state.close()
        return out

    with tempfile.TemporaryDirectory(prefix="ftc_chaos_bench_") as d:
        return asyncio.run(run(Path(d)))


def _measure_sched() -> dict:
    """BENCH_MODE=sched: fair-share vs FIFO, and resize vs full eviction.

    Two gated comparisons on the deterministic simulator (pure control
    flow: no accelerator, milliseconds):

    1. **fair-share vs FIFO** on the canonical head-of-line-blocking trace
       (PR 5): small-job p95 wait and the Jain index must both improve.
    2. **resize vs full eviction** on the capacity-reclaim trace
       (``sched/sim.py::elastic_trace`` — a whole-cluster XL job loses
       chips to a high-priority reclaim + tenant stream): resize must
       strictly reduce chip-seconds-of-progress-lost (checkpoint replay +
       exit-grace overhead + demanded-but-idle capacity), with Jain no
       worse and small-job p95 wait within two exit graces of the evict
       leg (ISSUE 7).

    Knobs: BENCH_SCHED_SEED, BENCH_SCHED_CHIPS, BENCH_SCHED_BIG,
    BENCH_SCHED_SMALL, BENCH_SCHED_GROW_DELAY (virtual seconds the grow
    pass waits for tenant-quiet before restoring a shrunk job).
    """
    from finetune_controller_tpu.controller.backends.scheduler import (
        GangScheduler,
    )
    from finetune_controller_tpu.sched import FairShareScheduler
    from finetune_controller_tpu.sched.sim import (
        TRACE_QUEUES,
        ClusterSim,
        elastic_trace,
        percentile,
        sim_catalog,
        synthetic_trace,
    )

    seed = int(os.environ.get("BENCH_SCHED_SEED", "0"))
    chips = int(os.environ.get("BENCH_SCHED_CHIPS", "8"))
    n_big = int(os.environ.get("BENCH_SCHED_BIG", "4"))
    n_small = int(os.environ.get("BENCH_SCHED_SMALL", "24"))
    grow_delay = float(os.environ.get("BENCH_SCHED_GROW_DELAY", "5"))
    preempt_exit_s = 1.0
    catalog = sim_catalog(chips)
    trace = synthetic_trace(seed, n_big=n_big, n_small=n_small)
    reclaim_trace = elastic_trace(seed)

    def leg(factory, trace) -> tuple[dict, "object"]:
        # both legs score fairness against the SAME entitlements
        report = ClusterSim(
            catalog, factory, queue_weights=TRACE_QUEUES,
            preempt_exit_s=preempt_exit_s,
        ).run(trace)
        unfinished = [
            o.job_id for o in report.outcomes.values() if o.finish_s is None
        ]
        if unfinished:
            fail("sched bench: jobs never finished", unfinished=unfinished)
        waits = report.waits(max_chips=1)
        lat = report.preempt_resume_latencies_s
        out = {
            "makespan_s": round(report.makespan_s, 1),
            "jain_fairness": round(report.jain_fairness, 3),
            "preemptions": report.preemptions,
            "resizes": report.resizes,
            "small_job_wait_p50_s": round(percentile(waits, 50), 1),
            "small_job_wait_p95_s": round(percentile(waits, 95), 1),
            "preempt_readmit_p50_s": (
                round(percentile(lat, 50), 1) if lat else None
            ),
            "preempt_readmit_p95_s": (
                round(percentile(lat, 95), 1) if lat else None
            ),
            "progress_lost_chip_s": round(
                report.progress_lost_chip_seconds, 1
            ),
            "replay_lost_chip_s": round(report.replay_lost_chip_seconds, 1),
            "exit_overhead_chip_s": round(
                report.exit_overhead_chip_seconds, 1
            ),
            "idle_demand_chip_s": round(report.idle_demand_chip_seconds, 1),
        }
        # gating uses the RAW report: an improvement smaller than the
        # display rounding grain must still count as an improvement
        return out, report

    def p95(report) -> float:
        return percentile(report.waits(max_chips=1), 95)

    # -- gate 1: fair-share vs FIFO (PR 5, unchanged) -----------------------
    fifo, fifo_r = leg(lambda clock: GangScheduler(catalog), trace)
    fair, fair_r = leg(
        lambda clock: FairShareScheduler(catalog, TRACE_QUEUES, clock=clock),
        trace,
    )
    if p95(fair_r) >= p95(fifo_r):
        fail(
            "sched bench: fair-share did not reduce small-job p95 wait",
            fifo=fifo, fairshare=fair,
        )
    if fair_r.jain_fairness <= fifo_r.jain_fairness:
        fail(
            "sched bench: fair-share did not improve the Jain index",
            fifo=fifo, fairshare=fair,
        )

    # -- gate 2: resize vs full eviction (ISSUE 7) --------------------------
    evict, evict_r = leg(
        lambda clock: FairShareScheduler(
            catalog, TRACE_QUEUES, clock=clock, resize=False,
        ),
        reclaim_trace,
    )
    resize, resize_r = leg(
        lambda clock: FairShareScheduler(
            catalog, TRACE_QUEUES, clock=clock,
            resize=True, grow_delay_s=grow_delay,
        ),
        reclaim_trace,
    )
    if (resize_r.progress_lost_chip_seconds
            >= evict_r.progress_lost_chip_seconds):
        fail(
            "sched bench: resize did not reduce chip-seconds of progress "
            "lost vs full eviction",
            evict=evict, resize=resize,
        )
    if resize_r.jain_fairness < evict_r.jain_fairness:
        fail(
            "sched bench: resize regressed Jain fairness vs eviction",
            evict=evict, resize=resize,
        )
    if p95(resize_r) > p95(evict_r) + 2.0 * preempt_exit_s + 0.5:
        # resize may pay up to two extra exit graces on the wait tail
        # (shrink cascades free chips in smaller pieces); more is a
        # regression
        fail(
            "sched bench: resize regressed small-job p95 wait vs eviction",
            evict=evict, resize=resize,
        )
    if resize_r.resizes <= 0:
        fail("sched bench: the resize leg never resized", resize=resize)

    return {
        "metric": (
            f"sched_progress_lost_chip_s[chips{chips},seed{seed},"
            f"grow{grow_delay:g}]"
        ),
        "value": resize["progress_lost_chip_s"],
        "unit": "chip-seconds of progress lost (resize, reclaim trace)",
        "fifo": fifo,
        "fairshare": fair,
        "fairshare_evict": evict,
        "fairshare_resize": resize,
        "wait_p95_speedup_vs_fifo": round(
            fifo["small_job_wait_p95_s"]
            / max(fair["small_job_wait_p95_s"], 1e-9), 1,
        ),
        "jain_delta_vs_fifo": round(
            fair["jain_fairness"] - fifo["jain_fairness"], 3
        ),
        "progress_lost_reduction": round(
            1.0 - resize_r.progress_lost_chip_seconds
            / max(evict_r.progress_lost_chip_seconds, 1e-9), 3,
        ),
        "jain_delta_resize_vs_evict": round(
            resize["jain_fairness"] - evict["jain_fairness"], 3
        ),
        "queues": TRACE_QUEUES,
    }


def _measure_dpo() -> dict:
    """BENCH_MODE=dpo: the preference-optimization gates (ISSUE 8).

    Gated legs on the tiny CPU-runnable config (the third is ISSUE 19's):

    1. **DPO** — train on the seeded synthetic preference set
       (``data/preference.py``): the reward margin must STRICTLY increase
       over the run (last-quarter mean > first-quarter mean) and final DPO
       accuracy on HELD-OUT pairs (disjoint seed region) must reach >= 0.7.
    2. **Actor/learner smoke** — the rlhf loop (``prefs/learner.py``) over
       two checkpoint commits: the actor must generate from checkpoint N,
       the learner commit N+1, and the actor reload N+1 within one rollout
       round — all inside the serve engine's existing compile budget (the
       armed RecompileGuard raises otherwise).
    3. **Disaggregated overlap** — one real remote rollout worker: its
       decode throughput while the learner steps concurrently must hold
       >= 0.9x its unloaded rate (records-only below 4 cores, per the
       ``gates_enforced`` convention).

    Knobs: BENCH_STEPS (DPO optimizer steps), BENCH_BATCH, BENCH_SEQ,
    BENCH_DPO_BETA, BENCH_DPO_EVAL_BATCHES, BENCH_DPO_OVERLAP_TOKENS.
    """
    import numpy as np

    import jax

    from finetune_controller_tpu.data.preference import (
        synthetic_preference_batches,
    )
    from finetune_controller_tpu.models.llama import PRESETS
    from finetune_controller_tpu.models.lora import LoRAConfig
    from finetune_controller_tpu.prefs.dpo_trainer import DPOTrainer
    from finetune_controller_tpu.train.trainer import TrainConfig

    preset = os.environ.get("BENCH_PRESET", "tiny-test")
    steps = int(os.environ.get("BENCH_STEPS", "80"))
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    seq = int(os.environ.get("BENCH_SEQ", "32"))
    beta = float(os.environ.get("BENCH_DPO_BETA", "0.2"))
    eval_batches = int(os.environ.get("BENCH_DPO_EVAL_BATCHES", "8"))

    model_cfg = PRESETS[preset].replace(lora=LoRAConfig(rank=8))
    train_cfg = TrainConfig(
        task="dpo", dpo_beta=beta, batch_size=batch, seq_len=seq,
        total_steps=steps, warmup_steps=2, learning_rate=1e-3,
        eval_steps=eval_batches,
        log_every=10**9, checkpoint_every=10**9, prefetch=0,
        recompile_budget=int(os.environ.get("BENCH_RECOMPILE_BUDGET", "4")),
        recompile_action="raise",
    )
    trainer = DPOTrainer(model_cfg, train_cfg)
    state = trainer.init_state()
    batches = synthetic_preference_batches(
        batch, seq, model_cfg.vocab_size, seed=0
    )
    margins: list[float] = []
    pair_tput: list[float] = []
    for _ in range(steps):
        b = next(batches)
        t0 = time.perf_counter()
        state, metrics = trainer.step(state, b)
        margins.append(float(metrics["reward_margin"]))  # syncs the device
        pair_tput.append(batch / (time.perf_counter() - t0))
    if not all(np.isfinite(margins)):
        fail("dpo bench: non-finite reward margin", margins=margins[:10])
    q = max(1, steps // 4)
    margin_first = float(np.mean(margins[:q]))
    margin_last = float(np.mean(margins[-q:]))
    if not margin_last > margin_first:
        fail(
            "dpo bench: reward margin did not increase over the run",
            margin_first=round(margin_first, 4),
            margin_last=round(margin_last, 4),
        )

    # held-out accuracy via the REAL eval path (disjoint seed region, the
    # train/cli.py offset) — the same evaluate() a dpo job's eval cadence runs
    held_out = synthetic_preference_batches(
        batch, seq, model_cfg.vocab_size, seed=100_003
    )
    heldout_acc = float(
        trainer.evaluate(state, held_out)["eval_dpo_accuracy"]
    )
    if heldout_acc < 0.7:
        fail(
            "dpo bench: held-out DPO accuracy below the 0.7 gate",
            heldout_accuracy=round(heldout_acc, 3),
        )

    # --- actor/learner smoke: generate from N, commit N+1, reload N+1 -----
    import csv
    import tempfile

    from finetune_controller_tpu.prefs.learner import (
        RolloutConfig, build_rlhf_loop,
    )

    ckpt_every = int(os.environ.get("BENCH_DPO_CKPT_EVERY", "5"))
    loop_cfg = TrainConfig(
        task="rlhf", dpo_beta=beta, batch_size=4, seq_len=seq,
        total_steps=3 * ckpt_every, warmup_steps=1, learning_rate=1e-3,
        log_every=ckpt_every, checkpoint_every=ckpt_every, prefetch=0,
        heartbeat_interval_s=0,
    )
    learner = DPOTrainer(model_cfg, loop_cfg)
    with tempfile.TemporaryDirectory(prefix="ftc_dpo_bench_") as d:
        stream, actor, buffer = build_rlhf_loop(
            learner, d,
            rollout=RolloutConfig(
                pairs_per_round=6, min_fill=6, buffer_capacity=64,
                max_new_tokens=8, slots=4, temperature=0.9,
            ),
        )
        learner.fit(stream, d, resume=True)
        with open(os.path.join(d, "metrics.csv"), newline="") as f:
            rows = list(csv.DictReader(f))
    versions = [int(float(r["actor_version"])) for r in rows]
    # the row logged at step k*ckpt_every trained on rollouts from the
    # checkpoint committed at (k-1)*ckpt_every: reload lag is exactly one
    # round
    expected = [max(0, int(float(r["step"])) - ckpt_every) for r in rows]
    if versions != expected:
        fail(
            "dpo bench: actor did not reload each committed checkpoint "
            "within one round",
            actor_versions=versions, expected=expected,
        )
    if actor.reloads < 2:
        fail("dpo bench: actor never cycled checkpoints",
             reloads=actor.reloads)
    if actor.compilations > actor.compile_budget:
        fail(  # the armed guard should have raised first
            "dpo bench: rollout engine exceeded its compile budget",
            compilations=actor.compilations, budget=actor.compile_budget,
        )
    loop_margins = [float(r["reward_margin"]) for r in rows]

    # --- disaggregated overlap leg (docs/preference.md §Disaggregated) ----
    # One REAL remote rollout worker; the gate: its decode throughput while
    # the learner steps concurrently must hold >= 0.9x its unloaded rate.
    # Enforced only with >= 4 cores (worker + learner need separate cores);
    # below that the numbers are recorded, not gated (`gates_enforced`).
    from finetune_controller_tpu.prefs.learner import (  # noqa: F811
        RolloutConfig as _RC,
    )
    from finetune_controller_tpu.prefs.rollout_plane import (
        build_remote_rlhf_loop,
    )

    overlap_enforced = (os.cpu_count() or 1) >= 4
    min_tokens = int(os.environ.get("BENCH_DPO_OVERLAP_TOKENS", "300"))
    overlap_cfg = TrainConfig(
        task="rlhf", dpo_beta=beta, batch_size=4, seq_len=seq,
        total_steps=10**9, warmup_steps=1, learning_rate=1e-3,
        log_every=10**9, checkpoint_every=10**9, prefetch=0,
        heartbeat_interval_s=0, rollout_workers=1,
    )
    ov_learner = DPOTrainer(model_cfg, overlap_cfg)
    with tempfile.TemporaryDirectory(prefix="ftc_dpo_overlap_") as d:
        stream, plane, _buf = build_remote_rlhf_loop(
            ov_learner, d,
            rollout=_RC(
                pairs_per_round=6, min_fill=6, buffer_capacity=256,
                max_new_tokens=8, slots=4, temperature=0.9,
            ),
            model_spec={"preset": preset, "lora": {"rank": 8}},
        )
        try:
            ov_state = ov_learner.init_state()
            b = next(stream)  # waits for the worker's first rounds
            ov_state, m = ov_learner.step(ov_state, b)
            float(m["reward_margin"])  # compile outside both windows

            def _decode_window(step_fn, timeout_s: float):
                # windowed decode rate from the worker's own cumulative
                # counters (tokens / seconds spent inside generate_pairs)
                s0 = plane.stats()
                k0 = s0["rollout_actor_tokens_generated"]
                deadline = time.monotonic() + timeout_s
                steps_done = 0
                while time.monotonic() < deadline:
                    st = plane.stats()
                    if st["rollout_actor_tokens_generated"] - k0 >= min_tokens:
                        break
                    if step_fn is not None:
                        step_fn()
                        steps_done += 1
                    else:
                        time.sleep(0.05)
                s1 = plane.stats()
                dtok = s1["rollout_actor_tokens_generated"] - k0
                dsec = (s1["rollout_actor_generate_seconds"]
                        - s0["rollout_actor_generate_seconds"])
                return dtok / max(dsec, 1e-9), dtok, steps_done

            rate_unloaded, tok_a, _ = _decode_window(None, 90.0)

            def _one_step():
                bb = next(stream)
                ov = ov_learner.step(_one_step.state, bb)
                _one_step.state = ov[0]
                float(ov[1]["reward_margin"])  # sync

            _one_step.state = ov_state
            rate_loaded, tok_b, learner_steps = _decode_window(
                _one_step, 180.0
            )
        finally:
            plane.close()
    overlap_ratio = rate_loaded / max(rate_unloaded, 1e-9)
    if overlap_enforced:
        if tok_a < min_tokens or tok_b < min_tokens:
            fail(
                "dpo bench: remote worker generated too few tokens to "
                "measure the overlap windows",
                unloaded_tokens=tok_a, loaded_tokens=tok_b,
                min_tokens=min_tokens,
            )
        if learner_steps < 2:
            fail(
                "dpo bench: learner made too few concurrent steps to prove "
                "overlap", learner_steps=learner_steps,
            )
        if overlap_ratio < 0.9:
            fail(
                "dpo bench: remote actor decode rate collapsed under "
                "concurrent learner steps",
                rate_unloaded=round(rate_unloaded, 1),
                rate_loaded=round(rate_loaded, 1),
                ratio=round(overlap_ratio, 3),
            )

    return {
        "metric": f"dpo_heldout_accuracy[{preset},bs{batch},seq{seq},"
                  f"steps{steps},beta{beta:g}]",
        "value": round(heldout_acc, 3),
        "unit": "held-out pair-ranking accuracy",
        "margin_first_quarter": round(margin_first, 4),
        "margin_last_quarter": round(margin_last, 4),
        "margin_gain": round(margin_last - margin_first, 4),
        "pairs_per_sec": round(float(np.median(pair_tput)), 1),
        "rlhf_smoke": {
            "actor_versions": versions,
            "reloads": actor.reloads,
            "bootstrap_pairs": actor.bootstrap_pairs,
            "rollout_pairs": actor.pairs_generated,
            "actor_tokens_per_sec": round(actor.tokens_per_sec, 1),
            "engine_compilations": actor.compilations,
            "engine_compile_budget": actor.compile_budget,
            "loop_margins": [round(m, 4) for m in loop_margins],
            "buffer_depth": buffer.depth,
        },
        "rollout_overlap": {
            "rate_unloaded_tok_s": round(rate_unloaded, 1),
            "rate_loaded_tok_s": round(rate_loaded, 1),
            "ratio": round(overlap_ratio, 3),
            "unloaded_tokens": tok_a,
            "loaded_tokens": tok_b,
            "learner_steps_concurrent": learner_steps,
            "gates_enforced": overlap_enforced,
            "cpu_count": os.cpu_count(),
        },
        "device_kind": jax.devices()[0].device_kind,
    }


def _measure_serve() -> dict:
    """BENCH_MODE=serve: continuous-batching engine vs sequential decode.

    The serving headline: aggregate tokens/s of ``serve.engine.BatchEngine``
    over N concurrent requests against the same requests run one at a time
    through ``cached_generate`` (the pre-serve path), plus per-request
    completion latency p50/p95 measured from a common start — the number a
    queued client actually experiences.  Both legs are warmed first (compiles
    excluded; steady-state serving is what is measured), and the engine's
    recompile guard is armed with on_excess="raise": a decode step compiling
    mid-window is a measurement bug, not a slow number.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from finetune_controller_tpu.models.generate import cached_generate
    from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
    from finetune_controller_tpu.models.lora import LoRAConfig
    from finetune_controller_tpu.serve.engine import (
        BatchEngine,
        EngineConfig,
        GenRequest,
    )

    from finetune_controller_tpu.platform import env_flag

    # transfer guard (analysis/transfer_guard.py): every engine this bench
    # builds — including process-mode workers, which inherit the env — runs
    # its decode dispatch under FTC_TRANSFER_GUARD=raise, so a reintroduced
    # device<->host sync ABORTS the timed window instead of deflating the
    # measured tok/s. BENCH_TRANSFER_GUARD=0 disables; an explicit
    # FTC_TRANSFER_GUARD in the env wins.
    transfer_guard_armed = env_flag("BENCH_TRANSFER_GUARD", default=True)
    if transfer_guard_armed:
        os.environ.setdefault("FTC_TRANSFER_GUARD", "raise")
    # shard audit (analysis/shard_audit.py): any serve-side model load this
    # bench (or its process-mode workers, which inherit the env) performs
    # asserts the rule-table shardings on the way in. An explicit
    # FTC_SHARD_AUDIT in the env wins; BENCH_SHARD_AUDIT=0 disables.
    if env_flag("BENCH_SHARD_AUDIT", default=True):
        os.environ.setdefault("FTC_SHARD_AUDIT", "raise")

    preset = os.environ.get("BENCH_PRESET", "tiny-test")
    n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "8"))
    max_new = int(os.environ.get("BENCH_SERVE_NEW_TOKENS", "32"))
    slots = int(os.environ.get("BENCH_SERVE_SLOTS", str(n_requests)))

    cfg = PRESETS[preset].replace(lora=LoRAConfig(rank=8))
    model = LlamaForCausalLM(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32)
    )
    rng = np.random.default_rng(0)
    # mixed prompt lengths across two buckets — the shape serving traffic has
    prompts = [
        list(rng.integers(1, cfg.vocab_size - 1, size=int(n)))
        for n in rng.integers(4, 24, size=n_requests)
    ]

    def reqs():
        return [
            GenRequest(request_id=f"r{i}", tokens=p, max_new_tokens=max_new)
            for i, p in enumerate(prompts)
        ]

    # --- sequential baseline: one request at a time through cached_generate
    def run_sequential() -> list[float]:
        done_at, t0 = [], time.perf_counter()
        for p in prompts:
            out = cached_generate(
                model, variables, jnp.asarray([p], jnp.int32),
                max_new_tokens=max_new,
            )
            jax.block_until_ready(out)
            done_at.append(time.perf_counter() - t0)
        return done_at

    run_sequential()  # warm: per-prompt-length decode fns compile here
    seq_done = run_sequential()
    seq_window = seq_done[-1]

    engine = BatchEngine(
        model, variables,
        EngineConfig(slots=slots, prompt_buckets=(32, 128),
                     max_new_tokens=max_new + 8),
    )
    engine.run(reqs())  # warm: fill buckets + the decode step compile here
    t0 = time.perf_counter()
    results = engine.run(reqs())
    engine_window = time.perf_counter() - t0
    # finished_at is monotonic-clock; re-zero against the earliest admission
    base = min(r.admitted_at for r in results.values())
    engine_done = sorted(r.finished_at - base for r in results.values())

    total_tokens = sum(len(r.generated) for r in results.values())
    if total_tokens != n_requests * max_new:
        fail(
            "serve bench generated an unexpected token count",
            total_tokens=total_tokens, expected=n_requests * max_new,
        )
    engine_tps = total_tokens / engine_window
    seq_tps = total_tokens / seq_window
    speedup = engine_tps / seq_tps

    def pct(xs: list[float], p: float) -> float:
        return float(np.percentile(np.asarray(xs), p))

    # --- prefix-reuse A/B (docs/serving.md): the ISSUE 6 gates ------------
    # (a) the EXISTING mixed workload must not regress with the cache on;
    # (b) a shared-system-prompt workload must cut time-to-first-token >= 2x
    #     and save > 50% of prefill tokens.
    cache_mb = int(os.environ.get("BENCH_SERVE_PREFIX_CACHE_MB", "64"))
    engine_on = BatchEngine(
        model, variables,
        EngineConfig(slots=slots, prompt_buckets=(32, 128),
                     max_new_tokens=max_new + 8,
                     prefix_cache_bytes=cache_mb << 20),
    )
    engine_on.run(reqs())  # warm pass 1: fill compiles + seeds the cache
    engine_on.run(reqs())  # warm pass 2: the hit path compiles fill_from
    t0 = time.perf_counter()
    results_on = engine_on.run(reqs())
    on_window = time.perf_counter() - t0
    for rid, r in results.items():
        if results_on[rid].generated != r.generated:
            fail("prefix cache changed greedy output on the mixed workload",
                 request_id=rid)
    mixed_on_tps = total_tokens / on_window
    if mixed_on_tps < 0.8 * engine_tps:
        # the cache must be ~free when it cannot help (same-run baseline =
        # the PR-4 configuration); 0.8 absorbs CPU timer noise on the tiny
        # preset — a real regression from trie/insert overhead is far larger
        fail(
            "prefix cache regressed the mixed serve workload",
            mixed_on_tps=round(mixed_on_tps, 1),
            mixed_off_tps=round(engine_tps, 1),
        )

    prefix_len = int(os.environ.get("BENCH_SERVE_PREFIX_LEN", "240"))
    suffix_len = 8
    pre_buckets = (32, prefix_len + 2 * suffix_len)
    system_prompt = list(
        rng.integers(1, cfg.vocab_size - 1, size=prefix_len)
    )
    shared_prompts = [
        system_prompt + list(
            rng.integers(1, cfg.vocab_size - 1, size=suffix_len)
        )
        for _ in range(n_requests)
    ]

    def shared_reqs(tag):
        return [
            GenRequest(request_id=f"{tag}{i}", tokens=p,
                       max_new_tokens=max_new)
            for i, p in enumerate(shared_prompts)
        ]

    def ttft_and_drain(eng, requests):
        """Admit with per-request wall timing (TTFT: prefill + first token
        selection happen inside admit), then drain the batch."""
        ttfts, out, pending = [], {}, list(requests)
        while pending or eng.active_requests:
            while pending and eng.free_slots:
                r = pending.pop(0)
                t1 = time.perf_counter()
                done = eng.admit(r)
                ttfts.append(time.perf_counter() - t1)
                if done is not None:
                    out[r.request_id] = done
            for done in eng.step():
                out[done.request_id] = done
        return ttfts, out

    ab = {}
    for leg, cache_bytes in (("off", 0), ("on", cache_mb << 20)):
        eng = BatchEngine(
            model, variables,
            EngineConfig(slots=slots, prompt_buckets=pre_buckets,
                         max_new_tokens=max_new + 8,
                         prefix_cache_bytes=cache_bytes),
        )
        ttft_and_drain(eng, shared_reqs("w"))  # warm + seed the cache
        saved0 = eng.prefill_tokens_saved_total
        ttfts, out = ttft_and_drain(eng, shared_reqs("m"))
        ab[leg] = {
            "ttft_p50_s": round(pct(ttfts, 50), 5),
            "ttft_p95_s": round(pct(ttfts, 95), 5),
            "prefill_tokens_saved": eng.prefill_tokens_saved_total - saved0,
            "prefix_hits": eng.prefix_hits_total,
            "compilations": eng.compilations,
            "tokens": {r: out[r].generated for r in sorted(out)},
        }
    if ab["on"].pop("tokens") != ab["off"].pop("tokens"):
        fail("prefix cache changed greedy output on the shared-prefix "
             "workload")
    ttft_speedup = ab["off"]["ttft_p50_s"] / ab["on"]["ttft_p50_s"]
    if ttft_speedup < 2.0:
        fail(
            "shared-prefix TTFT improvement below the 2x gate",
            ttft_speedup=round(ttft_speedup, 2), **{
                f"ttft_{leg}_p50_s": ab[leg]["ttft_p50_s"]
                for leg in ("off", "on")
            },
        )
    prompt_tokens_total = sum(len(p) for p in shared_prompts)
    saved_fraction = ab["on"]["prefill_tokens_saved"] / prompt_tokens_total
    if saved_fraction <= 0.5:
        fail(
            "prefix cache saved <= 50% of prompt tokens on the "
            "shared-prefix workload",
            saved_fraction=round(saved_fraction, 3),
        )
    compile_bound = 2 * len(pre_buckets) + 1
    if ab["on"]["compilations"] > compile_bound:
        fail(  # the armed RecompileGuard should have raised first
            "prefix-cache engine exceeded the compile budget",
            compilations=ab["on"]["compilations"], bound=compile_bound,
        )

    # --- fleet serve-chaos + zero-downtime rollover (ISSUE 10 gates) ------
    fleet_metrics = _measure_serve_fleet(
        model, variables, prompts, n_requests=n_requests, max_new=max_new,
        slots=slots,
    )

    # --- paged KV + multi-tenant adapter gates (ISSUE 11) -----------------
    paged_metrics: dict = {}
    adapter_metrics: dict = {}
    if os.environ.get("BENCH_SERVE_PAGED", "1").strip().lower() not in (
            "0", "false", "no"):
        paged_metrics = _measure_serve_paged(
            model, variables, prompts, max_new=max_new,
        )
        adapter_metrics = _measure_serve_adapters(cfg, variables, max_new=max_new)

    # --- cross-process transport A/B + 64-concurrency gate (ISSUE 12) ----
    transport_metrics: dict = {}
    if os.environ.get("BENCH_SERVE_TRANSPORT", "1").strip().lower() not in (
            "0", "false", "no"):
        transport_metrics = _measure_serve_transport(max_new=max_new)

    return {
        "metric": f"serve_tokens_per_sec[{preset},req{n_requests},"
                  f"new{max_new},slots{slots}]",
        "value": round(engine_tps, 1),
        "unit": "tokens/sec",
        "speedup_vs_sequential": round(speedup, 2),
        "sequential_tokens_per_sec": round(seq_tps, 1),
        "p50_latency_s": round(pct(engine_done, 50), 4),
        "p95_latency_s": round(pct(engine_done, 95), 4),
        "sequential_p50_latency_s": round(pct(seq_done, 50), 4),
        "sequential_p95_latency_s": round(pct(seq_done, 95), 4),
        "n_requests": n_requests,
        "max_new_tokens": max_new,
        "slots": slots,
        "compilations": engine.compilations,
        "recompile_budget": engine.guard.budget,
        # the timed windows above ran to completion, so an armed guard saw
        # ZERO device<->host syncs in the decode hot path (it aborts on one)
        "transfer_guard_armed": transfer_guard_armed,
        "transfer_guard_trips": (
            engine._transfer_guard.trips
            if engine._transfer_guard is not None else 0
        ),
        "mixed_prefix_on_tokens_per_sec": round(mixed_on_tps, 1),
        "prefix_ab": {
            "ttft_speedup": round(ttft_speedup, 2),
            "prefill_tokens_saved_fraction": round(saved_fraction, 3),
            "prefix_len": prefix_len,
            "cache_mb": cache_mb,
            **{f"{leg}_{k}": v for leg in ("off", "on")
               for k, v in ab[leg].items()},
        },
        "fleet": fleet_metrics,
        "paged": paged_metrics,
        "adapters": adapter_metrics,
        "transport": transport_metrics,
        "device_kind": jax.devices()[0].device_kind,
    }


def _measure_serve_transport(*, max_new) -> dict:
    """The ISSUE 12 cross-process gates, run inside ``BENCH_MODE=serve``:

    1. **scaling A/B**: the same 64+-concurrent mixed-length workload runs
       on four fleets — in-process 1 and N replicas, process-mode 1 and N
       workers.  On a multi-core host, N process workers must reach >= 1.5x
       the single worker's throughput AND beat the in-process N-replica
       ratio (in-process replicas share one JAX runtime, so their "scaling"
       is contention — measuring that baseline is part of the gate);
    2. **the deferred 64+-concurrent mixed-length latency gate** (ISSUE 10
       deferred it until replicas stopped sharing cores): every accepted
       request completes exactly once, and p95 completion latency on the
       N-worker process fleet stays within the fair-share queueing bound
       ``(conc / (workers * slots) + 2) x solo-request latency``.

    Every leg uses the deterministic ``tiny_test`` payload so in-process
    and worker processes decode identical weights.  Gates are enforced only
    on hosts with >= 2 cores per worker (BENCH notes in ROADMAP.md: this
    box is 2-CPU — numbers are recorded, the scaling assertion needs real
    cores); ``BENCH_SERVE_TRANSPORT=0`` skips the whole leg.
    """
    import asyncio
    import tempfile
    from pathlib import Path

    import numpy as np

    from finetune_controller_tpu.serve.engine import EngineConfig, GenRequest
    from finetune_controller_tpu.serve.fleet import ReplicaFleet
    from finetune_controller_tpu.serve.router import ReplicaRouter
    from finetune_controller_tpu.transport.builders import tiny_test
    from finetune_controller_tpu.transport.process import ProcessTransport

    conc = max(64, int(os.environ.get("BENCH_SERVE_CONC", "64")))
    workers = max(2, int(os.environ.get("BENCH_SERVE_TRANSPORT_WORKERS", "2")))
    slots = int(os.environ.get("BENCH_SERVE_TRANSPORT_SLOTS", "4"))
    new_tokens = min(max_new, 16)  # bounds the 4-leg wall clock
    ecfg = EngineConfig(slots=slots, prompt_buckets=(16, 32),
                        max_new_tokens=new_tokens + 8)
    model, variables = tiny_test()
    rng = np.random.default_rng(7)
    prompts = [
        [int(t) for t in rng.integers(1, model.cfg.vocab_size - 1, size=int(n))]
        for n in rng.integers(4, 30, size=conc)
    ]

    def reqs(tag, subset=None):
        chosen = prompts if subset is None else prompts[:subset]
        return [
            GenRequest(request_id=f"{tag}{i}", tokens=p,
                       max_new_tokens=new_tokens)
            for i, p in enumerate(chosen)
        ]

    def pct(xs, p):
        return float(np.percentile(np.asarray(xs), p))

    async def leg(mode: str, replicas: int, root) -> dict:
        if mode == "process":
            transport = ProcessTransport(
                job_id="bench-transport", root=Path(root),
                payload={"builder": "tiny_test", "kwargs": {}},
                spawn_timeout_s=600.0,
            )
            fleet = ReplicaFleet("bench-transport", None, None, ecfg,
                                 replicas=replicas, transport=transport)
        else:
            fleet = ReplicaFleet("bench-transport", model, variables, ecfg,
                                 replicas=replicas)
        t_spawn = time.perf_counter()
        await fleet.start()
        spawn_s = time.perf_counter() - t_spawn
        router = ReplicaRouter(fleet, default_timeout_s=600,
                               failover_retries=2)
        # engines warm-start at spawn; this wave warms the routing/RPC path
        await asyncio.gather(*(
            router.submit(r) for r in reqs("w", subset=replicas * slots)
        ))
        t1 = time.perf_counter()
        await router.submit(GenRequest(
            request_id="solo", tokens=prompts[0], max_new_tokens=new_tokens,
        ))
        solo_s = time.perf_counter() - t1
        lat: list[float] = []

        outputs: dict[str, list[int]] = {}

        async def one(r):
            t2 = time.perf_counter()
            res = await router.submit(r)
            lat.append(time.perf_counter() - t2)
            outputs[res.request_id] = [int(t) for t in res.generated]
            return len(res.generated)

        t0 = time.perf_counter()
        tokens = sum(await asyncio.gather(*(one(r) for r in reqs("m"))))
        window = time.perf_counter() - t0
        stats = fleet.stats()
        await fleet.close()
        completed_wave = len(lat)
        if completed_wave != conc:
            fail("transport leg lost requests", mode=mode,
                 replicas=replicas, completed=completed_wave, expected=conc)
        return {
            "tokens_per_sec": round(tokens / window, 1),
            "window_s": round(window, 3),
            "spawn_s": round(spawn_s, 2),
            "solo_latency_s": round(solo_s, 4),
            "p50_latency_s": round(pct(lat, 50), 4),
            "p95_latency_s": round(pct(lat, 95), 4),
            "transport": stats["transport"],
            "worker_pids": stats.get("worker_pids", []),
            "_outputs": outputs,
        }

    async def chaos_leg(root, baseline: dict[str, list[int]]) -> dict:
        """The serve-chaos satellite in PROCESS mode: the same
        ``FTC_FAULT_SERVE_*`` env, forwarded into the worker spawn, makes
        the victim REALLY SIGKILL itself mid-decode — exactly-once and
        bit-identity are then proven against genuine process death."""
        from finetune_controller_tpu.resilience.faults import ServeFault
        from finetune_controller_tpu.resilience.policy import RetryPolicy

        once = Path(root) / "fault-spent"
        transport = ProcessTransport(
            job_id="bench-transport-chaos", root=Path(root),
            payload={"builder": "tiny_test", "kwargs": {}},
            spawn_timeout_s=600.0,
            extra_env=ServeFault(
                replica_id="r0", at_step=2, mode="kill",
                once_file=str(once),
            ).to_env(),
        )
        fleet = ReplicaFleet(
            "bench-transport-chaos", None, None, ecfg, replicas=workers,
            transport=transport,
            restart_policy=RetryPolicy(max_attempts=3, base_delay_s=0.1,
                                       max_delay_s=0.3, seed=0),
        )
        await fleet.start()
        router = ReplicaRouter(fleet, default_timeout_s=600,
                               failover_retries=2)

        async def health_loop():
            while True:
                await fleet.health_tick()
                await asyncio.sleep(0.1)

        hl = asyncio.ensure_future(health_loop())
        try:
            results = await asyncio.gather(
                *(router.submit(r) for r in reqs("m", subset=16))
            )
            seen: dict[str, list[int]] = {}
            for r in results:
                if r.request_id in seen:
                    fail("process serve-chaos: request completed twice",
                         request_id=r.request_id)
                seen[r.request_id] = [int(t) for t in r.generated]
            if len(seen) != 16:
                fail("process serve-chaos: accepted requests were lost",
                     completed=len(seen))
            if not once.exists():
                fail("process serve-chaos: the forwarded SIGKILL fault "
                     "never fired")
            for rid, toks in seen.items():
                if toks != baseline.get(rid):
                    fail("process serve-chaos: output diverged from the "
                         "unkilled run", request_id=rid)
            stats = fleet.stats()
        finally:
            hl.cancel()
            await fleet.close()
        return {
            "real_sigkill": True,
            "exactly_once": True,
            "bit_identical_to_unkilled": True,
            "failovers": router.failovers_total,
            "replica_restarts": stats["replica_restarts_total"],
        }

    async def all_legs() -> dict:
        with tempfile.TemporaryDirectory(prefix="ftc-bench-transport-") as td:
            out = {
                "inproc_1r": await leg("inproc", 1, None),
                "inproc_multi": await leg("inproc", workers, None),
                "process_1w": await leg("process", 1, Path(td) / "w1"),
                "process_multi": await leg("process", workers, Path(td) / "wN"),
            }
            out["serve_chaos_process"] = await chaos_leg(
                Path(td) / "chaos", out["inproc_1r"]["_outputs"],
            )
            return out

    legs = asyncio.run(all_legs())
    chaos_process = legs.pop("serve_chaos_process")
    for doc in legs.values():
        doc.pop("_outputs", None)
    proc_ratio = (legs["process_multi"]["tokens_per_sec"]
                  / max(1e-9, legs["process_1w"]["tokens_per_sec"]))
    inproc_ratio = (legs["inproc_multi"]["tokens_per_sec"]
                    / max(1e-9, legs["inproc_1r"]["tokens_per_sec"]))
    # fair-share queueing bound for the latency gate: conc requests over
    # workers*slots lanes, two requests' slack for admission jitter
    waves = conc / (workers * slots) + 2
    latency_bound = waves * max(1e-3, legs["process_multi"]["solo_latency_s"])
    gates_enforced = (os.cpu_count() or 1) >= 2 * workers
    if gates_enforced:
        if proc_ratio < 1.5:
            fail("process-mode workers did not scale >= 1.5x",
                 process_ratio=round(proc_ratio, 2), workers=workers)
        if proc_ratio <= inproc_ratio:
            fail("process-mode scaling did not beat the in-process "
                 "contention baseline",
                 process_ratio=round(proc_ratio, 2),
                 inproc_ratio=round(inproc_ratio, 2))
        if legs["process_multi"]["p95_latency_s"] > latency_bound:
            fail("64-concurrent mixed-length p95 exceeded the fair-share "
                 "bound on process workers",
                 p95_s=legs["process_multi"]["p95_latency_s"],
                 bound_s=round(latency_bound, 3))
    return {
        "concurrency": conc,
        "workers": workers,
        "slots_per_replica": slots,
        "new_tokens": new_tokens,
        "process_scaling_x": round(proc_ratio, 2),
        "inproc_scaling_x": round(inproc_ratio, 2),
        "latency_gate_bound_s": round(latency_bound, 3),
        "gates_enforced": gates_enforced,
        "cpu_count": os.cpu_count(),
        "serve_chaos_process": chaos_process,
        "legs": legs,
    }


def _measure_serve_paged(model, variables, prompts, *, max_new) -> dict:
    """The ISSUE 11 paged-KV gates, run inside ``BENCH_MODE=serve``:

    1. **lanes-per-byte**: at a FIXED KV byte budget (the pool holds exactly
       the pages a ``slots_u``-lane unpaged cache would), the paged engine
       must run >= 2x ``slots_u`` concurrent mixed-length lanes — the
       capacity argument for paging: short requests stop paying full-length
       reservations;
    2. **throughput parity**: at EQUAL concurrency the paged engine's mixed
       workload must hold >= 0.9x the unpaged tokens/s (interleaved
       best-of-4 windows — the gather indirection must stay in the noise),
       with bit-identical greedy outputs.

    The parity RATIO is timing on a shared box, so it follows the ISSUE 12
    convention: enforced only with >= 2 cores per timed leg (4 cores — the
    two engines contend for the same runtime threads), recorded always
    (``gates_enforced`` in the metrics).  Bit-identity and the compile
    budget are load-independent and enforced everywhere.
    """
    import numpy as np

    from finetune_controller_tpu.serve.engine import (
        BatchEngine,
        EngineConfig,
        GenRequest,
    )

    page_tokens = int(os.environ.get("BENCH_SERVE_PAGE_TOKENS", "16"))
    buckets = (32, 128)
    slots_u = 4

    # --- gate 1: >= 2x concurrent lanes at a fixed byte budget ------------
    cfg_u = EngineConfig(slots=slots_u, prompt_buckets=buckets,
                         max_new_tokens=max_new + 8)
    pages_per_lane = -(-cfg_u.cache_len // page_tokens)
    budget_pages = slots_u * pages_per_lane   # == the unpaged cache's bytes
    cfg_p = EngineConfig(
        slots=4 * slots_u, prompt_buckets=buckets, max_new_tokens=max_new + 8,
        page_tokens=page_tokens, pool_pages=budget_pages + 1,
    )
    eng = BatchEngine(model, variables, cfg_p)
    rng = np.random.default_rng(7)
    short_prompts = [
        list(rng.integers(1, 200, size=int(n)))
        for n in rng.integers(4, 12, size=4 * slots_u)
    ]

    def short_reqs(tag):
        return [
            GenRequest(request_id=f"{tag}{i}", tokens=p, max_new_tokens=8)
            for i, p in enumerate(short_prompts)
        ]

    eng.run(short_reqs("w"))  # warm: compiles land here
    pending = short_reqs("m")
    max_active = 0
    while pending or eng.active_requests:
        while pending and eng.free_slots and eng.can_admit(pending[0]):
            eng.admit(pending.pop(0))
        max_active = max(max_active, eng.active_requests)
        eng.step()
    if max_active < 2 * slots_u:
        fail(
            "paged engine below the 2x lanes-per-byte gate",
            max_concurrent_lanes=max_active, unpaged_lanes=slots_u,
            budget_pages=budget_pages, page_tokens=page_tokens,
        )

    # --- gate 2: >= 0.9x tokens/s at equal concurrency, bit-identical -----
    def mixed_reqs(tag):
        return [
            GenRequest(request_id=f"{tag}{i}", tokens=p,
                       max_new_tokens=max_new)
            for i, p in enumerate(prompts)
        ]

    eng_u8 = BatchEngine(model, variables, EngineConfig(
        slots=8, prompt_buckets=buckets, max_new_tokens=max_new + 8))
    eng_p8 = BatchEngine(model, variables, EngineConfig(
        slots=8, prompt_buckets=buckets, max_new_tokens=max_new + 8,
        page_tokens=page_tokens))
    # interleave the legs (the obs-bench recipe): alternating short windows
    # cancel the box's slow drift, and best-of-N is robust because noise on
    # a shared CPU only ever makes a leg SLOWER, never faster
    tps_u = tps_p = 0.0
    out_u: dict = {}
    out_p: dict = {}
    for engine in (eng_u8, eng_p8):
        engine.run(mixed_reqs("w"))  # warm: compiles land outside timing
    for attempt in range(4):
        for which, engine in (("u", eng_u8), ("p", eng_p8)):
            t0 = time.perf_counter()
            out = engine.run(mixed_reqs(f"t{attempt}-"))
            window = time.perf_counter() - t0
            tps = sum(len(r.generated) for r in out.values()) / window
            if which == "u":
                tps_u, out_u = max(tps_u, tps), out
            else:
                tps_p, out_p = max(tps_p, tps), out
    for rid, r in out_u.items():
        if out_p[rid].generated != r.generated:
            fail("paged decode changed greedy output on the mixed workload",
                 request_id=rid)
    ratio = tps_p / tps_u
    gates_enforced = (os.cpu_count() or 1) >= 4
    if gates_enforced and ratio < 0.9:
        fail(
            "paged engine below the 0.9x throughput-parity gate",
            paged_tokens_per_sec=round(tps_p, 1),
            unpaged_tokens_per_sec=round(tps_u, 1),
            ratio=round(ratio, 3),
        )
    if eng_p8.compilations > eng_p8.guard.budget:
        fail(  # the armed RecompileGuard should have raised first
            "paged engine exceeded the compile budget",
            compilations=eng_p8.compilations, budget=eng_p8.guard.budget,
        )
    return {
        "page_tokens": page_tokens,
        "budget_pages": budget_pages,
        "max_concurrent_lanes_at_budget": max_active,
        "unpaged_lanes_at_budget": slots_u,
        "lanes_per_byte_gain": round(max_active / slots_u, 2),
        "paged_tokens_per_sec": round(tps_p, 1),
        "unpaged_tokens_per_sec": round(tps_u, 1),
        "throughput_ratio": round(ratio, 3),
        "gates_enforced": gates_enforced,
        "compilations": eng_p8.compilations,
        "recompile_budget": eng_p8.guard.budget,
        "tiering": _measure_serve_tiering(model, variables, max_new=max_new),
    }


def _measure_serve_tiering(model, variables, *, max_new) -> dict:
    """The ISSUE 16 host-KV-tier gates: tiering on vs off, everything else
    equal — same model, same prompts, same DEVICE page budget, same device
    prefix-cache budget.  The device prefix budget is set to HALF one
    entry's footprint, so the working set (3 shared prefixes) cannot live on
    the device at all: the off leg's cache refuses every insert and serves
    pure misses, the on leg births entries straight to host slots and pages
    them back in on touch.

    1. **capacity**: round 2 re-touches each of the 3 prefixes — the on leg
       must serve >= 2x the device-resident capacity (0 entries here, gate
       floor 2) as restore hits where the off leg records none.  Pure
       allocator arithmetic: enforced everywhere.
    2. **lanes**: a grouped wave (3 prefixes x 4 lanes) admitted until
       ``PoolExhausted`` at a pool sized to ~8 miss-lanes.  On-leg lanes
       share restored prefix pages (first lane of a group pays the full
       span, followers only the tail), off-leg lanes each reserve the full
       span, so admitted_on >= 1.5x admitted_off.  Allocator-deterministic:
       enforced everywhere.
    3. **throughput**: mixed touch rounds, interleaved best-of-4 — the on
       leg (restore + suffix prefill) must hold >= 0.8x the off leg's
       tokens/s.  Timing on a shared box: ISSUE 12 convention, enforced
       only with >= 4 cores (2 per timed leg), recorded always.

    Every request that runs in both legs must be bit-identical (demote /
    restore moves KV bytes, never changes them), and the decode windows run
    under the armed transfer guard — ``trips`` must stay 0 (tier d2h/h2d
    traffic lives in admission paths, never the decode dispatch).
    """
    import numpy as np

    from finetune_controller_tpu.serve.engine import (
        BatchEngine,
        EngineConfig,
        GenRequest,
    )
    from finetune_controller_tpu.serve.kv_pages import PoolExhausted

    page_tokens = int(os.environ.get("BENCH_SERVE_PAGE_TOKENS", "16"))
    buckets = (32, 128)
    prefix_len = max(buckets) - 1
    entry_pages = -(-max(buckets) // page_tokens)
    budget_pages = max(1, entry_pages // 2)  # device budget < one entry
    n_prefix, group = 3, 4

    probe = BatchEngine(model, variables, EngineConfig(
        slots=1, prompt_buckets=buckets, max_new_tokens=max_new + 8,
        page_tokens=page_tokens))
    page_bytes = probe._pool.page_bytes
    del probe

    rng = np.random.default_rng(16)
    prefixes = [list(rng.integers(1, 200, size=prefix_len))
                for _ in range(n_prefix)]

    def reqs(tag, tails, new_tokens):
        """One request per (prefix, tail): the shared 127-token prefix plus
        a distinct final token, so every prompt is a fresh cache KEY whose
        longest cached match is exactly the shared prefix."""
        return [
            GenRequest(request_id=f"{tag}-p{j}t{tl}",
                       tokens=prefixes[j] + [int(tl)],
                       max_new_tokens=new_tokens)
            for j in range(n_prefix) for tl in tails
        ]

    def make_engine(tiered: bool, slots: int, pool_pages: int):
        return BatchEngine(model, variables, EngineConfig(
            slots=slots, prompt_buckets=buckets,
            max_new_tokens=max_new + 8, page_tokens=page_tokens,
            pool_pages=pool_pages,
            prefix_cache_bytes=budget_pages * page_bytes,
            host_pool_bytes=(256 * page_bytes) if tiered else 0,
        ))

    # --- gates 1 + 3: capacity beyond the device budget, tok/s parity -----
    eng_on = make_engine(True, 4, 0)
    eng_off = make_engine(False, 4, 0)
    outs: dict[str, dict] = {"on": {}, "off": {}}
    hits_round2 = {}
    for which, eng in (("on", eng_on), ("off", eng_off)):
        outs[which].update(eng.run(reqs("r1", [210], max_new)))  # seed
        h0 = eng.prefix_hits_total
        outs[which].update(eng.run(reqs("r2", [211], max_new)))  # re-touch
        hits_round2[which] = eng.prefix_hits_total - h0
    if hits_round2["on"] < 2 * max(hits_round2["off"], 1):
        fail(
            "host tier below the 2x effective-prefix-capacity gate",
            round2_hits_tiered=hits_round2["on"],
            round2_hits_untiered=hits_round2["off"],
            working_set_entries=n_prefix,
            device_budget_pages=budget_pages, entry_pages=entry_pages,
        )

    tps_on = tps_off = 0.0
    for attempt in range(4):  # interleaved best-of-4, as in the paged gate
        for which, eng in (("on", eng_on), ("off", eng_off)):
            batch = reqs(f"t{attempt}", [220 + attempt, 230 + attempt],
                         max_new)
            t0 = time.perf_counter()
            out = eng.run(batch)
            window = time.perf_counter() - t0
            tps = sum(len(r.generated) for r in out.values()) / window
            if which == "on":
                tps_on = max(tps_on, tps)
            else:
                tps_off = max(tps_off, tps)
            outs[which].update(out)
    ratio = tps_on / tps_off
    gates_enforced = (os.cpu_count() or 1) >= 4
    if gates_enforced and ratio < 0.8:
        fail(
            "tiered decode below the 0.8x mixed tokens/s gate",
            tiered_tokens_per_sec=round(tps_on, 1),
            untiered_tokens_per_sec=round(tps_off, 1),
            ratio=round(ratio, 3),
        )

    # --- gate 2: >= 1.5x concurrent lanes at the same pool ----------------
    # pool sized to ~8 full-span miss lanes; the +8 span headroom keeps it
    # off lane-count boundaries for nearby page_tokens values
    span = max(buckets) + 8 - 1
    lane_pages = -(-span // page_tokens)
    lanes = {}
    wave_outs: dict[str, dict] = {}
    for which, tiered in (("on", True), ("off", False)):
        eng = make_engine(tiered, 2 * n_prefix * group, 8 * lane_pages)
        eng.run(reqs("seed", [240], 8))  # entries exist (host) / refused
        pending = reqs("wave", [250, 251, 252, 253], 8)
        admitted = []
        for req in pending:
            try:
                eng.admit(req)
            except PoolExhausted:
                break
            admitted.append(req.request_id)
        results: dict = {}
        while eng.active_requests:
            for r in eng.step():
                results[r.request_id] = r
        lanes[which] = len(admitted)
        wave_outs[which] = results
        if which == "on":
            tier_stats = eng.kv_page_stats()
            guard = eng._transfer_guard
    if lanes["on"] < 1.5 * lanes["off"]:
        fail(
            "host tier below the 1.5x concurrent-lanes gate",
            lanes_tiered=lanes["on"], lanes_untiered=lanes["off"],
            pool_pages=8 * lane_pages, lane_pages=lane_pages,
        )

    # --- bit-identity: every request served by BOTH legs must match -------
    for leg_on, leg_off, where in (
        (outs["on"], outs["off"], "mixed rounds"),
        (wave_outs["on"], wave_outs["off"], "lane wave"),
    ):
        for rid in set(leg_on) & set(leg_off):
            if leg_on[rid].generated != leg_off[rid].generated:
                fail("KV tiering changed greedy output "
                     f"({where})", request_id=rid)

    trips = guard.trips if guard is not None else None
    if trips:
        fail("transfer guard tripped inside the tiered decode window",
             trips=trips)
    return {
        "page_tokens": page_tokens,
        "device_prefix_budget_pages": budget_pages,
        "entry_pages": entry_pages,
        "working_set_entries": n_prefix,
        "round2_prefix_hits_tiered": hits_round2["on"],
        "round2_prefix_hits_untiered": hits_round2["off"],
        "lanes_admitted_tiered": lanes["on"],
        "lanes_admitted_untiered": lanes["off"],
        "lanes_gain": round(lanes["on"] / max(lanes["off"], 1), 2),
        "tiered_tokens_per_sec": round(tps_on, 1),
        "untiered_tokens_per_sec": round(tps_off, 1),
        "throughput_ratio": round(ratio, 3),
        "gates_enforced": gates_enforced,
        "demotions_total": tier_stats.get("demotions_total", 0),
        "restores_total": tier_stats.get("restores_total", 0),
        "host_pages_used": tier_stats.get("tier_host_pages_used", 0),
        "transfer_guard_trips": trips,
    }


def _measure_serve_adapters(cfg, variables, *, max_new) -> dict:
    """The ISSUE 11 multi-tenant gate: N adapters multiplexed UNMERGED on one
    engine produce outputs bit-identical to N dedicated single-tenant
    engines — the deployment alternative being displaced (one replica set
    per fine-tuned job).  Dedicated engines serve the same unmerged math: a
    merged-weights engine computes ``(W + sAB)x`` instead of
    ``Wx + s(xA)B``, which differs by floating-point reassociation (the
    logits agree to ~1e-6; argmax can flip on a tiny random-init model), so
    merged-vs-unmerged parity is pinned at the logits level in
    tests/test_serve_adapters.py rather than gated here."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from finetune_controller_tpu.models.llama import LlamaForCausalLM
    from finetune_controller_tpu.models.lora import LoRAConfig
    from finetune_controller_tpu.serve.engine import (
        BatchEngine,
        EngineConfig,
        GenRequest,
    )

    n_adapters = int(os.environ.get("BENCH_SERVE_ADAPTERS", "4"))
    page_tokens = int(os.environ.get("BENCH_SERVE_PAGE_TOKENS", "16"))
    base_cfg = cfg.replace(lora=LoRAConfig(rank=0))
    base_model = LlamaForCausalLM(base_cfg)
    base_vars = {"params": variables["params"]}

    # adapter stacks shaped by a rank-4 init; B nonzero so tenants diverge
    lora_shapes = jax.eval_shape(
        LlamaForCausalLM(cfg.replace(lora=LoRAConfig(rank=4))).init,
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 4), jnp.int32),
    )["lora"]

    def make_adapter(seed):
        return jax.tree.map(
            lambda s: 0.05 * np.asarray(
                jax.random.normal(jax.random.PRNGKey(seed), s.shape),
                np.float32,
            ),
            lora_shapes,
        )

    adapters = {f"tenant-{i}": make_adapter(101 + i)
                for i in range(n_adapters)}
    rng = np.random.default_rng(11)
    prompts = {
        aid: list(rng.integers(1, 200, size=int(rng.integers(4, 20))))
        for aid in adapters
    }

    ecfg = EngineConfig(
        slots=max(4, n_adapters), prompt_buckets=(32, 128),
        max_new_tokens=max_new + 8, page_tokens=page_tokens,
        tenant_slots=n_adapters + 1, tenant_rank=8,
    )
    multi = BatchEngine(base_model, base_vars, ecfg)
    for aid, tree in adapters.items():
        multi.adapters.register(aid, tree, 16.0, 4)
        multi.install_adapter(aid)
    reqs = [
        GenRequest(request_id=f"m-{aid}", tokens=prompts[aid],
                   max_new_tokens=max_new, adapter_id=aid)
        for aid in adapters
    ]
    multi.run(reqs)  # warm
    t0 = time.perf_counter()
    res_multi = multi.run(reqs)
    multi_window = time.perf_counter() - t0

    dedicated = {}
    for aid, tree in adapters.items():
        eng = BatchEngine(base_model, base_vars, EngineConfig(
            slots=2, prompt_buckets=(32, 128), max_new_tokens=max_new + 8,
            page_tokens=page_tokens, tenant_slots=2, tenant_rank=8,
        ))
        eng.adapters.register(aid, tree, 16.0, 4)
        eng.install_adapter(aid)
        dedicated[aid] = eng.run([GenRequest(
            request_id="d", tokens=prompts[aid], max_new_tokens=max_new,
            adapter_id=aid,
        )])["d"].generated

    for aid in adapters:
        if res_multi[f"m-{aid}"].generated != dedicated[aid]:
            fail(
                "multiplexed output differs from the dedicated engine",
                adapter=aid,
            )
    distinct = len({tuple(r.generated) for r in res_multi.values()})
    if distinct < 2:
        fail(  # the per-lane gather must actually select different weights
            "multiplexed tenants produced identical outputs",
            distinct=distinct, adapters=n_adapters,
        )
    total_tokens = sum(len(r.generated) for r in res_multi.values())
    return {
        "adapters": n_adapters,
        "bit_identical_vs_dedicated": True,
        "distinct_outputs": distinct,
        "multiplexed_tokens_per_sec": round(total_tokens / multi_window, 1),
        "engines_displaced": n_adapters,  # one shared fleet instead of N
    }


def _measure_serve_fleet(model, variables, prompts, *, n_requests, max_new,
                         slots) -> dict:
    """The ISSUE 10 fleet gates, run inside ``BENCH_MODE=serve``:

    1. **serve-chaos**: a seeded replica kill mid-mixed-workload at 2+
       replicas — every accepted request must complete EXACTLY once with
       greedy outputs bit-identical to an unkilled fleet run (none lost,
       none duplicated);
    2. **zero-downtime rollover**: a checkpoint rollover under sustained
       load must complete with 0 failed requests and drain-window p99
       latency <= 2x steady state (plus a small absolute grace for CPU
       compile jitter on the tiny preset — new replicas pay their prefill
       compiles inside the window).

    Both legs share the seeded ``resilience/faults.py::ServeFault``
    injection path with the serve-chaos tests (``tests/test_serve_fleet.py``).
    """
    import asyncio

    import numpy as np

    from finetune_controller_tpu.resilience.faults import (
        ServeFault,
        ServeFaultInjector,
    )
    from finetune_controller_tpu.serve.engine import EngineConfig, GenRequest
    from finetune_controller_tpu.serve.fleet import ReplicaFleet
    from finetune_controller_tpu.serve.router import ReplicaRouter

    n_replicas = max(2, int(os.environ.get("BENCH_SERVE_REPLICAS", "2")))
    kill_step = int(os.environ.get("BENCH_SERVE_KILL_STEP",
                                   str(max(2, max_new // 2))))
    ecfg = EngineConfig(slots=slots, prompt_buckets=(32, 128),
                        max_new_tokens=max_new + 8)

    def reqs(tag, new_tokens=max_new):
        return [
            GenRequest(request_id=f"{tag}{i}", tokens=p,
                       max_new_tokens=new_tokens)
            for i, p in enumerate(prompts)
        ]

    def pct(xs, p):
        return float(np.percentile(np.asarray(xs), p))

    async def fleet_run(fault=None, tag="u"):
        fleet = ReplicaFleet("bench", model, variables, ecfg,
                             replicas=n_replicas, fault=fault)
        await fleet.start()
        router = ReplicaRouter(fleet, default_timeout_s=300,
                               failover_retries=2)
        t0 = time.perf_counter()
        results = await asyncio.gather(
            *(router.submit(r) for r in reqs(tag))
        )
        window = time.perf_counter() - t0
        stats = fleet.stats()
        await fleet.close()
        return results, router, stats, window

    async def chaos_leg():
        baseline, _r, _s, _w = await fleet_run()
        base_tokens = {r.request_id[1:]: r.generated for r in baseline}
        fault = ServeFaultInjector(
            ServeFault(replica_id="r1", at_step=kill_step, mode="kill")
        )
        killed, router, stats, window = await fleet_run(fault=fault, tag="k")
        if not fault.fired:
            fail("serve-chaos kill never fired; raise the workload or "
                 "lower BENCH_SERVE_KILL_STEP", kill_step=kill_step)
        seen: dict[str, list[int]] = {}
        for r in killed:
            if r.request_id in seen:
                fail("serve-chaos: request completed twice",
                     request_id=r.request_id)
            seen[r.request_id] = r.generated
        if len(seen) != len(prompts):
            fail("serve-chaos: accepted requests were lost",
                 completed=len(seen), accepted=len(prompts))
        for rid, toks in seen.items():
            if toks != base_tokens[rid[1:]]:
                fail("serve-chaos: output diverged from the unkilled run",
                     request_id=rid)
        if stats["requests_completed_total"] != len(prompts):
            fail("serve-chaos: completion counter disagrees",
                 counted=stats["requests_completed_total"])
        return {
            "replicas": n_replicas,
            "kill_step": kill_step,
            "failovers": router.failovers_total,
            "step_errors": stats["step_errors_total"],
            "window_s": round(window, 3),
            "exactly_once": True,
            "bit_identical_to_unkilled": True,
        }

    async def rollover_leg():
        fleet = ReplicaFleet("bench-roll", model, variables, ecfg,
                             replicas=n_replicas)
        await fleet.start()
        router = ReplicaRouter(fleet, default_timeout_s=300,
                               failover_retries=2)
        failures: list[BaseException] = []

        async def wave(tag, lats):
            async def one(i, p):
                t1 = time.perf_counter()
                try:
                    await router.submit(GenRequest(
                        request_id=f"{tag}{i}", tokens=p, max_new_tokens=8,
                    ))
                    lats.append(time.perf_counter() - t1)
                except Exception as exc:
                    failures.append(exc)
            await asyncio.gather(
                *(one(i, p) for i, p in enumerate(prompts))
            )

        steady: list[float] = []
        for w in range(3):  # warm + steady-state sample
            await wave(f"s{w}-", steady if w else [])
        during: list[float] = []
        roll = asyncio.ensure_future(fleet.rollover(model, variables))
        w = 0
        while not roll.done():
            await wave(f"d{w}-", during)
            w += 1
        await roll
        # post-rollover sanity wave on the new generation
        await wave("post-", during)
        stats = fleet.stats()
        await fleet.close()
        if failures:
            fail("rollover dropped requests",
                 failed=len(failures), first=str(failures[0]))
        if stats["generation"] != 1 or stats["rollovers_total"] != 1:
            fail("rollover did not complete", **{
                k: stats[k] for k in ("generation", "rollovers_total")
            })
        p99_steady = pct(steady, 99)
        p99_during = pct(during, 99)
        # the 2x acceptance gate, with an absolute grace floor: on the tiny
        # CPU preset steady-state p99 is milliseconds, and the new
        # generation's prefill compiles land inside the drain window
        gate = max(2.0 * p99_steady, p99_steady + 0.75)
        if p99_during > gate:
            fail("rollover drain-window p99 exceeded 2x steady state",
                 p99_steady_s=round(p99_steady, 4),
                 p99_during_s=round(p99_during, 4))
        return {
            "failed_requests": 0,
            "p99_steady_s": round(p99_steady, 4),
            "p99_during_s": round(p99_during, 4),
            "p99_ratio": round(p99_during / max(p99_steady, 1e-9), 2),
            "drain_waves": w,
            "drains": stats["drains_total"],
        }

    async def both():
        return {
            "serve_chaos": await chaos_leg(),
            "rollover": await rollover_leg(),
        }

    return asyncio.run(both())


def main() -> None:
    if os.environ.get("BENCH_MODE", "").strip().lower() == "obs":
        # tracing-overhead gate: scale-free ratio on the tiny config, so it
        # runs on CPU by default like chaos/sched/dpo
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps(_measure_obs()))
        return
    if os.environ.get("BENCH_MODE", "").strip().lower() == "chaos":
        # controller-plane bench: the parent process needs no accelerator —
        # the trainers run as subprocesses with their own JAX runtime
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps(_measure_chaos_recovery()))
        return
    if os.environ.get("BENCH_MODE", "").strip().lower() == "sched":
        # scheduler-policy bench: pure simulator, no accelerator at all
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps(_measure_sched()))
        return
    if os.environ.get("BENCH_MODE", "").strip().lower() == "dpo":
        # preference-optimization gates (docs/preference.md): the gates are
        # scale-free (margin trend + held-out accuracy on the tiny config),
        # so this runs on CPU by default like chaos/sched — pin
        # JAX_PLATFORMS=tpu explicitly to measure pair throughput on chips
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps(_measure_dpo()))
        return
    _init_backend_with_fallback()
    import jax

    from finetune_controller_tpu.platform import assert_platform_env, env_flag

    assert_platform_env()

    if os.environ.get("BENCH_MODE", "").strip().lower() == "serve":
        result = _measure_serve()
        if jax.devices()[0].platform == "tpu":
            _session_log_append(result)
        print(json.dumps(result))
        return

    import numpy as np

    from finetune_controller_tpu.data.synthetic import synthetic_batches
    from finetune_controller_tpu.models.llama import PRESETS
    from finetune_controller_tpu.models.lora import LoRAConfig
    from finetune_controller_tpu.parallel.mesh import MeshSpec
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    tiny = env_flag("BENCH_TINY") or not on_tpu

    n_chips = len(devices)
    # Default global batch must divide evenly over the fsdp=all-chips mesh,
    # so scale it with the chip count (a v5e-16 slice gets batch 16, not 8).
    default_batch = max(8, n_chips)
    # BENCH_MODE selects the BASELINE config family:
    #   lora (default) — config #1 (TinyLlama LoRA)
    #   qlora          — config #3 (int4 frozen base; a 7B fits one v5e chip)
    #   mm             — config #5 (LLaVA multimodal SFT; int4 text tower +
    #                    bf16 ViT — that combination fits one chip)
    #   moe            — config #4 proxy (Mixtral-architecture 8-expert top-2
    #                    at single-chip scale, bf16 frozen base; MFU uses
    #                    active_param_count so idle experts earn no credit)
    mode = os.environ.get("BENCH_MODE", "lora").strip().lower()
    qlora = mode == "qlora"
    mm = mode == "mm"
    moe = mode == "moe"
    if tiny:
        preset = os.environ.get(
            "BENCH_PRESET",
            "tiny-mm-test" if mm else ("tiny-moe-test" if moe else "tiny-test"),
        )
        batch = int(os.environ.get("BENCH_BATCH", str(default_batch)))
        seq = int(os.environ.get("BENCH_SEQ", "128"))
        steps = int(os.environ.get("BENCH_STEPS", "10"))
        lora = LoRAConfig(rank=8)
    elif mm:
        preset = os.environ.get("BENCH_PRESET", "llava-1.5-7b")
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        # seq = TEXT tokens; the decoder additionally attends the 576-patch
        # image prefix, which the FLOP accounting below includes
        seq = int(os.environ.get("BENCH_SEQ", "1472"))
        steps = int(os.environ.get("BENCH_STEPS", "10"))
        lora = LoRAConfig(rank=16)
    elif moe:
        preset = os.environ.get("BENCH_PRESET", "mixtral-proxy")
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        seq = int(os.environ.get("BENCH_SEQ", "2048"))
        steps = int(os.environ.get("BENCH_STEPS", "10"))
        lora = LoRAConfig(rank=16)
    else:
        preset = os.environ.get(
            "BENCH_PRESET", "mistral-7b" if qlora else "tinyllama-1.1b"
        )
        batch = int(os.environ.get("BENCH_BATCH", str(default_batch)))
        seq = int(os.environ.get("BENCH_SEQ", "2048"))
        steps = int(os.environ.get("BENCH_STEPS", "20"))
        lora = LoRAConfig(rank=16)

    if mm:
        from finetune_controller_tpu.models.multimodal import MM_PRESETS

        base_presets = MM_PRESETS
    else:
        base_presets = PRESETS
    model_cfg = base_presets[preset].replace(lora=lora, max_seq_len=max(seq, 128))
    if qlora or (mm and not tiny):
        # int4 base; the d_ff-wide "mlp" remat saves don't fit next to a 7B
        # model's activations on one chip — full recompute is the measured
        # config (override via BENCH_REMAT_POLICY to experiment). For mm the
        # quantization covers the frozen text tower (the ViT + projector are
        # plain flax Dense and ride the bf16 frozen cast instead).
        model_cfg = model_cfg.replace(quantize_base=True, remat_policy="full")
    if os.environ.get("BENCH_REMAT_POLICY"):
        model_cfg = model_cfg.replace(remat_policy=os.environ["BENCH_REMAT_POLICY"])
    if os.environ.get("BENCH_ATTN_IMPL"):
        model_cfg = model_cfg.replace(attention_impl=os.environ["BENCH_ATTN_IMPL"])
    if os.environ.get("BENCH_LOGITS_DTYPE"):
        import jax.numpy as _jnp

        model_cfg = model_cfg.replace(
            logits_dtype=_jnp.dtype(os.environ["BENCH_LOGITS_DTYPE"])
        )
    probe_steps = min(5, steps)  # individually-blocked spread probe
    mesh = MeshSpec(fsdp=-1).build(devices)
    # bf16 storage for the frozen base halves its HBM footprint (measured
    # ~1% step win on its own, and the headroom is what lets the "mlp" remat
    # policy fit); the tiny CPU leg keeps f32 for checkpoint-test parity
    frozen_default = "bfloat16" if not tiny else ""
    train_cfg = TrainConfig(
        mode="lora", batch_size=batch, seq_len=seq,
        # 3 warmup + the individually-blocked probe window + the timed window
        # must all fit inside the LR schedule (steps past total_steps would
        # train at the clamped min-LR floor, not the declared regime)
        total_steps=steps + 3 + probe_steps,
        log_every=10**9, checkpoint_every=10**9,
        frozen_dtype=os.environ.get("BENCH_FROZEN_DTYPE", frozen_default) or None,
        # recompilation guard (analysis/recompile_guard.py): a step that
        # recompiles mid-window is a measurement bug (the timed window would
        # include XLA compiles), so the bench RAISES instead of printing a
        # slow number. Budget 0 disables; the default of 4 covers every batch
        # structure a bench run legitimately produces (text window, mm A/B
        # legs) while a per-step shape leak burns through it immediately.
        recompile_budget=int(os.environ.get("BENCH_RECOMPILE_BUDGET", "4")),
        recompile_action="raise",
        # transfer guard (analysis/transfer_guard.py): same contract for
        # device<->host syncs — a stray device_get / implicit np transfer
        # inside the timed step window ABORTS the bench instead of silently
        # serializing the dispatch pipeline. BENCH_TRANSFER_GUARD=0 disables.
        transfer_guard=(
            "raise" if env_flag("BENCH_TRANSFER_GUARD", default=True)
            else "off"
        ),
        # shard audit (analysis/shard_audit.py): state leaves that lose
        # their rule-table sharding pay a silent GSPMD reshard every step —
        # a slow number that is a BUG, not a result. Armed, a mis-sharded
        # run ABORTS. BENCH_SHARD_AUDIT=0 disables.
        shard_audit=(
            "raise" if env_flag("BENCH_SHARD_AUDIT", default=True)
            else "off"
        ),
    )
    trainer = Trainer(model_cfg, train_cfg, mesh=mesh)
    state = trainer.init_state()
    image_size = model_cfg.image_size  # 0 on text-only configs
    batches = synthetic_batches(
        batch, seq, model_cfg.vocab_size, seed=0,
        task="brightness" if mm else "increment",
        image_size=image_size,
    )
    # background input prefetch (data/prefetch.py) — the trainer-path default;
    # BENCH_PREFETCH=0 measures the synchronous legacy pipeline
    from finetune_controller_tpu.data.prefetch import prefetch_batches

    prefetch_depth = int(os.environ.get("BENCH_PREFETCH", "2"))
    batches = prefetch_batches(
        batches, depth=prefetch_depth, transfer=trainer._shard_batch
    )

    # Warmup: first step compiles; two more reach dispatch steady-state.
    warmup_losses = []
    for _ in range(3):
        state, metrics = trainer.step(state, next(batches))
        state = jax.block_until_ready(state)
        warmup_losses.append(float(metrics["loss"]))

    # Spread probe: a few individually-blocked steps expose per-step jitter
    # (compile stragglers, tunnel hiccups) that the overlapped window hides.
    probe_times: list[float] = []
    timed_losses: list[float] = []
    for _ in range(probe_steps):
        step_batch = next(batches)
        t0 = time.perf_counter()
        state, metrics = trainer.step(state, step_batch)
        state = jax.block_until_ready(state)
        probe_times.append(time.perf_counter() - t0)
        timed_losses.append(float(metrics["loss"]))

    # Timed window: dispatch all steps, block once on the final state — the
    # throughput an uninstrumented training loop achieves, with every step's
    # device work still forced to complete inside the window.  The input wait
    # (time blocked on next(batches)) is accounted separately: its share of
    # the window is the input_fraction the JSON reports.
    t0 = time.perf_counter()
    window_metrics = []
    input_s = 0.0
    for _ in range(steps):
        t_in = time.perf_counter()
        step_batch = next(batches)
        input_s += time.perf_counter() - t_in
        state, metrics = trainer.step(state, step_batch)
        window_metrics.append(metrics)
    state = jax.block_until_ready(state)
    window_s = time.perf_counter() - t0
    timed_losses += [float(m["loss"]) for m in window_metrics]
    if hasattr(batches, "close"):
        batches.close()

    # shard audit over the FINAL live state (the checkpoint-boundary trap,
    # run explicitly here since the bench never checkpoints): every device
    # leaf must still carry its rule-table NamedSharding after the timed
    # window, or the measured number was taxed by silent resharding
    if trainer._shard_auditor is not None:
        trainer._audit_state_sharding(state, "bench-final-state")

    # --- sanity: the steps must have done real optimization work -----------
    if not all(np.isfinite(warmup_losses + timed_losses)):
        fail("non-finite loss", warmup_losses=warmup_losses, timed_losses=timed_losses)
    if float(np.mean(timed_losses)) > float(np.mean(warmup_losses)) + 0.5:
        fail(
            "timed-window loss regressed above warmup — step is not optimizing",
            warmup_losses=warmup_losses, timed_losses=timed_losses,
        )

    med = window_s / steps
    p10 = float(np.percentile(probe_times, 10))
    p90 = float(np.percentile(probe_times, 90))
    tokens_per_step = batch * seq
    tok_per_sec_chip = tokens_per_step / med / n_chips

    if mm:
        # tokens = TEXT tokens, but the step's FLOPs also cover the decoder
        # attending the image prefix and the ViT+projector encoding it —
        # fold that into flops_per_(text-)token so the MFU stays honest
        patches = model_cfg.vision.n_patches
        n_text = model_cfg.text.param_count()
        n_vision = model_cfg.param_count() - n_text
        flops_per_step = 6.0 * (
            n_text * batch * (seq + patches) + n_vision * batch * patches
        )
        flops_per_token = flops_per_step / tokens_per_step
    else:
        # active_param_count == param_count on dense configs; on MoE it
        # counts the router + top-k experts a token actually runs through.
        # NOTE: capacity-factor padding means the expert einsums execute over
        # e*capacity slots (≈ capacity_factor × the credited k·T rows), so
        # executed FLOPs exceed this figure by ~capacity_factor on the expert
        # share — MoE MFU here is a deliberate LOWER BOUND (useful-work MFU:
        # padding slots earn no credit). Keep that in mind when tuning
        # against these numbers.
        flops_per_token = 6.0 * model_cfg.active_param_count()
    # --- plausibility guard, platform-independent: no single chip of any ---
    # known kind sustains more than the best published peak; a figure above
    # that is a measurement bug (e.g. an async runtime making steps look
    # free), not a result.  On a recognised TPU the guard tightens to that
    # chip's own peak via the MFU > 1.0 check below.
    achieved_flops = tok_per_sec_chip * flops_per_token
    if achieved_flops > BEST_KNOWN_PEAK_TFLOPS * 1e12:
        fail(
            "throughput exceeds any known chip's peak — measurement invalid",
            tok_per_sec_chip=round(tok_per_sec_chip, 1),
            implied_tflops=round(achieved_flops / 1e12, 1),
            best_known_peak_tflops=BEST_KNOWN_PEAK_TFLOPS,
            step_time_avg_s=med,
            platform=devices[0].platform,
        )
    mfu = None
    if on_tpu:
        peak = _peak_tflops(devices[0].device_kind) or 197.0
        target = TARGET_MFU * peak * 1e12 / flops_per_token
        mfu = achieved_flops / (peak * 1e12)
        # --- a >100% MFU figure is a measurement bug, not a result ---------
        if mfu > 1.0:
            fail(
                "achieved MFU > 1.0 — physically impossible, measurement invalid",
                mfu=round(mfu, 3),
                tok_per_sec_chip=round(tok_per_sec_chip, 1),
                step_time_avg_s=med,
                probe_step_p10_s=p10,
                probe_step_p90_s=p90,
                device_kind=devices[0].device_kind,
                peak_tflops=peak,
            )
    else:
        target = CPU_FALLBACK_TARGET_TOKENS_PER_SEC

    kind = "qlora" if qlora else ("mm_lora" if mm else ("moe_lora" if moe else "lora"))
    result = {
        "metric": f"{kind}_sft_tokens_per_sec_per_chip"
                  f"[{preset},bs{batch},seq{seq}]",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tok_per_sec_chip / target, 3),
        "mfu": None if mfu is None else round(mfu, 4),
        "fallback": env_flag("BENCH_IS_FALLBACK"),
        "step_time_avg_s": round(med, 4),
        "probe_step_p10_s": round(p10, 4),
        "probe_step_p90_s": round(p90, 4),
        "prefetch_depth": prefetch_depth,
        "input_ms_avg": round(input_s / steps * 1000, 3),
        "input_fraction": round(input_s / window_s, 4),
        "n_chips": n_chips,
        "device_kind": devices[0].device_kind,
        "warmup_loss_mean": round(float(np.mean(warmup_losses)), 4),
        "timed_loss_mean": round(float(np.mean(timed_losses)), 4),
        # the audit above ran to completion under action="raise", so an
        # armed run reaching this line proves zero violations
        "shard_audit_armed": trainer._shard_auditor is not None,
        "shard_audit_checks": (
            trainer._shard_auditor.checks
            if trainer._shard_auditor is not None else 0
        ),
        "shard_audit_violations": (
            trainer._shard_auditor.violations
            if trainer._shard_auditor is not None else 0
        ),
    }
    if mm and env_flag("BENCH_PREFETCH_AB", default=True):
        # prefetch off/on A/B over REAL decoded images (BASELINE #5's "mixed
        # host-image pipeline"): measured, not asserted — the JSON carries
        # both legs so a regression in the overlap is visible per round
        import tempfile

        with tempfile.TemporaryDirectory(prefix="ftc_mm_bench_") as d:
            ds = _write_mm_bench_dataset(
                d, n_rows=max(3 * batch, 24),
                src_px=max(512, 2 * image_size),
            )
            state, result["prefetch_ab"] = measure_mm_prefetch_ab(
                trainer, state, ds, image_size=image_size,
                batch=batch, seq=seq,
                steps=min(8, steps), depth=max(prefetch_depth, 1),
            )

    if on_tpu:
        _session_log_append(result)
    elif env_flag("BENCH_IS_FALLBACK"):
        # Tunnel outage: surface the latest committed chip measurement so the
        # round artifact still carries a TPU number next to the honest
        # clearly-labelled CPU figure.
        requested_kind = os.environ.get("BENCH_FALLBACK_KIND", kind)
        cached = _latest_session_tpu_record(f"{requested_kind}_")
        if cached is not None:
            result["source"] = "cpu-fallback+session-cache"
            result["tpu_session_cache"] = cached
    print(json.dumps(result))


if __name__ == "__main__":
    main()
