"""Headline benchmark: LoRA SFT decode-training throughput, tokens/sec/chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference (`acceleratedscience/finetune-controller`) publishes **no**
performance numbers (BASELINE.json: "published": {}) — it is a k8s control
plane whose training throughput belongs to user containers.  The baseline is
therefore self-established: ``vs_baseline`` is measured throughput divided by
a roofline-derived target for the benchmark hardware (40% MFU on the model's
6*N FLOPs/token), so >1.0 means we beat the target, and the number stays
comparable across rounds.

Env knobs: BENCH_PRESET, BENCH_STEPS, BENCH_BATCH, BENCH_SEQ, BENCH_TINY=1
(CI-sized run).
"""

from __future__ import annotations

import json
import os
import time


# Peak bf16 TFLOP/s per chip, by jax device_kind substring (public specs).
PEAK_TFLOPS = [
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]
TARGET_MFU = 0.40
CPU_FALLBACK_TARGET_TOKENS_PER_SEC = 2000.0  # tiny model on one CPU host


def _peak_tflops(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for key, tflops in PEAK_TFLOPS:
        if key in kind:
            return tflops
    return None


def main() -> None:
    import jax
    import numpy as np

    from finetune_controller_tpu.data.synthetic import synthetic_batches
    from finetune_controller_tpu.models.llama import PRESETS
    from finetune_controller_tpu.models.lora import LoRAConfig
    from finetune_controller_tpu.parallel.mesh import MeshSpec
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    tiny = bool(os.environ.get("BENCH_TINY")) or not on_tpu

    n_chips = len(devices)
    # Default global batch must divide evenly over the fsdp=all-chips mesh,
    # so scale it with the chip count (a v5e-16 slice gets batch 16, not 8).
    default_batch = max(8, n_chips)
    if tiny:
        preset = os.environ.get("BENCH_PRESET", "tiny-test")
        batch = int(os.environ.get("BENCH_BATCH", str(default_batch)))
        seq = int(os.environ.get("BENCH_SEQ", "128"))
        steps = int(os.environ.get("BENCH_STEPS", "10"))
        lora = LoRAConfig(rank=8)
    else:
        preset = os.environ.get("BENCH_PRESET", "tinyllama-1.1b")
        batch = int(os.environ.get("BENCH_BATCH", str(default_batch)))
        seq = int(os.environ.get("BENCH_SEQ", "2048"))
        steps = int(os.environ.get("BENCH_STEPS", "20"))
        lora = LoRAConfig(rank=16)

    model_cfg = PRESETS[preset].replace(lora=lora, max_seq_len=max(seq, 128))
    mesh = MeshSpec(fsdp=-1).build(devices)
    train_cfg = TrainConfig(
        mode="lora", batch_size=batch, seq_len=seq,
        total_steps=steps + 3, log_every=10**9, checkpoint_every=10**9,
    )
    trainer = Trainer(model_cfg, train_cfg, mesh=mesh)
    state = trainer.init_state()
    batches = synthetic_batches(batch, seq, model_cfg.vocab_size, seed=0)

    # Warmup (compile + 2 steady steps), then timed window.
    for _ in range(3):
        state, metrics = trainer.step(state, next(batches))
    jax.block_until_ready(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.step(state, next(batches))
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens = steps * batch * seq
    tok_per_sec_chip = tokens / dt / n_chips

    if on_tpu:
        peak = _peak_tflops(devices[0].device_kind) or 197.0
        flops_per_token = 6.0 * model_cfg.param_count()
        target = TARGET_MFU * peak * 1e12 / flops_per_token
    else:
        target = CPU_FALLBACK_TARGET_TOKENS_PER_SEC
    print(json.dumps({
        "metric": f"lora_sft_tokens_per_sec_per_chip[{preset},bs{batch},seq{seq}]",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tok_per_sec_chip / target, 3),
    }))


if __name__ == "__main__":
    main()
