#!/usr/bin/env python
"""Dump the Kueue CRDs (TPU ResourceFlavors + ClusterQueue + LocalQueues)
generated from the device catalog, plus the controller Deployments, as YAML
for `kubectl apply -f` (reference: static `crds/kueue/*.yaml` the operator had
to hand-edit; ours are derived from the same catalog the scheduler enforces —
`controller/backends/k8s.py:render_kueue_crds`).

Usage:
    python scripts/render_crds.py [--device-config config.json] \
        [--namespace default] [--out deploy/]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import yaml

from finetune_controller_tpu.controller.backends.k8s import render_kueue_crds
from finetune_controller_tpu.controller.devices import load_catalog


def controller_deployments(namespace: str, image: str) -> list[dict]:
    """ONE Deployment running the API and monitor as two containers in the
    same pod, sharing the state volume.

    The reference deploys the two processes as separate Deployments sharing
    an external MongoDB (``scripts/cluster_install.sh``; SURVEY.md §1); the
    rebuild's store is an embedded WAL-mode SQLite file, which is
    multi-process-safe only on one host — so the layout co-locates the two
    processes in one pod (same node, shared volume) rather than pretending
    two Deployments could land anywhere and still share the file.
    """
    state_mount = {"name": "state", "mountPath": "/state"}
    shared_env = [
        {"name": "FTC_BACKEND", "value": "k8s"},
        {"name": "FTC_OBJECT_STORE_BACKEND", "value": "gcs"},
        {"name": "FTC_NAMESPACE", "value": namespace},
        {"name": "FTC_STATE_DIR", "value": "/state"},
        {"name": "FTC_STATE_BACKEND", "value": "sqlite"},
    ]
    api = {
        "name": "api",
        "image": image,
        "command": ["python", "-m", "finetune_controller_tpu.controller.server",
                    "--host", "0.0.0.0", "--port", "8787"],
        "env": shared_env,
        "ports": [{"containerPort": 8787}],
        "volumeMounts": [state_mount],
    }
    monitor = {
        "name": "monitor",
        "image": image,
        "command": ["python", "-m",
                    "finetune_controller_tpu.controller.monitor_main"],
        "env": shared_env,
        "volumeMounts": [state_mount],
    }
    pvc = {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": "finetune-controller-state", "namespace": namespace},
        "spec": {
            "accessModes": ["ReadWriteOnce"],
            "resources": {"requests": {"storage": "10Gi"}},
        },
    }
    deployment = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "finetune-controller", "namespace": namespace},
        "spec": {
            # single writer-pod by construction: the embedded store is shared
            # within the pod, not across replicas
            "replicas": 1,
            "strategy": {"type": "Recreate"},  # two pods must never share the PVC
            "selector": {"matchLabels": {"app": "finetune-controller"}},
            "template": {
                "metadata": {"labels": {"app": "finetune-controller"}},
                "spec": {
                    "serviceAccountName": "finetune-controller",
                    "containers": [api, monitor],
                    "volumes": [{
                        "name": "state",
                        "persistentVolumeClaim": {
                            "claimName": "finetune-controller-state"
                        },
                    }],
                },
            },
        },
    }
    service = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": "finetune-controller-api", "namespace": namespace},
        "spec": {
            "selector": {"app": "finetune-controller"},
            "ports": [{"port": 80, "targetPort": 8787}],
        },
    }
    return [pvc, deployment, service]


def ha_deployments(namespace: str, image: str, api_replicas: int = 3) -> list[dict]:
    """The HA layout (round-5): N stateless API replicas + one monitor, all
    pointing ``state_backend=remote`` at the shared state service — the role
    the reference's external MongoDB plays for its API×4 + monitor split
    (``app/database/db.py:51``). Only the state service owns the PVC, so the
    API replicas can land on any node and scale horizontally; rate limits
    enforced through the service are cluster-scope."""
    token_env = {
        "name": "FTC_STATE_SERVICE_TOKEN",
        "valueFrom": {"secretKeyRef": {
            "name": "finetune-controller-state-token", "key": "token",
        }},
    }
    svc_token_env = {
        "name": "FTC_STATE_TOKEN",
        "valueFrom": {"secretKeyRef": {
            "name": "finetune-controller-state-token", "key": "token",
        }},
    }
    shared_env = [
        {"name": "FTC_BACKEND", "value": "k8s"},
        {"name": "FTC_OBJECT_STORE_BACKEND", "value": "gcs"},
        {"name": "FTC_NAMESPACE", "value": namespace},
        {"name": "FTC_STATE_BACKEND", "value": "remote"},
        {"name": "FTC_STATE_SERVICE_URL",
         "value": "http://finetune-controller-state:8081"},
        token_env,
    ]
    pvc = {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": "finetune-controller-state", "namespace": namespace},
        "spec": {
            "accessModes": ["ReadWriteOnce"],
            "resources": {"requests": {"storage": "10Gi"}},
        },
    }
    statestore = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "finetune-controller-state", "namespace": namespace},
        "spec": {
            "replicas": 1,  # the one stateful writer; everyone else is stateless
            "strategy": {"type": "Recreate"},
            "selector": {"matchLabels": {"app": "finetune-controller-state"}},
            "template": {
                "metadata": {"labels": {"app": "finetune-controller-state"}},
                "spec": {
                    "containers": [{
                        "name": "statestore",
                        "image": image,
                        "command": [
                            "python", "-m",
                            "finetune_controller_tpu.controller.statestore_main",
                            "--state-dir", "/state", "--port", "8081",
                        ],
                        "env": [svc_token_env],
                        "ports": [{"containerPort": 8081}],
                        "volumeMounts": [{"name": "state", "mountPath": "/state"}],
                        "readinessProbe": {
                            "httpGet": {"path": "/healthz", "port": 8081},
                        },
                    }],
                    "volumes": [{
                        "name": "state",
                        "persistentVolumeClaim": {
                            "claimName": "finetune-controller-state"
                        },
                    }],
                },
            },
        },
    }
    state_svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": "finetune-controller-state", "namespace": namespace},
        "spec": {
            "selector": {"app": "finetune-controller-state"},
            "ports": [{"port": 8081, "targetPort": 8081}],
        },
    }
    api = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "finetune-controller-api", "namespace": namespace},
        "spec": {
            "replicas": api_replicas,
            "selector": {"matchLabels": {"app": "finetune-controller-api"}},
            "template": {
                "metadata": {"labels": {"app": "finetune-controller-api"}},
                "spec": {
                    "serviceAccountName": "finetune-controller",
                    "containers": [{
                        "name": "api",
                        "image": image,
                        "command": [
                            "python", "-m",
                            "finetune_controller_tpu.controller.server",
                            "--host", "0.0.0.0", "--port", "8787",
                        ],
                        "env": shared_env + [
                            {"name": "FTC_MONITOR_IN_PROCESS", "value": "false"},
                        ],
                        "ports": [{"containerPort": 8787}],
                    }],
                },
            },
        },
    }
    monitor = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "finetune-controller-monitor",
                     "namespace": namespace},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": "finetune-controller-monitor"}},
            "template": {
                "metadata": {
                    "labels": {"app": "finetune-controller-monitor"}
                },
                "spec": {
                    "serviceAccountName": "finetune-controller",
                    "containers": [{
                        "name": "monitor",
                        "image": image,
                        "command": [
                            "python", "-m",
                            "finetune_controller_tpu.controller.monitor_main",
                        ],
                        "env": shared_env,
                    }],
                },
            },
        },
    }
    api_svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": "finetune-controller-api", "namespace": namespace},
        "spec": {
            "selector": {"app": "finetune-controller-api"},
            "ports": [{"port": 80, "targetPort": 8787}],
        },
    }
    return [pvc, statestore, state_svc, api, monitor, api_svc]


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--device-config", default=None,
                   help="device catalog JSON (defaults to the built-in catalog)")
    p.add_argument("--namespace", default="default")
    p.add_argument("--image", default="finetune-controller-tpu:latest")
    p.add_argument("--out", default="deploy")
    p.add_argument("--layout", choices=("single", "ha"), default="single",
                   help="single: API+monitor co-located with an embedded "
                        "sqlite store; ha: N stateless API replicas + monitor "
                        "sharing the state service")
    p.add_argument("--api-replicas", type=int, default=3,
                   help="API replica count for --layout ha")
    args = p.parse_args()

    catalog = load_catalog(args.device_config)
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)

    crds = render_kueue_crds(catalog, namespace=args.namespace)
    (out / "kueue-crds.yaml").write_text(yaml.safe_dump_all(crds, sort_keys=False))
    if args.layout == "ha":
        deployments = ha_deployments(
            args.namespace, args.image, args.api_replicas
        )
    else:
        deployments = controller_deployments(args.namespace, args.image)
    (out / "controller.yaml").write_text(
        yaml.safe_dump_all(deployments, sort_keys=False)
    )
    print(f"wrote {out / 'kueue-crds.yaml'} ({len(crds)} objects)")
    print(f"wrote {out / 'controller.yaml'} ({len(deployments)} objects)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
