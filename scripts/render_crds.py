#!/usr/bin/env python
"""Dump the Kueue CRDs (TPU ResourceFlavors + ClusterQueue + LocalQueues)
generated from the device catalog, plus the controller Deployments, as YAML
for `kubectl apply -f` (reference: static `crds/kueue/*.yaml` the operator had
to hand-edit; ours are derived from the same catalog the scheduler enforces —
`controller/backends/k8s.py:render_kueue_crds`).

Usage:
    python scripts/render_crds.py [--device-config config.json] \
        [--namespace default] [--out deploy/]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import yaml

from finetune_controller_tpu.controller.backends.k8s import render_kueue_crds
from finetune_controller_tpu.controller.devices import load_catalog


def controller_deployments(namespace: str, image: str) -> list[dict]:
    """API + monitor Deployments (reference: scripts/cluster_install.sh
    deploys both processes; SURVEY.md §1)."""

    def deployment(name: str, command: list[str], port: int | None) -> dict:
        container = {
            "name": name,
            "image": image,
            "command": command,
            "env": [
                {"name": "FTC_BACKEND", "value": "k8s"},
                {"name": "FTC_OBJECT_STORE_BACKEND", "value": "gcs"},
                {"name": "FTC_NAMESPACE", "value": namespace},
            ],
        }
        if port is not None:
            container["ports"] = [{"containerPort": port}]
        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": namespace},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app": name}},
                "template": {
                    "metadata": {"labels": {"app": name}},
                    "spec": {
                        "serviceAccountName": "finetune-controller",
                        "containers": [container],
                    },
                },
            },
        }

    api = deployment(
        "finetune-controller-api",
        ["python", "-m", "finetune_controller_tpu.controller.server",
         "--host", "0.0.0.0", "--port", "8787"],
        8787,
    )
    monitor = deployment(
        "finetune-controller-monitor",
        ["python", "-m", "finetune_controller_tpu.controller.monitor_main"],
        None,
    )
    service = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": "finetune-controller-api", "namespace": namespace},
        "spec": {
            "selector": {"app": "finetune-controller-api"},
            "ports": [{"port": 80, "targetPort": 8787}],
        },
    }
    return [api, monitor, service]


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--device-config", default=None,
                   help="device catalog JSON (defaults to the built-in catalog)")
    p.add_argument("--namespace", default="default")
    p.add_argument("--image", default="finetune-controller-tpu:latest")
    p.add_argument("--out", default="deploy")
    args = p.parse_args()

    catalog = load_catalog(args.device_config)
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)

    crds = render_kueue_crds(catalog, namespace=args.namespace)
    (out / "kueue-crds.yaml").write_text(yaml.safe_dump_all(crds, sort_keys=False))
    deployments = controller_deployments(args.namespace, args.image)
    (out / "controller.yaml").write_text(
        yaml.safe_dump_all(deployments, sort_keys=False)
    )
    print(f"wrote {out / 'kueue-crds.yaml'} ({len(crds)} objects)")
    print(f"wrote {out / 'controller.yaml'} ({len(deployments)} objects)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
