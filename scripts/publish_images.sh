#!/usr/bin/env bash
# Build and push the controller image (reference: scripts/publish_local.sh +
# publish_git.sh — build from the working tree or a clean git archive, tag,
# push to a registry).
#
# One image serves all three roles (API / monitor / trainer pod) — the
# rendered Deployments override the command (scripts/render_crds.py), so
# nothing consumes a separate monitor image. Dockerfile.monitor exists for
# operators who want a dedicated monitor image and can be built the same way.
#
# Usage:
#   scripts/publish_images.sh REGISTRY [TAG] [--git]
#
#   REGISTRY  e.g. us-docker.pkg.dev/my-proj/ftc or ghcr.io/my-org
#   TAG       defaults to the short git SHA (plus -dirty when the working
#             tree is and the build uses it)
#   --git     build from `git archive HEAD` instead of the working tree, so
#             the image provably matches a commit
set -euo pipefail

REGISTRY="${1:?usage: publish_images.sh REGISTRY [TAG] [--git]}"
TAG="${2:-}"
MODE="${3:-}"

if [[ "${TAG}" == "--git" ]]; then
  MODE="--git"
  TAG=""
fi
if [[ -n "${MODE}" && "${MODE}" != "--git" ]]; then
  # a typo'd --git must not silently publish a working-tree build that
  # claims commit provenance
  echo "error: unrecognized argument '${MODE}' (expected --git)" >&2
  exit 2
fi
if [[ -z "${TAG}" ]]; then
  TAG="$(git rev-parse --short HEAD)"
  # a --git build comes from the clean HEAD archive — it IS the commit,
  # dirty working tree or not; only working-tree builds get the suffix
  if [[ "${MODE}" != "--git" && -n "$(git status --porcelain)" ]]; then
    TAG="${TAG}-dirty"
  fi
fi

CTX="."
CLEANUP=""
if [[ "${MODE}" == "--git" ]]; then
  CTX="$(mktemp -d)"
  CLEANUP="${CTX}"
  trap '[[ -n "${CLEANUP}" ]] && rm -rf "${CLEANUP}"' EXIT
  git archive HEAD | tar -x -C "${CTX}"
  echo "==> building from clean git archive of $(git rev-parse HEAD)"
fi

IMAGE="${REGISTRY}/finetune-controller-tpu:${TAG}"
echo "==> building ${IMAGE}"
# the Dockerfile must come from the build context too, or a --git build
# would silently use uncommitted Dockerfile edits
docker build -f "${CTX}/Dockerfile" -t "${IMAGE}" "${CTX}"
echo "==> pushing ${IMAGE}"
docker push "${IMAGE}"

echo "==> done. Deploy with IMAGE=${IMAGE} scripts/cluster_install.sh"
