#!/usr/bin/env python
"""One-shot TPU measurement session — run when the axon tunnel is up.

Runs, in order, everything round 3 owes the chip (VERDICT r2 next-round
items 1, 3, 5 + the pending compiled-segments parity check), recording every
result to a JSONL log so a mid-session tunnel drop loses nothing:

1. compiled-with-segments Pallas parity (fwd + grads vs XLA, real TPU — the
   CPU CI only exercises interpreter mode);
2. headline bench (TinyLlama bs8 seq2048) — target MFU >= 0.406;
3. long-context kernel A/B: exp dtype {f32, bf16} x block {512, 1024} on the
   seq-8192 flash grad microbench;
4. long-context bench: TinyLlama seq8192 with the A/B winner, and
   Mistral-7B QLoRA seq8192 (head-dim-128 shapes);
5. Gemma-7B + Qwen2-7B QLoRA measurements (first batch size that fits HBM);
6. 7B cached-decode generation smoke (cold/warm latency + decode tok/s).

Round 6 adds `baseline_rows`: one committed record for every BASELINE table
entry still cited only in prose (Llama-3.2-1B/3B, the 16k-context Mistral
point, the Llama-3-8B QLoRA proxy, and `BENCH_MODE=mm` — whose record now
also carries the input-pipeline prefetch off/on A/B).

Usage:  python scripts/tpu_session.py [--log tpu_session.jsonl] [--only STEP]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def log_result(log_path: Path, record: dict) -> None:
    record = {"ts": round(time.time(), 1), **record}
    with log_path.open("a") as f:
        f.write(json.dumps(record) + "\n")
    print("LOGGED:", json.dumps(record), flush=True)


def run_bench(env_overrides: dict[str, str], timeout: float = 1500.0) -> dict:
    """Run bench.py with overrides; return its JSON line (or error record)."""
    env = dict(os.environ)
    env.update(env_overrides)
    env.setdefault("BENCH_NO_CPU_FALLBACK", "1")  # this session IS the probe
    # the session writes its own step-named record below — suppress bench.py's
    # ad-hoc auto-append so each measurement lands exactly once
    env["BENCH_SESSION_LOG"] = "0"
    try:
        out = subprocess.run(
            [sys.executable, str(REPO / "bench.py")],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {"error": "timeout", "env": env_overrides}
    if out.returncode != 0:
        tail = "\n".join(out.stderr.strip().splitlines()[-8:])
        oom = "Exceeded hbm capacity" in out.stderr or "RESOURCE_EXHAUSTED" in out.stderr
        return {"error": "oom" if oom else "failed", "env": env_overrides,
                "stderr_tail": tail}
    try:
        return {"env": env_overrides,
                **json.loads(out.stdout.strip().splitlines()[-1])}
    except (json.JSONDecodeError, IndexError):
        return {"error": "no-json", "env": env_overrides,
                "stdout_tail": out.stdout[-500:]}


# ---------------------------------------------------------------------------
# step 1: compiled-with-segments parity on real TPU
# ---------------------------------------------------------------------------

PARITY_SNIPPET = r"""
import jax, numpy as np
import jax.numpy as jnp
from finetune_controller_tpu.ops.pallas.flash_attention import flash_attention
from finetune_controller_tpu.ops.attention import xla_causal_attention

assert jax.devices()[0].platform == "tpu", jax.devices()
rng = np.random.default_rng(0)
b, s, h, hkv, d = 2, 2048, 8, 4, 64
q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.bfloat16)
v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.bfloat16)
# packed-document segments: monotone ids with ragged boundaries + padded tail
seg = np.zeros((b, s), np.int32)
for row in range(b):
    bounds = sorted(rng.choice(np.arange(64, s - 64), 5, replace=False))
    for i, lo in enumerate(bounds):
        seg[row, lo:] = i + 1
seg[:, -37:] = 99  # padding segment
seg = jnp.asarray(seg)

ref = xla_causal_attention(q, k, v, segment_ids=seg)
out = jax.jit(
    lambda q, k, v: flash_attention(q, k, v, segment_ids=seg, interpret=False)
)(q, k, v)
err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))))

def loss_flash(q, k, v):
    o = flash_attention(q, k, v, segment_ids=seg, interpret=False)
    return jnp.sum(o.astype(jnp.float32) ** 2)

def loss_ref(q, k, v):
    return jnp.sum(xla_causal_attention(q, k, v, segment_ids=seg).astype(jnp.float32) ** 2)

gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
gerr = max(
    float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
    for a, b in zip(gf, gr)
)
import json
print(json.dumps({"fwd_max_err": err, "grad_max_err": gerr,
                  "ok": bool(err < 3e-2 and gerr < 2.0)}))
"""


def _run_snippet(log_path: Path, step: str, snippet: str, timeout: float) -> dict | None:
    """Run a measurement snippet in a TPU subprocess; log-and-continue on any
    failure (timeout, crash, or chatty/non-JSON stdout) so one bad step never
    kills the rest of the session."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", snippet],
            capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "tpu"}, cwd=str(REPO),
        )
    except subprocess.TimeoutExpired:
        log_result(log_path, {"step": step, "error": "timeout"})
        return None
    if out.returncode != 0:
        log_result(log_path, {"step": step, "error": "failed",
                              "stderr_tail": out.stderr[-1000:]})
        return None
    for line in reversed(out.stdout.strip().splitlines() or [""]):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    log_result(log_path, {"step": step, "error": "no-json",
                          "stdout_tail": out.stdout[-500:]})
    return None


def step_parity(log_path: Path) -> None:
    rec = _run_snippet(log_path, "segment_parity_tpu", PARITY_SNIPPET, 900)
    if rec is not None:
        log_result(log_path, {"step": "segment_parity_tpu", **rec})


# ---------------------------------------------------------------------------
# step 3: long-context kernel A/B (exp dtype x block size)
# ---------------------------------------------------------------------------

KERNEL_AB_SNIPPET = r"""
import json
import jax
from finetune_controller_tpu.ops.kernel_bench import bench_flash_variants

assert jax.devices()[0].platform == "tpu"
# TinyLlama long-context shape (b2 h32/4 d64 seq8192), chained timing —
# reproducible by hand: python -m finetune_controller_tpu.ops.kernel_bench
#   --flash-variants --batch 2 --seq 8192
results = bench_flash_variants()
print(json.dumps({k: round(v * 1e3, 2) for k, v in results.items()}))
"""


def step_kernel_ab(log_path: Path) -> None:
    rec = _run_snippet(log_path, "kernel_ab_seq8192", KERNEL_AB_SNIPPET, 1200)
    if rec is not None:
        log_result(log_path, {"step": "kernel_ab_seq8192",
                              "grad_ms_per_call": rec})


GEN7B_SNIPPET = r"""
import time, json
import jax, numpy as np
import jax.numpy as jnp
from finetune_controller_tpu.models.llama import PRESETS
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.models.generate import cached_generate
from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

assert jax.devices()[0].platform == "tpu"
# Mistral-7B int4 (the measured QLoRA config) with random weights: proves the
# cached decode path is USABLE at 7B (VERDICT r2 weak #7) and measures its
# latency; output quality needs a real finetune, not this smoke.
cfg = PRESETS["mistral-7b"].replace(
    lora=LoRAConfig(rank=16), quantize_base=True, remat_policy="full",
    max_seq_len=256 + 64,
)
tc = TrainConfig(mode="lora", batch_size=1, seq_len=256, total_steps=1,
                 frozen_dtype="bfloat16")
tr = Trainer(cfg, tc)
state = tr.init_state()
variables = tr._assemble(state.frozen, state.trainable)
prompt = jnp.asarray(np.arange(256)[None, :] % 1000, jnp.int32)

def timed(n_new):
    t0 = time.perf_counter()
    out = cached_generate(tr.model, variables, prompt, max_new_tokens=n_new)
    jax.block_until_ready(out)
    return time.perf_counter() - t0, out

cold_s, out = timed(64)          # includes fill + decode compiles
warm64_s, out = timed(64)        # jitted fns cached module-level -> no recompile
timed(8)                         # n=8 shapes compile once...
warm8_s, _ = timed(8)            # ...then warm
# decode rate isolated from the 256-token prefill: both warm windows share
# the fill cost, so the difference is 56 pure decode steps
decode_tok_per_s = 56 / max(warm64_s - warm8_s, 1e-6)
print(json.dumps({
    "tokens": 64, "cold_s": round(cold_s, 2), "warm_s": round(warm64_s, 2),
    "full_call_tok_per_s": round(64 / warm64_s, 2),
    "decode_tok_per_s": round(decode_tok_per_s, 2),
    "shape_ok": bool(out.shape == (1, 320)),
}))
"""


def step_gen7b(log_path: Path) -> None:
    rec = _run_snippet(log_path, "gen7b_cached_decode", GEN7B_SNIPPET, 1500)
    if rec is not None:
        log_result(log_path, {"step": "gen7b_cached_decode", **rec})


# ---------------------------------------------------------------------------
# bench steps
# ---------------------------------------------------------------------------


def step_headline(log_path: Path) -> None:
    rec = run_bench({})
    log_result(log_path, {"step": "headline_tinyllama_seq2048", **rec})


def step_headline_tuned(log_path: Path, winner_env: dict[str, str]) -> None:
    """Headline config re-measured under the kernel A/B winner — if bf16 exp
    or a different block size wins at long sequence, check whether the
    seq-2048 headline moves too (it may not: attention is ~15% of that
    step)."""
    if not winner_env:
        print("no kernel A/B winner recorded; skipping tuned headline",
              flush=True)
        return
    rec = run_bench(dict(winner_env))
    log_result(log_path, {"step": "headline_tinyllama_seq2048_tuned", **rec})


def step_longctx(log_path: Path, winner_env: dict[str, str]) -> None:
    rec = run_bench({"BENCH_SEQ": "8192", "BENCH_BATCH": "2", **winner_env})
    log_result(log_path, {"step": "longctx_tinyllama_seq8192", **rec})
    # head-dim-128 long-context shapes (VERDICT r2 #3): Mistral-7B QLoRA
    for batch in ("2", "1"):
        rec = run_bench({
            "BENCH_MODE": "qlora", "BENCH_SEQ": "8192", "BENCH_BATCH": batch,
            "BENCH_LOGITS_DTYPE": "bfloat16", **winner_env,
        })
        log_result(log_path, {"step": f"longctx_mistral7b_seq8192_bs{batch}", **rec})
        if "error" not in rec:
            break


def step_new_families(log_path: Path) -> None:
    for preset, batches in (("gemma-7b", ("4", "2", "1")),
                            ("qwen2-7b", ("4", "2", "1"))):
        for batch in batches:
            rec = run_bench({
                "BENCH_MODE": "qlora", "BENCH_PRESET": preset,
                "BENCH_BATCH": batch, "BENCH_LOGITS_DTYPE": "bfloat16",
            })
            log_result(log_path, {"step": f"qlora_{preset}_bs{batch}", **rec})
            if "error" not in rec:
                break


def step_moe(log_path: Path) -> None:
    """MoE proxies (added after the 2026-07-31 tunnel drop): reproduce the
    committed permutation-dispatch numbers (mixtral-proxy bs4 MFU 0.5374,
    bs8 0.4912; proxy-10b int4 bs8 0.3268 — BASELINE rows 4/10) and run the
    one probe the outage interrupted, bs8 with bf16 logits."""
    for step, env in (
        ("moe_proxy_bs4", {"BENCH_MODE": "moe"}),
        ("moe_proxy_bs8_bf16logits",
         {"BENCH_MODE": "moe", "BENCH_BATCH": "8",
          "BENCH_LOGITS_DTYPE": "bfloat16"}),
        ("moe_proxy10b_bs8",
         {"BENCH_MODE": "qlora", "BENCH_PRESET": "mixtral-proxy-10b",
          "BENCH_BATCH": "8", "BENCH_LOGITS_DTYPE": "bfloat16"}),
    ):
        rec = run_bench(dict(env))
        log_result(log_path, {"step": step, **rec})


def step_baseline_rows(log_path: Path) -> None:
    """Erase the remaining prose-only BASELINE rows (VERDICT r5 next-round
    #3): every table entry whose number lives only in BASELINE.md prose gets
    a committed `tpu_session.jsonl` record in one tunnel-up window. Configs
    are copied verbatim from the rows' own reproduction command lines
    (BASELINE rows 2, 5, 8, 9 and the 16k long-context table)."""
    for step, env in (
        # rows 8/9: the Llama-3.2 family (128k-vocab → bf16 logits to fit)
        ("lora_llama3.2-1b_bs4",
         {"BENCH_PRESET": "llama3.2-1b", "BENCH_BATCH": "4",
          "BENCH_LOGITS_DTYPE": "bfloat16"}),
        ("lora_llama3.2-3b_bs2",
         {"BENCH_PRESET": "llama3.2-3b", "BENCH_BATCH": "2",
          "BENCH_LOGITS_DTYPE": "bfloat16"}),
        # long-context table: deepest single-chip point, 16k on the 32k preset
        ("longctx_mistral7b-32k_seq16384_bs1",
         {"BENCH_MODE": "qlora", "BENCH_PRESET": "mistral-7b-32k",
          "BENCH_SEQ": "16384", "BENCH_BATCH": "1",
          "BENCH_LOGITS_DTYPE": "bfloat16"}),
        # row 2's single-chip proxy: Llama-3-8B QLoRA int4
        ("qlora_llama3-8b_bs4",
         {"BENCH_MODE": "qlora", "BENCH_PRESET": "llama3-8b",
          "BENCH_BATCH": "4", "BENCH_LOGITS_DTYPE": "bfloat16"}),
        # row 5: LLaVA multimodal SFT — also carries the prefetch off/on A/B
        # over real decoded images (input_fraction + prefetch_ab in the JSON)
        ("mm_llava_bs4", {"BENCH_MODE": "mm"}),
    ):
        rec = run_bench(dict(env))
        log_result(log_path, {"step": step, **rec})


def step_fidelity(log_path: Path) -> None:
    """Round-5 fidelity proof on the chip (VERDICT #1/#9): the full
    pretrain→export→controller-LoRA→before/after-generation pipeline via
    scripts/fidelity_proof.py, which appends its own `fidelity` record to
    THIS session log when it sees a TPU platform (--session-log plumbs the
    path so a --log override keeps success and failure records together)."""
    try:
        out = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "fidelity_proof.py"),
             "--session-log", str(log_path)],
            capture_output=True, text=True, timeout=3600,
        )
    except subprocess.TimeoutExpired:
        # a crash-resilient session must RECORD the timeout, not die on it
        log_result(log_path, {
            "step": "fidelity", "error": "timeout after 3600s",
        })
        return
    if out.returncode != 0:
        log_result(log_path, {
            "step": "fidelity", "error": out.stderr[-800:],
        })
    else:
        print(out.stdout[-400:], flush=True)


def winner_from_log(log_path: Path) -> dict[str, str]:
    """Latest kernel_ab verdict recorded in the session log, as env vars."""
    best: dict[str, str] = {}
    if not log_path.exists():
        return best
    for line in log_path.read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        times = rec.get("grad_ms_per_call")
        if rec.get("step") == "kernel_ab_seq8192" and times:
            fastest = min(times, key=times.get)
            edt, blk = fastest.rsplit("-b", 1)
            best = {"FTC_FLASH_EXP_DTYPE": edt,
                    "FTC_FLASH_BLOCK_Q": blk,
                    "FTC_FLASH_BLOCK_K": blk}
    return best


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--log", default=str(REPO / "tpu_session.jsonl"))
    ap.add_argument("--only", default="",
                    help="parity|headline|kernel_ab|headline_tuned|longctx|"
                         "families|moe|baseline_rows|gen7b|fidelity")
    args = ap.parse_args()
    log_path = Path(args.log)

    steps = args.only.split(",") if args.only else [
        "parity", "headline", "kernel_ab", "headline_tuned", "longctx",
        "families", "moe", "baseline_rows", "gen7b", "fidelity"
    ]
    for step in steps:
        print(f"=== step: {step} ===", flush=True)
        if step == "parity":
            step_parity(log_path)
        elif step == "headline":
            step_headline(log_path)
        elif step == "kernel_ab":
            step_kernel_ab(log_path)
        elif step == "headline_tuned":
            step_headline_tuned(log_path, winner_from_log(log_path))
        elif step == "longctx":
            # winner comes from the log, so a --only longctx resume after a
            # tunnel drop still applies the recorded kernel_ab verdict
            winner_env = winner_from_log(log_path)
            if winner_env:
                print("kernel A/B winner env:", winner_env, flush=True)
            step_longctx(log_path, winner_env)
        elif step == "families":
            step_new_families(log_path)
        elif step == "moe":
            step_moe(log_path)
        elif step == "baseline_rows":
            step_baseline_rows(log_path)
        elif step == "gen7b":
            step_gen7b(log_path)
        elif step == "fidelity":
            step_fidelity(log_path)
        else:
            print(f"unknown step {step!r}", file=sys.stderr)
            return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
