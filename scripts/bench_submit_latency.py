"""North-star metric #2 (BASELINE.md): job-submit -> first-training-step latency.

Measures the control-plane overhead between an accepted submission and the
first metrics row a user can see:

    submit (task_builder) -> backend launch -> trainer process boots
    -> jax import + first-step compile -> metrics.csv row 1 -> monitor upsert

Runs entirely on the local backend (CPU, tiny preset), so the number is the
plane's own overhead, not model FLOPs. The reference never measured this —
its equivalent span crosses Kueue admission + pod scheduling + image pull,
all cluster-dependent (reference ``app/jobs/task_builder.py:19-81``,
``app/core/monitor.py:124-197``).

Prints ONE JSON line:
    {"metric": "submit_to_first_step_latency", "value": N, "unit": "s", ...}
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def measure(tmp: str, warm_workers: int = 0) -> dict:
    from finetune_controller_tpu.controller.backends.local import LocalProcessBackend
    from finetune_controller_tpu.controller.datasets import upload_dataset_bytes
    from finetune_controller_tpu.controller.examples import (
        LoRASFTArguments,
        TinyTestLoRA,
    )
    from finetune_controller_tpu.controller.monitor import JobMonitor
    from finetune_controller_tpu.controller.objectstore import LocalObjectStore
    from finetune_controller_tpu.controller.schemas import DatabaseStatus, JobInput
    from finetune_controller_tpu.controller.statestore import StateStore
    from finetune_controller_tpu.controller.task_builder import (
        DatasetInput,
        task_builder,
    )
    from finetune_controller_tpu.controller.devices import (
        DeviceCatalog,
        DeviceFlavor,
        FlavorQuota,
    )

    state = StateStore(f"{tmp}/state")
    store = LocalObjectStore(f"{tmp}/objects")
    catalog = DeviceCatalog(
        flavors=[DeviceFlavor(name="chip-1", generation="cpu", hosts=1,
                              chips_per_host=1, runtime="cpu", queue="q")],
        quotas=[FlavorQuota(flavor="chip-1", nominal_chips=2)],
        default_flavor="chip-1",
    )
    backend = LocalProcessBackend(f"{tmp}/sandboxes", store, catalog,
                                  sync_interval_s=0.1,
                                  warm_workers=warm_workers)
    monitor = JobMonitor(state, store, backend, interval_s=0.05)
    await state.connect()
    if warm_workers:
        # block until the pool reports ready: the measurement is of a
        # steady-state warm service, not a racing spawn
        await backend.prewarm(wait_s=120)
        if not any(
            p.returncode is None
            for pool in backend._warm.values() for p in pool
        ):
            raise RuntimeError(
                "warm-worker pool failed to start — refusing to publish a "
                "'warm' number from a cold-spawn run (see warm_workers.log)"
            )

    rows = b'{"text": "the quick brown fox jumps over the lazy dog"}\n' * 16
    ds = await upload_dataset_bytes(
        store, state, user_id="bench", filename="train.jsonl",
        data=rows, bucket="datasets",
    )
    # total_steps=1: the metrics row lands right after the first step (the
    # trainer always writes on the final step), so "first step visible" is
    # exactly what the poll below observes
    spec = TinyTestLoRA(training_arguments=LoRASFTArguments(
        total_steps=1, warmup_steps=1, batch_size=2, seq_len=16, lora_rank=2,
    ))
    job = JobInput(job_id="lat-1", user_id="bench", model_name="tiny-test-lora",
                   device="chip-1", arguments={"total_steps": 1})

    t_submit = time.perf_counter()
    await task_builder(
        job, spec, DatasetInput(dataset_id=ds.dataset_id),
        state=state, store=store, backend=backend, catalog=catalog,
        datasets_bucket="datasets", artifacts_bucket="artifacts",
    )

    t_running = None
    deadline = time.perf_counter() + 300
    # poll exactly like the monitor daemon would; first metrics row == the
    # first completed training step became user-visible
    while True:
        await monitor.tick()
        now = time.perf_counter()
        if t_running is None:
            rec = await state.get_job("lat-1")
            if rec and rec.status is DatabaseStatus.RUNNING:
                t_running = now
        doc = await state.get_metrics("lat-1")
        if doc is not None and len(doc.records) >= 1:
            t_first = now
            break
        rec = await state.get_job("lat-1")
        if rec and rec.status.is_final:
            raise RuntimeError(f"job finished without metrics: {rec}")
        if now > deadline:
            raise TimeoutError("no first step within 300s")
        await asyncio.sleep(0.05)

    await backend.close()
    await state.close()
    return {
        "metric": "submit_to_first_step_latency[tiny-test,local-backend,cpu]",
        "value": round(t_first - t_submit, 2),
        "unit": "s",
        "submit_to_running_s": round((t_running or t_first) - t_submit, 2),
    }


def main() -> None:
    with tempfile.TemporaryDirectory() as tmp:
        cold = asyncio.run(measure(tmp))
    with tempfile.TemporaryDirectory() as tmp:
        warm = asyncio.run(measure(tmp, warm_workers=1))
    cold["value_warm_pool"] = warm["value"]
    cold["submit_to_running_warm_pool_s"] = warm["submit_to_running_s"]
    print(json.dumps(cold))


if __name__ == "__main__":
    sys.exit(main())
