#!/usr/bin/env bash
# One-shot cluster install (reference: scripts/cluster_install.sh:54-81 —
# Kubeflow Training Operator + Kueue + Mongo + app; the TPU build needs the
# JobSet operator + Kueue + the controller itself, state rides in-process).
#
# Usage: scripts/cluster_install.sh [namespace]
set -euo pipefail

NAMESPACE="${1:-default}"
JOBSET_VERSION="${JOBSET_VERSION:-v0.7.2}"
KUEUE_VERSION="${KUEUE_VERSION:-v0.10.1}"
IMAGE="${IMAGE:-finetune-controller-tpu:latest}"

echo "==> installing JobSet operator ${JOBSET_VERSION}"
kubectl apply --server-side -f \
  "https://github.com/kubernetes-sigs/jobset/releases/download/${JOBSET_VERSION}/manifests.yaml"

echo "==> installing Kueue ${KUEUE_VERSION}"
kubectl apply --server-side -f \
  "https://github.com/kubernetes-sigs/kueue/releases/download/${KUEUE_VERSION}/manifests.yaml"

echo "==> waiting for operators"
kubectl -n jobset-system rollout status deploy/jobset-controller-manager --timeout=180s
kubectl -n kueue-system rollout status deploy/kueue-controller-manager --timeout=180s

echo "==> rendering Kueue CRDs + controller deployments from the device catalog"
python "$(dirname "$0")/render_crds.py" --namespace "${NAMESPACE}" --image "${IMAGE}"

echo "==> service account + RBAC (JobSet/ConfigMap/pod-log access)"
kubectl -n "${NAMESPACE}" apply -f - <<EOF
apiVersion: v1
kind: ServiceAccount
metadata:
  name: finetune-controller
---
apiVersion: rbac.authorization.k8s.io/v1
kind: Role
metadata:
  name: finetune-controller
rules:
  - apiGroups: ["jobset.x-k8s.io"]
    resources: ["jobsets"]
    verbs: ["create", "get", "list", "delete", "watch"]
  - apiGroups: [""]
    resources: ["configmaps"]
    verbs: ["create", "get", "delete"]
  - apiGroups: [""]
    resources: ["pods", "pods/log", "events"]
    verbs: ["get", "list", "watch"]
---
apiVersion: rbac.authorization.k8s.io/v1
kind: RoleBinding
metadata:
  name: finetune-controller
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: Role
  name: finetune-controller
subjects:
  - kind: ServiceAccount
    name: finetune-controller
EOF

echo "==> applying rendered manifests"
kubectl -n "${NAMESPACE}" apply -f deploy/kueue-crds.yaml
kubectl -n "${NAMESPACE}" apply -f deploy/controller.yaml

echo "==> done; API service: finetune-controller-api.${NAMESPACE}.svc"
