#!/usr/bin/env bash
# The one-command CI gate: static analysis, the fast chaos suite, then the
# tier-1 test suite.
#
#   scripts/ci_check.sh            # lint + chaos-fast + tests
#   scripts/ci_check.sh --lint-only
#
# Lint: `ftc-lint finetune_controller_tpu/` must exit 0 — every finding is
# fixed or carries a justified `# ftc: ignore[rule-id] -- reason`
# (docs/static_analysis.md).
# Chaos-fast: the resilience/fault-injection suite (docs/resilience.md)
# runs first and alone — a broken recovery path should fail in seconds,
# before the full tier-1 wall-clock is spent.  The full kill→resume loss-
# trajectory proof is marked `slow` and excluded here (run it with
# `pytest tests/test_chaos.py -m slow`).
# Tests: the tier-1 command from ROADMAP.md.
set -uo pipefail

cd "$(dirname "$0")/.."

echo "== ftc-lint ==" >&2
python -m finetune_controller_tpu.analysis finetune_controller_tpu/
lint_rc=$?
if [ "$lint_rc" -ne 0 ]; then
    echo "ci_check: ftc-lint failed (exit $lint_rc)" >&2
    exit "$lint_rc"
fi

if [ "${1:-}" = "--lint-only" ]; then
    exit 0
fi

echo "== chaos-fast (resilience) ==" >&2
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_resilience.py tests/test_chaos.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly
chaos_rc=$?
if [ "$chaos_rc" -ne 0 ]; then
    echo "ci_check: chaos-fast failed (exit $chaos_rc)" >&2
    exit "$chaos_rc"
fi

echo "== tier-1 tests ==" >&2
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
