#!/usr/bin/env bash
# The one-command CI gate: static analysis, the fast serve suite, the fast
# chaos suite, then the tier-1 test suite.
#
#   scripts/ci_check.sh            # lint + obs/dpo/elastic/sched/serve/chaos-fast + tests
#   scripts/ci_check.sh --lint-only
#
# Lint: `ftc-lint finetune_controller_tpu/` must exit 0 — every finding is
# fixed or carries a justified `# ftc: ignore[rule-id] -- reason`
# (docs/static_analysis.md).  The v2 run includes the project-wide pass
# (call graph, lock discipline, RPC/metric conformance) under a 10s
# wall-clock budget so the interprocedural engine can never rot into a
# slow gate (budget also asserted, more precisely, in
# tests/test_project_analysis.py).
# Serve-fast: the continuous-batching inference suite (docs/serving.md) —
# batching invariance is THE serving correctness anchor, and a broken
# engine should fail in seconds, before the full tier-1 wall-clock.
# Chaos-fast: the resilience/fault-injection suite (docs/resilience.md)
# runs next and alone.  The full kill→resume loss-trajectory proof is
# marked `slow` and excluded here (run it with
# `pytest tests/test_chaos.py -m slow`).
# Tests: the tier-1 command from ROADMAP.md.
set -uo pipefail

cd "$(dirname "$0")/.."

echo "== ftc-lint (per-file + project-wide, 10s budget) ==" >&2
lint_start=$(date +%s)
python -m finetune_controller_tpu.analysis finetune_controller_tpu/
lint_rc=$?
lint_elapsed=$(( $(date +%s) - lint_start ))
if [ "$lint_rc" -ne 0 ]; then
    echo "ci_check: ftc-lint failed (exit $lint_rc)" >&2
    exit "$lint_rc"
fi
if [ "$lint_elapsed" -gt 10 ]; then
    echo "ci_check: ftc-lint took ${lint_elapsed}s — over the 10s budget;" \
         "the interprocedural pass must stay a fast gate" >&2
    exit 1
fi

if [ "${1:-}" = "--lint-only" ]; then
    exit 0
fi

echo "== shard-audit-fast (sharding conformance: heavy rules + AOT collective audit) ==" >&2
# The jax-importing sharding layer (docs/static_analysis.md §v3): the
# HEAVY project rules — rule-table coverage against abstract catalog param
# trees, axis-divisibility on every catalog topology, and the AOT
# collective audit that compiles the train/serve steps on simulated meshes
# and diffs the HLO collective set against docs/performance.md's
# Collective catalog — plus their test files (mutation flips included).
# These CANNOT ride the pure-AST lint stage above: importing jax alone
# blows the 10s budget, which is why the rules are registry-excluded by
# default and named explicitly here.
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m finetune_controller_tpu.analysis \
    --rules shard-rule-coverage,shard-divisibility,collective-conformance \
    finetune_controller_tpu/
shard_lint_rc=$?
if [ "$shard_lint_rc" -ne 0 ]; then
    echo "ci_check: shard-audit-fast lint failed (exit $shard_lint_rc)" >&2
    exit "$shard_lint_rc"
fi
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_shard_conformance.py tests/test_collective_audit.py \
    tests/test_shard_audit.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
shard_rc=$?
if [ "$shard_rc" -ne 0 ]; then
    echo "ci_check: shard-audit-fast failed (exit $shard_rc)" >&2
    exit "$shard_rc"
fi

echo "== obs-fast (tracing, timelines, histograms, phase profiling) ==" >&2
# The observability layer (docs/observability.md): span/event recorders,
# trace assembly + the gap-free validator, histogram exposition, the
# monitor's event ingest, and the hard-path timeline e2e (preempt ->
# resize -> retry -> promote).  Runs first among the suites — every later
# stage's diagnosis leans on these surfaces when IT fails.
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_obs.py tests/test_metrics_endpoint.py -q -m "not slow" \
    -p no:cacheprovider -p no:xdist -p no:randomly
obs_rc=$?
if [ "$obs_rc" -ne 0 ]; then
    echo "ci_check: obs-fast failed (exit $obs_rc)" >&2
    exit "$obs_rc"
fi

echo "== rlhf-fast (disaggregated rollout plane + reward model) ==" >&2
# The distributed RLHF data plane (docs/preference.md §Disaggregated
# rollouts): rollout RPC protocol idempotence, exactly-once dedup across
# respawns, policy rollover as adapter deltas, the Bradley–Terry reward
# trainer, AND the slow-marked chaos (SIGKILL mid-round) and remote-overlap
# e2e runs.  No 'not slow' filter: the e2es are excluded from tier-1 only
# to protect that stage's wall-clock.
timeout -k 10 900 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_rollout_plane.py tests/test_reward_model.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
rlhf_rc=$?
if [ "$rlhf_rc" -ne 0 ]; then
    echo "ci_check: rlhf-fast failed (exit $rlhf_rc)" >&2
    exit "$rlhf_rc"
fi

echo "== dpo-fast (preference optimization: losses, data, actor/learner) ==" >&2
# DPO loss math (hand-computed logits, beta monotonicity, stop-gradient),
# seeded preference-pair round trips, rollout buffer/actor/learner loop,
# AND the slow-marked DPO preemption->resume e2e (docs/preference.md) —
# the prefs/ subsystem fails in minutes here, before everything else.
# No 'not slow' filter: the e2e is excluded from tier-1 only to protect
# that stage's wall-clock.
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_prefs.py tests/test_preference_data.py \
    tests/test_dpo_e2e.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
dpo_rc=$?
if [ "$dpo_rc" -ne 0 ]; then
    echo "ci_check: dpo-fast failed (exit $dpo_rc)" >&2
    exit "$dpo_rc"
fi

echo "== elastic-fast (topology-portable checkpoints + resize) ==" >&2
# manifest round-trips, cross-topology (dp=2<->dp=1) restore bit-identity,
# resize planner/reservations/grow pass, supervisor topology handling, the
# resize-beats-evict sim gate, AND the slow-marked shrink->resume->grow e2e
# on real subprocesses (docs/elasticity.md) — the elastic layer fails in
# minutes here, before the sched/serve/chaos stages.  No 'not slow' filter:
# the e2e is excluded from tier-1 only to protect that stage's wall-clock.
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_elastic_restore.py tests/test_resize.py \
    "tests/test_sched_e2e.py::test_resize_shrinks_resumes_and_grows_back" -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
elastic_rc=$?
if [ "$elastic_rc" -ne 0 ]; then
    echo "ci_check: elastic-fast failed (exit $elastic_rc)" >&2
    exit "$elastic_rc"
fi

echo "== sched-fast (fair-share properties on the simulator) ==" >&2
# pure control-flow (no trainer subprocesses): quota safety under
# preemption/backfill, victims-always-resume, Jain >= 0.8, FIFO starvation
# pins (docs/scheduling.md) — fails in seconds if admission regresses
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_sched.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
sched_rc=$?
if [ "$sched_rc" -ne 0 ]; then
    echo "ci_check: sched-fast failed (exit $sched_rc)" >&2
    exit "$sched_rc"
fi

echo "== transport-fast (worker spawn, RPC protocol, cross-process failover) ==" >&2
# The cross-process serve transport (docs/serving.md §Cross-process
# transport): wire framing, the worker RPC protocol (in-process loopback),
# real worker-process spawn/probe/drain, the SIGKILLed-worker exactly-once
# proof, and the adapter registry-sync RPCs — the transport layer fails in
# minutes here, before the fleet suite that rides it.
timeout -k 10 900 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_transport.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
transport_rc=$?
if [ "$transport_rc" -ne 0 ]; then
    echo "ci_check: transport-fast failed (exit $transport_rc)" >&2
    exit "$transport_rc"
fi

echo "== serve-chaos-fast (replica kill, drain, failover, autoscale) ==" >&2
# The fleet robustness anchors (docs/serving.md §Fleet): the 'not slow'
# replica-kill/drain/failover/autoscale tests lead, and the slow-marked
# fleet HTTP loops (429 Retry-After, concurrent-load CAS) ride along so
# the whole fleet layer is covered exactly once per gate, before the full
# serve suite below.
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_serve_fleet.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
serve_chaos_rc=$?
if [ "$serve_chaos_rc" -ne 0 ]; then
    echo "ci_check: serve-chaos-fast failed (exit $serve_chaos_rc)" >&2
    exit "$serve_chaos_rc"
fi

echo "== kernels-fast (paged-attention kernel bit-identity + dispatch) ==" >&2
# The Pallas paged-attention kernel (docs/serving.md §Paged KV): interpret-
# mode bit-identity against the gather+chunked oracle across shapes/dtypes,
# the FTC_PAGED_ATTN dispatch gate, VMEM sizing, and the engine anchors
# under the forced kernel — a broken kernel fails here in seconds, before
# the serve suite exercises it indirectly.
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_paged_attention.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
kernels_rc=$?
if [ "$kernels_rc" -ne 0 ]; then
    echo "ci_check: kernels-fast failed (exit $kernels_rc)" >&2
    exit "$kernels_rc"
fi

echo "== serve-fast (batching invariance + prefix cache + paged KV + adapters + metrics) ==" >&2
# no 'not slow' filter here: the serve suite IS this stage's whole job, so
# its slow-marked extras (sampled-decode parity, prefix-cache eviction
# mid-flight, the multi-tenant HTTP loop) run too — they are excluded from
# tier-1 below only to protect that stage's wall-clock budget
timeout -k 10 900 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_serve.py tests/test_prefix_cache.py \
    tests/test_kv_pages.py tests/test_serve_adapters.py \
    tests/test_metrics_endpoint.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
serve_rc=$?
if [ "$serve_rc" -ne 0 ]; then
    echo "ci_check: serve-fast failed (exit $serve_rc)" >&2
    exit "$serve_rc"
fi

echo "== chaos-fast (resilience) ==" >&2
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_resilience.py tests/test_chaos.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly
chaos_rc=$?
if [ "$chaos_rc" -ne 0 ]; then
    echo "ci_check: chaos-fast failed (exit $chaos_rc)" >&2
    exit "$chaos_rc"
fi

echo "== tier-1 tests ==" >&2
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
