#!/usr/bin/env bash
# Run the control plane locally for development (reference: the local serve
# wrappers in scripts/): API server with the in-process monitor and the
# subprocess "fake cluster" local backend — the full submit -> train ->
# metrics -> promote lifecycle with zero cluster dependencies.
#
# Usage: scripts/serve_local.sh [port]
set -euo pipefail

PORT="${1:-8787}"

export FTC_ENVIRONMENT="${FTC_ENVIRONMENT:-local}"
export FTC_BACKEND="${FTC_BACKEND:-local}"
export FTC_MONITOR_IN_PROCESS="${FTC_MONITOR_IN_PROCESS:-true}"
# pre-warmed trainer processes: first submit skips the JAX import wait
export FTC_WARM_WORKERS="${FTC_WARM_WORKERS:-1}"
# local training runs on the CPU backend unless the host has TPUs
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

exec python -m finetune_controller_tpu.controller.server --port "${PORT}"
