"""Run the full-scale fidelity proof and record the result.

``python scripts/fidelity_proof.py [--work-dir DIR]`` executes
``finetune_controller_tpu/fidelity.py`` at its full scale (600-step pretrain
on 400 KB of real English, 200-step controller-submitted LoRA SFT), prints
the record, and writes it to ``FIDELITY.json`` at the repo root — the raw
evidence behind BASELINE.md's fidelity row.

On a real TPU the record is also appended to ``tpu_session.jsonl`` (the
committed measurement log) with ``step: "fidelity"``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main(argv: list[str] | None = None) -> int:
    from finetune_controller_tpu.platform import assert_platform_env

    assert_platform_env()

    p = argparse.ArgumentParser(prog="fidelity-proof")
    p.add_argument("--work-dir", default=str(REPO / "artifacts" / "fidelity"))
    p.add_argument("--pretrain-steps", type=int, default=600)
    p.add_argument("--sft-steps", type=int, default=200)
    p.add_argument("--corpus-bytes", type=int, default=400_000)
    p.add_argument("--max-new-tokens", type=int, default=48)
    p.add_argument("--session-log", default=str(REPO / "tpu_session.jsonl"),
                   help="where the TPU-run record is appended "
                        "(scripts/tpu_session.py passes its --log here)")
    args = p.parse_args(argv)

    import jax

    from finetune_controller_tpu.fidelity import run_proof

    device = jax.devices()[0]
    t0 = time.time()
    record = run_proof(
        args.work_dir,
        pretrain_steps=args.pretrain_steps,
        sft_steps=args.sft_steps,
        corpus_bytes=args.corpus_bytes,
        max_new_tokens=args.max_new_tokens,
    )
    record["wall_s"] = round(time.time() - t0, 1)
    record["device_kind"] = device.device_kind
    record["platform"] = device.platform

    print(json.dumps(record, indent=2))
    (REPO / "FIDELITY.json").write_text(json.dumps(record, indent=2) + "\n")

    if device.platform == "tpu":
        session_rec = {
            "ts": round(time.time(), 1),
            "step": "fidelity",
            "metric": "fidelity_final_loss",
            "value": record["final_loss"],
            "device_kind": device.device_kind,
            "detail": {
                k: record[k]
                for k in (
                    "random_init_loss", "base_step0_loss", "final_loss",
                    "pretrain_final_loss", "passed",
                )
            },
        }
        with open(args.session_log, "a") as f:
            f.write(json.dumps(session_rec) + "\n")

    return 0 if record["passed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
