"""Multimodal (LLaVA-style) model family tests — BASELINE config #5.

The reference has no model code at all (SURVEY.md §2.2); these tests cover the
greenfield multimodal compute path: forward shape, the vision→text wiring probe
(brightness task — the target token is predictable only through pixels), the
projector-trains-with-LoRA split, and the e2e control-plane lifecycle.
"""

import json

import numpy as np
import pytest

import jax

from conftest import run_async
from finetune_controller_tpu.data.synthetic import BRIGHTNESS_LEVELS, synthetic_batches
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.models.multimodal import MM_PRESETS, LlavaForCausalLM
from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

TINY = MM_PRESETS["tiny-mm-test"]


def test_llava_forward_shape():
    cfg = TINY
    model = LlavaForCausalLM(cfg)
    variables = model.init_variables(jax.random.PRNGKey(0), batch=2, seq=8)
    tokens = np.zeros((2, 8), np.int32)
    pixels = np.zeros((2, cfg.vision.image_size, cfg.vision.image_size, 3), np.float32)
    logits = model.apply(variables, tokens, pixels)
    # logits cover text positions only (image prefix sliced off), f32
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert logits.dtype == np.float32

    # text-only call works too (pixels optional)
    logits_text = model.apply(variables, tokens)
    assert logits_text.shape == (2, 8, cfg.vocab_size)


def test_projector_trains_with_lora():
    cfg = TINY.replace(lora=LoRAConfig(rank=4))
    trainer = Trainer(cfg, TrainConfig(mode="lora", total_steps=2, batch_size=2, seq_len=16))
    state = trainer.init_state()
    # trainable: LoRA adapters + the projector; frozen params exclude the projector
    assert set(state.trainable) == {"lora", "projector"}
    assert set(state.trainable["projector"]) == {"projector_fc1", "projector_fc2"}
    assert "projector_fc1" not in state.frozen["params"]
    assert "vision_tower" in state.frozen["params"]  # ViT stays frozen


def test_brightness_task_vision_wiring():
    """Loss on the brightness token falls well below the text-only floor
    log(BRIGHTNESS_LEVELS) — impossible unless pixels reach the decoder."""
    cfg = TINY.replace(lora=LoRAConfig(rank=4))
    tc = TrainConfig(
        mode="lora", learning_rate=0.01, total_steps=300, batch_size=16,
        seq_len=16, log_every=10**9, checkpoint_every=10**9,
    )
    trainer = Trainer(cfg, tc)
    state = trainer.init_state()
    batches = synthetic_batches(
        16, 16, cfg.vocab_size, task="brightness", seed=0,
        image_size=cfg.vision.image_size,
    )
    losses = []
    for _ in range(300):
        state, metrics = trainer.step(state, next(batches))
        losses.append(float(metrics["loss"]))
    text_only_floor = np.log(BRIGHTNESS_LEVELS)
    final = np.mean(losses[-25:])
    assert final < text_only_floor - 0.5, (
        f"final loss {final:.2f} vs text-only floor {text_only_floor:.2f}: "
        "vision path is not wired"
    )


def test_multimodal_e2e_lifecycle(tmp_path):
    """Submit a tiny multimodal job through the API → SUCCEEDED with metrics
    (VERDICT round-1: multimodal must train end-to-end to count)."""
    from test_api import _client, _runtime, _wait_final

    async def main():
        client = await _client(_runtime(tmp_path))
        body = {
            "model_name": "tiny-mm-test-lora",
            "device": "chip-1",
            "arguments": {"total_steps": 3, "warmup_steps": 1, "batch_size": 2,
                          "seq_len": 16, "lora_rank": 2},
        }
        r = await client.post("/api/v1/jobs", json=body)
        assert r.status == 200, await r.text()
        job_id = (await r.json())["job_id"]
        job = await _wait_final(client, job_id)
        assert job["status"] == "succeeded", job

        r = await client.get(f"/api/v1/jobs/{job_id}/metrics")
        records = (await r.json())["records"]
        assert records and "loss" in records[0]
        await client.close()

    run_async(main())


def test_llava_job_trains_from_imported_tower_and_exports(tmp_path):
    """Round-5 (VERDICT #3): the LLaVA path end to end on REAL pixels — a
    tiny HF LLaVA checkpoint imports as the pretrained base (CLIP tower +
    projector + decoder), a jsonl dataset of actual PNG files trains through
    the CLI, and the run exports the PEFT adapter (keyed under
    language_model — HF LLaVA's layout) plus the trained projector."""
    torch = pytest.importorskip("torch")
    pytest.importorskip("PIL")
    from transformers import (
        CLIPVisionConfig,
        LlamaConfig as HFLlamaConfig,
        LlavaConfig as HFLlavaConfig,
        LlavaForConditionalGeneration,
    )

    torch.manual_seed(0)
    hf_cfg = HFLlavaConfig(
        vision_config=CLIPVisionConfig(
            hidden_size=32, intermediate_size=64, num_hidden_layers=3,
            num_attention_heads=2, image_size=16, patch_size=8,
            hidden_act="quick_gelu",
        ),
        text_config=HFLlamaConfig(
            vocab_size=256, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=128, max_position_embeddings=128,
            tie_word_embeddings=False,
        ),
        image_token_index=255, projector_hidden_act="gelu",
        vision_feature_layer=-2, vision_feature_select_strategy="default",
    )
    ckpt = tmp_path / "llava-base"
    LlavaForConditionalGeneration(hf_cfg).save_pretrained(
        str(ckpt), safe_serialization=True
    )

    # real pixels: 6 distinct PNGs + prompt/completion rows referencing them
    from PIL import Image

    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    rows = []
    rng = np.random.default_rng(0)
    for i in range(6):
        arr = (rng.uniform(0, 255, (20, 24, 3))).astype(np.uint8)
        Image.fromarray(arr).save(img_dir / f"im{i}.png")
        rows.append(json.dumps({
            "image": str(img_dir / f"im{i}.png"),
            "prompt": f"describe {i}: ",
            "completion": f"a picture {i}",
        }))
    data = tmp_path / "mm.jsonl"
    data.write_text("\n".join(rows) + "\n")

    from finetune_controller_tpu.train import cli

    spec = {
        "job_id": "mm-e2e",
        "model": {"preset": "tiny-mm-clip-test", "lora": {"rank": 2},
                  "weights_dir": str(ckpt)},
        "training": {"mode": "lora", "total_steps": 3, "batch_size": 2,
                     "seq_len": 32, "log_every": 1, "checkpoint_every": 100,
                     "learning_rate": 1e-3},
        "mesh": {"dp": 1, "fsdp": 1},
        "dataset": {"path": str(data)},
        "artifacts_dir": str(tmp_path / "artifacts"),
    }
    cli.run_job(spec)

    art = tmp_path / "artifacts"
    assert (art / "done.txt").exists()
    rows = (art / "metrics.csv").read_text().strip().splitlines()
    assert len(rows) >= 4  # header + 3 steps
    from safetensors.numpy import load_file

    adapter = load_file(str(art / "adapter" / "adapter_model.safetensors"))
    assert all(
        k.startswith("base_model.model.language_model.model.layers.")
        for k in adapter
    )
    proj = load_file(str(art / "adapter" / "projector.safetensors"))
    assert proj["multi_modal_projector.linear_1.weight"].shape == (64, 32)
    assert proj["multi_modal_projector.linear_2.weight"].shape == (64, 64)

    # post-finetune sanity generation WITH an image, from the job's own
    # artifacts (the operator surface; oracle path for multimodal)
    import contextlib
    import io

    from finetune_controller_tpu.models.generate_cli import main as gen_main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = gen_main([
            "--artifacts", str(art),
            "--prompt", "describe 0: ",
            "--image", str(img_dir / "im0.png"),
            "--max-new-tokens", "4",
        ])
    assert rc == 0
    out = json.loads(buf.getvalue())
    assert len(out["new_tokens"]) == 4

    # a text-only prompt against multimodal artifacts must refuse clearly
    with pytest.raises(SystemExit, match="--image"):
        gen_main(["--artifacts", str(art), "--prompt", "x",
                  "--max-new-tokens", "2"])


def test_mm_loader_decodes_paths_npy_and_base64(tmp_path):
    """The multimodal loader's row schemas and image reference forms."""
    import base64 as b64

    pytest.importorskip("PIL")
    from PIL import Image

    from finetune_controller_tpu.data.mm_loader import mm_jsonl_batches

    img = (np.random.default_rng(1).uniform(0, 255, (10, 10, 3))).astype(np.uint8)
    Image.fromarray(img).save(tmp_path / "a.png")
    np.save(tmp_path / "b.npy", img.astype(np.float32) / 255.0)
    import io as _io

    buf = _io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")
    data_uri = "data:image/png;base64," + b64.b64encode(buf.getvalue()).decode()

    rows = [
        {"image": "a.png", "prompt": "p: ", "completion": "done"},  # relative
        {"image": str(tmp_path / "b.npy"), "text": "plain lm row"},
        {"image": data_uri,
         "messages": [{"role": "user", "content": "hi"},
                      {"role": "assistant", "content": "yo"}]},
    ]
    path = tmp_path / "mm.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")

    it = mm_jsonl_batches(str(path), batch_size=3, seq_len=48, image_size=8)
    batch = next(it)
    assert batch["tokens"].shape == (3, 48)
    assert batch["pixels"].shape == (3, 8, 8, 3)
    assert batch["loss_mask"].shape == (3, 48)
    # SFT rows mask the prompt; plain text rows count everything unpadded
    assert batch["loss_mask"].sum() > 0
    # CLIP normalization: values are centered (not raw [0,1])
    assert batch["pixels"].min() < -0.5

    # a row without an image must fail loudly
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"text": "no image"}) + "\n")
    with pytest.raises(ValueError, match="image"):
        next(mm_jsonl_batches(str(bad), batch_size=1, seq_len=8, image_size=8))

    # a row whose every loss position falls past seq_len would train on
    # NOTHING — the loader must refuse, not silently zero the gradient
    longp = tmp_path / "long.jsonl"
    longp.write_text(json.dumps({
        "image": str(tmp_path / "a.png"),
        "prompt": "x" * 32, "completion": "y",
    }) + "\n")
    with pytest.raises(ValueError, match="past seq_len"):
        next(mm_jsonl_batches(str(longp), batch_size=1, seq_len=16, image_size=8))

    # a row with NO loss-counted tokens at all (empty completion) is the
    # same zero-gradient failure, before truncation even enters — refuse it
    # like the chat-row empty-mask check in data/loader.py
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({
        "image": str(tmp_path / "a.png"), "prompt": "p: ", "completion": "",
    }) + "\n")
    with pytest.raises(ValueError, match="no loss-counted tokens"):
        next(mm_jsonl_batches(str(empty), batch_size=1, seq_len=16, image_size=8))


def test_pixel_cache_is_a_bounded_lru():
    """The decoded-pixel cache evicts ONLY the least-recently-used entry at
    capacity — not clear-everything — so an epoch over a dataset just past
    the cap keeps most decodes warm instead of re-decoding the whole set."""
    from finetune_controller_tpu.data.mm_loader import PixelCache

    cache = PixelCache(3)
    px = {k: np.full((2, 2, 3), k, np.float32) for k in range(5)}
    for k in (0, 1, 2):
        cache.put(k, px[k])
    assert cache.get(0) is px[0]  # refresh 0 → 1 is now the LRU
    cache.put(3, px[3])
    assert len(cache) == 3
    assert 1 not in cache and 0 in cache and 2 in cache and 3 in cache
    # re-putting an existing key refreshes it instead of growing the cache
    cache.put(2, px[2])
    cache.put(4, px[4])
    assert 0 not in cache and 2 in cache and len(cache) == 3

    # capacity <= 0 disables caching (the bench's measure-every-decode mode)
    off = PixelCache(0)
    off.put(1, px[1])
    assert len(off) == 0 and off.get(1) is None


def test_mm_loader_lru_avoids_full_redecide_per_epoch(tmp_path, monkeypatch):
    """Steady-state epochs over a dataset ONE row past the cache cap decode
    ~1 row per epoch (the evicted one), not the whole dataset — the failure
    mode of the old clear-at-capacity cache."""
    from finetune_controller_tpu.data import mm_loader
    from finetune_controller_tpu.data.mm_loader import mm_jsonl_batches

    n_rows, cap = 6, 5
    rows = []
    for i in range(n_rows):
        np.save(tmp_path / f"{i}.npy", np.full((4, 4, 3), i / 8, np.float32))
        rows.append({"image": f"{i}.npy", "prompt": "p: ", "completion": "z"})
    path = tmp_path / "mm.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")

    decodes = []
    real = mm_loader.preprocess_image

    def counting(image, *a, **kw):
        decodes.append(image)
        return real(image, *a, **kw)

    monkeypatch.setattr(mm_loader, "preprocess_image", counting)
    it = mm_jsonl_batches(
        str(path), batch_size=n_rows, seq_len=16, image_size=4,
        pixel_cache_size=cap,
    )
    next(it)  # epoch 1: cold — all rows decode
    assert len(decodes) == n_rows
    for _ in range(3):  # steady state: ≤ 2 decodes/epoch (evictee + churn)
        decodes.clear()
        next(it)
        assert len(decodes) <= 2, f"cache thrash: {len(decodes)} decodes"
