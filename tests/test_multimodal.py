"""Multimodal (LLaVA-style) model family tests — BASELINE config #5.

The reference has no model code at all (SURVEY.md §2.2); these tests cover the
greenfield multimodal compute path: forward shape, the vision→text wiring probe
(brightness task — the target token is predictable only through pixels), the
projector-trains-with-LoRA split, and the e2e control-plane lifecycle.
"""

import numpy as np

import jax

from conftest import run_async
from finetune_controller_tpu.data.synthetic import BRIGHTNESS_LEVELS, synthetic_batches
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.models.multimodal import MM_PRESETS, LlavaForCausalLM
from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

TINY = MM_PRESETS["tiny-mm-test"]


def test_llava_forward_shape():
    cfg = TINY
    model = LlavaForCausalLM(cfg)
    variables = model.init_variables(jax.random.PRNGKey(0), batch=2, seq=8)
    tokens = np.zeros((2, 8), np.int32)
    pixels = np.zeros((2, cfg.vision.image_size, cfg.vision.image_size, 3), np.float32)
    logits = model.apply(variables, tokens, pixels)
    # logits cover text positions only (image prefix sliced off), f32
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert logits.dtype == np.float32

    # text-only call works too (pixels optional)
    logits_text = model.apply(variables, tokens)
    assert logits_text.shape == (2, 8, cfg.vocab_size)


def test_projector_trains_with_lora():
    cfg = TINY.replace(lora=LoRAConfig(rank=4))
    trainer = Trainer(cfg, TrainConfig(mode="lora", total_steps=2, batch_size=2, seq_len=16))
    state = trainer.init_state()
    # trainable: LoRA adapters + the projector; frozen params exclude the projector
    assert set(state.trainable) == {"lora", "projector"}
    assert set(state.trainable["projector"]) == {"projector_fc1", "projector_fc2"}
    assert "projector_fc1" not in state.frozen["params"]
    assert "vision_tower" in state.frozen["params"]  # ViT stays frozen


def test_brightness_task_vision_wiring():
    """Loss on the brightness token falls well below the text-only floor
    log(BRIGHTNESS_LEVELS) — impossible unless pixels reach the decoder."""
    cfg = TINY.replace(lora=LoRAConfig(rank=4))
    tc = TrainConfig(
        mode="lora", learning_rate=0.01, total_steps=300, batch_size=16,
        seq_len=16, log_every=10**9, checkpoint_every=10**9,
    )
    trainer = Trainer(cfg, tc)
    state = trainer.init_state()
    batches = synthetic_batches(
        16, 16, cfg.vocab_size, task="brightness", seed=0,
        image_size=cfg.vision.image_size,
    )
    losses = []
    for _ in range(300):
        state, metrics = trainer.step(state, next(batches))
        losses.append(float(metrics["loss"]))
    text_only_floor = np.log(BRIGHTNESS_LEVELS)
    final = np.mean(losses[-25:])
    assert final < text_only_floor - 0.5, (
        f"final loss {final:.2f} vs text-only floor {text_only_floor:.2f}: "
        "vision path is not wired"
    )


def test_multimodal_e2e_lifecycle(tmp_path):
    """Submit a tiny multimodal job through the API → SUCCEEDED with metrics
    (VERDICT round-1: multimodal must train end-to-end to count)."""
    from test_api import _client, _runtime, _wait_final

    async def main():
        client = await _client(_runtime(tmp_path))
        body = {
            "model_name": "tiny-mm-test-lora",
            "device": "chip-1",
            "arguments": {"total_steps": 3, "warmup_steps": 1, "batch_size": 2,
                          "seq_len": 16, "lora_rank": 2},
        }
        r = await client.post("/api/v1/jobs", json=body)
        assert r.status == 200, await r.text()
        job_id = (await r.json())["job_id"]
        job = await _wait_final(client, job_id)
        assert job["status"] == "succeeded", job

        r = await client.get(f"/api/v1/jobs/{job_id}/metrics")
        records = (await r.json())["records"]
        assert records and "loss" in records[0]
        await client.close()

    run_async(main())
