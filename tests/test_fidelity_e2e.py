"""End-to-end fidelity proof on the CPU backend (round-5, VERDICT #1).

Everything else in the suite measures mechanics; this test proves the
*product claim* — a pretrained base fine-tuned through the full controller
path gets measurably better on real data and visibly changes behavior —
mirroring how the reference's one example trains real MNIST to convergence
(reference ``app/models/examples/mnist.py:13-99``).

The scale is shrunk (smaller corpus, fewer steps) but nothing is mocked:
real English text, a real pretraining run, an HF-format export/import round
trip, a controller-submitted subprocess LoRA job, and greedy generation from
the job's synced artifacts.
"""

import json
from pathlib import Path

from finetune_controller_tpu.fidelity import (
    HOLDOUT_TOPICS,
    SFT_PREFIX,
    run_proof,
    sft_prompt,
)


def test_fidelity_proof_end_to_end(tmp_path):
    record = run_proof(
        tmp_path,
        pretrain_steps=120,
        corpus_bytes=80_000,
        sft_steps=80,
        job_deadline_s=240.0,
    )

    # the base must have learned real English: far below random-init loss
    assert record["pretrain_final_loss"] < 0.7 * record["pretrain_first_loss"]

    # step-0 loss from the base << random init (knowledge transferred
    # through export -> controller submit -> hf_import)
    assert record["checks"]["base_transfers"], record
    assert record["base_step0_loss"] < 0.75 * record["random_init_loss"]

    # the fine-tune learned from the SFT signal
    assert record["checks"]["finetune_learns"], record
    assert record["final_loss"] < record["base_step0_loss"]

    # behavior change on a HELD-OUT topic: the SFT style appears only after
    assert record["probe_prompt"] == sft_prompt(HOLDOUT_TOPICS[0])
    assert record["after_generation"].startswith(SFT_PREFIX)
    assert not record["before_generation"].startswith(SFT_PREFIX)
    assert record["passed"]

    # the record ships with the job's artifacts (promotion publishes it)
    on_disk = json.loads(Path(record["record_path"]).read_text())
    assert on_disk["passed"] is True
    art = Path(record["record_path"]).parent
    assert (art / "adapter" / "adapter_config.json").exists()
    assert (art / "metrics.csv").exists()
