"""ftc-ctl terminal client tests: drive the real CLI against a real
(socket-bound) control-plane server — the operator surface the reference
only offered through its browser frontend."""

import json

from conftest import run_async

from test_api import _runtime  # reuse the API tests' runtime builder

from finetune_controller_tpu.controller import ctl


def test_ctl_submit_watch_metrics_logs(tmp_path, capsys):
    async def main():
        from aiohttp.test_utils import TestServer

        from finetune_controller_tpu.controller.server import build_app

        rt = _runtime(tmp_path)
        await rt.start(with_monitor=True)
        server = TestServer(build_app(rt))
        await server.start_server()
        api = f"http://{server.host}:{server.port}"
        try:
            rc = await ctl.amain(ctl.build_parser().parse_args([
                "--api", api, "submit", "tiny-test-lora",
                "--arg", "total_steps=2", "--arg", "batch_size=2",
                "--arg", "seq_len=16", "--arg", "lora_rank=2",
                "--arg", "warmup_steps=1",
                "--device", "chip-1",
                "--task", "causal_lm",  # the optional task cross-check
                "--watch",
            ]))
            assert rc == 0
            out = capsys.readouterr().out
            job_id = json.loads(out[: out.index("}\n") + 2])["job_id"]

            # an unknown --task is a 400 naming the known tasks (ISSUE 8)
            import pytest

            with pytest.raises(ctl.ApiError, match="known tasks"):
                await ctl.amain(ctl.build_parser().parse_args([
                    "--api", api, "submit", "tiny-test-lora",
                    "--task", "reinforcement",
                ]))

            assert await ctl.amain(ctl.build_parser().parse_args(
                ["--api", api, "jobs"])) == 0
            jobs_out = capsys.readouterr().out
            assert job_id in jobs_out
            # the table carries the task-type column from the job metadata
            assert "causal_lm" in jobs_out

            assert await ctl.amain(ctl.build_parser().parse_args(
                ["--api", api, "metrics", job_id])) == 0
            rows = json.loads(capsys.readouterr().out)
            assert rows and "loss" in rows[-1]

            assert await ctl.amain(ctl.build_parser().parse_args(
                ["--api", api, "logs", job_id])) == 0
            assert "finished" in capsys.readouterr().out

            # timeline waterfall (docs/observability.md): a real run's
            # lifecycle events with offsets, trace id, and gap columns
            assert await ctl.amain(ctl.build_parser().parse_args(
                ["--api", api, "timeline", job_id])) == 0
            tl = capsys.readouterr().out
            assert "trace=" in tl and "submitted" in tl
            assert "running" in tl and "succeeded" in tl
            assert "train-started" in tl  # trainer events were ingested

            # artifacts: inventory listing + zip download
            assert await ctl.amain(ctl.build_parser().parse_args(
                ["--api", api, "artifacts", job_id])) == 0
            inv = capsys.readouterr().out
            assert "metrics.csv" in inv and "done.txt" in inv
            zip_path = tmp_path / "artifacts.zip"
            assert await ctl.amain(ctl.build_parser().parse_args(
                ["--api", api, "artifacts", job_id, "-o", str(zip_path)])) == 0
            import zipfile

            with zipfile.ZipFile(zip_path) as zf:
                assert any("metrics" in n for n in zf.namelist())

            # unknown job -> ApiError (main() maps it to exit 1)
            import pytest

            with pytest.raises(ctl.ApiError):
                await ctl.amain(ctl.build_parser().parse_args(
                    ["--api", api, "status", "nope"]))
        finally:
            await server.close()
            await rt.close()

    run_async(main())


def test_ctl_queue_renders_tenant_table(tmp_path, capsys):
    """`ftc-ctl queue` renders GET /admin/scheduler: per-queue usage/share/
    borrowed plus pending positions (ISSUE 5 satellite)."""

    async def main():
        from aiohttp.test_utils import TestServer

        from finetune_controller_tpu.controller.server import build_app
        from finetune_controller_tpu.sched import FairShareScheduler

        rt = _runtime(tmp_path)
        await rt.start(with_monitor=False)
        server = TestServer(build_app(rt))
        await server.start_server()
        api = f"http://{server.host}:{server.port}"
        try:
            sched = FairShareScheduler(rt.catalog, {"prod": 4.0, "batch": 1.0})
            sched.submit("q-run", "chip-1", 2, queue="prod", priority="high")
            sched.try_admit()
            sched.submit("q-wait", "chip-1", 1, queue="batch", priority="low")
            sched.try_admit()
            rt.backend.scheduler = sched

            assert await ctl.amain(ctl.build_parser().parse_args(
                ["--api", api, "queue"])) == 0
            out = capsys.readouterr().out
            assert "QUEUE" in out and "SHARE" in out and "BORROW" in out
            assert "prod" in out and "batch" in out
            assert "#1  q-wait  (batch)" in out
        finally:
            await server.close()
            await rt.close()

    run_async(main())

