import jax
import jax.numpy as jnp
import numpy as np

from finetune_controller_tpu.models import PRESETS, LlamaForCausalLM, LoRAConfig


def _tiny(lora_rank=0, **kw):
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=lora_rank), **kw)
    return cfg, LlamaForCausalLM(cfg)


def test_forward_shapes():
    cfg, model = _tiny()
    vars_ = model.init_variables(jax.random.PRNGKey(0), batch=2, seq=16)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = model.apply(vars_, toks)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_lora_starts_as_identity():
    """lora_b is zero-init, so the adapter branch contributes nothing at init:
    perturbing lora_a must not change the output, perturbing lora_b must."""
    cfg, model = _tiny(lora_rank=8)
    vars_ = model.init_variables(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    base = model.apply(vars_, toks)

    def perturb(tree, name, scale):
        return jax.tree_util.tree_map_with_path(
            lambda kp, v: v + scale if name in jax.tree_util.keystr(kp) else v, tree
        )

    junk_a = {**vars_, "lora": perturb(vars_["lora"], "lora_a", 7.0)}
    np.testing.assert_allclose(
        np.asarray(base), np.asarray(model.apply(junk_a, toks)), atol=1e-5
    )
    junk_b = {**vars_, "lora": perturb(vars_["lora"], "lora_b", 0.5)}
    assert not np.allclose(np.asarray(base), np.asarray(model.apply(junk_b, toks)), atol=1e-3)


def test_scan_and_loop_paths_agree():
    """nn.scan layer stacking must be numerically identical to the loop."""
    import jax.numpy as jnp

    cfg_scan, model_scan = _tiny(scan_layers=True, remat=False, dtype=jnp.float32)
    cfg_loop, model_loop = _tiny(scan_layers=False, remat=False, dtype=jnp.float32)
    vs = model_scan.init_variables(jax.random.PRNGKey(0))
    # map scanned params (leading layer axis) onto loop layout
    import flax

    ps = flax.core.unfreeze(vs)["params"]
    loop_params = {k: v for k, v in ps.items() if k != "blocks"}
    stacked = ps["blocks"]["block"]
    for i in range(cfg_loop.n_layers):
        loop_params[f"layer_{i}"] = jax.tree.map(lambda x: x[i], stacked)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, cfg_scan.vocab_size)
    out_scan = model_scan.apply(vs, toks)
    out_loop = model_loop.apply({"params": loop_params}, toks)
    np.testing.assert_allclose(np.asarray(out_scan), np.asarray(out_loop), atol=1e-4)


def test_segment_mask_blocks_cross_document_attention():
    cfg, model = _tiny()
    vars_ = model.init_variables(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, cfg.vocab_size)
    seg_one = jnp.ones((1, 16), jnp.int32)
    seg_split = jnp.concatenate(
        [jnp.ones((1, 8), jnp.int32), 2 * jnp.ones((1, 8), jnp.int32)], axis=1
    )
    full = model.apply(vars_, toks, segment_ids=seg_one)
    split = model.apply(vars_, toks, segment_ids=seg_split)
    # first segment can't see the second either way → identical prefix
    np.testing.assert_allclose(
        np.asarray(full[:, :8]), np.asarray(split[:, :8]), atol=1e-5
    )
    # second segment differs (it lost its prefix context)
    assert not np.allclose(np.asarray(full[:, 8:]), np.asarray(split[:, 8:]), atol=1e-3)


def test_causal_attention_gqa_matches_mha_expansion():
    from finetune_controller_tpu.ops.attention import xla_causal_attention

    rng = jax.random.PRNGKey(0)
    b, s, h, hkv, d = 2, 8, 4, 2, 16
    q = jax.random.normal(rng, (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d), jnp.float32)
    out = xla_causal_attention(q, k, v)
    # expand kv to full heads and compare
    k_full = jnp.repeat(k, h // hkv, axis=2)
    v_full = jnp.repeat(v, h // hkv, axis=2)
    # repeat maps kv head j -> heads [j*g, (j+1)*g); q reshape in impl maps
    # q head i -> group (i // g) — same layout, so results must match.
    out_full = xla_causal_attention(q, k_full, v_full)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_full), atol=1e-5)


def test_lora_dropout_is_live_when_enabled():
    """deterministic=False + dropout rng must actually perturb the lora branch."""
    cfg, model = _tiny(lora_rank=8)
    cfg = cfg.replace(lora=cfg.lora.__class__(rank=8, dropout=0.5))
    from finetune_controller_tpu.models import LlamaForCausalLM

    model = LlamaForCausalLM(cfg)
    vars_ = model.init_variables(jax.random.PRNGKey(0))
    # make lora_b nonzero so the (dropped-out) branch contributes
    lora = jax.tree.map(lambda v: v + 0.1, vars_["lora"])
    vars_ = {**vars_, "lora": lora}
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    det = model.apply(vars_, toks, deterministic=True)
    d1 = model.apply(vars_, toks, deterministic=False, rngs={"dropout": jax.random.PRNGKey(2)})
    d2 = model.apply(vars_, toks, deterministic=False, rngs={"dropout": jax.random.PRNGKey(3)})
    assert not np.allclose(np.asarray(det), np.asarray(d1), atol=1e-4)
    assert not np.allclose(np.asarray(d1), np.asarray(d2), atol=1e-4)


def test_remat_policies_are_numerically_identical():
    """Every remat_policy value yields the same loss and gradients — the
    policy only changes what the backward pass recomputes, never the math."""
    from finetune_controller_tpu.models.llama import remat_policy_fn

    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    # the policy only affects the backward pass, never the parameters —
    # one init serves every policy (repeating it was pure wall-clock)
    _, init_model = _tiny(lora_rank=4, remat_policy="full")
    vars_ = init_model.init_variables(jax.random.PRNGKey(0), batch=2, seq=16)
    frozen = {"params": vars_["params"]}

    def loss_and_grads(policy):
        cfg, model = _tiny(lora_rank=4, remat_policy=policy)

        def loss_fn(lora):
            logits = model.apply({**frozen, "lora": lora}, toks)
            return jnp.mean(
                -jax.nn.log_softmax(logits)[..., 0]
            )

        loss, grads = jax.value_and_grad(loss_fn)(vars_["lora"])
        return float(loss), grads

    ref_loss, ref_grads = loss_and_grads("full")
    for policy in ("attn", "mlp", "mlp_qkv", "flash", "mlp_flash", "wide",
                   "matmuls", "none"):
        loss, grads = loss_and_grads(policy)
        assert abs(loss - ref_loss) < 1e-6, policy
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6
            ),
            ref_grads, grads,
        )
    # unknown names fail loudly at model build, not silently as no-remat
    try:
        remat_policy_fn("bogus")
    except ValueError:
        pass
    else:
        raise AssertionError("bogus remat_policy accepted")


def test_frozen_dtype_casts_base_params():
    """frozen_dtype='bfloat16' downcasts every float32 frozen base leaf in
    lora mode, the trainable adapters stay float32, and training steps to a
    finite loss with the same loss value as the f32-frozen run (compute was
    already bf16; only storage rounding changes)."""
    from finetune_controller_tpu.data.synthetic import synthetic_batches
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    def run(frozen_dtype):
        cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
        tc = TrainConfig(
            mode="lora", batch_size=2, seq_len=16, total_steps=2,
            log_every=10**9, checkpoint_every=10**9, frozen_dtype=frozen_dtype,
        )
        tr = Trainer(cfg, tc)
        state = tr.init_state()
        batches = synthetic_batches(2, 16, cfg.vocab_size, seed=0)
        state, metrics = tr.step(state, next(batches))
        return state, float(metrics["loss"])

    state, loss = run("bfloat16")
    frozen_dtypes = {str(x.dtype) for x in jax.tree.leaves(state.frozen)}
    assert frozen_dtypes == {"bfloat16"}, frozen_dtypes
    trainable_dtypes = {str(x.dtype) for x in jax.tree.leaves(state.trainable)}
    assert trainable_dtypes == {"float32"}, trainable_dtypes
    assert np.isfinite(loss)
    _, loss_f32 = run(None)
    # tiny-test weights round-trip bf16 compute either way — losses match
    np.testing.assert_allclose(loss, loss_f32, atol=1e-3)


def test_gemma_family_trains():
    """tiny-gemma-test (decoupled head_dim, GeGLU, tied head) trains through
    the standard trainer and the loss decreases."""
    from finetune_controller_tpu.data.synthetic import synthetic_batches
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    cfg = PRESETS["tiny-gemma-test"].replace(lora=LoRAConfig(rank=4))
    assert cfg.head_dim == 32 and cfg.head_dim != cfg.d_model // cfg.n_heads
    tc = TrainConfig(
        mode="lora", learning_rate=0.02, batch_size=8, seq_len=32,
        total_steps=30, log_every=10**9, checkpoint_every=10**9,
    )
    tr = Trainer(cfg, tc)
    state = tr.init_state()
    batches = synthetic_batches(8, 32, cfg.vocab_size, seed=0, task="increment")
    first = None
    for _ in range(30):
        state, metrics = tr.step(state, next(batches))
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first * 0.8, (first, float(metrics["loss"]))


def test_qwen_family_trains():
    """tiny-qwen-test (q/k/v biases) trains through the standard trainer on
    a sharded mesh and the loss decreases."""
    from finetune_controller_tpu.data.synthetic import synthetic_batches
    from finetune_controller_tpu.parallel.mesh import MeshSpec
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    cfg = PRESETS["tiny-qwen-test"].replace(lora=LoRAConfig(rank=4))
    mesh = MeshSpec(dp=2, fsdp=2, tp=2).build(jax.devices("cpu")[:8])
    tc = TrainConfig(
        mode="lora", learning_rate=0.02, batch_size=8, seq_len=32,
        total_steps=30, log_every=10**9, checkpoint_every=10**9,
    )
    tr = Trainer(cfg, tc, mesh=mesh)
    state = tr.init_state()
    # the bias params exist and are frozen (lora mode)
    assert any(
        "bias" in jax.tree_util.keystr(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(state.frozen)[0]
    )
    batches = synthetic_batches(8, 32, cfg.vocab_size, seed=0, task="increment")
    first = None
    for _ in range(30):
        state, metrics = tr.step(state, next(batches))
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first * 0.8, (first, float(metrics["loss"]))


def test_generate_learns_increment_task():
    """End-to-end sanity loop: train tiny LoRA on the increment task, then
    greedy-generate and check the model actually continues the sequence —
    the verification surface a fine-tuning framework owes its users."""
    from finetune_controller_tpu.data.synthetic import synthetic_batches
    from finetune_controller_tpu.models.generate import generate, greedy_generate
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=8))
    tc = TrainConfig(
        mode="lora", learning_rate=0.03, batch_size=16, seq_len=32,
        total_steps=120, warmup_steps=5, log_every=10**9, checkpoint_every=10**9,
    )
    tr = Trainer(cfg, tc)
    state = tr.init_state()
    batches = synthetic_batches(16, 32, cfg.vocab_size, seed=0, task="increment")
    for _ in range(120):
        state, metrics = tr.step(state, next(batches))
    assert float(metrics["accuracy"]) > 0.9, float(metrics["accuracy"])

    variables = tr._assemble(state.frozen, state.trainable)
    # increment task: tokens count upward mod vocab; continuation must too
    prompt = jnp.asarray([[10, 11, 12, 13, 14, 15, 16, 17]], jnp.int32)
    out = greedy_generate(tr.model, variables, prompt, max_new_tokens=6)
    continuation = np.asarray(out[0, 8:])
    np.testing.assert_array_equal(continuation, np.arange(18, 24))

    # sampling path shapes + eos latching
    out2 = generate(
        tr.model, variables, prompt, max_new_tokens=4,
        temperature=0.8, top_k=5, eos_id=19, rng=jax.random.PRNGKey(1),
    )
    assert out2.shape == (1, 12)


def test_seq_len_beyond_preset_max_warns(caplog):
    """Training past the preset's max_seq_len silently degrades RoPE and
    truncates the exported max_position_embeddings — warn loudly."""
    import logging

    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=2))
    with caplog.at_level(logging.WARNING):
        Trainer(cfg, TrainConfig(mode="lora", batch_size=2, seq_len=256,
                                 total_steps=1))
    assert any("max_seq_len" in r.message for r in caplog.records)


def test_active_param_count_accounting():
    """MFU accounting: dense configs are unchanged; MoE counts the router
    plus top-k experts only — idle experts must not earn FLOP credit
    (bench.py uses 6 * active_param_count per token)."""
    dense = PRESETS["tinyllama-1.1b"]
    assert dense.active_param_count() == dense.param_count()

    moe = PRESETS["tiny-moe-test"]
    total, active = moe.param_count(), moe.active_param_count()
    # stored-vs-active differ by exactly the idle experts' weights
    d, f = moe.d_model, moe.d_ff
    idle = (moe.n_experts - moe.moe_top_k) * 3 * d * f * moe.n_layers
    assert total - active == idle
    assert active < total

    proxy = PRESETS["mixtral-proxy"]
    # the proxy docstring's sizing claims, pinned: ~3.6B stored, ~1.1B active
    assert 3.3e9 < proxy.param_count() < 3.9e9
    assert 0.9e9 < proxy.active_param_count() < 1.3e9


def test_moe_permutation_dispatch_matches_dense():
    """The scatter/gather MoE dispatch must be bit-equivalent (up to dtype
    rounding) to the reference GShard dense one-hot dispatch it replaced —
    outputs AND input gradients, including dropped tokens: tiny capacity
    forces real drops."""
    from finetune_controller_tpu.models.moe import MoEMLP

    d, f, e, k = 16, 32, 4, 2
    b, s = 2, 24

    mlp = MoEMLP(d_model=d, d_ff=f, n_experts=e, top_k=k,
                 capacity_factor=0.5,  # capacity < fair share -> forced drops
                 dtype=jnp.float32, param_dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (b, s, d), jnp.float32)
    variables = mlp.init({"params": jax.random.PRNGKey(1)}, x)
    params = variables["params"]

    def run(x):
        out, _ = mlp.apply({"params": params}, x, mutable=("moe_aux",))
        return out

    out = run(x)

    def dense_reference(params, x):
        """The pre-permutation GShard dense dispatch, re-derived."""
        bb, ss, dd = x.shape
        t = bb * ss
        import math as _math

        capacity = max(8, _math.ceil(t / e * 0.5 * k))
        capacity = min(capacity, t)
        xt = x.reshape(t, dd)
        logits = xt.astype(jnp.float32) @ params["router_kernel"].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        top_w, top_idx = jax.lax.top_k(probs, k)
        top_w = top_w / jnp.maximum(top_w.sum(-1, keepdims=True), 1e-9)
        onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)
        slot_major = onehot.transpose(1, 0, 2).reshape(k * t, e)
        position = jnp.cumsum(slot_major, axis=0) - slot_major
        position = position.reshape(k, t, e).transpose(1, 0, 2)
        in_cap = (position < capacity).astype(jnp.float32) * onehot
        pos_idx = (position * onehot).sum(-1).astype(jnp.int32)
        cap_onehot = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)
        dispatch = jnp.einsum("tke,tkc->tec", in_cap, cap_onehot)
        combine = jnp.einsum("tke,tkc,tk->tec", in_cap, cap_onehot, top_w)
        expert_in = jnp.einsum("tec,td->ecd", dispatch, xt)
        gate = jnp.einsum("ecd,edf->ecf", expert_in, params["experts_gate"])
        up = jnp.einsum("ecd,edf->ecf", expert_in, params["experts_up"])
        h = jax.nn.silu(gate) * up
        expert_out = jnp.einsum("ecf,efd->ecd", h, params["experts_down"])
        return jnp.einsum("tec,ecd->td", combine, expert_out).reshape(bb, ss, dd)

    ref = dense_reference(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    # drops really happened (otherwise this test proves less than it claims):
    # some expert must have been assigned more pairs than its capacity,
    # computed with the same formula the module uses
    import math as _math

    t = b * s
    capacity = min(max(8, _math.ceil(t / e * 0.5 * k)), t)
    logits = x.reshape(t, d) @ params["router_kernel"]
    _, top_idx = jax.lax.top_k(jax.nn.softmax(logits, -1), k)
    counts = np.bincount(np.asarray(top_idx).reshape(-1), minlength=e)
    assert counts.max() > capacity

    g1 = jax.grad(lambda x: (run(x) ** 2).sum())(x)
    g2 = jax.grad(lambda x: (dense_reference(params, x) ** 2).sum())(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)
