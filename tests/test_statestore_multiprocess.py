"""Multi-process safety of the sqlite state-store engine.

The deployed layout runs the API server and the monitor as separate processes
against one state dir — the way the reference's two deployments share one
MongoDB (``app/database/db.py:51``, ``Dockerfile.monitor:30``).  These tests
spawn REAL OS processes doing concurrent read-modify-writes against the same
store and prove no update is lost and no read is stale — the round-2 jsonl
engine failed both by construction (in-memory indexes, no reload, compaction
``replace()`` clobbering the other process's appends).
"""

from __future__ import annotations

import asyncio
import json
import subprocess
import sys
from pathlib import Path

from finetune_controller_tpu.controller.schemas import DatabaseStatus, JobRecord
from finetune_controller_tpu.controller.statestore import StateStore


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


#: worker: CAS-increments the shared counter N times, merges N unique keys
#: into the shared doc's metadata, and inserts N jobs of its own
_WORKER = """
import asyncio, sys
from finetune_controller_tpu.controller.statestore import StateStore

async def main(state_dir, who, n):
    store = StateStore(state_dir, backend="sqlite")
    await store.connect()
    for i in range(n):
        await store.jobs.insert({"job_id": f"{who}-{i}", "user_id": who})
        await store.jobs.merge_subdoc("shared", "metadata", {f"{who}{i}": i})
        while True:  # optimistic-CAS counter: atomicity proof
            doc = await store.jobs.get("shared")
            c = doc["count"]
            if await store.jobs.update_if(
                "shared", {"count": c + 1}, lambda d: d["count"] == c
            ):
                break
    await store.close()

asyncio.run(main(sys.argv[1], sys.argv[2], int(sys.argv[3])))
"""


def test_two_processes_no_lost_updates(tmp_path):
    state_dir = tmp_path / "state"
    store = StateStore(state_dir, backend="sqlite")
    n = 40

    async def setup():
        await store.connect()
        await store.jobs.insert({"job_id": "shared", "count": 0, "metadata": {}})

    run(setup())

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(state_dir), who, str(n)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for who in ("api", "mon")
    ]
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, err

    async def check():
        # the parent's ORIGINAL store instance must see the children's writes
        # (no stale in-process cache)
        shared = await store.jobs.get("shared")
        assert shared["count"] == 2 * n  # every CAS increment survived
        assert len(shared["metadata"]) == 2 * n  # every merge survived
        for who in ("api", "mon"):
            docs = await store.jobs.find(eq={"user_id": who})
            assert len(docs) == n  # every insert survived
        await store.close()

    run(check())


def test_monitor_write_visible_to_api_process(tmp_path):
    """The API-vs-monitor split specifically: monitor flips a job RUNNING in
    its own process; the API process's long-lived store sees it."""
    state_dir = tmp_path / "state"
    api_store = StateStore(state_dir, backend="sqlite")

    async def setup():
        await api_store.connect()
        await api_store.create_job(
            JobRecord(job_id="j1", user_id="alice", model_name="m")
        )

    run(setup())

    monitor = (
        "import asyncio, sys\n"
        "from finetune_controller_tpu.controller.statestore import StateStore\n"
        "from finetune_controller_tpu.controller.schemas import DatabaseStatus\n"
        "async def main():\n"
        "    s = StateStore(sys.argv[1], backend='sqlite')\n"
        "    await s.connect()\n"
        "    ok = await s.update_job_status(\n"
        "        'j1', DatabaseStatus.RUNNING, metadata={'node': 'w0'})\n"
        "    assert ok\n"
        "    await s.close()\n"
        "asyncio.run(main())\n"
    )
    subprocess.run(
        [sys.executable, "-c", monitor, str(state_dir)], check=True, timeout=60
    )

    async def check():
        job = await api_store.get_job("j1")
        assert job.status == DatabaseStatus.RUNNING
        assert job.metadata == {"node": "w0"}
        await api_store.close()

    run(check())


def test_jsonl_state_migrates_into_sqlite(tmp_path):
    """A round-2 state dir (jsonl logs) upgrades in place on connect()."""
    state_dir = tmp_path / "state"
    legacy = StateStore(state_dir, backend="jsonl")

    async def write_legacy():
        await legacy.connect()
        await legacy.create_job(JobRecord(job_id="old1", user_id="u", model_name="m"))
        await legacy.create_job(JobRecord(job_id="old2", user_id="u", model_name="m"))
        await legacy.update_job_status("old2", DatabaseStatus.SUCCEEDED)

    run(write_legacy())

    upgraded = StateStore(state_dir, backend="sqlite")

    async def check():
        await upgraded.connect()
        assert (await upgraded.get_job("old1")).status == DatabaseStatus.QUEUED
        assert (await upgraded.get_job("old2")).status == DatabaseStatus.SUCCEEDED
        # the legacy log is retired: a deleted job + restart with an empty
        # table must NOT resurrect pre-migration docs
        assert not (state_dir / "jobs.jsonl").exists()
        assert (state_dir / "jobs.jsonl.migrated").exists()
        await upgraded.delete_job("old1")
        await upgraded.delete_job("old2")
        again = StateStore(state_dir, backend="sqlite")
        await again.connect()
        assert await again.jobs.find() == []  # stays empty — no resurrection
        assert (await again.archived_jobs.count()) == 2
        await upgraded.close()
        await again.close()

    run(check())


def test_unknown_backend_rejected(tmp_path):
    import pytest

    with pytest.raises(ValueError, match="unknown state backend"):
        StateStore(tmp_path / "state", backend="sqllite")
