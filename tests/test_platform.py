"""Platform-resolution helper tests.

The helper has to thread a needle: honour JAX_PLATFORMS for the CPU-mesh
test/CI paths, but not let the literal "tpu" platform list break boxes where
a site tunnel plugin serves the TPU under its own platform name (the axon
gotcha — .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import pytest

from finetune_controller_tpu import platform as plat


def test_assert_platform_env_honours_cpu(monkeypatch):
    import jax

    calls = []
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(jax.config, "update", lambda k, v: calls.append((k, v)))
    plat.assert_platform_env()
    assert calls == [("jax_platforms", "cpu")]


def test_assert_platform_env_noop_when_unset(monkeypatch):
    import jax

    calls = []
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(jax.config, "update", lambda k, v: calls.append((k, v)))
    plat.assert_platform_env()
    assert calls == []


class _FakeTpuDevice:
    platform = "tpu"


class _FakeCpuDevice:
    platform = "cpu"


def test_assert_platform_env_tpu_falls_back_when_literal_init_fails(monkeypatch):
    """On a tunnel box, forcing platforms="tpu" selects the deviceless local
    libtpu; the helper must probe init, restore the plugin's resolution, and
    confirm the restored resolution actually serves a TPU."""
    import jax

    calls = []
    prev = jax.config.jax_platforms
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.setattr(jax.config, "update", lambda k, v: calls.append((k, v)))

    outcomes = iter(["boom", "tunnel-tpu"])

    def devices():
        if next(outcomes) == "boom":
            raise RuntimeError("Unable to initialize backend 'tpu'")
        return [_FakeTpuDevice()]

    monkeypatch.setattr(jax, "devices", devices)
    plat.assert_platform_env()
    assert calls == [("jax_platforms", "tpu"), ("jax_platforms", prev)]


def test_assert_platform_env_tpu_refuses_silent_cpu_fallback(monkeypatch):
    """If the restored resolution has no TPU either, the helper must fail
    loudly — a JAX_PLATFORMS=tpu run silently landing on CPU would produce
    CPU numbers labelled as TPU measurements."""
    import jax

    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.setattr(jax.config, "update", lambda k, v: None)

    outcomes = iter(["boom", "cpu-only"])

    def devices():
        if next(outcomes) == "boom":
            raise RuntimeError("Unable to initialize backend 'tpu'")
        return [_FakeCpuDevice()]

    monkeypatch.setattr(jax, "devices", devices)
    with pytest.raises(RuntimeError, match="no TPU device"):
        plat.assert_platform_env()


def test_assert_platform_env_tpu_kept_when_init_succeeds(monkeypatch):
    import jax

    calls = []
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.setattr(jax.config, "update", lambda k, v: calls.append((k, v)))
    monkeypatch.setattr(jax, "devices", lambda: ["fake-tpu"])
    plat.assert_platform_env()
    assert calls == [("jax_platforms", "tpu")]


@pytest.mark.parametrize(
    "raw,expect",
    [("1", True), ("true", True), ("0", False), ("off", False), ("", False)],
)
def test_env_flag(monkeypatch, raw, expect):
    monkeypatch.setenv("FTC_SOME_FLAG", raw)
    assert plat.env_flag("FTC_SOME_FLAG") is expect
