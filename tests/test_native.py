"""Native C++ packer: build, parity with the Python loader, error contract.

The reference keeps zero native code in-repo (SURVEY.md §2.2); this framework
owns its data-path hot loop in C++ — these tests pin byte-exact parity
between the two implementations so the native path can never silently drift.
"""

import json

import numpy as np
import pytest

from finetune_controller_tpu.data.loader import (
    jsonl_token_batches,
    load_token_documents,
    pack_documents,
)
from finetune_controller_tpu.data.native_loader import available, pack_jsonl_native

pytestmark = pytest.mark.skipif(
    not available(), reason="no C++ toolchain available for the native loader"
)


TRICKY_ROWS = [
    {"text": "plain ascii text"},
    {"text": 'quotes " and \\ backslashes \\" mixed'},
    {"text": "tabs\tnewlines\nand\rcontrol \b\f chars"},
    {"text": "unicodé café ♞ \U0001f600 mixed"},
    {"tokens": [1, 2, 3, 500, 65535, 0]},
    {"text": ""},
    {"text": "x" * 300},
]


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


def test_native_pack_parity_with_python(tmp_path):
    p = tmp_path / "data.jsonl"
    _write_jsonl(p, TRICKY_ROWS)
    for seq_len in (16, 64, 1024):
        docs = load_token_documents(str(p))
        py_tokens, py_segs, _ = pack_documents(docs, seq_len)
        nat = pack_jsonl_native(str(p), seq_len)
        assert nat is not None
        np.testing.assert_array_equal(nat[0], py_tokens)
        np.testing.assert_array_equal(nat[1], py_segs)


def test_native_pack_parity_ensure_ascii_false(tmp_path):
    # raw (non-escaped) UTF-8 in the file
    p = tmp_path / "raw.jsonl"
    with open(p, "w") as f:
        for row in [{"text": "café ♞ emoji 😀"}, {"text": "δοκιμή"}]:
            f.write(json.dumps(row, ensure_ascii=False) + "\n")
    docs = load_token_documents(str(p))
    py_tokens, py_segs, _ = pack_documents(docs, 32)
    nat = pack_jsonl_native(str(p), 32)
    np.testing.assert_array_equal(nat[0], py_tokens)
    np.testing.assert_array_equal(nat[1], py_segs)


def test_native_error_contract(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"neither": 1}\n')
    with pytest.raises(ValueError):
        pack_jsonl_native(str(p), 16)
    missing = tmp_path / "nope.jsonl"
    with pytest.raises(ValueError):
        pack_jsonl_native(str(missing), 16)


def test_jsonl_token_batches_uses_native(tmp_path, caplog):
    p = tmp_path / "data.jsonl"
    _write_jsonl(p, [{"text": "hello world, a training document"}] * 8)
    it = jsonl_token_batches(str(p), batch_size=2, seq_len=16)
    batch = next(it)
    assert batch["tokens"].shape == (2, 16)
    assert batch["segment_ids"].shape == (2, 16)
    assert (batch["loss_mask"] <= 1).all()


def test_native_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv("FTC_NATIVE", "0")
    import importlib

    from finetune_controller_tpu.data import native_loader

    importlib.reload(native_loader)
    try:
        assert native_loader.available() is False
        assert native_loader.pack_jsonl_native("x.jsonl", 16) is None
    finally:
        monkeypatch.delenv("FTC_NATIVE")
        importlib.reload(native_loader)


def test_native_top_level_key_matching(tmp_path):
    """Nested 'tokens'/'text' keys must not shadow the top-level row schema."""
    p = tmp_path / "nested.jsonl"
    rows = [
        {"id": "a", "text": "hello world", "meta": {"tokens": [9, 9, 9]}},
        {"meta": {"tokens": 5}, "text": "hi"},
    ]
    _write_jsonl(p, rows)
    docs = load_token_documents(str(p))
    py_tokens, py_segs, _ = pack_documents(docs, 16)
    nat = pack_jsonl_native(str(p), 16)
    np.testing.assert_array_equal(nat[0], py_tokens)
    np.testing.assert_array_equal(nat[1], py_segs)
