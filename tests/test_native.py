"""Native C++ packer: build, parity with the Python loader, error contract.

The reference keeps zero native code in-repo (SURVEY.md §2.2); this framework
owns its data-path hot loop in C++ — these tests pin byte-exact parity
between the two implementations so the native path can never silently drift.
"""

import json

import numpy as np
import pytest

from finetune_controller_tpu.data.loader import (
    jsonl_token_batches,
    load_token_documents,
    pack_documents,
)
from finetune_controller_tpu.data.native_loader import available, pack_jsonl_native

pytestmark = pytest.mark.skipif(
    not available(), reason="no C++ toolchain available for the native loader"
)


TRICKY_ROWS = [
    {"text": "plain ascii text"},
    {"text": 'quotes " and \\ backslashes \\" mixed'},
    {"text": "tabs\tnewlines\nand\rcontrol \b\f chars"},
    {"text": "unicodé café ♞ \U0001f600 mixed"},
    {"tokens": [1, 2, 3, 500, 65535, 0]},
    {"text": ""},
    {"text": "x" * 300},
]


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


def test_native_pack_parity_with_python(tmp_path):
    p = tmp_path / "data.jsonl"
    _write_jsonl(p, TRICKY_ROWS)
    for seq_len in (16, 64, 1024):
        docs = load_token_documents(str(p))
        py_tokens, py_segs, _ = pack_documents(docs, seq_len)
        nat = pack_jsonl_native(str(p), seq_len)
        assert nat is not None
        np.testing.assert_array_equal(nat[0], py_tokens)
        np.testing.assert_array_equal(nat[1], py_segs)


def test_native_pack_parity_ensure_ascii_false(tmp_path):
    # raw (non-escaped) UTF-8 in the file
    p = tmp_path / "raw.jsonl"
    with open(p, "w") as f:
        for row in [{"text": "café ♞ emoji 😀"}, {"text": "δοκιμή"}]:
            f.write(json.dumps(row, ensure_ascii=False) + "\n")
    docs = load_token_documents(str(p))
    py_tokens, py_segs, _ = pack_documents(docs, 32)
    nat = pack_jsonl_native(str(p), 32)
    np.testing.assert_array_equal(nat[0], py_tokens)
    np.testing.assert_array_equal(nat[1], py_segs)


def test_native_error_contract(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"neither": 1}\n')
    with pytest.raises(ValueError):
        pack_jsonl_native(str(p), 16)
    missing = tmp_path / "nope.jsonl"
    with pytest.raises(ValueError):
        pack_jsonl_native(str(missing), 16)


def test_jsonl_token_batches_uses_native(tmp_path, caplog):
    p = tmp_path / "data.jsonl"
    _write_jsonl(p, [{"text": "hello world, a training document"}] * 8)
    it = jsonl_token_batches(str(p), batch_size=2, seq_len=16)
    batch = next(it)
    assert batch["tokens"].shape == (2, 16)
    assert batch["segment_ids"].shape == (2, 16)
    assert (batch["loss_mask"] <= 1).all()


def test_native_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv("FTC_NATIVE", "0")
    import importlib

    from finetune_controller_tpu.data import native_loader

    importlib.reload(native_loader)
    try:
        assert native_loader.available() is False
        assert native_loader.pack_jsonl_native("x.jsonl", 16) is None
    finally:
        monkeypatch.delenv("FTC_NATIVE")
        importlib.reload(native_loader)


def test_native_top_level_key_matching(tmp_path):
    """Nested 'tokens'/'text' keys must not shadow the top-level row schema."""
    p = tmp_path / "nested.jsonl"
    rows = [
        {"id": "a", "text": "hello world", "meta": {"tokens": [9, 9, 9]}},
        {"meta": {"tokens": 5}, "text": "hi"},
    ]
    _write_jsonl(p, rows)
    docs = load_token_documents(str(p))
    py_tokens, py_segs, _ = pack_documents(docs, 16)
    nat = pack_jsonl_native(str(p), 16)
    np.testing.assert_array_equal(nat[0], py_tokens)
    np.testing.assert_array_equal(nat[1], py_segs)


SFT_ROWS = [
    {"prompt": "Q: what is 2+2?\nA: ", "completion": "4"},
    {"prompt_tokens": [10, 11, 12], "completion_tokens": [13, 14]},
    {"prompt": "unicodé prompt ♞ ", "completion": "réponse 😀"},
    {"text": "a plain LM row mixed into the SFT corpus"},
    {"tokens": [7, 8, 9]},
]

CHAT_ROWS = [
    {"messages": [
        {"role": "system", "content": "be terse"},
        {"role": "user", "content": "hi there"},
        {"role": "assistant", "content": "hello!"},
        {"role": "user", "content": "more?"},
        {"role": "assistant", "content": 'sure: "quoted" ♘ text'},
    ]},
    {"messages": [
        {"role": "user", "content": "only\nturn"},
        {"role": "assistant", "content": ""},
    ]},
]


def test_native_sft_parity_with_python(tmp_path):
    """SFT prompt/completion rows: tokens, segments AND loss flags match the
    Python loader byte-for-byte (completion-only loss)."""
    p = tmp_path / "sft.jsonl"
    _write_jsonl(p, SFT_ROWS)
    for seq_len in (16, 128):
        docs = load_token_documents(str(p))
        py_tokens, py_segs, py_flags = pack_documents(docs, seq_len)
        nat = pack_jsonl_native(str(p), seq_len)
        assert nat is not None
        np.testing.assert_array_equal(nat[0], py_tokens)
        np.testing.assert_array_equal(nat[1], py_segs)
        np.testing.assert_array_equal(nat[2], py_flags)
        assert 0.0 < py_flags.mean() < 1.0  # genuinely masked


def test_native_chat_parity_with_python(tmp_path):
    """Chat rows render the same template with assistant-only loss."""
    p = tmp_path / "chat.jsonl"
    _write_jsonl(p, CHAT_ROWS)
    docs = load_token_documents(str(p))
    py_tokens, py_segs, py_flags = pack_documents(docs, 64)
    nat = pack_jsonl_native(str(p), 64)
    assert nat is not None
    np.testing.assert_array_equal(nat[0], py_tokens)
    np.testing.assert_array_equal(nat[1], py_segs)
    np.testing.assert_array_equal(nat[2], py_flags)


def test_native_chat_raw_utf8(tmp_path):
    p = tmp_path / "chat_raw.jsonl"
    with open(p, "w") as f:
        for row in CHAT_ROWS:
            f.write(json.dumps(row, ensure_ascii=False) + "\n")
    docs = load_token_documents(str(p))
    py_tokens, _, py_flags = pack_documents(docs, 64)
    nat = pack_jsonl_native(str(p), 64)
    np.testing.assert_array_equal(nat[0], py_tokens)
    np.testing.assert_array_equal(nat[2], py_flags)


def test_native_all_masked_chat_rejected(tmp_path):
    """The wrong-role footgun ({'role': 'model'}) errors in the native path
    too, so the fallback re-raises the Python loader's detailed message."""
    p = tmp_path / "bad_chat.jsonl"
    _write_jsonl(p, [{"messages": [{"role": "model", "content": "hi"}]}])
    with pytest.raises(ValueError):
        pack_jsonl_native(str(p), 16)


def test_jsonl_token_batches_native_sft_mask(tmp_path):
    """End-to-end: the batch iterator's loss_mask carries the native flags
    (completion-only) AND the packing-boundary zeros."""
    p = tmp_path / "sft2.jsonl"
    _write_jsonl(p, [{"prompt": "ppppp", "completion": "cc"}] * 10)
    it = jsonl_token_batches(str(p), batch_size=2, seq_len=14)
    batch = next(it)
    assert batch["loss_mask"].shape == batch["tokens"].shape
    m = batch["loss_mask"].mean()
    assert 0.0 < m < 0.5  # 2 of 7 positions per doc, minus boundary masking


def test_native_truncated_chat_row_rejected(tmp_path):
    """A row cut mid-array (interrupted download) must error, not train."""
    p = tmp_path / "trunc.jsonl"
    p.write_text('{"messages": [{"role": "assistant", "content": "x"}\n')
    with pytest.raises(ValueError):
        pack_jsonl_native(str(p), 16)
