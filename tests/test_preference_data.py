"""Seeded preference-pair datasets (ISSUE 8 satellite): determinism,
prompt-masking, jsonl parsing, and prefetch bit-identity."""

import json

import numpy as np
import pytest

from finetune_controller_tpu.data.preference import (
    _pad_pair,
    load_preference_rows,
    make_increment_pair,
    preference_jsonl_batches,
    synthetic_preference_batches,
)

BATCH_KEYS = {"chosen_tokens", "chosen_mask", "rejected_tokens", "rejected_mask"}


def _take(it, n):
    return [next(it) for _ in range(n)]


def test_same_seed_identical_pairs():
    a = _take(synthetic_preference_batches(4, 32, 256, seed=7), 3)
    b = _take(synthetic_preference_batches(4, 32, 256, seed=7), 3)
    for ba, bb in zip(a, b):
        assert set(ba) == BATCH_KEYS
        for k in BATCH_KEYS:
            np.testing.assert_array_equal(ba[k], bb[k])
    c = next(synthetic_preference_batches(4, 32, 256, seed=8))
    assert any(
        not np.array_equal(a[0][k], c[k]) for k in BATCH_KEYS
    ), "different seeds produced identical batches"


def test_masks_exclude_prompt_tokens_and_padding():
    batch = next(synthetic_preference_batches(8, 32, 256, seed=0))
    prompt_len = 16  # prompt_fraction=0.5 of seq 32
    for key in ("chosen", "rejected"):
        mask = batch[f"{key}_mask"]
        # prompt positions never count; every row has completion targets
        assert not mask[:, :prompt_len].any()
        assert (mask[:, prompt_len:].sum(axis=1) > 0).all()
    # shared prompt prefix between the two sides of each pair
    np.testing.assert_array_equal(
        batch["chosen_tokens"][:, :prompt_len],
        batch["rejected_tokens"][:, :prompt_len],
    )
    # chosen continues the increment; rejected breaks it at the first target
    tok = batch["chosen_tokens"]
    assert (tok[:, prompt_len] == (tok[:, prompt_len - 1] + 1) % 256).all()
    rej = batch["rejected_tokens"]
    assert (rej[:, prompt_len] != (rej[:, prompt_len - 1] + 1) % 256).all()


def test_make_increment_pair_rewards_separate():
    rng = np.random.default_rng(0)
    prompt, chosen, rejected = make_increment_pair(rng, 32, 256)
    assert chosen != rejected
    assert chosen[0] == (prompt[-1] + 1) % 256


def test_pad_pair_truncation_keeps_full_prompt():
    tokens, mask = _pad_pair(list(range(10)), list(range(100, 140)), 16)
    assert tokens.shape == (16,) and mask.shape == (16,)
    np.testing.assert_array_equal(tokens[:10], np.arange(10))
    assert mask[:10].sum() == 0 and mask[10:].sum() == 6  # truncated completion
    # a prompt >= seq_len leaves at least one completion slot
    tokens, mask = _pad_pair(list(range(40)), [7, 8], 16)
    assert mask.sum() >= 1


def test_jsonl_rows_text_and_tokens(tmp_path):
    path = tmp_path / "prefs.jsonl"
    rows = [
        {"prompt": "ab", "chosen": "cd", "rejected": "xy"},
        {"prompt_tokens": [1, 2], "chosen_tokens": [3, 4],
         "rejected_tokens": [9, 9]},
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    loaded = load_preference_rows(str(path))
    assert loaded[0] == ([97, 98], [99, 100], [120, 121])  # byte tokenizer
    assert loaded[1] == ([1, 2], [3, 4], [9, 9])
    batches = preference_jsonl_batches(str(path), batch_size=2, seq_len=8,
                                       seed=3)
    a, b = next(batches), next(batches)
    assert set(a) == BATCH_KEYS and a["chosen_tokens"].shape == (2, 8)
    # deterministic replay
    again = preference_jsonl_batches(str(path), batch_size=2, seq_len=8,
                                     seed=3)
    np.testing.assert_array_equal(next(again)["chosen_tokens"],
                                  a["chosen_tokens"])
    np.testing.assert_array_equal(next(again)["rejected_mask"],
                                  b["rejected_mask"])


def test_jsonl_bad_rows_raise(tmp_path):
    bad_schema = tmp_path / "bad.jsonl"
    bad_schema.write_text(json.dumps({"prompt": "a", "completion": "b"}) + "\n")
    with pytest.raises(ValueError, match="preference jsonl rows"):
        load_preference_rows(str(bad_schema))
    empty_side = tmp_path / "empty.jsonl"
    empty_side.write_text(
        json.dumps({"prompt": "a", "chosen": "", "rejected": "b"}) + "\n"
    )
    with pytest.raises(ValueError, match="non-empty"):
        load_preference_rows(str(empty_side))
    with pytest.raises(ValueError, match="no preference pairs"):
        nothing = tmp_path / "none.jsonl"
        nothing.write_text("\n")
        load_preference_rows(str(nothing))


def test_prefetch_on_off_bit_identical():
    """The DPO batch stream rides the existing background-prefetch path
    unchanged: same seed, prefetch on vs off, bit-identical batches."""
    from finetune_controller_tpu.data.prefetch import PrefetchIterator

    raw = _take(synthetic_preference_batches(4, 32, 256, seed=11), 6)
    pre = PrefetchIterator(
        synthetic_preference_batches(4, 32, 256, seed=11), depth=2
    )
    try:
        fetched = _take(pre, 6)
    finally:
        pre.close()
    for r, f in zip(raw, fetched):
        for k in BATCH_KEYS:
            np.testing.assert_array_equal(r[k], f[k])


def test_dpo_real_dataset_without_eval_split_yields_none(tmp_path):
    """A dpo job with a real preference dataset but no eval_path must NOT
    silently evaluate on synthetic pairs: build_batches returns None for the
    eval split, which run_job turns into the explicit 'no eval split' error."""
    from finetune_controller_tpu.models.llama import PRESETS
    from finetune_controller_tpu.train.cli import build_batches
    from finetune_controller_tpu.train.trainer import TrainConfig

    path = tmp_path / "prefs.jsonl"
    path.write_text(json.dumps(
        {"prompt": "ab", "chosen": "cd", "rejected": "xy"}) + "\n")
    spec = {"dataset": {"path": str(path)}}
    cfg = TrainConfig(task="dpo")
    model_cfg = PRESETS["tiny-test"]
    train = build_batches(spec, model_cfg, cfg, 2, 0, 1, split="train")
    assert next(train)["chosen_tokens"].shape == (2, cfg.seq_len)
    assert build_batches(spec, model_cfg, cfg, 2, 0, 1, split="eval") is None
