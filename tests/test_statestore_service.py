"""Shared state service tests (round-5, VERDICT #5).

The reference scales its API horizontally because all replicas talk to one
external MongoDB (``app/database/db.py:51``). Our equivalent is the state
service (``controller/statestore_service.py``): these tests run the real
daemon app with TWO independent ``RemoteStateStore`` clients — the API×N +
monitor layout in miniature — and prove shared visibility, CAS semantics
across clients, cluster-scope rate limiting, and token auth. A subprocess
test covers the ``statestore_main`` entrypoint end to end.
"""

from __future__ import annotations

import asyncio
import socket
import subprocess
import sys
import time

from aiohttp.test_utils import TestServer

from conftest import run_async as run
from finetune_controller_tpu.controller.schemas import (
    DatabaseStatus,
    JobRecord,
    MetricsDocument,
    PromotionStatus,
)
from finetune_controller_tpu.controller.statestore import StateStore
from finetune_controller_tpu.controller.statestore_service import (
    RemoteStateStore,
    build_state_app,
)


def _job(job_id: str, user_id: str = "u") -> JobRecord:
    return JobRecord(
        job_id=job_id, user_id=user_id, model_name="tiny-test-lora",
        device="chip-1", arguments={},
    )


async def _service(tmp_path, token: str = ""):
    store = StateStore(tmp_path / "state", backend="sqlite")
    await store.connect()
    server = TestServer(build_state_app(store, token))
    await server.start_server()
    url = str(server.make_url("")).rstrip("/")
    return store, server, url


def test_two_clients_share_one_view(tmp_path):
    async def go():
        store, server, url = await _service(tmp_path)
        a = RemoteStateStore(url)
        b = RemoteStateStore(url)
        await a.connect()
        await b.connect()

        # writes by A are immediately visible to B (the monitor/API split)
        await a.create_job(_job("j-1"))
        rec = await b.get_job("j-1")
        assert rec is not None and rec.status is DatabaseStatus.QUEUED

        assert await b.update_job_status(
            "j-1", DatabaseStatus.RUNNING,
            metadata={"node": "n1"}, start_time=100.0,
        )
        rec = await a.get_job("j-1")
        assert rec.status is DatabaseStatus.RUNNING
        assert rec.start_time == 100.0 and rec.metadata["node"] == "n1"

        # batch + active sweeps
        await a.create_job(_job("j-2"))
        jobs = await b.get_jobs_by_ids(["j-1", "j-2", "missing"])
        assert set(jobs) == {"j-1", "j-2"}
        assert {j.job_id for j in await a.get_active_jobs()} == {"j-1", "j-2"}

        # paginated table with computed fields
        page = await b.get_user_jobs("u", page=1, page_size=1)
        assert page.total == 2 and len(page.items) == 1
        assert "status_merged" in page.items[0]

        # metrics + datasets round-trip
        await a.upsert_metrics(MetricsDocument(
            job_id="j-1", records=[{"step": 1, "loss": 2.0}]
        ))
        doc = await b.get_metrics("j-1")
        assert doc.records[0]["loss"] == 2.0

        # timeline events cross the wire with idempotency intact — single
        # append, batched append (the monitor ingest path), metadata merge
        from finetune_controller_tpu.obs import make_event

        assert await a.append_job_event(
            "j-1", make_event("running", key="running:a1")
        )
        assert not await b.append_job_event(
            "j-1", make_event("running", key="running:a1")
        )
        assert await b.append_job_events("j-1", [
            make_event("checkpoint-committed", key="trainer:a1:0", step=10),
            make_event("checkpoint-committed", key="trainer:a1:0", step=10),
            make_event("train-finished", key="trainer:a1:1", step=20),
        ]) == 2
        assert await a.append_job_events("j-1", []) == 0
        assert await a.merge_job_metadata("j-1", {"obs_events_ingested": 2})
        rec = await b.get_job("j-1")
        assert [e["event"] for e in rec.events] == [
            "running", "checkpoint-committed", "train-finished",
        ]
        assert rec.metadata["obs_events_ingested"] == 2

        # promotion recovery sweep crosses the wire without predicates
        await a.update_job_promotion("j-1", PromotionStatus.IN_PROGRESS, "obj://d/x")
        stuck = await b.find_jobs_with_promotion_in([PromotionStatus.IN_PROGRESS])
        assert [j.job_id for j in stuck] == ["j-1"]

        # archive-on-delete
        assert await b.delete_job("j-2")
        assert await a.get_job("j-2") is None

        await a.close()
        await b.close()
        await server.close()
        await store.close()

    run(go())


def test_begin_promotion_cas_across_clients(tmp_path):
    """Concurrent promotion claims from two replicas: exactly one wins."""

    async def go():
        store, server, url = await _service(tmp_path)
        a = RemoteStateStore(url)
        b = RemoteStateStore(url)
        await a.create_job(_job("p-1"))

        results = await asyncio.gather(*[
            c.begin_promotion("p-1", PromotionStatus.IN_PROGRESS, "obj://d/p")
            for c in (a, b) for _ in range(4)
        ])
        assert sum(results) == 1

        await a.close()
        await b.close()
        await server.close()
        await store.close()

    run(go())


def test_rate_limit_is_cluster_scope(tmp_path):
    """N replicas share ONE window through the service — the per-process
    multiplication the reference suffers (app/main.py:377) cannot happen."""

    async def go():
        store, server, url = await _service(tmp_path)
        a = RemoteStateStore(url)
        b = RemoteStateStore(url)

        grants = [
            await c.rate_limit_acquire("rl/submit/u", 5, 60.0)
            for _ in range(5) for c in (a, b)
        ]
        assert sum(grants) == 5  # NOT 10

        await a.close()
        await b.close()
        await server.close()
        await store.close()

    run(go())


def test_token_auth_rejects_bad_clients(tmp_path):
    async def go():
        store, server, url = await _service(tmp_path, token="s3cret")
        good = RemoteStateStore(url, token="s3cret")
        await good.create_job(_job("t-1"))
        assert (await good.get_job("t-1")).job_id == "t-1"

        bad = RemoteStateStore(url, token="wrong")
        try:
            await bad.get_job("t-1")
            raise AssertionError("expected auth rejection")
        except IOError as e:
            assert "401" in str(e)

        await good.close()
        await bad.close()
        await server.close()
        await store.close()

    run(go())


def test_statestore_main_subprocess_entrypoint(tmp_path):
    """The real daemon process serves a real client — the deployment seam."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "finetune_controller_tpu.controller.statestore_main",
         "--state-dir", str(tmp_path / "state"),
         "--host", "127.0.0.1", "--port", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        async def go():
            client = RemoteStateStore(f"http://127.0.0.1:{port}")
            deadline = time.time() + 30
            while True:
                try:
                    await client.connect()
                    break
                except Exception:
                    assert time.time() < deadline, "state service never came up"
                    assert proc.poll() is None, "state service exited early"
                    await asyncio.sleep(0.2)
            await client.create_job(_job("sub-1"))
            assert (await client.get_job("sub-1")).job_id == "sub-1"
            assert await client.rate_limit_acquire("k", 1, 60.0)
            assert not await client.rate_limit_acquire("k", 1, 60.0)
            await client.close()

        run(go())
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_sqlite_rate_limit_shared_across_store_instances(tmp_path):
    """Two StateStore instances on one state dir (API worker + monitor on a
    node) share the sliding window through the WAL database."""

    async def go():
        a = StateStore(tmp_path / "state", backend="sqlite")
        b = StateStore(tmp_path / "state", backend="sqlite")
        await a.connect()
        await b.connect()
        grants = [
            await c.rate_limit_acquire("rl/read/u", 3, 60.0)
            for _ in range(3) for c in (a, b)
        ]
        assert sum(grants) == 3
        await a.close()
        await b.close()

    run(go())


def test_memory_store_rate_limit_window(tmp_path):
    """The in-memory engine keeps the old per-process semantics (dev)."""

    async def go():
        store = StateStore(None)
        assert await store.rate_limit_acquire("k", 2, 0.2)
        assert await store.rate_limit_acquire("k", 2, 0.2)
        assert not await store.rate_limit_acquire("k", 2, 0.2)
        await asyncio.sleep(0.25)
        assert await store.rate_limit_acquire("k", 2, 0.2)

    run(go())
