"""The serving subsystem: engine invariance, batching, loader, HTTP surface.

The correctness anchor (ISSUE 4): greedy decode from the continuous-batching
engine must be BIT-IDENTICAL to single-request ``cached_generate`` for every
request in a mixed concurrent batch — batching must never change what a user
gets.  Plus: bounded compile count under the recompile guard, slot reuse and
eviction, the asyncio batcher's backpressure/deadlines, LoRA merge math, the
promoted-checkpoint loader's refusal of non-COMPLETED promotions, and the
promote→serve HTTP loop end to end on the local fake cluster.
"""

from __future__ import annotations

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import one_chip_catalog, run_async
from finetune_controller_tpu.models.generate import cached_generate
from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.serve.batcher import (
    Batcher,
    DeadlineExceeded,
    QueueFull,
)
from finetune_controller_tpu.serve.engine import (
    BatchEngine,
    EngineBusy,
    EngineConfig,
    GenRequest,
    PromptTooLong,
)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    model = LlamaForCausalLM(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 4), jnp.int32)
    )
    return model, variables


def _engine(model, variables, **kw):
    defaults = dict(slots=4, prompt_buckets=(8, 16), max_new_tokens=24)
    defaults.update(kw)
    return BatchEngine(model, variables, EngineConfig(**defaults))


def _baseline(model, variables, prompt, n, **kw):
    out = cached_generate(
        model, variables, jnp.asarray([prompt], jnp.int32),
        max_new_tokens=n, **kw,
    )
    return list(np.asarray(out[0, len(prompt):]))


# ---------------------------------------------------------------------------
# Engine: batching invariance (the acceptance anchor)
# ---------------------------------------------------------------------------


def test_batching_invariance_mixed_concurrent(tiny_model):
    """Greedy tokens from a mixed batch — different prompt lengths, different
    max_new_tokens, requests joining MID-FLIGHT — are bit-identical to
    single-request cached_generate for every request."""
    model, variables = tiny_model
    eng = _engine(model, variables, slots=2)
    prompts = [
        [5, 9, 2, 7],
        [1, 3, 3, 8, 2, 2],
        [7, 7, 7],
        [11, 4, 9, 1, 2, 3, 4, 5, 6, 0, 2, 1],  # second bucket
        [2, 13],
    ]
    # per-request max_new varies (the invariance must not depend on it); the
    # values are picked so the cached_generate BASELINES collide on two
    # cache lengths (plen+max_new ∈ {14,16}) and share compiled decode fns —
    # wall-clock discipline, not a correctness constraint
    max_new = [10, 8, 11, 4, 12]
    reqs = [
        GenRequest(request_id=f"r{i}", tokens=p, max_new_tokens=max_new[i])
        for i, p in enumerate(prompts)
    ]
    results = {}

    def collect(done_list):
        for r in done_list:
            results[r.request_id] = r

    # staggered drive: r0 decodes alone, r1 joins mid-flight, the rest
    # refill lanes as they free — never a drained batch between requests
    eng.admit(reqs[0])
    collect(eng.step())
    collect(eng.step())
    eng.admit(reqs[1])
    collect(eng.step())
    pending = reqs[2:]
    while pending or eng.active_requests:
        while pending and eng.free_slots:
            done = eng.admit(pending.pop(0))
            if done is not None:
                results[done.request_id] = done
        collect(eng.step())

    for i, p in enumerate(prompts):
        want = _baseline(model, variables, p, reqs[i].max_new_tokens)
        assert results[f"r{i}"].generated == want, f"request r{i} diverged"
        assert results[f"r{i}"].finish_reason == "length"


@pytest.mark.slow  # beyond the greedy acceptance anchor; ci_check's
# serve-fast stage still runs it on every gate
def test_sampled_decode_reproducible_per_request(tiny_model):
    """Temperature sampling walks a PER-REQUEST rng stream: each request's
    tokens match single-request cached_generate with rng=PRNGKey(seed),
    independent of batch-mates."""
    model, variables = tiny_model
    eng = _engine(model, variables)
    prompts = [[5, 9, 2, 7], [1, 3, 3, 8, 2, 2], [7, 7, 7]]
    reqs = [
        GenRequest(request_id=f"s{i}", tokens=p, max_new_tokens=8,
                   temperature=0.7, top_k=5, seed=100 + i)
        for i, p in enumerate(prompts)
    ]
    results = eng.run(reqs)
    for i, p in enumerate(prompts):
        want = _baseline(
            model, variables, p, 8, temperature=0.7, top_k=5,
            rng=jax.random.PRNGKey(100 + i),
        )
        assert results[f"s{i}"].generated == want


def test_batching_invariance_with_prefix_cache_staggered(tiny_model):
    """ISSUE 6: the invariance anchor holds with the prefix cache ON —
    fill_from admissions (shared-prefix and exact-key hits) join MID-FLIGHT
    next to cold misses, and every request still matches single-request
    cached_generate bit-for-bit."""
    model, variables = tiny_model
    eng = _engine(model, variables, slots=2, prefix_cache_bytes=1 << 20)
    shared = [5, 9, 2, 7, 1, 3]
    prompts = [
        shared + [11, 4],        # miss: seeds the shared prefix
        shared + [7, 7, 7],      # shared-prefix hit, admitted mid-flight
        [2, 13],                 # miss next to a hit in the same batch
        shared + [11, 4],        # exact-key hit
        shared + [2, 2, 2, 2, 2, 2, 2, 2],  # longer prompt, shared-prefix hit
    ]
    max_new = [10, 8, 11, 4, 6]
    reqs = [
        GenRequest(request_id=f"r{i}", tokens=p, max_new_tokens=max_new[i])
        for i, p in enumerate(prompts)
    ]
    results = {}

    def collect(done_list):
        for r in done_list:
            results[r.request_id] = r

    eng.admit(reqs[0])
    collect(eng.step())
    collect(eng.step())
    eng.admit(reqs[1])           # hit splices in while r0 decodes
    collect(eng.step())
    pending = reqs[2:]
    while pending or eng.active_requests:
        while pending and eng.free_slots:
            done = eng.admit(pending.pop(0))
            if done is not None:
                results[done.request_id] = done
        collect(eng.step())

    assert eng.prefix_hits_total >= 3 and eng.prefix_misses_total == 2
    assert eng.prefill_tokens_saved_total >= 3 * len(shared)
    for i, p in enumerate(prompts):
        want = _baseline(model, variables, p, reqs[i].max_new_tokens)
        assert results[f"r{i}"].generated == want, f"request r{i} diverged"
    assert eng.compilations <= 2 * len(eng.config.prompt_buckets) + 1


def test_eos_latching_finishes_early(tiny_model):
    """A request whose greedy path emits eos finishes with reason "eos" and
    its tokens match the cached_generate prefix up to (and including) it."""
    model, variables = tiny_model
    prompt = [5, 9, 2, 7]
    free = _baseline(model, variables, prompt, 8)
    eos = free[3]  # an id the greedy path actually emits
    first = free.index(eos)
    eng = _engine(model, variables)
    results = eng.run([GenRequest(
        request_id="e", tokens=prompt, max_new_tokens=8, eos_id=eos,
    )])
    r = results["e"]
    assert r.finish_reason == "eos"
    assert r.generated == free[:first + 1]  # stops at the first occurrence


# ---------------------------------------------------------------------------
# Engine: compile count, slots, guards
# ---------------------------------------------------------------------------


def test_compile_count_bounded_by_buckets(tiny_model):
    """Many requests over both buckets compile at most buckets+1 programs —
    the recompile guard is ARMED (raise) and must not trip."""
    model, variables = tiny_model
    eng = _engine(model, variables, slots=3)
    prompts = [[i + 1] * ((i % 14) + 1) for i in range(12)]
    reqs = [
        GenRequest(request_id=f"c{i}", tokens=p, max_new_tokens=3)
        for i, p in enumerate(prompts)
    ]
    results = eng.run(reqs)
    assert len(results) == len(reqs)
    assert eng.guard.on_excess == "raise"  # armed: excess would have raised
    assert eng.compilations <= len(eng.config.prompt_buckets) + 1
    # slot lanes were reused: 12 requests through 3 lanes
    assert eng.free_slots == eng.config.slots


@pytest.mark.slow  # runs on every ci_check gate via the serve-fast stage
def test_eviction_frees_lane_and_preserves_others(tiny_model):
    """Evicting one request mid-flight frees its lane without disturbing the
    tokens any other in-flight request produces."""
    model, variables = tiny_model
    eng = _engine(model, variables, slots=2)
    keep = GenRequest(request_id="keep", tokens=[5, 9, 2, 7], max_new_tokens=8)
    gone = GenRequest(request_id="gone", tokens=[1, 3, 3, 8], max_new_tokens=8)
    results = {}
    eng.admit(keep)
    eng.admit(gone)
    for r in eng.step():
        results[r.request_id] = r
    evicted = eng.evict("gone")
    assert evicted is not None and evicted.finish_reason == "evicted"
    assert len(evicted.generated) >= 1
    assert eng.free_slots == 1
    # a new request takes over the freed lane while "keep" continues
    late = GenRequest(request_id="late", tokens=[7, 7, 7], max_new_tokens=4)
    done = eng.admit(late)
    assert done is None
    while eng.active_requests:
        for r in eng.step():
            results[r.request_id] = r
    assert results["keep"].generated == _baseline(model, variables, [5, 9, 2, 7], 8)
    assert results["late"].generated == _baseline(model, variables, [7, 7, 7], 4)


def test_evicted_lane_parks_benign(tiny_model):
    """ISSUE 6 satellite: a freed lane must not keep decoding at its stale
    cache position.  After evict, the lane's device cache index rows read 0
    (benign, in-bounds), post-evict steps generate tokens only for live
    lanes, and the survivor's output stays bit-identical."""
    import jax.tree_util as jtu

    model, variables = tiny_model

    def index_rows(eng, lane):
        rows = []
        for path, leaf in jtu.tree_flatten_with_path(eng._cache)[0]:
            name = getattr(path[-1], "key", getattr(path[-1], "name", ""))
            if name == "index":
                rows.extend(np.asarray(leaf)[..., lane].reshape(-1).tolist())
        assert rows, "no cache index leaves found"
        return rows

    eng = _engine(model, variables, slots=2)
    keep = GenRequest(request_id="keep", tokens=[5, 9, 2, 7], max_new_tokens=8)
    gone = GenRequest(request_id="gone", tokens=[1, 3, 3, 8], max_new_tokens=8)
    eng.admit(keep)
    eng.admit(gone)
    eng.step()
    gone_lane = next(
        i for i, s in enumerate(eng._slots)
        if s.req is not None and s.req.request_id == "gone"
    )
    assert all(r > 0 for r in index_rows(eng, gone_lane))  # mid-decode
    eng.evict("gone")
    assert all(r == 0 for r in index_rows(eng, gone_lane))  # parked at 0
    # post-evict steps advance ONLY the live lane's token count
    before = eng.tokens_generated_total
    results = {}
    steps = 0
    while eng.active_requests:
        for r in eng.step():
            results[r.request_id] = r
        steps += 1
    assert eng.tokens_generated_total - before == steps  # 1 live lane
    assert results["keep"].generated == _baseline(
        model, variables, [5, 9, 2, 7], 8
    )


def test_decode_index_saturates_at_cache_end(tiny_model):
    """The decode write clamps to the last cache slot and the index advance
    saturates at ``max_seq_len``: identity for live rows, but a parked lane
    riding the batched step indefinitely can never creep out of bounds —
    the invariant ``test_evicted_lane_parks_benign`` relies on holds for
    arbitrarily long idle stretches, not just the first few steps."""
    import jax.tree_util as jtu

    model, variables = tiny_model
    dcfg = model.cfg.replace(remat=False, attention_impl="xla", max_seq_len=8)
    dmodel = type(model)(cfg=dcfg)
    tokens = jnp.asarray([[5, 9, 2, 7], [1, 3, 3, 8]], jnp.int32)
    _, upd = dmodel.apply(
        variables, tokens, deterministic=True, decode=True,
        mutable=("cache",),
    )

    def park_row0(path, leaf):
        name = getattr(path[-1], "key", getattr(path[-1], "name", ""))
        return leaf.at[..., 0].set(8) if name == "index" else leaf

    cache = jtu.tree_map_with_path(park_row0, upd["cache"])  # row0 at the end
    logits, upd2 = dmodel.apply(
        {**variables, "cache": cache},
        jnp.asarray([[0], [4]], jnp.int32),
        positions=jnp.asarray([[0], [4]], jnp.int32),
        deterministic=True, decode=True, mutable=("cache",),
    )
    assert bool(jnp.isfinite(logits).all())
    for path, leaf in jtu.tree_flatten_with_path(upd2["cache"])[0]:
        name = getattr(path[-1], "key", getattr(path[-1], "name", ""))
        if name == "index":
            rows = np.asarray(leaf).reshape(-1, 2)
            assert (rows[:, 0] == 8).all()  # saturated, NOT 9
            assert (rows[:, 1] == 5).all()  # live row advances normally


def test_engine_input_validation(tiny_model):
    model, variables = tiny_model
    eng = _engine(model, variables, slots=1)
    with pytest.raises(PromptTooLong):
        eng.admit(GenRequest(request_id="x", tokens=[1] * 17, max_new_tokens=2))
    with pytest.raises(ValueError, match="empty prompt"):
        eng.admit(GenRequest(request_id="x", tokens=[], max_new_tokens=2))
    with pytest.raises(ValueError, match="engine cap"):
        eng.admit(GenRequest(request_id="x", tokens=[1], max_new_tokens=999))
    eng.admit(GenRequest(request_id="busy", tokens=[1, 2], max_new_tokens=8))
    with pytest.raises(EngineBusy):
        eng.admit(GenRequest(request_id="y", tokens=[1, 2], max_new_tokens=2))


def test_engine_refuses_moe():
    cfg = PRESETS["tiny-moe-test"].replace(lora=LoRAConfig(rank=4))
    model = LlamaForCausalLM(cfg)
    with pytest.raises(ValueError, match="MoE"):
        BatchEngine(model, {}, EngineConfig())


# ---------------------------------------------------------------------------
# LoRA merge
# ---------------------------------------------------------------------------


def test_merge_lora_matches_unmerged_logits():
    """Merged weights (W + (α/r)AB, rank-0 config) produce the same logits as
    the unmerged adapter forward, and the merged tree has no lora collection.
    f32 compute isolates the merge MATH from bf16 rounding (in bf16 the two
    paths legitimately round differently: x(W+AB) vs xW + (xA)B)."""
    from finetune_controller_tpu.serve.loader import merge_lora_variables

    cfg = PRESETS["tiny-test"].replace(
        lora=LoRAConfig(rank=4), dtype=jnp.float32
    )
    model = LlamaForCausalLM(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(1)}, jnp.zeros((1, 4), jnp.int32)
    )
    # non-zero B so the delta is real (init B is zeros = identity adapter)
    lora = jax.tree.map(
        lambda x: x + 0.01 * jnp.ones_like(x), variables["lora"]
    )
    variables = {**variables, "lora": lora}
    merged_cfg, merged_vars = merge_lora_variables(cfg, variables)
    assert "lora" not in merged_vars
    assert merged_cfg.lora.rank == 0
    tokens = jnp.asarray([[5, 9, 2, 7, 1]], jnp.int32)
    base = model.apply(variables, tokens)
    merged = LlamaForCausalLM(merged_cfg).apply(merged_vars, tokens)
    np.testing.assert_allclose(
        np.asarray(base), np.asarray(merged), atol=1e-4, rtol=1e-4
    )


# ---------------------------------------------------------------------------
# Batcher: backpressure + deadlines
# ---------------------------------------------------------------------------


def test_batcher_queue_overflow_rejects(tiny_model):
    model, variables = tiny_model

    async def main():
        eng = _engine(model, variables, slots=1)
        b = Batcher(eng, max_queue=0)  # zero queue: every submit sheds
        with pytest.raises(QueueFull):
            await b.submit(GenRequest(request_id="q", tokens=[1], max_new_tokens=2))
        assert b.rejected_total == 1
        await b.close()

    run_async(main())


def test_max_wait_ms_is_the_idle_park_interval(tiny_model):
    """ISSUE 6 satellite: the once-dead ``max_wait_ms`` knob now sets the
    drive loop's idle park interval (with a 1 ms floor), and a parked driver
    still wakes IMMEDIATELY on submit — the knob bounds the fallback
    re-check, never first-token latency."""
    import time as _time

    model, variables = tiny_model

    async def main():
        eng = _engine(model, variables, slots=1)
        b = Batcher(eng, max_wait_ms=30_000.0)
        assert b._park_timeout_s == 30.0
        assert Batcher(eng, max_wait_ms=0.0)._park_timeout_s == 0.001
        # default 1 s: submissions wake the loop via the event, so a large
        # idle interval costs nothing — a small one just burns idle CPU
        assert Batcher(eng)._park_timeout_s == 1.0
        b.start()
        await asyncio.sleep(0.05)  # the driver parks on the 30 s interval
        t0 = _time.monotonic()
        res = await b.submit(
            GenRequest(request_id="wake", tokens=[5, 9], max_new_tokens=2)
        )
        # served via the wake event, nowhere near the 30 s park interval
        assert _time.monotonic() - t0 < 10.0
        assert res.generated == _baseline(model, variables, [5, 9], 2)
        await b.close()

    run_async(main())


@pytest.mark.slow  # runs on every ci_check gate via the serve-fast stage
def test_batcher_serves_more_requests_than_slots(tiny_model):
    model, variables = tiny_model

    async def main():
        eng = _engine(model, variables, slots=2)
        b = Batcher(eng, max_queue=16)
        reqs = [
            GenRequest(request_id=f"b{i}", tokens=[i + 1, 2, 3],
                       max_new_tokens=4)
            for i in range(6)
        ]
        results = await asyncio.gather(*(b.submit(r) for r in reqs))
        for req, res in zip(reqs, results):
            assert res.request_id == req.request_id
            assert res.generated == _baseline(model, variables, req.tokens, 4)
        stats = b.stats()
        assert stats["requests_completed_total"] == 6
        assert stats["queue_depth"] == 0 and stats["slots_busy"] == 0
        await b.close()

    run_async(main())


@pytest.mark.slow  # runs on every ci_check gate via the serve-fast stage
def test_batcher_deadline_drops_queued_request(tiny_model):
    model, variables = tiny_model

    async def main():
        eng = _engine(model, variables, slots=1)
        b = Batcher(eng, max_queue=8)
        long_req = b.submit(
            GenRequest(request_id="long", tokens=[1, 2], max_new_tokens=24)
        )
        task = asyncio.ensure_future(long_req)
        await asyncio.sleep(0.01)  # the long request occupies the only lane
        with pytest.raises(DeadlineExceeded):
            await b.submit(
                GenRequest(request_id="doomed", tokens=[3, 4], max_new_tokens=2),
                timeout_s=0.001,
            )
        assert b.deadline_drops_total >= 1
        res = await task  # the occupying request still completes correctly
        assert res.generated == _baseline(model, variables, [1, 2], 24)
        await b.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Loader: promotion-state gate
# ---------------------------------------------------------------------------


def test_loader_refuses_unpromoted_and_in_flight(tmp_path):
    from finetune_controller_tpu.controller.schemas import (
        JobRecord,
        PromotionStatus,
    )
    from finetune_controller_tpu.controller.statestore import StateStore
    from finetune_controller_tpu.serve.loader import (
        ServeLoadError,
        resolve_promoted,
    )

    async def main():
        state = StateStore(tmp_path / "state")
        await state.connect()
        await state.create_job(JobRecord(
            job_id="j1", user_id="u", model_name="tiny-test-lora",
        ))
        with pytest.raises(ServeLoadError, match="not_promoted"):
            await resolve_promoted(state, "j1")
        await state.update_job_promotion(
            "j1", PromotionStatus.IN_PROGRESS, "local://deploy/j1"
        )
        with pytest.raises(ServeLoadError, match="in_progress"):
            await resolve_promoted(state, "j1")
        with pytest.raises(ServeLoadError, match="not found"):
            await resolve_promoted(state, "nope")
        await state.update_job_promotion(
            "j1", PromotionStatus.COMPLETED, "local://deploy/j1"
        )
        job = await resolve_promoted(state, "j1")
        assert job.promotion_uri == "local://deploy/j1"
        await state.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Service: the promote → serve loop over HTTP (local fake cluster)
# ---------------------------------------------------------------------------


def _serve_runtime(tmp_path):
    from test_api import _runtime

    rt = _runtime(tmp_path)
    # small serving shape so the tiny model loads/decodes in milliseconds
    rt.settings.serve_slots = 4
    rt.settings.serve_prompt_buckets = [8, 16]
    rt.settings.serve_max_new_tokens = 32
    return rt


async def _fabricate_promoted_job(rt, job_id="tiny-fab-0001"):
    """A COMPLETED-promotion job with a REAL checkpoint in the deploy bucket,
    built in-process (no trainer subprocess) — the fast path for tests that
    exercise the serve surface, not the training lifecycle."""
    import tempfile
    from pathlib import Path

    from finetune_controller_tpu.controller.schemas import (
        DatabaseStatus,
        JobRecord,
        PromotionStatus,
    )
    from finetune_controller_tpu.train.checkpoint import CheckpointManager
    from finetune_controller_tpu.train.cli import (
        build_model_config,
        build_train_config,
    )
    from finetune_controller_tpu.train.trainer import Trainer

    spec = {
        "job_id": job_id,
        "model": {"preset": "tiny-test", "lora": {"rank": 2}},
        "training": {
            "mode": "lora", "total_steps": 2, "batch_size": 2, "seq_len": 16,
            "log_every": 10**9, "checkpoint_every": 10**9,
        },
        "artifacts_dir": "unused",
    }
    trainer = Trainer(build_model_config(spec), build_train_config(spec))
    state = trainer.init_state()
    host = trainer.state_to_host(state)
    prefix = f"obj://{rt.settings.deploy_bucket}/models/{job_id}"
    with tempfile.TemporaryDirectory() as d:
        import json as _json

        CheckpointManager(f"{d}/checkpoints").save(1, host, blocking=True)
        (Path(d) / "resolved_config.json").write_text(_json.dumps(spec))
        for path in Path(d).rglob("*"):
            if path.is_file():
                rel = path.relative_to(d)
                await rt.store.put_file(f"{prefix}/{rel}", path)
    await rt.state.create_job(JobRecord(
        job_id=job_id, user_id="dev-user", model_name="tiny-test-lora",
        status=DatabaseStatus.SUCCEEDED,
        promotion_status=PromotionStatus.COMPLETED,
        promotion_uri=prefix,
    ))
    return job_id


async def _submitted_succeeded_job(client):
    from test_api import SUBMIT_BODY, _wait_final

    r = await client.post("/api/v1/jobs", json=SUBMIT_BODY)
    assert r.status == 200, await r.text()
    job_id = (await r.json())["job_id"]
    job = await _wait_final(client, job_id)
    assert job["status"] == "succeeded", job
    return job_id


@pytest.mark.slow  # runs on every ci_check gate via the serve-fast stage
def test_generate_endpoint_end_to_end(tmp_path):
    """fine-tune → promote → SERVE: the full loop over HTTP."""
    from test_api import _client

    async def main():
        rt = _serve_runtime(tmp_path)
        client = await _client(rt, with_monitor=True)
        job_id = await _submitted_succeeded_job(client)

        # serving before promotion refuses with the promotion state named
        r = await client.post(
            f"/api/v1/jobs/{job_id}/generate",
            json={"tokens": [5, 9, 2, 7], "max_new_tokens": 4},
        )
        assert r.status == 409
        assert "not_promoted" in (await r.json())["detail"]

        r = await client.post(f"/api/v1/jobs/{job_id}/promote")
        assert r.status == 202
        for _ in range(100):
            job = await (await client.get(f"/api/v1/jobs/{job_id}")).json()
            if job["promotion_status"] == "completed":
                break
            await asyncio.sleep(0.1)
        assert job["promotion_status"] == "completed"

        body = {"tokens": [5, 9, 2, 7], "max_new_tokens": 6}
        r = await client.post(f"/api/v1/jobs/{job_id}/generate", json=body)
        assert r.status == 200, await r.text()
        out = await r.json()
        assert len(out["tokens"]) == 6
        assert out["finish_reason"] == "length"
        assert out["model"]["checkpoint_step"] >= 1
        assert out["model"]["lora_merged"] is True

        # greedy decode is deterministic: a second identical request matches
        r2 = await client.post(f"/api/v1/jobs/{job_id}/generate", json=body)
        assert (await r2.json())["tokens"] == out["tokens"]

        # admin status sees the loaded session and its counters — including
        # the prefix cache economics: the repeated identical prompt above
        # was an exact-key hit that skipped most of its prefill
        r = await client.get("/api/v1/admin/serve")
        sessions = (await r.json())["sessions"]
        assert job_id in sessions
        assert sessions[job_id]["tokens_generated_total"] >= 12
        assert sessions[job_id]["prefix_misses_total"] >= 1
        assert sessions[job_id]["prefix_hits_total"] >= 1
        assert sessions[job_id]["prefill_tokens_saved_total"] >= 3
        assert sessions[job_id]["prefix_cache_bytes"] > 0

        # unload then explicit admin load round-trips
        r = await client.post(f"/api/v1/admin/serve/{job_id}/unload")
        assert r.status == 200
        r = await client.post(f"/api/v1/admin/serve/{job_id}/unload")
        assert r.status == 404
        r = await client.post(f"/api/v1/admin/serve/{job_id}/load")
        assert r.status == 200, await r.text()
        assert (await r.json())["model"]["job_id"] == job_id

        # validation: bad bodies are 400s, unknown jobs 404
        r = await client.post(f"/api/v1/jobs/{job_id}/generate", json={})
        assert r.status == 400
        r = await client.post(
            f"/api/v1/jobs/{job_id}/generate", json={"tokens": "nope"}
        )
        assert r.status == 400
        r = await client.post(
            "/api/v1/jobs/ghost/generate", json={"tokens": [1]}
        )
        assert r.status == 404
        await client.close()

    run_async(main())


@pytest.mark.slow  # runs on every ci_check gate via the serve-fast stage
def test_generate_autoload_off_requires_admin_load(tmp_path):
    """serve_autoload=False: generate refuses until an explicit admin load
    (fabricated promoted job — no trainer subprocess, keeps tier-1 fast)."""
    from test_api import _client

    async def main():
        rt = _serve_runtime(tmp_path)
        rt.settings.serve_autoload = False
        client = await _client(rt, with_monitor=False)
        job_id = await _fabricate_promoted_job(rt)
        r = await client.post(
            f"/api/v1/jobs/{job_id}/generate", json={"tokens": [1, 2]}
        )
        assert r.status == 409
        assert "load" in (await r.json())["detail"]
        r = await client.post(f"/api/v1/admin/serve/{job_id}/load")
        assert r.status == 200
        r = await client.post(
            f"/api/v1/jobs/{job_id}/generate", json={"tokens": [1, 2]}
        )
        assert r.status == 200
        await client.close()

    run_async(main())


@pytest.mark.slow  # runs on every ci_check gate via the serve-fast stage
def test_ctl_generate_hits_serving_endpoint(tmp_path, capsys):
    """`ftc-ctl generate JOB --tokens ...` decodes from a promoted job
    (ISSUE 4 satellite) — the terminal client against the real HTTP surface."""
    import json as _json

    from finetune_controller_tpu.controller import ctl

    async def main():
        from aiohttp.test_utils import TestServer

        from finetune_controller_tpu.controller.server import build_app

        rt = _serve_runtime(tmp_path)
        server = TestServer(build_app(rt, with_monitor=False))
        await server.start_server()
        api = f"http://{server.host}:{server.port}"
        try:
            job_id = await _fabricate_promoted_job(rt)
            rc = await ctl.amain(ctl.build_parser().parse_args([
                "--api", api, "generate", job_id,
                "--tokens", "5,9,2,7", "--max-new-tokens", "4",
            ]))
            assert rc == 0
            out = _json.loads(capsys.readouterr().out)
            assert out["job_id"] == job_id
            assert len(out["tokens"]) == 4
            assert out["finish_reason"] == "length"
            assert out["prompt_tokens"] == [5, 9, 2, 7]

            # `ftc-ctl serve`: the serving-session table with prefix stats
            rc = await ctl.amain(ctl.build_parser().parse_args([
                "--api", api, "serve",
            ]))
            assert rc == 0
            table = capsys.readouterr().out
            assert job_id in table
            assert "HITS" in table and "SAVED" in table

            # unknown job -> 404 through the client's error mapping
            with pytest.raises(ctl.ApiError, match="404"):
                await ctl.amain(ctl.build_parser().parse_args([
                    "--api", api, "generate", "ghost", "--tokens", "1,2",
                ]))
            # malformed --tokens fails client-side, no request sent
            with pytest.raises(SystemExit):
                await ctl.amain(ctl.build_parser().parse_args([
                    "--api", api, "generate", job_id, "--tokens", "a,b",
                ]))
        finally:
            await server.close()
            await rt.close()

    run_async(main())


@pytest.mark.slow  # spawns a real server process; runs in ci_check serve-fast
def test_server_module_entrypoint_serves_generate_route(tmp_path):
    """Regression: `python -m ...controller.server` loads the module as
    __main__; its AppKeys must be the CANONICAL module's or every serve
    handler (which imports the module by name) 500s on key lookup.  A 404
    for an unknown job — not a 500 — proves the keys resolve."""
    import json as _json
    import subprocess
    import sys
    import time
    import urllib.error
    import urllib.request

    port = 8797
    env = {
        "PYTHONPATH": ".",
        "PATH": "/usr/local/bin:/usr/bin:/bin",
        "JAX_PLATFORMS": "cpu",
        "FTC_STATE_DIR": str(tmp_path / "state"),
        "FTC_OBJECT_STORE_ROOT": str(tmp_path / "objects"),
        "FTC_ENVIRONMENT": "local",
        "FTC_BACKEND": "local",
        "FTC_MONITOR_IN_PROCESS": "false",
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "finetune_controller_tpu.controller.server",
         "--port", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        base = f"http://127.0.0.1:{port}/api/v1"
        for _ in range(120):
            try:
                with urllib.request.urlopen(f"{base}/health", timeout=1) as r:
                    if r.status == 200:
                        break
            except (urllib.error.URLError, ConnectionError, OSError):
                if proc.poll() is not None:
                    out = proc.stdout.read().decode(errors="replace")
                    raise AssertionError(f"server died:\n{out[-2000:]}")
                time.sleep(0.5)
        else:
            raise AssertionError("server never became healthy")
        req = urllib.request.Request(
            f"{base}/jobs/ghost/generate",
            data=_json.dumps({"tokens": [1, 2]}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("expected an HTTP error for unknown job")
        except urllib.error.HTTPError as e:
            assert e.code == 404, f"got {e.code} (500 = AppKey mismatch bug)"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_batcher_survives_decode_step_failure(tiny_model):
    """A decode-step fault (OOM, XLA error, tripped recompile guard) must
    fail the in-flight requests LOUDLY and leave the batcher serving — not
    kill the drive loop and hang every future client."""
    model, variables = tiny_model

    async def main():
        eng = _engine(model, variables, slots=2)
        b = Batcher(eng, max_queue=8)
        boom = RuntimeError("injected decode fault")
        real_step = eng.step
        calls = {"n": 0}

        def flaky_step():
            calls["n"] += 1
            if calls["n"] == 1:
                raise boom
            return real_step()

        eng.step = flaky_step
        with pytest.raises(RuntimeError, match="injected decode fault"):
            await b.submit(GenRequest(
                request_id="victim", tokens=[5, 9, 2, 7], max_new_tokens=4,
            ))
        # lanes were freed and the loop kept driving: the next request works
        res = await b.submit(GenRequest(
            request_id="next", tokens=[5, 9, 2, 7], max_new_tokens=4,
        ))
        assert res.generated == _baseline(model, variables, [5, 9, 2, 7], 4)
        assert eng.free_slots == eng.config.slots
        await b.close()

    run_async(main())
