"""Tests for control-plane core: settings, schemas, state store, object store.

Covers the capability surface of the reference's ``app/core/config.py``,
``app/schemas/``, ``app/database/db.py`` and ``app/utils/S3Handler.py``
(SURVEY.md §2 components 7,8,9,13) with the hermetic test seams the reference
lacked (SURVEY.md §4).
"""

import asyncio

import pytest

from finetune_controller_tpu.controller import config as cfg
from finetune_controller_tpu.controller.objectstore import (
    LocalObjectStore,
    Presigner,
    artifacts_prefix,
    build_uri,
    dataset_prefix,
    parse_uri,
)
from finetune_controller_tpu.controller.schemas import (
    BackendJobState,
    DatabaseStatus,
    DatasetRecord,
    JobRecord,
    MetricsDocument,
    PromotionStatus,
    map_backend_state,
)
from finetune_controller_tpu.controller.statestore import StateStore, generate_short_uuid


from conftest import run_async as run


# ---------------------------------------------------------------------------
# Settings
# ---------------------------------------------------------------------------


def test_settings_env_parsing(monkeypatch):
    monkeypatch.setenv("FTC_NAMESPACE", "prod-ns")
    monkeypatch.setenv("FTC_AUTH_ENABLED", "true")
    monkeypatch.setenv("FTC_JOB_MONITOR_INTERVAL_S", "0.5")
    monkeypatch.setenv("FTC_CORS_ORIGINS", "https://a.example,https://b.example")
    cfg.set_settings(None)
    s = cfg.get_settings()
    assert s.namespace == "prod-ns"
    assert s.auth_enabled is True
    assert s.job_monitor_interval_s == 0.5
    assert s.cors_origins == ["https://a.example", "https://b.example"]
    cfg.set_settings(None)


def test_settings_injectable():
    custom = cfg.Settings(namespace="injected")
    cfg.set_settings(custom)
    assert cfg.get_settings() is custom
    cfg.set_settings(None)


# ---------------------------------------------------------------------------
# State machine
# ---------------------------------------------------------------------------


def test_backend_state_mapping():
    assert map_backend_state(BackendJobState.RUNNING) == DatabaseStatus.RUNNING
    assert map_backend_state("Suspended") == DatabaseStatus.QUEUED
    assert map_backend_state("Succeeded") == DatabaseStatus.SUCCEEDED
    assert map_backend_state("bogus") == DatabaseStatus.UNKNOWN
    assert DatabaseStatus.SUCCEEDED.is_final
    assert not DatabaseStatus.RUNNING.is_final
    assert BackendJobState.RESTARTING in BackendJobState.running_states()


def test_short_uuid():
    uid = generate_short_uuid()
    assert len(uid) == 8 and uid == uid.lower()


# ---------------------------------------------------------------------------
# State store
# ---------------------------------------------------------------------------


@pytest.fixture()
def store(tmp_path):
    return StateStore(tmp_path / "state")


def _job(job_id="llama-abc12345", user="alice", **kw):
    return JobRecord(job_id=job_id, user_id=user, model_name="tinyllama-lora", **kw)


def test_job_crud_and_persistence(store, tmp_path):
    async def go():
        await store.connect()
        await store.create_job(_job())
        job = await store.get_job("llama-abc12345")
        assert job is not None and job.status == DatabaseStatus.QUEUED
        ok = await store.update_job_status(
            "llama-abc12345", DatabaseStatus.RUNNING,
            metadata={"node": "w0"}, start_time=123.0,
        )
        assert ok
        # metadata merges, not replaces (reference db.py:206-215)
        await store.update_job_status(
            "llama-abc12345", DatabaseStatus.RUNNING, metadata={"step": 5}
        )
        job = await store.get_job("llama-abc12345")
        assert job.metadata == {"node": "w0", "step": 5}
        assert job.start_time == 123.0

        # survives a process restart (new store over same dir)
        store2 = StateStore(tmp_path / "state")
        await store2.connect()
        job2 = await store2.get_job("llama-abc12345")
        assert job2.status == DatabaseStatus.RUNNING

    run(go())


def test_pagination_and_computed_fields(store):
    async def go():
        await store.connect()
        for i in range(25):
            await store.create_job(
                _job(job_id=f"job-{i:04d}", user="bob" if i % 2 else "alice")
            )
        await store.update_job_status(
            "job-0000", DatabaseStatus.SUCCEEDED, start_time=10.0, end_time=70.0
        )
        await store.update_job_promotion("job-0000", PromotionStatus.COMPLETED)

        page = await store.get_user_jobs("alice", page=1, page_size=5,
                                         sort_by="job_id", descending=False)
        assert page.total == 13 and len(page.items) == 5
        assert page.items[0]["job_id"] == "job-0000"
        assert page.items[0]["duration"] == 60.0
        assert page.items[0]["status_merged"] == "succeeded/completed"
        assert [it["index_"] for it in page.items] == [0, 1, 2, 3, 4]

        page2 = await store.get_user_jobs("alice", page=2, page_size=5,
                                          sort_by="job_id", descending=False)
        assert page2.items[0]["index_"] == 5

        filtered = await store.get_user_jobs("alice", status=DatabaseStatus.SUCCEEDED)
        assert filtered.total == 1

        searched = await store.get_user_jobs("alice", search="JOB-0002")
        assert searched.total == 1

        admin = await store.get_user_jobs(None)
        assert admin.total == 25

    run(go())


def test_promotion_cas_state_machine(store):
    """ISSUE 4 satellite: every promotion transition is a compare-and-swap.
    promote-while-IN_PROGRESS, promote-while-DELETING, and a stale task's
    completion write all LOSE in the store, not in handler guards."""
    PROMOTE_FROM = [
        PromotionStatus.NOT_PROMOTED,
        PromotionStatus.FAILED,
        PromotionStatus.COMPLETED,
    ]
    UNPROMOTE_FROM = [PromotionStatus.COMPLETED, PromotionStatus.FAILED]

    async def go():
        await store.connect()
        await store.create_job(_job())
        jid = "llama-abc12345"

        # claim promote; a second promote and an unpromote both lose
        assert await store.begin_promotion(
            jid, PromotionStatus.IN_PROGRESS, "d://1", expect_from=PROMOTE_FROM
        )
        assert not await store.begin_promotion(
            jid, PromotionStatus.IN_PROGRESS, "d://2", expect_from=PROMOTE_FROM
        )
        assert not await store.begin_promotion(
            jid, PromotionStatus.DELETING, "d://1", expect_from=UNPROMOTE_FROM
        )

        # the winning task settles via CAS from the state it claimed
        assert await store.transition_job_promotion(
            jid, [PromotionStatus.IN_PROGRESS], PromotionStatus.COMPLETED, "d://1"
        )
        # ... and its now-stale duplicate settle is a no-op
        assert not await store.transition_job_promotion(
            jid, [PromotionStatus.IN_PROGRESS], PromotionStatus.FAILED
        )
        job = await store.get_job(jid)
        assert job.promotion_status is PromotionStatus.COMPLETED

        # unpromote claims DELETING; promote-while-DELETING is refused
        assert await store.begin_promotion(
            jid, PromotionStatus.DELETING, "d://1", expect_from=UNPROMOTE_FROM
        )
        assert not await store.begin_promotion(
            jid, PromotionStatus.IN_PROGRESS, "d://3", expect_from=PROMOTE_FROM
        )
        # a promote task's stale COMPLETED write cannot stomp the delete
        assert not await store.transition_job_promotion(
            jid, [PromotionStatus.IN_PROGRESS], PromotionStatus.COMPLETED
        )
        assert await store.transition_job_promotion(
            jid, [PromotionStatus.DELETING], PromotionStatus.NOT_PROMOTED
        )
        job = await store.get_job(jid)
        assert job.promotion_status is PromotionStatus.NOT_PROMOTED

    run(go())


def test_promotion_task_settle_respects_concurrent_transition(store, tmp_path):
    """A PromotionTask that lost its claim (crash-recovery marked the job
    FAILED; the user re-promoted) must not overwrite the newer state when its
    stale copy finally completes."""
    from finetune_controller_tpu.controller.promotion import PromotionTask

    async def go():
        await store.connect()
        await store.create_job(_job())
        jid = "llama-abc12345"
        obj_store = LocalObjectStore(tmp_path / "objects")
        await obj_store.put_bytes("obj://artifacts/a/x.bin", b"payload")
        promo = PromotionTask(store, obj_store)

        assert await store.begin_promotion(
            jid, PromotionStatus.IN_PROGRESS, "obj://deploy/a"
        )
        # another process's recovery sweep declares the attempt dead ...
        assert await store.transition_job_promotion(
            jid, [PromotionStatus.IN_PROGRESS], PromotionStatus.FAILED
        )
        # ... and a fresh promote claims the next attempt
        assert await store.begin_promotion(
            jid, PromotionStatus.IN_PROGRESS, "obj://deploy/b"
        )
        await store.transition_job_promotion(
            jid, [PromotionStatus.IN_PROGRESS], PromotionStatus.COMPLETED,
            "obj://deploy/b",
        )
        # the STALE task finally finishes its copy: its settle must lose
        await promo.promote_job_task(
            jid, "obj://artifacts/a", "obj://deploy/a"
        )
        job = await store.get_job(jid)
        assert job.promotion_status is PromotionStatus.COMPLETED
        assert job.promotion_uri == "obj://deploy/b"
        await obj_store.close()

    run(go())


def test_delete_archives(store):
    async def go():
        await store.connect()
        await store.create_job(_job())
        await store.upsert_metrics(
            MetricsDocument(job_id="llama-abc12345", records=[{"loss": 1.0}])
        )
        assert await store.delete_job("llama-abc12345")
        assert await store.get_job("llama-abc12345") is None
        assert await store.get_metrics("llama-abc12345") is None
        archived = await store.archived_jobs.get("llama-abc12345")
        assert archived is not None and "archived_at" in archived

    run(go())


def test_datasets(store):
    async def go():
        await store.connect()
        ds = DatasetRecord(dataset_id="ds1", user_id="alice", name="corpus",
                           uri="obj://datasets/finetune_jobs/alice/j1/dataset/corpus.jsonl")
        await store.insert_dataset(ds)
        assert await store.add_dataset_job_ref("ds1", "job-1")
        assert await store.add_dataset_job_ref("ds1", "job-1")  # idempotent
        got = await store.get_dataset("ds1")
        assert got.job_refs == ["job-1"]
        assert len(await store.get_user_datasets("alice")) == 1
        assert await store.delete_dataset("ds1")

    run(go())


def test_batch_get_no_n_plus_1(store):
    async def go():
        await store.connect()
        for i in range(5):
            await store.create_job(_job(job_id=f"j{i}"))
        got = await store.get_jobs_by_ids(["j0", "j3", "missing"])
        assert set(got) == {"j0", "j3"}

    run(go())


# ---------------------------------------------------------------------------
# Object store
# ---------------------------------------------------------------------------


def test_uri_conventions():
    uri = dataset_prefix("datasets", "alice", "job-1")
    assert uri == "obj://datasets/finetune_jobs/alice/job-1/dataset"
    assert parse_uri(uri) == ("datasets", "finetune_jobs/alice/job-1/dataset")
    assert artifacts_prefix("artifacts", "a", "j").endswith("/artifacts")
    with pytest.raises(ValueError):
        parse_uri("s3://nope/key")


def test_object_store_roundtrip(tmp_path):
    store = LocalObjectStore(tmp_path / "obj")

    async def go():
        uri = build_uri("artifacts", "finetune_jobs/a/j/artifacts/ckpt.bin")
        await store.put_bytes(uri, b"\x00\x01")
        assert await store.exists(uri)
        assert await store.get_bytes(uri) == b"\x00\x01"

        async def chunks():
            yield b"abc"
            yield b"def"

        surl = build_uri("datasets", "finetune_jobs/a/j/dataset/d.jsonl")
        n = await store.put_stream(surl, chunks())
        assert n == 6 and await store.get_bytes(surl) == b"abcdef"

        objs = await store.list_prefix(build_uri("artifacts", "finetune_jobs/a/j"))
        assert len(objs) == 1 and objs[0]["size"] == 2

    run(go())


def test_metrics_csv_and_zip_and_copy(tmp_path):
    store = LocalObjectStore(tmp_path / "obj")

    async def go():
        prefix = artifacts_prefix("artifacts", "a", "j")
        await store.put_bytes(f"{prefix}/metrics_old.csv", b"step,loss\n1,2.0\n")
        await asyncio.sleep(0.02)
        await store.put_bytes(f"{prefix}/metrics.csv", b"step,loss\n1,2.0\n2,1.5\n")
        await store.put_bytes(f"{prefix}/adapter.ckpt", b"ww")

        res = await store.get_metrics_records(prefix)
        assert res is not None
        records, src = res
        assert src.endswith("metrics.csv") and len(records) == 2
        assert records[1] == {"step": 2, "loss": 1.5}

        blob = await store.zip_prefix(prefix)
        import io, zipfile
        names = zipfile.ZipFile(io.BytesIO(blob)).namelist()
        assert "adapter.ckpt" in names and "metrics.csv" in names

        # promotion copy (reference S3Handler.py:375-439)
        dst = "obj://deploy/models/tinyllama/j"
        n = await store.copy_prefix(prefix, dst)
        assert n == 3
        assert await store.get_bytes(f"{dst}/adapter.ckpt") == b"ww"

        assert await store.delete_prefix(prefix) == 3
        assert await store.list_prefix(prefix) == []

    run(go())


def test_zip_prefix_to_path_streams(tmp_path):
    """Disk-targeted zip streams objects chunk-by-chunk (bounded memory) and
    produces a byte-correct archive."""
    store = LocalObjectStore(tmp_path / "obj")

    async def go():
        prefix = artifacts_prefix("artifacts", "a", "big")
        big = bytes(range(256)) * 8192  # 2 MiB, crosses the 1 MiB chunk size
        await store.put_bytes(f"{prefix}/shard.bin", big)
        await store.put_bytes(f"{prefix}/metrics.csv", b"step,loss\n1,2.0\n")
        dest = tmp_path / "out.zip"
        n = await store.zip_prefix_to_path(prefix, dest)
        assert n == 2
        import zipfile
        with zipfile.ZipFile(dest) as zf:
            assert sorted(zf.namelist()) == ["metrics.csv", "shard.bin"]
            assert zf.read("shard.bin") == big
            info = zf.getinfo("shard.bin")
            assert info.compress_type == zipfile.ZIP_DEFLATED

    run(go())


def test_object_store_rejects_path_escape(tmp_path):
    store = LocalObjectStore(tmp_path / "obj")

    async def go():
        # sibling directory sharing the bucket-name prefix must not be reachable
        await store.put_bytes("obj://data-private/secret.txt", b"s3cr3t")
        with pytest.raises(ValueError):
            store.path_for("obj://data/../data-private/secret.txt")
        with pytest.raises(ValueError):
            store.path_for("obj://data/../../etc/passwd")

    run(go())


def test_statestore_log_compaction(tmp_path):
    store = StateStore(tmp_path / "state", backend="jsonl")

    async def go():
        await store.connect()
        await store.create_job(_job(job_id="j0"))
        # enough updates to cross the compaction threshold
        for i in range(1100):
            await store.update_job_fields("j0", queue_position=i)
        job = await store.get_job("j0")
        assert job.queue_position == 1099
        # log compacted: far fewer lines than writes
        lines = (tmp_path / "state" / "jobs.jsonl").read_text().splitlines()
        assert len(lines) < 600
        # reload still correct
        store2 = StateStore(tmp_path / "state", backend="jsonl")
        await store2.connect()
        assert (await store2.get_job("j0")).queue_position == 1099

    run(go())


def test_presigner():
    p = Presigner("secret", expiry_s=100)
    tok = p.sign("obj://b/k", now=1000.0)
    assert p.verify("obj://b/k", tok, now=1050.0)
    assert not p.verify("obj://b/k", tok, now=1200.0)  # expired
    assert not p.verify("obj://b/other", tok, now=1050.0)  # wrong uri
    assert not p.verify("obj://b/k", "garbage", now=1050.0)


def test_secondary_indexes_consistent(tmp_path):
    """Equality lookups use the in-memory secondary indexes (no collection
    scan) and stay consistent across insert/update/delete AND log replay."""
    from finetune_controller_tpu.controller.schemas import JobRecord

    store = StateStore(tmp_path / "state")

    async def go():
        await store.connect()
        for i in range(6):
            await store.create_job(JobRecord(
                job_id=f"j{i}", user_id="alice" if i % 2 else "bob",
                model_name="m", device="d",
            ))
        alice = await store.jobs.find(eq={"user_id": "alice"})
        assert {d["job_id"] for d in alice} == {"j1", "j3", "j5"}

        # status transitions move docs between index buckets
        await store.update_job_status("j1", DatabaseStatus.RUNNING)
        running = await store.jobs.find(eq={"status": "running"})
        assert [d["job_id"] for d in running] == ["j1"]
        combo = await store.jobs.find(eq={"user_id": "alice", "status": "running"})
        assert [d["job_id"] for d in combo] == ["j1"]

        # delete removes from buckets
        await store.delete_job("j3")
        alice = await store.jobs.find(eq={"user_id": "alice"})
        assert {d["job_id"] for d in alice} == {"j1", "j5"}

        # unindexed field refuses (a silent scan would hide the regression)
        import pytest as _pytest
        with _pytest.raises(KeyError):
            await store.jobs.find(eq={"model_name": "m"})
        await store.close()

    run(go())

    # fresh process: indexes rebuilt from the JSONL log replay
    store2 = StateStore(tmp_path / "state")

    async def go2():
        await store2.connect()
        alice = await store2.jobs.find(eq={"user_id": "alice"})
        assert {d["job_id"] for d in alice} == {"j1", "j5"}
        running = await store2.jobs.find(eq={"status": "running"})
        assert [d["job_id"] for d in running] == ["j1"]
        await store2.close()

    run(go2())
