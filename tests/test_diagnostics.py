"""Collective-bandwidth diagnostics (the nccl-tests workflow, TPU-native)."""

from __future__ import annotations

import jax

from finetune_controller_tpu.parallel.diagnostics import collective_diagnostics


def test_sweep_on_virtual_mesh(devices8):
    rep = collective_diagnostics(sizes_mb=(0.25,), devices=devices8)
    assert rep["n_devices"] == 8
    assert set(rep["collectives"]) == {"psum", "all_gather", "ppermute"}
    for op, rows in rep["collectives"].items():
        row = rows["0.25"]
        assert row["time_ms"] > 0
        assert row["algo_bw_gbps"] > 0
        assert row["bus_bw_gbps"] > 0
    # nccl-tests convention: all-reduce bus bandwidth accounts 2(n-1)/n
    # (loose tolerance: the reported values are rounded to 3 decimals)
    ar = rep["collectives"]["psum"]["0.25"]
    assert abs(ar["bus_bw_gbps"] / ar["algo_bw_gbps"] - 2 * 7 / 8) < 0.06


def test_single_device_degrades_gracefully():
    rep = collective_diagnostics(sizes_mb=(0.25,), devices=jax.devices()[:1])
    assert rep["n_devices"] == 1
    assert "note" in rep and rep["collectives"] == {}
