"""Tests that only run on real TPU hardware (skipped on the CPU CI mesh).

CPU CI exercises the Pallas kernels in interpreter mode only; a Mosaic
miscompile — particularly in the segment-mask path — would ship unnoticed
without a compiled-on-TPU parity check.  ``scripts/tpu_session.py`` runs the
same check as part of the measurement session; this is the pytest-gated
form for TPU-equipped CI.

Run with:  JAX_PLATFORMS=tpu python -m pytest tests/test_tpu_only.py -q
(the conftest pins the suite to CPU, so the TPU run must override it via
FTC_TEST_TPU=1).
"""

from __future__ import annotations

import os

import pytest

requires_tpu = pytest.mark.skipif(
    not os.environ.get("FTC_TEST_TPU"),
    reason="TPU-only: set FTC_TEST_TPU=1 on a TPU host",
)


@requires_tpu
def test_compiled_flash_attention_with_segments_matches_xla():
    import subprocess
    import sys

    from scripts.tpu_session import PARITY_SNIPPET  # single source of truth

    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["JAX_PLATFORMS"] = "tpu"
    out = subprocess.run(
        [sys.executable, "-c", PARITY_SNIPPET],
        capture_output=True, text=True, timeout=900, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    import json

    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["ok"], rec
