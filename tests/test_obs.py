"""Tests for the observability layer (``finetune_controller_tpu/obs/`` —
docs/observability.md).

Layers covered:

* ``prom``   — histogram bucket/render semantics, the ObsHub registry,
  ``ftc_build_info`` / ``ftc_uptime_seconds``;
* ``phase``  — the trainer's step-phase clock (residual compute, reset);
* ``trace``  — span recorder crash-safety, trace assembly from the event
  timeline, the gap-free/nesting validator;
* ``events`` — the trainer-side event log and the torn-line-tolerant parser;
* statestore — ``append_job_event`` idempotency on BOTH engines;
* trainer    — fit-loop integration (events/spans/phase columns on, all
  quiet with ``FTC_TRACE=0``) and the on-demand profiler window;
* monitor    — trainer-event ingest exactly-once, terminal trace export;
* supervisor — the HARD-PATH timeline e2e: a job that is preempted,
  resized, retried, and promoted has every transition event exactly once,
  in order, with monotonic timestamps, and its assembled span tree is
  gap-free with valid parent/child nesting (the ISSUE 9 acceptance gate);
* HTTP       — ``GET /jobs/{id}/timeline``, ``GET /jobs/{id}/trace``,
  ``POST /jobs/{id}/profile`` guards, ``GET /admin/resilience`` progress;
* backends   — ``deliver_file`` atomicity + sandbox containment;
* satellites — stream-logger trace/attempt prefix, heartbeat
  ``last_step``/``last_step_ms``.
"""

import asyncio
import json
import math
import os
import time

import pytest

from conftest import one_chip_catalog as _catalog
from conftest import run_async as run
from conftest import tiny_job_spec as _spec
from test_lifecycle import ScriptedBackend

from finetune_controller_tpu.controller import registry
from finetune_controller_tpu.controller.monitor import JobMonitor
from finetune_controller_tpu.controller.objectstore import LocalObjectStore
from finetune_controller_tpu.controller.schemas import (
    BackendJobReport,
    BackendJobState,
    DatabaseStatus,
    JobInput,
)
from finetune_controller_tpu.controller.statestore import StateStore
from finetune_controller_tpu.controller.task_builder import (
    DatasetInput,
    task_builder,
)
from finetune_controller_tpu.obs import (
    EventLogWriter,
    Histogram,
    ObsHub,
    PhaseClock,
    SpanRecorder,
    build_trace,
    make_event,
    new_trace_id,
    parse_event_lines,
    parse_span_lines,
    validate_trace,
)
from finetune_controller_tpu.resilience.policy import RetryPolicy
from finetune_controller_tpu.resilience.supervisor import RetrySupervisor


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# prom: histograms + the hub
# ---------------------------------------------------------------------------


def test_histogram_buckets_and_cumulative_render():
    h = Histogram("ftc_test_seconds", "help", (0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 100.0):
        h.observe(v)
    lines = h.render()
    assert "# TYPE ftc_test_seconds histogram" in lines
    # cumulative le series: 1, 3, 4, then +Inf catches the overflow
    assert 'ftc_test_seconds_bucket{le="0.1"} 1' in lines
    assert 'ftc_test_seconds_bucket{le="1"} 3' in lines
    assert 'ftc_test_seconds_bucket{le="10"} 4' in lines
    assert 'ftc_test_seconds_bucket{le="+Inf"} 5' in lines
    assert "ftc_test_seconds_count 5" in lines
    assert any(line.startswith("ftc_test_seconds_sum ") for line in lines)
    assert h.count() == 5


def test_histogram_labels_fixed_and_validated():
    h = Histogram("ftc_phase_ms", "help", (1, 10), label_names=("phase",))
    h.observe(0.5, phase="input")
    h.observe(5, phase="input")
    h.observe(5, phase="compute")
    with pytest.raises(ValueError):
        h.observe(1, wrong="x")
    with pytest.raises(ValueError):
        h.observe(1)  # missing the declared label
    lines = h.render()
    assert 'ftc_phase_ms_bucket{phase="compute",le="10"} 1' in lines
    assert 'ftc_phase_ms_bucket{phase="input",le="+Inf"} 2' in lines
    assert h.count(phase="input") == 2


def test_histogram_empty_renders_family_header_only():
    h = Histogram("ftc_idle", "help", (1,))
    lines = h.render()
    assert lines == ["# HELP ftc_idle help", "# TYPE ftc_idle histogram"]
    with pytest.raises(ValueError):
        Histogram("ftc_none", "help", ())  # at least one finite bucket


def test_obshub_observes_phase_columns_from_csv_row():
    hub = ObsHub()
    row = {
        "step": "10", "loss": "0.5",
        "phase_input_ms": "2.5", "phase_compute_ms": "7.5",
        "phase_checkpoint_ms": "", "phase_sync_ms": "garbage",
        "phase_eval_ms": None,
    }
    assert hub.observe_step_phases(row) == 2  # only the parseable columns
    assert hub.step_phase_ms.count(phase="input") == 1
    assert hub.step_phase_ms.count(phase="compute") == 1
    assert hub.step_phase_ms.count(phase="checkpoint") == 0
    # a row with no phase columns (pre-obs metrics CSV) is a no-op
    assert hub.observe_step_phases({"step": "1", "loss": "1.0"}) == 0


def test_obshub_process_info_lines():
    clock = FakeClock(100.0)
    hub = ObsHub(_clock=clock)
    clock.advance(42.0)
    lines = hub.render_process_info(
        process="monitor", version="0.1.0", backend='lo"cal'
    )
    joined = "\n".join(lines)
    assert 'ftc_build_info{process="monitor",version="0.1.0",' in joined
    assert 'backend="lo\\"cal"' in joined  # label escaping
    assert 'ftc_uptime_seconds{process="monitor"} 42.000' in joined


# ---------------------------------------------------------------------------
# phase: the step-phase clock
# ---------------------------------------------------------------------------


def test_phase_clock_residual_compute_and_reset():
    t = {"now": 0.0}
    clock = PhaseClock(_clock=lambda: t["now"])
    with clock.phase("input"):
        t["now"] += 0.2
    with clock.phase("checkpoint"):
        t["now"] += 0.3
    clock.add("sync", 0.1)
    # 4 steps over a 1.0s window: 0.6s measured, 0.4s residual compute
    row = clock.window_row(steps=4, wall_s=1.0)
    assert row["phase_input_ms"] == pytest.approx(50.0)
    assert row["phase_checkpoint_ms"] == pytest.approx(75.0)
    assert row["phase_sync_ms"] == pytest.approx(25.0)
    assert row["phase_eval_ms"] == 0.0
    assert row["phase_compute_ms"] == pytest.approx(100.0)
    assert set(row) == set(PhaseClock.columns())
    # the window reset: a second row starts from zero
    row2 = clock.window_row(steps=1, wall_s=0.0)
    assert all(v == 0.0 for v in row2.values())


def test_phase_clock_compute_clamped_at_zero():
    clock = PhaseClock(_clock=time.perf_counter)
    clock.add("input", 2.0)
    row = clock.window_row(steps=1, wall_s=1.0)  # measured > wall
    assert row["phase_compute_ms"] == 0.0


# ---------------------------------------------------------------------------
# trace: span recorder + parser
# ---------------------------------------------------------------------------


def test_span_recorder_writes_crash_safe_jsonl(tmp_path):
    rec = SpanRecorder(str(tmp_path), "t" * 32, attempt=2)
    with rec.span("checkpoint", step=40):
        pass
    span = rec.start("io")
    rec.finish(span, status="error", bytes=123)
    raw = (tmp_path / "trace" / "trainer.jsonl").read_text()
    # one flushed line per FINISHED span + a torn tail must not poison parse
    spans = parse_span_lines(raw + '{"span_id": "torn')
    assert [s["name"] for s in spans] == ["checkpoint", "io"]
    assert spans[0]["trace_id"] == "t" * 32
    assert spans[0]["attributes"]["step"] == 40
    assert spans[0]["attributes"]["attempt"] == 2
    assert spans[1]["status"] == "error"
    assert spans[1]["attributes"]["bytes"] == 123
    assert all(s["end_ns"] >= s["start_ns"] for s in spans)


def test_span_recorder_context_marks_error_on_exception(tmp_path):
    rec = SpanRecorder(str(tmp_path), new_trace_id())
    with pytest.raises(RuntimeError):
        with rec.span("fit"):
            raise RuntimeError("boom")
    spans = parse_span_lines((tmp_path / "trace" / "trainer.jsonl").read_text())
    assert spans[0]["status"] == "error"


def test_span_recorder_disabled_writes_nothing(tmp_path):
    for rec in (
        SpanRecorder(str(tmp_path), new_trace_id(), enabled=False),
        SpanRecorder(str(tmp_path), ""),  # no trace id -> disabled
    ):
        with rec.span("noop"):
            pass
    assert not (tmp_path / "trace").exists()


def test_span_recorder_swallows_write_failures(tmp_path):
    target = tmp_path / "trace"
    target.write_text("a file where the spans dir should go")
    rec = SpanRecorder(str(tmp_path), new_trace_id())
    with rec.span("doomed"):
        pass  # must not raise
    assert rec.write_failures == 1


# ---------------------------------------------------------------------------
# events: the trainer-side log
# ---------------------------------------------------------------------------


def test_event_log_writer_roundtrip_and_attribution(tmp_path):
    w = EventLogWriter(str(tmp_path), trace_id="abc123", attempt=3)
    assert w.emit("train-started", step=0)
    assert w.emit("checkpoint-committed", step=20, blocking=True)
    raw = (tmp_path / "events.jsonl").read_text()
    events = parse_event_lines(raw + "\n{torn")
    assert [e["event"] for e in events] == [
        "train-started", "checkpoint-committed",
    ]
    assert all(e["trace_id"] == "abc123" for e in events)
    assert all(e["attrs"]["attempt"] == 3 for e in events)
    assert events[1]["attrs"]["step"] == 20


def test_event_log_writer_disabled_and_failure_tolerant(tmp_path):
    w = EventLogWriter(str(tmp_path), enabled=False)
    assert not w.emit("train-started")
    assert not (tmp_path / "events.jsonl").exists()
    w2 = EventLogWriter(str(tmp_path / "missing" / "dir"))
    assert not w2.emit("train-started")  # unwritable: swallowed, reported
    assert w2.write_failures == 1


def test_make_event_filters_none_attrs():
    e = make_event("running", key="running:a1", attempt=1, slices=None)
    assert e["event"] == "running"
    assert e["key"] == "running:a1"
    assert e["attrs"] == {"attempt": 1}
    assert isinstance(e["ts"], float)


# ---------------------------------------------------------------------------
# statestore: exactly-once event append (both engines)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("engine", ["jsonl", "sqlite"])
def test_append_job_event_idempotent(tmp_path, engine):
    from finetune_controller_tpu.controller.schemas import JobRecord

    async def main():
        state = StateStore(tmp_path / "state", backend=engine)
        await state.connect()
        await state.create_job(JobRecord(
            job_id="e-1", user_id="u", model_name="tiny-test-lora",
        ))
        assert await state.append_job_event(
            "e-1", make_event("running", key="running:a1", attempt=1)
        )
        # same idempotency key: dropped (the crash-retry convergence path)
        assert not await state.append_job_event(
            "e-1", make_event("running", key="running:a1", attempt=1)
        )
        # different key: appended
        assert await state.append_job_event(
            "e-1", make_event("running", key="running:a2", attempt=2)
        )
        # keyless events always append (trainer rows carry trainer:{idx})
        assert await state.append_job_event("e-1", make_event("succeeded"))
        job = await state.get_job("e-1")
        assert [e["event"] for e in job.events] == [
            "running", "running", "succeeded",
        ]
        # unknown job: refused, not crashed
        assert not await state.append_job_event(
            "nope", make_event("running", key="k")
        )
        await state.close()

    run(main())


@pytest.mark.parametrize("engine", ["jsonl", "sqlite"])
def test_append_job_events_batch_idempotent(tmp_path, engine):
    """The batch append (monitor ingest's one-write-per-tick path): per-item
    key dedupe against the stored list AND within the batch, survivors land
    in a single document write."""
    from finetune_controller_tpu.controller.schemas import JobRecord

    async def main():
        state = StateStore(tmp_path / "state", backend=engine)
        await state.connect()
        await state.create_job(JobRecord(
            job_id="e-2", user_id="u", model_name="tiny-test-lora",
        ))
        assert await state.append_job_event(
            "e-2", make_event("running", key="running:a1", attempt=1)
        )
        added = await state.append_job_events("e-2", [
            make_event("running", key="running:a1", attempt=1),  # stored dup
            make_event("checkpoint-committed", key="trainer:a1:0", step=10),
            make_event("checkpoint-committed", key="trainer:a1:0", step=10),
            make_event("checkpoint-committed", key="trainer:a1:1", step=20),
        ])
        assert added == 2
        job = await state.get_job("e-2")
        assert [e["event"] for e in job.events] == [
            "running", "checkpoint-committed", "checkpoint-committed",
        ]
        assert [
            e["attrs"]["step"] for e in job.events
            if e["event"] == "checkpoint-committed"
        ] == [10, 20]
        # empty batch and unknown jobs: no-ops, not crashes
        assert await state.append_job_events("e-2", []) == 0
        assert await state.append_job_events(
            "nope", [make_event("running", key="k")]
        ) == 0
        await state.close()

    run(main())


# ---------------------------------------------------------------------------
# trace assembly + the gap-free validator
# ---------------------------------------------------------------------------


def _job_doc(events, *, status="succeeded", end_time=None, trace_id="t" * 32):
    return {
        "job_id": "j-1",
        "status": status,
        "submitted_at": events[0]["ts"] if events else 0.0,
        "end_time": end_time,
        "metadata": {"trace_id": trace_id},
        "events": events,
    }


def test_build_trace_single_attempt_lifecycle():
    t0 = 100.0
    events = [
        make_event("submitted", ts=t0, key="submitted:1"),
        make_event("running", ts=t0 + 5, key="running:a1", attempt=1),
        make_event("checkpoint-committed", ts=t0 + 20, step=10),
        make_event("succeeded", ts=t0 + 30, key="succeeded:a1"),
    ]
    trace = build_trace(_job_doc(events, end_time=t0 + 30))
    assert trace["problems"] == []
    names = [s["name"] for s in trace["spans"]]
    assert names[0] == "job"
    assert "pending" in names and "attempt-1" in names
    root = trace["spans"][0]
    for s in trace["spans"][1:]:
        assert s["parent_span_id"] == root["span_id"]
    pending = next(s for s in trace["spans"] if s["name"] == "pending")
    attempt = next(s for s in trace["spans"] if s["name"] == "attempt-1")
    # pending runs submit -> running; the attempt takes over from there
    assert pending.get("end_ns") == attempt["start_ns"]


def test_build_trace_grafts_trainer_spans_under_their_attempt():
    t0 = 50.0
    events = [
        make_event("submitted", ts=t0, key="submitted:1"),
        make_event("running", ts=t0 + 1, key="running:a1", attempt=1),
        make_event("retrying", ts=t0 + 10, key="retrying:i0", attempt=1),
        make_event("running", ts=t0 + 20, key="running:a2", attempt=2),
        make_event("succeeded", ts=t0 + 30, key="succeeded:a2"),
    ]
    trainer_spans = [
        {
            "name": "checkpoint", "trace_id": "x", "span_id": "s" * 16,
            "parent_span_id": None,
            "start_ns": int((t0 + 22) * 1e9), "end_ns": int((t0 + 23) * 1e9),
            "status": "ok", "attributes": {"attempt": 2},
        },
        {
            "name": "orphan", "trace_id": "x", "span_id": "o" * 16,
            "parent_span_id": None,
            "start_ns": int((t0 + 5) * 1e9), "end_ns": int((t0 + 6) * 1e9),
            "status": "ok", "attributes": {},  # no attempt -> hangs off root
        },
    ]
    trace = build_trace(_job_doc(events, end_time=t0 + 30), trainer_spans)
    assert trace["problems"] == []
    by_name = {s["name"]: s for s in trace["spans"]}
    assert by_name["checkpoint"]["parent_span_id"] \
        == by_name["attempt-2"]["span_id"]
    assert by_name["orphan"]["parent_span_id"] == by_name["job"]["span_id"]
    assert by_name["checkpoint"]["trace_id"] == "t" * 32  # normalized


def test_build_trace_reparents_spans_whose_parent_never_landed():
    """A kill loses the spans still open (the crash-safe JSONL holds
    finished spans only), so a killed job's surviving children reference a
    fit span that never landed — they must re-graft under their attempt,
    not dangle as an 'unknown parent' problem."""
    t0 = 50.0
    events = [
        make_event("submitted", ts=t0, key="submitted:1"),
        make_event("running", ts=t0 + 1, key="running:a1", attempt=1),
        make_event("cancelled", ts=t0 + 30, key="cancelled:1"),
    ]
    orphaned = {
        "name": "init", "trace_id": "x", "span_id": "i" * 16,
        "parent_span_id": "f" * 16,  # the lost (still-open) fit span
        "start_ns": int((t0 + 3) * 1e9), "end_ns": int((t0 + 8) * 1e9),
        "status": "ok", "attributes": {"attempt": 1},
    }
    trace = build_trace(
        _job_doc(events, status="cancelled", end_time=t0 + 30), [orphaned]
    )
    assert trace["problems"] == [], trace["problems"]
    by_name = {s["name"]: s for s in trace["spans"]}
    assert by_name["init"]["parent_span_id"] \
        == by_name["attempt-1"]["span_id"]


def test_build_trace_open_job_closes_phases_at_now():
    t0 = 10.0
    events = [
        make_event("submitted", ts=t0, key="submitted:1"),
        make_event("running", ts=t0 + 1, key="running:a1", attempt=1),
    ]
    trace = build_trace(_job_doc(events, status="running"), now=t0 + 60)
    assert trace["problems"] == []
    attempt = next(s for s in trace["spans"] if s["name"] == "attempt-1")
    assert attempt["attributes"].get("in_progress") is True
    assert attempt["end_ns"] == int((t0 + 60) * 1e9)


def test_validate_trace_flags_structural_problems():
    tid = "t" * 32
    from finetune_controller_tpu.obs.trace import make_span

    root = make_span("job", tid, start_ns=0, end_ns=100)
    ok_child = make_span(
        "attempt-1", tid, start_ns=10, end_ns=90,
        parent_span_id=root["span_id"],
    )
    # child escapes its parent's interval
    escapee = make_span(
        "late", tid, start_ns=50, end_ns=int(1e9),
        parent_span_id=root["span_id"],
    )
    orphan = make_span("orphan", tid, start_ns=5, end_ns=6,
                       parent_span_id="f" * 16)
    problems = validate_trace([root, ok_child, escapee, orphan])
    assert any("ends after parent" in p for p in problems)
    assert any("unknown parent" in p for p in problems)
    # an event outside every non-root span is a GAP
    problems = validate_trace(
        [root, ok_child], [{"event": "preempted", "ts": 500.0}]
    )
    assert any("not covered" in p for p in problems)
    # the same event inside the attempt span is covered
    assert validate_trace(
        [root, ok_child],
        [{"event": "preempted", "ts": 50e-9}],
    ) == []


# ---------------------------------------------------------------------------
# trainer integration: the fit loop records events/spans/phase columns
# ---------------------------------------------------------------------------


def _tiny_trainer(total_steps=6, **overrides):
    from finetune_controller_tpu.models import PRESETS, LoRAConfig
    from finetune_controller_tpu.train import Trainer, TrainConfig

    model_cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=2))
    cfg = TrainConfig(
        mode="lora", learning_rate=1e-3, warmup_steps=1,
        total_steps=total_steps, batch_size=2, seq_len=16,
        log_every=3, checkpoint_every=1000, prefetch=0,
        heartbeat_interval_s=0, **overrides,
    )
    return Trainer(model_cfg, cfg), model_cfg


def test_fit_records_events_spans_and_phase_columns(tmp_path, monkeypatch):
    from finetune_controller_tpu.data import synthetic_batches

    monkeypatch.setenv("FTC_TRACE_ID", "f" * 32)
    monkeypatch.setenv("FTC_ATTEMPT", "2")
    monkeypatch.delenv("FTC_TRACE", raising=False)
    trainer, model_cfg = _tiny_trainer()
    batches = synthetic_batches(2, 16, model_cfg.vocab_size, task="increment")
    trainer.fit(batches, str(tmp_path), resume=False)

    events = parse_event_lines((tmp_path / "events.jsonl").read_text())
    names = [e["event"] for e in events]
    assert names[0] == "train-started"
    assert "checkpoint-committed" in names  # the final save
    assert names[-1] == "train-finished"
    assert all(e["trace_id"] == "f" * 32 for e in events)
    assert all(e["attrs"]["attempt"] == 2 for e in events)

    spans = parse_span_lines(
        (tmp_path / "trace" / "trainer.jsonl").read_text()
    )
    by_name = {s["name"]: s for s in spans}
    assert {"init", "checkpoint", "fit"} <= set(by_name)
    assert by_name["init"]["parent_span_id"] == by_name["fit"]["span_id"]
    assert by_name["fit"]["status"] == "ok"
    assert validate_trace(spans) == []

    import csv

    with open(tmp_path / "metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    assert rows, "no metrics rows logged"
    for col in PhaseClock.columns():
        assert col in rows[0], f"missing {col} in metrics header"
    # phases are per-step ms and the split is sane: nonnegative, with the
    # device step (compute) claiming a nonzero share
    total = sum(float(rows[0][c]) for c in PhaseClock.columns())
    assert total > 0
    assert float(rows[0]["phase_compute_ms"]) >= 0


def test_fit_trace_kill_switch(tmp_path, monkeypatch):
    from finetune_controller_tpu.data import synthetic_batches

    monkeypatch.setenv("FTC_TRACE", "0")
    monkeypatch.setenv("FTC_TRACE_ID", "f" * 32)
    trainer, model_cfg = _tiny_trainer()
    batches = synthetic_batches(2, 16, model_cfg.vocab_size, task="increment")
    trainer.fit(batches, str(tmp_path), resume=False)
    assert not (tmp_path / "events.jsonl").exists()
    assert not (tmp_path / "trace").exists()
    import csv

    with open(tmp_path / "metrics.csv", newline="") as f:
        header = next(csv.reader(f))
    assert not any(c.startswith("phase_") for c in header)


def test_consume_profile_request_retires_the_file(tmp_path):
    from finetune_controller_tpu.train.trainer import Trainer

    req = tmp_path / "profile_request.json"
    req.write_text(json.dumps({"steps": 3}))
    assert Trainer._consume_profile_request(str(req)) == 3
    assert not req.exists()  # retired either way
    assert (tmp_path / "profile_request.json.consumed").exists()
    # garbage payload: 0 steps, still retired (no per-step retrigger)
    req.write_text("{torn")
    assert Trainer._consume_profile_request(str(req)) == 0
    assert not req.exists()
    # out-of-range step counts are clamped
    req.write_text(json.dumps({"steps": 10**9}))
    assert Trainer._consume_profile_request(str(req)) == 1000


def test_fit_on_demand_profiler_window(tmp_path, monkeypatch):
    """The artifact-channel profile request arms jax.profiler mid-run:
    profile/ appears and the profile-captured event lands on the log."""
    from finetune_controller_tpu.data import synthetic_batches

    monkeypatch.setenv("FTC_TRACE_ID", "p" * 32)
    monkeypatch.delenv("FTC_TRACE", raising=False)
    # deliver the request BEFORE the run: the first step consumes it
    (tmp_path / "profile_request.json").write_text(json.dumps({"steps": 2}))
    trainer, model_cfg = _tiny_trainer(total_steps=5)
    batches = synthetic_batches(2, 16, model_cfg.vocab_size, task="increment")
    trainer.fit(batches, str(tmp_path), resume=False)
    assert (tmp_path / "profile_request.json.consumed").exists()
    assert (tmp_path / "profile").is_dir()
    assert any((tmp_path / "profile").rglob("*")), "empty profiler trace"
    events = parse_event_lines((tmp_path / "events.jsonl").read_text())
    captured = [e for e in events if e["event"] == "profile-captured"]
    assert len(captured) == 1
    # armed before step 1: the 2-step window covers steps 1-2
    assert captured[0]["attrs"]["step"] == 2


def test_fit_on_demand_window_clamped_to_run_end(tmp_path, monkeypatch):
    """A window armed near the end of the run clamps to total_steps: the
    in-loop stop (and its profile-captured confirmation) still fires —
    an armed window must never complete silently via the finally-block."""
    from finetune_controller_tpu.data import synthetic_batches

    monkeypatch.setenv("FTC_TRACE_ID", "p" * 32)
    monkeypatch.delenv("FTC_TRACE", raising=False)
    (tmp_path / "profile_request.json").write_text(json.dumps({"steps": 50}))
    trainer, model_cfg = _tiny_trainer(total_steps=4)
    batches = synthetic_batches(2, 16, model_cfg.vocab_size, task="increment")
    trainer.fit(batches, str(tmp_path), resume=False)
    events = parse_event_lines((tmp_path / "events.jsonl").read_text())
    captured = [e for e in events if e["event"] == "profile-captured"]
    assert [e["attrs"]["step"] for e in captured] == [4]
    assert any((tmp_path / "profile").rglob("*")), "empty profiler trace"


def test_fit_on_demand_window_does_not_starve_configured_trace(tmp_path, monkeypatch):
    """An on-demand window that spans the configured profile_start_step must
    not swallow the configured trace: it starts at the first free step
    after the on-demand capture ends, and BOTH windows land."""
    from finetune_controller_tpu.data import synthetic_batches

    monkeypatch.setenv("FTC_TRACE_ID", "p" * 32)
    monkeypatch.delenv("FTC_TRACE", raising=False)
    # on-demand: armed before step 0, 3-step window [0, 3) — covering the
    # configured start (profile_start_step=1, 2 steps)
    (tmp_path / "profile_request.json").write_text(json.dumps({"steps": 3}))
    trainer, model_cfg = _tiny_trainer(
        total_steps=8, profile_steps=2, profile_start_step=1,
    )
    batches = synthetic_batches(2, 16, model_cfg.vocab_size, task="increment")
    trainer.fit(batches, str(tmp_path), resume=False)
    events = parse_event_lines((tmp_path / "events.jsonl").read_text())
    captured = [e["attrs"]["step"] for e in events
                if e["event"] == "profile-captured"]
    # on-demand [0,3) closes at step 3; the configured 2-step window then
    # runs [3,5) instead of silently never firing
    assert captured == [3, 5]


def test_fit_on_demand_profiler_window_with_trace_off(tmp_path, monkeypatch):
    """FTC_TRACE=0 silences spans/events but NOT on-demand profiling: the
    delivered request is still consumed and the trace captured — otherwise
    POST /jobs/{id}/profile would 202 into a file nothing ever reads."""
    from finetune_controller_tpu.data import synthetic_batches

    monkeypatch.setenv("FTC_TRACE", "0")
    monkeypatch.setenv("FTC_TRACE_ID", "p" * 32)
    monkeypatch.delenv("FTC_PROFILE", raising=False)
    (tmp_path / "profile_request.json").write_text(json.dumps({"steps": 2}))
    trainer, model_cfg = _tiny_trainer(total_steps=5)
    batches = synthetic_batches(2, 16, model_cfg.vocab_size, task="increment")
    trainer.fit(batches, str(tmp_path), resume=False)
    assert (tmp_path / "profile_request.json.consumed").exists()
    assert (tmp_path / "profile").is_dir()
    assert any((tmp_path / "profile").rglob("*")), "empty profiler trace"
    # the tracing kill switch still holds for spans and ordinary events —
    # but the capture CONFIRMATION is forced through (profiling is
    # decoupled from tracing, so its timeline evidence must be too)
    events = parse_event_lines((tmp_path / "events.jsonl").read_text())
    assert [e["event"] for e in events] == ["profile-captured"]
    assert not (tmp_path / "trace").exists()


def test_fit_profile_kill_switch(tmp_path, monkeypatch):
    """FTC_PROFILE=0 is profiling's own opt-out: the request file is left
    unconsumed and no trace is captured."""
    from finetune_controller_tpu.data import synthetic_batches

    monkeypatch.setenv("FTC_PROFILE", "0")
    monkeypatch.setenv("FTC_TRACE_ID", "p" * 32)
    monkeypatch.delenv("FTC_TRACE", raising=False)
    (tmp_path / "profile_request.json").write_text(json.dumps({"steps": 2}))
    trainer, model_cfg = _tiny_trainer(total_steps=5)
    batches = synthetic_batches(2, 16, model_cfg.vocab_size, task="increment")
    trainer.fit(batches, str(tmp_path), resume=False)
    assert (tmp_path / "profile_request.json").exists()
    assert not (tmp_path / "profile").exists()


# ---------------------------------------------------------------------------
# monitor: trainer-event ingest + terminal trace export
# ---------------------------------------------------------------------------


async def _plane(tmp_path, *, clock, max_attempts=4, obs=None):
    registry.reset()
    registry.load_builtin_models()
    state = StateStore(tmp_path / "state")
    store = LocalObjectStore(tmp_path / "objects")
    backend = ScriptedBackend()
    catalog = _catalog()
    supervisor = RetrySupervisor(
        state, backend, catalog,
        policy=RetryPolicy(
            max_attempts=max_attempts, base_delay_s=5.0, max_delay_s=5.0,
            seed=0,
        ),
        obs=obs,
        _clock=clock,
    )
    monitor = JobMonitor(
        state, store, backend, interval_s=0.1, supervisor=supervisor, obs=obs,
    )
    await state.connect()
    return state, store, backend, catalog, supervisor, monitor


async def _submit(state, store, backend, catalog, job_id="o-1",
                  user_id="u"):
    spec = _spec()
    job = JobInput(
        job_id=job_id, user_id=user_id, model_name="tiny-test-lora",
        device="chip-1", arguments=spec.training_arguments.model_dump(),
    )
    await task_builder(
        job, spec, DatasetInput(),
        state=state, store=store, backend=backend, catalog=catalog,
        datasets_bucket="datasets", artifacts_bucket="artifacts",
    )
    return job


def test_monitor_ingests_trainer_events_exactly_once(tmp_path):
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        await _submit(state, store, backend, catalog)
        job = await state.get_job("o-1")
        lines = [
            json.dumps(make_event("train-started", ts=1.0, step=0)),
            json.dumps(make_event("checkpoint-committed", ts=2.0, step=10)),
        ]
        await store.put_bytes(
            f"{job.artifacts_uri}/events.jsonl",
            ("\n".join(lines) + "\n").encode(),
        )
        backend.reports["o-1"] = BackendJobReport(
            job_id="o-1", state=BackendJobState.RUNNING, start_time=1.0,
        )
        await monitor.tick()
        await monitor.tick()  # second pass must not duplicate
        job = await state.get_job("o-1")
        trainer_events = [
            e for e in job.events
            if e["event"] in ("train-started", "checkpoint-committed")
        ]
        assert [e["event"] for e in trainer_events] == [
            "train-started", "checkpoint-committed",
        ]
        assert job.metadata["obs_events_ingested"] == 2
        # the trainer appends a new line; only IT is ingested
        lines.append(
            json.dumps(make_event("checkpoint-committed", ts=3.0, step=20))
        )
        await store.put_bytes(
            f"{job.artifacts_uri}/events.jsonl",
            ("\n".join(lines) + "\n").encode(),
        )
        await monitor.tick()
        job = await state.get_job("o-1")
        commits = [
            e for e in job.events if e["event"] == "checkpoint-committed"
        ]
        assert [e["attrs"]["step"] for e in commits] == [10, 20]
        assert job.metadata["obs_events_ingested"] == 3

    run(main())


def test_monitor_ingest_survives_events_file_restart(tmp_path):
    """A retry's fresh sandbox on a backend that does not stage events.jsonl
    back (e.g. a k8s pod) re-begins the file at line 0 and the sidecar
    overwrites the stored copy.  The ingest must neither stall (watermark
    above the line count) nor drop the new attempt's rows to positional key
    collisions with the old attempt's."""
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        await _submit(state, store, backend, catalog)
        job = await state.get_job("o-1")
        uri = f"{job.artifacts_uri}/events.jsonl"
        a1 = [
            json.dumps(make_event("train-started", ts=1.0, step=0, attempt=1)),
            json.dumps(make_event(
                "checkpoint-committed", ts=2.0, step=10, attempt=1,
            )),
        ]
        await store.put_bytes(uri, ("\n".join(a1) + "\n").encode())
        backend.reports["o-1"] = BackendJobReport(
            job_id="o-1", state=BackendJobState.RUNNING, start_time=1.0,
        )
        await monitor.tick()
        job = await state.get_job("o-1")
        assert job.metadata["obs_events_ingested"] == 2
        # attempt 2's pod starts a FRESH file, shorter than the watermark
        a2 = [json.dumps(make_event(
            "train-started", ts=9.0, step=10, attempt=2,
        ))]
        await store.put_bytes(uri, (a2[0] + "\n").encode())
        await monitor.tick()
        job = await state.get_job("o-1")
        starts = [e for e in job.events if e["event"] == "train-started"]
        assert [e["attrs"]["attempt"] for e in starts] == [1, 2]
        assert job.metadata["obs_events_ingested"] == 1  # the new file's count
        # the new attempt keeps appending: new rows land exactly once
        a2.append(json.dumps(make_event(
            "checkpoint-committed", ts=10.0, step=20, attempt=2,
        )))
        await store.put_bytes(uri, ("\n".join(a2) + "\n").encode())
        await monitor.tick()
        await monitor.tick()
        job = await state.get_job("o-1")
        commits = [
            e for e in job.events if e["event"] == "checkpoint-committed"
        ]
        assert [e["attrs"]["step"] for e in commits] == [10, 20]
        # a restarted file that has already GROWN past the watermark (slow
        # sync cadence): only the first-line fingerprint can detect it —
        # a length check would silently drop the first rows
        a3 = [
            json.dumps(make_event("train-started", ts=20.0, step=20, attempt=3)),
            json.dumps(make_event(
                "checkpoint-committed", ts=21.0, step=30, attempt=3,
            )),
            json.dumps(make_event(
                "checkpoint-committed", ts=22.0, step=40, attempt=3,
            )),
        ]
        await store.put_bytes(uri, ("\n".join(a3) + "\n").encode())
        await monitor.tick()
        job = await state.get_job("o-1")
        starts = [e for e in job.events if e["event"] == "train-started"]
        assert [e["attrs"]["attempt"] for e in starts] == [1, 2, 3]
        commits = [
            e for e in job.events if e["event"] == "checkpoint-committed"
        ]
        assert [e["attrs"]["step"] for e in commits] == [10, 20, 30, 40]

    run(main())


def test_monitor_ingest_is_best_effort_and_poison_tolerant(tmp_path):
    """The module contract — the timeline must never stall reconciliation:
    a garbage ts in a row must not raise every tick, and a failing store
    write aborts only THIS job's ingest (retried next tick), not the pass."""
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        await _submit(state, store, backend, catalog)
        job = await state.get_job("o-1")
        uri = f"{job.artifacts_uri}/events.jsonl"
        poison = dict(make_event("train-started", attempt=1))
        poison["ts"] = "not-a-number"
        await store.put_bytes(
            uri, (json.dumps(poison) + "\n").encode(),
        )
        backend.reports["o-1"] = BackendJobReport(
            job_id="o-1", state=BackendJobState.RUNNING, start_time=1.0,
        )
        # a transient write failure must not escape the ingest
        real_batch = state.append_job_events
        fail_once = {"armed": True}

        async def flaky_batch(jid, evs):
            if fail_once.pop("armed", None):
                raise IOError("injected statestore outage")
            return await real_batch(jid, evs)

        state.append_job_events = flaky_batch
        await monitor.tick()  # write fails; tick must complete anyway
        job = await state.get_job("o-1")
        assert "obs_events_ingested" not in job.metadata
        await monitor.tick()  # retried: poison ts lands with a now-stamp
        job = await state.get_job("o-1")
        starts = [e for e in job.events if e["event"] == "train-started"]
        assert len(starts) == 1
        assert isinstance(starts[0]["ts"], float)
        assert job.metadata["obs_events_ingested"] == 1

    run(main())


def test_monitor_ingest_batches_writes_and_skips_unchanged_reads(tmp_path):
    """Per-tick cost of the trainer-event ingest: all new rows of a tick fold
    into ONE batched document write, and an unchanged events.jsonl costs a
    stat — not a read — on every subsequent tick."""
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        await _submit(state, store, backend, catalog)
        job = await state.get_job("o-1")
        uri = f"{job.artifacts_uri}/events.jsonl"
        lines = [json.dumps(make_event("train-started", ts=1.0, attempt=1))]
        lines += [
            json.dumps(make_event(
                "checkpoint-committed", ts=float(i), step=i * 10, attempt=1,
            ))
            for i in range(1, 5)
        ]
        await store.put_bytes(uri, ("\n".join(lines) + "\n").encode())
        backend.reports["o-1"] = BackendJobReport(
            job_id="o-1", state=BackendJobState.RUNNING, start_time=1.0,
        )
        reads: list[str] = []
        batches: list[int] = []
        singles: list[dict] = []
        real_get, real_batch, real_single = (
            store.get_bytes, state.append_job_events, state.append_job_event,
        )

        async def counting_get(u):
            if u.endswith("events.jsonl"):
                reads.append(u)
            return await real_get(u)

        async def counting_batch(jid, evs):
            batches.append(len(evs))
            return await real_batch(jid, evs)

        async def counting_single(jid, ev):
            singles.append(ev)
            return await real_single(jid, ev)

        store.get_bytes = counting_get
        state.append_job_events = counting_batch
        state.append_job_event = counting_single
        await monitor.tick()
        assert batches == [5], "all five rows must land in one write"
        assert not [
            e for e in singles
            if str(e.get("key", "")).startswith("trainer:")
        ], "trainer rows must not go through the per-event path"
        assert len(reads) == 1
        await monitor.tick()  # unchanged file: stat short-circuit, no read
        await monitor.tick()
        assert len(reads) == 1
        assert batches == [5]

    run(main())


def test_supervisor_events_use_dispatch_numbering_after_resize(tmp_path):
    """A resize is budget-exempt but still a dispatch: after resize-then-
    preempt, the retrying events must name dispatches 1 and 2 — the same
    numbering as running/FTC_ATTEMPT/trainer spans.  (The budget count,
    which excludes resizes, would label BOTH retrying events attempt=1.)"""
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        await _submit(state, store, backend, catalog)
        await state.update_job_status("o-1", DatabaseStatus.RUNNING)
        job = await state.get_job("o-1")
        # dispatch 1 ends in a scheduler resize (budget-exempt)
        assert await sup.on_job_failed(
            job, exit_code=143, message="resized by scheduler",
            resize_to=1, report_metadata={"resize_kind": "shrink"},
        )
        await state.update_job_status("o-1", DatabaseStatus.RUNNING)
        job = await state.get_job("o-1")
        # dispatch 2 ends in a genuine preemption (burns budget attempt 1)
        assert await sup.on_job_failed(
            job, exit_code=143, message="preempted",
            report_metadata={"preempted": True, "preempted_by": "hi"},
        )
        job = await state.get_job("o-1")
        retries = [e for e in job.events if e["event"] == "retrying"]
        assert [e["attrs"]["attempt"] for e in retries] == [1, 2]

    run(main())


def test_phase_histograms_not_double_counted_across_resume_truncation(tmp_path):
    """Crash-resume truncates replayed rows from the metrics CSV (the
    MetricsWriter replay-drop) and the trainer then re-logs those windows:
    the step-phase histograms must observe each step exactly once — the
    stored record COUNT is not a safe watermark across the truncation."""
    async def main():
        clock = FakeClock()
        obs = ObsHub()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock, obs=obs
        )
        await _submit(state, store, backend, catalog)
        job = await state.get_job("o-1")
        uri = f"{job.artifacts_uri}/metrics.csv"
        backend.reports["o-1"] = BackendJobReport(
            job_id="o-1", state=BackendJobState.RUNNING, start_time=1.0,
        )

        def csv_for(steps):
            head = "step,loss,phase_input_ms\n"
            return (
                head + "".join(f"{s},1.0,{5.0 + s}\n" for s in steps)
            ).encode()

        def observed():
            return sum(obs.step_phase_ms._counts.get(("input",), []))

        await store.put_bytes(uri, csv_for(range(1, 11)))
        await monitor.tick()
        assert observed() == 10
        # crash + resume from the step-5 checkpoint: rows 6-10 truncated
        await store.put_bytes(uri, csv_for(range(1, 6)))
        await monitor.tick()
        assert observed() == 10
        # the resumed attempt re-logs steps 6-10 with fresh timings — same
        # steps, so they must NOT observe a second time
        await store.put_bytes(uri, csv_for(range(1, 11)))
        await monitor.tick()
        assert observed() == 10
        # genuinely new steps still observe
        await store.put_bytes(uri, csv_for(range(1, 13)))
        await monitor.tick()
        assert observed() == 12

    run(main())


def test_monitor_exports_trace_on_success(tmp_path):
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        await _submit(state, store, backend, catalog)
        backend.reports["o-1"] = BackendJobReport(
            job_id="o-1", state=BackendJobState.RUNNING, start_time=1.0,
        )
        await monitor.tick()
        backend.reports["o-1"] = BackendJobReport(
            job_id="o-1", state=BackendJobState.SUCCEEDED,
            start_time=1.0, completion_time=9.0,
        )
        await monitor.tick()
        job = await state.get_job("o-1")
        assert job.status is DatabaseStatus.SUCCEEDED
        raw = await store.get_bytes(f"{job.artifacts_uri}/trace/trace.json")
        trace = json.loads(raw)
        assert trace["trace_id"] == job.metadata["trace_id"]
        assert trace["problems"] == []
        assert {"job", "pending", "attempt-1"} <= {
            s["name"] for s in trace["spans"]
        }
        assert job.metadata["trace_exported"] is True

    run(main())


def test_build_trace_covers_promotion_settles_without_start():
    """An unpromote (and a failed unpromote) appends a settle event with no
    ``promotion-started`` before it — the trace must still cover it instead
    of reporting a healthy lifecycle as gap-ridden."""
    t0 = 100.0
    events = [
        make_event("submitted", ts=t0, key="submitted:1"),
        make_event("running", ts=t0 + 1, key="running:a1", attempt=1),
        make_event("succeeded", ts=t0 + 10, key="succeeded:a1"),
        make_event("promotion-started", ts=t0 + 20, key="ps:1"),
        make_event("promoted", ts=t0 + 25, key="p:1"),
        make_event("unpromoted", ts=t0 + 40, key="u:1"),
        # a later unpromote attempt that fails also settles start-less
        make_event("promotion-failed", ts=t0 + 50, key="pf:1"),
    ]
    trace = build_trace(_job_doc(events, end_time=t0 + 10))
    assert trace["problems"] == [], trace["problems"]
    promos = [s for s in trace["spans"] if s["name"] == "promotion"]
    assert [s["attributes"]["outcome"] for s in promos] == [
        "promoted", "unpromoted", "promotion-failed",
    ]
    # the started->promoted pair brackets a real interval; the start-less
    # settles are instantaneous
    assert promos[0]["end_ns"] - promos[0]["start_ns"] == int(5e9)
    assert promos[1]["end_ns"] == promos[1]["start_ns"]


def test_monitor_ingest_tolerates_reserved_and_corrupt_attr_rows(tmp_path):
    """events.jsonl is untrusted input: attrs shadowing ``make_event``'s own
    parameters must be dropped (not raise a TypeError that aborts the tick),
    and a row whose attempt is NaN is skipped without losing its neighbors."""
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        await _submit(state, store, backend, catalog)
        job = await state.get_job("o-1")
        lines = [
            json.dumps({
                "ts": 1.0, "event": "train-started",
                "attrs": {"ts": 99.0, "event": "zap", "key": "boom", "step": 0},
            }),
            json.dumps({
                "ts": 2.0, "event": "checkpoint-committed",
                "attrs": {"attempt": float("nan"), "step": 10},
            }),
            json.dumps(make_event("train-finished", ts=3.0, step=20)),
        ]
        await store.put_bytes(
            f"{job.artifacts_uri}/events.jsonl",
            ("\n".join(lines) + "\n").encode(),
        )
        backend.reports["o-1"] = BackendJobReport(
            job_id="o-1", state=BackendJobState.RUNNING, start_time=1.0,
        )
        await monitor.tick()  # must not raise
        job = await state.get_job("o-1")
        by_name = {e["event"]: e for e in job.events}
        started = by_name["train-started"]
        assert started["ts"] == 1.0  # the file-level ts, not the attr
        assert started["attrs"]["step"] == 0
        assert "ts" not in started["attrs"] and "key" not in started["attrs"]
        # the NaN-attempt row is dropped; its neighbor still lands
        assert "checkpoint-committed" not in by_name
        assert by_name["train-finished"]["attrs"]["step"] == 20
        assert job.metadata["obs_events_ingested"] == 3

    run(main())


def test_monitor_exports_trace_for_job_settled_outside_report_loop(tmp_path):
    """A job that went terminal outside the succeeded/failed report branches
    (user cancel racing the tick) still gets its trace exported while its
    backend report lingers."""
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        await _submit(state, store, backend, catalog)
        await state.append_job_event(
            "o-1", make_event("cancelled", key="cancelled:1")
        )
        await state.update_job_status(
            "o-1", DatabaseStatus.CANCELLED, end_time=5.0, queue_position=None
        )
        backend.reports["o-1"] = BackendJobReport(
            job_id="o-1", state=BackendJobState.RUNNING, start_time=1.0,
        )
        await monitor.tick()
        job = await state.get_job("o-1")
        assert job.metadata.get("trace_exported") is True
        trace = json.loads(
            await store.get_bytes(f"{job.artifacts_uri}/trace/trace.json")
        )
        assert trace["problems"] == [], trace["problems"]

    run(main())


def test_supervisor_terminal_failure_exports_trace(tmp_path):
    """Terminal FAILED writes on paths the report loop never revisits (lease
    kill, sweep, resubmit failures) flow through the supervisor's
    ``on_terminal`` hook, which the monitor wires to its trace export."""
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock, max_attempts=1
        )
        assert sup.on_terminal is not None  # wired by JobMonitor.__init__
        await _submit(state, store, backend, catalog)
        await state.update_job_status("o-1", DatabaseStatus.RUNNING)
        job = await state.get_job("o-1")
        retried = await sup.on_job_failed(
            job, exit_code=1, message="stuck; killed by the liveness lease"
        )
        assert retried is False
        job = await state.get_job("o-1")
        assert job.status is DatabaseStatus.FAILED
        assert job.metadata.get("trace_exported") is True
        assert await store.exists(f"{job.artifacts_uri}/trace/trace.json")

    run(main())


def test_restart_recovery_events_get_fresh_keys_and_crash_retry_dedupes(tmp_path):
    """A pod restart inside ONE attempt produces RESTARTING -> RUNNING ->
    RESTARTING transitions that must each land on the timeline (per-attempt
    keys alone would fold them into the first occurrence) — while a monitor
    crash between the event append and the status write still dedupes to
    exactly one event on the re-observed transition."""
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        await _submit(state, store, backend, catalog)

        async def observe(state_):
            backend.reports["o-1"] = BackendJobReport(
                job_id="o-1", state=state_, start_time=1.0,
            )
            await monitor.tick()

        await observe(BackendJobState.RUNNING)
        await observe(BackendJobState.RESTARTING)
        await observe(BackendJobState.RUNNING)
        await observe(BackendJobState.RESTARTING)
        job = await state.get_job("o-1")
        names = [e["event"] for e in job.events]
        assert names == [
            "submitted", "running", "restarting", "running", "restarting",
        ]
        keys = [e["key"] for e in job.events if "key" in e]
        assert len(keys) == len(set(keys))
        # crash-retry: the event for the NEXT transition was appended but the
        # process died before the status write — the re-observed transition
        # reuses the same seq-scoped key and the duplicate is dropped
        seq = job.metadata["obs_transition_seq"]
        await state.append_job_event(
            "o-1",
            make_event("running", key=f"running:a1:t{seq}", attempt=1),
        )
        await observe(BackendJobState.RUNNING)
        job = await state.get_job("o-1")
        assert [e["event"] for e in job.events].count("running") == 3
        assert job.status is DatabaseStatus.RUNNING

    run(main())


def test_cancel_endpoint_exports_trace(tmp_path):
    """POST /jobs/{id}/cancel deletes the backend half, so no report ever
    comes back — the handler itself must trigger the trace export."""
    from aiohttp.test_utils import TestClient, TestServer

    from finetune_controller_tpu.controller.config import Settings
    from finetune_controller_tpu.controller.objectstore import Presigner
    from finetune_controller_tpu.controller.runtime import Runtime
    from finetune_controller_tpu.controller.server import build_app

    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        settings = Settings(
            state_dir=str(tmp_path / "state"),
            object_store_root=str(tmp_path / "objects"),
        )
        runtime = Runtime(
            settings=settings, state=state, store=store, catalog=catalog,
            backend=backend, monitor=monitor,
            presigner=Presigner(settings.presign_secret),
        )
        app = build_app(runtime, with_monitor=False)
        client = TestClient(TestServer(app))
        await client.start_server()
        await _submit(state, store, backend, catalog, user_id="dev-user")

        r = await client.post("/api/v1/jobs/o-1/cancel")
        assert r.status == 200, await r.text()
        job = None
        for _ in range(100):
            job = await state.get_job("o-1")
            if job.metadata.get("trace_exported"):
                break
            await asyncio.sleep(0.05)
        assert job.metadata.get("trace_exported") is True
        trace = json.loads(
            await store.get_bytes(f"{job.artifacts_uri}/trace/trace.json")
        )
        assert trace["problems"] == [], trace["problems"]
        cancelled = [e for e in job.events if e["event"] == "cancelled"]
        # fixed idempotency key: racing cancel requests fold into one event
        assert [e.get("key") for e in cancelled] == ["cancelled"]
        await client.close()

    run(main())


# ---------------------------------------------------------------------------
# THE hard-path e2e (ISSUE 9 acceptance): preempt -> resize -> retry ->
# promote, every transition exactly once, in order, monotonic; the span
# tree gap-free with valid nesting.
# ---------------------------------------------------------------------------


def test_timeline_complete_across_preempt_resize_retry_promote(tmp_path):
    from aiohttp.test_utils import TestClient, TestServer

    from finetune_controller_tpu.controller.config import Settings
    from finetune_controller_tpu.controller.objectstore import Presigner
    from finetune_controller_tpu.controller.runtime import Runtime
    from finetune_controller_tpu.controller.server import build_app

    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock, obs=ObsHub()
        )
        settings = Settings(
            state_dir=str(tmp_path / "state"),
            object_store_root=str(tmp_path / "objects"),
            rate_limit_promote_per_min=1000,
        )
        runtime = Runtime(
            settings=settings, state=state, store=store, catalog=catalog,
            backend=backend, monitor=monitor,
            presigner=Presigner(settings.presign_secret),
        )
        app = build_app(runtime, with_monitor=False)
        client = TestClient(TestServer(app))
        await client.start_server()

        await _submit(state, store, backend, catalog, user_id="dev-user")

        def report(state_, **meta):
            kw = {}
            if state_ is BackendJobState.RUNNING:
                kw["start_time"] = clock.t
            if state_ is BackendJobState.SUCCEEDED:
                kw["start_time"], kw["completion_time"] = clock.t - 5, clock.t
            backend.reports["o-1"] = BackendJobReport(
                job_id="o-1", state=state_, metadata=meta, **kw
            )

        # attempt 1 runs, then is PREEMPTED (SIGTERM -> 143)
        report(BackendJobState.RUNNING)
        await monitor.tick()
        report(
            BackendJobState.FAILED, exit_code=143,
            preempted=True, preempted_by="job-hi",
        )
        await monitor.tick()
        assert (await state.get_job("o-1")).status is DatabaseStatus.RETRYING
        clock.advance(10)
        await monitor.tick()  # backoff expired -> resubmitted

        # attempt 2 runs, then a scheduler RESIZE (shrink to 1 slice)
        report(BackendJobState.RUNNING)
        await monitor.tick()
        report(
            BackendJobState.FAILED, exit_code=143,
            resize_to_num_slices=1, resize_kind="shrink",
        )
        await monitor.tick()
        clock.advance(10)
        await monitor.tick()

        # attempt 3 runs to completion
        report(BackendJobState.RUNNING)
        await monitor.tick()
        report(BackendJobState.SUCCEEDED)
        await monitor.tick()
        job = await state.get_job("o-1")
        assert job.status is DatabaseStatus.SUCCEEDED

        # promote through the real HTTP handler (promotion-started) and the
        # real background task (promoted)
        await store.put_bytes(
            f"{job.artifacts_uri}/checkpoints/step_2/state.msgpack", b"w"
        )
        r = await client.post("/api/v1/jobs/o-1/promote")
        assert r.status == 202, await r.text()
        for _ in range(100):
            job = await state.get_job("o-1")
            if job.promotion_status.value == "completed":
                break
            await asyncio.sleep(0.05)
        assert job.promotion_status.value == "completed"

        # --- the completeness assertions -------------------------------
        events = job.events
        names = [e["event"] for e in events]
        assert names == [
            "submitted",
            "running",
            "preempted", "retrying", "resubmitted",
            "running",
            "resized", "retrying", "resubmitted",
            "running",
            "succeeded",
            "promotion-started", "promoted",
        ]
        # exactly once: every keyed transition instance is unique
        keys = [e["key"] for e in events if "key" in e]
        assert len(keys) == len(set(keys))
        # in order, monotonic timestamps
        ts = [e["ts"] for e in events]
        assert ts == sorted(ts)
        # attempts attributed: the three running events are attempts 1..3
        runs = [e for e in events if e["event"] == "running"]
        assert [e["attrs"]["attempt"] for e in runs] == [1, 2, 3]
        # ONE numbering across planes: the supervisor's retrying events name
        # the dispatch that just ended and resubmitted names the next one —
        # the same scheme as running/FTC_ATTEMPT/trainer spans (a resize is
        # budget-exempt but still a dispatch)
        retries = [e for e in events if e["event"] == "retrying"]
        assert [e["attrs"]["attempt"] for e in retries] == [1, 2]
        resubs = [e for e in events if e["event"] == "resubmitted"]
        assert [e["attrs"]["attempt"] for e in resubs] == [2, 3]
        resized = next(e for e in events if e["event"] == "resized")
        assert resized["attrs"]["to_slices"] == 1
        assert resized["attrs"]["kind"] == "shrink"
        preempted = next(e for e in events if e["event"] == "preempted")
        assert preempted["attrs"]["by"] == "job-hi"

        # latency histograms observed along the way
        assert monitor.obs.queue_wait_seconds.count() == 3
        assert sup.obs.retry_latency_seconds.count() == 2

        # --- the gap-free span tree (acceptance criterion) -------------
        r = await client.get("/api/v1/jobs/o-1/trace")
        assert r.status == 200
        trace = await r.json()
        assert trace["trace_id"] == job.metadata["trace_id"]
        assert trace["problems"] == [], trace["problems"]
        names = {s["name"] for s in trace["spans"]}
        assert {
            "job", "pending", "attempt-1", "attempt-2", "attempt-3",
            "promotion",
        } <= names
        # parent/child nesting is valid and every lifecycle event is
        # covered by a span — re-check through the validator directly
        assert validate_trace(trace["spans"], job.events) == []

        # the timeline API serves the same events, oldest first
        r = await client.get("/api/v1/jobs/o-1/timeline")
        assert r.status == 200
        body = await r.json()
        assert [e["event"] for e in body["events"]] \
            == [e["event"] for e in job.events]
        assert body["trace_id"] == job.metadata["trace_id"]

        # the exported trace artifact landed next to the checkpoints
        assert await store.exists(f"{job.artifacts_uri}/trace/trace.json")

        await client.close()

    run(main())


# ---------------------------------------------------------------------------
# HTTP surface: profile guards, admin progress
# ---------------------------------------------------------------------------


def test_profile_endpoint_guards(tmp_path):
    from aiohttp.test_utils import TestClient, TestServer

    from finetune_controller_tpu.controller.config import Settings
    from finetune_controller_tpu.controller.objectstore import Presigner
    from finetune_controller_tpu.controller.runtime import Runtime
    from finetune_controller_tpu.controller.server import build_app

    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        settings = Settings(
            state_dir=str(tmp_path / "state"),
            object_store_root=str(tmp_path / "objects"),
        )
        runtime = Runtime(
            settings=settings, state=state, store=store, catalog=catalog,
            backend=backend, monitor=monitor,
            presigner=Presigner(settings.presign_secret),
        )
        client = TestClient(TestServer(build_app(runtime, with_monitor=False)))
        await client.start_server()
        await _submit(state, store, backend, catalog, user_id="dev-user")

        # not running -> 409
        r = await client.post("/api/v1/jobs/o-1/profile", json={"steps": 3})
        assert r.status == 409
        await state.update_job_status("o-1", DatabaseStatus.RUNNING)
        # bad steps -> 400
        r = await client.post("/api/v1/jobs/o-1/profile", json={"steps": 0})
        assert r.status == 400
        # ScriptedBackend cannot deliver control files -> 501
        r = await client.post("/api/v1/jobs/o-1/profile", json={"steps": 3})
        assert r.status == 501
        # the ftc-ctl command routes through the same endpoint and
        # surfaces the server's refusal as an ApiError
        from finetune_controller_tpu.controller import ctl

        api = f"http://{client.server.host}:{client.server.port}"
        with pytest.raises(ctl.ApiError, match="cannot deliver"):
            await ctl.amain(ctl.build_parser().parse_args(
                ["--api", api, "profile", "o-1", "--steps", "3"]
            ))
        await client.close()

    run(main())


def test_admin_resilience_shows_progress_rate(tmp_path):
    from aiohttp.test_utils import TestClient, TestServer

    from finetune_controller_tpu.controller.config import Settings
    from finetune_controller_tpu.controller.objectstore import Presigner
    from finetune_controller_tpu.controller.runtime import Runtime
    from finetune_controller_tpu.controller.server import build_app
    from finetune_controller_tpu.resilience.heartbeat import (
        HEARTBEAT_FILENAME,
    )

    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        settings = Settings(
            state_dir=str(tmp_path / "state"),
            object_store_root=str(tmp_path / "objects"),
        )
        runtime = Runtime(
            settings=settings, state=state, store=store, catalog=catalog,
            backend=backend, monitor=monitor,
            presigner=Presigner(settings.presign_secret),
        )
        client = TestClient(TestServer(build_app(runtime, with_monitor=False)))
        await client.start_server()
        await _submit(state, store, backend, catalog, user_id="dev-user")
        await state.update_job_status("o-1", DatabaseStatus.RUNNING)
        job = await state.get_job("o-1")
        await store.put_bytes(
            f"{job.artifacts_uri}/{HEARTBEAT_FILENAME}",
            json.dumps({
                "step": 120, "last_step": 120, "last_step_ms": 250.0,
                "ts": time.time(), "wall_time_s": 30.0, "pid": 1,
            }).encode(),
        )
        r = await client.get("/api/v1/admin/resilience")
        assert r.status == 200
        body = await r.json()
        rows = {p["job_id"]: p for p in body["progress"]}
        assert rows["o-1"]["last_step"] == 120
        assert rows["o-1"]["last_step_ms"] == 250.0
        assert rows["o-1"]["steps_per_min"] == pytest.approx(240.0)
        assert rows["o-1"]["heartbeat_age_s"] < 10
        await client.close()

    run(main())


def test_monitor_lease_kill_logs_last_known_step(tmp_path):
    """Satellite: LeaseChecker remembers the last heartbeat it parsed and
    the lease-killed timeline event names the step the job stalled at."""
    from finetune_controller_tpu.resilience.heartbeat import (
        HEARTBEAT_FILENAME,
        LeaseChecker,
    )

    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup, monitor = await _plane(
            tmp_path, clock=clock
        )
        monitor.lease = LeaseChecker(store, lease_s=5.0)
        await _submit(state, store, backend, catalog)
        await state.update_job_status("o-1", DatabaseStatus.RUNNING)
        job = await state.get_job("o-1")
        stale_ts = time.time() - 3600
        await store.put_bytes(
            f"{job.artifacts_uri}/{HEARTBEAT_FILENAME}",
            json.dumps({
                "step": 77, "last_step": 77, "last_step_ms": 120.0,
                "ts": stale_ts, "wall_time_s": 60.0, "pid": 1,
            }).encode(),
        )
        backend.reports["o-1"] = BackendJobReport(
            job_id="o-1", state=BackendJobState.RUNNING,
            start_time=stale_ts - 10,
        )
        await monitor.tick()
        job = await state.get_job("o-1")
        killed = [e for e in job.events if e["event"] == "lease-killed"]
        assert len(killed) == 1
        assert killed[0]["attrs"]["last_step"] == 77
        assert monitor.lease_kills == 1
        # routed through the supervisor like any infra failure
        assert job.status is DatabaseStatus.RETRYING

    run(main())


# ---------------------------------------------------------------------------
# backends: deliver_file (the artifact channel, reverse direction)
# ---------------------------------------------------------------------------


def test_local_backend_deliver_file_atomic_and_contained(tmp_path):
    from finetune_controller_tpu.controller.backends.base import BackendError
    from finetune_controller_tpu.controller.backends.local import (
        LocalProcessBackend,
        _JobHandle,
    )

    async def main():
        store = LocalObjectStore(tmp_path / "objects")
        backend = LocalProcessBackend(
            tmp_path / "sandboxes", store, _catalog()
        )
        sandbox = tmp_path / "sandboxes" / "d-1"
        handle = _JobHandle("d-1", sandbox, "artifacts/d-1", [])
        handle.artifacts_dir.mkdir(parents=True)
        backend._handles["d-1"] = handle

        assert await backend.deliver_file(
            "d-1", "profile_request.json", b'{"steps": 3}'
        )
        dest = handle.artifacts_dir / "profile_request.json"
        assert json.loads(dest.read_text()) == {"steps": 3}
        assert not dest.with_name(dest.name + ".tmp").exists()  # atomic

        # sandbox containment: a traversal path is refused loudly
        with pytest.raises(BackendError):
            await backend.deliver_file(
                "d-1", "../../outside.json", b"x"
            )
        assert not (tmp_path / "outside.json").exists()

        # unknown job: not delivered, not crashed
        assert not await backend.deliver_file("nope", "f.json", b"x")
        await backend.close()

    run(main())


# ---------------------------------------------------------------------------
# satellites: stream-logger attribution prefix, heartbeat progress fields
# ---------------------------------------------------------------------------


def test_stream_logger_prefixes_lines_with_trace_and_attempt():
    from finetune_controller_tpu.controller.stream_logger import (
        LogStreamManager,
    )

    class _Job:
        metadata = {
            "trace_id": "abcdef0123456789" * 2,
            "attempt_history": [{"attempt": 1}],
        }

    mgr = LogStreamManager.__new__(LogStreamManager)
    mgr._gate_open = True
    mgr._prefix = ""
    mgr.search_string = ""
    mgr._set_prefix(_Job())
    assert mgr._filter("loss 0.5") == "[abcdef01#a2] loss 0.5"
    # jobs from before the observability layer stream unprefixed
    mgr2 = LogStreamManager.__new__(LogStreamManager)
    mgr2._gate_open = True
    mgr2._prefix = ""
    mgr2.search_string = ""

    class _Legacy:
        metadata = {}

    mgr2._set_prefix(_Legacy())
    assert mgr2._filter("plain line") == "plain line"


def test_stream_logger_prefix_tracks_retry_attempts():
    """A follow stream attached during attempt 1 must label attempt 2's
    lines with #a2: the supervisor resubmits into the SAME log stream, so
    the prefix is re-resolved on the poll cadence, not frozen at start."""
    from finetune_controller_tpu.controller.stream_logger import (
        LogStreamManager,
    )

    class _Ws:
        closed = False

        def __init__(self):
            self.sent = []

        async def send_str(self, text):
            self.sent.append(text)

    class _Job:
        status = DatabaseStatus.RUNNING
        queue_position = None
        metadata = {
            "trace_id": "abcdef0123456789" * 2,
            "attempt_history": [],
        }

    class _State:
        async def get_job(self, job_id):
            return _Job()

    class _Backend:
        async def read_logs(self, job_id, follow=True, last_lines=None):
            async def gen():
                yield "attempt one line"
                # the retry lands: one more failure in the history
                _Job.metadata = {
                    **_Job.metadata,
                    "attempt_history": [{"attempt": 1}],
                }
                yield "attempt two line"

            return gen()

    ws = _Ws()
    mgr = LogStreamManager(
        ws, "j-1", _State(), _Backend(), follow=True, start_poll_s=0.0,
    )
    run(mgr.run())
    assert ws.sent == [
        "[abcdef01#a1] attempt one line",
        "[abcdef01#a2] attempt two line",
    ]


def test_stream_logger_prefix_refresh_stays_throttled_without_a_record():
    """A gone job record must not defeat the refresh throttle: the poll
    interval holds even when get_job keeps returning None (otherwise every
    streamed line costs a statestore query)."""
    from finetune_controller_tpu.controller.stream_logger import (
        LogStreamManager,
    )

    calls = []

    class _State:
        async def get_job(self, job_id):
            calls.append(job_id)
            return None

    mgr = LogStreamManager.__new__(LogStreamManager)
    mgr.job_id = "j-1"
    mgr.state = _State()
    mgr.start_poll_s = 60.0
    mgr._prefix = ""
    mgr._prefix_at = 0.0

    async def main():
        await mgr._refresh_prefix()  # first call: throttle expired, queries
        await mgr._refresh_prefix()  # immediately after: throttled
        await mgr._refresh_prefix()

    run(main())
    assert calls == ["j-1"]


def test_warm_spawn_scrubs_trace_env(tmp_path, monkeypatch):
    """The warm pool is replenished with the finished job's env: the dead
    job's FTC_TRACE_ID/FTC_ATTEMPT must not ride into a pooled worker (the
    next claimant injects its own identity via the request line)."""
    from finetune_controller_tpu.controller.backends.local import (
        LocalProcessBackend,
    )

    async def main():
        store = LocalObjectStore(tmp_path / "objects")
        backend = LocalProcessBackend(
            tmp_path / "sandboxes", store, _catalog(), warm_workers=1,
        )
        captured = {}

        async def fake_exec(*cmd, env=None, **kwargs):
            captured["env"] = env

            class _Proc:
                returncode = None
                pid = 4242

            return _Proc()

        monkeypatch.setattr(asyncio, "create_subprocess_exec", fake_exec)
        await backend._spawn_warm({
            "JAX_PLATFORMS": "cpu",
            "FTC_TRACE_ID": "d" * 32,
            "FTC_ATTEMPT": "3",
        })
        env = captured["env"]
        assert "FTC_TRACE_ID" not in env and "FTC_ATTEMPT" not in env
        assert env["JAX_PLATFORMS"] == "cpu"  # runtime env is preserved

    run(main())


def test_heartbeat_carries_progress_fields(tmp_path):
    from finetune_controller_tpu.resilience.heartbeat import (
        HeartbeatWriter,
        parse_heartbeat,
    )

    w = HeartbeatWriter(str(tmp_path), interval_s=0.0)
    assert w.beat(42, step_ms=123.4567)
    hb = parse_heartbeat((tmp_path / "heartbeat.json").read_bytes())
    assert hb["last_step"] == 42
    assert hb["step"] == 42  # the PR-3 field stays for old readers
    assert hb["last_step_ms"] == 123.457
    # step_ms is optional — the eval-loop beats don't carry one
    assert w.beat(43, force=True)
    hb = parse_heartbeat((tmp_path / "heartbeat.json").read_bytes())
    assert hb["last_step"] == 43
    assert "last_step_ms" not in hb
