"""DPO jobs inherit the full lifecycle unchanged (ISSUE 8 acceptance).

Mirror of ``tests/test_sched_e2e.py`` with a DPO victim: a tiny-dpo-test job
saturates a one-chip cluster, trains past its first committed checkpoint, is
preempted by a high-priority submission (SIGTERM → trainer checkpoints →
exit 143), lands in RETRYING via the resilience supervisor, RESUMES from its
checkpoint, and finishes with a step-continuous, still-rising reward-margin
trajectory.  Real subprocesses, real SIGTERMs.
"""

import asyncio
import csv
import re
import time

import pytest

from conftest import one_chip_catalog
from conftest import run_async as run

from finetune_controller_tpu.controller import registry
from finetune_controller_tpu.controller.backends.local import LocalProcessBackend
from finetune_controller_tpu.controller.examples import (
    DPOArguments,
    LoRASFTArguments,
    TinyDPOTest,
    TinyTestLoRA,
)
from finetune_controller_tpu.controller.monitor import JobMonitor
from finetune_controller_tpu.controller.objectstore import LocalObjectStore
from finetune_controller_tpu.controller.schemas import DatabaseStatus, JobInput
from finetune_controller_tpu.controller.statestore import StateStore
from finetune_controller_tpu.controller.task_builder import (
    DatasetInput,
    task_builder,
)
from finetune_controller_tpu.resilience.policy import RetryPolicy
from finetune_controller_tpu.resilience.supervisor import RetrySupervisor


def _plane(tmp_path):
    registry.reset()
    registry.load_builtin_models()
    root = tmp_path / "plane"
    state = StateStore(root / "state")
    store = LocalObjectStore(root / "objects")
    catalog = one_chip_catalog(quota=1)
    backend = LocalProcessBackend(
        root / "sandboxes", store, catalog,
        sync_interval_s=0.2, backoff_limit=0,
        sched_queues={"batch": 1.0, "prod": 4.0},
    )
    supervisor = RetrySupervisor(
        state, backend, catalog,
        policy=RetryPolicy(max_attempts=3, base_delay_s=0.2, max_delay_s=0.5,
                           seed=0),
    )
    monitor = JobMonitor(state, store, backend, interval_s=0.1,
                         supervisor=supervisor)
    return state, store, catalog, backend, supervisor, monitor


@pytest.mark.slow
def test_dpo_preemption_resumes_margin_trajectory(tmp_path):
    async def main():
        total, cadence = 40, 10
        state, store, catalog, backend, sup, monitor = _plane(tmp_path)
        await state.connect()

        dpo_args = DPOArguments(
            total_steps=total, warmup_steps=1, batch_size=2, seq_len=16,
            lora_rank=2, learning_rate=5e-3, beta=0.2,
            log_every=cadence, checkpoint_every=cadence,
        )
        await task_builder(
            JobInput(job_id="dpo-victim", user_id="u",
                     model_name="tiny-dpo-test", device="chip-1",
                     arguments=dpo_args.model_dump(),
                     queue="batch", priority="low"),
            TinyDPOTest(training_arguments=dpo_args), DatasetInput(),
            state=state, store=store, backend=backend, catalog=catalog,
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        # the task type rides the job document from submit on
        assert (await state.get_job("dpo-victim")).metadata["task"] == "dpo"

        victim = backend._handles["dpo-victim"]
        ckpt_dir = victim.artifacts_dir / "checkpoints"
        committed = re.compile(r"^step_\d+$")
        deadline = time.monotonic() + 240
        while not (ckpt_dir.is_dir()
                   and any(committed.match(p.name) for p in ckpt_dir.iterdir())):
            assert time.monotonic() < deadline, "no checkpoint within 240s"
            await asyncio.sleep(0.1)

        # high-priority SFT submission preempts the DPO job
        sft_args = LoRASFTArguments(
            total_steps=4, warmup_steps=1, batch_size=2, seq_len=16,
            lora_rank=2, log_every=2, checkpoint_every=2,
        )
        await task_builder(
            JobInput(job_id="urgent", user_id="u",
                     model_name="tiny-test-lora", device="chip-1",
                     arguments=sft_args.model_dump(),
                     queue="prod", priority="high"),
            TinyTestLoRA(training_arguments=sft_args), DatasetInput(),
            state=state, store=store, backend=backend, catalog=catalog,
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        assert backend.scheduler.preemptions_total == 1

        deadline = time.monotonic() + 300
        saw_retrying = False
        while True:
            await monitor.tick()
            vrec = await state.get_job("dpo-victim")
            saw_retrying |= vrec.status is DatabaseStatus.RETRYING
            urec = await state.get_job("urgent")
            if vrec.status.is_final and urec.status.is_final:
                break
            assert time.monotonic() < deadline, (
                vrec.status, vrec.metadata, urec.status,
            )
            await asyncio.sleep(0.05)

        assert urec.status is DatabaseStatus.SUCCEEDED, urec.metadata
        assert vrec.status is DatabaseStatus.SUCCEEDED, vrec.metadata
        assert saw_retrying
        history = vrec.metadata["attempt_history"]
        assert len(history) == 1 and history[0]["failure_class"] == "preemption"

        # resume proof: continued, not restarted
        log_text = (victim.sandbox / "logs.txt").read_text()
        assert "resumed from checkpoint step" in log_text

        # the reward-margin trajectory is step-continuous ACROSS the
        # preemption and still rising at the end
        with open(victim.artifacts_dir / "metrics.csv", newline="") as f:
            rows = list(csv.DictReader(f))
        steps = [int(float(r["step"])) for r in rows]
        assert steps == list(range(cadence, total + 1, cadence)), steps
        margins = [float(r["reward_margin"]) for r in rows]
        assert margins[-1] > margins[0], margins
        accs = [float(r["dpo_accuracy"]) for r in rows]
        assert accs[-1] >= accs[0]

        await backend.close()
        await state.close()

    run(main())
