"""HF export round-trip tests: PEFT adapters and merged checkpoints are
verified by loading them back with ``peft``/``transformers`` and comparing
logits against our own forward — the strongest possible deployability check.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from finetune_controller_tpu.models.hf_export import (
    export_lora_adapter,
    export_merged_checkpoint,
)
from finetune_controller_tpu.models.hf_import import load_llama_params
from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.models.lora import LoRAConfig

TINY = PRESETS["tiny-test"].replace(dtype=jnp.float32, lora=LoRAConfig(rank=4))


def _hf_base(tmp_path):
    import torch
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM as HFModel

    torch.manual_seed(0)
    hf_cfg = HFConfig(
        vocab_size=TINY.vocab_size, hidden_size=TINY.d_model,
        num_hidden_layers=TINY.n_layers, num_attention_heads=TINY.n_heads,
        num_key_value_heads=TINY.n_kv_heads, intermediate_size=TINY.d_ff,
        rms_norm_eps=TINY.rms_eps, rope_theta=TINY.rope_theta,
        max_position_embeddings=TINY.max_seq_len, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False,
    )
    model = HFModel(hf_cfg).eval()
    ckpt = tmp_path / "base"
    model.save_pretrained(str(ckpt), safe_serialization=True)
    return model, ckpt


def _random_lora(variables, seed=7):
    """Non-zero adapters (lora_b inits to zero → the delta would be trivial)."""
    leaves, treedef = jax.tree.flatten(variables["lora"])
    rng = np.random.default_rng(seed)
    new = [np.asarray(rng.normal(0, 0.05, l.shape), np.float32) for l in leaves]
    return jax.tree.unflatten(treedef, new)


def test_adapter_roundtrip_through_peft(tmp_path):
    torch = pytest.importorskip("torch")
    peft = pytest.importorskip("peft")
    hf_model, ckpt = _hf_base(tmp_path)

    params = load_llama_params(ckpt, TINY, dtype=jnp.float32)
    ours = LlamaForCausalLM(TINY)
    init_vars = ours.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(init_vars)

    adapter_dir = export_lora_adapter(
        TINY, lora, tmp_path / "adapter", base_model_name=str(ckpt)
    )

    peft_model = peft.PeftModel.from_pretrained(hf_model, str(adapter_dir)).eval()
    tokens = np.random.default_rng(0).integers(0, TINY.vocab_size, (2, 16))
    with torch.no_grad():
        ref = peft_model(torch.tensor(tokens)).logits.float().numpy()
    out = ours.apply(
        {"params": params, "lora": lora}, jnp.asarray(tokens, jnp.int32)
    )
    np.testing.assert_allclose(np.asarray(out), ref, atol=3e-4, rtol=1e-3)


def test_merged_checkpoint_roundtrip_through_transformers(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import LlamaForCausalLM as HFModel

    _, ckpt = _hf_base(tmp_path)
    params = load_llama_params(ckpt, TINY, dtype=jnp.float32)
    ours = LlamaForCausalLM(TINY)
    init_vars = ours.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(init_vars)

    merged_dir = export_merged_checkpoint(
        TINY, {"params": params, "lora": lora}, tmp_path / "merged"
    )
    reloaded = HFModel.from_pretrained(str(merged_dir)).eval()

    tokens = np.random.default_rng(1).integers(0, TINY.vocab_size, (2, 16))
    out = ours.apply(
        {"params": params, "lora": lora}, jnp.asarray(tokens, jnp.int32)
    )
    with torch.no_grad():
        ref = reloaded(torch.tensor(tokens)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=3e-4, rtol=1e-3)


def test_cli_run_ships_adapter(tmp_path):
    from finetune_controller_tpu.train import cli

    spec = {
        "job_id": "export-e2e",
        "model": {"preset": "tiny-test", "lora": {"rank": 2}},
        "training": {"mode": "lora", "total_steps": 3, "batch_size": 2,
                     "seq_len": 16, "log_every": 10, "checkpoint_every": 100,
                     "export_merged": True},
        "mesh": {"dp": 1, "fsdp": 1},
        "dataset": {"synthetic": {"task": "increment"}},
        "artifacts_dir": str(tmp_path / "artifacts"),
    }
    cli.run_job(spec)
    art = tmp_path / "artifacts"
    assert (art / "adapter" / "adapter_model.safetensors").exists()
    assert (art / "adapter" / "adapter_config.json").exists()
    assert (art / "merged" / "model.safetensors").exists()
    assert (art / "merged" / "config.json").exists()


def test_gemma_adapter_roundtrip_through_peft(tmp_path):
    """The PEFT adapter export is model-family-agnostic: a Gemma base
    (tied head, decoupled head_dim, GeGLU) round-trips through peft with
    matching logits."""
    torch = pytest.importorskip("torch")
    peft = pytest.importorskip("peft")
    from transformers import GemmaConfig, GemmaForCausalLM

    cfg = PRESETS["tiny-gemma-test"].replace(
        dtype=jnp.float32, lora=LoRAConfig(rank=4)
    )
    torch.manual_seed(0)
    hf_cfg = GemmaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        num_hidden_layers=cfg.n_layers, num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads, intermediate_size=cfg.d_ff,
        head_dim=cfg.head_dim, rms_norm_eps=cfg.rms_eps,
        rope_theta=cfg.rope_theta, max_position_embeddings=cfg.max_seq_len,
        hidden_activation="gelu_pytorch_tanh", tie_word_embeddings=True,
        attention_bias=False,
    )
    hf_model = GemmaForCausalLM(hf_cfg).eval()
    ckpt = tmp_path / "gemma-base"
    hf_model.save_pretrained(str(ckpt), safe_serialization=True)

    params = load_llama_params(ckpt, cfg, dtype=jnp.float32)
    ours = LlamaForCausalLM(cfg)
    init_vars = ours.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(init_vars)

    adapter_dir = export_lora_adapter(
        cfg, lora, tmp_path / "gemma-adapter", base_model_name=str(ckpt)
    )
    peft_model = peft.PeftModel.from_pretrained(hf_model, str(adapter_dir)).eval()
    tokens = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16))
    with torch.no_grad():
        ref = peft_model(torch.tensor(tokens)).logits.float().numpy()
    out = ours.apply(
        {"params": params, "lora": lora}, jnp.asarray(tokens, jnp.int32)
    )
    np.testing.assert_allclose(np.asarray(out), ref, atol=5e-4, rtol=1e-3)


def test_qwen2_merged_checkpoint_keeps_biases(tmp_path):
    """Merged export for a Qwen-2-family model must carry the q/k/v biases
    and declare the qwen2 architecture — silent bias loss would corrupt the
    deployed model's logits."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM, Qwen2Config, Qwen2ForCausalLM

    cfg = PRESETS["tiny-qwen-test"].replace(
        dtype=jnp.float32, lora=LoRAConfig(rank=4)
    )
    torch.manual_seed(0)
    hf_cfg = Qwen2Config(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        num_hidden_layers=cfg.n_layers, num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads, intermediate_size=cfg.d_ff,
        rms_norm_eps=cfg.rms_eps, rope_theta=cfg.rope_theta,
        max_position_embeddings=cfg.max_seq_len, tie_word_embeddings=False,
    )
    base = Qwen2ForCausalLM(hf_cfg).eval()
    ckpt = tmp_path / "qwen-base"
    base.save_pretrained(str(ckpt), safe_serialization=True)

    params = load_llama_params(ckpt, cfg, dtype=jnp.float32)
    ours = LlamaForCausalLM(cfg)
    init_vars = ours.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(init_vars)

    merged_dir = export_merged_checkpoint(
        cfg, {"params": params, "lora": lora}, tmp_path / "qwen-merged"
    )
    reloaded = AutoModelForCausalLM.from_pretrained(str(merged_dir)).eval()
    assert reloaded.config.model_type == "qwen2"

    tokens = np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 16))
    out = ours.apply(
        {"params": params, "lora": lora}, jnp.asarray(tokens, jnp.int32)
    )
    with torch.no_grad():
        ref = reloaded(torch.tensor(tokens)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=5e-4, rtol=1e-3)


def test_gemma_merged_checkpoint_roundtrip(tmp_path):
    """Round-5 (VERDICT #4): Gemma merged export — the offset-form norms,
    GeGLU, embed scaling and tied head ride the exported config; transformers'
    GemmaForCausalLM reproduces our merged forward."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    cfg = PRESETS["tiny-gemma-test"].replace(
        dtype=jnp.float32, lora=LoRAConfig(rank=4)
    )
    ours = LlamaForCausalLM(cfg)
    variables = ours.init(
        {"params": jax.random.PRNGKey(4)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(variables)

    merged_dir = export_merged_checkpoint(
        cfg, {"params": variables["params"], "lora": lora},
        tmp_path / "gemma-merged",
    )
    reloaded = AutoModelForCausalLM.from_pretrained(str(merged_dir)).eval()
    assert reloaded.config.model_type == "gemma"

    tokens = np.random.default_rng(5).integers(0, cfg.vocab_size, (2, 16))
    out = ours.apply(
        {"params": variables["params"], "lora": lora},
        jnp.asarray(tokens, jnp.int32),
    )
    with torch.no_grad():
        ref = reloaded(torch.tensor(tokens)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=5e-4, rtol=1e-3)


def test_partial_gemma_semantics_still_refuse(tmp_path):
    """A hybrid config (embed scaling without the rest) matches no HF
    architecture — the exporter must refuse before writing any file."""
    cfg = TINY.replace(embed_scale=True)
    with pytest.raises(NotImplementedError, match="adapter"):
        export_merged_checkpoint(cfg, {"params": {}}, tmp_path / "nope")
    assert not (tmp_path / "nope").exists()


def test_mixtral_merged_checkpoint_roundtrip(tmp_path):
    """Round-5 (VERDICT #4): MoE merged export — stacked experts unstack to
    per-expert w1/w2/w3, the router exports as gate, attention LoRA merges;
    transformers' MixtralForCausalLM reproduces our forward (dropless
    capacity so our static-capacity routing matches HF's per-token top-k)."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    cfg = PRESETS["tiny-moe-test"].replace(
        dtype=jnp.float32, lora=LoRAConfig(rank=4),
        capacity_factor=float(PRESETS["tiny-moe-test"].n_experts),
    )
    ours = LlamaForCausalLM(cfg)
    variables = ours.init(
        {"params": jax.random.PRNGKey(6)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(variables)

    merged_dir = export_merged_checkpoint(
        cfg, {"params": variables["params"], "lora": lora},
        tmp_path / "moe-merged",
    )
    reloaded = AutoModelForCausalLM.from_pretrained(str(merged_dir)).eval()
    assert reloaded.config.model_type == "mixtral"
    assert reloaded.config.num_local_experts == cfg.n_experts
    assert reloaded.config.num_experts_per_tok == cfg.moe_top_k

    tokens = np.random.default_rng(7).integers(0, cfg.vocab_size, (2, 16))
    out, _ = ours.apply(
        {"params": variables["params"], "lora": lora},
        jnp.asarray(tokens, jnp.int32), mutable=("moe_aux",),
    )
    with torch.no_grad():
        ref = reloaded(torch.tensor(tokens)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-3, rtol=1e-2)


def test_mixtral_int4_experts_merged_export(tmp_path):
    """MoE-QLoRA: int4-packed expert stacks dequantize on export; the written
    tensors equal the dequantized stacks our forward computes with."""
    from safetensors.numpy import load_file

    from finetune_controller_tpu.models.quant import dequantize_int4

    cfg = PRESETS["tiny-moe-test"].replace(
        dtype=jnp.float32, lora=LoRAConfig(rank=2), quantize_base=True,
    )
    ours = LlamaForCausalLM(cfg)
    variables = ours.init(
        {"params": jax.random.PRNGKey(8)}, jnp.zeros((1, 8), jnp.int32)
    )
    merged_dir = export_merged_checkpoint(
        cfg, {"params": variables["params"], "lora": variables["lora"]},
        tmp_path / "moe-int4-merged",
    )
    tensors = load_file(str(merged_dir / "model.safetensors"))
    moe = variables["params"]["blocks"]["block"]["moe"]
    want = np.asarray(dequantize_int4(
        moe["experts_gate_packed"][0][1], moe["experts_gate_scales"][0][1],
        dtype=jnp.float32,
    )).T
    got = tensors["model.layers.0.block_sparse_moe.experts.1.w1.weight"]
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_multihost_merged_export_reloads_base(tmp_path, monkeypatch):
    """Round-5 (VERDICT #4): on a multi-host mesh the frozen base is never
    gathered cross-host — rank 0 reloads it from the job's pretrained dir
    and merges the (already-gathered) adapter into it."""
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    ours = LlamaForCausalLM(TINY)
    base_vars = ours.init(
        {"params": jax.random.PRNGKey(9)}, jnp.zeros((1, 8), jnp.int32)
    )
    base_dir = export_merged_checkpoint(
        TINY, {"params": base_vars["params"]}, tmp_path / "base"
    )

    tcfg = TrainConfig(mode="lora", batch_size=2, seq_len=16, total_steps=1,
                       export_merged=True)
    tr = Trainer(TINY, tcfg)
    state = tr.init_state()
    state = tr.load_pretrained(state, str(base_dir))
    state = state.replace(trainable=_random_lora({"lora": state.trainable}))

    # simulate the 2-host view: process_count lies; the collective gather is
    # replaced by the single-host equivalent (the adapter IS addressable
    # here — what the fake must preserve is the code path that skips
    # gathering the frozen base and reloads it from disk instead)
    monkeypatch.setattr(
        Trainer, "state_to_host",
        lambda self, st, fields=("step", "trainable", "opt_state"): {
            f: jax.tree.map(lambda x: np.asarray(x), getattr(st, f))
            for f in fields
        },
    )
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    try:
        tr.export_artifacts(
            state, str(tmp_path / "art"), pretrained_dir=str(base_dir)
        )
    finally:
        monkeypatch.undo()

    from safetensors.numpy import load_file

    merged = load_file(str(tmp_path / "art" / "merged" / "model.safetensors"))
    base = load_file(str(base_dir / "model.safetensors"))
    lora = state.trainable["blocks"]["block"]["attn"]["q_proj"]
    scale = TINY.lora.alpha / TINY.lora.rank
    want = base["model.layers.0.self_attn.q_proj.weight"].T + scale * (
        np.asarray(lora["lora_a"][0]) @ np.asarray(lora["lora_b"][0])
    )
    got = merged["model.layers.0.self_attn.q_proj.weight"].T
    np.testing.assert_allclose(got, want, atol=1e-5)
    # the adapter shipped too (every LoRA run exports one)
    assert (tmp_path / "art" / "adapter" / "adapter_model.safetensors").exists()


def test_rope_scaled_merged_export_roundtrip(tmp_path):
    """A llama3-rope-scaled config exports its rope_scaling block, and the
    reloaded transformers model reproduces our scaled forward — proving the
    exported config.json reconstructs the same frequency schedule."""
    torch = pytest.importorskip("torch")
    import json as _json

    from transformers import LlamaForCausalLM as HFModel

    cfg = TINY.replace(
        tie_embeddings=True, rope_scaling_factor=8.0,
        rope_scaling_original_max_len=16, max_seq_len=128,
    )
    ours = LlamaForCausalLM(cfg)
    variables = ours.init(
        {"params": jax.random.PRNGKey(2)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(variables)

    merged_dir = export_merged_checkpoint(
        cfg, {"params": variables["params"], "lora": lora}, tmp_path / "m32"
    )
    written = _json.loads((merged_dir / "config.json").read_text())
    assert written["rope_scaling"] == {
        "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
        "high_freq_factor": 4.0, "original_max_position_embeddings": 16,
    }

    reloaded = HFModel.from_pretrained(str(merged_dir)).eval()
    tokens = np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 48))
    out = ours.apply(
        {"params": variables["params"], "lora": lora},
        jnp.asarray(tokens, jnp.int32),
    )
    with torch.no_grad():
        ref = reloaded(torch.tensor(tokens)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=3e-4, rtol=1e-3)
