"""HF export round-trip tests: PEFT adapters and merged checkpoints are
verified by loading them back with ``peft``/``transformers`` and comparing
logits against our own forward — the strongest possible deployability check.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from finetune_controller_tpu.models.hf_export import (
    export_lora_adapter,
    export_merged_checkpoint,
)
from finetune_controller_tpu.models.hf_import import load_llama_params
from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.models.lora import LoRAConfig

TINY = PRESETS["tiny-test"].replace(dtype=jnp.float32, lora=LoRAConfig(rank=4))


def _hf_base(tmp_path):
    import torch
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM as HFModel

    torch.manual_seed(0)
    hf_cfg = HFConfig(
        vocab_size=TINY.vocab_size, hidden_size=TINY.d_model,
        num_hidden_layers=TINY.n_layers, num_attention_heads=TINY.n_heads,
        num_key_value_heads=TINY.n_kv_heads, intermediate_size=TINY.d_ff,
        rms_norm_eps=TINY.rms_eps, rope_theta=TINY.rope_theta,
        max_position_embeddings=TINY.max_seq_len, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False,
    )
    model = HFModel(hf_cfg).eval()
    ckpt = tmp_path / "base"
    model.save_pretrained(str(ckpt), safe_serialization=True)
    return model, ckpt


def _random_lora(variables, seed=7):
    """Non-zero adapters (lora_b inits to zero → the delta would be trivial)."""
    leaves, treedef = jax.tree.flatten(variables["lora"])
    rng = np.random.default_rng(seed)
    new = [np.asarray(rng.normal(0, 0.05, l.shape), np.float32) for l in leaves]
    return jax.tree.unflatten(treedef, new)


def test_adapter_roundtrip_through_peft(tmp_path):
    torch = pytest.importorskip("torch")
    peft = pytest.importorskip("peft")
    hf_model, ckpt = _hf_base(tmp_path)

    params = load_llama_params(ckpt, TINY, dtype=jnp.float32)
    ours = LlamaForCausalLM(TINY)
    init_vars = ours.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(init_vars)

    adapter_dir = export_lora_adapter(
        TINY, lora, tmp_path / "adapter", base_model_name=str(ckpt)
    )

    peft_model = peft.PeftModel.from_pretrained(hf_model, str(adapter_dir)).eval()
    tokens = np.random.default_rng(0).integers(0, TINY.vocab_size, (2, 16))
    with torch.no_grad():
        ref = peft_model(torch.tensor(tokens)).logits.float().numpy()
    out = ours.apply(
        {"params": params, "lora": lora}, jnp.asarray(tokens, jnp.int32)
    )
    np.testing.assert_allclose(np.asarray(out), ref, atol=3e-4, rtol=1e-3)


def test_merged_checkpoint_roundtrip_through_transformers(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import LlamaForCausalLM as HFModel

    _, ckpt = _hf_base(tmp_path)
    params = load_llama_params(ckpt, TINY, dtype=jnp.float32)
    ours = LlamaForCausalLM(TINY)
    init_vars = ours.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(init_vars)

    merged_dir = export_merged_checkpoint(
        TINY, {"params": params, "lora": lora}, tmp_path / "merged"
    )
    reloaded = HFModel.from_pretrained(str(merged_dir)).eval()

    tokens = np.random.default_rng(1).integers(0, TINY.vocab_size, (2, 16))
    out = ours.apply(
        {"params": params, "lora": lora}, jnp.asarray(tokens, jnp.int32)
    )
    with torch.no_grad():
        ref = reloaded(torch.tensor(tokens)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=3e-4, rtol=1e-3)


def test_cli_run_ships_adapter(tmp_path):
    from finetune_controller_tpu.train import cli

    spec = {
        "job_id": "export-e2e",
        "model": {"preset": "tiny-test", "lora": {"rank": 2}},
        "training": {"mode": "lora", "total_steps": 3, "batch_size": 2,
                     "seq_len": 16, "log_every": 10, "checkpoint_every": 100,
                     "export_merged": True},
        "mesh": {"dp": 1, "fsdp": 1},
        "dataset": {"synthetic": {"task": "increment"}},
        "artifacts_dir": str(tmp_path / "artifacts"),
    }
    cli.run_job(spec)
    art = tmp_path / "artifacts"
    assert (art / "adapter" / "adapter_model.safetensors").exists()
    assert (art / "adapter" / "adapter_config.json").exists()
    assert (art / "merged" / "model.safetensors").exists()
    assert (art / "merged" / "config.json").exists()


def test_gemma_adapter_roundtrip_through_peft(tmp_path):
    """The PEFT adapter export is model-family-agnostic: a Gemma base
    (tied head, decoupled head_dim, GeGLU) round-trips through peft with
    matching logits."""
    torch = pytest.importorskip("torch")
    peft = pytest.importorskip("peft")
    from transformers import GemmaConfig, GemmaForCausalLM

    cfg = PRESETS["tiny-gemma-test"].replace(
        dtype=jnp.float32, lora=LoRAConfig(rank=4)
    )
    torch.manual_seed(0)
    hf_cfg = GemmaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        num_hidden_layers=cfg.n_layers, num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads, intermediate_size=cfg.d_ff,
        head_dim=cfg.head_dim, rms_norm_eps=cfg.rms_eps,
        rope_theta=cfg.rope_theta, max_position_embeddings=cfg.max_seq_len,
        hidden_activation="gelu_pytorch_tanh", tie_word_embeddings=True,
        attention_bias=False,
    )
    hf_model = GemmaForCausalLM(hf_cfg).eval()
    ckpt = tmp_path / "gemma-base"
    hf_model.save_pretrained(str(ckpt), safe_serialization=True)

    params = load_llama_params(ckpt, cfg, dtype=jnp.float32)
    ours = LlamaForCausalLM(cfg)
    init_vars = ours.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(init_vars)

    adapter_dir = export_lora_adapter(
        cfg, lora, tmp_path / "gemma-adapter", base_model_name=str(ckpt)
    )
    peft_model = peft.PeftModel.from_pretrained(hf_model, str(adapter_dir)).eval()
    tokens = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16))
    with torch.no_grad():
        ref = peft_model(torch.tensor(tokens)).logits.float().numpy()
    out = ours.apply(
        {"params": params, "lora": lora}, jnp.asarray(tokens, jnp.int32)
    )
    np.testing.assert_allclose(np.asarray(out), ref, atol=5e-4, rtol=1e-3)


def test_qwen2_merged_checkpoint_keeps_biases(tmp_path):
    """Merged export for a Qwen-2-family model must carry the q/k/v biases
    and declare the qwen2 architecture — silent bias loss would corrupt the
    deployed model's logits."""
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM, Qwen2Config, Qwen2ForCausalLM

    cfg = PRESETS["tiny-qwen-test"].replace(
        dtype=jnp.float32, lora=LoRAConfig(rank=4)
    )
    torch.manual_seed(0)
    hf_cfg = Qwen2Config(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        num_hidden_layers=cfg.n_layers, num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads, intermediate_size=cfg.d_ff,
        rms_norm_eps=cfg.rms_eps, rope_theta=cfg.rope_theta,
        max_position_embeddings=cfg.max_seq_len, tie_word_embeddings=False,
    )
    base = Qwen2ForCausalLM(hf_cfg).eval()
    ckpt = tmp_path / "qwen-base"
    base.save_pretrained(str(ckpt), safe_serialization=True)

    params = load_llama_params(ckpt, cfg, dtype=jnp.float32)
    ours = LlamaForCausalLM(cfg)
    init_vars = ours.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(init_vars)

    merged_dir = export_merged_checkpoint(
        cfg, {"params": params, "lora": lora}, tmp_path / "qwen-merged"
    )
    reloaded = AutoModelForCausalLM.from_pretrained(str(merged_dir)).eval()
    assert reloaded.config.model_type == "qwen2"

    tokens = np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 16))
    out = ours.apply(
        {"params": params, "lora": lora}, jnp.asarray(tokens, jnp.int32)
    )
    with torch.no_grad():
        ref = reloaded(torch.tensor(tokens)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=5e-4, rtol=1e-3)


def test_gemma_merged_export_refuses(tmp_path):
    """Gemma semantics have no Llama-config encoding — merged export must
    refuse loudly, not emit a checkpoint transformers evaluates differently."""
    cfg = PRESETS["tiny-gemma-test"].replace(
        dtype=jnp.float32, lora=LoRAConfig(rank=2)
    )
    ours = LlamaForCausalLM(cfg)
    variables = ours.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32)
    )
    with pytest.raises(NotImplementedError, match="adapter"):
        export_merged_checkpoint(cfg, variables, tmp_path / "nope")


def test_rope_scaled_merged_export_roundtrip(tmp_path):
    """A llama3-rope-scaled config exports its rope_scaling block, and the
    reloaded transformers model reproduces our scaled forward — proving the
    exported config.json reconstructs the same frequency schedule."""
    torch = pytest.importorskip("torch")
    import json as _json

    from transformers import LlamaForCausalLM as HFModel

    cfg = TINY.replace(
        tie_embeddings=True, rope_scaling_factor=8.0,
        rope_scaling_original_max_len=16, max_seq_len=128,
    )
    ours = LlamaForCausalLM(cfg)
    variables = ours.init(
        {"params": jax.random.PRNGKey(2)}, jnp.zeros((1, 8), jnp.int32)
    )
    lora = _random_lora(variables)

    merged_dir = export_merged_checkpoint(
        cfg, {"params": variables["params"], "lora": lora}, tmp_path / "m32"
    )
    written = _json.loads((merged_dir / "config.json").read_text())
    assert written["rope_scaling"] == {
        "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
        "high_freq_factor": 4.0, "original_max_position_embeddings": 16,
    }

    reloaded = HFModel.from_pretrained(str(merged_dir)).eval()
    tokens = np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 48))
    out = ours.apply(
        {"params": variables["params"], "lora": lora},
        jnp.asarray(tokens, jnp.int32),
    )
    with torch.no_grad():
        ref = reloaded(torch.tensor(tokens)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=3e-4, rtol=1e-3)
