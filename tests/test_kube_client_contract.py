"""Contract tests for ``AiohttpKubeClient`` against recorded apiserver payloads.

The hand-rolled REST client (``backends/k8s.py``) never talks to a real
apiserver in CI; these tests pin it to the REAL payload shapes (JobSet CR,
Status error objects, pod-list envelopes, chunked log streams — recorded from
a kind cluster running the JobSet operator) and to apiserver misbehavior:
503-then-recover, 429 with ``Retry-After``, 401 token rotation, 409
AlreadyExists, chunked log follow.  The reference leans on the official SDKs
for all of this (``app/utils/kube_config.py:22-23``); our client must prove
its own discipline.
"""

from __future__ import annotations

import asyncio
import json

import pytest
from aiohttp import web

from finetune_controller_tpu.controller.backends.base import BackendError
from finetune_controller_tpu.controller.backends.k8s import AiohttpKubeClient

from conftest import run_async

JOBSET_PATH = "/apis/jobset.x-k8s.io/v1alpha2/namespaces/default/jobsets"

#: recorded JobSet object as the apiserver returns it (server-populated
#: metadata + status the deployer's state mapping consumes)
JOBSET_OBJ = {
    "apiVersion": "jobset.x-k8s.io/v1alpha2",
    "kind": "JobSet",
    "metadata": {
        "name": "tiny-abc123",
        "namespace": "default",
        "uid": "f0e95d62-9d3c-4fd9-a1f2-3c7b8ee01f55",
        "resourceVersion": "123456",
        "creationTimestamp": "2026-07-30T12:00:00Z",
        "labels": {"ftc/job-id": "tiny-abc123"},
    },
    "spec": {
        "suspend": False,
        "replicatedJobs": [{
            "name": "workers",
            "replicas": 1,
            "template": {"spec": {"parallelism": 2, "completions": 2}},
        }],
    },
    "status": {
        "conditions": [{
            "type": "Completed",
            "status": "True",
            "reason": "AllJobsCompleted",
            "message": "jobset completed successfully",
            "lastTransitionTime": "2026-07-30T12:10:00Z",
        }],
        "restarts": 0,
    },
}

#: recorded apiserver Status error body (the standard error envelope)
STATUS_409 = {
    "kind": "Status", "apiVersion": "v1", "status": "Failure",
    "reason": "AlreadyExists",
    "message": 'jobsets.jobset.x-k8s.io "tiny-abc123" already exists',
    "code": 409,
}

POD_LIST = {
    "kind": "PodList", "apiVersion": "v1",
    "metadata": {"resourceVersion": "123999"},
    "items": [{
        "metadata": {
            "name": "tiny-abc123-workers-0-0-abcde",
            "labels": {"jobset.sigs.k8s.io/jobset-name": "tiny-abc123"},
        },
        "status": {"phase": "Running"},
    }],
}


class _FakeApiServer:
    """Scriptable apiserver: each (method, path) pops a queued behavior."""

    def __init__(self):
        self.calls: list[tuple[str, str, dict]] = []
        self.script: list[web.Response | None] = []  # None = serve normally
        self.auth_required: str | None = None

    def _next_scripted(self):
        return self.script.pop(0) if self.script else None

    async def handle(self, request: web.Request) -> web.StreamResponse:
        body = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:
                body = {}
        self.calls.append((request.method, request.path, body))
        if self.auth_required is not None:
            if request.headers.get("Authorization") != f"Bearer {self.auth_required}":
                return web.json_response(
                    {"kind": "Status", "code": 401, "reason": "Unauthorized"},
                    status=401,
                )
        scripted = self._next_scripted()
        if scripted is not None:
            return scripted
        # default happy-path routing
        if request.method == "POST" and request.path == JOBSET_PATH:
            return web.json_response(JOBSET_OBJ, status=201)
        if request.method == "GET" and request.path == f"{JOBSET_PATH}/tiny-abc123":
            return web.json_response(JOBSET_OBJ)
        if request.method == "GET" and request.path.endswith("/pods"):
            return web.json_response(POD_LIST)
        if request.method == "DELETE":
            return web.json_response({"kind": "Status", "status": "Success"})
        if request.path.endswith("/log"):
            resp = web.StreamResponse()
            resp.content_type = "text/plain"
            await resp.prepare(request)
            for line in (b"step 1 loss 5.9\n", b"step 2 loss 5.1\n"):
                await resp.write(line)
            await resp.write_eof()
            return resp
        return web.json_response(
            {"kind": "Status", "code": 404, "reason": "NotFound"}, status=404
        )


async def _serve(fake: _FakeApiServer):
    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", fake.handle)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


def _fast_client(base_url: str, token: str | None = "t0") -> AiohttpKubeClient:
    client = AiohttpKubeClient(base_url=base_url, token=token)
    client.BASE_DELAY_S = 0.01  # keep retry backoff test-fast
    return client


def test_create_get_list_delete_roundtrip():
    async def main():
        fake = _FakeApiServer()
        runner, url = await _serve(fake)
        client = _fast_client(url)
        try:
            created = await client.create(JOBSET_PATH, {
                "apiVersion": "jobset.x-k8s.io/v1alpha2", "kind": "JobSet",
                "metadata": {"name": "tiny-abc123", "namespace": "default"},
            })
            assert created["metadata"]["uid"]  # server-populated fields parsed
            got = await client.get(JOBSET_PATH, "tiny-abc123")
            assert got["status"]["conditions"][0]["type"] == "Completed"
            assert await client.get(JOBSET_PATH, "missing") is None  # 404→None
            pods = await client.list(
                "/api/v1/namespaces/default/pods",
                label_selector="jobset.sigs.k8s.io/jobset-name=tiny-abc123",
            )
            assert pods[0]["status"]["phase"] == "Running"
            assert await client.delete(JOBSET_PATH, "tiny-abc123") is True
        finally:
            await client.close()
            await runner.cleanup()

    run_async(main())


def test_retry_on_503_then_success():
    async def main():
        fake = _FakeApiServer()
        fake.script = [
            web.json_response({"kind": "Status", "code": 503}, status=503),
            web.json_response({"kind": "Status", "code": 503}, status=503),
        ]
        runner, url = await _serve(fake)
        client = _fast_client(url)
        try:
            got = await client.get(JOBSET_PATH, "tiny-abc123")
            assert got["metadata"]["name"] == "tiny-abc123"
            assert len(fake.calls) == 3  # 2 failures + 1 success
        finally:
            await client.close()
            await runner.cleanup()

    run_async(main())


def test_retry_429_honors_retry_after():
    async def main():
        fake = _FakeApiServer()
        fake.script = [
            web.json_response(
                {"kind": "Status", "code": 429}, status=429,
                headers={"Retry-After": "0.05"},
            ),
        ]
        runner, url = await _serve(fake)
        client = _fast_client(url)
        try:
            t0 = asyncio.get_event_loop().time()
            got = await client.get(JOBSET_PATH, "tiny-abc123")
            assert got is not None
            assert asyncio.get_event_loop().time() - t0 >= 0.05
        finally:
            await client.close()
            await runner.cleanup()

    run_async(main())


def test_401_rereads_rotated_token(tmp_path):
    async def main():
        fake = _FakeApiServer()
        fake.auth_required = "fresh-token"
        runner, url = await _serve(fake)
        client = AiohttpKubeClient(base_url=url, token=None)
        client.BASE_DELAY_S = 0.01
        # projected SA dir with a rotated token on disk
        (tmp_path / "token").write_text("fresh-token\n")
        client.SA_DIR = tmp_path
        client._token = "stale-token"  # cached pre-rotation token
        client._token_read_at = 1e18   # cache looks fresh; only 401 invalidates
        try:
            got = await client.get(JOBSET_PATH, "tiny-abc123")
            assert got["metadata"]["name"] == "tiny-abc123"
            # first call was rejected with the stale token, retry used the
            # re-read one
            assert len(fake.calls) == 2
        finally:
            await client.close()
            await runner.cleanup()

    run_async(main())


def test_create_409_adopts_existing():
    async def main():
        fake = _FakeApiServer()
        fake.script = [web.json_response(STATUS_409, status=409)]
        runner, url = await _serve(fake)
        client = _fast_client(url)
        try:
            created = await client.create(JOBSET_PATH, {
                "metadata": {"name": "tiny-abc123", "namespace": "default"},
            })
            # adopted the live object instead of failing the resubmit
            assert created["metadata"]["uid"] == JOBSET_OBJ["metadata"]["uid"]
        finally:
            await client.close()
            await runner.cleanup()

    run_async(main())


def test_terminal_error_raises_with_status_body():
    async def main():
        fake = _FakeApiServer()
        fake.script = [web.json_response(
            {"kind": "Status", "code": 403, "reason": "Forbidden",
             "message": "jobsets is forbidden"}, status=403,
        )]
        runner, url = await _serve(fake)
        client = _fast_client(url)
        try:
            with pytest.raises(BackendError) as ei:
                await client.get(JOBSET_PATH, "tiny-abc123")
            assert "403" in str(ei.value)
            assert len(fake.calls) == 1  # terminal: no retry burn
        finally:
            await client.close()
            await runner.cleanup()

    run_async(main())


def test_pod_log_follow_stream():
    async def main():
        fake = _FakeApiServer()
        runner, url = await _serve(fake)
        client = _fast_client(url)
        try:
            lines = []
            aiter = await client.pod_log_lines(
                "default", "tiny-abc123-workers-0-0-abcde",
                container="trainer", follow=True, tail_lines=10,
            )
            async for line in aiter:
                lines.append(line)
            assert lines == ["step 1 loss 5.9", "step 2 loss 5.1"]
            method, path, _ = fake.calls[-1]
            assert path.endswith("/pods/tiny-abc123-workers-0-0-abcde/log")
        finally:
            await client.close()
            await runner.cleanup()

    run_async(main())
