"""API-server tests: routes, auth, validation, lifecycle over HTTP/WS.

Covers the capability surface of the reference's ``app/main.py`` route table
(SURVEY.md §2 component 1) + middleware (component 20) + OpenAPI customization
(component 21) + WS log streaming (§3.3), all against the in-repo fake
cluster — no network, no external services.
"""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from conftest import one_chip_catalog, run_async
from finetune_controller_tpu.controller import registry
from finetune_controller_tpu.controller.backends.local import LocalProcessBackend
from finetune_controller_tpu.controller.config import Settings
from finetune_controller_tpu.controller.devices import default_catalog
from finetune_controller_tpu.controller.monitor import JobMonitor
from finetune_controller_tpu.controller.objectstore import LocalObjectStore, Presigner
from finetune_controller_tpu.controller.runtime import Runtime
from finetune_controller_tpu.controller.schemas import DatabaseStatus
from finetune_controller_tpu.controller.security import dev_generate_token
from finetune_controller_tpu.controller.server import build_app
from finetune_controller_tpu.controller.statestore import StateStore


def _runtime(tmp_path, *, auth_enabled=False, monitor_interval=0.1):
    settings = Settings(
        auth_enabled=auth_enabled,
        state_dir=str(tmp_path / "state"),
        object_store_root=str(tmp_path / "objects"),
        job_monitor_interval_s=monitor_interval,
        artifact_sync_interval_s=0.2,
        rate_limit_submit_per_min=1000,
        rate_limit_read_per_min=1000,
        rate_limit_promote_per_min=1000,
    )
    registry.reset()
    registry.load_builtin_models()
    state = StateStore(settings.state_path)
    store = LocalObjectStore(settings.object_store_path)
    catalog = one_chip_catalog(quota=2)
    backend = LocalProcessBackend(
        settings.state_path / "sandboxes", store, catalog, sync_interval_s=0.2
    )
    monitor = JobMonitor(state, store, backend, interval_s=monitor_interval)
    return Runtime(
        settings=settings, state=state, store=store, catalog=catalog,
        backend=backend, monitor=monitor,
        presigner=Presigner(settings.presign_secret),
    )


async def _client(runtime, **app_kw):
    app = build_app(runtime, **app_kw)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


SUBMIT_BODY = {
    "model_name": "tiny-test-lora",
    "device": "chip-1",
    "arguments": {"total_steps": 3, "warmup_steps": 1, "batch_size": 2,
                  "seq_len": 16, "lora_rank": 2},
}


async def _wait_final(client, job_id, timeout=120.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        r = await client.get(f"/api/v1/jobs/{job_id}")
        job = await r.json()
        if job["status"] in ("succeeded", "failed", "cancelled"):
            return job
        assert asyncio.get_event_loop().time() < deadline, job
        await asyncio.sleep(0.3)


# ---------------------------------------------------------------------------
# Models & schema
# ---------------------------------------------------------------------------


def test_models_and_schema_routes(tmp_path):
    async def main():
        client = await _client(_runtime(tmp_path), with_monitor=False)
        r = await client.get("/api/v1/models")
        assert r.status == 200
        models = {m["name"] for m in (await r.json())["models"]}
        assert "tiny-test-lora" in models and "llama3-8b-lora" in models

        r = await client.get("/api/v1/models/tiny-test-lora/schema")
        body = await r.json()
        assert body["arguments_schema"]["properties"]["learning_rate"]["description"]
        assert body["default_device"] == "cpu-test"

        r = await client.get("/api/v1/models/nope/schema")
        assert r.status == 404
        await client.close()

    run_async(main())


def test_openapi_has_bearer_security(tmp_path):
    async def main():
        client = await _client(_runtime(tmp_path), with_monitor=False)
        r = await client.get("/api/v1/openapi.json")
        doc = await r.json()
        assert "BearerAuth" in doc["components"]["securitySchemes"]
        post_jobs = doc["paths"]["/api/v1/jobs"]["post"]
        assert post_jobs["security"] == [{"BearerAuth": []}]
        await client.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Auth
# ---------------------------------------------------------------------------


def test_auth_required_and_token_flow(tmp_path):
    async def main():
        rt = _runtime(tmp_path, auth_enabled=True)
        client = await _client(rt, with_monitor=False)
        # health is open
        assert (await client.get("/api/v1/health")).status == 200
        # everything else is 401 without a token
        assert (await client.get("/api/v1/jobs")).status == 401
        r = await client.get(
            "/api/v1/jobs", headers={"Authorization": "Bearer garbage"}
        )
        assert r.status == 401
        # dev token mint → authorized
        r = await client.post("/api/v1/auth/dev-token", json={"user_id": "alice"})
        token = (await r.json())["access_token"]
        r = await client.get(
            "/api/v1/jobs", headers={"Authorization": f"Bearer {token}"}
        )
        assert r.status == 200
        await client.close()

    run_async(main())


def test_entitlements_restrict_models(tmp_path):
    async def main():
        rt = _runtime(tmp_path, auth_enabled=True)
        client = await _client(rt, with_monitor=False)
        token = dev_generate_token(
            "bob", rt.settings.jwt_secret, scopes=["llama3-8b-lora"]
        )
        hdr = {"Authorization": f"Bearer {token}"}
        r = await client.get("/api/v1/models", headers=hdr)
        names = {m["name"] for m in (await r.json())["models"]}
        assert names == {"llama3-8b-lora"}
        r = await client.post("/api/v1/jobs", json=SUBMIT_BODY, headers=hdr)
        assert r.status == 403
        await client.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Submission validation
# ---------------------------------------------------------------------------


def test_submit_validation_errors(tmp_path):
    async def main():
        client = await _client(_runtime(tmp_path), with_monitor=False)
        r = await client.post("/api/v1/jobs", json={})
        assert r.status == 400

        r = await client.post("/api/v1/jobs", json={"model_name": "ghost"})
        assert r.status == 404

        bad = dict(SUBMIT_BODY, arguments={"learning_rate": -5})
        r = await client.post("/api/v1/jobs", json=bad)
        assert r.status == 400
        detail = (await r.json())["detail"]
        assert any("learning_rate" in e["field"] for e in detail)

        bad = dict(SUBMIT_BODY, arguments={"nonsense_knob": 1})
        r = await client.post("/api/v1/jobs", json=bad)
        assert r.status == 400

        bad = dict(SUBMIT_BODY, device="h100")  # not a TPU flavor
        r = await client.post("/api/v1/jobs", json=bad)
        assert r.status == 400

        bad = dict(SUBMIT_BODY, task="classification")
        r = await client.post("/api/v1/jobs", json=bad)
        assert r.status == 400

        # an UNKNOWN task value 400s naming the known tasks (ISSUE 8
        # satellite — previously any string passed the cross-check)
        bad = dict(SUBMIT_BODY, task="reinforcement")
        r = await client.post("/api/v1/jobs", json=bad)
        assert r.status == 400
        detail = (await r.json())["detail"]
        assert "known tasks" in detail and "dpo" in detail and "rlhf" in detail

        # unknown top-level field rejected, not silently defaulted — a typo'd
        # "training_arguments" must not train 100 default steps
        bad = {"model_name": "tiny-test-lora",
               "training_arguments": SUBMIT_BODY["arguments"]}
        r = await client.post("/api/v1/jobs", json=bad)
        assert r.status == 400
        assert "training_arguments" in (await r.json())["detail"]
        await client.close()

    run_async(main())


def test_submit_queue_priority_and_admin_scheduler(tmp_path):
    """Per-job tenant queue + priority (docs/scheduling.md): validated at
    submit, persisted crash-safe in job metadata, and visible through
    ``GET /admin/scheduler``."""

    async def main():
        client = await _client(_runtime(tmp_path), with_monitor=False)

        bad = dict(SUBMIT_BODY, priority="urgent")
        r = await client.post("/api/v1/jobs", json=bad)
        assert r.status == 400
        assert "priority" in (await r.json())["detail"]

        bad = dict(SUBMIT_BODY, num_slices=99)  # beyond the flavor quota
        r = await client.post("/api/v1/jobs", json=bad)
        assert r.status == 400
        assert "quota" in (await r.json())["detail"]

        good = dict(SUBMIT_BODY, queue="prod", priority="high")
        r = await client.post("/api/v1/jobs", json=good)
        assert r.status == 200, await r.text()
        job_id = (await r.json())["job_id"]
        job = await (await client.get(f"/api/v1/jobs/{job_id}")).json()
        assert job["metadata"]["queue"] == "prod"
        assert job["metadata"]["priority"] == "high"

        snap = await (await client.get("/api/v1/admin/scheduler")).json()
        assert snap["policy"] == "fairshare"
        assert "prod" in snap["queues"]
        q = snap["queues"]["prod"]
        assert q["running"] + q["depth"] == 1  # our job, admitted or pending
        assert "preemptions_total" in snap
        await client.close()

    run_async(main())


def test_rate_limit_429(tmp_path):
    async def main():
        rt = _runtime(tmp_path)
        rt.settings.rate_limit_submit_per_min = 2  # before build_app reads it
        client = await _client(rt, with_monitor=False)
        bad = {"model_name": "ghost"}  # fails fast after the limiter
        assert (await client.post("/api/v1/jobs", json=bad)).status == 404
        assert (await client.post("/api/v1/jobs", json=bad)).status == 404
        assert (await client.post("/api/v1/jobs", json=bad)).status == 429
        await client.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Full lifecycle over the API
# ---------------------------------------------------------------------------


def test_api_full_lifecycle(tmp_path):
    async def main():
        client = await _client(_runtime(tmp_path))  # monitor in-process
        # submit with an uploaded dataset file (multipart)
        import aiohttp

        form = aiohttp.FormData()
        form.add_field("model_name", "tiny-test-lora")
        form.add_field("device", "chip-1")
        form.add_field("arguments", json.dumps(SUBMIT_BODY["arguments"]))
        form.add_field(
            "dataset_file",
            b'{"text": "the quick brown fox jumps over the lazy dog"}\n' * 8,
            filename="train.jsonl",
            content_type="application/jsonl",
        )
        r = await client.post("/api/v1/jobs", data=form)
        assert r.status == 200, await r.text()
        job_id = (await r.json())["job_id"]
        assert job_id.startswith("tiny-test-lora-")

        # paginated table contains it
        r = await client.get("/api/v1/jobs")
        page = await r.json()
        assert page["total"] == 1 and page["items"][0]["job_id"] == job_id

        job = await _wait_final(client, job_id)
        assert job["status"] == "succeeded", job

        # metrics + presigned CSV
        r = await client.get(f"/api/v1/jobs/{job_id}/metrics")
        body = await r.json()
        assert body["records"] and "loss" in body["records"][0]
        assert body["csv_url"]
        r = await client.get(body["csv_url"])
        assert r.status == 200
        assert b"loss" in await r.read()

        # REST logs
        r = await client.get(f"/api/v1/jobs/{job_id}/logs?last_lines=5")
        assert r.status == 200

        # artifacts zip
        r = await client.get(f"/api/v1/jobs/{job_id}/artifacts")
        assert r.status == 200
        assert r.headers["Content-Type"] == "application/zip"

        # promote → completed
        r = await client.post(f"/api/v1/jobs/{job_id}/promote")
        assert r.status == 202, await r.text()
        for _ in range(100):
            await asyncio.sleep(0.1)
            r = await client.get(f"/api/v1/jobs/{job_id}")
            job = await r.json()
            if job["promotion_status"] == "completed":
                break
        assert job["promotion_status"] == "completed"
        assert job["promotion_uri"]

        # unpromote → back to not_promoted
        r = await client.post(f"/api/v1/jobs/{job_id}/unpromote")
        assert r.status == 202
        for _ in range(100):
            await asyncio.sleep(0.1)
            r = await client.get(f"/api/v1/jobs/{job_id}")
            job = await r.json()
            if job["promotion_status"] == "not_promoted":
                break
        assert job["promotion_status"] == "not_promoted"

        # delete (final job) → archived
        r = await client.delete(f"/api/v1/jobs/{job_id}")
        assert r.status == 200
        assert (await client.get(f"/api/v1/jobs/{job_id}")).status == 404
        await client.close()

    run_async(main())


def test_api_concurrent_promote_single_winner(tmp_path):
    """Two promote requests racing through the guard must spawn exactly one
    copy task (CAS in the statestore — round-1 ADVICE finding)."""

    async def main():
        client = await _client(_runtime(tmp_path))
        r = await client.post("/api/v1/jobs", json=SUBMIT_BODY)
        job_id = (await r.json())["job_id"]
        await _wait_final(client, job_id)

        r1, r2 = await asyncio.gather(
            client.post(f"/api/v1/jobs/{job_id}/promote"),
            client.post(f"/api/v1/jobs/{job_id}/promote"),
        )
        bodies = [await r1.json(), await r2.json()]
        started = [b for b in bodies if b.get("message") == "promotion started"]
        raced = [b for b in bodies if "already in progress" in b.get("detail", "")]
        assert len(started) == 1 and len(raced) == 1, bodies
        for _ in range(100):
            await asyncio.sleep(0.1)
            job = await (await client.get(f"/api/v1/jobs/{job_id}")).json()
            if job["promotion_status"] == "completed":
                break
        assert job["promotion_status"] == "completed"
        await client.close()

    run_async(main())


def test_api_cancel_and_promote_guards(tmp_path):
    async def main():
        client = await _client(_runtime(tmp_path))
        body = dict(SUBMIT_BODY)
        body["arguments"] = dict(body["arguments"], total_steps=500)
        r = await client.post("/api/v1/jobs", json=body)
        job_id = (await r.json())["job_id"]

        # cannot promote a non-final job
        r = await client.post(f"/api/v1/jobs/{job_id}/promote")
        assert r.status == 400

        # cannot delete a live job
        r = await client.delete(f"/api/v1/jobs/{job_id}")
        assert r.status == 400

        # cancel works, then a second cancel 400s
        r = await client.post(f"/api/v1/jobs/{job_id}/cancel")
        assert r.status == 200
        r = await client.get(f"/api/v1/jobs/{job_id}")
        assert (await r.json())["status"] == "cancelled"
        r = await client.post(f"/api/v1/jobs/{job_id}/cancel")
        assert r.status == 400

        # cannot promote a cancelled job
        r = await client.post(f"/api/v1/jobs/{job_id}/promote")
        assert r.status == 400
        await client.close()

    run_async(main())


def test_cors_preflight_and_headers(tmp_path):
    async def main():
        rt = _runtime(tmp_path)
        rt.settings.cors_origins = ["https://ui.example.com"]
        client = await _client(rt, with_monitor=False)

        # preflight from an allowed origin
        r = await client.options(
            "/api/v1/jobs",
            headers={
                "Origin": "https://ui.example.com",
                "Access-Control-Request-Method": "POST",
                "Access-Control-Request-Headers": "authorization",
            },
        )
        assert r.status == 204
        assert r.headers["Access-Control-Allow-Origin"] == "https://ui.example.com"
        assert "POST" in r.headers["Access-Control-Allow-Methods"]
        assert "authorization" in r.headers["Access-Control-Allow-Headers"].lower()

        # preflight from a disallowed origin is refused
        r = await client.options(
            "/api/v1/jobs",
            headers={"Origin": "https://evil.example.com",
                     "Access-Control-Request-Method": "POST"},
        )
        assert r.status == 403

        # normal responses carry the CORS header for allowed origins only
        r = await client.get("/api/v1/health",
                             headers={"Origin": "https://ui.example.com"})
        assert r.headers["Access-Control-Allow-Origin"] == "https://ui.example.com"
        r = await client.get("/api/v1/health",
                             headers={"Origin": "https://evil.example.com"})
        assert "Access-Control-Allow-Origin" not in r.headers
        await client.close()

    run_async(main())


def test_default_jwt_secret_refused_outside_local(tmp_path):
    """ADVICE r1 (medium): auth enabled + well-known default secret + no
    introspection/JWKS must refuse to start outside environment=local."""
    from finetune_controller_tpu.controller.server import build_app

    rt = _runtime(tmp_path, auth_enabled=True)
    rt.settings.environment = "production"
    with pytest.raises(RuntimeError, match="forgeable"):
        build_app(rt)
    # a real secret is accepted
    rt.settings.jwt_secret = "an-actually-configured-secret"
    build_app(rt)
    # and local keeps working with the default (warn only)
    rt2 = _runtime(tmp_path, auth_enabled=True)
    assert rt2.settings.environment == "local"
    build_app(rt2)


def test_admin_resilience_route_reports_policy_and_pending(tmp_path):
    async def main():
        from finetune_controller_tpu.resilience.heartbeat import LeaseChecker
        from finetune_controller_tpu.resilience.policy import RetryPolicy
        from finetune_controller_tpu.resilience.supervisor import RetrySupervisor

        rt = _runtime(tmp_path)
        rt.monitor.supervisor = RetrySupervisor(
            rt.state, rt.backend, rt.catalog,
            policy=RetryPolicy(max_attempts=4, base_delay_s=1.0,
                               max_delay_s=9.0, seed=0),
        )
        rt.monitor.lease = LeaseChecker(rt.store, lease_s=123.0)
        client = await _client(rt, with_monitor=False)
        body = await (await client.get("/api/v1/admin/resilience")).json()
        assert body["enabled"] is True and body["lease_enabled"] is True
        assert body["policy"] == {
            "max_attempts": 4, "base_delay_s": 1.0, "max_delay_s": 9.0,
        }
        assert body["pending_retries"] == []
        assert body["lease_s"] == 123.0
        assert body["counters"]["resubmits"] == 0
        await client.close()

    run_async(main())


def test_api_job_isolation_between_users(tmp_path):
    async def main():
        rt = _runtime(tmp_path, auth_enabled=True)
        client = await _client(rt, with_monitor=False)
        tok_a = dev_generate_token("alice", rt.settings.jwt_secret)
        tok_b = dev_generate_token("bob", rt.settings.jwt_secret)
        ha = {"Authorization": f"Bearer {tok_a}"}
        hb = {"Authorization": f"Bearer {tok_b}"}
        r = await client.post("/api/v1/jobs", json=SUBMIT_BODY, headers=ha)
        assert r.status == 200, await r.text()
        job_id = (await r.json())["job_id"]
        # bob can't see alice's job
        assert (await client.get(f"/api/v1/jobs/{job_id}", headers=hb)).status == 404
        page = await (await client.get("/api/v1/jobs", headers=hb)).json()
        assert page["total"] == 0
        # admin can
        tok_admin = dev_generate_token("root", rt.settings.jwt_secret, is_admin=True)
        hadm = {"Authorization": f"Bearer {tok_admin}"}
        assert (await client.get(f"/api/v1/jobs/{job_id}", headers=hadm)).status == 200
        # admin-only routes refuse plain users
        assert (await client.get("/api/v1/admin/jobs", headers=ha)).status == 403
        r = await client.get("/api/v1/admin/jobs", headers=hadm)
        assert r.status == 200 and (await r.json())["total"] == 1
        # resilience surface (docs/resilience.md): admin-only, and this
        # runtime wires no supervisor/lease -> reports disabled
        assert (await client.get("/api/v1/admin/resilience",
                                 headers=ha)).status == 403
        r = await client.get("/api/v1/admin/resilience", headers=hadm)
        assert r.status == 200
        body = await r.json()
        assert body["enabled"] is False and body["lease_enabled"] is False
        await client.close()

    run_async(main())


def test_datasets_routes(tmp_path):
    async def main():
        import aiohttp

        client = await _client(_runtime(tmp_path), with_monitor=False)
        form = aiohttp.FormData()
        form.add_field("file", b'{"text": "hi"}\n', filename="d.jsonl",
                       content_type="application/jsonl")
        r = await client.post("/api/v1/datasets", data=form)
        assert r.status == 201
        ds = await r.json()
        r = await client.get("/api/v1/datasets")
        assert len((await r.json())["datasets"]) == 1
        r = await client.get(f"/api/v1/datasets/{ds['dataset_id']}")
        body = await r.json()
        assert body["download_url"]
        r = await client.get(body["download_url"])
        assert r.status == 200 and await r.read() == b'{"text": "hi"}\n'
        r = await client.delete(f"/api/v1/datasets/{ds['dataset_id']}")
        assert r.status == 200
        r = await client.get(f"/api/v1/datasets/{ds['dataset_id']}")
        assert r.status == 404
        await client.close()

    run_async(main())


def test_ws_log_streaming_with_search_gate(tmp_path):
    async def main():
        client = await _client(_runtime(tmp_path))
        r = await client.post("/api/v1/jobs", json=SUBMIT_BODY)
        assert r.status == 200
        job_id = (await r.json())["job_id"]
        ws = await client.ws_connect(
            f"/api/v1/logs/{job_id}?search_string=trainer&follow=true"
        )
        collected = []
        try:
            while True:
                msg = await ws.receive(timeout=120)
                if msg.type.name in ("CLOSE", "CLOSED", "CLOSING", "ERROR"):
                    break
                collected.append(msg.data)
        finally:
            await ws.close()
        text = "\n".join(collected)
        # the gate swallowed pre-marker lines; trainer logs flowed through
        assert "trainer" in text, text[:500]
        payload = [l for l in collected if not l.startswith("waiting:")]
        assert payload and "trainer" in payload[0]
        await _wait_final(client, job_id)
        await client.close()

    run_async(main())


def test_prometheus_metrics_endpoint(tmp_path):
    async def main():
        client = await _client(_runtime(tmp_path), with_monitor=False)
        r = await client.get("/metrics")
        assert r.status == 200
        text = await r.text()
        assert "ftc_monitor_ticks_total" in text
        assert "ftc_quota_chips" in text
        await client.close()

    run_async(main())


def test_api_metrics_json_valid_with_eval_columns(tmp_path):
    """An eval-enabled job's metrics (ragged eval columns) must serve as
    RFC-valid JSON through the API — empty cells become null, never NaN."""

    async def main():
        client = await _client(_runtime(tmp_path))  # monitor in-process
        body = {
            "model_name": "tiny-test-lora",
            "device": "chip-1",
            "arguments": {"total_steps": 4, "warmup_steps": 1, "batch_size": 2,
                          "seq_len": 16, "lora_rank": 2, "eval_every": 2,
                          "eval_steps": 1},
        }
        r = await client.post("/api/v1/jobs", json=body)
        assert r.status == 200, await r.text()
        job_id = (await r.json())["job_id"]
        job = await _wait_final(client, job_id)
        assert job["status"] == "succeeded", job

        r = await client.get(f"/api/v1/jobs/{job_id}/metrics")
        raw = await r.read()
        # strict parse: literal NaN tokens are RFC-invalid and must not appear
        body = json.loads(raw.decode(), parse_constant=lambda c: (_ for _ in ()).throw(
            AssertionError(f"non-RFC JSON constant {c!r} in metrics response")))
        eval_rows = [rec for rec in body["records"] if rec.get("eval_loss") is not None]
        assert eval_rows, body["records"]
        assert all(rec["eval_loss"] > 0 for rec in eval_rows)
        # (ragged-cell -> null conversion is unit-tested at the store level:
        # tests/test_lifecycle.py + objectstore.get_metrics_records)
        await client.close()

    run_async(main())
