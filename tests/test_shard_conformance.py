"""Sharding-conformance lint rules (analysis/rules_sharding.py).

Layers, mirroring ``tests/test_project_analysis.py``:

* registry plumbing — the jax-importing rules are HEAVY (excluded from the
  default registry that rides the 10s lint stage, opted in via
  ``--rules``/``include_heavy``), the pure-AST rules ride by default, and
  ``--list-rules``/SARIF surface all of them;
* per-rule TP / clean / suppression fixtures for the two fast rules
  (``shard-undefined-axis``, ``shard-unsharded-device-put``);
* MUTATION tests against the real package via ``source_overrides``
  (slow-marked; run by the ``shard-audit-fast`` ci_check stage): delete a
  live ``LLAMA_RULES`` entry and the weight-fallthrough check turns red;
  duplicate a pattern and the shadowed-rule check turns red; add a rule
  matching nothing and the dead-rule check turns red — while HEAD stays
  green on the same machinery.
"""

import json
from pathlib import Path

import pytest

from finetune_controller_tpu.analysis import rules_sharding
from finetune_controller_tpu.analysis.engine import (
    all_project_rules,
    lint_paths,
    main,
)
from finetune_controller_tpu.analysis.project import build_project

PKG = Path(__file__).resolve().parent.parent / "finetune_controller_tpu"

FAST_IDS = ("shard-undefined-axis", "shard-unsharded-device-put")
HEAVY_IDS = ("shard-rule-coverage", "shard-divisibility",
             "collective-conformance")


def _write(tmp_path: Path, files: dict[str, str]) -> Path:
    import textwrap

    for name, src in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def _fast_lint(tmp_path, files, rules=FAST_IDS):
    root = _write(tmp_path, files)
    prules = all_project_rules()
    prules = {k: prules[k] for k in rules}
    return lint_paths([str(root)], rules={}, project_rules=prules)


def _heavy_lint(rule_ids, source_overrides=None):
    """Run a heavy-rule subset over the REAL package (the ci_check stage's
    shape), optionally with mutated sources swapped in memory."""
    prules = {
        k: v for k, v in all_project_rules(include_heavy=True).items()
        if k in rule_ids
    }
    assert set(prules) == set(rule_ids)
    return lint_paths(
        [str(PKG)], rules={}, project_rules=prules,
        source_overrides=source_overrides or {},
    )


MESH_SRC = """
    class AxisNames:
        DATA = "dp"
        FSDP = "fsdp"
        TENSOR = "tp"
        BATCH_AXES = (DATA, FSDP)
"""


# ---------------------------------------------------------------------------
# registry plumbing
# ---------------------------------------------------------------------------


def test_heavy_rules_excluded_from_default_registry():
    """The 10s lint budget survives v3 because the jax-importing rules are
    not in the default registry — they run only when named."""
    default = all_project_rules()
    for rid in HEAVY_IDS:
        assert rid not in default
    for rid in FAST_IDS:
        assert rid in default


def test_heavy_rules_present_with_include_heavy():
    full = all_project_rules(include_heavy=True)
    for rid in FAST_IDS + HEAVY_IDS:
        assert rid in full
        assert full[rid].plane == "sharding"
    for rid in HEAVY_IDS:
        assert full[rid].heavy


def test_list_rules_tags_heavy(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in FAST_IDS + HEAVY_IDS:
        assert rid in out
    for line in out.splitlines():
        if any(line.strip().startswith(rid) for rid in HEAVY_IDS):
            assert "[heavy" in line


def test_sarif_covers_sharding_findings(tmp_path, capsys):
    """A sharding finding round-trips through SARIF with its rule id and
    summary in the driver's rule list (CI annotations)."""
    _write(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/parallel/__init__.py": "",
        "pkg/parallel/mesh.py": MESH_SRC,
        "pkg/train/__init__.py": "",
        "pkg/train/loader.py": (
            "import jax\n\n\ndef f(x):\n    return jax.device_put(x)\n"
        ),
    })
    assert main([str(tmp_path), "--format", "sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    run = doc["runs"][0]
    assert any(
        r["ruleId"] == "shard-unsharded-device-put" for r in run["results"]
    )
    driver_rules = {
        r["id"]: r["shortDescription"]["text"]
        for r in run["tool"]["driver"]["rules"]
    }
    assert "explicit sharding" in driver_rules["shard-unsharded-device-put"]


# ---------------------------------------------------------------------------
# shard-undefined-axis (fast, fixtures)
# ---------------------------------------------------------------------------


def test_undefined_axis_flagged(tmp_path):
    result = _fast_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/parallel/__init__.py": "",
        "pkg/parallel/mesh.py": MESH_SRC,
        "pkg/train/__init__.py": "",
        "pkg/train/step.py": """
            from jax.sharding import PartitionSpec

            SPEC = PartitionSpec("fsdp", "tensr")
        """,
    })
    assert [f.rule for f in result.findings] == ["shard-undefined-axis"]
    assert "'tensr'" in result.findings[0].message


def test_defined_axes_clean(tmp_path):
    result = _fast_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/parallel/__init__.py": "",
        "pkg/parallel/mesh.py": MESH_SRC,
        "pkg/train/__init__.py": "",
        "pkg/train/step.py": """
            from jax.sharding import NamedSharding, PartitionSpec

            def shard(mesh, x):
                return NamedSharding(mesh, PartitionSpec("dp", "fsdp"))
        """,
    })
    assert result.findings == []


def test_keyword_args_are_not_axis_names(tmp_path):
    """memory_kind="pinned_host" (the KV host-tiering idiom) is a keyword
    argument, not an axis — it must not false-positive."""
    result = _fast_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/parallel/__init__.py": "",
        "pkg/parallel/mesh.py": MESH_SRC,
        "pkg/serve/__init__.py": "",
        "pkg/serve/kv.py": """
            from jax.sharding import NamedSharding, PartitionSpec

            def host_spec(mesh):
                return NamedSharding(
                    mesh, PartitionSpec(), memory_kind="pinned_host"
                )
        """,
    })
    assert result.findings == []


def test_local_mesh_axes_allowed(tmp_path):
    """A module constructing its own diagnostics Mesh may name its axes."""
    result = _fast_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/parallel/__init__.py": "",
        "pkg/parallel/mesh.py": MESH_SRC,
        "pkg/tools/__init__.py": "",
        "pkg/tools/diag.py": """
            import jax
            from jax.sharding import Mesh, PartitionSpec

            def probe(devs):
                mesh = Mesh(devs, ("probe",))
                return PartitionSpec("probe")
        """,
    })
    assert result.findings == []


def test_no_mesh_module_opts_out(tmp_path):
    result = _fast_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/train/__init__.py": "",
        "pkg/train/step.py": """
            from jax.sharding import PartitionSpec

            SPEC = PartitionSpec("anything")
        """,
    })
    assert result.findings == []


def test_undefined_axis_suppression(tmp_path):
    result = _fast_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/parallel/__init__.py": "",
        "pkg/parallel/mesh.py": MESH_SRC,
        "pkg/train/__init__.py": "",
        "pkg/train/step.py": """
            from jax.sharding import PartitionSpec

            # ftc: ignore[shard-undefined-axis] -- fixture
            SPEC = PartitionSpec("tensr")
        """,
    })
    assert len(result.findings) == 1 and result.findings[0].suppressed


# ---------------------------------------------------------------------------
# shard-unsharded-device-put (fast, fixtures)
# ---------------------------------------------------------------------------


def test_bare_device_put_on_multichip_path_flagged(tmp_path):
    result = _fast_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/parallel/__init__.py": "",
        "pkg/parallel/mesh.py": MESH_SRC,
        "pkg/train/__init__.py": "",
        "pkg/train/loader.py": """
            import jax

            def to_device(x):
                return jax.device_put(x)
        """,
    })
    assert [f.rule for f in result.findings] == ["shard-unsharded-device-put"]


def test_device_put_with_sharding_clean(tmp_path):
    result = _fast_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/parallel/__init__.py": "",
        "pkg/parallel/mesh.py": MESH_SRC,
        "pkg/train/__init__.py": "",
        "pkg/train/loader.py": """
            import jax

            def to_device(x, sharding):
                a = jax.device_put(x, sharding)
                b = jax.device_put(x, device=sharding)
                return a, b
        """,
    })
    assert result.findings == []


def test_device_put_outside_multichip_segments_ignored(tmp_path):
    """controller/ ctl code moves host scalars around — not a hot path."""
    result = _fast_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/parallel/__init__.py": "",
        "pkg/parallel/mesh.py": MESH_SRC,
        "pkg/controller/__init__.py": "",
        "pkg/controller/admin.py": """
            import jax

            def stage(x):
                return jax.device_put(x)
        """,
    })
    assert result.findings == []


# ---------------------------------------------------------------------------
# table reconstruction parity (the AST twin matches the runtime table)
# ---------------------------------------------------------------------------


def test_ast_table_matches_runtime_fingerprint():
    """The coverage rule lints the table it RECONSTRUCTS from source — this
    pin proves the reconstruction is the real LLAMA_RULES (same patterns,
    same specs, same order) so mutation tests mutate the thing that runs."""
    from finetune_controller_tpu.parallel.sharding import LLAMA_RULES

    project = build_project([str(PKG)])
    mesh_mod = rules_sharding._mesh_module(project)
    attr_map, _defined = rules_sharding._axis_table(mesh_mod)
    tables = [
        t for t in rules_sharding._find_tables(project, attr_map)
        if t.parsed and t.name == "LLAMA_RULES"
    ]
    assert len(tables) == 1
    rebuilt = rules_sharding._build_rules(tables[0])
    assert rebuilt.fingerprint() == LLAMA_RULES.fingerprint()


# ---------------------------------------------------------------------------
# heavy rules on the real package: HEAD green, mutations red (slow)
# ---------------------------------------------------------------------------

SHARD_PY = PKG / "parallel" / "sharding.py"


@pytest.mark.slow
def test_head_is_clean_under_heavy_rules():
    """The repo's own rule table passes coverage + divisibility at HEAD —
    the lint-clean satellite, and the baseline every mutation test below
    flips from."""
    result = _heavy_lint(("shard-rule-coverage", "shard-divisibility"))
    assert [f for f in result.findings if not f.suppressed] == []
    assert result.errors == []


@pytest.mark.slow
def test_deleted_rule_turns_coverage_red():
    """Delete the live down_proj/kernel rule: the leaf falls through to the
    bare ``.*`` catch-all and the weight-fallthrough check fires — the
    deleted-rule trap the ISSUE names."""
    src = SHARD_PY.read_text()
    line = '        (r"down_proj/kernel", P(Ax.TENSOR, Ax.FSDP)),\n'
    assert line in src
    mutated = src.replace(line, "")
    result = _heavy_lint(
        ("shard-rule-coverage",), {str(SHARD_PY): mutated}
    )
    hits = [f for f in result.findings if "down_proj/kernel" in f.message]
    assert hits, [f.message for f in result.findings]
    assert all(f.rule == "shard-rule-coverage" for f in hits)
    assert any("catch-all" in f.message for f in hits)


@pytest.mark.slow
def test_shadowed_rule_turns_coverage_red():
    """A duplicate pattern inserted after the original never matches first
    — flagged as shadowed, at its own line, naming the superseding rule."""
    src = SHARD_PY.read_text()
    anchor = '        (r".*", P()),'
    assert anchor in src
    mutated = src.replace(
        anchor,
        '        (r"router_kernel", P(Ax.FSDP, None)),\n' + anchor,
    )
    result = _heavy_lint(
        ("shard-rule-coverage",), {str(SHARD_PY): mutated}
    )
    assert any(
        "shadowed" in f.message and "router_kernel" in f.message
        for f in result.findings
    ), [f.message for f in result.findings]


@pytest.mark.slow
def test_dead_rule_turns_coverage_red():
    """A rule whose pattern matches no catalog leaf is dead weight."""
    src = SHARD_PY.read_text()
    anchor = '        (r".*", P()),'
    mutated = src.replace(
        anchor,
        '        (r"no_such_param_family/kernel2", P()),\n' + anchor,
    )
    result = _heavy_lint(
        ("shard-rule-coverage",), {str(SHARD_PY): mutated}
    )
    assert any(
        "dead" in f.message and "no_such_param_family" in f.message
        for f in result.findings
    ), [f.message for f in result.findings]


@pytest.mark.slow
def test_undefined_axis_in_table_turns_coverage_red():
    """A spec axis the AxisNames table does not define is red even before
    any topology is consulted."""
    src = SHARD_PY.read_text()
    line = '        (r"router_kernel", P(Ax.FSDP, None)),'
    assert line in src
    mutated = src.replace(
        line, '        (r"router_kernel", P("bogus_axis", None)),'
    )
    result = _heavy_lint(
        ("shard-rule-coverage",), {str(SHARD_PY): mutated}
    )
    assert any("bogus_axis" in f.message for f in result.findings), \
        [f.message for f in result.findings]


@pytest.mark.slow
def test_indivisible_spec_turns_divisibility_red():
    """Shard the tiny LoRA rank dim (16) over the dp×fsdp product: on the
    REALSCALE dcn2x16 topology that product is 32 and stops dividing —
    the static twin of validate_spec fires at the entry's line."""
    src = SHARD_PY.read_text()
    line = '        (r"o_proj/lora_a|down_proj/lora_a", P(Ax.TENSOR, None)),'
    assert line in src
    mutated = src.replace(
        line,
        '        (r"o_proj/lora_a|down_proj/lora_a",'
        ' P(Ax.TENSOR, (Ax.DATA, Ax.FSDP))),',
    )
    result = _heavy_lint(("shard-divisibility",), {str(SHARD_PY): mutated})
    hits = [f for f in result.findings if "lora_a" in f.message]
    assert hits, [f.message for f in result.findings]
    assert all(f.rule == "shard-divisibility" for f in hits)
    assert any("divisible" in f.message for f in hits)
