"""AOT validation of the BASELINE configs that cannot run on one chip.

BASELINE #2 (Llama-3-8B LoRA FSDP, v5e-16) and #4 (Mixtral-8x7B MoE LoRA,
v5p-64) at their REAL shapes: the full training step is abstractly lowered,
SPMD-partitioned and XLA-compiled over 16-/64-virtual-device meshes in a
subprocess (no parameter memory is allocated — ``train/aot.py``).  Asserts
the sharding specs, the cross-device collectives, and the per-device state
fitting the target chip's HBM.
"""

from __future__ import annotations

import pytest

from finetune_controller_tpu.train.aot import run_report_subprocess as _report


@pytest.mark.slow
def test_llama3_8b_fsdp16_real_shapes():
    rep = _report("llama3-8b-fsdp16")
    assert rep["param_count"] > 8e9  # the REAL model, not a shrunk proxy
    assert rep["mesh"]["fsdp"] == 16
    # every frozen weight matrix FSDP-sharded; FSDP needs parameter
    # all-gather + gradient reduction collectives in the compiled program
    assert rep["fsdp_sharded_leaves"] >= 20
    assert "all-gather" in rep["collectives"]
    assert {"all-reduce", "reduce-scatter"} & set(rep["collectives"])
    # resident train state must fit a v5e chip's HBM with room for
    # activations (state alone below 1/4 of HBM)
    assert rep["state_fits_hbm"]
    assert rep["state_bytes_per_device"] < rep["hbm_bytes"] / 4


@pytest.mark.slow
def test_mixtral_ep8_fsdp8_real_shapes():
    rep = _report("mixtral-8x7b-ep8-fsdp8")
    assert rep["param_count"] > 46e9
    assert rep["mesh"]["ep"] == 8 and rep["mesh"]["fsdp"] == 8
    assert rep["ep_sharded_leaves"] >= 3  # expert kernels on the ep axis
    # MoE dispatch/combine requires all-to-all traffic
    assert "all-to-all" in rep["collectives"]
    assert "all-gather" in rep["collectives"]
    assert rep["state_fits_hbm"]


@pytest.mark.slow
def test_llama3_8b_pp2_real_shapes():
    """Round-5 (VERDICT #7): the PIPELINE leg at real 8B shapes — the layer
    stack splits into 2 GPipe stages (leading-axis pp sharding), composes
    with dp4, compiles with stage-hop collectives, and reports the analytic
    bubble for its schedule."""
    rep = _report("llama3-8b-dp4-pp2")
    assert rep["param_count"] > 8e9
    assert rep["mesh"]["pp"] == 2 and rep["mesh"]["dp"] == 4
    # the stacked block weights are stage-sharded on the layer axis
    assert rep["pp_sharded_leaves"] >= 20
    assert rep["unsharded_big_leaves"] <= 3  # embed/head/norm replicate by design
    # activation hops between stages ride collective-permute
    assert "collective-permute" in rep["collectives"]
    # trainer default schedule: local batch 8 over pp2 -> 4 microbatches
    assert rep["pp_schedule"] == {"n_micro": 4, "bubble_fraction": 0.2}
    assert rep["state_fits_hbm"]
