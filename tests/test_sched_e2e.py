"""End-to-end preemption on the REAL local backend (ISSUE 5 acceptance).

The chaos-style loop, scheduler edition: a full cluster runs a low-priority
job past its first committed checkpoint; a high-priority submission preempts
it through the scheduler (SIGTERM -> trainer checkpoints -> exit 143); the
victim lands in RETRYING via the resilience supervisor and later RESUMES
from its checkpoint with step-continuous metrics, while the preemptor is
admitted the moment the chips free (within one monitor tick).

Reuses the PR 3 proof harness patterns from tests/test_chaos.py.
"""

import asyncio
import csv
import re
import time

import pytest

from conftest import one_chip_catalog
from conftest import run_async as run

from finetune_controller_tpu.controller import registry
from finetune_controller_tpu.controller.backends.local import LocalProcessBackend
from finetune_controller_tpu.controller.examples import LoRASFTArguments, TinyTestLoRA
from finetune_controller_tpu.controller.monitor import JobMonitor
from finetune_controller_tpu.controller.objectstore import LocalObjectStore
from finetune_controller_tpu.controller.schemas import (
    BackendJobState,
    DatabaseStatus,
    JobInput,
)
from finetune_controller_tpu.controller.statestore import StateStore
from finetune_controller_tpu.controller.task_builder import DatasetInput, task_builder
from finetune_controller_tpu.resilience.policy import RetryPolicy
from finetune_controller_tpu.resilience.supervisor import RetrySupervisor


def _arguments(total_steps, cadence=10):
    return LoRASFTArguments(
        total_steps=total_steps, warmup_steps=1, batch_size=2, seq_len=16,
        lora_rank=2, log_every=cadence, checkpoint_every=cadence,
    )


def _plane(tmp_path):
    """Real control plane on a FULL one-chip cluster, fair-share scheduler
    (the default), backend restart budget zeroed so recovery flows through
    the supervisor, fast seeded backoff."""
    registry.reset()
    registry.load_builtin_models()
    root = tmp_path / "plane"
    state = StateStore(root / "state")
    store = LocalObjectStore(root / "objects")
    catalog = one_chip_catalog(quota=1)
    backend = LocalProcessBackend(
        root / "sandboxes", store, catalog,
        sync_interval_s=0.2, backoff_limit=0,
        sched_queues={"batch": 1.0, "prod": 4.0},
    )
    supervisor = RetrySupervisor(
        state, backend, catalog,
        policy=RetryPolicy(max_attempts=3, base_delay_s=0.2, max_delay_s=0.5,
                           seed=0),
    )
    monitor = JobMonitor(state, store, backend, interval_s=0.1,
                         supervisor=supervisor)
    return state, store, catalog, backend, supervisor, monitor


async def _submit(state, store, backend, catalog, arguments, job_id, *,
                  queue, priority):
    spec = TinyTestLoRA(training_arguments=arguments)
    await task_builder(
        JobInput(job_id=job_id, user_id="u", model_name="tiny-test-lora",
                 device="chip-1", arguments=arguments.model_dump(),
                 queue=queue, priority=priority),
        spec, DatasetInput(),
        state=state, store=store, backend=backend, catalog=catalog,
        datasets_bucket="datasets", artifacts_bucket="artifacts",
    )


def _metric_steps(artifacts_dir):
    with open(artifacts_dir / "metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    return [int(float(r["step"])) for r in rows]


def test_preemption_evicts_checkpoints_and_resumes(tmp_path):
    async def main():
        total, cadence = 40, 10
        state, store, catalog, backend, sup, monitor = _plane(tmp_path)
        await state.connect()

        # -- the victim saturates the (one-chip) cluster -------------------
        await _submit(state, store, backend, catalog, _arguments(total, cadence),
                      "victim-1", queue="batch", priority="low")
        victim = backend._handles["victim-1"]
        ckpt_dir = victim.artifacts_dir / "checkpoints"
        committed = re.compile(r"^step_\d+$")
        deadline = time.monotonic() + 240
        while not (ckpt_dir.is_dir()
                   and any(committed.match(p.name) for p in ckpt_dir.iterdir())):
            assert time.monotonic() < deadline, "no checkpoint within 240s"
            await asyncio.sleep(0.1)

        # -- a high-priority submit preempts it through the scheduler ------
        await _submit(state, store, backend, catalog, _arguments(4, 2),
                      "preemptor-1", queue="prod", priority="high")
        assert backend.scheduler.preemptions_total == 1

        # -- drive the plane; record when each side transitions ------------
        victim_retrying_tick = None
        preemptor_admitted_tick = None
        preemptor_done = False
        deadline = time.monotonic() + 300
        tick = 0
        while True:
            await monitor.tick()
            tick += 1
            vrec = await state.get_job("victim-1")
            if victim_retrying_tick is None and (
                vrec.status is DatabaseStatus.RETRYING
            ):
                victim_retrying_tick = tick
            prep = await backend.get_job("preemptor-1")
            if preemptor_admitted_tick is None and prep is not None and (
                prep.state not in (BackendJobState.PENDING,
                                   BackendJobState.SUSPENDED)
            ):
                preemptor_admitted_tick = tick
            prec = await state.get_job("preemptor-1")
            preemptor_done = prec.status is DatabaseStatus.SUCCEEDED
            if vrec.status.is_final and preemptor_done:
                break
            assert time.monotonic() < deadline, (
                vrec.status, vrec.metadata, prec.status,
            )
            await asyncio.sleep(0.05)

        # victim: preempted -> RETRYING -> resumed -> SUCCEEDED
        assert vrec.status is DatabaseStatus.SUCCEEDED, vrec.metadata
        history = vrec.metadata["attempt_history"]
        assert len(history) == 1, history
        assert history[0]["failure_class"] == "preemption"
        assert vrec.metadata.get("preempted") is True
        assert vrec.metadata.get("preempted_by") == "preemptor-1"
        # queue/priority survive in metadata across the retry (crash-safe)
        assert vrec.metadata["queue"] == "batch"
        assert vrec.metadata["priority"] == "low"
        assert victim_retrying_tick is not None

        # the preemptor was admitted the moment the victim's chip freed —
        # no later than one monitor tick around the RETRYING transition
        assert preemptor_admitted_tick is not None
        assert preemptor_admitted_tick <= victim_retrying_tick + 1, (
            preemptor_admitted_tick, victim_retrying_tick,
        )

        # resume proof (the PR 3 harness): continued, not restarted
        log_text = (victim.sandbox / "logs.txt").read_text()
        assert "resumed from checkpoint step" in log_text
        steps = _metric_steps(victim.artifacts_dir)
        assert steps == list(range(cadence, total + 1, cadence)), steps

        # scheduler bookkeeping drained cleanly
        snap = backend.scheduler.snapshot()
        assert snap["preemptions_total"] == 1
        assert snap["reservations"] == {}
        assert sup.retries_scheduled == 1 and sup.resubmits == 1
        await backend.close()
        await state.close()

    run(main())


@pytest.mark.slow
def test_resize_shrinks_resumes_and_grows_back(tmp_path):
    """ISSUE 7 acceptance: a 2-slice borrower past its first checkpoint is
    SHRUNK (not evicted) when a high-priority job arrives — it lands
    RETRYING classified as a resize (zero backoff, no attempt burned),
    resumes STEP-CONTINUOUS at dp=1 through the elastic-restore path, and
    is grown back to 2 slices after the preemptor finishes.  Real
    subprocesses, real SIGTERMs, real cross-topology checkpoint restores."""

    async def main():
        registry.reset()
        registry.load_builtin_models()
        root = tmp_path / "plane"
        state = StateStore(root / "state")
        store = LocalObjectStore(root / "objects")
        catalog = one_chip_catalog(quota=2)
        backend = LocalProcessBackend(
            root / "sandboxes", store, catalog,
            sync_interval_s=0.2, backoff_limit=0,
            sched_queues={"batch": 1.0, "prod": 4.0},
            sched_grow_delay_s=1.0,
        )
        supervisor = RetrySupervisor(
            state, backend, catalog,
            policy=RetryPolicy(max_attempts=3, base_delay_s=0.2,
                               max_delay_s=0.5, seed=0),
        )
        monitor = JobMonitor(state, store, backend, interval_s=0.1,
                             supervisor=supervisor)
        await state.connect()

        total, cadence = 2000, 100
        # the victim saturates the 2-chip cluster at dp=2 (batch_size 2
        # divides both the dp=2 and the shrunk dp=1 topology)
        victim_args = _arguments(total, cadence)
        spec = TinyTestLoRA(training_arguments=victim_args)
        await task_builder(
            JobInput(job_id="borrower", user_id="u",
                     model_name="tiny-test-lora", device="chip-1",
                     num_slices=2, arguments=victim_args.model_dump(),
                     queue="batch", priority="low"),
            spec, DatasetInput(),
            state=state, store=store, backend=backend, catalog=catalog,
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        victim = backend._handles["borrower"]
        ckpt_dir = victim.artifacts_dir / "checkpoints"
        committed = re.compile(r"^step_\d+$")
        deadline = time.monotonic() + 240
        while not (ckpt_dir.is_dir()
                   and any(committed.match(p.name) for p in ckpt_dir.iterdir())):
            assert time.monotonic() < deadline, "no checkpoint within 240s"
            await asyncio.sleep(0.1)

        # -- a high-priority 1-chip job arrives: SHRINK, not evict ---------
        await _submit(state, store, backend, catalog, _arguments(4, 2),
                      "urgent", queue="prod", priority="high")
        assert backend.scheduler.preemptions_total == 0  # nobody evicted
        assert backend.scheduler.shrinks_total == 1

        # -- drive the plane to completion ---------------------------------
        saw_shrunk_running = False
        grown = False
        deadline = time.monotonic() + 420
        while True:
            await monitor.tick()
            vrec = await state.get_job("borrower")
            meta = vrec.metadata
            if (vrec.status is DatabaseStatus.RUNNING
                    and meta.get("current_num_slices") == 1):
                saw_shrunk_running = True
            if backend.scheduler.grows_total >= 1:
                grown = True
            urec = await state.get_job("urgent")
            if vrec.status.is_final and urec.status.is_final:
                break
            assert time.monotonic() < deadline, (
                vrec.status, meta, urec.status,
            )
            await asyncio.sleep(0.05)

        assert urec.status is DatabaseStatus.SUCCEEDED, urec.metadata
        assert vrec.status is DatabaseStatus.SUCCEEDED, vrec.metadata
        # the victim ran at dp=1 while the preemptor held the other chip,
        # and was grown back once the chips freed
        assert saw_shrunk_running
        assert grown
        history = vrec.metadata["attempt_history"]
        assert len(history) == 2, history  # shrink, then grow — no failures
        for entry, to_slices in zip(history, (1, 2)):
            assert entry["resize"] is True
            assert entry["resize_to_num_slices"] == to_slices
            assert entry["delay_s"] == 0.0   # resizes skip the backoff
            assert entry["attempt"] == 1     # ... and the retry budget
            assert entry["failure_class"] == "preemption"
            assert entry["exit_code"] == 143
        assert vrec.metadata["last_ran_num_slices"] == 2
        assert supervisor.resizes == 2
        assert supervisor.elastic_restores == 2

        # resume proof: BOTH restarts resumed from a checkpoint, through the
        # cross-topology (elastic) restore path
        log_text = (victim.sandbox / "logs.txt").read_text()
        assert log_text.count("resumed from checkpoint step") == 2
        assert "elastic restore: checkpoint mesh" in log_text
        # metrics are step-continuous across dp=2 -> dp=1 -> dp=2
        steps = _metric_steps(victim.artifacts_dir)
        assert steps == list(range(cadence, total + 1, cadence)), steps

        snap = backend.scheduler.snapshot()
        assert snap["resizes_total"] >= 2
        assert snap["shrinks_total"] == 1 and snap["grows_total"] == 1
        assert [h["kind"] for h in snap["resize_history"]] == ["shrink", "grow"]
        assert snap["resize_reservations"] == {}
        await backend.close()
        await state.close()

    run(main())
