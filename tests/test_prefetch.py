"""The overlapped input pipeline's contract (data/prefetch.py): order
preservation, bounded memory, crash transparency, clean shutdown — and the
trainer-level guarantee that turning prefetch on changes WHEN batches are
built, never WHICH batches a step sees (bit-identical loss trajectories,
including across a checkpoint-resume)."""

import threading
import time

import numpy as np
import pytest

from finetune_controller_tpu.data.prefetch import (
    PrefetchIterator,
    prefetch_batches,
)


def test_order_preserved_exactly():
    src = list(range(200))
    with PrefetchIterator(iter(src), depth=4) as it:
        assert list(it) == src


def test_depth_zero_is_the_synchronous_passthrough():
    it = prefetch_batches(iter([1, 2, 3]), depth=0)
    assert not isinstance(it, PrefetchIterator)
    assert list(it) == [1, 2, 3]


def test_invalid_depth_rejected():
    with pytest.raises(ValueError, match="depth"):
        PrefetchIterator(iter([]), depth=0)


def test_queue_is_bounded():
    """The producer must build at most depth+1 batches ahead of the consumer
    (depth finished in the queue + one in flight) — not eat the dataset."""
    built = []

    def gen():
        for i in range(100):
            built.append(i)
            yield i

    with PrefetchIterator(gen(), depth=2) as it:
        deadline = time.monotonic() + 5.0
        while len(built) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)  # would overrun here if the queue were unbounded
        assert len(built) <= 3, f"producer ran ahead: built {len(built)}"
        assert next(it) == 0


def test_producer_exception_reraised_verbatim():
    """A producer crash must surface on the consumer thread as the ORIGINAL
    exception — no hang, no wrapper type — after the good batches drain."""

    class BoomError(RuntimeError):
        pass

    def gen():
        yield 1
        yield 2
        raise BoomError("decoder exploded")

    it = PrefetchIterator(gen(), depth=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(BoomError, match="decoder exploded"):
        next(it)
    # the iterator is dead, not wedged
    with pytest.raises(StopIteration):
        next(it)


def test_close_unblocks_producer_stuck_on_full_queue():
    """close() while the producer is waiting for queue space must stop the
    thread promptly — the shutdown path a trainer's finally block takes."""
    it = PrefetchIterator(iter(range(1000)), depth=1)
    deadline = time.monotonic() + 5.0
    while it._queue.empty() and time.monotonic() < deadline:
        time.sleep(0.01)
    it.close()
    it._thread.join(timeout=5.0)
    assert not it._thread.is_alive()
    it.close()  # idempotent


def test_next_after_close_raises_instead_of_hanging():
    """close() drains the queue and the producer exits without posting the
    done sentinel — a later next() must StopIteration, not block forever."""
    it = PrefetchIterator(iter(range(1000)), depth=1)
    next(it)
    it.close()
    with pytest.raises(StopIteration):
        next(it)


def test_transfer_stage_runs_on_producer_thread():
    seen_threads = []

    def transfer(x):
        seen_threads.append(threading.current_thread())
        return x * 10

    with PrefetchIterator(iter([1, 2, 3]), depth=2, transfer=transfer) as it:
        assert list(it) == [10, 20, 30]
    main = threading.main_thread()
    assert all(t is not main for t in seen_threads)


def test_stats_window_counts_build_and_wait():
    def slow_gen():
        for i in range(4):
            time.sleep(0.01)
            yield i

    with PrefetchIterator(slow_gen(), depth=2) as it:
        list(it)
        stats = it.pop_stats()
    assert stats["batches"] == 4
    assert stats["build_s"] >= 0.03
    assert stats["wait_s"] >= 0.0
    # the pop drained the window
    assert it.pop_stats()["batches"] == 0


# ---------------------------------------------------------------------------
# trainer-level: prefetch on/off bit-identity, incl. checkpoint-resume
# ---------------------------------------------------------------------------


def _run_losses(tmp_path, prefetch, legs):
    """Train len(legs) legs into one artifacts dir (later legs resume from
    the earlier legs' checkpoints); return the full loss trajectory."""
    from finetune_controller_tpu.data import synthetic_batches
    from finetune_controller_tpu.models import PRESETS, LoRAConfig
    from finetune_controller_tpu.train import Trainer, TrainConfig

    model_cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    losses = []
    for total_steps in legs:
        cfg = TrainConfig(
            mode="lora", total_steps=total_steps, batch_size=4, seq_len=16,
            log_every=1, checkpoint_every=4, prefetch=prefetch,
        )
        trainer = Trainer(model_cfg, cfg)
        batches = synthetic_batches(
            4, 16, model_cfg.vocab_size, task="increment"
        )
        trainer.fit(
            batches, str(tmp_path),
            on_metrics=lambda s, m: losses.append(float(m["loss"])),
        )
    return losses


def test_prefetch_bit_identical_losses_and_resume(tmp_path):
    """Acceptance: prefetch on (default, with the device_put transfer stage)
    reproduces the synchronous iterator's loss trajectory BIT-identically —
    same batches, same order — including after a checkpoint-resume whose
    fast-forward skip must consume the same stream positions."""
    sync = _run_losses(tmp_path / "sync", 0, legs=(8,))
    over = _run_losses(tmp_path / "over", 2, legs=(8,))
    assert over == sync  # exact float equality, not approx

    # interrupted at step 4 (checkpoint) then resumed to 8: the resumed
    # prefetch producer must start AFTER the fast-forward skip, seeing
    # exactly the batches an uninterrupted run would have
    resumed = _run_losses(tmp_path / "resumed", 2, legs=(4, 8))
    assert resumed == sync


def test_trainer_metrics_csv_carries_input_columns(tmp_path):
    """input_ms / input_fraction are first-class metrics.csv columns with
    sane values, and the step metrics callback carries them too."""
    import csv

    from finetune_controller_tpu.data import synthetic_batches
    from finetune_controller_tpu.models import PRESETS, LoRAConfig
    from finetune_controller_tpu.train import Trainer, TrainConfig

    model_cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    cfg = TrainConfig(
        mode="lora", total_steps=4, batch_size=4, seq_len=16,
        log_every=2, checkpoint_every=100,
    )
    seen = []
    Trainer(model_cfg, cfg).fit(
        synthetic_batches(4, 16, model_cfg.vocab_size),
        str(tmp_path), on_metrics=lambda s, m: seen.append(m),
    )
    rows = list(csv.DictReader(open(tmp_path / "metrics.csv")))
    assert rows, "no metrics rows written"
    for row in rows:
        assert float(row["input_ms"]) >= 0.0
        assert 0.0 <= float(row["input_fraction"]) <= 1.0
    assert all("input_fraction" in m for m in seen)
