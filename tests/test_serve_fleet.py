"""Serve fleet robustness (ISSUE 10, docs/serving.md §Fleet).

The serve-chaos anchors: a seeded mid-workload replica kill loses no
request and duplicates none (greedy outputs bit-identical to the no-kill
baseline), a stuck decode is caught by the health check and the replica
restarts with backoff, drain finishes in-flight lanes before the replica
leaves, rollover is zero-downtime, failover preserves the ORIGINAL request
deadline, 429s carry a derived Retry-After, racing loads resolve to one
winner, and the autoscale round-trip returns chips a training tenant can
admit within one scheduler tick.
"""

from __future__ import annotations

import asyncio
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import run_async
from finetune_controller_tpu.models.generate import cached_generate
from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.resilience.faults import (
    ServeFault,
    ServeFaultInjector,
)
from finetune_controller_tpu.resilience.policy import RetryPolicy
from finetune_controller_tpu.serve.batcher import (
    Batcher,
    DeadlineExceeded,
    QueueFull,
)
from finetune_controller_tpu.serve.engine import (
    BatchEngine,
    EngineConfig,
    GenRequest,
)
from finetune_controller_tpu.serve.fleet import ReplicaFleet, ReplicaState
from finetune_controller_tpu.serve.router import FleetUnavailable, ReplicaRouter


@pytest.fixture(scope="module")
def tiny_model():
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    model = LlamaForCausalLM(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 4), jnp.int32)
    )
    return model, variables


# same shapes as tests/test_serve.py so the warm XLA cache is shared
ENGINE_CFG = dict(slots=2, prompt_buckets=(8, 16), max_new_tokens=24)


def _fleet(model, variables, **kw):
    defaults = dict(
        replicas=2,
        # comfortably above a first-use decode compile on this box (the
        # production default is 120 s for exactly this reason)
        stall_timeout_s=1.0,
        drain_timeout_s=10.0,
        restart_policy=RetryPolicy(
            max_attempts=3, base_delay_s=0.05, max_delay_s=0.1, seed=0
        ),
    )
    defaults.update(kw)
    engine_kw = defaults.pop("engine", {})
    return ReplicaFleet(
        "job-under-test", model, variables,
        EngineConfig(**{**ENGINE_CFG, **engine_kw}), **defaults,
    )


def _baseline(model, variables, prompt, n):
    out = cached_generate(
        model, variables, jnp.asarray([prompt], jnp.int32), max_new_tokens=n
    )
    return list(np.asarray(out[0, len(prompt):]))


PROMPTS = [
    [5, 9, 2, 7],
    [1, 3, 3, 8, 2, 2],
    [7, 7, 7],
    [2, 13],
    [11, 4, 9, 1],
    [3, 3, 1],
    [6, 2, 8, 8, 1],
    [9, 9],
]


def _reqs(max_new=8):
    return [
        GenRequest(request_id=f"r{i}", tokens=p, max_new_tokens=max_new)
        for i, p in enumerate(PROMPTS)
    ]


# ---------------------------------------------------------------------------
# Fault plumbing (ISSUE 10 satellite: FTC_FAULT_SERVE_*)
# ---------------------------------------------------------------------------


def test_serve_fault_env_roundtrip(tmp_path):
    once = str(tmp_path / "spent")
    fault = ServeFault(replica_id="r1", at_step=7, mode="stall",
                       once_file=once)
    env = fault.to_env()
    assert env["FTC_FAULT_SERVE_REPLICA"] == "r1"
    assert env["FTC_FAULT_SERVE_AT_STEP"] == "7"
    assert env["FTC_FAULT_SERVE_MODE"] == "stall"
    assert ServeFault.from_env(env) == fault
    # malformed / absent env arms nothing
    assert ServeFault.from_env({}) is None
    assert ServeFault.from_env({"FTC_FAULT_SERVE_REPLICA": "r0",
                                "FTC_FAULT_SERVE_AT_STEP": "x"}) is None
    assert ServeFault.from_env({"FTC_FAULT_SERVE_REPLICA": "r0",
                                "FTC_FAULT_SERVE_AT_STEP": "1",
                                "FTC_FAULT_SERVE_MODE": "nuke"}) is None


def test_serve_fault_once_file_spends(tmp_path):
    """A spent once-file keeps the fault from re-firing on a restarted
    replica (mirrors StepFault's once semantics)."""

    class FakeEngine:
        steps_total = 5
        active_requests = 1

        def step(self):
            return ["ok"]

    once = str(tmp_path / "once")
    inj = ServeFaultInjector(ServeFault("r0", at_step=1, once_file=once))
    eng = FakeEngine()
    assert inj.arm("r0", eng)
    assert not inj.arm("r9", FakeEngine())  # wrong replica: not armed
    with pytest.raises(Exception, match="killed"):
        eng.step()
    # restarted replica, same env: the once-file marks the fault spent
    inj2 = ServeFaultInjector(ServeFault("r0", at_step=1, once_file=once))
    eng2 = FakeEngine()
    inj2.arm("r0", eng2)
    assert eng2.step() == ["ok"]


# ---------------------------------------------------------------------------
# The serve-chaos anchor: replica kill → exactly once, bit-identical
# ---------------------------------------------------------------------------


def test_replica_kill_every_request_exactly_once_bit_identical(tiny_model):
    """Seeded mid-workload kill of one of two replicas: every accepted
    request completes EXACTLY once, greedy outputs bit-identical to the
    single-request anchor (== an unkilled run, by the PR-4 invariance
    proof), none lost, none duplicated."""
    model, variables = tiny_model

    async def main():
        fault = ServeFaultInjector(
            ServeFault(replica_id="r1", at_step=3, mode="kill")
        )
        fleet = _fleet(model, variables, fault=fault)
        await fleet.start()
        router = ReplicaRouter(fleet, default_timeout_s=60,
                               failover_retries=2)
        reqs = _reqs()
        results = await asyncio.gather(*(router.submit(r) for r in reqs))
        by_id = {}
        for res in results:
            assert res.request_id not in by_id, "request completed twice"
            by_id[res.request_id] = res
        assert len(by_id) == len(reqs)  # none lost
        for req in reqs:
            want = _baseline(model, variables, req.tokens, req.max_new_tokens)
            got = by_id[req.request_id]
            assert got.generated == want, f"{req.request_id} diverged"
            assert got.finish_reason == "length"
            assert got.replica_id  # the router → replica trace hop
        # the kill actually happened and was survived via failover
        assert fault.fired
        assert router.failovers_total >= 1
        stats = fleet.stats()
        assert stats["step_errors_total"] >= 1
        # aggregate counter audit: completions == accepted requests even
        # though a replica died mid-workload (retired totals folded in)
        assert stats["requests_completed_total"] == len(reqs)
        await fleet.close()

    run_async(main())


def test_router_retry_after_failure_is_classified(tiny_model):
    """Failover reuses the resilience classification: a retryable decode
    fault fails over; a per-request error (bad params) surfaces
    immediately without burning retries."""
    model, variables = tiny_model

    async def main():
        fleet = _fleet(model, variables, replicas=1)
        await fleet.start()
        router = ReplicaRouter(fleet, failover_retries=2)
        with pytest.raises(ValueError, match="engine cap"):
            await router.submit(GenRequest(
                request_id="bad", tokens=[1, 2], max_new_tokens=999,
            ))
        assert router.failovers_total == 0
        await fleet.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Stuck decode: health check + restart with backoff
# ---------------------------------------------------------------------------


def test_stuck_decode_detected_drained_and_restarted(tiny_model):
    """A wedged replica (decode stops progressing while holding lanes) is
    caught by the active health check, torn down — its requests fail over
    and still complete bit-identically — and restarted after the seeded
    backoff delay."""
    model, variables = tiny_model

    async def main():
        fault = ServeFaultInjector(
            ServeFault(replica_id="r1", at_step=2, mode="stall")
        )
        fleet = _fleet(model, variables, fault=fault, stall_timeout_s=1.0)
        await fleet.start()
        router = ReplicaRouter(fleet, default_timeout_s=60,
                               failover_retries=2)
        reqs = _reqs(max_new=6)
        tasks = [asyncio.ensure_future(router.submit(r)) for r in reqs]
        # drive health ticks until the stall is caught and everything lands
        failed: list[str] = []
        for _ in range(200):
            acts = await fleet.health_tick()
            failed.extend(acts["failed"])
            if all(t.done() for t in tasks):
                break
            await asyncio.sleep(0.05)
        results = await asyncio.gather(*tasks)
        assert failed, "the stalled replica was never caught"
        for req, res in zip(reqs, results):
            assert res.generated == _baseline(
                model, variables, req.tokens, req.max_new_tokens
            )
        # restart lands after the (tiny, seeded) backoff
        for _ in range(100):
            acts = await fleet.health_tick()
            if acts["restarted"]:
                break
            await asyncio.sleep(0.02)
        assert fleet.replica_restarts_total == 1
        stats = fleet.stats()
        assert stats["replicas_healthy"] == 2
        assert stats["replicas_failed_total"] == 1
        # the restarted replica serves traffic
        res = await router.submit(GenRequest(
            request_id="after", tokens=[5, 9, 2, 7], max_new_tokens=4,
        ))
        assert res.generated == _baseline(model, variables, [5, 9, 2, 7], 4)
        await fleet.close()

    run_async(main())


def test_restart_budget_exhaustion_probes_instead_of_dying(tiny_model):
    """Past the restart budget a zero-replica fleet keeps exactly ONE slow
    revival probe pending (bounded cadence, never a storm, never a
    permanently dead fleet holding chips) — and once the failures stop,
    the probe revives it and it serves again."""
    model, variables = tiny_model

    async def main():
        fleet = _fleet(
            model, variables, replicas=1,
            restart_policy=RetryPolicy(
                max_attempts=2, base_delay_s=0.01, max_delay_s=0.05, seed=0
            ),
        )
        await fleet.start()
        for i in range(4):
            for rid in list(fleet.replicas):
                await fleet.fail_replica(rid, error=f"boom {i}")
            # no storm, and LIVENESS: a dead fleet always has a restart
            # (or revival probe) pending
            assert len(fleet._restarts_pending) <= 1
            assert fleet.replicas or fleet._restarts_pending
            await asyncio.sleep(0.06)  # past the 0.05 backoff ceiling
            await fleet.health_tick()
        # failures stop: the pending probe revives the fleet
        for _ in range(100):
            if fleet.healthy_replicas():
                break
            await asyncio.sleep(0.02)
            await fleet.health_tick()
        router = ReplicaRouter(fleet)
        res = await router.submit(GenRequest(
            request_id="revived", tokens=[5, 9, 2, 7], max_new_tokens=4,
        ))
        assert res.generated == _baseline(model, variables, [5, 9, 2, 7], 4)
        await fleet.close()

    run_async(main())


def test_router_passes_unlimited_timeout_through(tiny_model):
    """timeout_s=0 means NO deadline end to end: the router must not let
    the batcher re-mint its default deadline for the failover-capable
    path (a regression a review caught)."""
    model, variables = tiny_model

    async def main():
        fleet = _fleet(model, variables, replicas=1)
        await fleet.start()
        router = ReplicaRouter(fleet, default_timeout_s=60)
        r0 = fleet.replicas["r0"]
        task = asyncio.ensure_future(router.submit(
            GenRequest(request_id="nolimit", tokens=[5, 9, 2, 7],
                       max_new_tokens=24),
            timeout_s=0,
        ))
        pend: list = []
        for _ in range(400):
            pend = (list(r0.batcher.queued())
                    + list(r0.batcher._inflight.values()))
            if pend:
                break
            await asyncio.sleep(0.002)
        assert pend and pend[0].deadline is None  # unlimited survived
        res = await task
        assert res.finish_reason == "length"
        await fleet.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Drain: in-flight lanes finish, admissions stop
# ---------------------------------------------------------------------------


def test_drain_finishes_inflight_and_blocks_new_admissions(tiny_model):
    model, variables = tiny_model

    async def main():
        fleet = _fleet(model, variables, replicas=2)
        await fleet.start()
        router = ReplicaRouter(fleet)
        rids = sorted(fleet.replicas)
        victim = fleet.replicas[rids[0]]
        # park a long request on the victim directly
        task = asyncio.ensure_future(victim.batcher.submit(GenRequest(
            request_id="inflight", tokens=[5, 9, 2, 7], max_new_tokens=24,
        )))
        for _ in range(200):  # admitted (or mid-admission) on the victim
            if victim.batcher._inflight:
                break
            await asyncio.sleep(0.01)
        assert victim.batcher._inflight
        drained = await fleet.drain_replica(rids[0], reason="test")
        assert drained  # in-flight lane finished inside the budget
        res = await task
        assert res.finish_reason == "length"
        assert res.generated == _baseline(model, variables, [5, 9, 2, 7], 24)
        # the drained replica is gone; new traffic lands on the survivor
        assert rids[0] not in fleet.replicas
        res2 = await router.submit(GenRequest(
            request_id="after-drain", tokens=[7, 7, 7], max_new_tokens=4,
        ))
        assert res2.replica_id == rids[1]
        assert fleet.stats()["drains_total"] == 1
        # monotonic aggregates: the drained replica's tokens are not lost
        assert fleet.stats()["tokens_generated_total"] >= 24
        await fleet.close()

    run_async(main())


def test_drain_bounces_queued_requests_to_survivor(tiny_model):
    """Requests still QUEUED on a draining replica never ran — they bounce
    with ReplicaUnavailable and the router completes them on a survivor."""
    model, variables = tiny_model

    async def main():
        fleet = _fleet(model, variables, replicas=2)
        await fleet.start()
        router = ReplicaRouter(fleet, failover_retries=2)
        rids = sorted(fleet.replicas)
        victim = fleet.replicas[rids[0]]
        # fill the victim's lanes, then queue one more behind them
        lane_tasks = [
            asyncio.ensure_future(victim.batcher.submit(GenRequest(
                request_id=f"lane{i}", tokens=[5, 9, 2, 7],
                max_new_tokens=24,
            )))
            for i in range(ENGINE_CFG["slots"])
        ]
        await asyncio.sleep(0.05)
        queued = asyncio.ensure_future(router.submit(GenRequest(
            request_id="queued", tokens=[2, 13], max_new_tokens=4,
        )))
        await asyncio.sleep(0.02)
        await fleet.drain_replica(rids[0], reason="test")
        res = await queued
        assert res.generated == _baseline(model, variables, [2, 13], 4)
        for t in lane_tasks:
            assert (await t).finish_reason == "length"
        await fleet.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Rollover: zero downtime, traffic shifts to the new generation
# ---------------------------------------------------------------------------


def test_rollover_zero_downtime_and_traffic_shift(tiny_model):
    model, variables = tiny_model

    async def main():
        fleet = _fleet(model, variables, replicas=2)
        await fleet.start()
        router = ReplicaRouter(fleet)
        gen0 = set(fleet.replicas)
        # sustained trickle of traffic THROUGH the rollover
        failures: list[BaseException] = []
        results: list = []

        async def traffic():
            i = 0
            while len(results) + len(failures) < 30:
                try:
                    results.append(await router.submit(GenRequest(
                        request_id=f"t{i}", tokens=PROMPTS[i % len(PROMPTS)],
                        max_new_tokens=4,
                    )))
                except Exception as exc:  # noqa: BLE001 - the assertion target
                    failures.append(exc)
                i += 1

        stream = asyncio.ensure_future(traffic())
        await asyncio.sleep(0.05)
        await fleet.rollover(model, variables)
        await stream
        assert not failures, f"rollover dropped requests: {failures[:3]}"
        for res in results:
            want = _baseline(
                model, variables, res.prompt_tokens, len(res.generated)
            )
            assert res.generated == want
        # old generation fully drained; fleet is generation 1
        assert not (gen0 & set(fleet.replicas))
        stats = fleet.stats()
        assert stats["generation"] == 1
        assert stats["rollovers_total"] == 1
        assert stats["replicas_healthy"] == 2
        # post-rollover traffic decodes on the new generation only
        res = await router.submit(GenRequest(
            request_id="post", tokens=[5, 9, 2, 7], max_new_tokens=4,
        ))
        assert res.replica_id in set(fleet.replicas) - gen0
        await fleet.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Failover deadline semantics (ISSUE 10 satellite)
# ---------------------------------------------------------------------------


def test_failover_keeps_original_deadline_and_drops_once(tiny_model):
    """A request re-enqueued on a survivor keeps its ORIGINAL deadline (the
    survivor's pending entry carries the same absolute instant), and the
    post-failover deadline drop decrements slot/queue gauges exactly once
    across the fleet."""
    model, variables = tiny_model

    async def main():
        # survivor r1 is wedged from its first step: the failed-over request
        # can never finish there, so only its ORIGINAL deadline can end it
        fault = ServeFaultInjector(
            ServeFault(replica_id="r1", at_step=0, mode="stall")
        )
        fleet = _fleet(model, variables, fault=fault, stall_timeout_s=60)
        await fleet.start()
        router = ReplicaRouter(fleet, failover_retries=2)
        r0 = fleet.replicas["r0"]
        r1 = fleet.replicas["r1"]
        timeout_s = 1.2
        t0 = time.monotonic()
        task = asyncio.ensure_future(router.submit(
            GenRequest(request_id="doomed", tokens=[5, 9, 2, 7],
                       max_new_tokens=24),
            timeout_s=timeout_s,
        ))
        # the request lands on r0 (r1 idle, tie broken by id) — kill r0
        # once it is in flight there (admission may pay a prefill compile)
        for _ in range(100):
            if r0.batcher._inflight:
                break
            await asyncio.sleep(0.005)
        assert r0.batcher._inflight
        await fleet.fail_replica("r0", error="test kill", restart=False)
        # failed over to r1 with the ORIGINAL absolute deadline
        pend: list = []
        for _ in range(100):
            pend = (list(r1.batcher._inflight.values())
                    + list(r1.batcher.queued()))
            if pend:
                break
            await asyncio.sleep(0.005)
        assert len(pend) == 1
        assert pend[0].deadline == pytest.approx(t0 + timeout_s, abs=0.1)
        with pytest.raises(DeadlineExceeded):
            await task
        elapsed = time.monotonic() - t0
        # ended by the original deadline, NOT a fresh one minted at failover
        # (a re-minted deadline could not expire before ~2x timeout_s)
        assert elapsed < timeout_s + 0.5, elapsed
        # the drop was accounted exactly once fleet-wide, and the gauges
        # returned to baseline (no leaked slot/queue occupancy)
        stats = fleet.stats()
        assert stats["deadline_drops_total"] == 1
        assert stats["queue_depth"] == 0
        assert stats["slots_busy"] == 0
        assert r1.engine.free_slots == r1.engine.config.slots
        await fleet.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Router: idempotent request ids, shedding
# ---------------------------------------------------------------------------


def test_router_duplicate_request_id_never_double_decodes(tiny_model):
    model, variables = tiny_model

    async def main():
        fleet = _fleet(model, variables, replicas=1)
        await fleet.start()
        router = ReplicaRouter(fleet)
        req = GenRequest(request_id="dup", tokens=[5, 9, 2, 7],
                         max_new_tokens=6)
        # concurrent duplicates attach to ONE in-flight attempt
        a, b = await asyncio.gather(router.submit(req), router.submit(req))
        assert a.generated == b.generated
        assert router.duplicates_suppressed_total == 1
        tokens_after = fleet.stats()["tokens_generated_total"]
        assert tokens_after == 6  # decoded once, not twice
        # a replay after completion returns the cached result, no decode
        c = await router.submit(req)
        assert c.generated == a.generated
        assert fleet.stats()["tokens_generated_total"] == tokens_after
        assert router.duplicates_suppressed_total == 2
        await fleet.close()

    run_async(main())


def test_router_sheds_with_retry_after_when_all_queues_full(tiny_model):
    model, variables = tiny_model

    async def main():
        fleet = _fleet(
            model, variables, replicas=1,
            batcher_kwargs={"max_queue": 0},
        )
        await fleet.start()
        router = ReplicaRouter(fleet)
        with pytest.raises(QueueFull) as exc_info:
            await router.submit(GenRequest(
                request_id="shed", tokens=[1, 2], max_new_tokens=2,
            ))
        assert exc_info.value.retry_after_s >= 1.0
        assert router.shed_total == 1
        await fleet.close()

    run_async(main())


def test_router_no_healthy_replica_is_503_shaped(tiny_model):
    model, variables = tiny_model

    async def main():
        fleet = _fleet(model, variables, replicas=1)
        await fleet.start()
        router = ReplicaRouter(fleet)
        await fleet.fail_replica("r0", error="gone", restart=False)
        with pytest.raises(FleetUnavailable):
            await router.submit(GenRequest(
                request_id="x", tokens=[1], max_new_tokens=2,
            ))
        await fleet.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Retry-After estimation (ISSUE 10 satellite)
# ---------------------------------------------------------------------------


def test_retry_after_derived_from_queue_depth_and_decode_rate(tiny_model):
    model, variables = tiny_model

    async def main():
        eng = BatchEngine(model, variables, EngineConfig(**ENGINE_CFG))
        b = Batcher(eng, max_queue=64)
        assert b.retry_after_s() == 1.0  # no signal yet: the floor
        for i in range(4):
            await b.submit(GenRequest(
                request_id=f"w{i}", tokens=[5, 9, 2, 7], max_new_tokens=8,
            ))
        base = b.retry_after_s()
        assert base >= 1.0
        # a (much) deeper queue means a later retry hint: deep enough that
        # the estimate clears the 1 s floor regardless of box speed
        import collections
        b._queues[""] = collections.deque([object()] * 5000)  # type: ignore
        deep = b.retry_after_s()
        assert deep > base
        assert deep <= 120.0
        b._queues.clear()
        await b.close()

    run_async(main())


@pytest.mark.slow  # HTTP loop; runs in ci_check serve-chaos-fast/serve-fast
def test_http_429_carries_retry_after_header(tmp_path):
    from test_api import _client
    from test_serve import _fabricate_promoted_job, _serve_runtime

    async def main():
        rt = _serve_runtime(tmp_path)
        rt.settings.serve_max_queue = 0
        client = await _client(rt, with_monitor=False)
        job_id = await _fabricate_promoted_job(rt)
        r = await client.post(f"/api/v1/admin/serve/{job_id}/load")
        assert r.status == 200
        r = await client.post(
            f"/api/v1/jobs/{job_id}/generate",
            json={"tokens": [1, 2], "max_new_tokens": 2},
        )
        assert r.status == 429
        assert int(r.headers["Retry-After"]) >= 1
        assert (await r.json())["retry_after_s"] >= 1
        await client.close()

    run_async(main())


def test_ctl_generate_honors_retry_after_once(capsys):
    """`ftc-ctl generate` backs off for the server's Retry-After and retries
    exactly once — a second 429 surfaces."""
    import argparse

    from finetune_controller_tpu.controller import ctl

    calls = {"n": 0}

    class StubClient:
        async def post(self, path, json=None):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ctl.ApiError("POST -> 429: busy", status=429,
                                   retry_after_s=0.01)
            return {"job_id": "j", "tokens": [1, 2], "request_id": "r"}

    ns = argparse.Namespace(
        job_id="j", tokens="5,9", max_new_tokens=None, temperature=None,
        top_k=None, eos_id=None, seed=None,
    )
    rc = run_async(ctl.cmd_generate(StubClient(), ns))
    assert rc == 0
    assert calls["n"] == 2
    out = capsys.readouterr()
    assert '"tokens"' in out.out
    assert "retrying once" in out.err

    # a 429 with no Retry-After (or a non-429) is NOT retried
    calls["n"] = 0

    class AlwaysBusy(StubClient):
        async def post(self, path, json=None):
            calls["n"] += 1
            raise ctl.ApiError("POST -> 429: busy", status=429)

    with pytest.raises(ctl.ApiError):
        run_async(ctl.cmd_generate(AlwaysBusy(), ns))
    assert calls["n"] == 1


# ---------------------------------------------------------------------------
# Concurrent loads: one winner (ISSUE 10 satellite)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # loader + HTTP runtime; runs in ci_check serve stages
def test_concurrent_loads_resolve_to_one_winner(tmp_path, monkeypatch):
    from test_api import _runtime
    from test_serve import _fabricate_promoted_job

    from finetune_controller_tpu.serve import service as service_mod

    async def main():
        rt = _runtime(tmp_path)
        rt.settings.serve_slots = 4
        rt.settings.serve_prompt_buckets = [8, 16]
        rt.settings.serve_max_new_tokens = 32
        await rt.state.connect()
        job_id = await _fabricate_promoted_job(rt)
        real = service_mod.load_promoted
        loads = {"n": 0}

        async def counting_load(*args, **kw):
            loads["n"] += 1
            await asyncio.sleep(0.05)  # widen the race window
            return await real(*args, **kw)

        monkeypatch.setattr(service_mod, "load_promoted", counting_load)
        manager = service_mod.ServeManager(
            rt.state, rt.store, rt.settings
        )
        rt.serve = manager  # rt.close() tears the sessions down
        meta1, meta2 = await asyncio.gather(
            manager.load(job_id), manager.load(job_id)
        )
        # ONE winner staged and loaded; the loser attached to its future
        assert loads["n"] == 1
        assert meta1 is meta2 or meta1 == meta2
        assert len(manager.sessions) == 1
        # the session serves
        result, _meta = await manager.generate(job_id, GenRequest(
            request_id="g", tokens=[5, 9, 2, 7], max_new_tokens=4,
        ))
        assert len(result.generated) == 4
        # a follow-up load of the SAME artifact is idempotent: the peek
        # pre-check answers from a store LISTING — no re-download, no
        # rollover, no extra loader call
        meta3 = await manager.load(job_id)
        assert meta3["checkpoint_step"] == meta1["checkpoint_step"]
        assert manager.sessions[job_id].fleet.generation == 0
        assert loads["n"] == 1
        await rt.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Autoscale round-trip: serve as a preemptible scheduler tenant
# ---------------------------------------------------------------------------


def _catalog(quota=4):
    from finetune_controller_tpu.controller.devices import (
        DeviceCatalog,
        DeviceFlavor,
        FlavorQuota,
    )

    return DeviceCatalog(
        flavors=[DeviceFlavor(
            name="chip", generation="cpu", hosts=1, chips_per_host=1,
            runtime="cpu", queue="q",
        )],
        quotas=[FlavorQuota(flavor="chip", nominal_chips=quota)],
        default_flavor="chip",
    )


def test_autoscale_grow_shrink_and_training_reclaims_in_one_tick(tiny_model):
    """The ISSUE 10 autoscale round-trip: queue-depth pressure grows the
    fleet through scheduler admissions, idleness shrinks it via DRAIN, and
    the reclaimed chips admit a training tenant within one scheduler tick."""
    from finetune_controller_tpu.sched import FairShareScheduler
    from finetune_controller_tpu.sched.serve_tenant import (
        ServeScalePolicy,
        ServeTenant,
    )

    model, variables = tiny_model

    async def main():
        sched = FairShareScheduler(
            _catalog(quota=4), {"serve": 1.0, "train": 1.0},
        )
        fleet = _fleet(model, variables, replicas=1)
        await fleet.start()
        depth = {"value": 0}
        tenant = ServeTenant(
            sched, fleet, flavor="chip", queue="serve",
            policy=ServeScalePolicy(
                min_replicas=1, max_replicas=3,
                scale_up_queue_depth=2, sustain_ticks=1, idle_ticks=1,
            ),
            drive_admission=True,
            queue_depth_fn=lambda: depth["value"],
        )
        await tenant.attach_initial()
        # --- grow under sustained queue pressure --------------------------
        depth["value"] = 12
        for _ in range(8):
            await tenant.tick()
            if fleet.stats()["replicas_healthy"] == 3:
                break
        assert fleet.stats()["replicas_healthy"] == 3
        assert tenant.scale_ups_total >= 2
        # serve now holds 3 of 4 chips in the scheduler's accounting
        used = sum(
            1 for wl in tenant._workloads.values()
            if sched.is_admitted(wl.workload_id)
        )
        assert used == 3
        # --- idle: shrink via drain (never kill) --------------------------
        depth["value"] = 0
        for _ in range(8):
            await tenant.tick()
            if fleet.stats()["replicas_healthy"] == 1:
                break
        assert fleet.stats()["replicas_healthy"] == 1
        assert fleet.drains_total >= 2  # scale-down went through drain
        assert tenant.scale_downs_total >= 2
        # --- training reclaims the freed chips in ONE tick ----------------
        sched.submit("train-big", "chip", 3, queue="train",
                     priority="normal")
        admitted = sched.try_admit()
        assert any(w.job_id == "train-big" for w in admitted)
        await fleet.close()

    run_async(main())


def test_preempted_serve_workload_drains_inflight_then_releases(tiny_model):
    """A training tenant preempting a serve replica triggers a DRAIN — the
    replica's in-flight request completes — and the released chips admit
    the preemptor on the next pass."""
    from finetune_controller_tpu.sched import FairShareScheduler
    from finetune_controller_tpu.sched.serve_tenant import (
        ServeScalePolicy,
        ServeTenant,
    )

    model, variables = tiny_model

    async def main():
        sched = FairShareScheduler(
            _catalog(quota=2), {"serve": 1.0, "train": 1.0},
        )
        fleet = _fleet(model, variables, replicas=2)
        await fleet.start()
        tenant = ServeTenant(
            sched, fleet, flavor="chip", queue="serve", priority="low",
            policy=ServeScalePolicy(min_replicas=1, max_replicas=2,
                                    scale_up_queue_depth=10**6),
            drive_admission=True,
        )
        await tenant.attach_initial()
        sched.try_admit()  # both serve workloads admitted: cluster full
        # park a long request on each replica
        router = ReplicaRouter(fleet)
        tasks = [
            asyncio.ensure_future(router.submit(GenRequest(
                request_id=f"long{i}", tokens=[5, 9, 2, 7],
                max_new_tokens=24,
            )))
            for i in range(2)
        ]
        await asyncio.sleep(0.05)
        # higher-priority training job wants a chip -> plans a preemption
        sched.submit("train-1", "chip", 1, queue="train", priority="normal")
        sched.try_admit()
        summary = await tenant.tick()
        assert summary["preempted"], "no serve workload was preempted"
        # the drain let the in-flight request finish (never killed)
        for t in tasks:
            res = await t
            assert res.finish_reason == "length"
        assert fleet.stats()["replicas_healthy"] == 1
        assert tenant.preempted_total == 1
        # the preemptor admits now that the chips are released (the tick's
        # own admission pass may already have done it)
        sched.try_admit()
        assert sched.is_admitted("train-1")
        await fleet.close()

    run_async(main())


def test_local_backend_skips_serve_owned_workloads():
    """The local backend's admission pass must leave serve-owned workloads
    alone: no tombstone FAILED report, no release — their lifecycle belongs
    to the serve tenant."""
    from finetune_controller_tpu.sched import FairShareScheduler

    class FakeBackend:
        """Just the _admit_pending-relevant surface."""

    async def main():
        from finetune_controller_tpu.controller.backends.local import (
            LocalProcessBackend,
        )

        sched = FairShareScheduler(_catalog(quota=2), {"serve": 1.0})
        backend = LocalProcessBackend.__new__(LocalProcessBackend)
        backend.scheduler = sched
        backend._handles = {}
        backend._lost = {}
        backend._closing = False
        sched.submit("serve-j-w0", "chip", 1, queue="serve", owner="serve")
        backend._admit_pending()
        assert sched.is_admitted("serve-j-w0")  # admitted, NOT released
        assert backend._lost == {}  # and no tombstone

    run_async(main())


def test_take_preemptions_owner_filter():
    """take_preemptions(owner=...) routes each plane its own victims and
    leaves the other plane's decisions pending."""
    from finetune_controller_tpu.sched import FairShareScheduler

    sched = FairShareScheduler(
        _catalog(quota=2), {"serve": 1.0, "train": 4.0},
    )
    sched.submit("serve-w0", "chip", 1, queue="serve", priority="low",
                 owner="serve")
    sched.submit("train-old", "chip", 1, queue="train", priority="low")
    sched.try_admit()
    sched.submit("train-new", "chip", 2, queue="train", priority="high")
    sched.try_admit()
    pending = list(sched._pending_preemptions)
    assert {d.job_id for d in pending} == {"serve-w0", "train-old"}
    train_side = sched.take_preemptions(owner="train")
    assert {d.job_id for d in train_side} == {"train-old"}
    serve_side = sched.take_preemptions(owner="serve")
    assert {d.job_id for d in serve_side} == {"serve-w0"}
    assert sched.take_preemptions() == []


# ---------------------------------------------------------------------------
# Cross-process transport (ISSUE 12): the same chaos, against a REAL process
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_replica_kill_is_a_real_sigkill_in_process_mode(tmp_path):
    """The ISSUE 12 satellite pin for THIS suite: the identical
    ``FTC_FAULT_SERVE_*`` env that drives the in-process kill above is
    forwarded into worker-process spawns (``serve_transport=process``), so
    the victim worker REALLY SIGKILLs itself mid-decode — detection,
    failover, exactly-once and respawn all run against genuine process
    death.  The deeper protocol proofs live in ``tests/test_transport.py``;
    this test keeps the serve-chaos suite honest about which fault it
    exercises."""
    import os

    from finetune_controller_tpu.transport.process import ProcessTransport

    async def main():
        once = tmp_path / "spent"
        transport = ProcessTransport(
            job_id="job-under-test", root=tmp_path / "workers",
            payload={"builder": "tiny_test", "kwargs": {"lora_rank": 4}},
            spawn_timeout_s=240.0, heartbeat_interval_s=0.5,
            extra_env=ServeFault(
                replica_id="r0", at_step=2, mode="kill",
                once_file=str(once),
            ).to_env(),
        )
        fleet = ReplicaFleet(
            "job-under-test", None, None, EngineConfig(**ENGINE_CFG),
            replicas=2, transport=transport, stall_timeout_s=30.0,
            restart_policy=RetryPolicy(
                max_attempts=3, base_delay_s=0.1, max_delay_s=0.3, seed=0
            ),
        )
        await fleet.start()
        victim_pids = set(fleet.stats()["worker_pids"])
        router = ReplicaRouter(fleet, default_timeout_s=120,
                               failover_retries=2)

        async def health_loop():
            while True:
                await fleet.health_tick()
                await asyncio.sleep(0.1)

        hl = asyncio.ensure_future(health_loop())
        try:
            results = await asyncio.gather(
                *(router.submit(r) for r in _reqs(max_new=8))
            )
            seen = {r.request_id: r.generated for r in results}
            assert len(seen) == len(PROMPTS)
            assert once.exists(), "the forwarded fault never fired"
            # bit-identical to cached_generate — across process boundaries
            model, variables = _worker_payload()
            for rid, toks in seen.items():
                i = int(rid[1:])
                assert [int(t) for t in toks] == \
                    _baseline(model, variables, PROMPTS[i], 8), rid
            # the SIGKILLed pid is gone and a FRESH process respawned
            for _ in range(150):
                if len(fleet.healthy_replicas()) >= 2 \
                        and fleet.replica_restarts_total >= 1:
                    break
                await asyncio.sleep(0.2)
            assert fleet.replica_restarts_total >= 1
            new_pids = set(fleet.stats()["worker_pids"])
            assert new_pids - victim_pids, "no fresh worker process spawned"
            dead = victim_pids - new_pids
            assert dead, "the victim pid is still in the fleet"
            for pid in dead:
                with pytest.raises(ProcessLookupError):
                    os.kill(pid, 0)
        finally:
            hl.cancel()
            await fleet.close()

    run_async(main())


def _worker_payload():
    """EXACTLY the worker builder's payload (transport/builders.py
    tiny_test(lora_rank=4)) — the same weights this module's ``tiny_model``
    fixture builds, constructed here so the bit-identity assertion names
    its comparator explicitly."""
    from finetune_controller_tpu.transport.builders import tiny_test

    return tiny_test(lora_rank=4)
