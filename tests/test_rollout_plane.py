"""Disaggregated RLHF data plane (ISSUE 19, docs/preference.md
§Disaggregated rollouts).

Anchors: the rollout RPC protocol is idempotent end to end (re-delivered
start/pull/ack/policy pushes change nothing); the worker's outbox replays
byte-identical round documents at a cursor; deterministic regeneration makes
a respawned worker re-emit the SAME pair ids so the learner's dedup keeps
every pair exactly-once across kills; policy rollover is a monotonic
adapter-delta push installed between rounds (never a reload stall); the
plane re-pushes its cached policy to every respawned incarnation BEFORE
streaming resumes; `remote_rollout_batch_stream` ships committed checkpoints
to the fleet and enforces the staleness watermark; RolloutTenant accounts
worker chips in the scheduler's rollout queue and hands preempted workers
back; DPOTrainer's prefetch=0/blocking-commit coupling applies ONLY to the
in-process loop (remote mode keeps both freedoms); and the slow-marked
chaos run SIGKILLs a real worker process mid-round — the learner keeps
stepping on buffered pairs, the worker respawns with backoff and resumes
streaming, and no duplicate pair ever enters the buffer.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import time

import numpy as np
import pytest

from conftest import run_async
from finetune_controller_tpu.models.llama import PRESETS
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.prefs import rollout_plane as rp
from finetune_controller_tpu.prefs.dpo_trainer import DPOTrainer
from finetune_controller_tpu.prefs.learner import RolloutConfig
from finetune_controller_tpu.prefs.rollout_buffer import (
    PreferencePair,
    RolloutBuffer,
)
from finetune_controller_tpu.prefs.rollout_plane import (
    RewardScorer,
    RolloutPlane,
    RolloutService,
    build_remote_rlhf_loop,
    pair_id,
    remote_rollout_batch_stream,
    write_rollout_base,
)
from finetune_controller_tpu.resilience.policy import RetryPolicy
from finetune_controller_tpu.train.trainer import TrainConfig
from finetune_controller_tpu.transport.wire import tree_from_blob, tree_to_blob


def _wait(cond, timeout=10.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# pair documents
# ---------------------------------------------------------------------------


def test_pair_id_and_doc_roundtrip():
    assert pair_id(3, 7, 1) == "v3:r7:p1"
    pair = PreferencePair(
        prompt=(1, 2), chosen=(1, 2, 3), rejected=(1, 2, 4),
        version=5, reward_chosen=1.5, reward_rejected=-0.5,
    )
    doc = rp._pair_doc(pair, pair_id(5, 2, 0))
    assert doc["id"] == "v5:r2:p0"
    # wire-safe: plain ints/floats/lists only
    json.dumps(doc)
    assert rp._pair_from_doc(doc) == pair


# ---------------------------------------------------------------------------
# RolloutService protocol (fake actor — no engine, pure protocol semantics)
# ---------------------------------------------------------------------------


class _FakeActor:
    """Deterministic per (seed, version, round) — the real actor's
    regeneration contract, without an engine."""

    def __init__(self, seed=0, fail_after=None):
        self.seed = seed
        self.version = 0
        self.rounds = 0
        self.pairs_generated = 0
        self.tokens_generated = 0
        self.generate_seconds = 0.0
        self.installs: list[tuple[int, object]] = []
        self._fail_after = fail_after

    @property
    def tokens_per_sec(self):
        return self.tokens_generated / max(self.generate_seconds, 1e-9)

    def install_policy(self, version, tree):
        if int(version) <= self.version:
            return False
        self.version = int(version)
        self.installs.append((self.version, tree))
        return True

    def generate_pairs(self, n):
        if self._fail_after is not None and self.rounds >= self._fail_after:
            raise RuntimeError("synthetic actor fault")
        self.rounds += 1
        out = []
        for i in range(n):
            base = (self.seed * 811 + self.version * 97
                    + self.rounds * 13 + i) % 23
            prompt = (base % 7 + 1, (base + 1) % 7 + 1)
            out.append(PreferencePair(
                prompt=prompt,
                chosen=prompt + ((base + 2) % 7 + 1,),
                rejected=prompt + ((base + 3) % 7 + 1,),
                version=self.version,
                reward_chosen=1.0, reward_rejected=0.0,
            ))
        self.pairs_generated += n
        self.tokens_generated += 2 * n
        self.generate_seconds += 1e-4
        return out


def test_service_start_is_idempotent_and_pull_replays_identically():
    svc = RolloutService(_FakeActor(seed=1), max_outbox_rounds=4)
    try:
        assert svc.start(2)["started"]
        assert svc.start(2)["started"]  # re-delivered start: no second thread
        assert _wait(lambda: svc.pull(0)["rounds"])
        first = svc.pull(0, max_rounds=2)
        again = svc.pull(0, max_rounds=2)
        # a re-delivered pull replays byte-identical round documents
        assert first["rounds"] == again["rounds"]
        ids = [p["id"] for r in first["rounds"] for p in r["pairs"]]
        assert len(ids) == len(set(ids))
        assert all(r["span"]["end_ns"] >= r["span"]["start_ns"]
                   for r in first["rounds"])
    finally:
        svc.stop()


def test_service_ack_trims_and_backpressures_the_producer():
    svc = RolloutService(_FakeActor(), max_outbox_rounds=3)
    try:
        svc.start(1)
        # producer fills to the outbox bound, then parks
        assert _wait(lambda: len(svc.pull(0)["rounds"]) == 3)
        time.sleep(0.05)
        out = svc.pull(0)
        assert len(out["rounds"]) == 3  # bounded: no 4th round piled up
        top = out["rounds"][-1]["seq"]
        acked = svc.ack(out["rounds"][0]["seq"])
        assert acked["acked"] == 1 and acked["outbox_depth"] == 2
        # stale ack is a no-op
        assert svc.ack(0)["acked"] == 0
        # the ack woke the producer: new rounds continue PAST the old top
        assert _wait(lambda: svc.pull(top)["rounds"])
    finally:
        svc.stop()


def test_service_policy_push_is_monotonic_and_installs_between_rounds():
    actor = _FakeActor()
    svc = RolloutService(actor, max_outbox_rounds=64)
    blob = tree_to_blob({"w": np.ones((2,), np.float32)})
    try:
        # pushed before start(): installs inline
        assert svc.push_policy(3, blob)["accepted"]
        assert actor.version == 3
        svc.start(1)
        assert _wait(lambda: svc.pull(0)["rounds"])
        # stale and duplicate pushes are no-ops
        assert not svc.push_policy(3, blob)["accepted"]
        assert not svc.push_policy(2, blob)["accepted"]
        assert actor.version == 3
        # a newer push is installed by the producer between rounds
        assert svc.push_policy(8, blob)["accepted"]
        assert _wait(lambda: actor.version == 8)
        top = svc.pull(0)["seq"]
        svc.ack(top)  # unpark the (possibly backpressured) producer
        assert _wait(lambda: any(
            r["version"] == 8 for r in svc.pull(top)["rounds"]
        ))
        (v, tree), = actor.installs[-1:]
        assert v == 8 and np.allclose(tree["w"], 1.0)
    finally:
        svc.stop()


def test_service_producer_death_surfaces_on_pull_not_silently():
    svc = RolloutService(_FakeActor(fail_after=2), max_outbox_rounds=64)
    try:
        svc.start(1)

        def _died():
            try:
                svc.pull(0)
                return False
            except RuntimeError:
                return True

        assert _wait(_died)
        with pytest.raises(RuntimeError, match="synthetic actor fault"):
            svc.pull(0)
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# RolloutPlane (fake worker handles — dedup / respawn / policy re-push)
# ---------------------------------------------------------------------------


class _FakeBackend:
    """One remote worker's deterministic round source, shared across its
    incarnations: every incarnation regenerates the SAME rounds from seq 1
    (the deterministic-regeneration contract that makes pair ids collide)."""

    def __init__(self, seed, *, pairs_per_round=2, total_rounds=6,
                 die_on_incarnation=None, die_after_pulls=2):
        self.seed = seed
        self.pairs_per_round = pairs_per_round
        self.total_rounds = total_rounds
        self.die_on_incarnation = die_on_incarnation
        self.die_after_pulls = die_after_pulls
        self.version = 0
        self.events: list[tuple] = []

    def make_round(self, seq):
        pairs = []
        for i in range(self.pairs_per_round):
            base = (self.seed * 811 + seq * 13 + i) % 23
            prompt = [base % 7 + 1, (base + 1) % 7 + 1]
            pairs.append({
                "id": pair_id(self.version, seq, i),
                "prompt": prompt,
                "chosen": prompt + [(base + 2) % 7 + 1],
                "rejected": prompt + [(base + 3) % 7 + 1],
                "version": self.version,
                "reward_chosen": 1.0, "reward_rejected": 0.0,
            })
        return {
            "seq": seq, "round": seq, "version": self.version,
            "pairs": pairs,
            "span": {"start_ns": seq * 1000, "end_ns": seq * 1000 + 500,
                     "pairs": len(pairs), "tokens": 2 * len(pairs)},
        }


class _FakeHandle:
    def __init__(self, backend: _FakeBackend, generation: int):
        self.backend = backend
        self.generation = generation
        self.produced = 0
        self.pulls = 0
        self.closed = False

    async def rollout_start(self, pairs_per_round):
        self.backend.events.append(("start", self.generation))
        return {"started": True, "seq": 0, "version": self.backend.version}

    async def rollout_policy_version(self, version, blob):
        self.backend.events.append(
            ("policy", self.generation, int(version))
        )
        accepted = int(version) > self.backend.version
        if accepted:
            self.backend.version = int(version)
        return {"accepted": accepted, "version": self.backend.version,
                "pending": False}

    async def rollout_pull(self, after_seq, max_rounds=8):
        if self.closed:
            raise ConnectionError("handle closed")
        self.pulls += 1
        b = self.backend
        if (b.die_on_incarnation == self.generation
                and self.pulls > b.die_after_pulls):
            raise ConnectionError("worker killed")
        self.produced = min(b.total_rounds, self.produced + 1)
        rounds = [
            b.make_round(s)
            for s in range(int(after_seq) + 1, self.produced + 1)
        ][: max_rounds]
        return {
            "rounds": rounds, "seq": self.produced, "version": b.version,
            "stats": {"actor_tokens_per_sec": 42.0,
                      "actor_version": b.version,
                      "actor_tokens_generated": 2 * self.produced,
                      "actor_generate_seconds": 0.01 * self.produced},
        }

    async def rollout_ack(self, up_to_seq):
        return {"acked": 0, "outbox_depth": 0}

    async def close(self, exc=None):
        self.closed = True


def _mk_plane(buffer, backends, **kw):
    handles = []

    async def spawn_fn(worker_id, generation):
        h = _FakeHandle(backends[worker_id], generation)
        handles.append(h)
        return h

    plane = RolloutPlane(
        buffer, num_workers=len(backends), spawn_fn=spawn_fn,
        pairs_per_round=2,
        retry=RetryPolicy(max_attempts=10**9, base_delay_s=0.01,
                          max_delay_s=0.05, seed=0),
        idle_sleep_s=0.005, **kw,
    )
    return plane, handles


def test_plane_respawns_dead_worker_and_dedups_regenerated_pairs():
    backend = _FakeBackend(
        seed=5, total_rounds=6, die_on_incarnation=1, die_after_pulls=3
    )
    buffer = RolloutBuffer(256)
    plane, handles = _mk_plane(buffer, {"rollout-0": backend})
    try:
        plane.start()
        # incarnation 1 dies after a few rounds; incarnation 2 regenerates
        # from seq 1 and must stream through to the end
        assert _wait(lambda: plane.respawns_total >= 1, timeout=20)
        assert _wait(
            lambda: buffer.pushed_total == 6 * backend.pairs_per_round,
            timeout=20,
        )
        # exactly-once: every regenerated (replayed) pair was suppressed
        assert plane.dup_pairs_total >= backend.pairs_per_round
        assert buffer.pushed_total == 6 * backend.pairs_per_round
        assert len(handles) >= 2
        assert handles[0].generation == 1 and handles[-1].generation >= 2
        assert plane.workers_alive() == 1
        st = plane.stats()
        assert st["rollout_respawns_total"] >= 1
        assert st["rollout_dup_pairs_total"] == plane.dup_pairs_total
        assert st["actor_tokens_per_sec"] == 42.0
    finally:
        plane.close()
    assert all(h.closed for h in handles)


def test_plane_repushes_cached_policy_to_respawned_worker_before_start():
    backend = _FakeBackend(
        seed=2, total_rounds=4, die_on_incarnation=1, die_after_pulls=2
    )
    buffer = RolloutBuffer(256)
    plane, handles = _mk_plane(buffer, {"rollout-0": backend})
    try:
        plane.start()
        assert _wait(lambda: buffer.pushed_total > 0, timeout=20)
        plane.push_policy(7, {"w": np.ones((2,), np.float32)})
        assert _wait(lambda: plane.respawns_total >= 1, timeout=20)
        assert _wait(
            lambda: ("policy", 2, 7) in backend.events, timeout=20
        )
        # the cached delta reached incarnation 2 BEFORE its stream started
        gen2 = [e for e in backend.events if e[1] == 2]
        assert gen2.index(("policy", 2, 7)) < gen2.index(("start", 2))
        assert plane._policy is not None and plane._policy[0] == 7
    finally:
        plane.close()


def test_remote_stream_ships_committed_checkpoints_and_evicts_stale():
    class _FakeReader:
        def __init__(self):
            self.step = None

        def latest_step(self):
            return self.step

        def restore(self, step, like=None):
            assert like is not None  # shape-validated restore path
            return {"trainable": {"w": np.full((2,), float(step),
                                               np.float32)}}

    # unbounded round supply: fresh (post-rollover) rounds must keep
    # arriving after the staleness eviction empties the buffer
    backend = _FakeBackend(seed=9, total_rounds=10**9)
    buffer = RolloutBuffer(256, version_granularity=1)
    plane, handles = _mk_plane(buffer, {"rollout-0": backend})
    reader = _FakeReader()
    rollout = RolloutConfig(pairs_per_round=2, min_fill=4,
                            staleness_checkpoints=1)
    try:
        plane.start()
        stream = remote_rollout_batch_stream(
            plane, reader, {"trainable": None},
            batch_size=2, seq_len=8, checkpoint_every=1, rollout=rollout,
            fill_timeout_s=30.0,
        )
        batch = next(stream)
        assert batch["chosen_tokens"].shape == (2, 8)
        assert not any(e[0] == "policy" for e in backend.events)
        # a committed checkpoint ships its trainable tree to the fleet...
        reader.step = 5
        next(stream)
        assert ("policy", 1, 5) in backend.events
        assert plane._policy[0] == 5
        blob_tree = tree_from_blob(plane._policy[1])
        assert np.allclose(blob_tree["w"], 5.0)
        # ...and the staleness watermark evicts every pre-rollover pair
        _wait(lambda: buffer.depth >= rollout.min_fill, timeout=20)
        next(stream)
        assert all(p.version >= 4 for p in buffer._pairs)
        assert buffer.evicted_stale_total > 0
    finally:
        plane.close()


# ---------------------------------------------------------------------------
# loopback rollout worker: the real RPC surface over the real wire
# ---------------------------------------------------------------------------


def test_rollout_worker_loopback_protocol_and_deterministic_regen(tmp_path):
    from finetune_controller_tpu.transport.client import (
        RemoteReplica,
        _Connection,
    )
    from finetune_controller_tpu.transport.worker import (
        WorkerSpec,
        build_worker,
    )

    def _spec(sandbox):
        return WorkerSpec(
            job_id="rl-loop", replica_id="w0", sandbox=str(sandbox),
            builder="tiny_test", builder_kwargs={}, engine={}, batcher={},
            rollout={"seq_len": 16, "prompt_fraction": 0.5,
                     "max_new_tokens": 8, "slots": 2, "seed": 11},
            warm_start=False,
        )

    async def _harvest(sandbox, n_rounds):
        os.makedirs(sandbox, exist_ok=True)
        server = build_worker(_spec(sandbox), exit_on_drain=False)
        port = await server.start()
        conn = await _Connection.open("127.0.0.1", port)
        hello = await conn.call("hello", {}, timeout_s=30)
        rep = RemoteReplica("w0", conn, hello, sandbox=str(sandbox),
                            heartbeat_interval_s=0.5)
        try:
            assert (await rep.rollout_start(2))["started"]
            assert (await rep.rollout_start(2))["started"]  # idempotent
            deadline = time.monotonic() + 120
            rounds = []
            while len(rounds) < n_rounds:
                assert time.monotonic() < deadline, "no rollout rounds"
                out = await rep.rollout_pull(0, max_rounds=n_rounds)
                rounds = out["rounds"]
                await asyncio.sleep(0.05)
            # replayed pull returns byte-identical documents per seq
            replay = {
                r["seq"]: r
                for r in (await rep.rollout_pull(0, n_rounds))["rounds"]
            }
            for r in rounds:
                assert replay[r["seq"]]["pairs"] == r["pairs"]
            # ack trims: seq 1 never comes back
            await rep.rollout_ack(rounds[0]["seq"])
            left = (await rep.rollout_pull(0, 64))["rounds"]
            assert all(r["seq"] > rounds[0]["seq"] for r in left)
            # stale policy push is a no-op over the wire too
            out = await rep.rollout_policy_version(0, None)
            assert not out["accepted"]
            return rounds[:n_rounds]
        finally:
            await rep.close()
            await server.stop()

    async def main():
        first = await _harvest(tmp_path / "a", 2)
        ids = [p["id"] for r in first for p in r["pairs"]]
        assert ids and len(ids) == len(set(ids))
        assert all(p["reward_chosen"] >= p["reward_rejected"]
                   for r in first for p in r["pairs"])
        # a FRESH worker from the same spec (same seed) regenerates the
        # same rounds under the same ids — the exactly-once foundation
        second = await _harvest(tmp_path / "b", 2)
        assert [r["pairs"] for r in second] == [r["pairs"] for r in first]

    run_async(main())


# ---------------------------------------------------------------------------
# rollout_base artifact round trip
# ---------------------------------------------------------------------------


def test_write_rollout_base_builder_roundtrip(tmp_path):
    import jax

    from finetune_controller_tpu.transport.builders import (
        resolve_builder,
        tiny_test,
    )

    model, variables = tiny_test()
    base = write_rollout_base(
        str(tmp_path), {"preset": "tiny-test"},
        dict(variables)["params"],
    )
    assert os.path.exists(os.path.join(base, "model.json"))
    model2, variables2 = resolve_builder("rollout_base")(dir=str(tmp_path))
    assert model2.cfg.vocab_size == model.cfg.vocab_size
    jax.tree.map(
        np.testing.assert_array_equal,
        jax.tree.map(np.asarray, dict(variables)["params"]),
        jax.tree.map(np.asarray, dict(variables2)["params"]),
    )


# ---------------------------------------------------------------------------
# RewardScorer
# ---------------------------------------------------------------------------


def test_reward_scorer_matches_reference_math(tmp_path):
    import jax.numpy as jnp

    from finetune_controller_tpu.data.preference import _pad_pair
    from finetune_controller_tpu.prefs.losses import reward_scores
    from finetune_controller_tpu.transport.builders import tiny_test

    model, variables = tiny_test()
    vocab = int(model.cfg.vocab_size)
    head = {
        "a": np.ones((), np.float32),
        "w": np.zeros((vocab,), np.float32),
        "b": np.zeros((), np.float32),
    }
    scorer = RewardScorer(model, variables, head)
    items = [
        {"prompt": [1, 2, 3], "completion": [4, 5]},
        {"prompt": [2, 2], "completion": [6, 1, 3]},
        {"prompt": [5], "completion": [7]},
    ]
    scores = scorer.score(items)
    assert len(scores) == 3 and all(np.isfinite(scores))
    assert scorer.scored_total == 3
    # reference: unbatched reward_scores over the same padding
    for it, got in zip(items, scores):
        t, m = _pad_pair(it["prompt"], it["completion"], 8)
        logits = model.apply(
            variables, jnp.asarray(t[None], jnp.int32), deterministic=True
        )
        ref = reward_scores(
            logits, jnp.asarray(t[None], jnp.int32),
            jnp.asarray(m[None], jnp.float32),
            {k: jnp.asarray(v) for k, v in head.items()},
        )
        assert abs(float(ref[0]) - got) < 1e-4
    # pow2 bucketing: batch of 3 and batch of 1 hit two compiled shapes only
    scorer.score(items[:1])
    assert set(scorer._fns) <= {(4, 8), (1, 8)}


def test_reward_scorer_from_artifacts_msgpack_and_missing(tmp_path):
    from flax import serialization

    from finetune_controller_tpu.transport.builders import tiny_test

    model, variables = tiny_test()
    vocab = int(model.cfg.vocab_size)
    head = {
        "a": np.float32(1.0),
        "w": np.zeros((vocab,), np.float32),
        "b": np.float32(0.5),
    }
    with open(tmp_path / rp.REWARD_HEAD_FILENAME, "wb") as f:
        f.write(serialization.msgpack_serialize(head))
    scorer = RewardScorer.from_artifacts(str(tmp_path), model, variables)
    assert float(scorer._head["b"]) == 0.5
    with pytest.raises(FileNotFoundError, match="task: reward"):
        RewardScorer.from_artifacts(str(tmp_path / "nope"), model, variables)


# ---------------------------------------------------------------------------
# scheduler accounting: RolloutTenant
# ---------------------------------------------------------------------------


def test_rollout_tenant_accounting_and_preemption_intake():
    from finetune_controller_tpu.controller.devices import (
        DeviceCatalog,
        DeviceFlavor,
        FlavorQuota,
    )
    from finetune_controller_tpu.sched import FairShareScheduler
    from finetune_controller_tpu.sched.serve_tenant import (
        ROLLOUT_QUEUE,
        RolloutTenant,
    )

    catalog = DeviceCatalog(
        flavors=[DeviceFlavor(name="chip", generation="cpu", hosts=1,
                              chips_per_host=1, runtime="cpu", queue="q")],
        quotas=[FlavorQuota(flavor="chip", nominal_chips=2)],
        default_flavor="chip",
    )
    sched = FairShareScheduler(catalog, {ROLLOUT_QUEUE: 1.0, "train": 1.0})
    tenant = RolloutTenant(sched, "job1", flavor="chip")
    tenant.submit("rollout-0")
    tenant.submit("rollout-1")
    sched.try_admit()
    assert tenant.is_admitted("rollout-0")
    summary = tenant.tick()
    assert sorted(summary["admitted"]) == ["rollout-0", "rollout-1"]
    assert summary["preempted"] == []
    # a normal-priority training job reclaims a low-priority rollout chip
    sched.submit("train-1", "chip", 1, queue="train", priority="normal")
    sched.try_admit()
    summary = tenant.tick()
    assert len(summary["preempted"]) == 1
    assert tenant.preempted_total == 1
    assert len(summary["admitted"]) == 1
    sched.try_admit()
    assert sched.is_admitted("train-1")
    tenant.close()
    assert tenant.stats()["workloads"] == {}


# ---------------------------------------------------------------------------
# DPOTrainer coupling: forced only for the IN-PROCESS rlhf loop
# ---------------------------------------------------------------------------


def _tiny_model_cfg():
    return PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))


def test_inprocess_rlhf_forces_prefetch_zero_and_blocking_commits():
    cfg = TrainConfig(task="rlhf", batch_size=2, seq_len=16, total_steps=4,
                      prefetch=2)
    trainer = DPOTrainer(_tiny_model_cfg(), cfg)
    assert cfg.prefetch == 0
    assert trainer._blocking_checkpoints is True


def test_remote_rlhf_keeps_prefetch_and_async_commits():
    cfg = TrainConfig(task="rlhf", batch_size=2, seq_len=16, total_steps=4,
                      prefetch=2, rollout_workers=2)
    trainer = DPOTrainer(_tiny_model_cfg(), cfg)
    # disaggregation's whole point: actors decode elsewhere, so the learner
    # keeps background prefetch AND async checkpoint commits
    assert cfg.prefetch == 2
    assert trainer._blocking_checkpoints is False
    # the remote-plane health columns ride the metrics header
    trainer.rollout_stats_fn = lambda: {}
    fields = trainer._writer_extra_fields(False)
    assert "rollout_workers_alive" in fields
    assert "rollout_respawns_total" in fields


def test_dpo_task_never_touches_prefetch():
    cfg = TrainConfig(task="dpo", batch_size=2, seq_len=16, total_steps=4,
                      prefetch=3)
    trainer = DPOTrainer(_tiny_model_cfg(), cfg)
    assert cfg.prefetch == 3
    assert trainer._blocking_checkpoints is False


# ---------------------------------------------------------------------------
# slow e2e: real worker processes
# ---------------------------------------------------------------------------


def _remote_loop(tmp_path, monkeypatch, *, total_steps, checkpoint_every,
                 trace_id=""):
    monkeypatch.setenv("FTC_TRACE_ID", trace_id)
    cfg = TrainConfig(
        task="rlhf", batch_size=2, seq_len=16, total_steps=total_steps,
        warmup_steps=1, learning_rate=1e-3, log_every=1,
        checkpoint_every=checkpoint_every, prefetch=0,
        heartbeat_interval_s=0, rollout_workers=1, trace=bool(trace_id),
    )
    learner = DPOTrainer(_tiny_model_cfg(), cfg)
    stream, plane, buffer = build_remote_rlhf_loop(
        learner, str(tmp_path),
        rollout=RolloutConfig(pairs_per_round=4, min_fill=4,
                              buffer_capacity=128, max_new_tokens=8,
                              slots=2, temperature=0.9),
        model_spec={"preset": "tiny-test", "lora": {"rank": 4}},
    )
    return learner, stream, plane, buffer


@pytest.mark.slow
def test_chaos_sigkill_remote_worker_streams_resume_exactly_once(
        tmp_path, monkeypatch):
    """SIGKILL the rollout worker mid-round: the learner keeps stepping on
    buffered pairs, the plane respawns the worker with backoff and streaming
    resumes, and the dedup admits NO pair twice."""
    ingested: list[str] = []
    real = rp._pair_from_doc

    def _spy(doc):
        ingested.append(str(doc["id"]))  # called only for FRESH pairs
        return real(doc)

    monkeypatch.setattr(rp, "_pair_from_doc", _spy)
    learner, stream, plane, _buf = _remote_loop(
        tmp_path, monkeypatch, total_steps=10**9, checkpoint_every=10**9
    )
    try:
        state = learner.init_state()
        b = next(stream)
        state, m = learner.step(state, b)
        assert np.isfinite(float(m["reward_margin"]))
        assert _wait(lambda: plane.workers_alive() == 1, timeout=30)
        pid = plane._workers[0].handle.pid
        rounds_before = plane.rounds_received_total
        os.kill(pid, signal.SIGKILL)
        # the learner never stops: buffered pairs keep feeding steps while
        # the worker is down and respawning (the respawn pays a fresh
        # process spawn + XLA compile, so bound by time, not step count)
        steps_during_outage = 0
        deadline = time.monotonic() + 300
        while plane.respawns_total < 1 and time.monotonic() < deadline:
            state, m = learner.step(state, plane.sample_batch(2, 16))
            float(m["reward_margin"])
            steps_during_outage += 1
        assert plane.respawns_total >= 1, "worker was never respawned"
        assert steps_during_outage >= 1
        # streaming resumes: fresh rounds arrive from the new incarnation
        assert _wait(
            lambda: plane.rounds_received_total > rounds_before, timeout=180
        ), "respawned worker never resumed streaming"
        new_pid = plane._workers[0].handle.pid
        assert new_pid != pid
    finally:
        plane.close()
    # exactly-once: every pair that entered the buffer did so ONCE — the
    # respawned worker regenerated earlier rounds (same seed, reset cursor)
    # and the dedup suppressed every replay
    assert len(ingested) == len(set(ingested)), (
        "duplicate pair entered the buffer"
    )
    assert plane.dup_pairs_total >= 0


@pytest.mark.slow
def test_remote_overlap_spans_and_policy_rollover_e2e(tmp_path, monkeypatch):
    """The PR-9 timeline proof: rollout.round spans (worker-stamped) overlap
    learner step intervals, and a committed checkpoint rolls the fleet's
    policy over as an adapter delta without restarting the worker."""
    from finetune_controller_tpu.obs.trace import (
        TRACE_DIRNAME,
        TRAINER_SPANS_FILENAME,
    )

    learner, stream, plane, _buf = _remote_loop(
        tmp_path, monkeypatch, total_steps=4, checkpoint_every=2,
        trace_id="trace-rl",
    )
    step_intervals = []
    try:
        state = learner.init_state()
        b = next(stream)
        state, _ = learner.step(state, b)  # compile before timing
        pid0 = plane._workers[0].handle.pid
        for _ in range(8):
            b = next(stream)
            t0 = time.time_ns()
            state, m = learner.step(state, b)
            float(m["reward_margin"])  # device sync closes the interval
            step_intervals.append((t0, time.time_ns()))
        # rollover: commit a checkpoint, then the stream's next() ships it
        learner.fit(stream, str(tmp_path), resume=True)
        next(stream)
        assert plane._policy is not None and plane._policy[0] >= 4
        assert _wait(
            lambda: plane.stats()["actor_version"] >= 4, timeout=120
        ), "fleet never installed the pushed adapter delta"
        # rollover was a push, not a worker restart
        assert plane._workers[0].handle.pid == pid0
        assert plane.respawns_total == 0
    finally:
        plane.close()
    spans_path = os.path.join(
        str(tmp_path), TRACE_DIRNAME, TRAINER_SPANS_FILENAME
    )
    with open(spans_path) as f:
        spans = [json.loads(line) for line in f]
    rollout_spans = [
        s for s in spans
        if s["name"] == "rollout.round"
        and s.get("attributes", {}).get("service") == "rollout"
    ]
    assert rollout_spans, "no rollout.round spans in the trace"
    overlapped = any(
        s["start_ns"] < t1 and s["end_ns"] > t0
        for s in rollout_spans
        for (t0, t1) in step_intervals
    )
    assert overlapped, (
        "no rollout round overlapped a learner step — generation and "
        "training serialized"
    )
