"""Runtime shard audit (analysis/shard_audit.py, docs/static_analysis.md §v3).

The trap's whole contract on one page:

* a tree whose device leaves carry exactly their rule-table
  ``NamedSharding`` audits clean (checks > 0, violations == 0);
* a leaf that lost its sharding to full replication is caught
  STRUCTURALLY — even on one device, where every layout is semantically
  equivalent — and ``action="raise"`` aborts with the offending path while
  ``action="warn"`` logs once per boundary and keeps going;
* the ``FTC_FAULT_SHARD`` chaos hand re-``device_put``s a real leaf as
  replicated, proving the abort end to end (this is the injected-fault
  mutation satellite: HEAD is green because the fault is opt-in);
* host-side numpy leaves carry no sharding and are skipped, so the
  checkpoint host-gather path can share trees with the audit;
* ``FTC_SHARD_AUDIT`` / ``TrainConfig.shard_audit`` wire the trap into the
  trainer, and the process-wide counters feed
  ``ftc_shard_audit_{checks,violations}_total``.

Also here: the ``sharding_for_tree`` upfront-validation satellite —
a rule resolving to an unknown mesh axis or an indivisible dimension
raises a typed ``ShardingRuleError`` naming the path, not a deep XLA
partitioner error at compile time.
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from finetune_controller_tpu.analysis.shard_audit import (
    ShardAuditError,
    ShardAuditor,
    metrics_snapshot,
)
from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.parallel.mesh import MeshSpec
from finetune_controller_tpu.parallel.sharding import (
    LLAMA_RULES,
    PartitionRules,
    ShardingRuleError,
    sharding_for_tree,
    validate_spec,
)
from finetune_controller_tpu.train.trainer import TrainConfig, Trainer


@pytest.fixture(scope="module")
def mesh():
    return MeshSpec(dp=1, fsdp=2).build(jax.devices()[:2])


def _tree(mesh):
    """A two-leaf tree device_put exactly onto its expected shardings."""
    expected = {
        "kernel": NamedSharding(mesh, P("fsdp", None)),
        "scale": NamedSharding(mesh, P()),
    }
    tree = {
        "kernel": jax.device_put(jnp.ones((8, 4)), expected["kernel"]),
        "scale": jax.device_put(jnp.ones((4,)), expected["scale"]),
    }
    return tree, expected


# ---- the audit itself ------------------------------------------------------


def test_clean_tree_audits_clean(mesh):
    tree, expected = _tree(mesh)
    auditor = ShardAuditor("raise", inject_fault=False)
    assert auditor.audit(tree, expected, label="t") == 0
    assert auditor.checks == 2
    assert auditor.violations == 0


def test_replicated_leaf_is_caught_structurally(mesh):
    """The production bug: a leaf silently landed fully replicated.  On the
    CPU test mesh this is semantically indistinguishable from the sharded
    layout — the audit must still flag it (structural comparison)."""
    tree, expected = _tree(mesh)
    tree["kernel"] = jax.device_put(
        jnp.ones((8, 4)), NamedSharding(mesh, P())
    )
    auditor = ShardAuditor("raise", inject_fault=False)
    with pytest.raises(ShardAuditError, match="kernel"):
        auditor.audit(tree, expected, label="restore")


def test_warn_mode_counts_without_raising(mesh, caplog):
    tree, expected = _tree(mesh)
    tree["kernel"] = jax.device_put(
        jnp.ones((8, 4)), NamedSharding(mesh, P())
    )
    auditor = ShardAuditor("warn", inject_fault=False)
    with caplog.at_level(logging.WARNING):
        assert auditor.audit(tree, expected, label="b1") == 1
        assert auditor.audit(tree, expected, label="b1") == 1  # warned once
    assert auditor.violations == 2
    assert sum("mis-sharded" in r.message for r in caplog.records) == 1


def test_error_names_path_and_both_specs(mesh):
    tree, expected = _tree(mesh)
    tree["kernel"] = jax.device_put(
        jnp.ones((8, 4)), NamedSharding(mesh, P())
    )
    with pytest.raises(ShardAuditError) as exc:
        ShardAuditor("raise", inject_fault=False).audit(
            tree, expected, label="restore"
        )
    msg = str(exc.value)
    assert "'fsdp'" in msg and "restore" in msg


def test_host_numpy_leaves_are_skipped(mesh):
    """Host-side leaves (checkpoint trees after state_to_host) carry no
    .sharding — the audit passes over them rather than false-positive."""
    _, expected = _tree(mesh)
    host = {"kernel": np.ones((8, 4)), "scale": np.ones((4,))}
    auditor = ShardAuditor("raise", inject_fault=False)
    assert auditor.audit(host, expected, label="host") == 0


def test_injected_fault_aborts(mesh):
    """The chaos hand (FTC_FAULT_SHARD / inject_fault=True): ONE sharded
    leaf is re-device_put as replicated before checking — a real
    mis-sharded array aborts the raise-mode audit.  HEAD stays green
    because injection is opt-in."""
    tree, expected = _tree(mesh)
    with pytest.raises(ShardAuditError):
        ShardAuditor("raise", inject_fault=True).audit(
            tree, expected, label="bench"
        )


def test_injected_fault_counts_in_warn_mode(mesh):
    tree, expected = _tree(mesh)
    auditor = ShardAuditor("warn", inject_fault=True)
    assert auditor.audit(tree, expected, label="bench") == 1
    # the hand fires once per auditor — the second pass is clean
    assert auditor.audit(tree, expected, label="bench2") == 0


def test_fault_env_arms_injection(mesh, monkeypatch):
    monkeypatch.setenv("FTC_FAULT_SHARD", "1")
    tree, expected = _tree(mesh)
    with pytest.raises(ShardAuditError):
        ShardAuditor("raise").audit(tree, expected, label="bench")


def test_metrics_counters_increment(mesh):
    before = metrics_snapshot()
    tree, expected = _tree(mesh)
    tree["kernel"] = jax.device_put(
        jnp.ones((8, 4)), NamedSharding(mesh, P())
    )
    ShardAuditor("warn", inject_fault=False).audit(tree, expected, label="m")
    after = metrics_snapshot()
    assert after["checks_total"] == before["checks_total"] + 2
    assert after["violations_total"] == before["violations_total"] + 1


def test_bad_action_rejected():
    with pytest.raises(ValueError):
        ShardAuditor("explode")


# ---- env / config wiring ---------------------------------------------------


@pytest.mark.parametrize("value", ["", "0", "off", "false"])
def test_from_env_off_values(value, monkeypatch):
    monkeypatch.setenv("FTC_SHARD_AUDIT", value)
    assert ShardAuditor.from_env() is None


@pytest.mark.parametrize(
    "value,action",
    [("raise", "raise"), ("1", "raise"), ("on", "raise"), ("true", "raise"),
     ("warn", "warn"), ("WARN", "warn")],
)
def test_from_env_on_values(value, action, monkeypatch):
    monkeypatch.setenv("FTC_SHARD_AUDIT", value)
    auditor = ShardAuditor.from_env()
    assert auditor is not None and auditor.action == action


def test_from_env_default_when_unset(monkeypatch):
    monkeypatch.delenv("FTC_SHARD_AUDIT", raising=False)
    assert ShardAuditor.from_env() is None
    assert ShardAuditor.from_env(default="warn").action == "warn"


def test_trainer_config_arms_auditor(monkeypatch):
    monkeypatch.delenv("FTC_SHARD_AUDIT", raising=False)
    model = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=2))
    mesh = MeshSpec(dp=1, fsdp=1).build(jax.devices()[:1])

    def build(**kw):
        cfg = TrainConfig(
            mode="lora", batch_size=2, seq_len=16, total_steps=2, **kw
        )
        return Trainer(model, cfg, mesh=mesh)

    assert build(shard_audit="raise")._shard_auditor.action == "raise"
    assert build(shard_audit="warn")._shard_auditor.action == "warn"
    assert build(shard_audit="off")._shard_auditor is None
    # the empty default inherits the env
    assert build()._shard_auditor is None
    monkeypatch.setenv("FTC_SHARD_AUDIT", "warn")
    assert build()._shard_auditor.action == "warn"


def test_trainer_state_audits_clean_after_init(monkeypatch):
    """The real wiring end to end: a freshly initialised trainer state
    (jit with out_shardings from the rule table) audits clean against
    trainer._state_shardings — the exact check fit() runs at the
    checkpoint/restore boundaries."""
    monkeypatch.delenv("FTC_FAULT_SHARD", raising=False)
    model = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=2))
    mesh = MeshSpec(dp=1, fsdp=2).build(jax.devices()[:2])
    cfg = TrainConfig(
        mode="lora", batch_size=2, seq_len=16, total_steps=2,
        shard_audit="raise",
    )
    trainer = Trainer(model, cfg, mesh=mesh)
    state = trainer.init_state()
    assert trainer._shard_auditor is not None
    trainer._audit_state_sharding(state, "test-init")
    assert trainer._shard_auditor.checks > 0
    assert trainer._shard_auditor.violations == 0


def test_trainer_resume_audits_clean(monkeypatch, tmp_path):
    """Regression for the restore boundary: EVERY restored leaf must ride
    ``reshard`` back onto the mesh — including the step scalar, which a
    bare ``jnp.asarray`` commits to one default device instead of the rule
    table's mesh-replicated spec.  The armed audit caught exactly that on
    the first live resume; two devices keep the structural check honest
    (on one device a SingleDeviceSharding is equivalent to replicated)."""
    monkeypatch.delenv("FTC_FAULT_SHARD", raising=False)
    from finetune_controller_tpu.data import synthetic_batches

    model = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=2))

    def leg(total_steps):
        mesh = MeshSpec(dp=1, fsdp=2).build(jax.devices()[:2])
        cfg = TrainConfig(
            mode="lora", batch_size=2, seq_len=16, total_steps=total_steps,
            log_every=2, checkpoint_every=2, shard_audit="raise",
        )
        trainer = Trainer(model, cfg, mesh=mesh)
        batches = synthetic_batches(
            2, 16, model.vocab_size, task="increment"
        )
        trainer.fit(batches, str(tmp_path))
        return trainer

    leg(2)
    # the second leg resumes from step_2 through the audited restore path;
    # a raise-mode auditor makes any mis-sharded restored leaf fatal here
    trainer = leg(4)
    assert trainer._shard_auditor.checks > 0
    assert trainer._shard_auditor.violations == 0


# ---- sharding_for_tree upfront validation (satellite bugfix) ---------------


def test_validate_spec_unknown_axis(mesh):
    with pytest.raises(ShardingRuleError, match="bogus"):
        validate_spec("a/kernel", (8, 4), P("bogus", None), mesh)


def test_validate_spec_indivisible_dim(mesh):
    # fsdp=2 cannot divide 7
    with pytest.raises(ShardingRuleError, match="divisible"):
        validate_spec("a/kernel", (7, 4), P("fsdp", None), mesh)


def test_validate_spec_clean(mesh):
    validate_spec("a/kernel", (8, 4), P("fsdp", None), mesh)
    validate_spec("a/scale", (4,), P(), mesh)


def test_sharding_for_tree_raises_upfront(mesh):
    """The bug this satellite fixed: a rule naming an axis the mesh does
    not define used to surface as a deep XLA partitioner error at compile
    time; now sharding_for_tree validates every leaf upfront and raises
    the typed error naming the offending path."""
    bad = PartitionRules([(r".*", P("bogus", None))])
    tree = {"layer": {"kernel": jnp.ones((8, 4))}}
    with pytest.raises(ShardingRuleError, match="layer/kernel"):
        sharding_for_tree(tree, mesh, bad)


def test_sharding_for_tree_rejects_indivisible(mesh):
    bad = PartitionRules([(r".*", P("fsdp", None))])
    tree = {"kernel": jnp.ones((7, 4))}
    with pytest.raises(ShardingRuleError, match="divisible"):
        sharding_for_tree(tree, mesh, bad)


def test_llama_rules_validate_on_test_mesh(mesh):
    """The shipped table stays applicable to the tiny preset on the CPU
    test mesh — the runtime twin of the shard-divisibility lint rule."""
    model = LlamaForCausalLM(PRESETS["tiny-test"].replace(
        lora=LoRAConfig(rank=2)
    ))
    variables = jax.eval_shape(
        model.init, {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
    )
    shardings = sharding_for_tree(variables, mesh, LLAMA_RULES)
    assert all(
        isinstance(s, NamedSharding) for s in jax.tree.leaves(
            shardings, is_leaf=lambda x: isinstance(x, NamedSharding)
        )
    )
