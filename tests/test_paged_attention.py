"""Pallas paged-attention kernel: bit-identity + dispatch (ISSUE 16).

The acceptance anchors: the block-sparse kernel (``ops/pallas/
paged_attention.py``) walks each lane's page list through the BlockSpec
index map instead of materialising a gathered logical cache, and CI
proves it BIT-IDENTICAL to the gather oracle in interpret mode — across
dtypes, page-table shapes with scratch-page slots, per-row and scalar
positions — and the serving engine under ``FTC_PAGED_ATTN=kernel``
reproduces ``cached_generate`` bit-for-bit (greedy AND sampled, staggered
mixed batches, page-boundary-straddling CoW splices) within the same
compile budget as the gather path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finetune_controller_tpu.models.generate import cached_generate
from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.ops.attention import (
    chunked_cache_attention,
    paged_attention_impl,
    paged_cache_attention,
    paged_gather,
)
from finetune_controller_tpu.ops.pallas.paged_attention import (
    paged_attention,
    paged_attention_vmem_bytes,
)
from finetune_controller_tpu.serve.engine import (
    BatchEngine,
    EngineConfig,
    GenRequest,
)


@jax.jit
def _gather_oracle(q, k_pool, v_pool, table, idx):
    """The reference path, jitted: gather + chunked_cache_attention —
    exactly what the gather impl of ``paged_cache_attention`` runs."""
    return chunked_cache_attention(
        q, paged_gather(k_pool, table), paged_gather(v_pool, table), idx
    )


def _case(key, *, b, s, mp, t, h, hkv, pool_pages, dtype):
    """Random pools (scratch page 0 holds garbage like the real pool),
    a random page table with some slots pointing at scratch, per-row
    positions that straddle page boundaries."""
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (b, s, h, 16), dtype)
    k_pool = jax.random.normal(ks[1], (pool_pages, t, hkv, 16), dtype)
    v_pool = jax.random.normal(ks[2], (pool_pages, t, hkv, 16), dtype)
    table = jax.random.randint(ks[3], (b, mp), 0, pool_pages, jnp.int32)
    # unmaterialised tail slots -> scratch page, like the engine's tables
    table = table.at[:, -1].set(0)
    idx = jax.random.randint(ks[4], (b,), 0, mp * t - s + 1, jnp.int32)
    return q, k_pool, v_pool, table, idx


CASES = [
    dict(b=1, s=1, mp=2, t=4, h=4, hkv=2, pool_pages=5),    # decode step
    dict(b=3, s=1, mp=4, t=8, h=4, hkv=2, pool_pages=9),    # batched decode
    dict(b=2, s=8, mp=3, t=8, h=4, hkv=4, pool_pages=7),    # suffix prefill
    dict(b=2, s=4, mp=5, t=4, h=8, hkv=2, pool_pages=11),   # g=4 grouping
    dict(b=4, s=2, mp=2, t=16, h=2, hkv=1, pool_pages=3),   # MQA
]


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("case", range(len(CASES)))
def test_kernel_bit_identical_to_gather_oracle(case, dtype):
    """The contract: not 'close', IDENTICAL — every bit, every shape."""
    spec = CASES[case]
    q, k, v, table, idx = _case(jax.random.PRNGKey(case), dtype=dtype, **spec)
    want = _gather_oracle(q, k, v, table, idx)
    got = paged_attention(q, k, v, table, idx, interpret=True)
    assert got.dtype == want.dtype
    assert jnp.array_equal(
        got.view(jnp.uint16 if dtype == jnp.bfloat16 else jnp.uint32),
        want.view(jnp.uint16 if dtype == jnp.bfloat16 else jnp.uint32),
    ), f"kernel diverged from gather oracle on case {spec} {dtype}"


def test_kernel_scalar_idx_matches_per_row():
    """A scalar position (cached_generate's lockstep decode) must hit the
    same program as the equivalent per-row vector."""
    q, k, v, table, _ = _case(
        jax.random.PRNGKey(7), b=3, s=1, mp=3, t=4, h=4, hkv=2,
        pool_pages=6, dtype=jnp.float32,
    )
    got_scalar = paged_attention(q, k, v, table, 5, interpret=True)
    got_vec = paged_attention(
        q, k, v, table, jnp.full((3,), 5, jnp.int32), interpret=True
    )
    assert jnp.array_equal(got_scalar, got_vec)


def test_kernel_batch_independence():
    """The finalize step replays the oracle at batch 1, which is only
    valid because ``chunked_cache_attention`` is batch-size-independent
    under jit — re-prove that load-bearing assumption here, per lane."""
    q, k, v, table, idx = _case(
        jax.random.PRNGKey(11), b=4, s=2, mp=3, t=8, h=4, hkv=2,
        pool_pages=8, dtype=jnp.bfloat16,
    )
    full = _gather_oracle(q, k, v, table, idx)
    for lane in range(4):
        solo = _gather_oracle(
            q[lane:lane + 1], k, v, table[lane:lane + 1], idx[lane:lane + 1]
        )
        assert jnp.array_equal(
            solo.view(jnp.uint16), full[lane:lane + 1].view(jnp.uint16)
        ), f"oracle is batch-dependent at lane {lane}"


def test_kernel_dtype_mismatch_raises():
    q, k, v, table, idx = _case(
        jax.random.PRNGKey(0), b=1, s=1, mp=2, t=4, h=4, hkv=2,
        pool_pages=4, dtype=jnp.float32,
    )
    with pytest.raises(ValueError, match="dtypes must match"):
        paged_attention(q.astype(jnp.bfloat16), k, v, table, idx)


def test_vmem_budget_scales_with_pages():
    small = paged_attention_vmem_bytes((1, 1, 4, 16), 2, 8, 2, 2)
    big = paged_attention_vmem_bytes((1, 1, 4, 16), 64, 8, 2, 2)
    assert 0 < small < big


# ---------------------------------------------------------------------------
# Dispatch: FTC_PAGED_ATTN / FTC_PAGED_VMEM_MB
# ---------------------------------------------------------------------------


def _dispatch_args(dtype=jnp.float32):
    q, k, v, table, _ = _case(
        jax.random.PRNGKey(1), b=1, s=1, mp=2, t=4, h=4, hkv=2,
        pool_pages=4, dtype=dtype,
    )
    return q, k, v, table


def test_dispatch_auto_is_gather_off_tpu(monkeypatch):
    monkeypatch.delenv("FTC_PAGED_ATTN", raising=False)
    if jax.default_backend() == "tpu":
        pytest.skip("auto resolves to kernel on TPU")
    assert paged_attention_impl(*_dispatch_args()) == "gather"


def test_dispatch_forced_kernel_everywhere(monkeypatch):
    monkeypatch.setenv("FTC_PAGED_ATTN", "kernel")
    assert paged_attention_impl(*_dispatch_args()) == "kernel"
    # mixed dtypes would break the bit-identity contract in auto mode,
    # but the explicit override is the operator's call
    q, k, v, table = _dispatch_args()
    assert paged_attention_impl(
        q.astype(jnp.bfloat16), k, v, table) == "kernel"


def test_dispatch_rejects_unknown_impl(monkeypatch):
    monkeypatch.setenv("FTC_PAGED_ATTN", "turbo")
    with pytest.raises(ValueError, match="FTC_PAGED_ATTN"):
        paged_attention_impl(*_dispatch_args())


def test_dispatch_rejects_bad_vmem_budget(monkeypatch):
    if jax.default_backend() != "tpu":
        pytest.skip("VMEM budget is only consulted on TPU")
    monkeypatch.delenv("FTC_PAGED_ATTN", raising=False)
    monkeypatch.setenv("FTC_PAGED_VMEM_MB", "-3")
    with pytest.raises(ValueError, match="FTC_PAGED_VMEM_MB"):
        paged_attention_impl(*_dispatch_args())


def test_paged_cache_attention_kernel_equals_gather(monkeypatch):
    """The public seam: flipping FTC_PAGED_ATTN must not change a bit."""
    q, k, v, table, idx = _case(
        jax.random.PRNGKey(3), b=2, s=4, mp=3, t=8, h=4, hkv=2,
        pool_pages=7, dtype=jnp.bfloat16,
    )
    monkeypatch.setenv("FTC_PAGED_ATTN", "gather")
    want = jax.jit(paged_cache_attention)(q, k, v, table, idx)
    monkeypatch.setenv("FTC_PAGED_ATTN", "kernel")
    got = jax.jit(paged_cache_attention)(q, k, v, table, idx)
    assert jnp.array_equal(got.view(jnp.uint16), want.view(jnp.uint16))


# ---------------------------------------------------------------------------
# Engine anchors under FTC_PAGED_ATTN=kernel
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    model = LlamaForCausalLM(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 4), jnp.int32)
    )
    return model, variables


def _baseline(model, variables, prompt, n, **kw):
    out = cached_generate(
        model, variables, jnp.asarray([prompt], jnp.int32),
        max_new_tokens=n, **kw,
    )
    return list(np.asarray(out[0, len(prompt):]))


def _kernel_engine(model, variables, **kw):
    defaults = dict(slots=2, prompt_buckets=(8, 16), max_new_tokens=24,
                    page_tokens=8)
    defaults.update(kw)
    return BatchEngine(model, variables, EngineConfig(**defaults))


def test_engine_greedy_kernel_staggered_bit_identity(tiny_model, monkeypatch):
    """Greedy decode through the kernel — mixed prompt lengths joining
    mid-flight — bit-identical to single-request cached_generate."""
    monkeypatch.setenv("FTC_PAGED_ATTN", "kernel")
    model, variables = tiny_model
    prompts = [
        [5, 9, 2, 7],
        [1, 3, 3, 8, 2, 2],
        [11, 4, 9, 1, 2, 3, 4, 5, 6, 0, 2, 1],  # second bucket
    ]
    reqs = [
        GenRequest(request_id=f"r{i}", tokens=p, max_new_tokens=5 + 2 * i)
        for i, p in enumerate(prompts)
    ]
    eng = _kernel_engine(model, variables, pool_pages=12)
    res = eng.run(list(reqs))
    for i, p in enumerate(prompts):
        want = _baseline(model, variables, p, 5 + 2 * i)
        assert res[f"r{i}"].generated == want, f"kernel diverged on r{i}"


def test_engine_sampled_kernel_reproducible(tiny_model, monkeypatch):
    """Sampled decode through the kernel reproduces the per-request
    PRNGKey(seed) stream bit-for-bit."""
    monkeypatch.setenv("FTC_PAGED_ATTN", "kernel")
    model, variables = tiny_model
    reqs = [
        GenRequest(request_id=f"s{i}", tokens=[3 + i, 1, 4, 1], seed=40 + i,
                   temperature=0.8, top_k=7, max_new_tokens=6)
        for i in range(2)
    ]
    eng = _kernel_engine(model, variables, pool_pages=12)
    res = eng.run(reqs)
    for i in range(2):
        want = _baseline(
            model, variables, [3 + i, 1, 4, 1], 6,
            temperature=0.8, top_k=7, rng=jax.random.PRNGKey(40 + i),
        )
        assert res[f"s{i}"].generated == want


def test_engine_kernel_page_boundary_cow_splice(tiny_model, monkeypatch):
    """Page size dividing neither bucket nor reuse length: the kernel
    serves CoW boundary splices bit-identically, within the paged
    compile budget (len(buckets) + 1 — unchanged by the kernel)."""
    monkeypatch.setenv("FTC_PAGED_ATTN", "kernel")
    model, variables = tiny_model
    eng = _kernel_engine(
        model, variables, page_tokens=7, pool_pages=16,
        prefix_cache_bytes=1 << 20,
    )
    assert eng.guard.budget == 3
    shared = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]   # 10 tokens: 1.43 pages of 7
    reqs = [
        GenRequest(request_id=f"b{i}", tokens=shared + [20 + i],
                   max_new_tokens=5)
        for i in range(3)
    ]
    res = eng.run(reqs)
    for i in range(3):
        want = _baseline(model, variables, shared + [20 + i], 5)
        assert res[f"b{i}"].generated == want, f"b{i} diverged"
    assert eng.prefix_hits_total >= 2
    assert eng.kv_page_stats()["cow_copies_total"] >= 1
    assert eng.compilations <= 3
