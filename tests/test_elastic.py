"""Elastic / fault-tolerant training tests (SURVEY.md §5.3-§5.4 — the gap the
TPU build must close; the reference delegates recovery entirely to K8s
restartPolicy and has no resume).

Two layers:

* e2e: a running local-backend job is killed mid-run (``inject_fault``, the
  spot-preemption stand-in); asserted path is RESTARTING → resume from
  checkpoint → SUCCEEDED with step-continuous metrics.
* multi-process: a real 2-process ``jax.distributed`` CPU run exercising the
  collective code paths that otherwise only run in their degenerate
  single-process form — ``state_to_host`` allgather, rank-0-authoritative
  broadcast-resume, and ``_sync_preemption``.
"""

import asyncio
import json
import os
import subprocess
import sys
import time
from pathlib import Path

from conftest import run_async as run
from finetune_controller_tpu.controller.backends.local import LocalProcessBackend
from finetune_controller_tpu.controller.examples import LoRASFTArguments, TinyTestLoRA
from finetune_controller_tpu.controller.monitor import JobMonitor
from finetune_controller_tpu.controller.objectstore import LocalObjectStore
from finetune_controller_tpu.controller.schemas import (
    BackendJobState,
    DatabaseStatus,
    JobInput,
)
from finetune_controller_tpu.controller.statestore import StateStore
from finetune_controller_tpu.controller.task_builder import DatasetInput, task_builder

from conftest import one_chip_catalog


def test_fault_injection_restart_resume_e2e(tmp_path):
    """Kill the training process mid-run; the job must restart, resume from
    the checkpoint (not step 0), and finish SUCCEEDED with continuous
    metrics."""

    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        catalog = one_chip_catalog()
        backend = LocalProcessBackend(
            tmp_path / "sandboxes", store, catalog, sync_interval_s=0.2
        )
        monitor = JobMonitor(state, store, backend, interval_s=0.1)
        await state.connect()

        total_steps = 2000
        ckpt_every = 100
        spec = TinyTestLoRA(
            training_arguments=LoRASFTArguments(
                total_steps=total_steps, warmup_steps=1, batch_size=2,
                seq_len=16, lora_rank=2,
            )
        )
        # log/checkpoint cadence rides through build_trainer_spec overrides
        job = JobInput(
            job_id="elastic-1", user_id="u", model_name="tiny-test-lora",
            device="chip-1",
            arguments={"total_steps": total_steps},
        )
        trainer_overrides = {"log_every": ckpt_every, "checkpoint_every": ckpt_every}

        await task_builder(
            job, spec, DatasetInput(),
            state=state, store=store, backend=backend, catalog=catalog,
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        # patch cadence into the rendered spec (the submit path fixes
        # log_every via spec args; edit the sandbox spec directly for the test)
        handle = backend._handles["elastic-1"]
        rendered = json.loads(handle.spec_path.read_text())
        rendered["training"].update(trainer_overrides)
        handle.spec_path.write_text(json.dumps(rendered))

        # wait for the first checkpoint, then preempt (SIGTERM, what a TPU
        # spot reclaim sends)
        ckpt_dir = handle.artifacts_dir / "checkpoints"
        deadline = time.monotonic() + 150
        while not any(ckpt_dir.glob("step_*")):
            assert time.monotonic() < deadline, "no checkpoint appeared"
            await asyncio.sleep(0.3)
        assert await backend.inject_fault("elastic-1", signum=15)

        # the backend must pass through RESTARTING on its way back up
        saw_restarting = False
        report = None
        deadline = time.monotonic() + 240
        while True:
            report = await backend.get_job("elastic-1")
            assert report is not None
            if report.state is BackendJobState.RESTARTING:
                saw_restarting = True
            if report.state in (BackendJobState.SUCCEEDED, BackendJobState.FAILED):
                break
            assert time.monotonic() < deadline, report
            await asyncio.sleep(0.1)
        assert report.state is BackendJobState.SUCCEEDED, report
        assert report.metadata["restarts"] == 1
        assert saw_restarting or report.metadata["restarts"] == 1

        # resume proof: training log shows the second attempt resuming from a
        # checkpoint step, not starting at 0
        log_text = (handle.sandbox / "logs.txt").read_text()
        assert "resumed from checkpoint step" in log_text

        # metrics are step-continuous across the restart: every cadence row
        # present once, up to total_steps
        metrics_csv = (handle.artifacts_dir / "metrics.csv").read_text().splitlines()
        steps = [int(row.split(",")[1]) for row in metrics_csv[1:]]
        # column order: timestamp,step,... — find the step column robustly
        header = metrics_csv[0].split(",")
        si = header.index("step")
        steps = [int(float(row.split(",")[si])) for row in metrics_csv[1:]]
        assert steps[-1] == total_steps
        assert steps == sorted(set(steps)), "duplicate or out-of-order metric rows"
        expected = list(range(ckpt_every, total_steps + 1, ckpt_every))
        assert [s for s in steps if s % ckpt_every == 0] == expected

        # monitor reconciles the DB to SUCCEEDED
        await monitor.tick()
        rec = await state.get_job("elastic-1")
        assert rec.status is DatabaseStatus.SUCCEEDED
        await backend.close()
        await state.close()

    run(main())


_DIST_DRIVER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")

rank = int(sys.argv[1])
port = sys.argv[2]
art_root = sys.argv[3]
jax.distributed.initialize(f"localhost:{port}", num_processes=2, process_id=rank)
assert jax.process_count() == 2, jax.process_count()

import numpy as np
from finetune_controller_tpu.models.llama import PRESETS
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.parallel.mesh import MeshSpec
from finetune_controller_tpu.train.trainer import TrainConfig, Trainer
from finetune_controller_tpu.data.synthetic import synthetic_batches

cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=2))
tc = TrainConfig(mode="lora", learning_rate=0.01, total_steps=6, batch_size=4,
                 seq_len=16, log_every=3, checkpoint_every=3)
trainer = Trainer(cfg, tc, mesh=MeshSpec(fsdp=2).build())

# rank-0-authoritative artifacts: only rank 0's dir receives checkpoints,
# rank 1 must learn the resume step via the broadcast
art = os.path.join(art_root, f"rank{rank}")
os.makedirs(art, exist_ok=True)

batches = synthetic_batches(trainer.local_batch_size, 16, cfg.vocab_size,
                            seed=rank)
state = trainer.fit(batches, art, resume=False)

# --- state_to_host: collective allgather must agree across ranks ----------
host = trainer.state_to_host(state)
step_val = int(host["step"])
l2 = float(np.sqrt(sum(float((x.astype(np.float64) ** 2).sum())
                       for x in jax.tree.leaves(host["trainable"]))))
print(f"RANK{rank} STEP {step_val} L2 {l2:.6f}", flush=True)

# --- _sync_preemption: any-rank flag ORs to all ranks ---------------------
got = trainer._sync_preemption(rank == 1)
assert got is True, f"rank {rank}: preemption OR failed"
got0 = trainer._sync_preemption(False)
assert got0 is False
print(f"RANK{rank} PREEMPT_OK", flush=True)

# --- broadcast-resume: rank 0 has the checkpoint, rank 1 does not ---------
tc2 = TrainConfig(mode="lora", learning_rate=0.01, total_steps=9, batch_size=4,
                  seq_len=16, log_every=3, checkpoint_every=3)
trainer2 = Trainer(cfg, tc2, mesh=MeshSpec(fsdp=2).build())
batches2 = synthetic_batches(trainer2.local_batch_size, 16, cfg.vocab_size,
                             seed=rank)
state2 = trainer2.fit(batches2, art, resume=True)
host2 = trainer2.state_to_host(state2)
print(f"RANK{rank} RESUMED_TO {int(host2['step'])}", flush=True)
"""


def test_two_process_distributed_cpu(tmp_path):
    """Real 2-process jax.distributed run: allgather state_to_host, preemption
    OR-sync, and rank-0-authoritative broadcast resume."""
    driver = tmp_path / "driver.py"
    driver.write_text(_DIST_DRIVER)
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(driver), str(r), str(port), str(tmp_path / "art")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for r in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-3000:]}"

    # both ranks agree on the gathered state (same step, same L2 norm)
    lines = {r: dict() for r in range(2)}
    for r, out in enumerate(outs):
        for tok in out.splitlines():
            if tok.startswith(f"RANK{r} STEP"):
                parts = tok.split()
                lines[r]["step"], lines[r]["l2"] = int(parts[2]), float(parts[4])
            if tok.startswith(f"RANK{r} RESUMED_TO"):
                lines[r]["resumed"] = int(tok.split()[2])
        assert f"RANK{r} PREEMPT_OK" in out, out[-2000:]
    assert lines[0]["step"] == lines[1]["step"] == 6
    assert abs(lines[0]["l2"] - lines[1]["l2"]) < 1e-6
    # rank 1 had no checkpoint files locally, yet resumed to the final step
    # because rank 0's view was broadcast
    assert lines[0]["resumed"] == lines[1]["resumed"] == 9
    rank1_ckpts = Path(tmp_path / "art" / "rank1" / "checkpoints")
    rank0_ckpts = Path(tmp_path / "art" / "rank0" / "checkpoints")
    assert any(rank0_ckpts.glob("step_*"))
