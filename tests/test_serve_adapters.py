"""Multi-tenant unmerged-LoRA multiplexing (ISSUE 11, docs/serving.md
§Multi-tenant adapters).

Anchors: N tenants multiplexed on ONE engine are bit-identical to N
dedicated single-tenant engines (greedy and sampled, staggered mixed
batches); rank padding is bit-neutral; the gathered-einsum math matches a
merged-weights model to float tolerance (merged differs only by fp
reassociation); prefix-cache keys include the adapter id so one tenant's KV
never splices into another's; deficit-round-robin admission keeps a hot
tenant from starving the rest; and the whole surface rides the HTTP loop —
load base unmerged, stage adapter deltas, generate per tenant, unload.
"""

from __future__ import annotations

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import run_async
from finetune_controller_tpu.models.generate import cached_generate
from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.serve.adapters import (
    AdapterError,
    AdapterRegistry,
    UnknownAdapter,
)
from finetune_controller_tpu.serve.batcher import Batcher
from finetune_controller_tpu.serve.engine import (
    BatchEngine,
    EngineConfig,
    GenRequest,
)

BASE_CFG = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=0))


@pytest.fixture(scope="module")
def base_model():
    model = LlamaForCausalLM(BASE_CFG)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 4), jnp.int32)
    )
    return model, {"params": variables["params"]}


def _lora_shapes(rank):
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=rank))
    return jax.eval_shape(
        LlamaForCausalLM(cfg).init,
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 4), jnp.int32),
    )["lora"]


def _make_adapter(seed, rank):
    """Random nonzero A and B (B nonzero so tenants actually diverge)."""
    return jax.tree.map(
        lambda s: 0.05 * np.asarray(
            jax.random.normal(jax.random.PRNGKey(seed), s.shape), np.float32
        ),
        _lora_shapes(rank),
    )


def _tenant_engine(model, variables, n_tenants, **kw):
    defaults = dict(slots=4, prompt_buckets=(8, 16), max_new_tokens=24,
                    page_tokens=8, tenant_slots=n_tenants + 1, tenant_rank=8)
    defaults.update(kw)
    return BatchEngine(model, variables, EngineConfig(**defaults))


def _dedicated(model, variables, aid, tree, alpha, rank, req, **kw):
    """One single-tenant engine — the deployment alternative multiplexing
    displaces (a whole replica set per fine-tuned job)."""
    eng = _tenant_engine(model, variables, 1, slots=2, **kw)
    eng.adapters.register(aid, tree, alpha, rank)
    eng.install_adapter(aid)
    return eng.run([req])[req.request_id].generated


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_registry_slots_capacity_and_reuse():
    reg = AdapterRegistry(capacity=3, max_rank=8)  # 2 tenant slots
    a = reg.register("a", {}, 16.0, 4)
    b = reg.register("b", {}, 16.0, 4)
    assert {a.slot, b.slot} == {1, 2}
    with pytest.raises(AdapterError, match="full"):
        reg.register("c", {}, 16.0, 4)
    assert reg.resolve("") == 0
    assert reg.resolve("a") == a.slot
    with pytest.raises(UnknownAdapter):
        reg.resolve("ghost")
    # re-register refreshes IN PLACE (tenant checkpoint rollover)
    a2 = reg.register("a", {"new": True}, 16.0, 6)
    assert a2.slot == a.slot and a2.rank == 6
    # unregister frees the slot for a different tenant
    reg.unregister("b")
    c = reg.register("c", {}, 16.0, 2)
    assert c.slot == b.slot


def test_registry_refuses_bad_ranks():
    reg = AdapterRegistry(capacity=3, max_rank=4)
    with pytest.raises(AdapterError, match="rank"):
        reg.register("big", {}, 16.0, 8)
    with pytest.raises(AdapterError, match="rank"):
        reg.register("zero", {}, 16.0, 0)


# ---------------------------------------------------------------------------
# Numerics: multiplexed == dedicated, padding bit-neutral, merged ~= unmerged
# ---------------------------------------------------------------------------


def test_multiplexed_bit_identical_to_dedicated_mixed_ranks(base_model):
    """Four tenants of DIFFERENT ranks multiplexed on one engine, staggered
    with base-model traffic: every output is bit-identical to a dedicated
    single-tenant engine (rank padding in the shared stack is bit-neutral),
    and the base lane is bit-identical to cached_generate."""
    model, variables = base_model
    tenants = {f"t{i}": (_make_adapter(60 + i, 2 * (i % 3) + 2),
                         2 * (i % 3) + 2)
               for i in range(4)}
    eng = _tenant_engine(model, variables, 4, slots=3)
    for aid, (tree, rank) in tenants.items():
        eng.adapters.register(aid, tree, 16.0, rank)
        eng.install_adapter(aid)
    prompt = [3, 1, 4, 1, 5, 9]
    reqs = [
        GenRequest(request_id=f"m-{aid}", tokens=prompt,
                   max_new_tokens=6 + i, adapter_id=aid)
        for i, aid in enumerate(tenants)
    ]
    reqs.append(GenRequest(request_id="m-base", tokens=prompt,
                           max_new_tokens=8))
    res = eng.run(reqs)  # slots=3 < 5 requests: tenants share steps
    outs = {}
    for i, (aid, (tree, rank)) in enumerate(tenants.items()):
        outs[aid] = _dedicated(
            model, variables, aid, tree, 16.0, rank,
            GenRequest(request_id="d", tokens=prompt, max_new_tokens=6 + i,
                       adapter_id=aid),
        )
        assert res[f"m-{aid}"].generated == outs[aid], f"{aid} diverged"
    base = cached_generate(model, variables, jnp.asarray([prompt], jnp.int32),
                           max_new_tokens=8)
    assert res["m-base"].generated == list(np.asarray(base[0, len(prompt):]))
    # the tenants genuinely compute different things
    assert len({tuple(v) for v in outs.values()}) >= 2
    # per-tenant accounting followed the lanes
    for aid in tenants:
        assert eng.tokens_by_tenant[aid] == len(res[f"m-{aid}"].generated)


def test_multiplexed_sampled_reproducible_per_tenant(base_model):
    model, variables = base_model
    tree = _make_adapter(77, 4)
    eng = _tenant_engine(model, variables, 2)
    eng.adapters.register("s", tree, 16.0, 4)
    eng.install_adapter("s")
    req = GenRequest(request_id="r", tokens=[7, 7, 2, 9], max_new_tokens=8,
                     temperature=0.9, top_k=5, seed=123, adapter_id="s")
    got = eng.run([req])["r"].generated
    want = _dedicated(
        model, variables, "s", tree, 16.0, 4,
        GenRequest(request_id="d", tokens=[7, 7, 2, 9], max_new_tokens=8,
                   temperature=0.9, top_k=5, seed=123, adapter_id="s"),
    )
    assert got == want


def test_unmerged_tenant_logits_match_merged_model():
    """The gathered-stack math computes the same function as merging
    ``W + (alpha/r) A B`` into the kernels — to float tolerance: the two
    evaluation orders differ by fp reassociation, which is why the serve
    gates compare multiplexed against DEDICATED UNMERGED engines for bit
    identity and against merged weights only at this tolerance."""
    from finetune_controller_tpu.serve.loader import merge_lora_variables

    # f32 compute isolates the reassociation claim from bf16 rounding
    # (in bf16 the two orders differ at bf16 epsilon, far above 1e-4)
    f32_cfg = BASE_CFG.replace(dtype=jnp.float32)
    model = LlamaForCausalLM(f32_cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 4), jnp.int32))["params"]
    tree = _make_adapter(88, 4)
    tokens = jnp.asarray([[3, 1, 4, 1, 5, 9]], jnp.int32)

    # unmerged: tenant stacks on the base model
    tcfg = f32_cfg.replace(lora_tenant_slots=2, lora_tenant_rank=4)
    tmodel = LlamaForCausalLM(tcfg)
    _, tvars = tmodel.apply(
        {"params": params}, tokens, deterministic=True,
        mutable=("tenants",), adapter_ids=jnp.zeros((1,), jnp.int32),
    )
    from finetune_controller_tpu.serve.adapters import install_into

    tenants = install_into(tvars["tenants"], 1, tree, 16.0, 4)
    lo_t = tmodel.apply(
        {"params": params, "tenants": tenants}, tokens, deterministic=True,
        adapter_ids=jnp.ones((1,), jnp.int32),
    )

    # merged: the production merge math folds the same deltas into W
    lcfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4),
                                        dtype=jnp.float32)
    mcfg, mvars = merge_lora_variables(
        lcfg, {"params": params, "lora": jax.tree.map(jnp.asarray, tree)}
    )
    lo_m = LlamaForCausalLM(mcfg).apply(mvars, tokens, deterministic=True)
    np.testing.assert_allclose(
        np.asarray(lo_t, np.float32), np.asarray(lo_m, np.float32),
        rtol=2e-4, atol=2e-4,
    )
    # and they are NOT bit-equal — the documented reason merged engines are
    # not the bit-identity comparator
    assert lo_t.shape == lo_m.shape


# ---------------------------------------------------------------------------
# Prefix cache: adapter-namespaced keys (the divergence satellite)
# ---------------------------------------------------------------------------


def test_prefix_cache_never_splices_across_adapters(base_model):
    """THE cross-tenant poisoning pin: with the prefix cache on, tenant B
    sending the exact prompt tenant A just cached must MISS (KV depends on
    the adapter that computed it) and produce B's own bit-exact output,
    while a same-tenant repeat still HITS."""
    model, variables = base_model
    ta, tb = _make_adapter(91, 4), _make_adapter(92, 4)
    eng = _tenant_engine(model, variables, 2, slots=2,
                         prefix_cache_bytes=1 << 20)
    eng.adapters.register("A", ta, 16.0, 4)
    eng.adapters.register("B", tb, 16.0, 4)
    eng.sync_adapters()
    shared = [3, 1, 4, 1, 5, 9, 2, 6]

    def req(rid, aid, tail):
        return GenRequest(request_id=rid, tokens=shared + [tail],
                          max_new_tokens=8, adapter_id=aid)

    out_a = eng.run([req("a1", "A", 30)])["a1"].generated
    misses0, hits0 = eng.prefix_misses_total, eng.prefix_hits_total
    # same prompt, OTHER adapter: must not touch A's cached KV
    out_b = eng.run([req("b1", "B", 30)])["b1"].generated
    assert eng.prefix_misses_total == misses0 + 1
    assert eng.prefix_hits_total == hits0
    # same prompt, SAME adapter: the hit path still works per namespace
    out_a2 = eng.run([req("a2", "A", 31)])["a2"].generated
    assert eng.prefix_hits_total == hits0 + 1
    # both tenants match their dedicated engines bit-for-bit
    assert out_a == _dedicated(model, variables, "A", ta, 16.0, 4,
                               req("d", "A", 30))
    assert out_b == _dedicated(model, variables, "B", tb, 16.0, 4,
                               req("d", "B", 30))
    assert out_a2 == _dedicated(model, variables, "A", ta, 16.0, 4,
                                req("d", "A", 31))
    assert out_a != out_b  # the adapters genuinely diverge on this prompt


def test_unload_drops_namespace_and_slot_reuse_is_clean(base_model):
    """After unregister, a NEW tenant reusing the slot id must not see the
    old tenant's cached KV (the namespace is the adapter id, dropped on
    unload) and must compute its own weights."""
    model, variables = base_model
    old, new = _make_adapter(93, 4), _make_adapter(94, 4)
    eng = _tenant_engine(model, variables, 1, slots=2,
                         prefix_cache_bytes=1 << 20)
    eng.adapters.register("old", old, 16.0, 4)
    eng.install_adapter("old")
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    eng.run([GenRequest(request_id="o", tokens=prompt + [1],
                        max_new_tokens=4, adapter_id="old")])
    entry = eng.adapters.get("old")
    eng.adapters.unregister("old")
    eng.remove_adapter("old", entry.slot)
    reused = eng.adapters.register("new", new, 16.0, 4)
    assert reused.slot == entry.slot
    eng.install_adapter("new")
    misses0 = eng.prefix_misses_total
    got = eng.run([GenRequest(request_id="n", tokens=prompt + [1],
                              max_new_tokens=6, adapter_id="new")])
    assert eng.prefix_misses_total == misses0 + 1  # old namespace is gone
    want = _dedicated(model, variables, "new", new, 16.0, 4,
                      GenRequest(request_id="d", tokens=prompt + [1],
                                 max_new_tokens=6, adapter_id="new"))
    assert got["n"].generated == want


def test_unknown_adapter_fails_the_request(base_model):
    model, variables = base_model
    eng = _tenant_engine(model, variables, 1)
    with pytest.raises(UnknownAdapter, match="ghost"):
        eng.admit(GenRequest(request_id="x", tokens=[1, 2],
                             max_new_tokens=4, adapter_id="ghost"))
    # an engine with NO registry names the knob
    plain = BatchEngine(model, variables, EngineConfig(
        slots=2, prompt_buckets=(8, 16), max_new_tokens=24))
    with pytest.raises(UnknownAdapter, match="serve_max_adapters"):
        plain.admit(GenRequest(request_id="x", tokens=[1, 2],
                               max_new_tokens=4, adapter_id="ghost"))


# ---------------------------------------------------------------------------
# Fairness: deficit round robin
# ---------------------------------------------------------------------------


def test_tenant_refresh_drops_stale_prefix_namespace(base_model):
    """Tenant rollover (re-register of an existing adapter id with NEW
    deltas): KV cached under the old weights must be dropped, or the next
    same-prompt request would splice old-checkpoint KV into a lane decoding
    with the new deltas — silently wrong output."""
    from finetune_controller_tpu.serve.fleet import ReplicaFleet

    model, variables = base_model
    old, new = _make_adapter(97, 4), _make_adapter(98, 4)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]

    async def main():
        fleet = ReplicaFleet(
            "job-y", model, variables,
            EngineConfig(slots=2, prompt_buckets=(8, 16), max_new_tokens=24,
                         page_tokens=8, prefix_cache_bytes=1 << 20),
            replicas=1, warm_start=False,
            adapters=AdapterRegistry(capacity=2, max_rank=8),
        )
        await fleet.start()
        await fleet.register_adapter("t", old, 16.0, 4)
        eng = fleet.replicas["r0"].engine
        eng.run([GenRequest(request_id="seed", tokens=prompt + [1],
                            max_new_tokens=4, adapter_id="t")])
        assert eng.prefix_cache_entries >= 1
        # refresh IN PLACE with new deltas (the tenant-rollover path)
        await fleet.register_adapter("t", new, 16.0, 4)
        misses0 = eng.prefix_misses_total
        got = eng.run([GenRequest(request_id="after", tokens=prompt + [1],
                                  max_new_tokens=8, adapter_id="t")])
        # the old-weights entry is GONE: this admission missed, and the
        # output matches a dedicated engine running only the new deltas
        assert eng.prefix_misses_total == misses0 + 1
        want = _dedicated(model, variables, "t", new, 16.0, 4,
                          GenRequest(request_id="d", tokens=prompt + [1],
                                     max_new_tokens=8, adapter_id="t"))
        assert got["after"].generated == want
        await fleet.close()

    run_async(main())


def test_unregister_busy_check_sees_mid_admission_requests(base_model):
    """A request can sit in batcher._inflight (mid-admission in the worker
    thread) before the engine shows a lane for it — the unload busy check
    must count that window, or the tenant's slot could be zeroed under a
    request that already resolved it."""
    from finetune_controller_tpu.serve.batcher import _Pending
    from finetune_controller_tpu.serve.fleet import AdapterBusy, ReplicaFleet

    model, variables = base_model
    tree = _make_adapter(99, 4)

    async def main():
        fleet = ReplicaFleet(
            "job-z", model, variables,
            EngineConfig(slots=2, prompt_buckets=(8, 16), max_new_tokens=24),
            replicas=1, warm_start=False,
            adapters=AdapterRegistry(capacity=2, max_rank=8),
        )
        await fleet.start()
        await fleet.register_adapter("t", tree, 16.0, 4)
        batcher = fleet.replicas["r0"].batcher
        req = GenRequest(request_id="mid", tokens=[1, 2], max_new_tokens=4,
                         adapter_id="t")
        # simulate the admission window: in _inflight, no engine lane yet
        batcher._inflight["mid"] = _Pending(
            req=req, future=asyncio.get_running_loop().create_future(),
            enqueued_at=0.0, deadline=None,
        )
        assert fleet.replicas["r0"].engine.active_by_tenant().get("t", 0) == 0
        with pytest.raises(AdapterBusy):
            await fleet.unregister_adapter("t")
        batcher._inflight.pop("mid").future.cancel()
        await fleet.unregister_adapter("t")  # idle now: unload succeeds
        assert fleet.adapters.get("t") is None
        await fleet.close()

    run_async(main())


def test_fleet_rollover_keeps_adapters_installed(base_model):
    """A rollover generation's replicas sync the adapter registry at build
    time: tenant traffic keeps decoding bit-identically after the swap."""
    from finetune_controller_tpu.serve.fleet import ReplicaFleet
    from finetune_controller_tpu.serve.router import ReplicaRouter

    model, variables = base_model
    tree = _make_adapter(96, 4)

    async def main():
        fleet = ReplicaFleet(
            "job-x", model, variables,
            EngineConfig(slots=2, prompt_buckets=(8, 16), max_new_tokens=24,
                         page_tokens=8),
            replicas=1, warm_start=False,
            adapters=AdapterRegistry(capacity=2, max_rank=8),
        )
        await fleet.start()
        await fleet.register_adapter("t", tree, 16.0, 4)
        router = ReplicaRouter(fleet)
        req = GenRequest(request_id="r1", tokens=[3, 1, 4, 1],
                         max_new_tokens=6, adapter_id="t")
        before = (await router.submit(req)).generated
        assert before == _dedicated(
            model, variables, "t", tree, 16.0, 4,
            GenRequest(request_id="d", tokens=[3, 1, 4, 1],
                       max_new_tokens=6, adapter_id="t"))
        await fleet.rollover(model, variables)
        assert fleet.generation == 1
        req2 = GenRequest(request_id="r2", tokens=[3, 1, 4, 1],
                          max_new_tokens=6, adapter_id="t")
        after = (await router.submit(req2)).generated
        assert after == before
        # aggregate stats carry the tenant counters across the retirement
        assert fleet.stats()["tokens_by_tenant"]["t"] == 12
        await fleet.close()

    run_async(main())


@pytest.mark.slow  # HTTP loop; runs on every ci_check gate via serve-fast
def test_multitenant_adapters_http_loop(tmp_path):
    """The whole multi-tenant surface over HTTP: base loads UNMERGED with
    its own adapter as tenant #1, a second promoted LoRA job stages only
    its deltas onto the running fleet, generate routes per tenant (body
    field AND the tenant job id directly), /metrics exports the page-pool
    and per-tenant gauges, and mismatched bases are refused."""
    import json as _json

    from test_api import _client
    from test_serve import _fabricate_promoted_job, _serve_runtime

    async def main():
        rt = _serve_runtime(tmp_path)
        rt.settings.serve_max_adapters = 2
        rt.settings.serve_paged_kv = True
        rt.settings.serve_kv_page_tokens = 8
        client = await _client(rt)
        base_id = await _fabricate_promoted_job(rt, "tiny-base-0001")
        tenant_id = await _fabricate_promoted_job(rt, "tiny-tena-0001")

        # adapter-load on a not-yet-loaded base refuses with direction
        r = await client.post(
            f"/api/v1/admin/serve/{base_id}/adapters/{tenant_id}/load")
        assert r.status == 409
        assert "load first" in (await r.json())["detail"]

        r = await client.post(f"/api/v1/admin/serve/{base_id}/load")
        assert r.status == 200, await r.text()
        meta = (await r.json())["model"]
        assert meta["multi_tenant"] is True
        assert meta["lora_merged"] is False
        assert meta["self_adapter"] is True  # the job's own fine-tune

        r = await client.post(
            f"/api/v1/admin/serve/{base_id}/adapters/{tenant_id}/load")
        assert r.status == 200, await r.text()
        ameta = (await r.json())["adapter"]
        assert ameta["base_job_id"] == base_id and ameta["slot"] >= 1

        # generate against the base with the tenant selected in the body
        body = {"tokens": [5, 9, 2, 7], "max_new_tokens": 6,
                "adapter": tenant_id}
        r = await client.post(f"/api/v1/jobs/{base_id}/generate", json=body)
        assert r.status == 200, await r.text()
        out = await r.json()
        assert out["model"]["adapter"] == tenant_id
        assert len(out["tokens"]) == 6

        # the tenant's own job id routes to the base fleet transparently
        r = await client.post(
            f"/api/v1/jobs/{tenant_id}/generate",
            json={"tokens": [5, 9, 2, 7], "max_new_tokens": 6},
        )
        assert r.status == 200, await r.text()
        assert (await r.json())["tokens"] == out["tokens"]

        # unknown adapter: 404 naming what IS loaded
        r = await client.post(
            f"/api/v1/jobs/{base_id}/generate",
            json={"tokens": [1, 2], "adapter": "ghost"},
        )
        assert r.status == 404
        assert tenant_id in (await r.json())["detail"]

        # admin view: adapters + page pool visible
        sessions = (await (await client.get("/api/v1/admin/serve")).json())[
            "sessions"]
        s = sessions[base_id]
        assert s["adapters_loaded"] == 2       # self adapter + tenant
        assert tenant_id in s["adapters"]
        assert s["kv_pages_total"] > 0
        assert s["kv_pages_used"] >= 0

        # /metrics: page-pool gauges + per-tenant series with labels
        text = await (await client.get("/metrics")).text()
        assert "ftc_serve_kv_pages_free" in text
        assert "ftc_serve_adapters_loaded" in text
        assert f'ftc_serve_tenant_tokens_total{{job_id="{base_id}",' \
               f'adapter="{tenant_id}"}}' in text

        # unload the tenant; its route disappears
        r = await client.post(
            f"/api/v1/admin/serve/{base_id}/adapters/{tenant_id}/unload")
        assert r.status == 200
        r = await client.post(
            f"/api/v1/admin/serve/{base_id}/adapters/{tenant_id}/unload")
        assert r.status == 404
        r = await client.post(
            f"/api/v1/jobs/{base_id}/generate",
            json={"tokens": [1, 2], "adapter": tenant_id},
        )
        assert r.status == 404

        # a job trained on a DIFFERENT base refuses with both bases named
        from finetune_controller_tpu.controller.schemas import (
            DatabaseStatus,
            JobRecord,
            PromotionStatus,
        )
        from finetune_controller_tpu.train.checkpoint import CheckpointManager
        from finetune_controller_tpu.train.cli import (
            build_model_config,
            build_train_config,
        )
        from finetune_controller_tpu.train.trainer import Trainer
        import tempfile
        from pathlib import Path

        other_id = "tiny-qwen-0001"
        spec = {
            "job_id": other_id,
            "model": {"preset": "tiny-qwen-test", "lora": {"rank": 2}},
            "training": {
                "mode": "lora", "total_steps": 2, "batch_size": 2,
                "seq_len": 16, "log_every": 10**9,
                "checkpoint_every": 10**9,
            },
            "artifacts_dir": "unused",
        }
        trainer = Trainer(build_model_config(spec), build_train_config(spec))
        host = trainer.state_to_host(trainer.init_state())
        prefix = f"obj://{rt.settings.deploy_bucket}/models/{other_id}"
        with tempfile.TemporaryDirectory() as d:
            CheckpointManager(f"{d}/checkpoints").save(1, host, blocking=True)
            (Path(d) / "resolved_config.json").write_text(_json.dumps(spec))
            for path in Path(d).rglob("*"):
                if path.is_file():
                    rel = path.relative_to(d)
                    await rt.store.put_file(f"{prefix}/{rel}", path)
        await rt.state.create_job(JobRecord(
            job_id=other_id, user_id="dev-user", model_name="tiny-qwen-lora",
            status=DatabaseStatus.SUCCEEDED,
            promotion_status=PromotionStatus.COMPLETED,
            promotion_uri=prefix,
        ))
        r = await client.post(
            f"/api/v1/admin/serve/{base_id}/adapters/{other_id}/load")
        assert r.status == 409
        assert "preset" in (await r.json())["detail"]
        await client.close()

    run_async(main())


def test_drr_hot_tenant_cannot_starve_cold_tenant(base_model):
    """A hot tenant floods the queue; a cold tenant's two requests arrive
    after all of them.  Deficit round robin must interleave: the cold
    requests finish well before the hot backlog drains."""
    model, variables = base_model
    tree = _make_adapter(95, 2)

    async def main():
        eng = _tenant_engine(model, variables, 1, slots=2)
        eng.adapters.register("cold", tree, 16.0, 2)
        eng.install_adapter("cold")
        b = Batcher(eng, max_queue=64, drr_quantum_tokens=16.0)
        order: list[str] = []

        async def track(req):
            await b.submit(req, timeout_s=120)
            order.append(req.request_id)

        hot = [
            GenRequest(request_id=f"hot{i}", tokens=[5, 9, 2, 7],
                       max_new_tokens=6)
            for i in range(20)
        ]
        cold = [
            GenRequest(request_id=f"cold{i}", tokens=[5, 9, 2, 7],
                       max_new_tokens=6, adapter_id="cold")
            for i in range(2)
        ]
        tasks = [asyncio.ensure_future(track(r)) for r in hot]
        await asyncio.sleep(0)  # the hot backlog is queued first
        tasks += [asyncio.ensure_future(track(r)) for r in cold]
        await asyncio.gather(*tasks)
        cold_pos = sorted(order.index(r.request_id) for r in cold)
        # both cold requests must land in the first half of completions —
        # FIFO would have put them dead last (positions 20, 21)
        assert cold_pos[-1] < len(order) // 2, (
            f"cold tenant starved: completion order {order}"
        )
        await b.close()

    run_async(main())
