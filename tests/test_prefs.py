"""The prefs/ subsystem (ISSUE 8): DPO loss math, the DPO trainer, the
rollout buffer, the actor/learner loop, and gang scheduling semantics.

Loss-math unit tests are the satellite checklist verbatim: a hand-computed
tiny-logit example, beta monotonicity, masked-logprob parity with
``next_token_loss``'s reductions, and gradient-flows-only-through-policy.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from finetune_controller_tpu.data.preference import synthetic_preference_batches
from finetune_controller_tpu.models.llama import PRESETS
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.prefs.actor import increment_prompts, increment_reward
from finetune_controller_tpu.prefs.dpo_trainer import DPOTrainer
from finetune_controller_tpu.prefs.losses import (
    dpo_loss,
    masked_sequence_logprobs,
)
from finetune_controller_tpu.prefs.rollout_buffer import (
    PreferencePair,
    RolloutBuffer,
)
from finetune_controller_tpu.train.losses import next_token_loss
from finetune_controller_tpu.train.trainer import TrainConfig


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


def test_dpo_loss_hand_computed():
    """B=1 with known logprobs: margin and loss match the closed form."""
    pc, pr = jnp.asarray([-1.0]), jnp.asarray([-2.0])
    rc, rr = jnp.asarray([-1.5]), jnp.asarray([-1.8])
    beta = 0.5
    # margin = beta * ((pc - rc) - (pr - rr)) = 0.5 * (0.5 - (-0.2)) = 0.35
    loss, metrics = dpo_loss(pc, pr, rc, rr, beta)
    assert math.isclose(float(metrics["reward_margin"]), 0.35, abs_tol=1e-6)
    expected = math.log(1.0 + math.exp(-0.35))
    assert math.isclose(float(loss), expected, rel_tol=1e-6)
    assert float(metrics["dpo_accuracy"]) == 1.0
    assert math.isclose(float(metrics["reward_chosen"]), 0.25, abs_tol=1e-6)
    assert math.isclose(float(metrics["reward_rejected"]), -0.1, abs_tol=1e-6)


def test_dpo_loss_tiny_logits_end_to_end():
    """Full pipeline on a hand-built (1, 3, 2) logit tensor.

    Uniform logits everywhere, one masked target per sequence ⇒ each
    per-sequence logprob is log(0.5); with policy == reference the margin is
    exactly 0 and the loss is log 2.
    """
    logits = jnp.zeros((1, 3, 2))
    tokens = jnp.asarray([[0, 1, 0]])
    mask = jnp.asarray([[0.0, 1.0, 0.0]])
    lp = masked_sequence_logprobs(logits, tokens, mask)
    assert math.isclose(float(lp[0]), math.log(0.5), rel_tol=1e-6)
    loss, metrics = dpo_loss(lp, lp, lp, lp, beta=0.3)
    assert math.isclose(float(loss), math.log(2.0), rel_tol=1e-6)
    assert float(metrics["reward_margin"]) == 0.0


def test_beta_monotonicity():
    """For a positive raw margin, larger beta ⇒ larger reward margin and
    smaller loss (the sigmoid sharpens); accuracy is beta-invariant."""
    pc, pr = jnp.asarray([-1.0, -1.2]), jnp.asarray([-2.0, -2.5])
    rc, rr = jnp.asarray([-1.5, -1.4]), jnp.asarray([-1.8, -2.0])
    prev_loss, prev_margin = None, None
    for beta in (0.1, 0.5, 2.0):
        loss, metrics = dpo_loss(pc, pr, rc, rr, beta)
        if prev_loss is not None:
            assert float(loss) < prev_loss
            assert float(metrics["reward_margin"]) > prev_margin
        assert float(metrics["dpo_accuracy"]) == 1.0
        prev_loss, prev_margin = float(loss), float(metrics["reward_margin"])


def test_masked_logprob_parity_with_next_token_loss():
    """-sum(per-seq masked logprobs) / mask_count == next_token_loss."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((4, 12, 32)), jnp.float32)
    tokens = jnp.asarray(rng.integers(0, 32, (4, 12)), jnp.int32)
    mask = jnp.asarray((rng.random((4, 12)) > 0.4), jnp.float32)
    loss, _ = next_token_loss(logits, tokens, mask)
    lp = masked_sequence_logprobs(logits, tokens, mask)
    denom = float(mask[:, 1:].sum())
    assert math.isclose(float(-lp.sum() / denom), float(loss), rel_tol=1e-5)


def test_gradient_flows_only_through_policy():
    """The reference side is stop-gradiented: d loss / d ref_lp == 0, while
    the policy side carries gradient."""
    pc, pr = jnp.asarray([-1.0]), jnp.asarray([-2.0])
    rc, rr = jnp.asarray([-1.5]), jnp.asarray([-1.8])

    def wrt_ref(rc_, rr_):
        return dpo_loss(pc, pr, rc_, rr_, 0.5)[0]

    def wrt_policy(pc_, pr_):
        return dpo_loss(pc_, pr_, rc, rr, 0.5)[0]

    g_rc, g_rr = jax.grad(wrt_ref, argnums=(0, 1))(rc, rr)
    assert float(jnp.abs(g_rc).sum()) == 0.0
    assert float(jnp.abs(g_rr).sum()) == 0.0
    g_pc, g_pr = jax.grad(wrt_policy, argnums=(0, 1))(pc, pr)
    assert float(jnp.abs(g_pc).sum()) > 0.0
    assert float(jnp.abs(g_pr).sum()) > 0.0


# ---------------------------------------------------------------------------
# DPO trainer
# ---------------------------------------------------------------------------


def _tiny_dpo_trainer(**overrides):
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    kw = dict(task="dpo", dpo_beta=0.2, batch_size=4, seq_len=16,
              total_steps=20, warmup_steps=2, learning_rate=1e-3,
              log_every=10**9, checkpoint_every=10**9, prefetch=0,
              heartbeat_interval_s=0)
    kw.update(overrides)
    return DPOTrainer(cfg, TrainConfig(**kw)), cfg


def test_dpo_trainer_margin_increases_and_ref_grad_free():
    trainer, cfg = _tiny_dpo_trainer(learning_rate=5e-3, total_steps=25)
    state = trainer.init_state()
    frozen_before = jax.tree.map(np.asarray, jax.device_get(
        dict(state.frozen)["params"]))
    batches = synthetic_preference_batches(4, 16, cfg.vocab_size, seed=0)
    margins = []
    for _ in range(25):
        state, metrics = trainer.step(state, next(batches))
        margins.append(float(metrics["reward_margin"]))
        assert "dpo_accuracy" in metrics and "accuracy" in metrics
    assert margins[-1] > margins[0] + 0.05, margins
    # the frozen reference never moved (stop-gradient + frozen collection)
    frozen_after = jax.tree.map(np.asarray, jax.device_get(
        dict(state.frozen)["params"]))
    jax.tree.map(np.testing.assert_array_equal, frozen_before, frozen_after)


def test_dpo_trainer_restrictions():
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    with pytest.raises(ValueError, match="mode='lora'"):
        DPOTrainer(cfg, TrainConfig(task="dpo", mode="full"))
    with pytest.raises(ValueError, match="dpo_beta"):
        DPOTrainer(cfg, TrainConfig(task="dpo", dpo_beta=0.0))
    moe = PRESETS["tiny-moe-test"].replace(lora=LoRAConfig(rank=4))
    with pytest.raises(ValueError, match="MoE"):
        DPOTrainer(moe, TrainConfig(task="dpo"))


@pytest.mark.slow
def test_dpo_fit_checkpoints_and_resumes(tmp_path):
    """The full SFT lifecycle machinery under the DPO objective: metrics CSV
    carries reward_margin/dpo_accuracy, checkpoints commit, and a resumed
    fit continues step-continuous."""
    import csv

    trainer, cfg = _tiny_dpo_trainer(total_steps=6, log_every=2,
                                     checkpoint_every=2, eval_every=2,
                                     eval_steps=2)
    art = str(tmp_path / "art")
    batches = synthetic_preference_batches(4, 16, cfg.vocab_size, seed=0)
    evals = synthetic_preference_batches(4, 16, cfg.vocab_size, seed=100_003)
    trainer.fit(batches, art, resume=True, eval_batches=evals)
    with open(f"{art}/metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    assert [int(float(r["step"])) for r in rows] == [2, 4, 6]
    for col in ("reward_margin", "dpo_accuracy", "eval_reward_margin",
                "eval_dpo_accuracy"):
        assert col in rows[0], sorted(rows[0])
        assert rows[-1][col] != ""
    # resume: a fresh trainer continues from the last committed step
    trainer2, _ = _tiny_dpo_trainer(total_steps=8, log_every=2,
                                    checkpoint_every=2)
    batches2 = synthetic_preference_batches(4, 16, cfg.vocab_size, seed=0)
    trainer2.fit(batches2, art, resume=True)
    with open(f"{art}/metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    assert [int(float(r["step"])) for r in rows] == [2, 4, 6, 8]


# ---------------------------------------------------------------------------
# rollout buffer
# ---------------------------------------------------------------------------


def _pair(version, tag=0):
    return PreferencePair(prompt=(1, 2, tag), chosen=(3, 4), rejected=(5, 6),
                          version=version)


def test_rollout_buffer_bounded_fifo():
    buf = RolloutBuffer(capacity=3, seed=0)
    for i in range(5):
        buf.push(_pair(version=i, tag=i))
    assert buf.depth == 3
    assert min(p.version for p in buf._pairs) == 2  # oldest two dropped
    assert buf.pushed_total == 5


def test_rollout_buffer_staleness_eviction_and_metric():
    buf = RolloutBuffer(capacity=10, seed=0)
    for v in (0, 0, 5, 10):
        buf.push(_pair(version=v))
    dropped = buf.evict_below(5, watermark=10)
    assert dropped == 2 and buf.depth == 2
    assert buf.evicted_stale_total == 2
    assert buf.staleness == 5  # oldest surviving pair is 5 behind watermark
    assert buf.stats()["rollout_staleness"] == 5


def test_rollout_buffer_deterministic_sampling():
    def build():
        buf = RolloutBuffer(capacity=8, seed=42)
        for i in range(6):
            buf.push(_pair(version=i, tag=i))
        return buf

    a, b = build(), build()
    for _ in range(3):
        ba, bb = a.sample_batch(4, 8), b.sample_batch(4, 8)
        for k in ba:
            np.testing.assert_array_equal(ba[k], bb[k])
    with pytest.raises(ValueError, match="empty"):
        RolloutBuffer(capacity=2).sample_batch(1, 8)


# ---------------------------------------------------------------------------
# actor / learner
# ---------------------------------------------------------------------------


def test_increment_reward():
    assert increment_reward([5], [6, 7, 8], 256) == 1.0
    assert increment_reward([5], [6, 9, 10], 256) == pytest.approx(2 / 3)
    assert increment_reward([255], [0], 256) == 1.0  # wraps mod vocab
    assert increment_reward([5], [], 256) == 0.0


def test_increment_prompts_deterministic():
    a = [next(increment_prompts(16, 256, seed=3)) for _ in range(1)]
    b = [next(increment_prompts(16, 256, seed=3)) for _ in range(1)]
    assert a == b
    p = a[0]
    assert len(p) == 8 and p[1] == (p[0] + 1) % 256


@pytest.mark.slow
def test_actor_reloads_committed_checkpoint(tmp_path):
    """The actor picks up a committed checkpoint, swaps weights with ZERO new
    compiles, and its pair stream is seed-deterministic."""
    from finetune_controller_tpu.prefs.learner import (
        RolloutConfig,
        build_rlhf_loop,
    )

    trainer, cfg = _tiny_dpo_trainer(task="rlhf", batch_size=2, seq_len=16,
                                     total_steps=2, checkpoint_every=1,
                                     log_every=1)
    art = str(tmp_path / "art")
    stream, actor, buffer = build_rlhf_loop(
        trainer, art,
        rollout=RolloutConfig(pairs_per_round=4, min_fill=4,
                              buffer_capacity=32, max_new_tokens=4,
                              slots=2, temperature=0.9),
    )
    assert actor.version == 0 and not actor.maybe_reload()
    first = next(stream)  # fills the buffer from the step-0 policy
    assert set(first) == {"chosen_tokens", "chosen_mask",
                          "rejected_tokens", "rejected_mask"}
    compiles_after_first = actor.compilations
    # commit checkpoints through the learner and observe the reload: the
    # step-2 pull sees the step-1 commit (the final step-2 commit has no
    # later pull to be observed by)
    trainer.fit(stream, art, resume=True)
    assert actor.reloads == 1 and actor.version == 1
    assert actor.compilations == compiles_after_first  # reload ≠ recompile
    assert actor.compilations <= actor.compile_budget
    now = actor.maybe_reload()  # a later round picks up the final commit
    assert now and actor.version == 2


@pytest.mark.slow
def test_rlhf_loop_generate_commit_reload_cycle(tmp_path):
    """ISSUE 8 acceptance smoke (in-process): the actor generates from
    checkpoint N, the learner commits N+1, and the actor reloads N+1 on the
    next rollout round — with the reward margin rising and the engine inside
    its compile budget."""
    import csv

    from finetune_controller_tpu.prefs.learner import (
        RolloutConfig,
        build_rlhf_loop,
    )

    trainer, cfg = _tiny_dpo_trainer(task="rlhf", batch_size=4, seq_len=32,
                                     total_steps=15, checkpoint_every=5,
                                     log_every=5)
    art = str(tmp_path / "art")
    stream, actor, buffer = build_rlhf_loop(
        trainer, art,
        rollout=RolloutConfig(pairs_per_round=6, min_fill=6,
                              buffer_capacity=64, max_new_tokens=8,
                              slots=4, temperature=0.9),
    )
    trainer.fit(stream, art, resume=True)
    with open(f"{art}/metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    # the row at step k*5 trained on rollouts from the checkpoint committed
    # at (k-1)*5 — a one-round reload lag, never more
    assert [int(float(r["actor_version"])) for r in rows] == [0, 5, 10]
    assert actor.reloads == 2 and actor.version == 10
    assert actor.compilations <= actor.compile_budget
    margins = [float(r["reward_margin"]) for r in rows]
    assert margins[-1] > margins[0], margins
    assert float(rows[-1]["rollout_buffer_depth"]) >= 6
    assert buffer.pushed_total > 0


@pytest.mark.slow
def test_rlhf_job_through_the_cli(tmp_path):
    """`train/cli.py` end to end for task=rlhf: the spec class renders the
    rollout section, run_job selects the DPO learner, wires the actor, and
    the artifacts carry rollout metrics + checkpoints + done.txt."""
    import csv
    import os

    from finetune_controller_tpu.controller.examples import (
        RLHFArguments,
        TinyRLHFTest,
    )
    from finetune_controller_tpu.train.cli import run_job

    spec = TinyRLHFTest(training_arguments=RLHFArguments(
        total_steps=4, warmup_steps=1, batch_size=2, seq_len=16, lora_rank=2,
        log_every=2, checkpoint_every=2, beta=0.2,
        rollout_pairs_per_round=4, rollout_min_fill=4,
        rollout_max_new_tokens=4, rollout_slots=2,
    ))
    art = str(tmp_path / "artifacts")
    # the backend normally renders the mesh from the device flavor; pin a
    # 1-device mesh here so the in-process run ignores the pytest host's
    # virtual device count
    trainer_spec = spec.build_trainer_spec("rlhf-cli-1", art,
                                           mesh={"fsdp": 1})
    assert trainer_spec["training"]["task"] == "rlhf"
    assert trainer_spec["training"]["dpo_beta"] == 0.2
    assert trainer_spec["rollout"]["pairs_per_round"] == 4
    assert "extra_arguments" not in trainer_spec
    run_job(trainer_spec)
    assert os.path.exists(f"{art}/done.txt")
    with open(f"{art}/metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    assert rows and "reward_margin" in rows[0]
    assert "rollout_buffer_depth" in rows[0]
    assert any(p.startswith("step_") for p in os.listdir(f"{art}/checkpoints"))


# ---------------------------------------------------------------------------
# gang scheduling (sched/ min_slices floor)
# ---------------------------------------------------------------------------


def _gang_sched(quota=2):
    from conftest import one_chip_catalog
    from finetune_controller_tpu.sched import FairShareScheduler

    return FairShareScheduler(one_chip_catalog(quota=quota),
                              {"prod": 4.0, "batch": 1.0})


def test_gang_never_admitted_shrunk():
    """Elastic admission starts ordinary multi-slice jobs shrunk on free
    chips — but an atomic gang waits for its FULL size."""
    sched = _gang_sched(quota=2)
    sched.submit("occupier", "chip-1", 1, queue="batch")
    sched.try_admit()
    # a plain 2-slice workload admits shrunk onto the free chip...
    sched.submit("elastic", "chip-1", 2, queue="prod")
    admitted = sched.try_admit()
    assert [w.job_id for w in admitted] == ["elastic"]
    assert sched.workload("elastic").num_slices == 1  # shrunk
    sched.release("elastic")
    # ...the same shape submitted as a gang stays pending
    sched.submit("gang", "chip-1", 2, queue="prod", min_slices=2)
    assert sched.try_admit() == []
    assert sched.workload("gang").admitted is False


def test_gang_victim_evicted_never_shrunk():
    """Preemption against a gang escalates straight to eviction: a partial
    gang cannot run, so there is nothing to shrink to."""
    sched = _gang_sched(quota=2)
    sched.submit("gang", "chip-1", 2, queue="batch", priority="low",
                 min_slices=2)
    assert [w.job_id for w in sched.try_admit()] == ["gang"]
    sched.submit("urgent", "chip-1", 1, queue="prod", priority="high")
    sched.try_admit()
    decisions = sched.take_preemptions()
    assert [d.kind for d in decisions] == ["evict"]
    assert decisions[0].job_id == "gang"


def test_non_gang_victim_still_shrinks():
    """Control: the identical scenario without the gang floor SHRINKS the
    victim (the PR-7 behavior is unchanged for ordinary jobs)."""
    sched = _gang_sched(quota=2)
    sched.submit("elastic", "chip-1", 2, queue="batch", priority="low")
    sched.try_admit()
    sched.submit("urgent", "chip-1", 1, queue="prod", priority="high")
    sched.try_admit()
    decisions = sched.take_preemptions()
    assert [d.kind for d in decisions] == ["shrink"]


def test_rlhf_spec_is_atomic_gang():
    from finetune_controller_tpu.controller.examples import TinyRLHFTest
    from finetune_controller_tpu.controller.specs import TrainingTask

    assert TinyRLHFTest.atomic_gang is True
    assert TinyRLHFTest.default_num_slices == 2
    assert TinyRLHFTest.task is TrainingTask.RLHF


def test_dpo_spec_renders_preference_dataset():
    from finetune_controller_tpu.controller.examples import (
        DPOArguments,
        TinyDPOTest,
    )

    spec = TinyDPOTest(training_arguments=DPOArguments(beta=0.3))
    rendered = spec.build_trainer_spec("dpo-1", "/tmp/a")
    assert rendered["training"]["task"] == "dpo"
    assert rendered["training"]["dpo_beta"] == 0.3
    assert rendered["dataset"] == {"synthetic": {"task": "preference"}}
    assert "rollout" not in rendered
    assert "extra_arguments" not in rendered
