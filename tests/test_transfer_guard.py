"""analysis/transfer_guard.py: the runtime device↔host sync guard.

Unit layer: window semantics (clean dispatch passes, implicit host→device
transfers abort, the ``jax.device_get`` trap works on EVERY backend
including this CPU box, warn mode observes without aborting, the first
call per label is compile-exempt).  Integration layer: the trainer's
jitted step and the serve engine's decode window run CLEAN under
``raise`` (zero trips on the default paths), and the ``FTC_FAULT_TRANSFER``
chaos hand — a real ``jax.device_get`` injected INSIDE the window — aborts
both, which is exactly the bench.py abort contract for timed windows.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from finetune_controller_tpu.analysis.transfer_guard import (
    TransferGuard,
    TransferGuardError,
)


@pytest.fixture()
def add_one():
    fn = jax.jit(lambda x: x + 1)
    fn(jnp.arange(4.0))  # warm so windows never see the compile
    return fn


# ---------------------------------------------------------------------------
# window semantics
# ---------------------------------------------------------------------------


def test_clean_dispatch_passes_and_counts_zero(add_one):
    guard = TransferGuard("raise", skip_first=False)
    x = jnp.arange(4.0)
    for _ in range(3):
        with guard.window("step"):
            y = add_one(x)
    assert float(y[0]) == 1.0
    assert guard.trips == 0


def test_implicit_host_to_device_transfer_aborts(add_one):
    guard = TransferGuard("raise", skip_first=False)
    with pytest.raises(TransferGuardError, match="transfer"):
        with guard.window("step"):
            add_one(np.arange(4.0))  # np leaf at the jit boundary
    assert guard.trips == 1


def test_device_get_trap_fires_inside_window_only(add_one):
    guard = TransferGuard("raise", skip_first=False)
    x = jnp.arange(4.0)
    jax.device_get(x)  # outside any window: fine
    with pytest.raises(TransferGuardError, match="device_get"):
        with guard.window("step"):
            jax.device_get(x)
    assert guard.trips == 1
    jax.device_get(x)  # and fine again after the window


def test_trap_is_thread_local(add_one):
    """Another thread's jax.device_get during a window must NOT trip the
    guard — the serve engine steps in worker threads while the rest of the
    process uses jax freely."""
    import threading

    guard = TransferGuard("raise", skip_first=False)
    x = jnp.arange(4.0)
    errors = []

    def other_thread():
        try:
            jax.device_get(x)
        except BaseException as exc:  # pragma: no cover - the failure case
            errors.append(exc)

    with guard.window("step"):
        t = threading.Thread(target=other_thread)
        t.start()
        t.join()
    assert errors == []
    assert guard.trips == 0


def test_first_call_per_label_is_compile_exempt(add_one):
    guard = TransferGuard("raise")  # skip_first defaults on
    with guard.window("step"):
        jax.device_get(jnp.arange(4.0))  # exempt: compile-time transfers
    with pytest.raises(TransferGuardError):
        with guard.window("step"):
            jax.device_get(jnp.arange(4.0))


def test_warn_mode_observes_without_aborting(add_one, caplog):
    guard = TransferGuard("warn", skip_first=False)
    x = jnp.arange(4.0)
    with guard.window("step"):
        jax.device_get(x)
        jax.device_get(x)
    assert guard.trips == 2  # counted...
    # ...and the dispatch completed — warn mode never raises


def test_nested_window_restores_outer(add_one):
    outer, inner = TransferGuard("raise", skip_first=False), \
        TransferGuard("raise", skip_first=False)
    x = jnp.arange(4.0)
    with outer.window("o"):
        with inner.window("i"):
            pass
        with pytest.raises(TransferGuardError):
            jax.device_get(x)  # the OUTER guard is active again
    assert outer.trips == 1 and inner.trips == 0


def test_from_env_parsing(monkeypatch):
    monkeypatch.delenv("FTC_TRANSFER_GUARD", raising=False)
    assert TransferGuard.from_env() is None
    monkeypatch.setenv("FTC_TRANSFER_GUARD", "off")
    assert TransferGuard.from_env() is None
    monkeypatch.setenv("FTC_TRANSFER_GUARD", "warn")
    assert TransferGuard.from_env().action == "warn"
    monkeypatch.setenv("FTC_TRANSFER_GUARD", "1")
    assert TransferGuard.from_env().action == "raise"
    with pytest.raises(ValueError):
        TransferGuard("explode")


def test_wrap_preserves_lower_for_aot(add_one):
    guard = TransferGuard("raise")
    wrapped = guard.wrap(add_one, "step")
    assert hasattr(wrapped, "lower")
    lowered = wrapped.lower(jnp.arange(4.0))
    assert lowered is not None


# ---------------------------------------------------------------------------
# trainer integration
# ---------------------------------------------------------------------------


def _tiny_trainer(tmp_path, **cfg_kw):
    from finetune_controller_tpu.models import PRESETS, LoRAConfig
    from finetune_controller_tpu.parallel import MeshSpec
    from finetune_controller_tpu.train import Trainer, TrainConfig

    model_cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    mesh = MeshSpec(dp=1).build(jax.devices()[:1])
    train_cfg = TrainConfig(
        mode="lora", total_steps=4, batch_size=4, seq_len=16,
        log_every=2, checkpoint_every=1000, **cfg_kw,
    )
    return Trainer(model_cfg, train_cfg, mesh=mesh), model_cfg


def test_trainer_step_clean_under_raise(tmp_path):
    from finetune_controller_tpu.data import synthetic_batches

    trainer, model_cfg = _tiny_trainer(tmp_path, transfer_guard="raise")
    batches = synthetic_batches(4, 16, model_cfg.vocab_size, task="increment")
    trainer.fit(batches, str(tmp_path))
    assert trainer._transfer_guard is not None
    assert trainer._transfer_guard.trips == 0


def test_trainer_injected_device_get_aborts_the_run(tmp_path, monkeypatch):
    from finetune_controller_tpu.data import synthetic_batches

    monkeypatch.setenv("FTC_FAULT_TRANSFER", "1")
    trainer, model_cfg = _tiny_trainer(tmp_path, transfer_guard="raise")
    batches = synthetic_batches(4, 16, model_cfg.vocab_size, task="increment")
    with pytest.raises(TransferGuardError, match="device_get"):
        trainer.fit(batches, str(tmp_path))


def test_trainer_guard_off_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("FTC_TRANSFER_GUARD", raising=False)
    trainer, _ = _tiny_trainer(tmp_path)
    assert trainer._transfer_guard is None


def test_trainer_guard_inherits_env(tmp_path, monkeypatch):
    monkeypatch.setenv("FTC_TRANSFER_GUARD", "warn")
    trainer, _ = _tiny_trainer(tmp_path)
    assert trainer._transfer_guard is not None
    assert trainer._transfer_guard.action == "warn"


# ---------------------------------------------------------------------------
# serve-engine integration
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serve_model():
    from finetune_controller_tpu.models import PRESETS, LoRAConfig
    from finetune_controller_tpu.models.llama import LlamaForCausalLM

    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    model = LlamaForCausalLM(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 4), jnp.int32)
    )
    return model, variables


def _engine(serve_model, monkeypatch, *, fault: bool):
    from finetune_controller_tpu.serve.engine import BatchEngine, EngineConfig

    monkeypatch.setenv("FTC_TRANSFER_GUARD", "raise")
    if fault:
        monkeypatch.setenv("FTC_FAULT_TRANSFER", "1")
    model, variables = serve_model
    return BatchEngine(
        model, variables,
        EngineConfig(slots=2, prompt_buckets=(8,), max_new_tokens=8),
    )


def test_engine_decode_clean_under_raise(serve_model, monkeypatch):
    from finetune_controller_tpu.serve.engine import GenRequest

    engine = _engine(serve_model, monkeypatch, fault=False)
    results = engine.run([
        GenRequest(request_id="a", tokens=[1, 2, 3], max_new_tokens=6),
        GenRequest(request_id="b", tokens=[4, 5], max_new_tokens=6),
    ])
    assert {len(r.generated) for r in results.values()} == {6}
    assert engine._transfer_guard is not None
    assert engine._transfer_guard.trips == 0


def test_engine_injected_device_get_aborts_decode(serve_model, monkeypatch):
    from finetune_controller_tpu.serve.engine import GenRequest

    engine = _engine(serve_model, monkeypatch, fault=True)
    with pytest.raises(TransferGuardError, match="decode"):
        engine.run([GenRequest(request_id="c", tokens=[1, 2, 3],
                               max_new_tokens=6)])
    assert engine._transfer_guard.trips == 1
