"""GCS object-store backend tests against an in-process fake GCS server.

The reference's S3 path is untestable without AWS (``S3Handler.py`` has zero
tests — SURVEY.md §4); here the cloud store speaks the GCS JSON API over an
injectable endpoint, so the whole surface — uploads, streaming downloads,
paginated listing, server-side copy, deletes, and the metrics/zip helpers —
runs hermetically in CI.
"""

import asyncio
import urllib.parse

from aiohttp import web
from aiohttp.test_utils import TestServer

from conftest import run_async as run
from finetune_controller_tpu.controller.gcs import GCSObjectStore
from finetune_controller_tpu.controller.objectstore import (
    artifacts_prefix,
    build_object_store,
    parse_uri,
)


def make_fake_gcs(page_size: int = 2):
    """Minimal GCS JSON API: media upload/download, metadata, paginated list,
    delete, server-side copyTo. Small page size exercises pagination."""
    blobs: dict[tuple[str, str], bytes] = {}

    async def handler(request: web.Request) -> web.Response:
        path = request.path  # aiohttp decodes %2F — keys arrive with slashes
        if path.startswith("/upload/storage/v1/b/"):
            bucket = path.split("/")[5]
            name = request.query["name"]
            blobs[(bucket, name)] = await request.read()
            return web.json_response({"name": name, "bucket": bucket})
        if "/copyTo/" in path:
            src_part, dst_part = path.split("/copyTo/")
            src_bits = src_part.split("/o/", 1)
            src_bucket = src_bits[0].rsplit("/", 1)[-1]
            src_key = urllib.parse.unquote(src_bits[1])
            dst_bits = dst_part.split("/o/", 1)
            dst_bucket = dst_bits[0].split("b/")[-1]
            dst_key = urllib.parse.unquote(dst_bits[1])
            data = blobs.get((src_bucket, src_key))
            if data is None:
                return web.json_response({}, status=404)
            blobs[(dst_bucket, dst_key)] = data
            return web.json_response({"done": True})
        if "/o/" in path:
            bucket = path.split("/o/")[0].rsplit("/", 1)[-1]
            key = urllib.parse.unquote(path.split("/o/", 1)[1])
            data = blobs.get((bucket, key))
            if request.method == "DELETE":
                if data is None:
                    return web.json_response({}, status=404)
                del blobs[(bucket, key)]
                return web.Response(status=204)
            if data is None:
                return web.json_response({}, status=404)
            if request.query.get("alt") == "media":
                return web.Response(body=data)
            return web.json_response(
                {"name": key, "size": str(len(data)),
                 "updated": "2026-01-01T00:00:00Z"}
            )
        if path.endswith("/o"):  # list
            bucket = path.split("/b/")[1].split("/")[0]
            prefix = request.query.get("prefix", "")
            items = sorted(
                (b, k) for (b, k) in blobs if b == bucket and k.startswith(prefix)
            )
            start = int(request.query.get("pageToken") or 0)
            page = items[start : start + page_size]
            body = {
                "items": [
                    {"name": k, "size": str(len(blobs[(b, k)])),
                     "updated": "2026-01-01T00:00:00Z"}
                    for b, k in page
                ]
            }
            if start + page_size < len(items):
                body["nextPageToken"] = str(start + page_size)
            return web.json_response(body)
        return web.json_response({"error": path}, status=404)

    app = web.Application(client_max_size=1 << 30)
    app.router.add_route("*", "/{tail:.*}", handler)
    return app, blobs


async def _store(page_size: int = 2):
    app, blobs = make_fake_gcs(page_size)
    server = TestServer(app)
    await server.start_server()

    async def token():
        return "fake-token"

    store = GCSObjectStore(
        endpoint=str(server.make_url("")).rstrip("/"), token_fn=token
    )
    return store, server, blobs


def test_gcs_roundtrip_list_copy_delete():
    async def go():
        store, server, blobs = await _store()
        prefix = artifacts_prefix("artifacts", "u", "job1")
        await store.put_bytes(f"{prefix}/a.bin", b"A" * 10)
        await store.put_bytes(f"{prefix}/sub/b.bin", b"B" * 20)
        await store.put_bytes(f"{prefix}/c.csv", b"step,loss\n1,2.0\n")

        assert await store.exists(f"{prefix}/a.bin")
        assert not await store.exists(f"{prefix}/missing")
        assert await store.get_bytes(f"{prefix}/sub/b.bin") == b"B" * 20

        objs = await store.list_prefix(prefix)  # paginated (page_size=2)
        assert len(objs) == 3
        assert {parse_uri(o["uri"])[1].rsplit("/", 1)[-1] for o in objs} == {
            "a.bin", "b.bin", "c.csv"
        }
        assert all(o["mtime"] > 0 for o in objs)

        # server-side promotion copy
        dst = "obj://deploy/models/x/job1"
        n = await store.copy_prefix(prefix, dst)
        assert n == 3
        assert await store.get_bytes(f"{dst}/sub/b.bin") == b"B" * 20

        assert await store.delete_prefix(prefix) == 3
        assert await store.list_prefix(prefix) == []
        await store.close()
        await server.close()

    run(go())


def test_gcs_streaming_and_files(tmp_path):
    async def go():
        store, server, blobs = await _store()
        big = bytes(range(256)) * 8192  # 2 MiB
        src = tmp_path / "big.bin"
        src.write_bytes(big)
        await store.put_file("obj://datasets/big.bin", src)
        assert blobs[("datasets", "big.bin")] == big

        # chunked download
        chunks = []
        async for chunk in store.get_chunks("obj://datasets/big.bin", 1 << 16):
            chunks.append(chunk)
        assert b"".join(chunks) == big and len(chunks) > 1

        dest = tmp_path / "out.bin"
        n = await store.get_file("obj://datasets/big.bin", dest)
        assert n == len(big) and dest.read_bytes() == big

        # async-iterator upload (the URL→store dataset streaming path)
        async def gen():
            for i in range(4):
                yield bytes([i]) * 1000

        total = await store.put_stream("obj://datasets/gen.bin", gen())
        assert total == 4000 and len(blobs[("datasets", "gen.bin")]) == 4000

        # shared helpers from the base class work against GCS too
        await store.put_bytes(
            "obj://artifacts/j/metrics.csv", b"step,loss\n1,2.5\n2,2.0\n"
        )
        res = await store.get_metrics_records("obj://artifacts/j")
        records, uri = res
        assert records[1]["loss"] == 2.0

        dest_zip = tmp_path / "a.zip"
        await store.put_bytes("obj://artifacts/j/w.bin", b"w" * 100)
        n = await store.zip_prefix_to_path("obj://artifacts/j", dest_zip)
        assert n == 2
        import zipfile
        assert sorted(zipfile.ZipFile(dest_zip).namelist()) == ["metrics.csv", "w.bin"]

        await store.close()
        await server.close()

    run(go())


def test_gcs_retry_and_exists_errors(tmp_path):
    """Round-5 hardening: the shared HttpObjectStore retry/backoff applies to
    the GCS engine, and exists() raises (not False) on server errors."""

    async def go():
        app, blobs = make_fake_gcs()
        fail = {"n": 0}

        @web.middleware
        async def flaky(request, handler):
            if fail["n"] > 0:
                fail["n"] -= 1
                return web.Response(status=503, text="transient")
            return await handler(request)

        app.middlewares.append(flaky)
        server = TestServer(app)
        await server.start_server()

        async def token():
            return "fake-token"

        store = GCSObjectStore(
            endpoint=str(server.make_url("")).rstrip("/"), token_fn=token
        )
        store.retry_base_delay = 0.0

        fail["n"] = 2
        await store.put_bytes("obj://datasets/r.bin", b"r" * 64)
        assert blobs[("datasets", "r.bin")] == b"r" * 64

        # put_file rebuilds its chunk generator per attempt -> retryable
        src = tmp_path / "f.bin"
        src.write_bytes(b"f" * 128)
        fail["n"] = 1
        await store.put_file("obj://datasets/f.bin", src)
        assert blobs[("datasets", "f.bin")] == b"f" * 128

        fail["n"] = 1
        dest = tmp_path / "out.bin"
        n = await store.get_file("obj://datasets/r.bin", dest)
        assert n == 64 and dest.read_bytes() == b"r" * 64
        assert not dest.with_name("out.bin.tmp").exists()

        fail["n"] = 10**6
        try:
            await store.exists("obj://datasets/r.bin")
            raise AssertionError("expected IOError from exists() on 5xx")
        except IOError as e:
            assert "503" in str(e)

        await store.close()
        await server.close()

    run(go())


def test_build_object_store_factory(tmp_path):
    from finetune_controller_tpu.controller.config import Settings

    local = build_object_store(Settings(object_store_root=str(tmp_path)))
    from finetune_controller_tpu.controller.objectstore import LocalObjectStore

    assert isinstance(local, LocalObjectStore)
    gcs = build_object_store(
        Settings(object_store_backend="gcs", gcs_endpoint="http://fake:1")
    )
    assert isinstance(gcs, GCSObjectStore)
    assert gcs.endpoint == "http://fake:1"
