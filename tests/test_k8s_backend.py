"""Tests for the K8s TPU backend: JobSet rendering, state mapping, Kueue CRDs.

Covers the capability surface of the reference's PyTorchJob deployer + Kueue
CRDs (SURVEY.md §2 components 6/24) re-targeted at TPU JobSets, exercised
against the in-memory Kubernetes API fake — the reference has zero cluster
test coverage (SURVEY.md §4: 'no kind/minikube harness, no fake
kube-apiserver').
"""

import json

import pytest

from conftest import run_async, tiny_job_spec
from finetune_controller_tpu.controller.backends.base import BackendError
from finetune_controller_tpu.controller.backends.k8s import (
    InMemoryKubeClient,
    K8sJobSetBackend,
    map_jobset_state,
    render_jobset,
    render_kueue_crds,
    render_spec_configmap,
    render_trainer_spec,
)
from finetune_controller_tpu.controller.config import Settings
from finetune_controller_tpu.controller.devices import default_catalog
from finetune_controller_tpu.controller.schemas import BackendJobState, JobInput
from finetune_controller_tpu.controller.monitor import JobMonitor
from finetune_controller_tpu.controller.objectstore import LocalObjectStore
from finetune_controller_tpu.controller.statestore import StateStore
from finetune_controller_tpu.controller.task_builder import DatasetInput, task_builder


CATALOG = default_catalog()


def _job(num_slices=1, device="v5e-16"):
    return JobInput(
        job_id="llama3-8b-lora-abc12345", user_id="alice",
        model_name="llama3-8b-lora", device=device, num_slices=num_slices,
        arguments={},
    )


def test_render_jobset_tpu_topology_and_resources():
    flavor = CATALOG.get("v5e-16")
    js = render_jobset(
        _job(), tiny_job_spec(), flavor,
        namespace="ftc", image="ftc:test",
        dataset_uri="obj://datasets/alice/d1/train.jsonl",
        artifacts_uri="obj://artifacts/finetune_jobs/alice/j/artifacts",
    )
    assert js["kind"] == "JobSet"
    # Kueue integration: suspended with a queue label
    assert js["spec"]["suspend"] is True
    assert js["metadata"]["labels"]["kueue.x-k8s.io/queue-name"] == flavor.queue
    assert js["metadata"]["labels"]["ftc/chips"] == "16"
    rj = js["spec"]["replicatedJobs"][0]
    job_spec = rj["template"]["spec"]
    # 4 hosts per v5e-16 slice, indexed gang
    assert job_spec["parallelism"] == 4 and job_spec["completions"] == 4
    assert job_spec["completionMode"] == "Indexed"
    pod = job_spec["template"]["spec"]
    # TPU slice topology selectors replace GPU counts (SURVEY §2.2)
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "4x4"
    trainer = pod["containers"][0]
    assert trainer["resources"]["limits"]["google.com/tpu"] == "4"
    # jax.distributed bootstrap env
    env = {e["name"]: e.get("value") for e in trainer["env"]}
    assert env["FTC_NUM_PROCESSES"] == "4"
    assert env["FTC_COORDINATOR_ADDRESS"].startswith("llama3-8b-lora-abc12345-slice-0-0.")
    # init container fetches the dataset; NATIVE sidecar (init container with
    # restartPolicy Always) syncs artifacts so a crashed trainer can't wedge
    # the pod in Running
    assert pod["initContainers"][0]["name"] == "dataset-fetch"
    sync = pod["initContainers"][1]
    assert sync["name"] == "artifact-sync"
    assert sync["restartPolicy"] == "Always"
    assert "done.txt" in " ".join(sync["command"])
    # the sidecar only ships the spec's asset patterns
    assert "--pattern" in sync["command"]
    # only the trainer is a main container
    assert [c["name"] for c in pod["containers"]] == ["trainer"]


def test_render_jobset_multislice():
    flavor = CATALOG.get("v5e-16")
    js = render_jobset(
        _job(num_slices=2), tiny_job_spec(), flavor,
        namespace="ftc", image="ftc:test",
        dataset_uri=None, artifacts_uri="obj://artifacts/x",
    )
    rj = js["spec"]["replicatedJobs"][0]
    assert rj["replicas"] == 2
    env = {e["name"]: e.get("value")
           for e in rj["template"]["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["FTC_NUM_PROCESSES"] == "8"  # 2 slices x 4 hosts
    assert js["metadata"]["labels"]["ftc/chips"] == "32"
    # multi-slice jobs carry the libtpu DCN contract alongside the FTC_* seam
    assert env["MEGASCALE_NUM_SLICES"] == "2"
    assert env["MEGASCALE_COORDINATOR_ADDRESS"].startswith(_job().job_id)
    assert "MEGASCALE_SLICE_ID" in env  # downward-API valueFrom (value=None)

    # single-slice jobs must NOT get MEGASCALE env (libtpu would try DCN init)
    js1 = render_jobset(
        _job(), tiny_job_spec(), flavor,
        namespace="ftc", image="ftc:test",
        dataset_uri=None, artifacts_uri="obj://artifacts/x",
    )
    env1 = {e["name"] for e in
            js1["spec"]["replicatedJobs"][0]["template"]["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert not any(n.startswith("MEGASCALE") for n in env1)


def test_render_trainer_spec_mesh_covers_slice():
    flavor = CATALOG.get("v5e-16")
    spec = render_trainer_spec(_job(num_slices=2), tiny_job_spec(), flavor,
                               dataset_uri=None)
    mesh = spec["mesh"]
    assert mesh["dp"] == 2 and mesh["fsdp"] == 16
    assert all(mesh.get(a, 1) == 1 for a in ("ep", "pp", "sp", "tp"))


def test_spec_configmap_roundtrip():
    spec = render_trainer_spec(_job(), tiny_job_spec(), CATALOG.get("v5e-16"),
                               dataset_uri="obj://d/x/train.jsonl")
    cm = render_spec_configmap(_job(), spec, "ftc")
    parsed = json.loads(cm["data"]["job.json"])
    assert parsed["dataset"]["path"] == "/data/dataset/train.jsonl"


def test_map_jobset_state():
    assert map_jobset_state({"spec": {"suspend": True}})[0] is BackendJobState.SUSPENDED
    assert map_jobset_state({"spec": {}})[0] is BackendJobState.CREATED
    assert map_jobset_state(
        {"spec": {}, "status": {"replicatedJobsStatus": [{"active": 1}]}}
    )[0] is BackendJobState.RUNNING
    assert map_jobset_state(
        {"spec": {}, "status": {"restarts": 1}}
    )[0] is BackendJobState.RESTARTING
    assert map_jobset_state(
        {"spec": {}, "status": {"conditions": [{"type": "Completed", "status": "True"}]}}
    )[0] is BackendJobState.SUCCEEDED
    assert map_jobset_state(
        {"spec": {}, "status": {"conditions": [{"type": "Failed", "status": "True",
                                                "message": "boom"}]}}
    ) == (BackendJobState.FAILED, "boom")


def test_kueue_crds_from_catalog():
    crds = render_kueue_crds(CATALOG, namespace="ftc")
    kinds = [c["kind"] for c in crds]
    assert kinds.count("ResourceFlavor") == len(CATALOG.flavors)
    assert kinds.count("ClusterQueue") == 1
    cq = next(c for c in crds if c["kind"] == "ClusterQueue")
    groups = cq["spec"]["resourceGroups"]
    # Kueue demands each resource in exactly ONE group: all TPU flavors share
    # the google.com/tpu group, the cpu flavor gets its own
    covered = [tuple(g["coveredResources"]) for g in groups]
    assert sorted(covered) == [("cpu",), ("google.com/tpu",)]
    tpu_group = next(g for g in groups if g["coveredResources"] == ["google.com/tpu"])
    by_name = {f["name"]: f for f in tpu_group["flavors"]}
    assert set(by_name) == {"v5e-4", "v5e-8", "v5e-16", "v5p-64"}
    assert by_name["v5e-16"]["resources"][0]["nominalQuota"] == 32
    local_queues = [c for c in crds if c["kind"] == "LocalQueue"]
    assert {q["metadata"]["name"] for q in local_queues} == {
        f.queue for f in CATALOG.flavors
    }
    rf = next(c for c in crds if c["kind"] == "ResourceFlavor"
              and c["metadata"]["name"] == "v5p-64")
    assert rf["spec"]["nodeLabels"]["cloud.google.com/gke-tpu-topology"] == "4x4x4"


def test_k8s_backend_lifecycle_with_fake_api(tmp_path):
    async def main():
        client = InMemoryKubeClient()
        settings = Settings(namespace="ftc")
        backend = K8sJobSetBackend(CATALOG, settings, client=client)
        job = _job()
        await backend.submit(
            job, tiny_job_spec(), CATALOG.get("v5e-16"),
            dataset_uri=None, artifacts_uri="obj://artifacts/x",
        )
        # configmap + suspended jobset created
        reports = await backend.list_jobs()
        assert len(reports) == 1
        assert reports[0].state is BackendJobState.SUSPENDED
        assert await backend.queue_snapshot() == [job.job_id]

        # Kueue admits: unsuspend + mark running
        key = (backend._jobsets_path, job.job_id)
        obj = client.objects[key]
        obj["spec"]["suspend"] = False
        obj["status"] = {"replicatedJobsStatus": [{"active": 1}], "startTime": 100.0}
        report = await backend.get_job(job.job_id)
        assert report.state is BackendJobState.RUNNING
        assert report.start_time == 100.0
        assert await backend.queue_snapshot() == []

        # completes
        obj["status"] = {
            "conditions": [{"type": "Completed", "status": "True"}],
            "startTime": 100.0, "completionTime": 200.0,
        }
        report = await backend.get_job(job.job_id)
        assert report.state is BackendJobState.SUCCEEDED

        # pod logs: rank-0 pod resolved by labels (real pods have random
        # name suffixes), logs read through the client seam
        pod_name = f"{job.job_id}-slice-0-0-x7k2p"
        client.objects[(f"/api/v1/namespaces/ftc/pods", pod_name)] = {
            "metadata": {
                "name": pod_name,
                "creationTimestamp": "2026-07-29T10:00:00Z",
                "labels": {
                    "jobset.sigs.k8s.io/jobset-name": job.job_id,
                    "batch.kubernetes.io/job-completion-index": "0",
                    "jobset.sigs.k8s.io/job-index": "0",
                },
            }
        }
        client.pod_logs[pod_name] = ["step 1", "step 2"]
        lines = [l async for l in await backend.read_logs(job.job_id, last_lines=1)]
        assert lines == ["step 2"]

        # delete removes jobset + configmap
        assert await backend.delete_job(job.job_id)
        assert await backend.list_jobs() == []
        assert (backend._configmaps_path, f"{job.job_id}-spec") not in client.objects
        await backend.close()

    run_async(main())


def test_k8s_backend_with_monitor_reconciliation(tmp_path):
    """The monitor works unchanged over the K8s backend (backend-neutral seam)."""

    async def main():
        client = InMemoryKubeClient()
        settings = Settings(namespace="ftc")
        backend = K8sJobSetBackend(CATALOG, settings, client=client)
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        await state.connect()
        monitor = JobMonitor(state, store, backend, interval_s=0.1)

        job = _job(device="v5e-16")
        await task_builder(
            job, tiny_job_spec(), DatasetInput(),
            state=state, store=store, backend=backend, catalog=CATALOG,
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        await monitor.tick()
        rec = await state.get_job(job.job_id)
        assert rec.status.value == "queued"
        assert rec.queue_position == 1

        obj = client.objects[(backend._jobsets_path, job.job_id)]
        obj["spec"]["suspend"] = False
        obj["status"] = {"replicatedJobsStatus": [{"active": 1}], "startTime": 5.0}
        await monitor.tick()
        rec = await state.get_job(job.job_id)
        assert rec.status.value == "running"

        obj["status"] = {
            "conditions": [{"type": "Completed", "status": "True"}],
            "startTime": 5.0, "completionTime": 65.0,
        }
        await monitor.tick()
        rec = await state.get_job(job.job_id)
        assert rec.status.value == "succeeded"
        assert rec.training_duration == 60.0
        # monitor cleaned the cluster objects after success
        assert await backend.list_jobs() == []
        await state.close()

    run_async(main())


def test_storage_cli_get_and_sync(tmp_path, monkeypatch):
    """The pod-side storage CLI (init/sidecar replacement) round-trips."""
    import asyncio

    from finetune_controller_tpu.controller import config as cfg
    from finetune_controller_tpu.controller import storage_cli

    monkeypatch.setenv("FTC_OBJECT_STORE_ROOT", str(tmp_path / "objects"))
    cfg.set_settings(None)  # force re-read of env
    store = LocalObjectStore(tmp_path / "objects")
    run_async(store.put_bytes("obj://datasets/u/d/train.jsonl", b"data\n"))

    dest = tmp_path / "fetched.jsonl"
    assert storage_cli.main(["get", "obj://datasets/u/d/train.jsonl", str(dest)]) == 0
    assert dest.read_bytes() == b"data\n"

    art = tmp_path / "artifacts"
    art.mkdir()
    (art / "metrics.csv").write_text("loss\n1.0\n")
    (art / "done.txt").write_text("done")
    rc = storage_cli.main([
        "sync", str(art), "obj://artifacts/u/j",
        "--interval", "0.1", "--until-done-file", str(art / "done.txt"),
    ])
    assert rc == 0
    assert run_async(store.get_bytes("obj://artifacts/u/j/metrics.csv")) == b"loss\n1.0\n"
    cfg.set_settings(None)


def test_parse_k8s_time_rfc3339():
    from finetune_controller_tpu.controller.backends.k8s import _parse_k8s_time

    assert _parse_k8s_time(100.5) == 100.5
    ts = _parse_k8s_time("2026-07-29T10:00:00Z")
    assert ts is not None and ts > 1.7e9
    assert _parse_k8s_time("not-a-time") is None
    assert _parse_k8s_time(None) is None


def test_report_uses_condition_transition_time():
    """Real JobSet status has no completionTime — the terminal condition's
    lastTransitionTime is the fallback."""
    client = InMemoryKubeClient()
    backend = K8sJobSetBackend(CATALOG, Settings(namespace="ftc"), client=client)
    obj = {
        "metadata": {"name": "j1"},
        "spec": {},
        "status": {
            "startTime": "2026-07-29T10:00:00Z",
            "conditions": [{
                "type": "Completed", "status": "True",
                "lastTransitionTime": "2026-07-29T11:00:00Z",
            }],
        },
    }
    report = backend._report(obj)
    assert report.state is BackendJobState.SUCCEEDED
    assert report.completion_time - report.start_time == 3600.0


def test_k8s_backend_simulated_kueue_lifecycle(tmp_path):
    """Full lifecycle against the SIMULATED Kueue/JobSet operators (round-1
    weak spot: transitions were only ever hand-written fixtures): FIFO
    admission under chip quota, pod materialisation with real JobSet labels,
    rank-0 log resolution against simulator-created pods, terminal states."""

    async def main():
        # quota fits one v5e-16 job (16 chips) at a time
        client = InMemoryKubeClient(quota_chips=16)
        backend = K8sJobSetBackend(CATALOG, Settings(namespace="ftc"), client=client)
        def mk(jid):
            return JobInput(job_id=jid, user_id="alice",
                            model_name="llama3-8b-lora", device="v5e-16",
                            arguments={})
        j1, j2 = mk("sim-1"), mk("sim-2")
        for j in (j1, j2):
            await backend.submit(
                j, tiny_job_spec(), CATALOG.get("v5e-16"),
                dataset_uri=None, artifacts_uri="obj://artifacts/x",
            )
        assert await backend.queue_snapshot() == ["sim-1", "sim-2"]

        # fake Kueue admits FIFO within quota: sim-1 runs, sim-2 waits
        client.kueue_tick()
        r1 = await backend.get_job("sim-1")
        r2 = await backend.get_job("sim-2")
        assert r1.state is BackendJobState.RUNNING
        assert r2.state is BackendJobState.SUSPENDED
        assert await backend.queue_snapshot() == ["sim-2"]

        # rank-0 pod was materialised by the simulator with real labels;
        # logs stream through it
        lines = [l async for l in await backend.read_logs("sim-1")]
        assert any("training started" in l for l in lines)

        # sim-1 finishes -> quota frees -> sim-2 admitted on the next tick
        client.finish_jobset("sim-1")
        assert (await backend.get_job("sim-1")).state is BackendJobState.SUCCEEDED
        client.kueue_tick()
        assert (await backend.get_job("sim-2")).state is BackendJobState.RUNNING

        # failed jobs keep their pods for forensics
        client.finish_jobset("sim-2", failed=True, message="boom")
        r2 = await backend.get_job("sim-2")
        assert r2.state is BackendJobState.FAILED and "boom" in r2.message
        pods = await client.list(
            "/api/v1/namespaces/ftc/pods",
            "jobset.sigs.k8s.io/jobset-name=sim-2",
        )
        assert pods, "failed job's pods must be retained"
        await backend.close()

    run_async(main())


def test_k8s_fake_rejects_malformed_jobset():
    """The fake API server enforces the operator contracts a real cluster
    would: coordinator DNS convention + downward-API annotations."""

    async def main():
        client = InMemoryKubeClient()
        backend = K8sJobSetBackend(CATALOG, Settings(namespace="ftc"), client=client)
        from finetune_controller_tpu.controller.backends.k8s import render_jobset

        js = render_jobset(
            JobInput(job_id="bad-1", user_id="a", model_name="m", device="v5e-16", arguments={}), tiny_job_spec(), CATALOG.get("v5e-16"),
            namespace="ftc", image="x", dataset_uri=None,
            artifacts_uri="obj://artifacts/x",
        )
        # break the coordinator address convention
        env = js["spec"]["replicatedJobs"][0]["template"]["spec"]["template"][
            "spec"]["containers"][0]["env"]
        next(e for e in env if e["name"] == "FTC_COORDINATOR_ADDRESS")[
            "value"] = "wrong-host:1234"
        with pytest.raises(BackendError, match="DNS convention"):
            await client.create(backend._jobsets_path, js)

        # break a downward-API annotation path
        js2 = render_jobset(
            JobInput(job_id="bad-2", user_id="a", model_name="m", device="v5e-16", arguments={}), tiny_job_spec(), CATALOG.get("v5e-16"),
            namespace="ftc", image="x", dataset_uri=None,
            artifacts_uri="obj://artifacts/x",
        )
        env2 = js2["spec"]["replicatedJobs"][0]["template"]["spec"]["template"][
            "spec"]["containers"][0]["env"]
        next(e for e in env2 if e["name"] == "FTC_SLICE_INDEX")["valueFrom"][
            "fieldRef"]["fieldPath"] = "metadata.annotations['wrong/key']"
        with pytest.raises(BackendError, match="downward-API"):
            await client.create(backend._jobsets_path, js2)

    run_async(main())
