import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from finetune_controller_tpu.parallel import MeshSpec, LLAMA_RULES
from finetune_controller_tpu.parallel.mesh import AxisNames


def test_mesh_resolve_infer():
    sizes = MeshSpec(dp=2, fsdp=-1, tp=2).resolve(8)
    assert sizes[AxisNames.FSDP] == 2
    assert np.prod(list(sizes.values())) == 8


def test_mesh_resolve_errors():
    with pytest.raises(ValueError):
        MeshSpec(dp=3, fsdp=-1).resolve(8)  # not divisible
    with pytest.raises(ValueError):
        MeshSpec(dp=2, fsdp=2, tp=4).resolve(8)  # product mismatch
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, fsdp=-1).resolve(8)  # two inferred


def test_build_mesh(devices8):
    mesh = MeshSpec(dp=2, fsdp=2, tp=2).build(devices8)
    assert mesh.shape[AxisNames.DATA] == 2
    assert mesh.shape[AxisNames.TENSOR] == 2
    assert mesh.devices.size == 8


def test_partition_rules_paths():
    class Arr:
        def __init__(self, ndim):
            self.ndim = ndim

    r = LLAMA_RULES
    assert r.spec_for("params/layer_0/attn/q_proj/kernel", Arr(2)) == P("fsdp", "tp")
    assert r.spec_for("params/layer_0/attn/o_proj/kernel", Arr(2)) == P("tp", "fsdp")
    assert r.spec_for("params/layer_0/mlp/down_proj/kernel", Arr(2)) == P("tp", "fsdp")
    assert r.spec_for("params/embed_tokens/embedding", Arr(2)) == P("tp", "fsdp")
    assert r.spec_for("params/layer_0/attn_norm/scale", Arr(1)) == P()
    # scanned stacks get a leading layer axis — the pipeline axis (size 1
    # unless the mesh actually has pp > 1)
    assert r.spec_for("params/blocks/block/attn/q_proj/kernel", Arr(3)) == P("pp", "fsdp", "tp")
    assert r.spec_for("lora/blocks/block/attn/q_proj/lora_a", Arr(3)) == P("pp", "fsdp", None)


def test_tree_specs_on_real_model(devices8):
    from finetune_controller_tpu.models import PRESETS, LlamaForCausalLM, LoRAConfig

    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    model = LlamaForCausalLM(cfg)
    shapes = jax.eval_shape(lambda r: model.init_variables(r), jax.random.PRNGKey(0))
    specs = LLAMA_RULES.tree_specs(shapes)
    flat = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P)
    )[0]
    # every scanned kernel got a 3-long spec with the layer axis on pp
    kernel_specs = [
        s for kp, s in flat if "kernel" in jax.tree_util.keystr(kp)
    ]
    assert kernel_specs, "no kernels found"
    for s in kernel_specs:
        if len(s) == 3:
            assert s[0] == "pp"


def test_order_devices_for_dcn_groups_slices():
    """Multi-slice device lists are regrouped so the outermost (dp) axis
    subdivides on slice boundaries: inner axes stay on ICI, only the dp
    gradient reduction crosses DCN."""
    import dataclasses

    from finetune_controller_tpu.parallel.mesh import (
        AxisNames,
        order_devices_for_dcn,
    )

    @dataclasses.dataclass
    class FakeDev:
        id: int
        slice_index: int

    # two slices of 4 chips, interleaved (the adversarial enumeration order)
    devs = [FakeDev(i, i % 2) for i in range(8)]
    sizes = {a: 1 for a in AxisNames.ORDER}
    sizes[AxisNames.DATA] = 2      # dp over DCN
    sizes[AxisNames.FSDP] = 4      # fsdp within a slice
    ordered = order_devices_for_dcn(devs, sizes)
    assert [d.slice_index for d in ordered] == [0, 0, 0, 0, 1, 1, 1, 1]
    # stable within a slice (preserves enumeration order)
    assert [d.id for d in ordered] == [0, 2, 4, 6, 1, 3, 5, 7]
    # dp blocks (row-major outermost) == one slice each
    assert {d.slice_index for d in ordered[:4]} == {0}
    assert {d.slice_index for d in ordered[4:]} == {1}

    # single-slice / CPU devices pass through untouched
    plain = list(range(8))
    assert order_devices_for_dcn(plain, sizes) == plain


def test_order_devices_for_dcn_warns_on_cross_slice_inner_axis(caplog):
    import dataclasses
    import logging

    from finetune_controller_tpu.parallel.mesh import (
        AxisNames,
        order_devices_for_dcn,
    )

    @dataclasses.dataclass
    class FakeDev:
        id: int
        slice_index: int

    devs = [FakeDev(i, i // 4) for i in range(8)]
    sizes = {a: 1 for a in AxisNames.ORDER}
    sizes[AxisNames.FSDP] = 8      # fsdp spanning both slices: DCN-bound
    with caplog.at_level(logging.WARNING):
        order_devices_for_dcn(devs, sizes)
    assert any("cross" in r.message for r in caplog.records)


def test_order_devices_for_dcn_slice_of_override():
    """Explicit slice_of models multi-slice on devices with no slice_index
    (virtual CPU meshes) and takes the same regrouping path."""
    from finetune_controller_tpu.parallel.mesh import (
        AxisNames,
        order_devices_for_dcn,
    )

    devs = list(range(8))  # no slice_index attribute at all
    sizes = {AxisNames.DATA: 2, AxisNames.FSDP: 4}
    # interleaved: even ids slice 0, odd ids slice 1
    ordered = order_devices_for_dcn(devs, sizes, slice_of=[i % 2 for i in devs])
    assert ordered == [0, 2, 4, 6, 1, 3, 5, 7]
    import pytest

    with pytest.raises(ValueError, match="slice_of has"):
        order_devices_for_dcn(devs, sizes, slice_of=[0, 1])


def test_build_mesh_slice_of_makes_dp_rows_slice_aligned():
    import jax

    from finetune_controller_tpu.parallel.mesh import MeshSpec

    devs = jax.devices()[:8]
    interleaved = [devs[i // 2 + (i % 2) * 4] for i in range(8)]
    mesh = MeshSpec(dp=2, fsdp=4).build(
        interleaved, slice_of=[i % 2 for i in range(8)]
    )
    rows = mesh.devices.reshape(2, -1)
    assert {d.id for d in rows[0].ravel()} == {d.id for d in devs[:4]}
    assert {d.id for d in rows[1].ravel()} == {d.id for d in devs[4:]}


def test_classify_collectives_parses_both_replica_group_forms():
    from finetune_controller_tpu.train.aot import (
        _parse_groups,
        classify_collectives,
    )

    assert _parse_groups("{{0,1},{2,3}}") == [[0, 1], [2, 3]]
    assert _parse_groups("[2,4]<=[8]") == [[0, 1, 2, 3], [4, 5, 6, 7]]
    # transposed iota: [4,2]<=[2,4]T(1,0) -> groups pair device i with i+4
    assert _parse_groups("[4,2]<=[2,4]T(1,0)") == [
        [0, 4], [1, 5], [2, 6], [3, 7]
    ]
    hlo = """
  %ag = f32[8]{0} all-gather(%p), replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}
  %ar = f32[] all-reduce(%x), replica_groups=[4,2]<=[2,4]T(1,0), to_apply=%add
  %ar2 = f32[] all-reduce-start(%y), replica_groups=[1,8]<=[8], to_apply=%add
"""
    split = classify_collectives(hlo, per_slice=4)
    assert split["all-gather"] == {"intra_slice": 1, "cross_slice": 0}
    # [4,2]T groups {i, i+4} cross the 4-device slice boundary; [1,8] too
    assert split["all-reduce"] == {"intra_slice": 0, "cross_slice": 2}
