"""Resize-instead-of-evict (docs/elasticity.md): scheduler plans,
reservations, elastic admission, the grow pass, the simulator's
progress-lost gates, and the supervisor's topology handling.

The e2e (real subprocesses, cross-topology resume) lives in
tests/test_sched_e2e.py; this module is the millisecond-scale policy layer.
"""

import dataclasses

import pytest

from conftest import run_async as run

from finetune_controller_tpu.controller.backends.local import LocalProcessBackend
from finetune_controller_tpu.controller.devices import (
    DeviceCatalog,
    DeviceFlavor,
    FlavorQuota,
)
from finetune_controller_tpu.controller.objectstore import LocalObjectStore
from finetune_controller_tpu.controller.schemas import DatabaseStatus, JobRecord
from finetune_controller_tpu.controller.statestore import StateStore
from finetune_controller_tpu.sched import FairShareScheduler
from finetune_controller_tpu.sched.preemption import (
    ResizeDecision,
    plan_preemption,
)
from finetune_controller_tpu.sched.queues import Workload
from finetune_controller_tpu.sched.sim import (
    TRACE_QUEUES,
    ClusterSim,
    elastic_trace,
    percentile,
    sim_catalog,
)
from finetune_controller_tpu.resilience.policy import RetryPolicy
from finetune_controller_tpu.resilience.supervisor import RetrySupervisor


def _catalog(quota=4, chips_per_slice=1):
    return DeviceCatalog(
        flavors=[DeviceFlavor(name="chip", generation="cpu", hosts=1,
                              chips_per_host=chips_per_slice, runtime="cpu",
                              queue="q")],
        quotas=[FlavorQuota(flavor="chip", nominal_chips=quota)],
        default_flavor="chip",
    )


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------


def _w(job_id, slices, *, queue="default", priority=50, seq=0, admitted=True):
    return Workload(
        job_id=job_id, flavor="chip", chips=slices, queue=queue,
        priority=priority, seq=seq, admitted=admitted,
        num_slices=slices, requested_slices=slices,
    )


def test_planner_prefers_shrink_over_evict():
    head = _w("hi", 2, priority=100, admitted=False)
    victim = _w("lo", 4, priority=0)
    plans = plan_preemption(
        head, [victim], 2, over_share={}, preemptor_under_share=False,
    )
    assert [(d.job_id, d.kind, d.from_slices, d.to_slices) for d in plans] == [
        ("lo", "shrink", 4, 2)
    ]
    assert plans[0].preemptor_id == "hi"


def test_planner_shrinks_to_fair_share_beyond_shortfall():
    """A victim whose queue is over share sheds its borrowed chips too —
    the freed headroom absorbs the next arrivals without another restart."""
    head = _w("hi", 1, queue="prod", priority=100, admitted=False)
    victim = _w("lo", 4, queue="batch", priority=0)
    plans = plan_preemption(
        head, [victim], 1,
        over_share={"batch": 3.0}, preemptor_under_share=False,
    )
    # need 1, fair deepening 3 -> shrink all the way to 1 slice
    assert [(d.kind, d.to_slices) for d in plans] == [("shrink", 1)]


def test_planner_escalates_to_evict_and_stays_all_or_nothing():
    head = _w("hi", 4, priority=100, admitted=False)
    victim = _w("lo", 2, priority=0)
    # shrink frees 1 < 4; eviction frees 2 < 4 -> nothing is touched
    assert plan_preemption(
        head, [victim], 4, over_share={}, preemptor_under_share=False,
    ) == []
    # 2 needed: shrink (1) cannot cover, escalates to a full eviction
    plans = plan_preemption(
        head, [victim], 2, over_share={}, preemptor_under_share=False,
    )
    assert [(d.kind, d.to_slices) for d in plans] == [("evict", 0)]


def test_planner_resize_off_degrades_to_pr5():
    head = _w("hi", 2, priority=100, admitted=False)
    victim = _w("lo", 4, priority=0)
    plans = plan_preemption(
        head, [victim], 2, over_share={}, preemptor_under_share=False,
        resize=False,
    )
    assert [(d.kind, d.to_slices) for d in plans] == [("evict", 0)]


def test_decision_kinds():
    assert ResizeDecision("j", "p", 4, 0).kind == "evict"
    assert ResizeDecision("j", "p", 4, 2).kind == "shrink"
    assert ResizeDecision("j", None, 2, 4).kind == "grow"


# ---------------------------------------------------------------------------
# Scheduler: shrink + reservation + resubmit
# ---------------------------------------------------------------------------


def test_shrink_reserves_survivor_chips_for_resubmit():
    """A shrunk victim's surviving slices are fenced: the preemptor gets
    exactly the shed chips, later arrivals get nothing, and the victim's
    resubmit admits through its own reservation within one pass."""
    sched = FairShareScheduler(_catalog(quota=4))
    sched.submit("lo", "chip", num_slices=4, priority="low")
    sched.try_admit()
    sched.submit("hi", "chip", num_slices=2, priority="high")
    sched.try_admit()
    decisions = sched.take_preemptions()
    assert [(d.job_id, d.kind, d.to_slices) for d in decisions] == [
        ("lo", "shrink", 2)
    ]
    # victim still holds its chips while exiting: nothing admits
    assert sched.try_admit() == []
    sched.release("lo")  # the backend reports the exit
    sched.submit("sneak", "chip", num_slices=2, priority="normal")
    admitted = [w.job_id for w in sched.try_admit()]
    # the preemptor takes the shed 2 chips; sneak must NOT take the 2
    # reserved for lo's resubmit
    assert admitted == ["hi"]
    assert not sched.is_admitted("sneak")
    sched.submit("lo", "chip", num_slices=2, requested_slices=4,
                 priority="low")
    admitted = [w.job_id for w in sched.try_admit()]
    assert admitted == ["lo"]
    w = sched.workload("lo")
    assert w.num_slices == 2 and w.requested_slices == 4 and w.shrunk
    snap = sched.snapshot()
    assert snap["shrinks_total"] == 1
    assert snap["shrunk_workloads"]["lo"]["num_slices"] == 2
    assert snap["resize_reservations"] == {}  # consumed on admission


def test_inflight_shrink_victim_not_double_counted():
    """While a shrink victim is still exiting it is counted in used chips
    AND holds a reservation for its surviving slices — the reservation must
    only cover the part BEYOND what it holds, or repeated admission passes
    see phantom negative capacity and evict innocent bystanders."""
    sched = FairShareScheduler(_catalog(quota=6))
    sched.submit("v1", "chip", num_slices=4, priority="low")
    sched.submit("bystander", "chip", num_slices=1, priority="low")
    sched.submit("v2", "chip", num_slices=1, priority="low")
    sched.try_admit()
    sched.submit("p", "chip", num_slices=2, priority="high")
    sched.try_admit()
    # youngest victims are 1-slice (unshrinkable): the 4-slice job sheds 2
    assert [(d.job_id, d.kind, d.to_slices)
            for d in sched.take_preemptions()] == [("v1", "shrink", 2)]
    # v1 has not exited yet: further passes must see the head as covered —
    # no new plans, and the bystanders (whose chips are not needed) untouched
    for _ in range(3):
        assert sched.try_admit() == []
        assert sched.take_preemptions() == []
    assert not sched.workload("v2").preempting
    assert not sched.workload("bystander").preempting
    sched.release("v1")
    assert [w.job_id for w in sched.try_admit()] == ["p"]


def test_elastic_admission_when_no_preemption_possible():
    """A blocked multi-slice head with no eligible victims starts SHRUNK on
    the free chips instead of starving behind a reservation (the PR-5
    anti-starvation pin, upgraded: the head RUNS instead of waiting)."""
    sched = FairShareScheduler(_catalog(quota=2))
    sched.submit("s0", "chip")
    sched.submit("s1", "chip")
    sched.try_admit()
    sched.submit("big", "chip", num_slices=2)
    sched.release("s0")  # one chip free; s1 is same-priority: no victims
    admitted = [w.job_id for w in sched.try_admit()]
    assert admitted == ["big"]
    w = sched.workload("big")
    assert w.num_slices == 1 and w.requested_slices == 2 and w.shrunk
    assert sched.take_preemptions() == []  # nobody was killed for this
    assert sched.snapshot()["resizes_total"] == 1
    assert sched.admitted_shrunk_total == 1


def test_elastic_admission_respects_fair_share_cap():
    """Elastic admission must not let a queue absorb idle capacity past its
    nominal share during contention — the share cap parks the workload as a
    blocked head instead."""
    clock = FakeClock()
    sched = FairShareScheduler(
        _catalog(quota=4), {"a": 1.0, "b": 1.0}, clock=clock,
    )
    sched.submit("a0", "chip", queue="a")
    sched.submit("a1", "chip", queue="a")
    sched.submit("b0", "chip", queue="b")
    sched.try_admit()
    # a is AT its share (2 of 4 with two active queues): a 3-slice a-job
    # must not elastically admit into the free chip
    sched.submit("a-big", "chip", num_slices=3, queue="a")
    assert sched.try_admit() == []
    assert not sched.is_admitted("a-big")
    sched.release("a-big")
    # b is under share: its 3-slice job may start shrunk on the free chip
    # (same priority everywhere, so no preemption path exists)
    sched.submit("b-big", "chip", num_slices=3, queue="b")
    admitted = [w.job_id for w in sched.try_admit()]
    assert "b-big" in admitted
    assert sched.workload("b-big").num_slices == 1


def test_grow_pass_restores_after_tenant_quiet():
    """A shrunk workload grows back (via a SIGTERM-shaped decision) once the
    flavor has been free of other tenants' demand for grow_delay_s."""
    clock = FakeClock()
    sched = FairShareScheduler(
        _catalog(quota=4), {"a": 1.0, "b": 1.0},
        clock=clock, grow_delay_s=10.0,
    )
    sched.submit("b0", "chip", num_slices=2, queue="b")
    sched.try_admit()
    # same priority + a not over share: no preemption path, so the 4-slice
    # job elastically admits at its share (2 of 4 chips)
    sched.submit("a-big", "chip", num_slices=4, queue="a")
    sched.try_admit()
    assert sched.workload("a-big").num_slices == 2
    clock.t = 5.0
    sched.release("b0")  # b finishes; flavor becomes tenant-quiet
    sched.try_admit()
    assert sched.take_preemptions() == []  # quiet window not yet elapsed
    clock.t = 20.0
    sched.try_admit()
    decisions = sched.take_preemptions()
    assert [(d.job_id, d.kind, d.from_slices, d.to_slices)
            for d in decisions] == [("a-big", "grow", 2, 4)]
    # the grown size is reserved through the exit/requeue window
    sched.release("a-big")
    sched.submit("squatter", "chip", num_slices=2, queue="b")
    assert [w.job_id for w in sched.try_admit()] == []
    sched.submit("a-big", "chip", num_slices=4, queue="a")
    assert [w.job_id for w in sched.try_admit()] == ["a-big"]
    assert sched.workload("a-big").num_slices == 4
    snap = sched.snapshot()
    assert snap["grows_total"] == 1
    assert [h["kind"] for h in snap["resize_history"]] == ["shrink", "grow"]


def test_resize_reservation_expires_on_ttl():
    """A reservation whose resubmit never arrives (cancel mid-resize) must
    not fence chips forever."""
    clock = FakeClock()
    sched = FairShareScheduler(
        _catalog(quota=2), clock=clock, reservation_ttl_s=30.0,
    )
    sched.submit("lo", "chip", num_slices=2, priority="low")
    sched.try_admit()
    sched.submit("hi", "chip", num_slices=1, priority="high")
    sched.try_admit()
    assert [d.kind for d in sched.take_preemptions()] == ["shrink"]
    sched.release("lo")  # exits; 1 chip reserved for lo's resubmit
    sched.try_admit()
    sched.submit("later", "chip", num_slices=1)
    assert not sched.try_admit()  # reservation holds
    clock.t = 100.0  # ... until the TTL
    assert [w.job_id for w in sched.try_admit()] == ["later"]


def test_forget_drops_reservation():
    sched = FairShareScheduler(_catalog(quota=2))
    sched.submit("lo", "chip", num_slices=2, priority="low")
    sched.try_admit()
    sched.submit("hi", "chip", num_slices=1, priority="high")
    sched.try_admit()
    sched.take_preemptions()
    sched.forget("lo")  # cancelled for good: reservation must die too
    sched.submit("later", "chip", num_slices=1)
    admitted = {w.job_id for w in sched.try_admit()}
    assert admitted == {"hi", "later"}


def test_fifo_scheduler_ignores_requested_slices():
    from finetune_controller_tpu.controller.backends.scheduler import (
        GangScheduler,
    )

    sched = GangScheduler(_catalog(quota=2))
    w = sched.submit("j", "chip", 1, requested_slices=2)
    assert w.chips == 1


# ---------------------------------------------------------------------------
# Simulator: the ISSUE 7 gated metric
# ---------------------------------------------------------------------------


def _run_leg(trace, *, resize, grow_delay_s=5.0):
    catalog = sim_catalog(8)
    report = ClusterSim(
        catalog,
        lambda clock: FairShareScheduler(
            catalog, TRACE_QUEUES, clock=clock,
            resize=resize, grow_delay_s=grow_delay_s,
        ),
        queue_weights=TRACE_QUEUES,
    ).run(trace)
    for o in report.outcomes.values():
        assert o.finish_s is not None, f"{o.job_id} never finished"
    return report


def test_sim_resize_beats_evict_on_progress_lost():
    """The BENCH_MODE=sched gate, pinned: on the capacity-reclaim trace,
    resize strictly beats full eviction on chip-seconds of progress lost,
    with Jain fairness no worse and small-job p95 wait within two exit
    graces of the evict leg."""
    trace = elastic_trace(0)
    evict = _run_leg(trace, resize=False)
    resize = _run_leg(trace, resize=True)
    assert resize.progress_lost_chip_seconds < evict.progress_lost_chip_seconds
    assert resize.jain_fairness >= evict.jain_fairness
    p95_e = percentile(evict.waits(max_chips=1), 95)
    p95_r = percentile(resize.waits(max_chips=1), 95)
    assert p95_r <= p95_e + 2.0 * 1.0 + 0.5  # two exit graces of slack
    assert resize.resizes > 0
    # the XL job ran through the contention window instead of parking
    xl = resize.outcomes["xl-0"]
    assert min(xl.sizes) < 8 and xl.sizes[-1] == 8  # shrank, grew back


def test_sim_resized_jobs_always_resume_and_finish():
    for seed in (0, 1, 2):
        report = _run_leg(elastic_trace(seed), resize=True)
        for o in report.outcomes.values():
            assert len(o.resumed_at) == len(o.preempted_at), o.job_id


def test_sim_deterministic_with_resize():
    a = _run_leg(elastic_trace(0), resize=True)
    b = _run_leg(elastic_trace(0), resize=True)
    assert dataclasses.asdict(a) == dataclasses.asdict(b)


# ---------------------------------------------------------------------------
# Supervisor: resize intake + topology downgrade
# ---------------------------------------------------------------------------


class _StubBackend:
    """Records submissions; always succeeds."""

    def __init__(self):
        self.submitted = []
        self.deleted = []

    async def submit(self, job, spec, flavor, *, dataset_uri, artifacts_uri):
        self.submitted.append(job)

    async def delete_job(self, job_id, *, forget_reservations=False):
        self.deleted.append(job_id)
        return True


def test_supervisor_resize_intake_skips_backoff_and_budget(tmp_path):
    """A resize rides the failure path but is not a failure: zero backoff,
    no attempt burned, topology recorded crash-safe."""

    async def main():
        from finetune_controller_tpu.controller import registry

        registry.reset()
        registry.load_builtin_models()
        state = StateStore(tmp_path / "state")
        await state.connect()
        backend = _StubBackend()
        clock = FakeClock(t=1000.0)
        sup = RetrySupervisor(
            state, backend, _catalog(quota=4),
            policy=RetryPolicy(max_attempts=2, base_delay_s=30.0, seed=0),
            _clock=clock,
        )
        job = JobRecord(
            job_id="rz-1", user_id="u", model_name="tiny-test-lora",
            device="chip", num_slices=4, status=DatabaseStatus.RUNNING,
        )
        await state.create_job(job)
        # three consecutive resizes: none burns the retry budget
        for i, to in enumerate((2, 1, 2)):
            rec = await state.get_job("rz-1")
            assert await sup.on_job_failed(
                rec, exit_code=143, message="resized by scheduler",
                resize_to=to,
            )
            rec = await state.get_job("rz-1")
            assert rec.status is DatabaseStatus.RETRYING
            assert rec.metadata["current_num_slices"] == to
            history = rec.metadata["attempt_history"]
            assert history[-1]["resize"] is True
            assert history[-1]["delay_s"] == 0.0  # no backoff on a resize
            assert history[-1]["attempt"] == 1  # budget untouched
            assert rec.metadata["retry_next_at"] <= clock()
            # resubmit happens on the next tick, at the resized topology
            assert await sup.tick() == 1
            sub = backend.submitted[-1]
            assert sub.num_slices == to
            assert sub.requested_num_slices == 4
            rec = await state.get_job("rz-1")
            assert rec.status is DatabaseStatus.QUEUED
            assert rec.metadata["last_ran_num_slices"] == to
            await state.update_job_status("rz-1", DatabaseStatus.RUNNING)
        assert sup.resizes == 3
        # 2->1, 1->2 changed topology; 4->2 (first) also differs from the
        # original 4: every resubmit here was an elastic restore
        assert sup.elastic_restores == 3
        await state.close()

    run(main())


def test_supervisor_downgrades_topology_that_no_longer_fits(tmp_path):
    """A RETRYING job whose recorded topology exceeds the (shrunk) catalog
    quota is requeued at the largest feasible size with a logged downgrade
    — not stranded (ISSUE 7 satellite)."""

    async def main():
        from finetune_controller_tpu.controller import registry

        registry.reset()
        registry.load_builtin_models()
        state = StateStore(tmp_path / "state")
        await state.connect()
        backend = _StubBackend()
        # the catalog the CONTROLLER restarts with: quota shrank to 2
        sup = RetrySupervisor(
            state, backend, _catalog(quota=2),
            policy=RetryPolicy(max_attempts=3, base_delay_s=0.0, seed=0),
            _clock=FakeClock(t=1000.0),
        )
        job = JobRecord(
            job_id="dg-1", user_id="u", model_name="tiny-test-lora",
            device="chip", num_slices=4, status=DatabaseStatus.RETRYING,
            metadata={"retry_next_at": 0.0},
        )
        await state.create_job(job)
        assert await sup.tick() == 1
        sub = backend.submitted[-1]
        assert sub.num_slices == 2  # largest feasible under the new quota
        rec = await state.get_job("dg-1")
        assert rec.status is DatabaseStatus.QUEUED
        assert rec.metadata["topology_downgraded"]["from_num_slices"] == 4
        assert rec.metadata["topology_downgraded"]["to_num_slices"] == 2
        assert sup.topology_downgrades == 1

        # a flavor that no longer fits even ONE slice is terminal, clearly
        big_flavor = DeviceCatalog(
            flavors=[DeviceFlavor(name="chip", generation="cpu", hosts=1,
                                  chips_per_host=4, runtime="cpu", queue="q")],
            quotas=[FlavorQuota(flavor="chip", nominal_chips=2)],
            default_flavor="chip",
        )
        sup2 = RetrySupervisor(
            state, backend, big_flavor,
            policy=RetryPolicy(max_attempts=3, base_delay_s=0.0, seed=0),
            _clock=FakeClock(t=1000.0),
        )
        job2 = JobRecord(
            job_id="dg-2", user_id="u", model_name="tiny-test-lora",
            device="chip", num_slices=1, status=DatabaseStatus.RETRYING,
            metadata={"retry_next_at": 0.0},
        )
        await state.create_job(job2)
        assert await sup2.tick() == 0
        rec = await state.get_job("dg-2")
        assert rec.status is DatabaseStatus.FAILED
        assert "no longer fits" in rec.metadata["backend_message"]
        await state.close()

    run(main())


# ---------------------------------------------------------------------------
# Backend: elastic admission re-renders the trainer spec
# ---------------------------------------------------------------------------


def test_backend_rerenders_spec_on_elastic_admission(tmp_path):
    """When the scheduler grants fewer slices than asked, the local backend
    rewrites the trainer spec's mesh and the XLA device-count env before
    spawning."""

    async def main():
        import json

        from finetune_controller_tpu.controller import registry
        from finetune_controller_tpu.controller.schemas import JobInput
        from finetune_controller_tpu.controller.task_builder import (
            DatasetInput,
            task_builder,
        )
        from conftest import tiny_job_spec

        registry.reset()
        registry.load_builtin_models()
        state = StateStore(tmp_path / "state")
        await state.connect()
        store = LocalObjectStore(tmp_path / "objects")
        catalog = _catalog(quota=2)
        backend = LocalProcessBackend(
            tmp_path / "sandboxes", store, catalog, sync_interval_s=5.0,
        )
        # a 1-chip job occupies half the cluster
        spec = tiny_job_spec()
        await task_builder(
            JobInput(job_id="occupant", user_id="u",
                     model_name="tiny-test-lora", device="chip",
                     arguments=spec.training_arguments.model_dump()),
            spec, DatasetInput(),
            state=state, store=store, backend=backend, catalog=catalog,
            datasets_bucket="d", artifacts_bucket="a",
        )
        # a 2-slice job elastically admits at 1 slice
        spec2 = tiny_job_spec()
        await task_builder(
            JobInput(job_id="elastic", user_id="u",
                     model_name="tiny-test-lora", device="chip",
                     num_slices=2,
                     arguments=spec2.training_arguments.model_dump()),
            spec2, DatasetInput(),
            state=state, store=store, backend=backend, catalog=catalog,
            datasets_bucket="d", artifacts_bucket="a",
        )
        handle = backend._handles["elastic"]
        assert handle.granted_slices == 1
        assert handle.requested_slices == 2
        rendered = json.loads(handle.spec_path.read_text())
        assert rendered["mesh"]["dp"] == 1  # re-rendered at the grant
        assert "device_count=1" in handle.env["XLA_FLAGS"]
        report = await backend.get_job("elastic")
        assert report.metadata["current_num_slices"] == 1
        assert report.metadata["requested_num_slices"] == 2
        await backend.close()
        await state.close()

    run(main())
