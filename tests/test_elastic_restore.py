"""Topology-portable checkpoints (train/elastic.py, docs/elasticity.md).

Proof layers, all on the 8-virtual-device CPU mesh of the test process:

* every committed checkpoint carries a ``manifest.json`` (mesh axes,
  partition-rule fingerprint, global-batch microstructure, per-leaf
  shape/dtype map) — satellite: manifest round-trip;
* a checkpoint written on dp=2 restores onto dp=1 and back onto dp=2 with
  every state leaf BIT-IDENTICAL and ``grad_accum_steps`` recomputed so the
  global batch decomposes into the same row-shards;
* the dp=2 → dp=1 → dp=2 resumed loss trajectory matches an uninterrupted
  dp=2 twin within reduction-order tolerance, and the elastic run itself is
  deterministically replayable bit-for-bit.  (Bit-identity ACROSS topologies
  is out of reach by construction: gradient contractions cross device
  boundaries differently on a different mesh, so bf16/f32 reduction order
  differs — docs/elasticity.md spells this out.  Same-shape resume stays
  bit-identical: tests/test_chaos.py.)
* restore refuses a manifest whose partition-rule fingerprint differs from
  the live rule table, and a mismatched ``like`` tree raises
  ``CheckpointShapeError`` naming the first offending path (satellites).
"""

import csv
import json
import logging
from pathlib import Path

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from finetune_controller_tpu.data.synthetic import synthetic_batches
from finetune_controller_tpu.models.llama import PRESETS
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.parallel.mesh import MeshSpec
from finetune_controller_tpu.parallel.sharding import LLAMA_RULES, PartitionRules
from finetune_controller_tpu.train.checkpoint import (
    CheckpointManager,
    CheckpointShapeError,
)
from finetune_controller_tpu.train.elastic import (
    ElasticManifestError,
    build_manifest,
    plan_elastic_resume,
)
from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

MODEL = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=2))
TOTAL, CADENCE, BATCH = 9, 3, 4


def _config(total_steps):
    # constant LR: the schedule must not depend on a segment's total_steps,
    # or the per-segment configs would train different trajectories
    return TrainConfig(
        mode="lora", learning_rate=0.01, schedule="constant", warmup_steps=1,
        total_steps=total_steps, batch_size=BATCH, seq_len=16,
        log_every=1, checkpoint_every=CADENCE, heartbeat_interval_s=0,
    )


def _trainer(dp, total_steps):
    mesh = MeshSpec(dp=dp, fsdp=1).build(jax.devices()[:dp])
    return Trainer(MODEL, _config(total_steps), mesh=mesh)


def _fit(dp, total_steps, art, resume=True):
    trainer = _trainer(dp, total_steps)
    batches = synthetic_batches(BATCH, 16, MODEL.vocab_size, seed=0)
    state = trainer.fit(batches, str(art), resume=resume)
    return trainer, state


def _rows(art):
    with open(Path(art) / "metrics.csv", newline="") as f:
        return list(csv.DictReader(f))


def _run_elastic(art):
    """dp=2 to step 3, RESUME on dp=1 to step 6, resume back on dp=2 to 9."""
    _fit(2, 3, art, resume=False)
    t1, _ = _fit(1, 6, art)
    assert t1.cfg.grad_accum_steps == 2  # microstructure preserved on dp=1
    t2, state = _fit(2, TOTAL, art)
    assert t2.cfg.grad_accum_steps == 1  # restored on the way back up
    return state


@pytest.fixture(scope="module")
def runs(tmp_path_factory):
    root = tmp_path_factory.mktemp("elastic")
    elastic = root / "elastic"
    state_elastic = _run_elastic(elastic)
    twin = root / "twin"
    _run_elastic(twin)
    straight = root / "straight"
    _, state_straight = _fit(2, TOTAL, straight, resume=False)
    return {
        "root": root,
        "elastic": elastic,
        "twin": twin,
        "straight": straight,
        "state_elastic": state_elastic,
        "state_straight": state_straight,
    }


def test_every_committed_checkpoint_carries_a_manifest(runs):
    ckpts = sorted((runs["elastic"] / "checkpoints").glob("step_*"))
    assert [p.name for p in ckpts] == ["step_3", "step_6", "step_9"]
    for p in ckpts:
        manifest = json.loads((p / "manifest.json").read_text())
        assert manifest["format"] == 1
        assert manifest["rule_fingerprint"] == LLAMA_RULES.fingerprint()
        assert manifest["global_batch_size"] == BATCH
        assert manifest["batch_shards"] == 2  # invariant across topologies
        assert manifest["leaves"]  # per-leaf shape/dtype map present
    # step_6 was written on the dp=1 mesh, step_9 on dp=2 after the grow
    m6 = json.loads((ckpts[1] / "manifest.json").read_text())
    m9 = json.loads((ckpts[2] / "manifest.json").read_text())
    assert (m6["mesh_axes"]["dp"], m6["grad_accum_steps"]) == (1, 2)
    assert (m9["mesh_axes"]["dp"], m9["grad_accum_steps"]) == (2, 1)


def test_cross_topology_restore_is_bitwise_on_state(runs):
    """The same committed step restores bit-identically through a dp=1 and
    a dp=2 trainer's template — the state is mesh-free."""
    ck = CheckpointManager(str(runs["elastic"] / "checkpoints"))
    t1 = _trainer(1, TOTAL)
    t2 = _trainer(2, TOTAL)
    host1 = ck.restore(9, like=t1.state_to_host(t1.init_state()))
    host2 = ck.restore(9, like=t2.state_to_host(t2.init_state()))
    leaves1, leaves2 = jax.tree.leaves(host1), jax.tree.leaves(host2)
    assert len(leaves1) == len(leaves2)
    for a, b in zip(leaves1, leaves2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_elastic_run_is_deterministically_replayable(runs):
    """Two elastic dp=2->1->2 runs are bit-identical to each other, row for
    row — the resharding path adds no nondeterminism (cache on or off:
    conftest enables the persistent XLA cache, so the twin leg typically
    replays through cached executables)."""
    rows_a, rows_b = _rows(runs["elastic"]), _rows(runs["twin"])
    assert [r["step"] for r in rows_a] == [str(s) for s in range(1, TOTAL + 1)]
    for ra, rb in zip(rows_a, rows_b):
        for col in ("loss", "accuracy", "grad_norm"):
            assert float(ra[col]) == float(rb[col]), (ra["step"], col)


def test_elastic_trajectory_tracks_uninterrupted_run(runs):
    """The dp=2->1->2 run continues the uninterrupted dp=2 trajectory:
    step-continuous rows, same step count, loss within reduction-order
    tolerance at every logged step (see module docstring for why tolerance,
    not bit-identity, is the cross-topology contract)."""
    rows_e, rows_s = _rows(runs["elastic"]), _rows(runs["straight"])
    assert [r["step"] for r in rows_e] == [r["step"] for r in rows_s]
    for re_, rs in zip(rows_e, rows_s):
        dl = abs(float(re_["loss"]) - float(rs["loss"]))
        assert dl <= 5e-2, (re_["step"], re_["loss"], rs["loss"])
    # the dp=2 segments BEFORE the first topology change are bit-identical
    for re_, rs in zip(rows_e[:3], rows_s[:3]):
        assert float(re_["loss"]) == float(rs["loss"]), re_["step"]


def test_elastic_restore_is_logged(runs, caplog, tmp_path):
    art = tmp_path / "logcheck"
    _fit(2, 3, art, resume=False)
    with caplog.at_level(logging.INFO):
        _fit(1, 6, art)
    assert any("elastic restore" in r.message for r in caplog.records)


def test_fingerprint_mismatch_is_refused(runs, tmp_path):
    """Restore through a DIFFERENT partition-rule table must refuse the
    checkpoint with a clear error, not silently mis-shard (satellite)."""
    art = tmp_path / "fp"
    _fit(1, 3, art, resume=False)
    other_rules = PartitionRules([(r".*", P())])
    mesh = MeshSpec(dp=1, fsdp=1).build(jax.devices()[:1])
    trainer = Trainer(MODEL, _config(6), mesh=mesh, rules=other_rules)
    batches = synthetic_batches(BATCH, 16, MODEL.vocab_size, seed=0)
    with pytest.raises(ElasticManifestError, match="fingerprint"):
        trainer.fit(batches, str(art), resume=True)


def test_shape_mismatch_names_first_offending_path(runs):
    """A mismatched ``like`` tree (wrong lora rank) surfaces as a
    CheckpointShapeError naming the path and both shapes — not a raw
    msgpack/XLA error (satellite)."""
    ck = CheckpointManager(str(runs["elastic"] / "checkpoints"))
    other = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    mesh = MeshSpec(dp=1, fsdp=1).build(jax.devices()[:1])
    trainer = Trainer(other, _config(TOTAL), mesh=mesh)
    template = trainer.state_to_host(trainer.init_state())
    with pytest.raises(CheckpointShapeError) as exc:
        ck.restore(9, like=template)
    assert "lora" in str(exc.value)
    assert "shape" in str(exc.value)


def test_legacy_manifestless_checkpoint_still_restores(runs, tmp_path):
    """Pre-manifest checkpoints (or a crash between tree-commit and
    manifest write) restore as before — same-shape only, no refusal."""
    art = tmp_path / "legacy"
    _fit(1, 3, art, resume=False)
    for m in (art / "checkpoints").glob("step_*/manifest.json"):
        m.unlink()
    t, state = _fit(1, 6, art)
    assert int(state.step) == 6
    assert t.cfg.grad_accum_steps == 1


# ---------------------------------------------------------------------------
# plan_elastic_resume unit coverage (no trainer)
# ---------------------------------------------------------------------------


def _manifest(dp, fsdp=1, ga=1, batch=8):
    return build_manifest(
        step=1,
        mesh_axes={"dp": dp, "fsdp": fsdp, "ep": 1, "pp": 1, "sp": 1, "tp": 1},
        rule_fingerprint="sha256:x",
        global_batch_size=batch,
        grad_accum_steps=ga,
        seq_len=16,
        seed=0,
        host_tree={"step": np.zeros(())},
    )


def test_plan_preserves_row_shards_across_topologies():
    m = _manifest(dp=4, ga=1, batch=8)  # 4 shards of 2 rows
    down = plan_elastic_resume(m, {"dp": 1}, batch_size=8, grad_accum_steps=1)
    assert down.grad_accum_steps == 4 and down.microstructure_preserved
    half = plan_elastic_resume(m, {"dp": 2}, batch_size=8, grad_accum_steps=1)
    assert half.grad_accum_steps == 2 and half.microstructure_preserved
    same = plan_elastic_resume(m, {"dp": 4}, batch_size=8, grad_accum_steps=1)
    assert same.grad_accum_steps == 1 and not same.topology_changed


def test_plan_redecomposes_when_shards_do_not_divide():
    m = _manifest(dp=3, ga=1, batch=6)  # 3 shards
    plan = plan_elastic_resume(m, {"dp": 2}, batch_size=6, grad_accum_steps=1)
    assert not plan.microstructure_preserved
    assert plan.grad_accum_steps >= 1
    assert 6 % (2 * plan.grad_accum_steps) == 0


def test_plan_rejects_indivisible_batch():
    m = _manifest(dp=2, ga=1, batch=2)
    with pytest.raises(ElasticManifestError, match="decomposed"):
        plan_elastic_resume(m, {"dp": 4}, batch_size=2, grad_accum_steps=1)
