"""Unit tests for bench.py's probe-cache and accounting helpers.

The bench is the driver's only window into performance; its fallback logic
(one bounded probe, failure-only caching) was rebuilt in round 3 after the
round-2 probe burned 12+ minutes of driver time — pin the behavior.
"""

from __future__ import annotations

import importlib.util
import json
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _load_bench(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location("bench_mod", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "PROBE_CACHE", str(tmp_path / "probe.json"))
    return mod


def test_probe_failure_cache_roundtrip(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    assert bench._cached_probe_failure() is False  # no file yet
    bench._store_probe_failure()
    assert bench._cached_probe_failure() is True


def test_probe_failure_cache_expires(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    bench._store_probe_failure()
    rec = json.loads((tmp_path / "probe.json").read_text())
    rec["ts"] -= bench.PROBE_CACHE_TTL_S + 1
    (tmp_path / "probe.json").write_text(json.dumps(rec))
    assert bench._cached_probe_failure() is False  # stale verdict ignored


def test_success_is_never_cached(monkeypatch, tmp_path):
    """Only FAILURE verdicts cache: a cached success would skip the bounded
    probe and let in-process init hang on a tunnel that died since."""
    bench = _load_bench(monkeypatch, tmp_path)
    (tmp_path / "probe.json").write_text(
        json.dumps({"ok": True, "ts": 10**12})
    )
    assert bench._cached_probe_failure() is False


def test_corrupt_cache_treated_as_no_verdict(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    (tmp_path / "probe.json").write_text("{not json")
    assert bench._cached_probe_failure() is False


def test_peak_tflops_mapping(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    assert bench._peak_tflops("TPU v5e") == 197.0
    assert bench._peak_tflops("TPU v5p") == 459.0
    assert bench._peak_tflops("TPU v5 lite") == 197.0
    assert bench._peak_tflops("unknown accelerator") is None


def test_jsonable_scrubs_nonfinite(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    out = bench._jsonable([1.0, float("nan"), float("inf")])
    assert out[0] == 1.0 and out[1] == "nan" and out[2] == "inf"
    json.dumps(out)  # RFC-JSON safe


def test_latest_session_tpu_record_prefers_kind(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    log = tmp_path / "session.jsonl"
    lines = [
        {"ts": 1, "step": "a", "metric": "lora_sft_tokens_per_sec_per_chip[x]",
         "value": 100.0, "device_kind": "TPU v5 lite", "fallback": False},
        {"ts": 2, "step": "b", "metric": "qlora_sft_tokens_per_sec_per_chip[y]",
         "value": 50.0, "device_kind": "TPU v5 lite", "fallback": False},
        # must be skipped: error record, CPU record, fallback record
        {"ts": 3, "step": "c", "error": "oom", "metric": "lora_x"},
        {"ts": 4, "step": "d", "metric": "lora_z", "value": 9,
         "device_kind": "cpu", "fallback": False},
        {"ts": 5, "step": "e", "metric": "lora_z", "value": 9,
         "device_kind": "TPU v5 lite", "fallback": True},
    ]
    log.write_text("".join(json.dumps(r) + "\n" for r in lines))
    monkeypatch.setattr(bench, "SESSION_LOG", str(log))
    rec = bench._latest_session_tpu_record("qlora_")
    assert rec["step"] == "b" and rec["value"] == 50.0
    # no same-kind record -> None (a different kind's headline cached under
    # this bench's name would misattribute the number)
    assert bench._latest_session_tpu_record("mm_lora_") is None
    monkeypatch.setattr(bench, "SESSION_LOG", str(tmp_path / "absent.jsonl"))
    assert bench._latest_session_tpu_record("lora_") is None


def test_session_log_append_captures_env(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    log = tmp_path / "session.jsonl"
    monkeypatch.setattr(bench, "SESSION_LOG", str(log))
    monkeypatch.setenv("BENCH_MODE", "qlora")
    monkeypatch.delenv("BENCH_SESSION_LOG", raising=False)
    bench._session_log_append({"metric": "m", "value": 1.0})
    rec = json.loads(log.read_text())
    assert rec["step"] == "adhoc_bench"
    assert rec["env"]["BENCH_MODE"] == "qlora"
    assert rec["metric"] == "m" and "ts" in rec
    # disabled via BENCH_SESSION_LOG=0 (what tpu_session.py sets)
    monkeypatch.setenv("BENCH_SESSION_LOG", "0")
    bench._session_log_append({"metric": "m2", "value": 2.0})
    assert len(log.read_text().splitlines()) == 1


def test_latest_session_prefers_newest_default_config(monkeypatch, tmp_path):
    """A newer default-config adhoc record must beat an older headline step;
    a non-default supplementary row (seq override) must not."""
    bench = _load_bench(monkeypatch, tmp_path)
    log = tmp_path / "session.jsonl"

    def rec(ts, step, env=None, value=1.0):
        return {"ts": ts, "step": step, "metric": "lora_sft[x]",
                "value": value, "device_kind": "TPU v5 lite",
                "fallback": False, "env": env or {}}

    log.write_text("".join(json.dumps(r) + "\n" for r in [
        rec(1, "headline_tinyllama_seq2048_tuned", value=13068.0),
        rec(2, "adhoc_bench", env={"FTC_FLASH_BLOCK_Q": "1024"}, value=14000.0),
        rec(3, "adhoc_bench", env={"BENCH_SEQ": "8192"}, value=8000.0),
    ]))
    monkeypatch.setattr(bench, "SESSION_LOG", str(log))
    picked = bench._latest_session_tpu_record("lora_")
    assert picked["ts"] == 2 and picked["value"] == 14000.0
