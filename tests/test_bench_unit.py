"""Unit tests for bench.py's probe-cache and accounting helpers.

The bench is the driver's only window into performance; its fallback logic
(one bounded probe, failure-only caching) was rebuilt in round 3 after the
round-2 probe burned 12+ minutes of driver time — pin the behavior.
"""

from __future__ import annotations

import importlib.util
import json
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _load_bench(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location("bench_mod", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "PROBE_CACHE", str(tmp_path / "probe.json"))
    return mod


def test_probe_failure_cache_roundtrip(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    assert bench._cached_probe_failure() is False  # no file yet
    bench._store_probe_failure()
    assert bench._cached_probe_failure() is True


def test_probe_failure_cache_expires(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    bench._store_probe_failure()
    rec = json.loads((tmp_path / "probe.json").read_text())
    rec["ts"] -= bench.PROBE_CACHE_TTL_S + 1
    (tmp_path / "probe.json").write_text(json.dumps(rec))
    assert bench._cached_probe_failure() is False  # stale verdict ignored


def test_success_is_never_cached(monkeypatch, tmp_path):
    """Only FAILURE verdicts cache: a cached success would skip the bounded
    probe and let in-process init hang on a tunnel that died since."""
    bench = _load_bench(monkeypatch, tmp_path)
    (tmp_path / "probe.json").write_text(
        json.dumps({"ok": True, "ts": 10**12})
    )
    assert bench._cached_probe_failure() is False


def test_corrupt_cache_treated_as_no_verdict(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    (tmp_path / "probe.json").write_text("{not json")
    assert bench._cached_probe_failure() is False


def test_peak_tflops_mapping(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    assert bench._peak_tflops("TPU v5e") == 197.0
    assert bench._peak_tflops("TPU v5p") == 459.0
    assert bench._peak_tflops("TPU v5 lite") == 197.0
    assert bench._peak_tflops("unknown accelerator") is None


def test_jsonable_scrubs_nonfinite(monkeypatch, tmp_path):
    bench = _load_bench(monkeypatch, tmp_path)
    out = bench._jsonable([1.0, float("nan"), float("inf")])
    assert out[0] == 1.0 and out[1] == "nan" and out[2] == "inf"
    json.dumps(out)  # RFC-JSON safe
