"""ftc-lint v2: project index, call graph, interprocedural rules.

Four layers, mirroring ``tests/test_lint_rules.py``'s fixture discipline:

* call-graph unit tests (import cycles, method resolution through
  ``self.<attr>`` type inference, thread-entry classification, nested-def
  boundaries);
* per-rule TP / clean / suppression fixtures for the three new rule
  families (transitive flow, lock discipline, protocol conformance);
* MUTATION tests against the real package: delete a worker RPC handler or
  rename a client op via ``source_overrides`` and the lint turns red —
  while HEAD stays green (``tests/test_lint_clean.py``);
* engine plumbing: SARIF output, the ``--rules``/``--exclude-rules``
  selector aliases, and the CI wall-clock budget for the whole v2 pass.
"""

import json
import textwrap
import time
from pathlib import Path

import pytest

from finetune_controller_tpu.analysis.engine import (
    all_project_rules,
    all_rules,
    lint_paths,
    main,
)
from finetune_controller_tpu.analysis.project import build_project

PKG = Path(__file__).resolve().parent.parent / "finetune_controller_tpu"


def _write(tmp_path: Path, files: dict[str, str]) -> Path:
    for name, src in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def _project_lint(tmp_path, files, rules=None):
    """Lint a fixture tree with ONLY project rules (optionally a subset)."""
    root = _write(tmp_path, files)
    prules = all_project_rules()
    if rules is not None:
        prules = {k: prules[k] for k in rules}
    return lint_paths([str(root)], rules={}, project_rules=prules)


# ---------------------------------------------------------------------------
# call graph
# ---------------------------------------------------------------------------


def test_import_cycle_builds_and_resolves(tmp_path):
    root = _write(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/a.py": """
            from .b import helper_b

            def helper_a():
                return helper_b()
        """,
        "pkg/b.py": """
            def helper_b():
                from .a import helper_a
                return helper_a
        """,
    })
    project = build_project([str(root)])
    a = project.function("pkg.a.helper_a")
    assert a is not None
    assert [c.callee for c in a.calls] == ["pkg.b.helper_b"]


def test_method_resolution_via_attr_type_hint(tmp_path):
    root = _write(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/eng.py": """
            class Engine:
                def crunch(self):
                    return 1
        """,
        "pkg/drv.py": """
            from .eng import Engine

            class Driver:
                def __init__(self, engine: Engine):
                    self.engine = engine

                def drive(self):
                    return self.engine.crunch()

                def chain(self):
                    return self.drive()
        """,
    })
    project = build_project([str(root)])
    drive = project.function("pkg.drv.Driver.drive")
    assert [c.callee for c in drive.calls] == ["pkg.eng.Engine.crunch"]
    chain = project.function("pkg.drv.Driver.chain")
    assert [c.callee for c in chain.calls] == ["pkg.drv.Driver.drive"]


def test_thread_entry_classification(tmp_path):
    root = _write(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/t.py": """
            import asyncio
            import threading

            class Worker:
                def body(self):
                    self.helper()

                def helper(self):
                    pass

                async def kick(self):
                    await asyncio.to_thread(self.body)

            def plain():
                pass

            def spawn():
                threading.Thread(target=plain).start()

            async def via_executor(loop, fn):
                await loop.run_in_executor(None, plain)
        """,
    })
    project = build_project([str(root)])
    assert "pkg.t.Worker.body" in project.thread_roots
    assert "pkg.t.plain" in project.thread_roots
    # reachability crosses sync self-calls from the entry
    assert "pkg.t.Worker.helper" in project.thread_reachable()
    # the deferred edge is NOT a sync edge of the async caller
    kick = project.function("pkg.t.Worker.kick")
    assert all(c.context == "deferred" for c in kick.calls)


def test_nested_def_is_a_boundary(tmp_path):
    root = _write(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/n.py": """
            def leaf():
                pass

            def outer():
                def inner():
                    leaf()
                return inner
        """,
    })
    project = build_project([str(root)])
    outer = project.function("pkg.n.outer")
    assert [c.callee for c in outer.calls] == []


def test_relative_import_resolution(tmp_path):
    root = _write(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/sub/__init__.py": "",
        "pkg/util.py": "def shared():\n    pass\n",
        "pkg/sub/mod.py": """
            from ..util import shared

            def caller():
                shared()
        """,
    })
    project = build_project([str(root)])
    caller = project.function("pkg.sub.mod.caller")
    assert [c.callee for c in caller.calls] == ["pkg.util.shared"]


# ---------------------------------------------------------------------------
# blocking-io-in-async-transitive
# ---------------------------------------------------------------------------

#: the acceptance fixture: open() is TWO sync hops from the async def
_TWO_HOP = {
    "pkg/__init__.py": "",
    "pkg/svc.py": """
        async def handler(path):
            return stage(path)

        def stage(path):
            return _read(path)

        def _read(path):
            with open(path) as f:
                return f.read()
    """,
}


def test_transitive_blocking_two_hops_flagged_with_chain(tmp_path):
    result = _project_lint(tmp_path, _TWO_HOP,
                           rules=["blocking-io-in-async-transitive"])
    assert len(result.active) == 1
    f = result.active[0]
    assert f.rule == "blocking-io-in-async-transitive"
    assert "`handler`" in f.message
    assert "`stage` -> `_read`" in f.message      # the rendered call chain
    assert "svc.py:" in f.message                 # ...and the leaf location


def test_per_file_rule_demonstrably_misses_the_two_hop_case(tmp_path):
    """PR 2's direct-call rule sees three innocent functions here — the
    interprocedural pass is what closes the helper evasion."""
    root = _write(tmp_path, _TWO_HOP)
    result = lint_paths([str(root)], rules=all_rules(), project_rules={})
    assert [f for f in result.active
            if f.rule == "blocking-io-in-async"] == []


def test_transitive_blocking_quiet_when_deferred_to_thread(tmp_path):
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/svc.py": """
            import asyncio

            async def handler(path):
                return await asyncio.to_thread(stage, path)

            def stage(path):
                with open(path) as f:
                    return f.read()
        """,
    }, rules=["blocking-io-in-async-transitive"])
    assert result.active == []


def test_transitive_blocking_does_not_descend_into_async_callees(tmp_path):
    """The async callee is its own root: one hazard, one finding."""
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/svc.py": """
            async def outer(path):
                await inner(path)

            async def inner(path):
                return stage(path)

            def stage(path):
                with open(path) as f:
                    return f.read()
        """,
    }, rules=["blocking-io-in-async-transitive"])
    assert len(result.active) == 1
    assert "`inner`" in result.active[0].message  # flagged at inner, not outer


def test_transitive_blocking_suppression_honored(tmp_path):
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/svc.py": """
            async def handler(path):
                # ftc: ignore[blocking-io-in-async-transitive] -- startup-only path
                return stage(path)

            def stage(path):
                with open(path) as f:
                    return f.read()
        """,
    }, rules=["blocking-io-in-async-transitive"])
    assert result.active == []
    assert len(result.findings) == 1 and result.findings[0].suppressed


# ---------------------------------------------------------------------------
# host-sync-in-jit-transitive
# ---------------------------------------------------------------------------


def test_transitive_host_sync_through_helper(tmp_path):
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/step.py": """
            import jax

            @jax.jit
            def train_step(state, batch):
                return _metrics(state)

            def _metrics(state):
                return state.loss.item()
        """,
    }, rules=["host-sync-in-jit-transitive"])
    assert len(result.active) == 1
    f = result.active[0]
    assert "`train_step`" in f.message and "`_metrics`" in f.message
    assert ".item()" in f.message


def test_transitive_host_sync_quiet_on_host_side_code(tmp_path):
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/step.py": """
            def host_loop(metrics):
                return _log(metrics)

            def _log(metrics):
                print(metrics)
        """,
    }, rules=["host-sync-in-jit-transitive"])
    assert result.active == []


def test_transitive_host_sync_skips_jitted_callees(tmp_path):
    """A jitted callee of a jitted root gets its OWN analysis."""
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/step.py": """
            import jax

            @jax.jit
            def outer_step(state):
                return inner_step(state)

            @jax.jit
            def inner_step(state):
                return _bad(state)

            def _bad(state):
                return jax.device_get(state)
        """,
    }, rules=["host-sync-in-jit-transitive"])
    assert len(result.active) == 1
    assert "`inner_step`" in result.active[0].message


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


def test_lock_discipline_guarded_field_outside_lock(tmp_path):
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/c.py": """
            import threading

            class Stats:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.total = 0

                def add(self, n):
                    with self._lock:
                        self.total += n

                def peek(self):
                    return self.total
        """,
    }, rules=["lock-discipline"])
    assert len(result.active) == 1
    assert "`Stats.total`" in result.active[0].message
    assert "outside" in result.active[0].message


def test_lock_discipline_unguarded_counter_in_locked_class(tmp_path):
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/c.py": """
            import threading

            class Writer:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.failures = 0

                def write(self, item):
                    with self._lock:
                        emit(item)

                def on_error(self):
                    self.failures += 1
        """,
    }, rules=["lock-discipline"])
    assert len(result.active) == 1
    assert "non-atomic mutation" in result.active[0].message


def test_lock_discipline_clean_when_disciplined(tmp_path):
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/c.py": """
            import threading

            class Stats:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.total = 0

                def add(self, n):
                    with self._lock:
                        self.total += n

                def peek(self):
                    with self._lock:
                        return self.total
        """,
    }, rules=["lock-discipline"])
    assert result.active == []


def test_lock_discipline_asyncio_lock_is_not_a_thread_lock(tmp_path):
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/c.py": """
            import asyncio

            class Store:
                def __init__(self):
                    self._lock = asyncio.Lock()
                    self.n = 0

                async def bump(self):
                    async with self._lock:
                        self.n += 1

                def peek(self):
                    return self.n
        """,
    }, rules=["lock-discipline"])
    assert result.active == []


def test_lock_discipline_lockfree_loop_vs_thread_race(tmp_path):
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/c.py": """
            import asyncio

            class Pump:
                def __init__(self):
                    self.moved = 0

                def _work(self):
                    self.moved += 1

                async def drive(self):
                    await asyncio.to_thread(self._work)
                    self.tick()

                def tick(self):
                    self.moved = 0
        """,
    }, rules=["lock-discipline"])
    assert len(result.active) == 1
    f = result.active[0]
    assert "`Pump.moved`" in f.message
    assert "worker thread" in f.message and "Pump.tick" in f.message


def test_lock_discipline_lockfree_quiet_single_side(tmp_path):
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/c.py": """
            import asyncio

            class Pump:
                def __init__(self):
                    self.moved = 0

                def _work(self):
                    self.moved += 1

                async def drive(self):
                    await asyncio.to_thread(self._work)
                    return self.moved  # loop-side READ only: below the bar
        """,
    }, rules=["lock-discipline"])
    assert result.active == []


def test_lock_discipline_suppression_honored(tmp_path):
    result = _project_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/c.py": """
            import threading

            class Stats:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.total = 0

                def add(self, n):
                    with self._lock:
                        self.total += n

                def peek(self):
                    # ftc: ignore[lock-discipline] -- monitoring read; staleness is fine
                    return self.total
        """,
    }, rules=["lock-discipline"])
    assert result.active == []
    assert any(f.suppressed for f in result.findings)


# ---------------------------------------------------------------------------
# rpc-conformance (fixtures)
# ---------------------------------------------------------------------------

_PROTOCOL_FIXTURE = {
    "pkg/__init__.py": "",
    "pkg/worker.py": """
        class Server:
            async def _dispatch(self, op, payload):
                handler = getattr(self, f"_op_{op}", None)
                return await handler(payload)

            async def _op_ping(self, payload):
                return {"n": payload["n"]}

            async def _op_unused(self, payload):
                return {}
    """,
    "pkg/client.py": """
        class Client:
            async def ping(self):
                return await self._conn.call("ping", {"n": 1})
    """,
}


def test_rpc_conformance_clean_pair(tmp_path):
    files = dict(_PROTOCOL_FIXTURE)
    files["pkg/worker.py"] = files["pkg/worker.py"].replace(
        "\n            async def _op_unused(self, payload):\n                return {}\n", "\n"
    )
    result = _project_lint(tmp_path, files, rules=["rpc-conformance"])
    assert result.active == []


def test_rpc_conformance_dead_op_flagged(tmp_path):
    result = _project_lint(tmp_path, _PROTOCOL_FIXTURE,
                           rules=["rpc-conformance"])
    assert len(result.active) == 1
    assert "_op_unused" in result.active[0].message
    assert "dead op" in result.active[0].message


def test_rpc_conformance_client_without_handler(tmp_path):
    files = dict(_PROTOCOL_FIXTURE)
    files["pkg/client.py"] = files["pkg/client.py"].replace(
        '.call("ping"', '.call("pingz"'
    )
    result = _project_lint(tmp_path, files, rules=["rpc-conformance"])
    msgs = [f.message for f in result.active]
    assert any("'pingz'" in m and "no worker handler" in m for m in msgs)


def test_rpc_conformance_payload_key_mismatches(tmp_path):
    files = dict(_PROTOCOL_FIXTURE)
    # client sends {"m": 1}: handler's required "n" missing, "m" unread
    files["pkg/client.py"] = files["pkg/client.py"].replace(
        '{"n": 1}', '{"m": 1}'
    )
    result = _project_lint(tmp_path, files, rules=["rpc-conformance"])
    msgs = " | ".join(f.message for f in result.active)
    assert "requires payload key 'n'" in msgs
    assert "'m' is sent but" in msgs


def test_rpc_conformance_opaque_payload_skips_key_checks(tmp_path):
    files = dict(_PROTOCOL_FIXTURE)
    files["pkg/worker.py"] = files["pkg/worker.py"].replace(
        'return {"n": payload["n"]}', "return decode(payload)"
    )
    files["pkg/client.py"] = files["pkg/client.py"].replace(
        '{"n": 1}', '{"anything": 1}'
    )
    result = _project_lint(tmp_path, files, rules=["rpc-conformance"])
    assert [f for f in result.active if "payload key" in f.message] == []


# ---------------------------------------------------------------------------
# rpc-conformance (mutation tests against the REAL package)
# ---------------------------------------------------------------------------

WORKER = PKG / "transport" / "worker.py"
CLIENT = PKG / "transport" / "client.py"
STATE_SVC = PKG / "controller" / "statestore_service.py"


def _rpc_lint(overrides):
    # both protocols' halves live entirely under these roots (worker +
    # client + process handshake; @_rpc handlers + RemoteStateStore in one
    # module) — the subset keeps each mutation lint fast while preserving
    # every anchor the rule needs.  tests/test_lint_clean.py still runs
    # the rule over the WHOLE package.
    return lint_paths(
        [str(PKG / "transport"), str(STATE_SVC)], rules={},
        project_rules={"rpc-conformance": all_project_rules()["rpc-conformance"]},
        source_overrides=overrides,
    )


def test_mutation_head_is_green():
    assert _rpc_lint(None).active == []


def test_mutation_deleting_worker_handler_turns_lint_red():
    src = WORKER.read_text()
    assert "async def _op_probe(" in src
    mutated = src.replace("async def _op_probe(", "async def _op_probe_gone(")
    result = _rpc_lint({str(WORKER): mutated})
    msgs = [f.message for f in result.active]
    assert any("'probe'" in m and "no worker handler" in m for m in msgs), msgs
    assert result.exit_code == 1


def test_mutation_renaming_client_op_turns_lint_red():
    src = CLIENT.read_text()
    assert '.call("generate"' in src.replace("\n", "").replace(" ", "") or \
        '"generate"' in src
    mutated = src.replace('"generate", payload', '"generatez", payload')
    assert mutated != src
    result = _rpc_lint({str(CLIENT): mutated})
    msgs = [f.message for f in result.active]
    # the renamed op has no handler AND the real handler goes dead
    assert any("'generatez'" in m for m in msgs), msgs
    assert any("_op_generate" in m and "dead op" in m for m in msgs), msgs


def test_mutation_deleting_rollout_handler_turns_lint_red():
    # the disaggregated-rlhf ops are covered exactly like the serve ops:
    # deleting one worker handler must turn rpc-conformance red for both
    # the now-unanswered client op and the dead handler name.
    src = WORKER.read_text()
    assert "async def _op_rollout_pull(" in src
    mutated = src.replace(
        "async def _op_rollout_pull(", "async def _op_rollout_pull_gone(")
    result = _rpc_lint({str(WORKER): mutated})
    msgs = [f.message for f in result.active]
    assert any("'rollout_pull'" in m and "no worker handler" in m
               for m in msgs), msgs
    assert result.exit_code == 1


def test_mutation_rollout_ops_covered_at_head():
    # green baseline: every rollout/reward op has a matching client call
    # site and worker handler, so none of them appear in head findings.
    result = _rpc_lint(None)
    assert result.active == []
    src = WORKER.read_text()
    client_src = CLIENT.read_text()
    for op in ("rollout_start", "rollout_pull", "rollout_ack",
               "rollout_policy_version", "reward_score"):
        assert f"async def _op_{op}(" in src, op
        assert f'"{op}"' in client_src, op


def test_mutation_deleting_state_rpc_handler_turns_lint_red():
    src = STATE_SVC.read_text()
    mutated = src.replace('@_rpc("get_job")', '@_rpc("get_job_gone")')
    assert mutated != src
    result = _rpc_lint({str(STATE_SVC): mutated})
    msgs = [f.message for f in result.active]
    assert any("'get_job'" in m and "no @_rpc handler" in m for m in msgs), msgs


def test_mutation_dropping_required_payload_key_turns_lint_red():
    src = STATE_SVC.read_text()
    # handler starts requiring a key the client never sends
    mutated = src.replace(
        'return _dump(await store.get_job(p["job_id"]))',
        'return _dump(await store.get_job(p["job_identifier"]))',
    )
    assert mutated != src
    result = _rpc_lint({str(STATE_SVC): mutated})
    msgs = [f.message for f in result.active]
    assert any("'job_identifier'" in m and "never sends it" in m
               for m in msgs), msgs


# ---------------------------------------------------------------------------
# metric-doc-drift
# ---------------------------------------------------------------------------

_METRIC_FILES = {
    "pkg/__init__.py": "",
    "pkg/metrics.py": """
        GAUGES = (
            ("ftc_demo_total", "counter", "total"),
        )

        def render():
            return ["# TYPE ftc_demo_up gauge", "ftc_demo_up 1"]
    """,
    "docs/observability.md": """
        # Demo

        ## Metric catalog

        | family | kind |
        |---|---|
        | `ftc_demo_total` | counter |
        | `ftc_demo_up` | gauge |

        ## Next section
    """,
}


def test_metric_drift_clean_when_in_sync(tmp_path):
    _write(tmp_path, _METRIC_FILES)
    result = lint_paths(
        [str(tmp_path / "pkg")], rules={},
        project_rules={"metric-doc-drift": all_project_rules()["metric-doc-drift"]},
    )
    assert result.active == []


def test_metric_drift_flags_both_directions(tmp_path):
    files = dict(_METRIC_FILES)
    files["docs/observability.md"] = files["docs/observability.md"].replace(
        "| `ftc_demo_total` | counter |", "| `ftc_demo_stale` | counter |"
    )
    _write(tmp_path, files)
    result = lint_paths(
        [str(tmp_path / "pkg")], rules={},
        project_rules={"metric-doc-drift": all_project_rules()["metric-doc-drift"]},
    )
    msgs = " | ".join(f.message for f in result.active)
    assert "ftc_demo_total" in msgs and "missing from" in msgs
    assert "ftc_demo_stale" in msgs and "no code emits it" in msgs
    # the stale-name finding anchors in the docs file itself
    assert any(f.path.endswith("observability.md") for f in result.active)


def test_metric_extraction_ignores_non_metric_ftc_strings(tmp_path):
    _write(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/auth.py": """
            def token(request):
                return request.cookies.get("ftc_token")
        """,
        "docs/observability.md": "## Metric catalog\n\n`ftc_real_metric`\n",
        # ftc_real_metric must be "emitted" somewhere to avoid the stale
        # finding being the only signal under test
        "pkg/m.py": 'LINES = ["# TYPE ftc_real_metric gauge"]\n',
    })
    result = lint_paths(
        [str(tmp_path / "pkg")], rules={},
        project_rules={"metric-doc-drift": all_project_rules()["metric-doc-drift"]},
    )
    assert result.active == []  # the cookie name is not an emitted metric


def test_real_catalog_is_nontrivial_and_in_sync():
    from finetune_controller_tpu.analysis.rules_protocol import (
        _catalog_metrics,
        _emitted_metrics,
    )

    project = build_project([str(PKG)])
    emitted = _emitted_metrics(project)
    catalogued = _catalog_metrics(PKG.parent / "docs" / "observability.md")
    assert len(emitted) >= 50  # the extraction found the real families
    assert emitted.keys() == catalogued.keys()


# ---------------------------------------------------------------------------
# engine plumbing: SARIF, selector aliases, wall-clock budget
# ---------------------------------------------------------------------------


def _bad_file(tmp_path) -> Path:
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    return bad


def test_sarif_output_shape(tmp_path, capsys):
    bad = _bad_file(tmp_path)
    rc = main([str(bad), "--format", "sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "ftc-lint"
    result = run["results"][0]
    assert result["ruleId"] == "silent-except"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] == 4
    rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert "silent-except" in rule_ids


def test_sarif_marks_suppressed_findings(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # ftc: ignore[silent-except] -- fixture\n"
        "        pass\n"
    )
    rc = main([str(bad), "--format", "sarif", "--show-suppressed"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    result = doc["runs"][0]["results"][0]
    assert result["suppressions"] == [{"kind": "inSource"}]


def test_rules_and_exclude_rules_aliases(tmp_path, capsys):
    bad = _bad_file(tmp_path)
    assert main([str(bad), "--rules", "host-sync-in-jit"]) == 0
    capsys.readouterr()
    assert main([str(bad), "--exclude-rules", "silent-except"]) == 0
    capsys.readouterr()
    assert main([str(bad), "--rules", "silent-except"]) == 1
    capsys.readouterr()
    with pytest.raises(SystemExit):
        main([str(bad), "--rules", "no-such-rule"])


def test_text_and_json_formats_unchanged_by_v2(tmp_path, capsys):
    """Byte-compatibility pin: the v1 text/JSON shapes survive the v2
    engine (same render, same JSON keys)."""
    bad = _bad_file(tmp_path)
    rc = main([str(bad), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert set(out.keys()) == {"findings", "errors", "counts"}
    f = out["findings"][0]
    assert set(f.keys()) == {"rule", "path", "line", "col", "message",
                             "suppressed"}
    rc = main([str(bad)])
    text = capsys.readouterr().out.strip()
    assert text.endswith("swallows the failure silently — log it "
                         "(logger.exception), re-raise, or narrow the "
                         "exception type")
    assert text.startswith(f"{bad}:4:4: silent-except:")


def test_list_rules_includes_project_rules(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("blocking-io-in-async-transitive", "host-sync-in-jit-transitive",
                "lock-discipline", "rpc-conformance", "metric-doc-drift"):
        assert rid in out


def test_full_v2_pass_fits_the_ci_wall_clock_budget():
    """scripts/ci_check.sh gives the lint stage 10 s for the whole package;
    the interprocedural pass must not rot into a slow gate."""
    t0 = time.perf_counter()
    result = lint_paths([str(PKG)])
    elapsed = time.perf_counter() - t0
    assert result.errors == []
    assert elapsed < 10.0, f"ftc-lint v2 took {elapsed:.1f}s on the package"
