"""KV-cached generation vs the uncached numerics oracle.

``cached_generate`` (fill-then-decode, static cache — ``models/generate.py``)
must produce the same tokens as the O(n²) uncached ``generate`` path, and its
per-step logits must match the oracle's within bf16 rounding, across every
text family shape: Llama (GQA), Gemma (tied head, embed scale, GeGLU,
head-dim override), Qwen-2 (qkv bias), Mixtral-style MoE.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finetune_controller_tpu.models.generate import (
    _logits_fn,
    cached_generate,
    generate,
    greedy_generate,
)
from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.models.lora import LoRAConfig


def _cached_stepwise_logits(model, variables, forced_tokens, prompt_len):
    """Fill the prompt, then decode forced continuation tokens; return the
    logits the cached path produced at each position (mirrors
    cached_generate's internals with the sampling replaced by forcing)."""
    cache_len = forced_tokens.shape[1]
    dcfg = model.cfg.replace(
        remat=False, attention_impl="xla", max_seq_len=cache_len)
    dmodel = LlamaForCausalLM(cfg=dcfg)
    mutable = ("cache", "moe_aux") if dcfg.n_experts else ("cache",)

    logits, updated = dmodel.apply(
        variables, forced_tokens[:, :prompt_len], deterministic=True,
        decode=True, mutable=mutable,
    )
    out = [logits[:, -1].astype(jnp.float32)]
    cache = updated["cache"]
    for pos in range(prompt_len, forced_tokens.shape[1] - 1):
        logits, updated = dmodel.apply(
            {**variables, "cache": cache},
            forced_tokens[:, pos:pos + 1],
            jnp.full((forced_tokens.shape[0], 1), pos, jnp.int32),
            deterministic=True, decode=True, mutable=mutable,
        )
        cache = updated["cache"]
        out.append(logits[:, -1].astype(jnp.float32))
    return out


@pytest.mark.parametrize(
    "preset", ["tiny-test", "tiny-gemma-test", "tiny-qwen-test", "tiny-moe-test"]
)
def test_cached_logits_match_oracle(preset):
    cfg = PRESETS[preset].replace(lora=LoRAConfig(rank=4))
    if cfg.n_experts:
        # capacity-based token dropping legitimately depends on the total
        # token count, which differs between a one-token decode and a
        # full-sequence recompute; a dropless capacity isolates the cache
        # math (what this test is about) from that routing semantic
        cfg = cfg.replace(capacity_factor=float(cfg.n_experts))
    model = LlamaForCausalLM(cfg)
    prompt = jnp.asarray([[5, 9, 2, 7], [1, 3, 3, 8]], jnp.int32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, prompt)

    # oracle rollout fixes the token sequence both paths score
    forced = generate(model, variables, prompt, max_new_tokens=5)
    cached = _cached_stepwise_logits(model, variables, forced, prompt.shape[1])

    for i in range(5):
        oracle = _logits_fn(model, variables, forced[:, : prompt.shape[1] + i])
        np.testing.assert_allclose(
            np.asarray(cached[i]), np.asarray(oracle), atol=3e-2, rtol=3e-2,
        )


def test_cached_generate_matches_oracle_tokens_after_training():
    """On a trained model (sharp logits — no argmax tie flakiness) the cached
    path must emit token-for-token what the oracle emits."""
    from finetune_controller_tpu.data.synthetic import synthetic_batches
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=8))
    tc = TrainConfig(
        mode="lora", learning_rate=0.03, batch_size=16, seq_len=32,
        total_steps=120, warmup_steps=5, log_every=10**9,
        checkpoint_every=10**9,
    )
    tr = Trainer(cfg, tc)
    state = tr.init_state()
    batches = synthetic_batches(16, 32, cfg.vocab_size, seed=0, task="increment")
    for _ in range(120):
        state, metrics = tr.step(state, next(batches))
    assert float(metrics["accuracy"]) > 0.9

    variables = tr._assemble(state.frozen, state.trainable)
    prompt = jnp.asarray([[10, 11, 12, 13, 14, 15, 16, 17]], jnp.int32)
    oracle = greedy_generate(tr.model, variables, prompt, max_new_tokens=8)
    cached = cached_generate(tr.model, variables, prompt, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(oracle), np.asarray(cached))
    # and both actually continue the increment task
    np.testing.assert_array_equal(np.asarray(cached[0, 8:]), np.arange(18, 26))


def test_cached_generate_eos_and_sampling_shapes():
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    model = LlamaForCausalLM(cfg)
    prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, prompt)
    out = cached_generate(
        model, variables, prompt, max_new_tokens=4,
        temperature=0.8, top_k=5, eos_id=19, rng=jax.random.PRNGKey(1),
    )
    assert out.shape == (1, 8)
    # eos latches: after the first 19, everything is 19
    row = np.asarray(out[0, 4:])
    seen = False
    for t in row:
        if seen:
            assert t == 19
        seen = seen or t == 19


def test_decode_fns_cache_is_lru_not_clear_all():
    """N+1 alternating decode configs must thrash ONE cache slot, not clear
    the whole cache (the old behavior re-traced all N+1 forever)."""
    from finetune_controller_tpu.models import generate as G

    G._DECODE_FNS_CACHE.clear()
    n = G._DECODE_FNS_MAX
    cfgs = [
        PRESETS["tiny-test"].replace(max_seq_len=128 + i) for i in range(n + 1)
    ]
    fns = [G._decode_fns(LlamaForCausalLM, c) for c in cfgs]

    # the (n+1)-th insert evicted only the least-recently-used entry (cfg 0)
    assert len(G._DECODE_FNS_CACHE) == n
    assert (LlamaForCausalLM, cfgs[0]) not in G._DECODE_FNS_CACHE
    for c, (fill, step) in zip(cfgs[1:], fns[1:]):
        hit_fill, hit_step = G._decode_fns(LlamaForCausalLM, c)
        assert hit_fill is fill and hit_step is step

    # re-admitting cfg 0 evicts exactly the new LRU (cfg 1), nothing else
    G._decode_fns(LlamaForCausalLM, cfgs[0])
    assert (LlamaForCausalLM, cfgs[1]) not in G._DECODE_FNS_CACHE
    for c in cfgs[2:]:
        assert (LlamaForCausalLM, c) in G._DECODE_FNS_CACHE
    G._DECODE_FNS_CACHE.clear()


def test_multimodal_cached_generate_matches_oracle():
    """Round-5: the KV-cached decode covers LLaVA — fill caches the
    [image; text] prefix, decode steps run at absolute positions; greedy
    tokens must match the per-step full-recompute oracle."""
    from finetune_controller_tpu.models.multimodal import (
        MM_PRESETS,
        LlavaForCausalLM,
    )

    cfg = MM_PRESETS["tiny-mm-clip-test"].replace(
        dtype=jnp.float32, lora=LoRAConfig(rank=0)
    )
    model = LlavaForCausalLM(cfg)
    rng = jax.random.PRNGKey(11)
    size = cfg.vision.image_size
    pixels = jax.random.uniform(rng, (1, size, size, 3), jnp.float32)
    variables = model.init(
        {"params": rng}, jnp.zeros((1, 6), jnp.int32), pixels
    )
    prompt = jnp.asarray([[7, 12, 99, 4, 5, 6]], jnp.int32)

    oracle = generate(
        model, variables, prompt, max_new_tokens=8, pixels=pixels
    )
    cached = cached_generate(
        model, variables, prompt, max_new_tokens=8, pixels=pixels
    )
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(oracle))

    with pytest.raises(ValueError, match="pixels"):
        cached_generate(model, variables, prompt, max_new_tokens=2)
