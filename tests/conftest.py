"""Test harness: an 8-virtual-device CPU mesh so every parallelism strategy
(DP/FSDP/TP/SP) is exercised without TPU hardware — the CPU-simulation test
seam the reference lacked entirely (SURVEY.md §4).

Note: the JAX_PLATFORMS *env var* is not enough in environments where a TPU
plugin calls ``jax.config.update("jax_platforms", ...)`` at interpreter
startup (an explicit config update outranks the env var), so we re-update the
config here, before any backend is initialised.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = f"{flags} --xla_force_host_platform_device_count=8".strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache for the test suite: every run re-compiles
# the same tiny-model programs (train steps per remat policy, decode fills,
# pipeline stages ...), which dominates tier-1 wall-clock on a small CPU box.
# Caching the compiled executables across runs (keyed by HLO hash — safe) cuts
# repeat-run time substantially.  Opt out with FTC_TEST_XLA_CACHE=0 when
# debugging compiler flags or suspecting a stale-cache artifact.
if os.environ.get("FTC_TEST_XLA_CACHE", "1") != "0":
    _xla_cache = os.path.abspath(
        os.path.join(os.path.dirname(__file__), os.pardir, ".cache", "xla")
    )
    jax.config.update("jax_compilation_cache_dir", _xla_cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import asyncio  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs[:8]


def run_async(coro):
    """Run a coroutine on a fresh, properly closed event loop."""
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def one_chip_catalog(quota: int = 2):
    """Single 1-chip CPU flavor catalog for backend/scheduler tests."""
    from finetune_controller_tpu.controller.devices import (
        DeviceCatalog,
        DeviceFlavor,
        FlavorQuota,
    )

    return DeviceCatalog(
        flavors=[DeviceFlavor(name="chip-1", generation="cpu", hosts=1,
                              chips_per_host=1, runtime="cpu", queue="q")],
        quotas=[FlavorQuota(flavor="chip-1", nominal_chips=quota)],
        default_flavor="chip-1",
    )


def tiny_job_spec(steps: int = 3):
    """Milliseconds-scale TinyTestLoRA spec for lifecycle tests."""
    from finetune_controller_tpu.controller.examples import (
        LoRASFTArguments,
        TinyTestLoRA,
    )

    return TinyTestLoRA(
        training_arguments=LoRASFTArguments(
            total_steps=steps, warmup_steps=1, batch_size=2, seq_len=16, lora_rank=2
        )
    )
