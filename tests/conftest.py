"""Test harness: an 8-virtual-device CPU mesh so every parallelism strategy
(DP/FSDP/TP/SP) is exercised without TPU hardware — the CPU-simulation test
seam the reference lacked entirely (SURVEY.md §4).

Note: the JAX_PLATFORMS *env var* is not enough in environments where a TPU
plugin calls ``jax.config.update("jax_platforms", ...)`` at interpreter
startup (an explicit config update outranks the env var), so we re-update the
config here, before any backend is initialised.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = f"{flags} --xla_force_host_platform_device_count=8".strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs[:8]
