import jax
import numpy as np

from finetune_controller_tpu.data import synthetic_batches
from finetune_controller_tpu.models import PRESETS, LoRAConfig
from finetune_controller_tpu.parallel import MeshSpec
from finetune_controller_tpu.train import Trainer, TrainConfig


def _tiny_cfg(rank=4):
    return PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=rank))


def test_lora_training_reduces_loss(devices8, tmp_path):
    model_cfg = _tiny_cfg()
    train_cfg = TrainConfig(
        mode="lora", learning_rate=2e-2, warmup_steps=2, total_steps=40,
        batch_size=8, seq_len=32, log_every=5, checkpoint_every=1000,
    )
    mesh = MeshSpec(dp=2, fsdp=2, tp=2).build(devices8)
    trainer = Trainer(model_cfg, train_cfg, mesh=mesh)
    batches = synthetic_batches(8, 32, model_cfg.vocab_size, task="increment")
    losses = []
    trainer.fit(
        batches, str(tmp_path), on_metrics=lambda s, m: losses.append(m["loss"])
    )
    assert losses[-1] < losses[0] * 0.7, f"loss did not drop: {losses}"
    assert (tmp_path / "metrics.csv").exists()


def test_full_finetune_mode(devices8, tmp_path):
    model_cfg = PRESETS["tiny-test"]  # no LoRA
    train_cfg = TrainConfig(
        mode="full", learning_rate=1e-3, warmup_steps=2, total_steps=10,
        batch_size=8, seq_len=16, log_every=5, checkpoint_every=1000,
    )
    mesh = MeshSpec(dp=1, fsdp=4, tp=2).build(devices8)
    trainer = Trainer(model_cfg, train_cfg, mesh=mesh)
    batches = synthetic_batches(8, 16, model_cfg.vocab_size, task="increment")
    losses = []
    trainer.fit(batches, str(tmp_path), on_metrics=lambda s, m: losses.append(m["loss"]))
    assert losses[-1] < losses[0]


def test_params_are_actually_sharded(devices8):
    model_cfg = _tiny_cfg()
    train_cfg = TrainConfig(total_steps=1, batch_size=8, seq_len=16)
    mesh = MeshSpec(dp=1, fsdp=2, tp=4).build(devices8)
    trainer = Trainer(model_cfg, train_cfg, mesh=mesh)
    state = trainer.init_state()
    # a scanned attention kernel should be sharded over fsdp×tp
    kern = state.frozen["params"]["blocks"]["block"]["attn"]["q_proj"]["kernel"]
    assert len(kern.sharding.device_set) == 8
    shard_shape = kern.sharding.shard_shape(kern.shape)
    assert shard_shape[1] == kern.shape[1] // 2  # fsdp split on in-features
    assert shard_shape[2] == kern.shape[2] // 4  # tp split on out-features


def test_checkpoint_resume_continues(devices8, tmp_path):
    model_cfg = _tiny_cfg()
    mesh = MeshSpec(dp=2, fsdp=2, tp=2).build(devices8)
    batches = lambda: synthetic_batches(4, 16, model_cfg.vocab_size, task="increment")

    cfg1 = TrainConfig(
        mode="lora", total_steps=6, batch_size=4, seq_len=16,
        log_every=2, checkpoint_every=3,
    )
    t1 = Trainer(model_cfg, cfg1, mesh=mesh)
    state1 = t1.fit(batches(), str(tmp_path))
    assert int(state1.step) == 6

    # same artifacts dir, more steps → resumes from step 6
    cfg2 = TrainConfig(
        mode="lora", total_steps=9, batch_size=4, seq_len=16,
        log_every=2, checkpoint_every=3,
    )
    t2 = Trainer(model_cfg, cfg2, mesh=mesh)
    state2 = t2.fit(batches(), str(tmp_path))
    assert int(state2.step) == 9

    # restored trainable matched what was saved (step-6 ckpt still on disk)
    from finetune_controller_tpu.train.checkpoint import CheckpointManager

    ckpt = CheckpointManager(str(tmp_path / "checkpoints"))
    assert set(ckpt.all_steps()) >= {6, 9}


def test_profiler_trace_ships_with_artifacts(tmp_path):
    """SURVEY.md §5.1 gap: a jax.profiler trace window lands under
    {artifacts}/profile so the artifact sync ships it with the job."""
    model_cfg = _tiny_cfg()
    cfg = TrainConfig(
        mode="lora", total_steps=6, batch_size=2, seq_len=16,
        log_every=100, checkpoint_every=1000,
        profile_steps=2, profile_start_step=1,
    )
    trainer = Trainer(model_cfg, cfg)
    batches = synthetic_batches(2, 16, model_cfg.vocab_size)
    trainer.fit(batches, str(tmp_path), resume=False)
    profile_dir = tmp_path / "profile"
    traces = list(profile_dir.rglob("*.xplane.pb"))
    assert traces, f"no trace files under {profile_dir}"


def test_metrics_writer_resume_gains_columns(tmp_path):
    """A resumed run that enables eval mid-life rewrites the CSV under the
    union header instead of silently dropping the new columns."""
    import csv

    from finetune_controller_tpu.train.metrics import MetricsWriter

    w = MetricsWriter(str(tmp_path))
    w.write({"step": 1, "loss": 2.0})
    w.close()
    w2 = MetricsWriter(
        str(tmp_path), append=True, extra_fields=("eval_loss", "eval_accuracy")
    )
    w2.write({"step": 2, "loss": 1.5, "eval_loss": 1.8, "eval_accuracy": 0.4})
    w2.close()
    rows = list(csv.DictReader(open(tmp_path / "metrics.csv")))
    assert rows[0]["loss"] == "2.0" and rows[0]["eval_loss"] == ""
    assert rows[1]["eval_loss"] == "1.8" and rows[1]["eval_accuracy"] == "0.4"


def test_grad_accumulation_matches_unsplit_step(devices8):
    """grad_accum_steps=N on a sharded mesh produces (near-)identical
    parameter updates to the unsplit step on the same global batch, and the
    invalid configurations fail loudly at construction."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    import pytest

    from finetune_controller_tpu.models import PRESETS, LoRAConfig
    from finetune_controller_tpu.parallel.mesh import MeshSpec
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    cfg = PRESETS["tiny-test"].replace(
        lora=LoRAConfig(rank=4), dtype=jnp.float32
    )
    mesh = MeshSpec(dp=2, fsdp=2, tp=2).build(devices8)

    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, cfg.vocab_size, (8, 32)).astype(np.int32),
        "loss_mask": np.ones((8, 32), np.float32),
    }

    def one_step(accum):
        tc = TrainConfig(
            mode="lora", batch_size=8, seq_len=32, total_steps=1,
            learning_rate=0.01, warmup_steps=0, clip_norm=0.0,
            log_every=10**9, checkpoint_every=10**9, grad_accum_steps=accum,
        )
        tr = Trainer(cfg, tc, mesh=mesh)
        state = tr.init_state()
        state, metrics = tr.step(state, dict(batch))
        host = jax.tree.map(lambda x: np.asarray(x), state.trainable)
        return host, {k: float(v) for k, v in metrics.items()}

    t1, m1 = one_step(1)
    t4, m4 = one_step(2)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5), t1, t4
    )
    assert abs(m1["loss"] - m4["loss"]) < 1e-4
    assert m1["target_tokens"] == m4["target_tokens"] == 8 * 31

    with pytest.raises(ValueError, match="not divisible by"):
        Trainer(cfg, TrainConfig(mode="lora", batch_size=8, grad_accum_steps=3),
                mesh=mesh)
    with pytest.raises(ValueError, match="batch sharding"):
        Trainer(cfg, TrainConfig(mode="lora", batch_size=8, grad_accum_steps=8),
                mesh=mesh)
