"""Cross-process serve transport (ISSUE 12, docs/serving.md §Cross-process
transport).

Anchors: the wire protocol round-trips (msgpack and the JSON fallback); the
worker RPC surface (generate with absolute deadline + idempotent request id,
probe, drain, adapter registry-sync) behaves like the in-process batcher —
proven against a loopback server without paying a process spawn; a REAL
worker process spawns, beats, serves bit-identically to `cached_generate`,
and drains to exit 0; a SIGKILLed worker (via `FTC_FAULT_SERVE_*` forwarded
across the process boundary) loses no request and duplicates none — greedy
outputs bit-identical to the unkilled run — and is respawned with backoff;
adapter load/unload propagates to every worker over the registry-sync RPC,
with a re-register racing an in-flight generate as the regression pin; a
wedged worker (stale heartbeat, unresponsive socket) fails the probe the
LeaseChecker way; and the k8s backend renders one pod per replica.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import run_async
from finetune_controller_tpu.models.generate import cached_generate
from finetune_controller_tpu.resilience.faults import ServeFault
from finetune_controller_tpu.resilience.policy import RetryPolicy
from finetune_controller_tpu.serve.adapters import (
    AdapterRegistry,
    entry_from_wire,
    entry_to_wire,
)
from finetune_controller_tpu.serve.batcher import (
    Batcher,
    DeadlineExceeded,
    QueueFull,
)
from finetune_controller_tpu.serve.engine import (
    BatchEngine,
    EngineConfig,
    GenRequest,
    PromptTooLong,
    warm_engine,
)
from finetune_controller_tpu.serve.fleet import ReplicaFleet
from finetune_controller_tpu.serve.router import ReplicaRouter
from finetune_controller_tpu.transport import TransportError
from finetune_controller_tpu.transport import wire
from finetune_controller_tpu.transport.builders import (
    resolve_builder,
    tiny_test,
)
from finetune_controller_tpu.transport.client import (
    RemoteReplica,
    _Connection,
)
from finetune_controller_tpu.transport.process import ProcessTransport
from finetune_controller_tpu.transport.worker import WorkerServer, WorkerSpec

# same shapes as tests/test_serve.py / test_serve_fleet.py so the warm XLA
# cache is shared by this suite AND by the spawned worker processes
ENGINE_CFG = dict(slots=2, prompt_buckets=(8, 16), max_new_tokens=24)

PROMPTS = [
    [5, 9, 2, 7],
    [1, 3, 3, 8, 2, 2],
    [7, 7, 7],
    [2, 13],
    [11, 4, 9, 1],
    [3, 3, 1],
    [6, 2, 8, 8, 1],
    [9, 9],
]


def _reqs(max_new=8, tag="r"):
    return [
        GenRequest(request_id=f"{tag}{i}", tokens=p, max_new_tokens=max_new)
        for i, p in enumerate(PROMPTS)
    ]


@pytest.fixture(scope="module")
def payload():
    # the SAME deterministic builder worker processes use — cross-process
    # bit-identity needs identical weights in every process
    return tiny_test()


def _baseline(payload, prompt, n):
    model, variables = payload
    out = cached_generate(
        model, variables, jnp.asarray([prompt], jnp.int32), max_new_tokens=n
    )
    return [int(t) for t in np.asarray(out[0, len(prompt):])]


# ---------------------------------------------------------------------------
# Wire framing + codec
# ---------------------------------------------------------------------------


def test_wire_roundtrip_with_bytes():
    doc = {"op": "x", "id": 3,
           "payload": {"blob": b"\x00\xffbinary", "n": [1, 2, 3],
                       "f": 1.5, "s": "text", "none": None}}
    assert wire.loads(wire.dumps(doc)) == doc


def test_wire_json_fallback_roundtrip(monkeypatch):
    monkeypatch.setattr(wire, "msgpack", None)
    doc = {"payload": {"blob": b"\x01\x02", "nested": {"b": b"zz"}}}
    data = wire.dumps(doc)
    json.loads(data.decode())  # really JSON
    assert wire.loads(data) == doc


def test_wire_frame_io_and_oversize_refusal():
    async def main():
        server_got = []

        async def handle(reader, writer):
            server_got.append(await wire.read_msg(reader))
            await wire.write_msg(writer, {"ok": True})
            writer.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        await wire.write_msg(writer, {"op": "ping", "id": 1, "payload": {}})
        reply = await wire.read_msg(reader)
        assert reply == {"ok": True}
        assert server_got[0]["op"] == "ping"
        # an oversized length prefix tears down instead of allocating
        writer2 = (await asyncio.open_connection("127.0.0.1", port))[1]
        writer.close()
        writer2.close()
        server.close()
        await server.wait_closed()

        class FakeReader:
            def __init__(self, data):
                self.data = data

            async def readexactly(self, n):
                out, self.data = self.data[:n], self.data[n:]
                return out

        big = (wire.MAX_FRAME + 1).to_bytes(4, "big")
        with pytest.raises(wire.FrameError, match="exceeds"):
            await wire.read_msg(FakeReader(big))

    run_async(main())


def test_builder_resolution():
    assert resolve_builder("tiny_test") is tiny_test
    fn = resolve_builder(
        "finetune_controller_tpu.transport.builders:tiny_test"
    )
    assert fn is tiny_test
    with pytest.raises(ValueError, match="unknown payload builder"):
        resolve_builder("nope")
    with pytest.raises(ValueError, match="not callable"):
        resolve_builder("finetune_controller_tpu.transport.builders:_BUILTINS")


def test_adapter_entry_wire_roundtrip():
    reg = AdapterRegistry(capacity=3, max_rank=8)
    tree = {"layer": {"q": {"lora_a": np.ones((4, 2), np.float32),
                            "lora_b": np.full((2, 4), 0.5, np.float32)}}}
    entry = reg.register("tenant-a", tree, 16.0, 2, meta={"step": 7})
    doc = entry_to_wire(entry)
    assert isinstance(doc["tree"], bytes)
    aid, tree2, alpha, rank, meta = entry_from_wire(doc)
    assert (aid, alpha, rank, meta) == ("tenant-a", 16.0, 2, {"step": 7})
    np.testing.assert_array_equal(
        tree2["layer"]["q"]["lora_b"], tree["layer"]["q"]["lora_b"]
    )


# ---------------------------------------------------------------------------
# Worker RPC protocol (loopback server — no process spawn)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def shared_engine(payload, tmp_path_factory):
    """One warm engine for the loopback protocol tests (per-test batcher +
    server are cheap; the engine's compiles are not)."""
    model, variables = payload
    registry = AdapterRegistry(capacity=4, max_rank=8)
    engine = BatchEngine(
        model, variables, EngineConfig(**ENGINE_CFG), adapters=registry
    )
    warm_engine(engine)
    return engine


class _Loopback:
    """Per-test loopback worker: fresh batcher + server over the shared
    engine, plus a connected RemoteReplica."""

    def __init__(self, engine, sandbox, **batcher_kw):
        self.engine = engine
        self.spec = WorkerSpec(
            job_id="loop-job", replica_id="r0", sandbox=str(sandbox),
            builder="tiny_test", builder_kwargs={},
            engine=dict(ENGINE_CFG, prompt_buckets=[8, 16]),
            batcher={},
        )
        self.server = WorkerServer(
            self.spec, engine, Batcher(engine, **batcher_kw),
            engine.adapters, exit_on_drain=False,
        )
        self.replica: RemoteReplica | None = None

    async def __aenter__(self):
        port = await self.server.start()
        conn = await _Connection.open("127.0.0.1", port)
        hello = await conn.call("hello", {}, timeout_s=10)
        self.replica = RemoteReplica(
            "r0", conn, hello, sandbox=self.spec.sandbox,
            heartbeat_interval_s=0.2,
        )
        return self

    async def __aexit__(self, *exc):
        await self.replica.close()
        await self.server.stop()


def test_generate_over_wire_bit_identical_and_dedupes(shared_engine, payload,
                                                      tmp_path):
    async def main():
        async with _Loopback(shared_engine, tmp_path) as loop:
            replica = loop.replica
            finished_before = shared_engine.requests_finished_total
            req = GenRequest(request_id="g1", tokens=[5, 9, 2, 7],
                            max_new_tokens=8)
            first, dup = await asyncio.gather(
                replica.submit(req), replica.submit(req)
            )
            # concurrent duplicate ATTACHED to the in-flight attempt
            assert first.generated == dup.generated
            assert shared_engine.requests_finished_total == finished_before + 1
            # completed duplicate REPLAYS from the worker's LRU
            replay = await replica.submit(req)
            assert replay.generated == first.generated
            assert shared_engine.requests_finished_total == finished_before + 1
            assert first.replica_id == "r0"
            assert [int(t) for t in first.generated] == \
                _baseline(payload, [5, 9, 2, 7], 8)

    run_async(main())


def test_typed_errors_cross_the_wire(shared_engine, tmp_path):
    async def main():
        async with _Loopback(shared_engine, tmp_path, max_queue=64) as loop:
            replica = loop.replica
            with pytest.raises(PromptTooLong):
                await replica.submit(GenRequest(
                    request_id="too-long", tokens=[1] * 99, max_new_tokens=4,
                ))
            # an already-spent deadline surfaces as DeadlineExceeded without
            # ever reaching the worker
            with pytest.raises(DeadlineExceeded):
                await replica.submit(
                    GenRequest(request_id="late", tokens=[1, 2],
                               max_new_tokens=4),
                    deadline=time.monotonic() - 1.0,
                )
            # a queued deadline expiring on the worker crosses back typed
            with pytest.raises(DeadlineExceeded):
                await replica.submit(
                    GenRequest(request_id="tight", tokens=[1, 2, 3],
                               max_new_tokens=24),
                    deadline=time.monotonic() + 0.0005,
                )

    run_async(main())


def test_probe_stats_and_tenant_busy(shared_engine, tmp_path):
    async def main():
        async with _Loopback(shared_engine, tmp_path) as loop:
            replica = loop.replica
            await replica.submit(GenRequest(
                request_id="p1", tokens=[7, 7, 7], max_new_tokens=4,
            ))
            probe = await replica.health_probe()
            assert probe["steps_total"] >= 1
            assert probe["slots_busy"] == 0
            assert probe["stats"]["requests_completed_total"] == 1
            assert probe["pid"] == os.getpid()
            # snapshot-backed sync surface the router reads between awaits
            assert replica.queue_depth == 0
            assert replica.engine.steps_total == probe["steps_total"]
            assert replica.stats()["transport"] == "process"
            assert await replica.tenant_busy("") == 0

    run_async(main())


def test_drain_bounces_queued_finishes_inflight(shared_engine, tmp_path):
    async def main():
        async with _Loopback(shared_engine, tmp_path) as loop:
            replica = loop.replica
            inflight = [
                asyncio.ensure_future(replica.submit(GenRequest(
                    request_id=f"d{i}", tokens=PROMPTS[i], max_new_tokens=6,
                ))) for i in range(len(PROMPTS))
            ]
            await asyncio.sleep(0.05)  # let some admit; the rest queue
            clean = await replica.drain(10.0)
            assert clean is True
            done = await asyncio.gather(*inflight, return_exceptions=True)
            finished = [r for r in done if not isinstance(r, Exception)]
            bounced = [r for r in done if isinstance(r, Exception)]
            # in-flight lanes finished; queued requests bounced retryably
            assert finished, "drain should let admitted lanes finish"
            from finetune_controller_tpu.serve.batcher import (
                ReplicaUnavailable,
            )

            assert all(isinstance(b, ReplicaUnavailable) for b in bounced)
            # post-drain submits refuse
            with pytest.raises(ReplicaUnavailable):
                await replica.submit(GenRequest(
                    request_id="late", tokens=[1], max_new_tokens=2,
                ))

    run_async(main())


def test_adapter_sync_rpcs_and_reregister_race(shared_engine, payload,
                                               tmp_path):
    """Registry-sync RPCs install/refresh/remove on the worker; the
    regression pin: a re-register racing an in-flight generate completes
    both — no crash, no torn stacks — and the refresh drops the tenant's
    prefix namespace (stale-KV poison fence)."""
    from test_serve_adapters import _make_adapter  # reuse the harness

    async def main():
        async with _Loopback(shared_engine, tmp_path) as loop:
            replica = loop.replica
            registry = AdapterRegistry(capacity=4, max_rank=8)
            tree_v1 = _make_adapter(seed=1, rank=4)
            entry = registry.register("ten-a", tree_v1, 16.0, 4)
            slot = await replica.adapter_register(entry_to_wire(entry))
            assert slot == entry.slot
            base = await replica.submit(GenRequest(
                request_id="a-base", tokens=[5, 9, 2, 7], max_new_tokens=6,
            ))
            tenant = await replica.submit(GenRequest(
                request_id="a-t1", tokens=[5, 9, 2, 7], max_new_tokens=6,
                adapter_id="ten-a",
            ))
            assert tenant.generated != base.generated, \
                "adapter must change decode"
            # --- re-register racing an in-flight generate ----------------
            racing = asyncio.ensure_future(replica.submit(GenRequest(
                request_id="a-race", tokens=PROMPTS[1], max_new_tokens=12,
                adapter_id="ten-a",
            )))
            await asyncio.sleep(0.02)
            tree_v2 = _make_adapter(seed=2, rank=4)
            entry2 = registry.register("ten-a", tree_v2, 16.0, 4)
            await replica.adapter_register(entry_to_wire(entry2),
                                           refresh=True)
            raced = await racing
            assert raced.finish_reason in ("length", "eos")
            # post-refresh decodes use the NEW deltas: bit-identical to a
            # fresh single-tenant run of tree_v2
            post = await replica.submit(GenRequest(
                request_id="a-t2", tokens=[5, 9, 2, 7], max_new_tokens=6,
                adapter_id="ten-a",
            ))
            from test_serve_adapters import _dedicated

            model, _vars = payload
            base_vars = {"params": tiny_test()[1]["params"]}
            expected = _dedicated(
                model, base_vars, "ten-a", tree_v2, 16.0, 4,
                GenRequest(request_id="ded", tokens=[5, 9, 2, 7],
                           max_new_tokens=6, adapter_id="ten-a"),
                page_tokens=0,
            )
            assert list(post.generated) == list(expected)
            # unregister clears the slot on the worker
            await replica.adapter_unregister("ten-a")
            from finetune_controller_tpu.serve.adapters import UnknownAdapter

            with pytest.raises(UnknownAdapter):
                await replica.submit(GenRequest(
                    request_id="a-gone", tokens=[5, 9], max_new_tokens=4,
                    adapter_id="ten-a",
                ))

    run_async(main())


def test_wedged_worker_fails_probe_lease_style(tmp_path):
    """A worker that accepts connections but never answers, with a stale
    heartbeat, must fail the probe (the fleet then kills it) — the
    LeaseChecker pattern applied to serve workers."""

    async def main():
        async def black_hole(reader, writer):
            await asyncio.sleep(3600)

        server = await asyncio.start_server(black_hole, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        # a heartbeat from the distant past
        with open(tmp_path / "heartbeat.json", "w") as f:
            json.dump({"step": 3, "ts": time.time() - 120.0}, f)
        conn = await _Connection.open("127.0.0.1", port)
        replica = RemoteReplica(
            "rX", conn, {"pid": 1, "engine": {}}, sandbox=str(tmp_path),
            heartbeat_interval_s=0.5, probe_timeout_s=0.5,
        )
        with pytest.raises(TransportError, match="stale"):
            await replica.health_probe()
        # a fresh beat moves the failure to the probe-timeout layer
        with open(tmp_path / "heartbeat.json", "w") as f:
            json.dump({"step": 3, "ts": time.time()}, f)
        with pytest.raises(TransportError, match="timed out"):
            await replica.health_probe()
        await replica.close()
        server.close()
        await server.wait_closed()

    run_async(main())


def test_k8s_renders_one_pod_per_replica():
    from finetune_controller_tpu.controller.backends.k8s import (
        render_serve_worker_pod,
    )

    pod = render_serve_worker_pod(
        "job-1", "r0", namespace="ftc", image="img:tag",
        worker_spec={"job_id": "job-1", "replica_id": "r0",
                     "builder": "deploy_dir",
                     "builder_kwargs": {"dir": "/stage"}},
        extra_env={"FTC_FAULT_SERVE_REPLICA": "r0"},
    )
    assert pod["kind"] == "Pod"
    assert pod["metadata"]["name"] == "job-1-serve-r0"
    assert pod["spec"]["restartPolicy"] == "Never"  # the FLEET respawns
    container = pod["spec"]["containers"][0]
    env = {e["name"]: e.get("value") for e in container["env"]}
    spec_doc = json.loads(env["FTC_SERVE_WORKER_SPEC"])
    assert spec_doc["replica_id"] == "r0"
    assert spec_doc["port"] == container["ports"][0]["containerPort"]
    # the chaos hand crosses the pod boundary like the process boundary
    assert env["FTC_FAULT_SERVE_REPLICA"] == "r0"
    assert "transport.worker" in container["command"][-1]


# ---------------------------------------------------------------------------
# Real worker processes
# ---------------------------------------------------------------------------


def _transport(tmp_path, **kw):
    defaults = dict(
        job_id="proc-job", root=tmp_path / "workers",
        payload={"builder": "tiny_test", "kwargs": {}},
        spawn_timeout_s=240.0, heartbeat_interval_s=0.5,
        probe_timeout_s=30.0,
    )
    defaults.update(kw)
    return ProcessTransport(**defaults)


def _process_fleet(tmp_path, replicas=2, transport=None, **kw):
    defaults = dict(
        replicas=replicas,
        stall_timeout_s=30.0,
        drain_timeout_s=15.0,
        restart_policy=RetryPolicy(
            max_attempts=3, base_delay_s=0.1, max_delay_s=0.3, seed=0
        ),
    )
    defaults.update(kw)
    return ReplicaFleet(
        "proc-job", None, None, EngineConfig(**ENGINE_CFG),
        transport=transport or _transport(tmp_path), **defaults,
    )


def test_process_worker_spawn_generate_heartbeat_drain(tmp_path, payload):
    """One real worker process: spawn handshake, bit-identical generate,
    live heartbeat, probe, graceful drain to exit 0."""

    async def main():
        transport = _transport(tmp_path)
        replica = await transport.spawn(
            "r0", 0, engine_config=EngineConfig(**ENGINE_CFG),
            batcher_kwargs={}, adapters=None,
        )
        try:
            assert replica.pid != os.getpid()  # its own process
            res = await replica.submit(GenRequest(
                request_id="p0", tokens=[5, 9, 2, 7], max_new_tokens=8,
            ))
            assert [int(t) for t in res.generated] == \
                _baseline(payload, [5, 9, 2, 7], 8)
            probe = await replica.health_probe()
            assert probe["steps_total"] >= 1
            # the worker beats into its sandbox (resilience/heartbeat.py)
            hb_path = os.path.join(replica.sandbox, "heartbeat.json")
            with open(hb_path) as f:
                hb = json.load(f)
            assert hb["pid"] == replica.pid
            clean = await replica.drain(10.0)
            assert clean is True
            # the drained worker EXITS (code 0)
            for _ in range(100):
                code = replica._proc.poll()
                if code is not None:
                    break
                await asyncio.sleep(0.1)
            assert code == 0
        finally:
            await replica.close()

    run_async(main())


def test_sigkilled_worker_exactly_once_bit_identical(tmp_path, payload):
    """THE cross-process chaos anchor: `FTC_FAULT_SERVE_*` forwarded into
    the worker spawn env makes worker r0 REALLY SIGKILL itself mid-decode;
    every accepted request completes exactly once, greedy outputs are
    bit-identical to the baseline, and the fleet respawns a fresh sandbox
    with backoff."""

    async def main():
        once = tmp_path / "fault-spent"
        fault_env = ServeFault(
            replica_id="r0", at_step=2, mode="kill", once_file=str(once),
        ).to_env()
        transport = _transport(tmp_path, extra_env=fault_env)
        fleet = _process_fleet(tmp_path, transport=transport)
        await fleet.start()
        router = ReplicaRouter(fleet, default_timeout_s=120,
                               failover_retries=2)

        async def health_loop():
            while True:
                await fleet.health_tick()
                await asyncio.sleep(0.1)

        hl = asyncio.ensure_future(health_loop())
        try:
            results = await asyncio.gather(
                *(router.submit(r) for r in _reqs(max_new=8, tag="k"))
            )
            seen = {}
            for r in results:
                assert r.request_id not in seen, "request completed twice"
                seen[r.request_id] = r.generated
            assert len(seen) == len(PROMPTS), "accepted requests were lost"
            # the fault actually fired as a REAL SIGKILL in the worker
            assert once.exists(), "serve fault never fired"
            for rid, toks in seen.items():
                i = int(rid[1:])
                assert [int(t) for t in toks] == \
                    _baseline(payload, PROMPTS[i], 8), rid
            # the dead worker was detected and a fresh sandbox respawned
            for _ in range(150):
                if fleet.replica_restarts_total >= 1 \
                        and len(fleet.healthy_replicas()) >= 2:
                    break
                await asyncio.sleep(0.2)
            assert fleet.replica_restarts_total >= 1
            assert len(fleet.healthy_replicas()) >= 2
            assert fleet.replicas_failed_total >= 1
        finally:
            hl.cancel()
            await fleet.close()

    run_async(main())


@pytest.mark.slow
def test_adapter_sync_propagates_to_all_workers(tmp_path, payload):
    """Adapter register/unregister reach EVERY worker process through the
    stack-sync RPC; a worker spawned after registration syncs at spawn."""
    from test_serve_adapters import _make_adapter

    async def main():
        transport = _transport(
            tmp_path,
            payload={"builder": "tiny_test",
                     "kwargs": {"lora_rank": 0}},
        )
        registry = AdapterRegistry(capacity=3, max_rank=8)
        fleet = _process_fleet(tmp_path, replicas=2, transport=transport,
                               adapters=registry)
        await fleet.start()
        try:
            tree = _make_adapter(seed=3, rank=4)
            await fleet.register_adapter("ten-p", tree, 16.0, 4)
            # route one request to EACH worker directly: propagation proof,
            # not routing luck
            outs = []
            for replica in fleet.healthy_replicas():
                res = await replica.batcher.submit(GenRequest(
                    request_id=f"ad-{replica.replica_id}",
                    tokens=[5, 9, 2, 7], max_new_tokens=6,
                    adapter_id="ten-p",
                ))
                outs.append(list(res.generated))
            assert outs[0] == outs[1], "workers disagree on the adapter"
            # ... and matches a dedicated in-process unmerged engine
            from test_serve_adapters import _dedicated

            model, _ = payload
            base_vars = {"params": tiny_test(lora_rank=0)[1]["params"]}
            expected = _dedicated(
                model, base_vars, "ten-p", tree, 16.0, 4,
                GenRequest(request_id="ded", tokens=[5, 9, 2, 7],
                           max_new_tokens=6, adapter_id="ten-p"),
                page_tokens=0,
            )
            assert outs[0] == list(expected)
            # a worker spawned AFTER registration syncs at spawn
            fleet.target_replicas = 3
            late = await fleet.spawn_replica()
            res = await late.batcher.submit(GenRequest(
                request_id="ad-late", tokens=[5, 9, 2, 7], max_new_tokens=6,
                adapter_id="ten-p",
            ))
            assert list(res.generated) == outs[0]
            # unload drops the tenant everywhere
            await fleet.unregister_adapter("ten-p")
            from finetune_controller_tpu.serve.adapters import UnknownAdapter

            with pytest.raises(UnknownAdapter):
                await late.batcher.submit(GenRequest(
                    request_id="ad-gone", tokens=[9, 9], max_new_tokens=4,
                    adapter_id="ten-p",
                ))
        finally:
            await fleet.close()

    run_async(main())
