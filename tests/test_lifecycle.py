"""Tests for task builder + monitor reconciliation + the full job lifecycle.

Covers the reference's submit path (``app/jobs/task_builder.py``, SURVEY.md
§3.1), the monitor loop (``app/core/monitor.py``, §3.2), and the end-to-end
lifecycle (submit → queue → train → metrics → succeeded → substrate cleanup)
that the reference could only exercise against a live cluster (SURVEY.md §4).
"""

import asyncio

import pytest

from finetune_controller_tpu.controller.backends.base import TrainingBackend
from finetune_controller_tpu.controller.backends.local import LocalProcessBackend
from finetune_controller_tpu.controller.datasets import (
    filename_from_content_disposition,
    upload_dataset_bytes,
)
from finetune_controller_tpu.controller.monitor import JobMonitor
from finetune_controller_tpu.controller.objectstore import LocalObjectStore
from finetune_controller_tpu.controller.schemas import (
    BackendJobReport,
    BackendJobState,
    DatabaseStatus,
    JobInput,
)
from finetune_controller_tpu.controller.statestore import StateStore
from finetune_controller_tpu.controller.task_builder import (
    DatasetInput,
    TaskBuildError,
    task_builder,
)


from conftest import one_chip_catalog as _catalog
from conftest import run_async as run
from conftest import tiny_job_spec as _spec


# ---------------------------------------------------------------------------
# Scripted fake backend for monitor unit tests
# ---------------------------------------------------------------------------


class ScriptedBackend(TrainingBackend):
    """Backend whose reports are set directly by the test."""

    def __init__(self):
        self.reports: dict[str, BackendJobReport] = {}
        self.pending: list[str] = []
        self.deleted: list[str] = []

    async def submit(self, job, spec, flavor, *, dataset_uri, artifacts_uri):
        self.reports[job.job_id] = BackendJobReport(
            job_id=job.job_id, state=BackendJobState.SUSPENDED
        )

    async def list_jobs(self):
        return list(self.reports.values())

    async def get_job(self, job_id):
        return self.reports.get(job_id)

    async def delete_job(self, job_id, *, forget_reservations=False):
        self.deleted.append(job_id)
        return self.reports.pop(job_id, None) is not None

    async def read_logs(self, job_id, *, follow=False, last_lines=None):
        async def aiter():
            yield "line"
        return aiter()

    async def queue_snapshot(self):
        return list(self.pending)


def test_filename_from_content_disposition():
    assert filename_from_content_disposition('attachment; filename="a b.csv"') == "a b.csv"
    assert filename_from_content_disposition("attachment; filename*=UTF-8''x%20y.jsonl") == "x y.jsonl"
    assert filename_from_content_disposition(None) is None


def test_monitor_status_mapping_and_queue_positions(tmp_path):
    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        backend = ScriptedBackend()
        monitor = JobMonitor(state, store, backend, interval_s=0.1)
        await state.connect()

        job = JobInput(job_id="m-1", user_id="u", model_name="tiny-test-lora",
                       device="chip-1", arguments={})
        await task_builder(
            job, _spec(), DatasetInput(),
            state=state, store=store, backend=backend, catalog=_catalog(),
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        backend.pending = ["m-1"]
        await monitor.tick()
        rec = await state.get_job("m-1")
        assert rec.status is DatabaseStatus.QUEUED
        assert rec.queue_position == 1

        # job starts running
        backend.pending = []
        backend.reports["m-1"] = BackendJobReport(
            job_id="m-1", state=BackendJobState.RUNNING, start_time=100.0
        )
        await monitor.tick()
        rec = await state.get_job("m-1")
        assert rec.status is DatabaseStatus.RUNNING
        assert rec.queue_position is None
        assert rec.start_time == 100.0

        # job succeeds -> duration computed, substrate cleaned
        backend.reports["m-1"] = BackendJobReport(
            job_id="m-1", state=BackendJobState.SUCCEEDED,
            start_time=100.0, completion_time=160.0,
        )
        await monitor.tick()
        rec = await state.get_job("m-1")
        assert rec.status is DatabaseStatus.SUCCEEDED
        assert rec.training_duration == 60.0
        assert backend.deleted == ["m-1"]

        # final jobs are skipped on later ticks (no re-update)
        await monitor.tick()
        assert (await state.get_job("m-1")).status is DatabaseStatus.SUCCEEDED

    run(main())


def test_monitor_failed_jobs_kept_for_forensics(tmp_path):
    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        backend = ScriptedBackend()
        monitor = JobMonitor(state, store, backend, interval_s=0.1)
        await state.connect()
        await task_builder(
            JobInput(job_id="f-1", user_id="u", model_name="tiny-test-lora",
                     device="chip-1", arguments={}),
            _spec(), DatasetInput(),
            state=state, store=store, backend=backend, catalog=_catalog(),
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        backend.reports["f-1"] = BackendJobReport(
            job_id="f-1", state=BackendJobState.FAILED,
            start_time=1.0, completion_time=2.0, message="exit code 1",
        )
        await monitor.tick()
        rec = await state.get_job("f-1")
        assert rec.status is DatabaseStatus.FAILED
        assert rec.metadata["backend_message"] == "exit code 1"
        assert backend.deleted == []  # failed jobs stay for inspection

    run(main())


def test_monitor_metrics_update_on_content_change(tmp_path):
    """Rewritten metrics rows with the SAME row count must still propagate
    (round-1 weak spot: the monitor skipped the upsert on unchanged len)."""

    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        backend = ScriptedBackend()
        monitor = JobMonitor(state, store, backend, interval_s=0.1)
        await state.connect()
        await task_builder(
            JobInput(job_id="mm-1", user_id="u", model_name="tiny-test-lora",
                     device="chip-1", arguments={}),
            _spec(), DatasetInput(),
            state=state, store=store, backend=backend, catalog=_catalog(),
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        backend.reports["mm-1"] = BackendJobReport(
            job_id="mm-1", state=BackendJobState.RUNNING, start_time=1.0
        )
        rec = await state.get_job("mm-1")
        await store.put_bytes(
            f"{rec.artifacts_uri}/metrics.csv", b"step,loss\n1,2.0\n2,1.5\n"
        )
        await monitor.tick()
        doc = await state.get_metrics("mm-1")
        assert doc is not None and doc.records[1]["loss"] == 1.5

        # same row count, corrected content — must be picked up
        await store.put_bytes(
            f"{rec.artifacts_uri}/metrics.csv", b"step,loss\n1,2.0\n2,1.25\n"
        )
        await monitor.tick()
        doc = await state.get_metrics("mm-1")
        assert doc.records[1]["loss"] == 1.25

    run(main())


def test_monitor_cleans_cancelled_jobs_backend_half(tmp_path):
    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        backend = ScriptedBackend()
        monitor = JobMonitor(state, store, backend, interval_s=0.1)
        await state.connect()
        await task_builder(
            JobInput(job_id="c-1", user_id="u", model_name="tiny-test-lora",
                     device="chip-1", arguments={}),
            _spec(), DatasetInput(),
            state=state, store=store, backend=backend, catalog=_catalog(),
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        await state.update_job_status("c-1", DatabaseStatus.CANCELLED)
        await monitor.tick()
        assert backend.deleted == ["c-1"]

    run(main())


def test_task_builder_dataset_branches(tmp_path):
    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        backend = ScriptedBackend()
        await state.connect()

        # dataset by id
        ds = await upload_dataset_bytes(
            store, state, user_id="u", filename="train.jsonl",
            data=b'{"text": "hi"}\n', bucket="datasets",
        )
        rec = await task_builder(
            JobInput(job_id="j-id", user_id="u", model_name="tiny-test-lora",
                     device="chip-1", arguments={}),
            _spec(), DatasetInput(dataset_id=ds.dataset_id),
            state=state, store=store, backend=backend, catalog=_catalog(),
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        assert rec.dataset_uri == ds.uri
        refreshed = await state.get_dataset(ds.dataset_id)
        assert "j-id" in refreshed.job_refs

        # dataset by file
        rec2 = await task_builder(
            JobInput(job_id="j-file", user_id="u", model_name="tiny-test-lora",
                     device="chip-1", arguments={}),
            _spec(),
            DatasetInput(file_name="up.jsonl", file_data=b'{"text": "yo"}\n'),
            state=state, store=store, backend=backend, catalog=_catalog(),
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        assert rec2.dataset_uri and await store.exists(rec2.dataset_uri)

        # unknown dataset id -> 404
        with pytest.raises(TaskBuildError) as ei:
            await task_builder(
                JobInput(job_id="j-bad", user_id="u", model_name="tiny-test-lora",
                         device="chip-1", arguments={}),
                _spec(), DatasetInput(dataset_id="nope"),
                state=state, store=store, backend=backend, catalog=_catalog(),
                datasets_bucket="datasets", artifacts_bucket="artifacts",
            )
        assert ei.value.status == 404

        # other-user dataset is invisible
        with pytest.raises(TaskBuildError):
            await task_builder(
                JobInput(job_id="j-xuser", user_id="intruder",
                         model_name="tiny-test-lora", device="chip-1", arguments={}),
                _spec(), DatasetInput(dataset_id=ds.dataset_id),
                state=state, store=store, backend=backend, catalog=_catalog(),
                datasets_bucket="datasets", artifacts_bucket="artifacts",
            )

    run(main())


def test_task_builder_submit_failure_rolls_back_job_ref(tmp_path):
    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        await state.connect()

        class ExplodingBackend(ScriptedBackend):
            async def submit(self, *a, **k):
                raise RuntimeError("no quota")

        ds = await upload_dataset_bytes(
            store, state, user_id="u", filename="t.jsonl",
            data=b"{}\n", bucket="datasets",
        )
        with pytest.raises(TaskBuildError) as ei:
            await task_builder(
                JobInput(job_id="j-boom", user_id="u", model_name="tiny-test-lora",
                         device="chip-1", arguments={}),
                _spec(), DatasetInput(dataset_id=ds.dataset_id),
                state=state, store=store, backend=ExplodingBackend(),
                catalog=_catalog(),
                datasets_bucket="datasets", artifacts_bucket="artifacts",
            )
        assert ei.value.status == 500
        refreshed = await state.get_dataset(ds.dataset_id)
        assert "j-boom" not in refreshed.job_refs
        assert await state.get_job("j-boom") is None

    run(main())


# ---------------------------------------------------------------------------
# Full lifecycle against the real local backend (the e2e slice, SURVEY §7 step 3)
# ---------------------------------------------------------------------------


def test_full_lifecycle_submit_train_metrics_succeed(tmp_path):
    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        catalog = _catalog()
        backend = LocalProcessBackend(
            tmp_path / "sandboxes", store, catalog, sync_interval_s=0.2
        )
        monitor = JobMonitor(state, store, backend, interval_s=0.1)
        await state.connect()

        rows = b'{"text": "the quick brown fox jumps over the lazy dog"}\n' * 16
        ds = await upload_dataset_bytes(
            store, state, user_id="u", filename="train.jsonl",
            data=rows, bucket="datasets",
        )
        job = JobInput(job_id="e2e-1", user_id="u", model_name="tiny-test-lora",
                       device="chip-1", arguments={"total_steps": 3})
        await task_builder(
            job, _spec(), DatasetInput(dataset_id=ds.dataset_id),
            state=state, store=store, backend=backend, catalog=catalog,
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )

        deadline = asyncio.get_event_loop().time() + 120
        while True:
            await monitor.tick()
            rec = await state.get_job("e2e-1")
            if rec.status.is_final:
                break
            assert asyncio.get_event_loop().time() < deadline, rec
            await asyncio.sleep(0.3)

        assert rec.status is DatabaseStatus.SUCCEEDED, rec
        assert rec.training_duration and rec.training_duration > 0
        # metrics flowed object store -> DB
        metrics = await state.get_metrics("e2e-1")
        assert metrics is not None and len(metrics.records) >= 1
        assert "loss" in metrics.records[0]
        # substrate cleaned up after success
        assert await backend.get_job("e2e-1") is None
        # artifacts remain in the object store
        assert await store.exists(rec.artifacts_uri + "/done.txt")
        await backend.close()
        await state.close()

    run(main())


def test_monitor_sweeps_jobs_lost_by_backend(tmp_path):
    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        backend = ScriptedBackend()
        monitor = JobMonitor(state, store, backend, interval_s=0.1)
        monitor.lost_job_grace_s = 0.0
        await state.connect()
        await task_builder(
            JobInput(job_id="lost-1", user_id="u", model_name="tiny-test-lora",
                     device="chip-1", arguments={}),
            _spec(), DatasetInput(),
            state=state, store=store, backend=backend, catalog=_catalog(),
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        # simulate a control-plane restart: backend forgot the job
        backend.reports.clear()
        await monitor.tick()
        rec = await state.get_job("lost-1")
        assert rec.status is DatabaseStatus.UNKNOWN
        assert "no longer tracked" in rec.metadata["backend_message"]

    run(main())
