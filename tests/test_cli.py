import json
import os
import subprocess
import sys

from finetune_controller_tpu.train import cli


def _spec(tmp_path, **training):
    return {
        "job_id": "test-job",
        "model": {"preset": "tiny-test", "lora": {"rank": 4}},
        "training": {
            "mode": "lora", "total_steps": 4, "batch_size": 4, "seq_len": 16,
            "log_every": 2, "checkpoint_every": 100, **training,
        },
        "mesh": {"dp": 1, "fsdp": 1, "tp": 1},
        "dataset": {"synthetic": {"task": "increment"}},
        "artifacts_dir": str(tmp_path / "artifacts"),
    }


def test_run_job_in_process(tmp_path):
    spec = _spec(tmp_path)
    cli.run_job(spec)
    art = tmp_path / "artifacts"
    assert (art / "done.txt").exists()
    assert (art / "metrics.csv").exists()
    assert (art / "resolved_config.json").exists()
    header = (art / "metrics.csv").read_text().splitlines()[0]
    assert "loss" in header and "tokens_per_sec" in header


def test_cli_subprocess(tmp_path):
    """The exact launch path the local training backend uses."""
    spec = _spec(tmp_path)
    spec_path = tmp_path / "job.json"
    spec_path.write_text(json.dumps(spec))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep tests off any TPU tunnel
    proc = subprocess.run(
        [sys.executable, "-m", "finetune_controller_tpu.train.cli", "--spec", str(spec_path)],
        capture_output=True, text=True, timeout=300, env=env, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert (tmp_path / "artifacts" / "done.txt").exists()


def test_unconsumed_extra_arguments_rejected(tmp_path):
    """A user argument the spec class never mapped must fail loudly, not be
    silently dropped (round-1 weak spot)."""
    spec = _spec(tmp_path)
    spec["extra_arguments"] = {"my_custom_knob": 3}
    try:
        cli.run_job(spec)
        raise AssertionError("should have raised")
    except ValueError as e:
        assert "my_custom_knob" in str(e)


def test_bad_spec_rejected(tmp_path):
    spec = _spec(tmp_path)
    spec["training"]["bogus_field"] = 1
    try:
        cli.run_job(spec)
        raise AssertionError("should have raised")
    except ValueError as e:
        assert "bogus_field" in str(e)


def test_eval_loop_writes_heldout_metrics(tmp_path):
    """eval_every drives a held-out evaluation: eval columns ride on the
    train log rows at the eval cadence (dense rows — ragged cells would
    parse as NaN in the control plane's pandas reader)."""
    import csv

    spec = _spec(tmp_path, total_steps=4, eval_every=2)
    spec["training"]["eval_steps"] = 2
    cli.run_job(spec)
    rows = list(csv.DictReader(open(tmp_path / "artifacts" / "metrics.csv")))
    assert "eval_loss" in rows[0]
    eval_rows = [r for r in rows if r["eval_loss"]]
    assert len(eval_rows) == 2  # steps 2 and 4
    assert {r["step"] for r in eval_rows} == {"2", "4"}
    for r in eval_rows:
        assert float(r["eval_loss"]) > 0
        assert float(r["loss"]) > 0  # eval rides on a full train row


def test_eval_without_heldout_split_fails_loudly(tmp_path):
    spec = _spec(tmp_path, eval_every=2)
    spec["dataset"] = {"path": str(tmp_path / "train.jsonl")}
    (tmp_path / "train.jsonl").write_text('{"text": "hello world"}\n' * 8)
    import pytest

    with pytest.raises(ValueError, match="no eval split"):
        cli.run_job(spec)
