import json
import os
import subprocess
import sys

from finetune_controller_tpu.train import cli


def _spec(tmp_path, **training):
    return {
        "job_id": "test-job",
        "model": {"preset": "tiny-test", "lora": {"rank": 4}},
        "training": {
            "mode": "lora", "total_steps": 4, "batch_size": 4, "seq_len": 16,
            "log_every": 2, "checkpoint_every": 100, **training,
        },
        "mesh": {"dp": 1, "fsdp": 1, "tp": 1},
        "dataset": {"synthetic": {"task": "increment"}},
        "artifacts_dir": str(tmp_path / "artifacts"),
    }


def test_run_job_in_process(tmp_path):
    spec = _spec(tmp_path)
    cli.run_job(spec)
    art = tmp_path / "artifacts"
    assert (art / "done.txt").exists()
    assert (art / "metrics.csv").exists()
    assert (art / "resolved_config.json").exists()
    header = (art / "metrics.csv").read_text().splitlines()[0]
    assert "loss" in header and "tokens_per_sec" in header


def test_cli_subprocess(tmp_path):
    """The exact launch path the local training backend uses."""
    spec = _spec(tmp_path)
    spec_path = tmp_path / "job.json"
    spec_path.write_text(json.dumps(spec))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep tests off any TPU tunnel
    proc = subprocess.run(
        [sys.executable, "-m", "finetune_controller_tpu.train.cli", "--spec", str(spec_path)],
        capture_output=True, text=True, timeout=300, env=env, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert (tmp_path / "artifacts" / "done.txt").exists()


def test_unconsumed_extra_arguments_rejected(tmp_path):
    """A user argument the spec class never mapped must fail loudly, not be
    silently dropped (round-1 weak spot)."""
    spec = _spec(tmp_path)
    spec["extra_arguments"] = {"my_custom_knob": 3}
    try:
        cli.run_job(spec)
        raise AssertionError("should have raised")
    except ValueError as e:
        assert "my_custom_knob" in str(e)


def test_bad_spec_rejected(tmp_path):
    spec = _spec(tmp_path)
    spec["training"]["bogus_field"] = 1
    try:
        cli.run_job(spec)
        raise AssertionError("should have raised")
    except ValueError as e:
        assert "bogus_field" in str(e)


def test_eval_loop_writes_heldout_metrics(tmp_path):
    """eval_every drives a held-out evaluation: eval columns ride on the
    train log rows at the eval cadence (dense rows — ragged cells would
    parse as NaN in the control plane's pandas reader)."""
    import csv

    spec = _spec(tmp_path, total_steps=4, eval_every=2)
    spec["training"]["eval_steps"] = 2
    cli.run_job(spec)
    rows = list(csv.DictReader(open(tmp_path / "artifacts" / "metrics.csv")))
    assert "eval_loss" in rows[0]
    eval_rows = [r for r in rows if r["eval_loss"]]
    assert len(eval_rows) == 2  # steps 2 and 4
    assert {r["step"] for r in eval_rows} == {"2", "4"}
    for r in eval_rows:
        assert float(r["eval_loss"]) > 0
        assert float(r["loss"]) > 0  # eval rides on a full train row


def test_eval_without_heldout_split_fails_loudly(tmp_path):
    spec = _spec(tmp_path, eval_every=2)
    spec["dataset"] = {"path": str(tmp_path / "train.jsonl")}
    (tmp_path / "train.jsonl").write_text('{"text": "hello world"}\n' * 8)
    import pytest

    with pytest.raises(ValueError, match="no eval split"):
        cli.run_job(spec)


def _run_generate(argv):
    """Invoke generate_cli.main, returning its one-line JSON output."""
    import io
    from contextlib import redirect_stdout

    from finetune_controller_tpu.models import generate_cli

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert generate_cli.main(argv) == 0
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_generate_cli_from_artifacts(tmp_path):
    """Post-finetune generation CLI: train a tiny job, then generate from
    its artifacts dir — the resume recipe (seeded init + latest checkpoint)
    plus both token-id and byte-prompt modes, greedy determinism across
    invocations."""
    spec = _spec(tmp_path, checkpoint_every=2)
    cli.run_job(spec)
    art = str(tmp_path / "artifacts")
    run = _run_generate

    out = run(["--artifacts", art, "--prompt-tokens", "5,6,7,8",
               "--max-new-tokens", "6"])
    assert out["checkpoint_step"] == 4
    assert len(out["new_tokens"]) == 6
    assert all(0 <= t < 256 for t in out["new_tokens"])
    assert out["text"] is None  # token-id mode: ids in, ids out

    # greedy is deterministic across fresh invocations
    again = run(["--artifacts", art, "--prompt-tokens", "5,6,7,8",
                 "--max-new-tokens", "6"])
    assert again["new_tokens"] == out["new_tokens"]

    # byte-prompt mode decodes text through the data pipeline's fallback
    out = run(["--artifacts", art, "--prompt", "abc", "--max-new-tokens", "4"])
    assert isinstance(out["text"], str)

    # guard rails: bad ids and missing checkpoint fail loudly
    import pytest

    with pytest.raises(SystemExit, match="out of range"):
        run(["--artifacts", art, "--prompt-tokens", "999999"])
    with pytest.raises(SystemExit, match="exactly one"):
        run(["--artifacts", art])


def test_generate_cli_uses_job_tokenizer(tmp_path):
    """--prompt must tokenize with the tokenizer the JOB trained with
    (dataset.tokenizer_file in resolved_config.json), not the byte
    fallback — and decode output through it."""
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {f"w{i}": i for i in range(16)}
    vocab["hello"] = 16
    vocab["[UNK]"] = 17
    tok = Tokenizer(WordLevel(vocab, unk_token="[UNK]"))
    tok.pre_tokenizer = Whitespace()
    tok_file = tmp_path / "tok.json"
    tok_file.write_text(tok.to_str())

    spec = _spec(tmp_path, checkpoint_every=2)
    spec["dataset"]["tokenizer_file"] = str(tok_file)
    cli.run_job(spec)

    out = _run_generate(
        ["--artifacts", str(tmp_path / "artifacts"), "--prompt", "hello",
         "--max-new-tokens", "3"]
    )
    # "hello" is ONE WordLevel token (id 16), not 5 byte tokens
    assert out["prompt_tokens"] == 1
    # output decodes through the same tokenizer (all ids < vocab 256 decode
    # to either known words or empty; text must be a str, not null)
    assert isinstance(out["text"], str)


def test_generate_cli_mesh_fallback_and_full_mode(tmp_path, capsys):
    """Two resume-recipe edges: a job mesh this host can't form falls back
    to the default single-device mesh (with a note, not a crash), and
    mode='full' jobs skip the pretrained-base reload (the checkpoint holds
    every weight)."""
    spec = _spec(tmp_path, checkpoint_every=2, mode="full", learning_rate=1e-3)
    del spec["model"]["lora"]
    cli.run_job(spec)

    # rewrite the recorded spec: a mesh the conftest's 8 devices cannot form
    # (-> fallback note, not a crash) and a weights_dir that would crash if
    # the full-mode skip didn't apply
    art_spec = json.loads(
        (tmp_path / "artifacts" / "resolved_config.json").read_text()
    )
    art_spec["mesh"] = {"dp": 64}
    art_spec["model"]["weights_dir"] = str(tmp_path / "does-not-exist")
    (tmp_path / "artifacts" / "resolved_config.json").write_text(
        json.dumps(art_spec)
    )

    out = _run_generate(
        ["--artifacts", str(tmp_path / "artifacts"),
         "--prompt-tokens", "5,6,7", "--max-new-tokens", "2"]
    )
    assert len(out["new_tokens"]) == 2
    err = capsys.readouterr().err
    assert "job mesh" in err and "unavailable here" in err
