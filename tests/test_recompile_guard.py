"""Runtime recompilation guard (analysis/recompile_guard.py): signature
counting, warn/raise policies, and the trainer integration behind
``TrainConfig.recompile_budget``."""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finetune_controller_tpu.analysis.recompile_guard import (
    RecompileBudgetExceeded,
    RecompileGuard,
    signature_of,
)


def test_signature_distinguishes_shape_dtype_and_scalars():
    a = np.zeros((4, 8), np.float32)
    assert signature_of(a) == signature_of(np.ones((4, 8), np.float32))
    assert signature_of(a) != signature_of(np.zeros((4, 9), np.float32))
    assert signature_of(a) != signature_of(a.astype(np.int32))
    # jit traces Python scalars as weak-typed arrays: a varying VALUE does
    # not recompile (must not count), but a varying TYPE does
    assert signature_of(a, 1) == signature_of(a, 2)
    assert signature_of(a, 1) != signature_of(a, 1.0)
    # non-numeric leaves only reach jit as static args — value-keyed
    assert signature_of(a, "relu") != signature_of(a, "gelu")
    assert signature_of(x=a) != signature_of(y=a)


def test_stable_fn_stays_within_budget():
    guard = RecompileGuard(1, on_excess="raise")
    fn = guard.wrap(jax.jit(lambda x: x * 2), label="double")
    for i in range(5):
        out = fn(jnp.full((8,), i, jnp.float32))
    assert float(out[0]) == 8.0
    assert guard.compilations == 1


def test_shape_unstable_fn_detected_and_raises():
    """The acceptance-criteria case: an intentionally shape-unstable jitted
    fn (a new sequence length every call — the padding bug this guard
    exists to catch) blows the budget."""
    guard = RecompileGuard(2, on_excess="raise")
    fn = guard.wrap(jax.jit(lambda x: x.sum()), label="unstable")
    fn(jnp.zeros((4,)))
    fn(jnp.zeros((5,)))  # second shape: still within budget
    with pytest.raises(RecompileBudgetExceeded) as err:
        fn(jnp.zeros((6,)))
    assert "3 distinct jit compilations" in str(err.value)
    assert "unstable" in str(err.value)


def test_warn_mode_logs_once_and_keeps_running(caplog):
    guard = RecompileGuard(1, on_excess="warn")
    fn = guard.wrap(jax.jit(lambda x: x + 1), label="warned")
    with caplog.at_level(logging.WARNING,
                         logger="finetune_controller_tpu.analysis.recompile_guard"):
        for n in range(2, 6):
            fn(jnp.zeros((n,)))
    warnings = [r for r in caplog.records if r.levelno == logging.WARNING]
    assert len(warnings) == 1  # one warning, not one per extra compile
    assert guard.compilations == 4


def test_budget_spans_labels():
    guard = RecompileGuard(2, on_excess="raise")
    f = guard.wrap(jax.jit(lambda x: x), label="a")
    g = guard.wrap(jax.jit(lambda x: -x), label="b")
    f(jnp.zeros((2,)))
    g(jnp.zeros((2,)))
    with pytest.raises(RecompileBudgetExceeded):
        g(jnp.zeros((3,)))
    assert guard.counts() == {"a": 1, "b": 2}


def test_guard_validates_config():
    with pytest.raises(ValueError):
        RecompileGuard(0)
    with pytest.raises(ValueError):
        RecompileGuard(1, on_excess="explode")


def test_trainer_threads_guard_behind_config_flag(devices8):
    from finetune_controller_tpu.data import synthetic_batches
    from finetune_controller_tpu.models import PRESETS, LoRAConfig
    from finetune_controller_tpu.parallel import MeshSpec
    from finetune_controller_tpu.train import Trainer, TrainConfig

    model_cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    train_cfg = TrainConfig(
        mode="lora", total_steps=4, batch_size=8, seq_len=16,
        recompile_budget=1, recompile_action="raise", prefetch=0,
    )
    mesh = MeshSpec(dp=2, fsdp=2, tp=2).build(devices8)
    trainer = Trainer(model_cfg, train_cfg, mesh=mesh)
    state = trainer.init_state()
    batches = synthetic_batches(8, 16, model_cfg.vocab_size, task="increment")
    # same batch structure every step: exactly one compile, budget holds
    for _ in range(3):
        state, _ = trainer.step(state, next(batches))
    assert trainer._recompile_guard.compilations == 1

    # a shape-unstable batch stream (seq_len drifts) must trip the guard
    short = {k: np.asarray(v)[:, :8] for k, v in next(batches).items()}
    with pytest.raises(RecompileBudgetExceeded):
        trainer.step(state, short)


def test_trainer_guard_off_by_default(devices8):
    from finetune_controller_tpu.models import PRESETS, LoRAConfig
    from finetune_controller_tpu.parallel import MeshSpec
    from finetune_controller_tpu.train import Trainer, TrainConfig

    model_cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    trainer = Trainer(
        model_cfg,
        TrainConfig(total_steps=1, batch_size=8, seq_len=16),
        mesh=MeshSpec(dp=2, fsdp=2, tp=2).build(devices8),
    )
    assert trainer._recompile_guard is None
