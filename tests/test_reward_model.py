"""The learned reward model (``task: reward``) — docs/preference.md.

Anchors: ``bradley_terry_loss`` is the standard pairwise objective (hand-math
pinned); :class:`RewardModelTrainer` rides the full SFT/DPO machinery with a
``{"lora", "head"}`` trainable tree whose head init (a=1, w=0, b=0) makes the
step-0 score exactly the mean completion likelihood; ``export_artifacts``
ships ``reward_head.msgpack`` and :class:`RewardScorer` loads it back — or,
for a staged serve prefix that carries only spec+checkpoints, restores the
head straight out of the latest checkpoint's trainable tree.  Slow: the
ISSUE-19 acceptance pair — a reward job trains to held-out pairwise accuracy
>= 0.7 on the increment task, and a remote-actor rlhf run scores its rollout
candidates through that model's batched ``reward_score`` RPC over the wire.
"""

from __future__ import annotations

import asyncio
import os
import threading

import numpy as np
import pytest

from finetune_controller_tpu.data.preference import (
    synthetic_preference_batches,
)
from finetune_controller_tpu.models.llama import PRESETS
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.prefs.losses import bradley_terry_loss
from finetune_controller_tpu.prefs.reward_trainer import (
    REWARD_HEAD_FILENAME,
    RewardModelTrainer,
)
from finetune_controller_tpu.prefs.rollout_plane import RewardScorer
from finetune_controller_tpu.train.trainer import TrainConfig


def _model_cfg(rank=4):
    return PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=rank))


def _train_cfg(**kw):
    kw.setdefault("task", "reward")
    kw.setdefault("batch_size", 4)
    kw.setdefault("seq_len", 16)
    kw.setdefault("warmup_steps", 1)
    kw.setdefault("heartbeat_interval_s", 0)
    return TrainConfig(**kw)


# ---------------------------------------------------------------------------
# the objective
# ---------------------------------------------------------------------------


def test_bradley_terry_loss_hand_math():
    import jax.numpy as jnp

    chosen = jnp.asarray([2.0, 0.0], jnp.float32)
    rejected = jnp.asarray([0.0, 1.0], jnp.float32)
    loss, metrics = bradley_terry_loss(chosen, rejected)
    # margins [2, -1]: loss = mean(-log sigmoid(margin))
    expect = -(np.log(1 / (1 + np.exp(-2.0)))
               + np.log(1 / (1 + np.exp(1.0)))) / 2
    assert abs(float(loss) - expect) < 1e-5
    assert float(metrics["bt_accuracy"]) == 0.5  # one pair ranked correctly
    assert abs(float(metrics["reward_margin"]) - 0.5) < 1e-6
    assert abs(float(metrics["score_chosen"]) - 1.0) < 1e-6
    # perfectly-ranked pairs: accuracy 1, loss below ln(2)
    loss2, m2 = bradley_terry_loss(chosen, rejected - 2.0)
    assert float(m2["bt_accuracy"]) == 1.0
    assert float(loss2) < float(np.log(2.0))


def test_reward_trainer_mode_guards():
    with pytest.raises(ValueError, match="mode='lora'"):
        RewardModelTrainer(_model_cfg(), _train_cfg(mode="full"))
    moe = PRESETS["tiny-moe-test"].replace(lora=LoRAConfig(rank=4))
    with pytest.raises(ValueError, match="MoE"):
        RewardModelTrainer(moe, _train_cfg())


def test_reward_trainer_head_init_and_step_smoke():
    trainer = RewardModelTrainer(_model_cfg(), _train_cfg(total_steps=4))
    state = trainer.init_state()
    trainable = trainer.state_to_host(state, fields=("trainable",))[
        "trainable"
    ]
    assert set(trainable) == {"lora", "head"}
    head = trainable["head"]
    vocab = int(trainer.model_cfg.vocab_size)
    # a=1, w=0, b=0: the step-0 score IS the mean completion likelihood
    assert float(head["a"]) == 1.0 and float(head["b"]) == 0.0
    assert head["w"].shape == (vocab,) and not np.any(head["w"])
    batches = synthetic_preference_batches(4, 16, vocab, seed=0)
    state, metrics = trainer.step(state, next(batches))
    for key in ("loss", "bt_accuracy", "accuracy", "reward_margin"):
        assert np.isfinite(float(metrics[key])), key
    assert float(metrics["accuracy"]) == float(metrics["bt_accuracy"])
    # the loss moves the head too, not just the trunk adapter
    state, _ = trainer.step(state, next(batches))
    head2 = trainer.state_to_host(state, fields=("trainable",))[
        "trainable"
    ]["head"]
    assert np.any(head2["w"]) or float(head2["b"]) != 0.0


def test_export_artifacts_and_scorer_roundtrip(tmp_path):
    from finetune_controller_tpu.transport.builders import tiny_test

    trainer = RewardModelTrainer(_model_cfg(), _train_cfg(total_steps=2))
    state = trainer.init_state()
    trainer.export_artifacts(state, str(tmp_path))
    assert os.path.exists(tmp_path / REWARD_HEAD_FILENAME)
    model, variables = tiny_test()
    scorer = RewardScorer.from_artifacts(str(tmp_path), model, variables)
    scores = scorer.score([
        {"prompt": [1, 2, 3], "completion": [4, 5, 6]},
        {"prompt": [1, 2, 3], "completion": [9, 0, 2]},
    ])
    assert len(scores) == 2 and all(np.isfinite(scores))
    # freshly-initialised head: score == mean completion likelihood, so two
    # different completions of one prompt almost surely score differently
    assert scores[0] != scores[1]


def test_scorer_checkpoint_fallback_without_msgpack(tmp_path):
    """A staged serve prefix carries only spec + checkpoints
    (``serve/loader.py::fetch_promoted``): the scorer must rebuild the head
    from the latest checkpoint's trainable tree."""
    from finetune_controller_tpu.transport.builders import tiny_test

    trainer = RewardModelTrainer(
        _model_cfg(),
        _train_cfg(total_steps=2, checkpoint_every=2, log_every=2,
                   learning_rate=1e-3, prefetch=0),
    )
    vocab = int(trainer.model_cfg.vocab_size)
    batches = synthetic_preference_batches(4, 16, vocab, seed=0)
    trainer.fit(batches, str(tmp_path), resume=False)
    assert not os.path.exists(tmp_path / REWARD_HEAD_FILENAME)
    model, variables = tiny_test()
    scorer = RewardScorer.from_artifacts(str(tmp_path), model, variables)
    assert scorer._head["w"].shape == (vocab,)
    scores = scorer.score([{"prompt": [3, 4], "completion": [5, 6]}])
    assert np.isfinite(scores[0])


# ---------------------------------------------------------------------------
# slow: the ISSUE-19 acceptance pair
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_reward_model_trains_to_heldout_pairwise_accuracy(tmp_path):
    """``task: reward`` learns the increment ranking: held-out Bradley–Terry
    pairwise accuracy >= 0.7 (the promotion gate's number)."""
    trainer = RewardModelTrainer(
        _model_cfg(),
        _train_cfg(total_steps=400, batch_size=8, learning_rate=5e-3,
                   warmup_steps=5, eval_steps=8),
    )
    vocab = int(trainer.model_cfg.vocab_size)
    batches = synthetic_preference_batches(8, 16, vocab, seed=0)
    state = trainer.init_state()
    acc = 0.0
    for i in range(400):
        state, _ = trainer.step(state, next(batches))
        if i >= 199 and (i + 1) % 20 == 0:
            held = synthetic_preference_batches(8, 16, vocab, seed=100_003)
            acc = float(
                trainer.evaluate(state, held)["eval_bt_accuracy"]
            )
            if acc >= 0.7:
                break
    assert acc >= 0.7, f"held-out bt_accuracy plateaued at {acc}"
    # the export of the TRAINED job round-trips through the scorer and
    # still ranks held-out pairs — over the trunk WITH its adapter, which
    # is what serving deploys (the head was trained over those logits)
    trainer.export_artifacts(state, str(tmp_path))
    from finetune_controller_tpu.data.preference import make_increment_pair

    variables = trainer._assemble(state.frozen, state.trainable)
    scorer = RewardScorer.from_artifacts(
        str(tmp_path), trainer.model, variables
    )
    rng = np.random.default_rng(7)
    margins, correct = [], 0
    for _ in range(32):
        prompt, chosen, rejected = make_increment_pair(rng, 16, vocab)
        sc, sr = scorer.score([
            {"prompt": prompt, "completion": chosen},
            {"prompt": prompt, "completion": rejected},
        ])
        margins.append(sc - sr)
        correct += sc > sr
    assert np.mean(margins) > 0
    assert correct >= 20, f"exported scorer ranked only {correct}/32"


@pytest.mark.slow
def test_remote_rlhf_scored_by_served_reward_model(tmp_path, monkeypatch):
    """End to end over real wires: a served reward model answers the batched
    ``reward_score`` RPC, a remote rollout worker (separate process) scores
    its candidate completions through it, and the learner trains on the
    resulting pairs.  The oracle bootstrap never runs — scores come from the
    learned head."""
    from finetune_controller_tpu.prefs.dpo_trainer import DPOTrainer
    from finetune_controller_tpu.prefs.learner import RolloutConfig
    from finetune_controller_tpu.prefs.rollout_plane import (
        build_remote_rlhf_loop,
    )
    from finetune_controller_tpu.transport.worker import (
        WorkerSpec,
        build_worker,
    )

    monkeypatch.setenv("FTC_TRACE_ID", "")
    reward_dir = tmp_path / "reward"
    reward_dir.mkdir()
    rm = RewardModelTrainer(_model_cfg(), _train_cfg(total_steps=2))
    rm.export_artifacts(rm.init_state(), str(reward_dir))

    # the reward fleet tenant, served from a background loop in this process
    # so the test can read its scorer's counters directly
    spec = WorkerSpec(
        job_id="reward-svc", replica_id="rw0",
        sandbox=str(tmp_path / "reward_sandbox"),
        builder="tiny_test", builder_kwargs={},
        engine=dict(slots=2, prompt_buckets=[8], max_new_tokens=8),
        batcher={},
        reward={"artifacts_dir": str(reward_dir)},
        warm_start=False,
    )
    os.makedirs(spec.sandbox, exist_ok=True)
    server = build_worker(spec, exit_on_drain=False)
    loop = asyncio.new_event_loop()
    loop_thread = threading.Thread(target=loop.run_forever, daemon=True)
    loop_thread.start()
    port = asyncio.run_coroutine_threadsafe(server.start(), loop).result(120)

    cfg = TrainConfig(
        task="rlhf", batch_size=2, seq_len=16, total_steps=10**9,
        warmup_steps=1, learning_rate=1e-3, log_every=10**9,
        checkpoint_every=10**9, prefetch=0, heartbeat_interval_s=0,
        rollout_workers=1,
    )
    learner = DPOTrainer(_model_cfg(), cfg)
    stream, plane, buffer = build_remote_rlhf_loop(
        learner, str(tmp_path / "rlhf"),
        # reward_port set ⇒ the worker scores through the RPC and never
        # builds the oracle bootstrap (build_rollout_worker)
        rollout=RolloutConfig(
            pairs_per_round=4, min_fill=4, buffer_capacity=128,
            max_new_tokens=8, slots=2, temperature=0.9,
            reward_host="127.0.0.1", reward_port=port,
        ),
        model_spec={"preset": "tiny-test", "lora": {"rank": 4}},
    )
    try:
        state = learner.init_state()
        batch = next(stream)
        state, metrics = learner.step(state, batch)
        assert np.isfinite(float(metrics["reward_margin"]))
        # every buffered pair was scored by the served model over the wire
        assert server.reward_scorer.scored_total > 0
        with plane._lock:
            pairs = list(buffer._pairs)
        assert pairs
        assert all(
            np.isfinite(p.reward_chosen) and np.isfinite(p.reward_rejected)
            for p in pairs
        )
        assert all(
            p.reward_chosen >= p.reward_rejected for p in pairs
        )
    finally:
        plane.close()
        asyncio.run_coroutine_threadsafe(server.stop(), loop).result(60)
        loop.call_soon_threadsafe(loop.stop)
        loop_thread.join(timeout=10)
