"""S3 object-store backend tests against an in-process fake S3 server.

The reference's S3 path (``app/utils/S3Handler.py``) has zero tests —
SURVEY.md §4.  Here the fake server *re-derives the SigV4 signature of every
request* with the known secret and rejects mismatches with 403, so these
contract tests pin the signer, not just the transport; a known-answer test
additionally pins the signer against the official AWS documentation vector.
"""

import datetime
import hashlib
import urllib.parse
import xml.etree.ElementTree as ET

from aiohttp import web
from aiohttp.test_utils import TestServer

from conftest import run_async as run
from finetune_controller_tpu.controller.objectstore import (
    artifacts_prefix,
    build_object_store,
    parse_uri,
)
from finetune_controller_tpu.controller.s3 import (
    EMPTY_SHA256,
    S3ObjectStore,
    sigv4_headers,
)

ACCESS, SECRET, REGION = "AKIDFAKE", "fake-secret-key", "us-test-1"


def test_sigv4_known_answer_vector():
    """Official AWS SigV4 example (docs 'Signature Version 4 signing
    process', GET iam ListUsers, 2015-08-30): the full HMAC chain must
    reproduce the documented signature."""
    headers = sigv4_headers(
        "GET",
        "iam.amazonaws.com",
        "/",
        [("Action", "ListUsers"), ("Version", "2010-05-08")],
        payload_hash=EMPTY_SHA256,
        access_key="AKIDEXAMPLE",
        secret_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        region="us-east-1",
        service="iam",
        amz_date="20150830T123600Z",
        extra_headers={
            "content-type": "application/x-www-form-urlencoded; charset=utf-8"
        },
        include_content_sha=False,
    )
    assert headers["authorization"].endswith(
        "Signature=5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b"
        "5924a6f2b5d7"
    )
    assert "content-type;host;x-amz-date" in headers["authorization"]


def make_fake_s3(page_size: int = 2):
    """Minimal S3 REST API: signed PUT/GET/HEAD/DELETE, ListObjectsV2 with
    continuation tokens, x-amz-copy-source, and multipart upload.  Every
    request's SigV4 signature is re-derived and verified."""
    blobs: dict[tuple[str, str], bytes] = {}
    uploads: dict[str, list[bytes]] = {}
    seen_auth: list[str] = []

    def verify_signature(request: web.Request, body: bytes) -> str | None:
        auth = request.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            return "missing sigv4 authorization"
        fields = dict(
            part.strip().split("=", 1)
            for part in auth[len("AWS4-HMAC-SHA256 "):].split(",")
        )
        signed_names = fields["SignedHeaders"].split(";")
        payload_hash = request.headers.get("x-amz-content-sha256", "")
        if payload_hash not in ("UNSIGNED-PAYLOAD",):
            if hashlib.sha256(body).hexdigest() != payload_hash:
                return "payload hash mismatch"
        expect = sigv4_headers(
            request.method,
            request.headers["Host"],
            request.path,
            sorted((k, v) for k, v in request.query.items()),
            payload_hash=payload_hash,
            access_key=ACCESS,
            secret_key=SECRET,
            region=REGION,
            amz_date=request.headers["x-amz-date"],
            extra_headers={
                k: request.headers[k]
                for k in signed_names
                if k not in ("host", "x-amz-date", "x-amz-content-sha256")
            },
        )
        if expect["authorization"] != auth:
            return f"signature mismatch: {expect['authorization']} != {auth}"
        seen_auth.append(auth)
        return None

    async def handler(request: web.Request) -> web.Response:
        body = await request.read()
        err = verify_signature(request, body)
        if err:
            return web.Response(status=403, text=err)
        parts = request.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""

        if request.method == "POST" and "uploads" in request.query:
            upload_id = f"up-{len(uploads)}"
            uploads[upload_id] = []
            return web.Response(
                text=f"<InitiateMultipartUploadResult><UploadId>{upload_id}"
                     "</UploadId></InitiateMultipartUploadResult>"
            )
        if request.method == "PUT" and "partNumber" in request.query:
            parts_list = uploads[request.query["uploadId"]]
            idx = int(request.query["partNumber"]) - 1
            while len(parts_list) <= idx:
                parts_list.append(b"")
            parts_list[idx] = body
            return web.Response(headers={"ETag": f'"etag-{idx}"'})
        if request.method == "POST" and "uploadId" in request.query:
            parts_list = uploads.pop(request.query["uploadId"])
            blobs[(bucket, key)] = b"".join(parts_list)
            return web.Response(
                text="<CompleteMultipartUploadResult/>"
            )
        if request.method == "DELETE" and "uploadId" in request.query:
            uploads.pop(request.query["uploadId"], None)
            return web.Response(status=204)

        if request.method == "POST" and "delete" in request.query:
            # DeleteObjects batch API: XML body of keys, delete each
            root = ET.fromstring(body)
            deleted = []
            for obj in root.iter():
                if obj.tag.split("}")[-1] == "Key":
                    blobs.pop((bucket, obj.text or ""), None)
                    deleted.append(obj.text or "")
            return web.Response(
                text="<DeleteResult>"
                     + "".join(f"<Deleted><Key>{k}</Key></Deleted>" for k in deleted)
                     + "</DeleteResult>"
            )
        if request.method == "PUT" and "x-amz-copy-source" in request.headers:
            src = urllib.parse.unquote(
                request.headers["x-amz-copy-source"]
            ).lstrip("/")
            src_bucket, _, src_key = src.partition("/")
            data = blobs.get((src_bucket, src_key))
            if data is None:
                return web.Response(status=404)
            blobs[(bucket, key)] = data
            return web.Response(text="<CopyObjectResult/>")
        if request.method == "PUT":
            blobs[(bucket, key)] = body
            return web.Response()
        if request.method == "HEAD":
            if (bucket, key) not in blobs:
                return web.Response(status=404)
            return web.Response(
                headers={"Content-Length": str(len(blobs[(bucket, key)]))}
            )
        if request.method == "DELETE":
            if (bucket, key) not in blobs:
                return web.Response(status=404)
            del blobs[(bucket, key)]
            return web.Response(status=204)
        if request.method == "GET" and not key and "list-type" in request.query:
            prefix = request.query.get("prefix", "")
            items = sorted(
                k for (b, k) in blobs if b == bucket and k.startswith(prefix)
            )
            start = int(request.query.get("continuation-token") or 0)
            page = items[start: start + page_size]
            truncated = start + page_size < len(items)
            now = datetime.datetime(2026, 1, 1).isoformat() + "Z"
            contents = "".join(
                f"<Contents><Key>{k}</Key><Size>{len(blobs[(bucket, k)])}"
                f"</Size><LastModified>{now}</LastModified></Contents>"
                for k in page
            )
            extra = (
                f"<IsTruncated>true</IsTruncated><NextContinuationToken>"
                f"{start + page_size}</NextContinuationToken>"
                if truncated else "<IsTruncated>false</IsTruncated>"
            )
            return web.Response(
                text=f"<ListBucketResult>{contents}{extra}</ListBucketResult>"
            )
        if request.method == "GET":
            data = blobs.get((bucket, key))
            if data is None:
                return web.Response(status=404)
            return web.Response(body=data)
        return web.Response(status=400, text=f"unhandled {request.method}")

    app = web.Application(client_max_size=1 << 30)
    app.router.add_route("*", "/{tail:.*}", handler)
    return app, blobs, seen_auth


async def _store(page_size: int = 2, **kw):
    app, blobs, seen_auth = make_fake_s3(page_size)
    server = TestServer(app)
    await server.start_server()

    async def creds():
        return ACCESS, SECRET, None

    store = S3ObjectStore(
        endpoint=str(server.make_url("")).rstrip("/"),
        region=REGION,
        creds_fn=creds,
        **kw,
    )
    return store, server, blobs, seen_auth


def test_s3_roundtrip_list_copy_delete():
    async def go():
        store, server, blobs, seen_auth = await _store()
        # the reference's exact layout: s3://bucket/finetune_jobs/{user}/{job}/
        prefix = artifacts_prefix("artifacts", "u", "job1")
        await store.put_bytes(f"{prefix}/a.bin", b"A" * 10)
        await store.put_bytes(f"{prefix}/sub/b.bin", b"B" * 20)
        await store.put_bytes(f"{prefix}/c.csv", b"step,loss\n1,2.0\n")

        assert await store.exists(f"{prefix}/a.bin")
        assert not await store.exists(f"{prefix}/missing")
        assert await store.get_bytes(f"{prefix}/sub/b.bin") == b"B" * 20
        assert ("artifacts", "finetune_jobs/u/job1/artifacts/a.bin") in blobs

        objs = await store.list_prefix(prefix)  # paginated (page_size=2)
        assert len(objs) == 3
        assert {parse_uri(o["uri"])[1].rsplit("/", 1)[-1] for o in objs} == {
            "a.bin", "b.bin", "c.csv"
        }
        assert all(o["mtime"] > 0 for o in objs)

        # server-side promotion copy (reference: S3Handler.py:375-439)
        dst = "obj://deploy/models/x/job1"
        n = await store.copy_prefix(prefix, dst)
        assert n == 3
        assert await store.get_bytes(f"{dst}/sub/b.bin") == b"B" * 20

        assert await store.delete_prefix(prefix) == 3
        assert await store.list_prefix(prefix) == []
        assert len(seen_auth) > 10  # every request carried a verified sig
        await store.close()
        await server.close()

    run(go())


def test_s3_streaming_files_and_multipart(tmp_path):
    async def go():
        # small multipart threshold exercises the Create/Part/Complete path
        store, server, blobs, _ = await _store(
            multipart_threshold=1 << 20, part_size=1 << 20
        )
        big = bytes(range(256)) * 8192  # 2 MiB -> 2 parts
        src = tmp_path / "big.bin"
        src.write_bytes(big)
        await store.put_file("obj://datasets/big.bin", src)
        assert blobs[("datasets", "big.bin")] == big

        chunks = []
        async for chunk in store.get_chunks("obj://datasets/big.bin", 1 << 16):
            chunks.append(chunk)
        assert b"".join(chunks) == big and len(chunks) > 1

        dest = tmp_path / "out.bin"
        n = await store.get_file("obj://datasets/big.bin", dest)
        assert n == len(big) and dest.read_bytes() == big

        # async-iterator upload (the URL→store dataset streaming path)
        async def gen():
            for i in range(4):
                yield bytes([i]) * 1000

        total = await store.put_stream("obj://datasets/gen.bin", gen())
        assert total == 4000 and len(blobs[("datasets", "gen.bin")]) == 4000

        # shared helpers from the base class work against S3 too
        await store.put_bytes(
            "obj://artifacts/j/metrics.csv", b"step,loss\n1,2.5\n2,2.0\n"
        )
        res = await store.get_metrics_records("obj://artifacts/j")
        records, _uri = res
        assert records[1]["loss"] == 2.0

        dest_zip = tmp_path / "a.zip"
        await store.put_bytes("obj://artifacts/j/w.bin", b"w" * 100)
        n = await store.zip_prefix_to_path("obj://artifacts/j", dest_zip)
        assert n == 2
        import zipfile

        assert sorted(zipfile.ZipFile(dest_zip).namelist()) == [
            "metrics.csv", "w.bin"
        ]

        await store.close()
        await server.close()

    run(go())


def test_s3_retry_batch_delete_and_exists_errors(tmp_path):
    """Round-5 hardening: transient 5xx retries with backoff, DeleteObjects
    batching, exists() raising (not False) on server errors, and
    signature-consistent wire encoding for keys containing spaces."""

    async def go():
        app, blobs, _seen = make_fake_s3(page_size=100)
        fail = {"n": 0}
        requests_log: list[tuple[str, bool]] = []

        @web.middleware
        async def flaky(request, handler):
            requests_log.append((request.method, "delete" in request.query))
            if fail["n"] > 0:
                fail["n"] -= 1
                return web.Response(status=503, text="transient")
            return await handler(request)

        app.middlewares.append(flaky)
        server = TestServer(app)
        await server.start_server()

        async def creds():
            return ACCESS, SECRET, None

        store = S3ObjectStore(
            endpoint=str(server.make_url("")).rstrip("/"),
            region=REGION, creds_fn=creds,
        )
        store.retry_base_delay = 0.0  # no real sleeping in tests

        # two 503s, then success — the put survives
        fail["n"] = 2
        await store.put_bytes("obj://datasets/r.bin", b"r" * 64)
        assert blobs[("datasets", "r.bin")] == b"r" * 64

        # whole-transfer retry on download-to-file
        fail["n"] = 1
        dest = tmp_path / "r.bin"
        n = await store.get_file("obj://datasets/r.bin", dest)
        assert n == 64 and dest.read_bytes() == b"r" * 64
        assert not dest.with_name("r.bin.tmp").exists()

        # persistent server error: exists must raise, not read as "absent"
        fail["n"] = 10**6
        try:
            await store.exists("obj://datasets/r.bin")
            raise AssertionError("expected IOError from exists() on 5xx")
        except IOError as e:
            assert "503" in str(e)
        fail["n"] = 0

        # keys with spaces: wire query encoding must match the signature
        # (MinIO-style gateways canonicalize '+' literally)
        prefix = "obj://datasets/sp aced"
        await store.put_bytes(f"{prefix}/a b.bin", b"x")
        await store.put_bytes(f"{prefix}/c.bin", b"y")
        objs = await store.list_prefix(prefix)
        assert len(objs) == 2

        # DeleteObjects batching: 2 keys -> ONE POST ?delete request
        requests_log.clear()
        assert await store.delete_prefix(prefix) == 2
        deletes = [r for r in requests_log if r[1]]
        assert deletes == [("POST", True)]
        assert await store.list_prefix(prefix) == []

        await store.close()
        await server.close()

    run(go())


def test_s3_tampered_secret_rejected():
    """A client signing with the wrong secret must get 403 from the fake —
    proving the fake actually verifies instead of rubber-stamping."""

    async def go():
        store, server, _, _ = await _store()

        async def bad_creds():
            return ACCESS, "wrong-secret", None

        store._creds_fn = bad_creds
        try:
            await store.put_bytes("obj://datasets/x", b"data")
            raise AssertionError("expected signature rejection")
        except IOError as e:
            assert "403" in str(e)
        await store.close()
        await server.close()

    run(go())


def test_build_object_store_s3_factory():
    from finetune_controller_tpu.controller.config import Settings

    store = build_object_store(
        Settings(
            object_store_backend="s3",
            s3_endpoint="http://fake:1",
            s3_region="eu-west-7",
            s3_bucket_prefix="acme-",
        )
    )
    assert isinstance(store, S3ObjectStore)
    assert store.endpoint == "http://fake:1"
    assert store.region == "eu-west-7"
    assert store._path("obj://datasets/a/b") == "/acme-datasets/a/b"
