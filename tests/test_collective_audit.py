"""AOT collective audit (analysis/collective_audit.py) vs the Collective
catalog in docs/performance.md.

Layers:

* ``parse_catalog`` unit fixtures — heading scoping, row parsing, the
  ``none`` sentinel, the absent-heading opt-out;
* ``diff_catalog`` is PURE (observed sets × catalog text), so every drift
  direction is provable without compiling anything: an undocumented
  collective, a documented-but-vanished one, a missing row, a catalog
  topology the audit no longer simulates — including the
  catalog-mutation satellite (drop `all-gather` from the fsdp2/train row
  of the REAL doc text and the diff turns red);
* slow: ``full_audit()`` compiles the train + serve steps on all three
  simulated meshes (one subprocess each, fake CPU devices) and the
  both-direction diff against the real catalog is EMPTY — the
  acceptance-criteria e2e.
"""

import textwrap

import pytest

from finetune_controller_tpu.analysis.collective_audit import (
    STEPS,
    TOPOLOGIES,
    catalog_path,
    diff_catalog,
    full_audit,
    parse_catalog,
)


def _real_catalog():
    text = catalog_path().read_text()
    rows, heading = parse_catalog(text)
    assert heading > 0
    return text, rows


def _observed_from(rows):
    """Recorded observed sets mirroring the parsed catalog — the pure
    mutation tests re-diff these against EDITED catalog text (the slow e2e
    proves these equal the compiled reality)."""
    return {
        topo: {step: sorted(rows[(topo, step)]) for step in STEPS}
        for topo in TOPOLOGIES
    }


# ---------------------------------------------------------------------------
# parse_catalog
# ---------------------------------------------------------------------------


def test_parse_catalog_basic():
    text = textwrap.dedent("""\
        # Performance

        ## Collective catalog

        | topology | step | collectives |
        |----------|------|-------------|
        | dp2 | train | `all-reduce` |
        | dp2 | serve | none |
        | fsdp2 | train | `all-gather`, `all-reduce` |
    """)
    rows, heading = parse_catalog(text)
    assert heading == 3
    assert rows[("dp2", "train")] == {"all-reduce"}
    assert rows[("dp2", "serve")] == set()
    assert rows[("fsdp2", "train")] == {"all-gather", "all-reduce"}


def test_parse_catalog_scoped_to_heading():
    """Rows after the NEXT same-level heading belong to someone else."""
    text = textwrap.dedent("""\
        ## Collective catalog

        | topology | step | collectives |
        |---|---|---|
        | dp2 | train | `all-reduce` |

        ## Something else

        | dp4 | train | `all-gather` |
    """)
    rows, _ = parse_catalog(text)
    assert ("dp2", "train") in rows
    assert ("dp4", "train") not in rows


def test_parse_catalog_absent_heading_opts_out():
    assert parse_catalog("# Performance\n\nno catalog here\n") == ({}, 0)


def test_real_catalog_covers_every_audited_pair():
    _text, rows = _real_catalog()
    for topo in TOPOLOGIES:
        for step in STEPS:
            assert (topo, step) in rows, (topo, step)


# ---------------------------------------------------------------------------
# diff_catalog (pure — every direction, no compilation)
# ---------------------------------------------------------------------------


def test_recorded_sets_conform_to_real_catalog():
    _text, rows = _real_catalog()
    assert diff_catalog(_observed_from(rows), rows) == []


def test_dropped_documented_collective_turns_red():
    """The catalog-mutation satellite: delete `all-gather` from the REAL
    doc's fsdp2/train row and the (recorded) compiled set now contains an
    op the catalog does not document."""
    text, rows = _real_catalog()
    observed = _observed_from(rows)
    row = "| fsdp2 | train | `all-gather`, `all-reduce`, `all-to-all` |"
    assert row in text
    mutated = text.replace(
        row, "| fsdp2 | train | `all-reduce`, `all-to-all` |"
    )
    mutated_rows, _ = parse_catalog(mutated)
    drift = diff_catalog(observed, mutated_rows)
    assert any(
        "'all-gather'" in m and "does not document" in m for m in drift
    ), drift


def test_undocumented_collective_turns_red():
    """The headline bug class: a NEW collective appears in the compiled
    step (the unexpected full-param all-gather)."""
    _text, rows = _real_catalog()
    observed = _observed_from(rows)
    observed["dp2"]["train"] = sorted(
        set(observed["dp2"]["train"]) | {"all-gather"}
    )
    drift = diff_catalog(observed, rows)
    assert any(
        "dp2/train" in m and "'all-gather'" in m
        and "does not document" in m for m in drift
    ), drift


def test_vanished_documented_collective_turns_red():
    """The other direction: the step no longer compiles a documented op."""
    _text, rows = _real_catalog()
    observed = _observed_from(rows)
    observed["dp2tp2"]["serve"] = [
        op for op in observed["dp2tp2"]["serve"] if op != "collective-permute"
    ]
    drift = diff_catalog(observed, rows)
    assert any(
        "no longer contains" in m and "'collective-permute'" in m
        for m in drift
    ), drift


def test_missing_catalog_row_turns_red():
    _text, rows = _real_catalog()
    observed = _observed_from(rows)
    observed["fsdp4"] = {"train": ["all-reduce"], "serve": []}
    drift = diff_catalog(observed, rows)
    assert any("fsdp4/train" in m and "no Collective catalog row" in m
               for m in drift), drift


def test_unaudited_catalog_topology_turns_red():
    """A documented topology the audit stopped simulating is drift too."""
    _text, rows = _real_catalog()
    extra = dict(rows)
    extra[("fsdp8", "train")] = {"all-gather"}
    drift = diff_catalog(_observed_from(rows), extra)
    assert any("'fsdp8'" in m and "does not simulate" in m for m in drift), \
        drift


# ---------------------------------------------------------------------------
# the real thing (slow: three subprocess compiles)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_full_audit_matches_catalog_exactly():
    """Acceptance criteria: AOT audit on >=3 topologies; the compiled HLO
    collective set matches docs/performance.md exactly, both ways."""
    observed = full_audit()
    assert len(observed) >= 3
    for topo, steps in observed.items():
        assert set(steps) == set(STEPS), topo
    _text, rows = _real_catalog()
    assert diff_catalog(observed, rows) == []
