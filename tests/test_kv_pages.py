"""Paged KV cache: allocator invariants + paged-engine numerics (ISSUE 11).

The acceptance anchors: greedy AND sampled decode through the page pool are
BIT-IDENTICAL to the unpaged path and to single-request ``cached_generate``
across staggered mixed-length batches, page-boundary-straddling prefills
(copy-on-write suffix splices), evict-refill page reuse (no stale reads),
and mid-flight prefix-entry eviction — while pool exhaustion surfaces as
queueing backpressure (and 429s with Retry-After past the queue), never as
an OOM or a corrupted lane.
"""

from __future__ import annotations

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import run_async
from finetune_controller_tpu.models.generate import cached_generate
from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.serve.batcher import Batcher, QueueFull
from finetune_controller_tpu.serve.engine import (
    BatchEngine,
    EngineConfig,
    GenRequest,
)
from finetune_controller_tpu.serve.kv_pages import (
    HostPagePool,
    HostRun,
    KVPagePool,
    PageRun,
    PoolExhausted,
)
from finetune_controller_tpu.serve.prefix_cache import PrefixCache


@pytest.fixture(scope="module")
def tiny_model():
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    model = LlamaForCausalLM(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 4), jnp.int32)
    )
    return model, variables


def _paged_engine(model, variables, **kw):
    defaults = dict(slots=4, prompt_buckets=(8, 16), max_new_tokens=24,
                    page_tokens=8)
    defaults.update(kw)
    return BatchEngine(model, variables, EngineConfig(**defaults))


def _baseline(model, variables, prompt, n, **kw):
    out = cached_generate(
        model, variables, jnp.asarray([prompt], jnp.int32),
        max_new_tokens=n, **kw,
    )
    return list(np.asarray(out[0, len(prompt):]))


# ---------------------------------------------------------------------------
# KVPagePool allocator invariants (pure host logic, no jax)
# ---------------------------------------------------------------------------


def test_pool_alloc_release_roundtrip():
    pool = KVPagePool(num_pages=8, page_tokens=4, page_bytes=100)
    assert pool.usable_pages == 7 and pool.free_count == 7
    pool.reserve(3)
    pages = [pool.alloc_reserved() for _ in range(3)]
    assert 0 not in pages  # scratch is never handed out
    assert pool.free_count == 4 and pool.used_count == 3
    assert pool.reserved_outstanding == 0
    pool.lane_release(pages)
    assert pool.free_count == 7 and pool.used_count == 0


def test_pool_reserve_respects_slack_and_raises():
    pool = KVPagePool(num_pages=6, page_tokens=4)
    pool.reserve(5)
    assert pool.slack() == 0
    with pytest.raises(PoolExhausted):
        pool.reserve(1)
    assert pool.exhaustions_total == 1
    pool.unreserve(5)
    assert pool.slack() == 5


def test_pool_cache_only_pages_count_toward_slack_and_evict_on_demand():
    """Pages held ONLY by prefix-cache entries are evictable capacity: they
    count in the admission slack and free when the entry releases them."""
    pool = KVPagePool(num_pages=6, page_tokens=4, page_bytes=10)
    pool.reserve(3)
    pages = [pool.alloc_reserved() for _ in range(3)]
    charged = pool.cache_ref(pages)
    assert charged == 3  # first cache reference charges each page once
    pool.lane_release(pages)          # lane done; entry keeps them resident
    assert pool.free_count == 2
    assert pool.slack() == 5          # 2 free + 3 evictable
    # a second entry sharing two of the pages charges nothing new
    assert pool.cache_ref(pages[:2]) == 0
    assert pool.cache_release(pages[:2]) == 0  # still held by entry 1
    evicted = {"n": 0}

    def evict_one():
        if evicted["n"] >= 1:
            return False
        evicted["n"] += 1
        pool.cache_release(pages)
        return True

    pool.reserve(4)
    got = [pool.alloc_reserved(evict_one) for _ in range(4)]
    assert len(set(got)) == 4 and evicted["n"] == 1


def test_pool_shared_count_tracks_multi_holder_pages():
    pool = KVPagePool(num_pages=6, page_tokens=4)
    pool.reserve(2)
    pages = [pool.alloc_reserved() for _ in range(2)]
    assert pool.shared_count == 0
    pool.lane_ref(pages[0])  # a second lane splices it
    assert pool.shared_count == 1
    pool.cache_ref(pages)
    assert pool.shared_count == 2


# ---------------------------------------------------------------------------
# Paged engine: the bit-identity anchors
# ---------------------------------------------------------------------------


def test_paged_batching_invariance_mixed_staggered(tiny_model):
    """Greedy tokens through the page pool — mixed prompt lengths, requests
    joining mid-flight — are bit-identical to single-request
    cached_generate AND to the unpaged engine, for every request."""
    model, variables = tiny_model
    prompts = [
        [5, 9, 2, 7],
        [1, 3, 3, 8, 2, 2],
        [7, 7, 7],
        [11, 4, 9, 1, 2, 3, 4, 5, 6, 0, 2, 1],  # second bucket
        [2, 13],
    ]
    reqs = [
        GenRequest(request_id=f"r{i}", tokens=p, max_new_tokens=6 + 2 * i)
        for i, p in enumerate(prompts)
    ]
    paged = _paged_engine(model, variables, slots=2, pool_pages=12)
    unpaged = BatchEngine(model, variables, EngineConfig(
        slots=2, prompt_buckets=(8, 16), max_new_tokens=24))
    res_p = paged.run(list(reqs))
    res_u = unpaged.run(list(reqs))
    for i, p in enumerate(prompts):
        want = _baseline(model, variables, p, 6 + 2 * i)
        assert res_p[f"r{i}"].generated == want, f"paged diverged on r{i}"
        assert res_u[f"r{i}"].generated == want
    # the run drained: every page returned to the free list
    stats = paged.kv_page_stats()
    assert stats["pages_used"] == 0
    assert stats["pages_free"] == stats["pages_total"]


def test_paged_sampled_decode_reproducible(tiny_model):
    """Sampled decode through the pool reproduces the per-request
    PRNGKey(seed) stream bit-for-bit, independent of batch-mates."""
    model, variables = tiny_model
    reqs = [
        GenRequest(request_id=f"s{i}", tokens=[3 + i, 1, 4, 1], seed=40 + i,
                   temperature=0.8, top_k=7, max_new_tokens=8)
        for i in range(4)
    ]
    eng = _paged_engine(model, variables, slots=4, pool_pages=20)
    res = eng.run(reqs)
    for i in range(4):
        want = _baseline(
            model, variables, [3 + i, 1, 4, 1], 8,
            temperature=0.8, top_k=7, rng=jax.random.PRNGKey(40 + i),
        )
        assert res[f"s{i}"].generated == want


def test_page_boundary_straddling_prefill_and_cow_splice(tiny_model):
    """A page size that divides NEITHER the buckets NOR the reuse length:
    suffix prefills straddle page boundaries and the prefix splice must
    copy-on-write the boundary page.  Outputs stay bit-identical and the
    CoW copy actually happens."""
    model, variables = tiny_model
    eng = _paged_engine(
        model, variables, slots=2, page_tokens=7, pool_pages=16,
        prefix_cache_bytes=1 << 20,
    )
    shared = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]   # 10 tokens: 1.43 pages of 7
    reqs = [
        GenRequest(request_id=f"b{i}", tokens=shared + [20 + i],
                   max_new_tokens=7)
        for i in range(4)
    ]
    res = eng.run(reqs)
    for i in range(4):
        want = _baseline(model, variables, shared + [20 + i], 7)
        assert res[f"b{i}"].generated == want, f"b{i} diverged"
    assert eng.prefix_hits_total >= 3
    assert eng.prefill_tokens_saved_total > 0
    # reuse length (bucket-rounded) is not page-aligned here, so the hit
    # path must have copied the boundary page instead of sharing it
    assert eng.kv_page_stats()["cow_copies_total"] >= 1


def test_paged_evict_refill_no_stale_reads(tiny_model):
    """Freed pages get reallocated to new lanes; the recycled pages must
    never leak the previous occupant's KV into a fresh request."""
    model, variables = tiny_model
    # pool sized so the second wave MUST reuse the first wave's pages
    eng = _paged_engine(model, variables, slots=2, pool_pages=11)
    first = [
        GenRequest(request_id=f"a{i}", tokens=[9 - i, 2, 7, 1, 8],
                   max_new_tokens=10)
        for i in range(2)
    ]
    for r in first:
        eng.admit(r)
    for _ in range(3):
        eng.step()
    assert eng.evict("a0") is not None  # mid-flight eviction frees pages NOW
    freed_stats = eng.kv_page_stats()
    assert freed_stats["pages_free"] > 0
    second = GenRequest(request_id="fresh", tokens=[4, 4, 2, 6, 1, 3],
                        max_new_tokens=9)
    eng.admit(second)
    done = {}
    while eng.active_requests:
        for r in eng.step():
            done[r.request_id] = r
    assert done["fresh"].generated == _baseline(
        model, variables, [4, 4, 2, 6, 1, 3], 9)
    # the survivor of the eviction is also unperturbed
    assert done["a1"].generated == _baseline(
        model, variables, [8, 2, 7, 1, 8], 10)


def test_paged_prefix_entry_eviction_mid_flight_is_invisible(tiny_model):
    """Evicting a prefix-cache entry while a lane decodes from its spliced
    pages must not perturb the lane: lane refs keep shared pages alive."""
    model, variables = tiny_model
    eng = _paged_engine(model, variables, slots=2, pool_pages=20,
                        prefix_cache_bytes=1 << 20)
    shared = [3, 1, 4, 1, 5, 9, 2, 6]
    eng.run([GenRequest(request_id="seed", tokens=shared + [1],
                        max_new_tokens=2)])
    hit = GenRequest(request_id="hit", tokens=shared + [2], max_new_tokens=10)
    eng.admit(hit)
    assert eng.prefix_hits_total >= 1
    # drop EVERY cache entry while the lane is mid-flight
    while eng._prefix_cache.evict_oldest():
        pass
    assert len(eng._prefix_cache) == 0
    done = {}
    while eng.active_requests:
        for r in eng.step():
            done[r.request_id] = r
    assert done["hit"].generated == _baseline(
        model, variables, shared + [2], 10)


def test_paged_prefix_cache_charges_physical_bytes_shared_once(tiny_model):
    """Byte accounting is physical: two entries sharing prefix pages charge
    the shared pages once, and eviction only credits pages dropping their
    last cache reference."""
    model, variables = tiny_model
    eng = _paged_engine(model, variables, slots=2, page_tokens=8,
                        prompt_buckets=(8, 32), pool_pages=24,
                        prefix_cache_bytes=1 << 24)
    cache = eng._prefix_cache
    page_bytes = eng.kv_page_stats()["page_bytes"]
    shared = list(range(1, 17))                   # exactly 2 pages
    eng.run([GenRequest(request_id="p1", tokens=shared + [30],
                        max_new_tokens=2)])
    bytes_one = cache.total_bytes
    assert bytes_one == 3 * page_bytes            # 17 tokens -> 3 pages
    eng.run([GenRequest(request_id="p2", tokens=shared + [31],
                        max_new_tokens=2)])
    # the second entry shares the two whole prefix pages: only its private
    # boundary page is a new physical charge
    assert cache.total_bytes == bytes_one + page_bytes
    assert eng.kv_page_stats()["pages_shared"] >= 2
    # evicting the first entry credits ONLY its exclusively-held page
    cache.evict_oldest()
    assert cache.total_bytes == bytes_one


def test_paged_compile_budget_single_fill_program(tiny_model):
    """Paged mode serves fresh prompts and suffix continuations with ONE
    prefill program per bucket: budget = len(buckets) + 1 even with the
    prefix cache on (the unpaged engine needs 2 per bucket)."""
    model, variables = tiny_model
    eng = _paged_engine(model, variables, slots=2, pool_pages=20,
                        prefix_cache_bytes=1 << 20)
    assert eng.guard.budget == 3
    shared = [3, 1, 4, 1, 5, 9, 2, 6]
    prompts = [[5, 9, 2, 7], shared + [1], shared + [2],
               [11, 4, 9, 1, 2, 3, 4, 5, 6, 0, 2, 1]]
    eng.run([
        GenRequest(request_id=f"c{i}", tokens=p, max_new_tokens=4)
        for i, p in enumerate(prompts)
    ])
    assert eng.prefix_hits_total >= 1     # the hit path ran
    assert eng.compilations <= 3


# ---------------------------------------------------------------------------
# Pool exhaustion: backpressure, never OOM
# ---------------------------------------------------------------------------


def test_paged_admission_backpressure_and_recovery(tiny_model):
    """A pool sized for ~one full request at a time: can_admit gates the
    second admission until the first frees its pages; everything still
    completes bit-identically (run() waits instead of failing)."""
    model, variables = tiny_model
    # pages_per_lane = 5; pool holds 6 usable pages: two 3-page requests
    # cannot both reserve (3+3 > 6 - only with both lanes' worst case 4..)
    eng = _paged_engine(model, variables, slots=4, pool_pages=7)
    big = GenRequest(request_id="big", tokens=list(range(1, 13)),
                     max_new_tokens=24)        # span 35 -> 5 pages
    eng.admit(big)
    small = GenRequest(request_id="small", tokens=[5, 2], max_new_tokens=8)
    assert eng.free_slots > 0
    assert not eng.can_admit(small)            # 2 pages > 1 page of slack
    with pytest.raises(PoolExhausted):
        eng.admit(small)
    # requests drain -> pages free -> the small request admits and matches
    done = {}
    while eng.active_requests:
        for r in eng.step():
            done[r.request_id] = r
    assert eng.can_admit(small)
    res = eng.run([small])
    assert res["small"].generated == _baseline(model, variables, [5, 2], 8)


def test_paged_pool_too_small_refused(tiny_model):
    model, variables = tiny_model
    with pytest.raises(ValueError, match="pool too small"):
        _paged_engine(model, variables, slots=2, page_tokens=8, pool_pages=4)


def test_pool_exhaustion_backpressures_through_batcher(tiny_model):
    """End of the backpressure chain: pool pressure keeps requests QUEUED
    (they all complete bit-identically once pages free), and a full queue
    sheds with QueueFull carrying the derived Retry-After — the HTTP
    layer's 429 — never an OOM, never a lost request."""
    model, variables = tiny_model

    async def main():
        # 10 usable pages; each big request reserves 5 -> two decode at a
        # time, the rest wait in the queue on pool pressure alone
        eng = _paged_engine(model, variables, slots=4, pool_pages=11)
        b = Batcher(eng, max_queue=8)
        big = [
            GenRequest(request_id=f"big{i}", tokens=list(range(1, 13)),
                       max_new_tokens=24)
            for i in range(6)
        ]
        tasks = [asyncio.ensure_future(b.submit(r, timeout_s=120))
                 for r in big]
        # pool fits 2 reservations (2 x 5 of 10 pages): the other 4 requests
        # sit QUEUED on pool pressure while slots stay free
        depth = 0
        for _ in range(2000):
            await asyncio.sleep(0.002)
            depth = b.queue_depth
            if depth >= 4:
                break
        assert depth >= 4, "pool pressure never queued the overflow"
        assert eng.free_slots >= 2  # lanes were NOT the bottleneck
        # cap the queue at its current depth: the next submit is the 429
        b.max_queue = depth
        with pytest.raises(QueueFull) as exc:
            await b.submit(GenRequest(
                request_id="shed", tokens=[1, 2], max_new_tokens=4,
            ), timeout_s=30)
        shed = exc.value
        assert shed.retry_after_s is None or shed.retry_after_s >= 1.0
        b.max_queue = 8
        results = await asyncio.gather(*tasks)
        want = _baseline(model, variables, list(range(1, 13)), 24)
        for r in results:
            assert r.generated == want
        await b.close()

    run_async(main())


# ---------------------------------------------------------------------------
# Host KV tier (docs/serving.md §KV tiering)
# ---------------------------------------------------------------------------


def test_host_pool_slot_lifecycle_and_bytes_roundtrip():
    host = HostPagePool(budget_bytes=100, page_bytes=25)
    assert host.capacity == 4 and host.free_count == 4
    slots = host.alloc(3)
    assert host.used_count == 3 and host.can_hold(1) and not host.can_hold(2)
    with pytest.raises(PoolExhausted):
        host.alloc(2)
    page = [np.arange(6, dtype=np.float32).reshape(2, 3),
            np.full((2, 3), 7.0, np.float32)]
    host.write(slots[0], page)
    got = host.read(slots[0])
    assert all(np.array_equal(a, b) for a, b in zip(got, page))
    host.free(slots)
    assert host.free_count == 4
    s = host.stats()
    assert s["tier_host_pages_total"] == 4
    assert s["tier_host_pages_used"] == 0 and s["tier_host_bytes"] == 0


def _tiered_trio(num_pages=7, budget_pages=6, host_pages=6, page_bytes=10):
    """KVPagePool + HostPagePool + PrefixCache wired with transfer fns that
    move accounting only (no device arrays) — the allocator-level seam the
    engine's _demote_run/_restore_run drive."""
    pool = KVPagePool(num_pages=num_pages, page_tokens=4,
                      page_bytes=page_bytes)
    host = HostPagePool(budget_bytes=host_pages * page_bytes,
                        page_bytes=page_bytes)
    cache = PrefixCache(budget_pages * page_bytes, pool=pool)

    def demote(run):
        if not host.can_hold(len(run.pages)):
            return None
        return HostRun(slots=tuple(host.alloc(len(run.pages))),
                       n_tokens=run.n_tokens)

    def restore(host_run):
        n = len(host_run.slots)
        try:
            pool.reserve(n)
        except PoolExhausted:
            return None
        pages = []
        try:
            for _ in range(n):
                pages.append(pool.alloc_reserved(cache.demote_or_evict))
        except BaseException:
            pool.lane_release(pages, n - len(pages))
            raise
        return PageRun(pages=tuple(pages), n_tokens=host_run.n_tokens)

    cache.enable_tier(host, demote, restore)
    return pool, host, cache


def _admit_entry(pool, cache, key, n_pages):
    """Admission-style insert: reserve, materialize, insert, lane done."""
    pool.reserve(n_pages)
    run = PageRun(
        pages=tuple(pool.alloc_reserved() for _ in range(n_pages)),
        n_tokens=n_pages * pool.page_tokens,
    )
    assert cache.insert(key, run)
    pool.lane_release(run.pages)
    return run


def test_tier_slack_invariant_across_demote_restore_inflight():
    """slack = free + cache-only - reserved must hold through every tier
    transition: demotion converts cache-only pages to free (slack
    UNCHANGED — demoted KV was already evictable capacity), restore
    converts them back, and a failed restore leaks no reservation."""
    pool, host, cache = _tiered_trio()
    _admit_entry(pool, cache, (1, 2, 3), 3)
    _admit_entry(pool, cache, (9, 8, 7), 3)
    assert (pool.free_count, pool._cache_only, pool.reserved_outstanding) \
        == (0, 6, 0)
    assert pool.slack() == 6

    # demote the LRU entry: its 3 pages move cache-only -> free
    assert cache.demote_or_evict()
    assert cache.stats()["entries_host"] == 1
    assert (pool.free_count, pool._cache_only, pool.reserved_outstanding) \
        == (3, 3, 0)
    assert pool.slack() == 6          # unchanged: evictable either way
    assert host.demotions_total == 3 and host.used_count == 3
    assert cache.total_bytes == 3 * pool.page_bytes  # host entry credited

    # a lane occupies the freed pages: restore must evict/demote to fit
    pool.reserve(3)
    lane = [pool.alloc_reserved() for _ in range(3)]
    assert pool.slack() == 3

    # restore-on-touch: entry A pages back in; the device budget then
    # forces entry B out (demoted, not evicted), via the nested
    # demote_or_evict hook — with A pinned "in-flight" throughout
    match, got = cache.lookup((1, 2, 3))
    assert match == 3 and isinstance(got, PageRun)
    assert host.restores_total == 3
    assert cache._lru[("", (1, 2, 3))].tier == "device"
    assert cache._lru[("", (9, 8, 7))].tier == "host"
    assert (pool.free_count, pool._cache_only, pool.reserved_outstanding) \
        == (0, 3, 0)
    assert pool.slack() == 3

    # failed restore is a miss and leaks nothing: consume the whole slack,
    # then touch the host entry
    pool.reserve(pool.slack())
    before = pool.reserved_outstanding
    match, got = cache.lookup((9, 8, 7))
    assert (match, got) == (0, None)
    assert cache._lru[("", (9, 8, 7))].tier == "host"
    assert pool.reserved_outstanding == before
    pool.unreserve(before - 3)
    pool.lane_release(lane, 3)


def test_tier_inflight_entry_pinned_against_eviction():
    pool, host, cache = _tiered_trio()
    _admit_entry(pool, cache, (1, 2, 3), 2)
    entry = cache._lru[("", (1, 2, 3))]
    entry.tier = "in-flight"
    assert not cache.evict_oldest()       # the only entry is pinned
    assert not cache._shed_one()          # and not demotable either
    entry.tier = "device"
    assert cache.evict_oldest()


def test_tier_demote_falls_back_to_eviction_when_host_full():
    pool, host, cache = _tiered_trio(host_pages=2)
    _admit_entry(pool, cache, (1, 2, 3), 3)   # 3 pages > host capacity 2
    assert cache.demote_or_evict()
    assert len(cache) == 0                    # evicted, not demoted
    assert host.demotions_total == 0 and cache.evictions_total == 1
    assert pool.free_count == 6


def test_tier_evicting_host_entry_frees_slots_not_device_pages():
    pool, host, cache = _tiered_trio()
    _admit_entry(pool, cache, (1, 2, 3), 3)
    assert cache.demote_or_evict()            # -> host
    free_before = pool.free_count
    assert cache.evict_oldest()               # drop the host entry
    assert host.used_count == 0
    assert pool.free_count == free_before     # no device pages involved
    assert cache.total_bytes == 0


def _tiered_engine(model, variables, device_budget_pages, **kw):
    """Paged engine with the host tier armed and a device prefix budget of
    exactly ``device_budget_pages`` pages."""
    probe = _paged_engine(model, variables, prefix_cache_bytes=1 << 20)
    page_bytes = probe.kv_page_stats()["page_bytes"]
    defaults = dict(
        slots=2, pool_pages=24,
        prefix_cache_bytes=device_budget_pages * page_bytes,
        host_pool_bytes=1 << 16,
    )
    defaults.update(kw)
    return _paged_engine(model, variables, **defaults)


def test_tier_capacity_beyond_device_budget(tiny_model):
    """The headline: a device prefix budget of ONE entry serves a working
    set of three distinct prefixes from the cache — entries past the
    budget demote to host instead of evicting, and the second round of
    touches hits via restore-on-touch, every output bit-identical."""
    model, variables = tiny_model
    eng = _tiered_engine(model, variables, device_budget_pages=2)
    prefixes = [list(range(1, 13)), list(range(40, 52)),
                list(range(70, 82))]
    for rnd, tail in enumerate((30, 33)):
        for j, shared in enumerate(prefixes):
            prompt = shared + [tail]
            rid = f"t{rnd}_{j}"
            res = eng.run([GenRequest(request_id=rid, tokens=prompt,
                                      max_new_tokens=4)])
            want = _baseline(model, variables, prompt, 4)
            assert res[rid].generated == want, f"{rid} diverged"
    hp = eng._host_pool
    assert hp.demotions_total > 0 and hp.restores_total > 0
    # every second-round touch was a prefix hit — the device budget alone
    # (1 entry) could have served at most one of the three
    assert eng.prefix_hits_total >= 3
    assert eng._prefix_cache.stats()["entries_host"] >= 1
    st = eng.kv_page_stats()
    for key in ("tier_host_pages_total", "tier_host_pages_used",
                "tier_host_bytes", "demotions_total", "restores_total"):
        assert key in st, key
    assert st["tier_host_pages_used"] == hp.used_count


def test_tier_mid_flight_demotion_is_invisible(tiny_model):
    """Demoting a prefix entry while a lane decodes from its spliced pages
    must not perturb the lane (lane refs pin shared pages; the snapshot
    only reads), and the demoted entry still restores and serves later
    hits bit-identically."""
    model, variables = tiny_model
    eng = _tiered_engine(model, variables, device_budget_pages=16)
    shared = list(range(1, 13))
    eng.run([GenRequest(request_id="seed", tokens=shared + [1],
                        max_new_tokens=2)])
    hit = GenRequest(request_id="hit", tokens=shared + [2],
                     max_new_tokens=10)
    eng.admit(hit)
    assert eng.prefix_hits_total >= 1
    # demote EVERY entry to host while the lane is mid-flight
    while eng._prefix_cache.stats()["entries_host"] < len(eng._prefix_cache):
        assert eng._prefix_cache.demote_or_evict()
    assert eng._host_pool.demotions_total > 0
    done = {}
    while eng.active_requests:
        for r in eng.step():
            done[r.request_id] = r
    assert done["hit"].generated == _baseline(
        model, variables, shared + [2], 10)
    # the host-resident entry restores on the next touch and still hits
    res = eng.run([GenRequest(request_id="hit2", tokens=shared + [3],
                              max_new_tokens=6)])
    assert eng._host_pool.restores_total > 0
    assert res["hit2"].generated == _baseline(
        model, variables, shared + [3], 6)


def test_tier_oversized_entry_born_demoted():
    """An entry bigger than the whole DEVICE budget is not refused when the
    tier is armed: it inserts straight to host (zero device charge) and
    restores on touch — long-context KV stops competing for device pages."""
    pool, host, cache = _tiered_trio(budget_pages=2)   # budget < 3 pages
    pool.reserve(3)
    run = PageRun(pages=tuple(pool.alloc_reserved() for _ in range(3)),
                  n_tokens=12)
    assert cache.insert((1, 2, 3), run)                # would be refused
    entry = cache._lru[("", (1, 2, 3))]                # without the tier
    assert entry.tier == "host" and cache.total_bytes == 0
    assert host.demotions_total == 3
    pool.lane_release(run.pages)                       # writer lane drains
    assert pool.free_count == 6                        # no device residue
    # touch: restores (transient overshoot of the device budget), and the
    # next shed re-demotes it as the LRU victim
    match, got = cache.lookup((1, 2, 3))
    assert match == 3 and isinstance(got, PageRun)
    assert cache.total_bytes == 3 * pool.page_bytes    # over budget, pinned
    pool.reserve(2)
    run2 = PageRun(pages=tuple(pool.alloc_reserved() for _ in range(2)),
                   n_tokens=8)
    assert cache.insert((7, 7), run2)
    pool.lane_release(run2.pages)
    assert cache._lru[("", (1, 2, 3))].tier == "host"  # re-demoted
    assert cache.total_bytes == 2 * pool.page_bytes


def test_tier_oversized_entry_refused_when_host_full():
    pool, host, cache = _tiered_trio(budget_pages=2, host_pages=2)
    pool.reserve(3)
    run = PageRun(pages=tuple(pool.alloc_reserved() for _ in range(3)),
                  n_tokens=12)
    assert not cache.insert((1, 2, 3), run)            # host can't hold it
    assert len(cache) == 0
    pool.lane_release(run.pages)
