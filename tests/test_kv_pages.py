"""Paged KV cache: allocator invariants + paged-engine numerics (ISSUE 11).

The acceptance anchors: greedy AND sampled decode through the page pool are
BIT-IDENTICAL to the unpaged path and to single-request ``cached_generate``
across staggered mixed-length batches, page-boundary-straddling prefills
(copy-on-write suffix splices), evict-refill page reuse (no stale reads),
and mid-flight prefix-entry eviction — while pool exhaustion surfaces as
queueing backpressure (and 429s with Retry-After past the queue), never as
an OOM or a corrupted lane.
"""

from __future__ import annotations

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import run_async
from finetune_controller_tpu.models.generate import cached_generate
from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.serve.batcher import Batcher, QueueFull
from finetune_controller_tpu.serve.engine import (
    BatchEngine,
    EngineConfig,
    GenRequest,
)
from finetune_controller_tpu.serve.kv_pages import (
    KVPagePool,
    PageRun,
    PoolExhausted,
)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    model = LlamaForCausalLM(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 4), jnp.int32)
    )
    return model, variables


def _paged_engine(model, variables, **kw):
    defaults = dict(slots=4, prompt_buckets=(8, 16), max_new_tokens=24,
                    page_tokens=8)
    defaults.update(kw)
    return BatchEngine(model, variables, EngineConfig(**defaults))


def _baseline(model, variables, prompt, n, **kw):
    out = cached_generate(
        model, variables, jnp.asarray([prompt], jnp.int32),
        max_new_tokens=n, **kw,
    )
    return list(np.asarray(out[0, len(prompt):]))


# ---------------------------------------------------------------------------
# KVPagePool allocator invariants (pure host logic, no jax)
# ---------------------------------------------------------------------------


def test_pool_alloc_release_roundtrip():
    pool = KVPagePool(num_pages=8, page_tokens=4, page_bytes=100)
    assert pool.usable_pages == 7 and pool.free_count == 7
    pool.reserve(3)
    pages = [pool.alloc_reserved() for _ in range(3)]
    assert 0 not in pages  # scratch is never handed out
    assert pool.free_count == 4 and pool.used_count == 3
    assert pool.reserved_outstanding == 0
    pool.lane_release(pages)
    assert pool.free_count == 7 and pool.used_count == 0


def test_pool_reserve_respects_slack_and_raises():
    pool = KVPagePool(num_pages=6, page_tokens=4)
    pool.reserve(5)
    assert pool.slack() == 0
    with pytest.raises(PoolExhausted):
        pool.reserve(1)
    assert pool.exhaustions_total == 1
    pool.unreserve(5)
    assert pool.slack() == 5


def test_pool_cache_only_pages_count_toward_slack_and_evict_on_demand():
    """Pages held ONLY by prefix-cache entries are evictable capacity: they
    count in the admission slack and free when the entry releases them."""
    pool = KVPagePool(num_pages=6, page_tokens=4, page_bytes=10)
    pool.reserve(3)
    pages = [pool.alloc_reserved() for _ in range(3)]
    charged = pool.cache_ref(pages)
    assert charged == 3  # first cache reference charges each page once
    pool.lane_release(pages)          # lane done; entry keeps them resident
    assert pool.free_count == 2
    assert pool.slack() == 5          # 2 free + 3 evictable
    # a second entry sharing two of the pages charges nothing new
    assert pool.cache_ref(pages[:2]) == 0
    assert pool.cache_release(pages[:2]) == 0  # still held by entry 1
    evicted = {"n": 0}

    def evict_one():
        if evicted["n"] >= 1:
            return False
        evicted["n"] += 1
        pool.cache_release(pages)
        return True

    pool.reserve(4)
    got = [pool.alloc_reserved(evict_one) for _ in range(4)]
    assert len(set(got)) == 4 and evicted["n"] == 1


def test_pool_shared_count_tracks_multi_holder_pages():
    pool = KVPagePool(num_pages=6, page_tokens=4)
    pool.reserve(2)
    pages = [pool.alloc_reserved() for _ in range(2)]
    assert pool.shared_count == 0
    pool.lane_ref(pages[0])  # a second lane splices it
    assert pool.shared_count == 1
    pool.cache_ref(pages)
    assert pool.shared_count == 2


# ---------------------------------------------------------------------------
# Paged engine: the bit-identity anchors
# ---------------------------------------------------------------------------


def test_paged_batching_invariance_mixed_staggered(tiny_model):
    """Greedy tokens through the page pool — mixed prompt lengths, requests
    joining mid-flight — are bit-identical to single-request
    cached_generate AND to the unpaged engine, for every request."""
    model, variables = tiny_model
    prompts = [
        [5, 9, 2, 7],
        [1, 3, 3, 8, 2, 2],
        [7, 7, 7],
        [11, 4, 9, 1, 2, 3, 4, 5, 6, 0, 2, 1],  # second bucket
        [2, 13],
    ]
    reqs = [
        GenRequest(request_id=f"r{i}", tokens=p, max_new_tokens=6 + 2 * i)
        for i, p in enumerate(prompts)
    ]
    paged = _paged_engine(model, variables, slots=2, pool_pages=12)
    unpaged = BatchEngine(model, variables, EngineConfig(
        slots=2, prompt_buckets=(8, 16), max_new_tokens=24))
    res_p = paged.run(list(reqs))
    res_u = unpaged.run(list(reqs))
    for i, p in enumerate(prompts):
        want = _baseline(model, variables, p, 6 + 2 * i)
        assert res_p[f"r{i}"].generated == want, f"paged diverged on r{i}"
        assert res_u[f"r{i}"].generated == want
    # the run drained: every page returned to the free list
    stats = paged.kv_page_stats()
    assert stats["pages_used"] == 0
    assert stats["pages_free"] == stats["pages_total"]


def test_paged_sampled_decode_reproducible(tiny_model):
    """Sampled decode through the pool reproduces the per-request
    PRNGKey(seed) stream bit-for-bit, independent of batch-mates."""
    model, variables = tiny_model
    reqs = [
        GenRequest(request_id=f"s{i}", tokens=[3 + i, 1, 4, 1], seed=40 + i,
                   temperature=0.8, top_k=7, max_new_tokens=8)
        for i in range(4)
    ]
    eng = _paged_engine(model, variables, slots=4, pool_pages=20)
    res = eng.run(reqs)
    for i in range(4):
        want = _baseline(
            model, variables, [3 + i, 1, 4, 1], 8,
            temperature=0.8, top_k=7, rng=jax.random.PRNGKey(40 + i),
        )
        assert res[f"s{i}"].generated == want


def test_page_boundary_straddling_prefill_and_cow_splice(tiny_model):
    """A page size that divides NEITHER the buckets NOR the reuse length:
    suffix prefills straddle page boundaries and the prefix splice must
    copy-on-write the boundary page.  Outputs stay bit-identical and the
    CoW copy actually happens."""
    model, variables = tiny_model
    eng = _paged_engine(
        model, variables, slots=2, page_tokens=7, pool_pages=16,
        prefix_cache_bytes=1 << 20,
    )
    shared = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]   # 10 tokens: 1.43 pages of 7
    reqs = [
        GenRequest(request_id=f"b{i}", tokens=shared + [20 + i],
                   max_new_tokens=7)
        for i in range(4)
    ]
    res = eng.run(reqs)
    for i in range(4):
        want = _baseline(model, variables, shared + [20 + i], 7)
        assert res[f"b{i}"].generated == want, f"b{i} diverged"
    assert eng.prefix_hits_total >= 3
    assert eng.prefill_tokens_saved_total > 0
    # reuse length (bucket-rounded) is not page-aligned here, so the hit
    # path must have copied the boundary page instead of sharing it
    assert eng.kv_page_stats()["cow_copies_total"] >= 1


def test_paged_evict_refill_no_stale_reads(tiny_model):
    """Freed pages get reallocated to new lanes; the recycled pages must
    never leak the previous occupant's KV into a fresh request."""
    model, variables = tiny_model
    # pool sized so the second wave MUST reuse the first wave's pages
    eng = _paged_engine(model, variables, slots=2, pool_pages=11)
    first = [
        GenRequest(request_id=f"a{i}", tokens=[9 - i, 2, 7, 1, 8],
                   max_new_tokens=10)
        for i in range(2)
    ]
    for r in first:
        eng.admit(r)
    for _ in range(3):
        eng.step()
    assert eng.evict("a0") is not None  # mid-flight eviction frees pages NOW
    freed_stats = eng.kv_page_stats()
    assert freed_stats["pages_free"] > 0
    second = GenRequest(request_id="fresh", tokens=[4, 4, 2, 6, 1, 3],
                        max_new_tokens=9)
    eng.admit(second)
    done = {}
    while eng.active_requests:
        for r in eng.step():
            done[r.request_id] = r
    assert done["fresh"].generated == _baseline(
        model, variables, [4, 4, 2, 6, 1, 3], 9)
    # the survivor of the eviction is also unperturbed
    assert done["a1"].generated == _baseline(
        model, variables, [8, 2, 7, 1, 8], 10)


def test_paged_prefix_entry_eviction_mid_flight_is_invisible(tiny_model):
    """Evicting a prefix-cache entry while a lane decodes from its spliced
    pages must not perturb the lane: lane refs keep shared pages alive."""
    model, variables = tiny_model
    eng = _paged_engine(model, variables, slots=2, pool_pages=20,
                        prefix_cache_bytes=1 << 20)
    shared = [3, 1, 4, 1, 5, 9, 2, 6]
    eng.run([GenRequest(request_id="seed", tokens=shared + [1],
                        max_new_tokens=2)])
    hit = GenRequest(request_id="hit", tokens=shared + [2], max_new_tokens=10)
    eng.admit(hit)
    assert eng.prefix_hits_total >= 1
    # drop EVERY cache entry while the lane is mid-flight
    while eng._prefix_cache.evict_oldest():
        pass
    assert len(eng._prefix_cache) == 0
    done = {}
    while eng.active_requests:
        for r in eng.step():
            done[r.request_id] = r
    assert done["hit"].generated == _baseline(
        model, variables, shared + [2], 10)


def test_paged_prefix_cache_charges_physical_bytes_shared_once(tiny_model):
    """Byte accounting is physical: two entries sharing prefix pages charge
    the shared pages once, and eviction only credits pages dropping their
    last cache reference."""
    model, variables = tiny_model
    eng = _paged_engine(model, variables, slots=2, page_tokens=8,
                        prompt_buckets=(8, 32), pool_pages=24,
                        prefix_cache_bytes=1 << 24)
    cache = eng._prefix_cache
    page_bytes = eng.kv_page_stats()["page_bytes"]
    shared = list(range(1, 17))                   # exactly 2 pages
    eng.run([GenRequest(request_id="p1", tokens=shared + [30],
                        max_new_tokens=2)])
    bytes_one = cache.total_bytes
    assert bytes_one == 3 * page_bytes            # 17 tokens -> 3 pages
    eng.run([GenRequest(request_id="p2", tokens=shared + [31],
                        max_new_tokens=2)])
    # the second entry shares the two whole prefix pages: only its private
    # boundary page is a new physical charge
    assert cache.total_bytes == bytes_one + page_bytes
    assert eng.kv_page_stats()["pages_shared"] >= 2
    # evicting the first entry credits ONLY its exclusively-held page
    cache.evict_oldest()
    assert cache.total_bytes == bytes_one


def test_paged_compile_budget_single_fill_program(tiny_model):
    """Paged mode serves fresh prompts and suffix continuations with ONE
    prefill program per bucket: budget = len(buckets) + 1 even with the
    prefix cache on (the unpaged engine needs 2 per bucket)."""
    model, variables = tiny_model
    eng = _paged_engine(model, variables, slots=2, pool_pages=20,
                        prefix_cache_bytes=1 << 20)
    assert eng.guard.budget == 3
    shared = [3, 1, 4, 1, 5, 9, 2, 6]
    prompts = [[5, 9, 2, 7], shared + [1], shared + [2],
               [11, 4, 9, 1, 2, 3, 4, 5, 6, 0, 2, 1]]
    eng.run([
        GenRequest(request_id=f"c{i}", tokens=p, max_new_tokens=4)
        for i, p in enumerate(prompts)
    ])
    assert eng.prefix_hits_total >= 1     # the hit path ran
    assert eng.compilations <= 3


# ---------------------------------------------------------------------------
# Pool exhaustion: backpressure, never OOM
# ---------------------------------------------------------------------------


def test_paged_admission_backpressure_and_recovery(tiny_model):
    """A pool sized for ~one full request at a time: can_admit gates the
    second admission until the first frees its pages; everything still
    completes bit-identically (run() waits instead of failing)."""
    model, variables = tiny_model
    # pages_per_lane = 5; pool holds 6 usable pages: two 3-page requests
    # cannot both reserve (3+3 > 6 - only with both lanes' worst case 4..)
    eng = _paged_engine(model, variables, slots=4, pool_pages=7)
    big = GenRequest(request_id="big", tokens=list(range(1, 13)),
                     max_new_tokens=24)        # span 35 -> 5 pages
    eng.admit(big)
    small = GenRequest(request_id="small", tokens=[5, 2], max_new_tokens=8)
    assert eng.free_slots > 0
    assert not eng.can_admit(small)            # 2 pages > 1 page of slack
    with pytest.raises(PoolExhausted):
        eng.admit(small)
    # requests drain -> pages free -> the small request admits and matches
    done = {}
    while eng.active_requests:
        for r in eng.step():
            done[r.request_id] = r
    assert eng.can_admit(small)
    res = eng.run([small])
    assert res["small"].generated == _baseline(model, variables, [5, 2], 8)


def test_paged_pool_too_small_refused(tiny_model):
    model, variables = tiny_model
    with pytest.raises(ValueError, match="pool too small"):
        _paged_engine(model, variables, slots=2, page_tokens=8, pool_pages=4)


def test_pool_exhaustion_backpressures_through_batcher(tiny_model):
    """End of the backpressure chain: pool pressure keeps requests QUEUED
    (they all complete bit-identically once pages free), and a full queue
    sheds with QueueFull carrying the derived Retry-After — the HTTP
    layer's 429 — never an OOM, never a lost request."""
    model, variables = tiny_model

    async def main():
        # 10 usable pages; each big request reserves 5 -> two decode at a
        # time, the rest wait in the queue on pool pressure alone
        eng = _paged_engine(model, variables, slots=4, pool_pages=11)
        b = Batcher(eng, max_queue=8)
        big = [
            GenRequest(request_id=f"big{i}", tokens=list(range(1, 13)),
                       max_new_tokens=24)
            for i in range(6)
        ]
        tasks = [asyncio.ensure_future(b.submit(r, timeout_s=120))
                 for r in big]
        # pool fits 2 reservations (2 x 5 of 10 pages): the other 4 requests
        # sit QUEUED on pool pressure while slots stay free
        depth = 0
        for _ in range(2000):
            await asyncio.sleep(0.002)
            depth = b.queue_depth
            if depth >= 4:
                break
        assert depth >= 4, "pool pressure never queued the overflow"
        assert eng.free_slots >= 2  # lanes were NOT the bottleneck
        # cap the queue at its current depth: the next submit is the 429
        b.max_queue = depth
        with pytest.raises(QueueFull) as exc:
            await b.submit(GenRequest(
                request_id="shed", tokens=[1, 2], max_new_tokens=4,
            ), timeout_s=30)
        shed = exc.value
        assert shed.retry_after_s is None or shed.retry_after_s >= 1.0
        b.max_queue = 8
        results = await asyncio.gather(*tasks)
        want = _baseline(model, variables, list(range(1, 13)), 24)
        for r in results:
            assert r.generated == want
        await b.close()

    run_async(main())
