"""Multi-tenant fair-share scheduler: unit + property tests (docs/scheduling.md).

Covers ISSUE 5's provable properties on the deterministic simulator —
quota safety under preemption/backfill, victims always resume, Jain >= 0.8
at steady state, head-of-line blocking eliminated vs the FIFO baseline —
plus the legacy-scheduler pins (per-instance sequence, FIFO starvation).
"""

import dataclasses
import random

import pytest

from conftest import one_chip_catalog

from finetune_controller_tpu.controller.backends.scheduler import GangScheduler
from finetune_controller_tpu.controller.devices import (
    DeviceCatalog,
    DeviceFlavor,
    FlavorQuota,
)
from finetune_controller_tpu.sched import FairShareScheduler, jain_index
from finetune_controller_tpu.sched.queues import parse_priority, priority_name
from finetune_controller_tpu.sched.sim import (
    TRACE_QUEUES,
    ClusterSim,
    SimJob,
    percentile,
    sim_catalog,
    synthetic_trace,
)


def _catalog(quota=8, chips_per_host=1):
    return DeviceCatalog(
        flavors=[DeviceFlavor(
            name="chip", generation="cpu", hosts=1,
            chips_per_host=chips_per_host, runtime="cpu", queue="q",
        )],
        quotas=[FlavorQuota(flavor="chip", nominal_chips=quota)],
        default_flavor="chip",
    )


# ---------------------------------------------------------------------------
# Priority classes
# ---------------------------------------------------------------------------


def test_parse_priority():
    assert parse_priority("high") > parse_priority("normal") > parse_priority("low")
    assert parse_priority("HIGH") == parse_priority("high")
    assert parse_priority(7) == 7
    assert parse_priority("7") == 7
    assert priority_name(parse_priority("normal")) == "normal"
    for bad in ("urgent", None, 1.5, True):
        with pytest.raises(ValueError):
            parse_priority(bad)


# ---------------------------------------------------------------------------
# Legacy scheduler pins (ISSUE 5 satellites)
# ---------------------------------------------------------------------------


def test_gang_scheduler_seq_is_per_instance():
    """The seed's module-global sequence leaked ordering across scheduler
    instances (test-order-dependent queue positions).  Two fresh schedulers
    must produce identical, instance-local orderings."""
    cat = one_chip_catalog(quota=1)
    for _ in range(2):
        sched = GangScheduler(cat)
        a = sched.submit("a", "chip-1")
        b = sched.submit("b", "chip-1")
        assert (a.seq, b.seq) == (0, 1)
        sched.try_admit()
        assert sched.pending() == ["b"]
        assert sched.position("b") == 1


def test_gang_scheduler_fifo_starvation_pinned():
    """Pin the legacy behavior the fair-share scheduler exists to fix: a
    blocked large job is starved forever by a stream of small jobs."""
    sched = GangScheduler(_catalog(quota=2))
    sched.submit("big", "chip", num_slices=2)
    sched.submit("s0", "chip")
    assert [w.job_id for w in sched.try_admit()] == ["big"]
    sched.release("big")
    # big resubmits while one small slot is held: now the stream starves it
    assert [w.job_id for w in sched.try_admit()] == ["s0"]
    sched.submit("big2", "chip", num_slices=2)
    for i in range(1, 6):
        sched.submit(f"s{i}", "chip")
        admitted = [w.job_id for w in sched.try_admit()]
        assert admitted == [f"s{i}"]  # small passes the blocked big
        sched.release(f"s{i - 1}")
    assert not sched.is_admitted("big2")
    assert sched.position("big2") == 1  # head of queue, never admitted


def test_fairshare_reserves_for_blocked_head_no_starvation():
    """The fix for the pin above: once the big job is head-of-line, free
    chips are reserved for it — small jobs stop slipping past, and the big
    job admits as soon as its reservation is satisfied.

    Pinned in evict mode (``resize=False``, the FTC_SCHED_RESIZE=false
    behavior): with resize on, the blocked head ELASTICALLY ADMITS at one
    slice instead of starving — pinned in tests/test_resize.py."""
    sched = FairShareScheduler(_catalog(quota=2), resize=False)
    sched.submit("s0", "chip")
    sched.submit("s1", "chip")
    assert {w.job_id for w in sched.try_admit()} == {"s0", "s1"}
    sched.submit("big", "chip", num_slices=2)
    sched.submit("s2", "chip")
    sched.release("s0")
    # one chip free, big (2 chips) is head: s2 must NOT take the free chip
    assert sched.try_admit() == []
    assert sched.pending() == ["big", "s2"]
    sched.release("s1")
    admitted = [w.job_id for w in sched.try_admit()]
    assert admitted == ["big"]  # reservation satisfied, head admits first
    assert not sched.is_admitted("s2")


def test_fairshare_rejects_never_fitting_workload():
    sched = FairShareScheduler(_catalog(quota=2))
    with pytest.raises(ValueError, match="never be admitted"):
        sched.submit("huge", "chip", num_slices=3)


# ---------------------------------------------------------------------------
# Fair-share admission ordering
# ---------------------------------------------------------------------------


def test_priority_orders_admission():
    sched = FairShareScheduler(_catalog(quota=1))
    sched.submit("lo", "chip", priority="low")
    sched.submit("hi", "chip", priority="high")
    sched.submit("mid", "chip", priority="normal")
    assert sched.pending() == ["hi", "mid", "lo"]
    assert [w.job_id for w in sched.try_admit()] == ["hi"]
    sched.release("hi")
    assert [w.job_id for w in sched.try_admit()] == ["mid"]


def test_under_share_queue_admits_first():
    """Same priority: the queue farthest below its weighted entitlement
    wins the next slot (weighted DRF ordering)."""
    sched = FairShareScheduler(_catalog(quota=4), {"a": 1.0, "b": 1.0})
    for i in range(3):
        sched.submit(f"a{i}", "chip", queue="a")
    sched.try_admit()  # a holds 3 of 4
    sched.submit("a3", "chip", queue="a")
    sched.submit("b0", "chip", queue="b")
    # b has zero usage: it ranks first despite submitting later
    assert sched.pending() == ["b0", "a3"]
    assert [w.job_id for w in sched.try_admit()] == ["b0"]


def test_idle_queue_quota_is_borrowable():
    """Cohort borrowing: with queue b idle, queue a may use the whole
    flavor quota (beyond its 50% nominal share); the borrowed amount shows
    up in the snapshot."""
    sched = FairShareScheduler(_catalog(quota=4), {"a": 1.0, "b": 1.0})
    for i in range(4):
        sched.submit(f"a{i}", "chip", queue="a")
    assert len(sched.try_admit()) == 4  # full quota, no cap at nominal
    snap = sched.snapshot()
    assert snap["queues"]["a"]["used_chips_total"] == 4
    assert snap["queues"]["a"]["borrowed_chips"] == 0.0  # cohort of one: all nominal
    # b wakes up: now the cohort splits 2/2 and a is over share
    sched.submit("b0", "chip", queue="b")
    snap = sched.snapshot()
    assert snap["queues"]["a"]["borrowed_chips"] == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# Preemption
# ---------------------------------------------------------------------------


def test_high_priority_preempts_lowest_youngest_first():
    sched = FairShareScheduler(_catalog(quota=3))
    sched.submit("lo-old", "chip", priority="low")
    sched.submit("lo-young", "chip", priority="low")
    sched.submit("mid", "chip", priority="normal")
    sched.try_admit()
    sched.submit("hi", "chip", priority="high")
    assert sched.try_admit() == []  # full: hi blocks as head
    victims = sched.take_preemptions()
    # exactly the shortfall: one victim, lowest priority, youngest first
    assert [d.pair for d in victims] == [("lo-young", "hi")]
    sched.release("lo-young")  # the backend reports the exit
    assert [w.job_id for w in sched.try_admit()] == ["hi"]


def test_preemption_is_all_or_nothing():
    """If eligible victims cannot cover the shortfall, nobody is killed —
    partial eviction would thrash victims without admitting the head."""
    sched = FairShareScheduler(_catalog(quota=4))
    sched.submit("lo", "chip", priority="low")
    sched.submit("hi-old", "chip", num_slices=3, priority="high")
    sched.try_admit()
    sched.submit("hi-new", "chip", num_slices=2, priority="high")
    sched.try_admit()
    assert sched.take_preemptions() == []  # only 1 low chip < 2 needed
    assert not sched.is_admitted("hi-new")


def test_reserved_chips_not_stolen_by_later_submit():
    """The no-admission-race guarantee: chips freed by a preemption go to
    the preemptor even when another job arrives (and ranks lower) while the
    victim is still exiting."""
    sched = FairShareScheduler(_catalog(quota=2))
    sched.submit("lo", "chip", num_slices=2, priority="low")
    sched.try_admit()
    sched.submit("hi", "chip", num_slices=2, priority="high")
    sched.try_admit()
    # a 2-slice victim for a 2-chip shortfall: shrinking to 1 would cover
    # only half, so the planner escalates to a full eviction
    assert [d.pair for d in sched.take_preemptions()] == [("lo", "hi")]
    # a normal-priority 1-chip job arrives mid-eviction
    sched.submit("sneak", "chip", priority="normal")
    assert sched.try_admit() == []  # nothing is free yet
    sched.release("lo")
    admitted = [w.job_id for w in sched.try_admit()]
    assert admitted == ["hi"]  # the full freed slice goes to the preemptor
    assert not sched.is_admitted("sneak")


def test_backfill_rides_preemption_excess():
    """A 1-chip job may ride along when a preemption frees more than the
    head needs — but only the excess, and only chips physically free.

    Pinned in evict mode: with resize on the 4-slice victim SHRINKS to 2
    instead (tests/test_resize.py pins that path)."""
    sched = FairShareScheduler(_catalog(quota=4), resize=False)
    sched.submit("lo", "chip", num_slices=4, priority="low")
    sched.try_admit()
    sched.submit("hi", "chip", num_slices=2, priority="high")
    sched.submit("small", "chip", num_slices=1, priority="normal")
    sched.try_admit()
    assert [d.pair for d in sched.take_preemptions()] == [("lo", "hi")]
    # victim still holds its chips: nothing admits while it exits
    assert sched.try_admit() == []
    sched.release("lo")
    admitted = [w.job_id for w in sched.try_admit()]
    # head first, then the backfill candidate into the freed excess
    assert admitted == ["hi", "small"]


def test_same_priority_reclaim_only_no_thrash():
    """Fairness preemption is reclaim-only: an under-share queue evicts a
    borrower, but the displaced borrower must NOT preempt back (the swap is
    a fixed point, not an oscillation)."""
    sched = FairShareScheduler(_catalog(quota=4), {"a": 1.0, "b": 1.0})
    for i in range(4):
        sched.submit(f"a{i}", "chip", queue="a")  # a borrows the lot
    sched.try_admit()
    sched.submit("b0", "chip", queue="b")
    sched.try_admit()
    victims = sched.take_preemptions()
    assert [d.pair for d in victims] == [("a3", "b0")]  # youngest borrower evicted
    sched.release("a3")
    assert [w.job_id for w in sched.try_admit()] == ["b0"]
    # the displaced a-job requeues: a is now AT its nominal share (2 used of
    # 2 nominal after the swap? no: 3 used, nominal 2 -> still over) and b is
    # within share holding 1 of 2 — the requeued a-job must not evict b0
    sched.submit("a3", "chip", queue="a")
    sched.try_admit()
    assert sched.take_preemptions() == []


# ---------------------------------------------------------------------------
# Simulator properties
# ---------------------------------------------------------------------------


class _CheckedScheduler(FairShareScheduler):
    """Asserts quota safety after every admission pass."""

    def try_admit(self):
        out = super().try_admit()
        for f in self._catalog.flavors:
            used = self._used_chips(f.name)
            quota = self._catalog.quota_for(f.name)
            assert used <= quota, (
                f"quota violated on {f.name}: {used} > {quota}"
            )
        return out


def _random_trace(seed: int, n_jobs: int = 20) -> list[SimJob]:
    rng = random.Random(seed)
    queues = list(TRACE_QUEUES)
    jobs = []
    for i in range(n_jobs):
        jobs.append(SimJob(
            job_id=f"j{i}", flavor="sim-chip",
            num_slices=rng.randint(1, 6),
            duration_s=rng.uniform(10.0, 200.0),
            arrival_s=rng.uniform(0.0, 120.0),
            queue=rng.choice(queues),
            priority=rng.choice(["low", "normal", "high"]),
            checkpoint_every_s=rng.choice([10.0, 30.0, 60.0]),
        ))
    return jobs


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_sim_quota_never_exceeded_and_victims_resume(seed):
    """Across random seeded traces: no admission pass ever exceeds the
    flavor quota (preemption + backfill included), every preempted job
    resumes, and every job finishes."""
    catalog = sim_catalog(8)
    sim = ClusterSim(
        catalog,
        lambda clock: _CheckedScheduler(catalog, TRACE_QUEUES, clock=clock),
    )
    report = sim.run(_random_trace(seed), horizon_s=1_000_000.0)
    for o in report.outcomes.values():
        assert o.finish_s is not None, f"{o.job_id} never finished"
        assert len(o.resumed_at) == len(o.preempted_at), (
            f"{o.job_id} was preempted but never resumed"
        )
    assert len(report.preempt_resume_latencies_s) == report.preemptions


def test_sim_is_deterministic():
    catalog = sim_catalog(8)

    def run():
        sim = ClusterSim(
            catalog,
            lambda clock: FairShareScheduler(
                catalog, TRACE_QUEUES, clock=clock
            ),
        )
        return sim.run(synthetic_trace(0))

    a, b = run(), run()
    assert dataclasses.asdict(a) == dataclasses.asdict(b)


def test_sim_fairshare_beats_fifo_on_canonical_trace():
    """The acceptance numbers (also BENCH_MODE=sched): vs FIFO on the same
    seeded trace, fair-share eliminates head-of-line blocking for small
    jobs, improves the Jain index past 0.8 at steady state, and reports
    preempt->readmit latency."""
    catalog = sim_catalog(8)
    trace = synthetic_trace(0)
    # both legs' Jain indices are normalised by the SAME entitlements
    fifo = ClusterSim(
        catalog, lambda clock: GangScheduler(catalog),
        queue_weights=TRACE_QUEUES,
    ).run(trace)
    fair = ClusterSim(
        catalog,
        lambda clock: FairShareScheduler(catalog, TRACE_QUEUES, clock=clock),
        queue_weights=TRACE_QUEUES,
    ).run(trace)
    fifo_p95 = percentile(fifo.waits(max_chips=1), 95)
    fair_p95 = percentile(fair.waits(max_chips=1), 95)
    assert fair_p95 < fifo_p95 / 10, (fair_p95, fifo_p95)
    assert fair.jain_fairness >= 0.8 > fifo.jain_fairness
    assert fair.preemptions > 0 == fifo.preemptions
    assert fair.preempt_resume_latencies_s  # the latency IS reported
    # starvation-free both ways: every batch job still completes
    for o in fair.outcomes.values():
        assert o.finish_s is not None


def test_jain_index():
    assert jain_index([1, 1, 1, 1]) == pytest.approx(1.0)
    assert jain_index([1, 0, 0, 0]) == pytest.approx(0.25)
    assert jain_index([]) == 1.0
