"""Multi-process API serving (--workers N over SO_REUSEPORT).

The reference serves with ``uvicorn --workers 4`` (``Dockerfile:28``); the
rebuild's equivalent is N forked aiohttp processes sharing the port.  Safe
only with the k8s backend + sqlite state store — the guard rails and the
actual fan-out are both tested here with real OS processes.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(tmp_path, **extra) -> dict[str, str]:
    env = dict(os.environ)
    env.update({
        "FTC_STATE_DIR": str(tmp_path / "state"),
        "FTC_OBJECT_STORE_ROOT": str(tmp_path / "objects"),
        "FTC_ENVIRONMENT": "local",
        "JAX_PLATFORMS": "cpu",
        # fake in-cluster env: the client is constructed lazily and /health
        # never touches the apiserver
        "KUBERNETES_SERVICE_HOST": "127.0.0.1",
        "KUBERNETES_SERVICE_PORT": "1",
        **extra,
    })
    return env


def test_workers_refused_on_local_backend(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "finetune_controller_tpu.controller.server",
         "--port", str(_free_port()), "--workers", "2"],
        env=_env(tmp_path, FTC_BACKEND="local"),
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode != 0
    assert "FTC_BACKEND=k8s" in out.stderr


def test_workers_refused_on_jsonl_store(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "finetune_controller_tpu.controller.server",
         "--port", str(_free_port()), "--workers", "2"],
        env=_env(tmp_path, FTC_BACKEND="k8s", FTC_STATE_BACKEND="jsonl"),
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode != 0
    assert "FTC_STATE_BACKEND=sqlite" in out.stderr


@pytest.mark.skipif(sys.platform != "linux", reason="SO_REUSEPORT fan-out")
def test_two_workers_share_the_port(tmp_path):
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "finetune_controller_tpu.controller.server",
         "--port", str(port), "--workers", "2"],
        env=_env(tmp_path, FTC_BACKEND="k8s", FTC_MONITOR_IN_PROCESS="false"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        url = f"http://127.0.0.1:{port}/api/v1/health"
        deadline = time.time() + 60
        up = False
        while time.time() < deadline and not up:
            try:
                with urllib.request.urlopen(url, timeout=2) as r:
                    up = json.load(r)["status"] == "ok"
            except OSError:
                time.sleep(0.5)
        assert up, "service never came up"
        # SO_REUSEPORT fan-out: the shared port keeps answering...
        for _ in range(5):
            with urllib.request.urlopen(url, timeout=5) as r:
                assert json.load(r)["status"] == "ok"
            time.sleep(0.2)
        # ...and a forked worker child exists next to the parent
        assert proc.poll() is None
        kids = _children_of(proc.pid)
        assert len(kids) >= 1, "expected a forked worker child"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def _children_of(pid: int) -> list[int]:
    try:
        out = subprocess.run(
            ["ps", "--ppid", str(pid), "-o", "pid="],
            capture_output=True, text=True, timeout=10,
        )
        return [int(p) for p in out.stdout.split()]
    except Exception:
        return []
