import json

import numpy as np

from finetune_controller_tpu.data.loader import (
    batches_from_tokens,
    jsonl_token_batches,
    load_token_documents,
    pack_documents,
)


def test_pack_documents_segments():
    docs = [[1, 2, 3], [4, 5, 6, 7, 8]]
    tokens, segs, _ = pack_documents(docs, seq_len=4)
    assert tokens.shape == (2, 4)
    assert segs.tolist() == [[1, 1, 1, 2], [2, 2, 2, 2]]


def test_pack_pads_tiny_dataset():
    tokens, segs, _ = pack_documents([[9, 9]], seq_len=8)
    assert tokens.shape == (1, 8)
    assert segs[0, :2].tolist() == [1, 1]
    assert segs[0, 2:].sum() == 0


def test_jsonl_loading_and_sharding(tmp_path):
    path = tmp_path / "data.jsonl"
    with open(path, "w") as f:
        for i in range(50):
            f.write(json.dumps({"tokens": list(range(i, i + 20))}) + "\n")
    docs = load_token_documents(str(path))
    assert len(docs) == 50

    it0 = jsonl_token_batches(str(path), batch_size=2, seq_len=16, shard_index=0, shard_count=2)
    it1 = jsonl_token_batches(str(path), batch_size=2, seq_len=16, shard_index=1, shard_count=2)
    b0, b1 = next(it0), next(it1)
    assert b0["tokens"].shape == (2, 16)
    # different shards see different blocks
    assert not np.array_equal(b0["tokens"], b1["tokens"])


def test_text_rows_byte_fallback(tmp_path):
    path = tmp_path / "text.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"text": "hello"}) + "\n")
    docs = load_token_documents(str(path))
    toks, flags = docs[0]
    assert toks == list(b"hello") and flags == [1] * 5


def test_batches_have_loss_mask_and_segments():
    tokens, segs, _ = pack_documents([list(range(100))], seq_len=10)
    b = next(batches_from_tokens(tokens, segs, batch_size=2))
    assert set(b) >= {"tokens", "loss_mask", "segment_ids"}
    assert b["loss_mask"].dtype == np.float32


def test_sft_prompt_completion_masking(tmp_path):
    """SFT rows: loss counts only completion targets, through packing and
    the segment-boundary masking."""
    path = tmp_path / "sft.jsonl"
    rows = [
        {"prompt": "ab", "completion": "XY"},
        {"prompt_tokens": [1, 2, 3], "completion_tokens": [7, 8]},
    ]
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    docs = load_token_documents(str(path))
    toks0, flags0 = docs[0]
    assert toks0 == list(b"abXY") and flags0 == [0, 0, 1, 1]
    assert docs[1] == ([1, 2, 3, 7, 8], [0, 0, 0, 1, 1])

    it = jsonl_token_batches(str(path), batch_size=1, seq_len=9)
    b = next(it)
    # stream: a b X Y | 1 2 3 7 8 → flags 0 0 1 1 0 0 0 1 1; doc-boundary
    # target (position 4, first token of doc 2) is already 0 via flags
    assert b["tokens"].shape == (1, 9)
    expect = np.array([[0, 0, 1, 1, 0, 0, 0, 1, 1]], np.float32)
    np.testing.assert_array_equal(b["loss_mask"], expect)
    # plain-LM rows in the same schema family still mask everything on
    assert b["segment_ids"].tolist() == [[1, 1, 1, 1, 2, 2, 2, 2, 2]]


def test_chat_messages_rows_mask_assistant_only(tmp_path):
    """{"messages": [...]} rows render with the fixed template; loss counts
    ONLY assistant content (every assistant turn in a multi-turn chat), and
    the mask rides through packing into batches."""
    import json

    import numpy as np

    from finetune_controller_tpu.data.loader import (
        jsonl_token_batches,
        load_token_documents,
    )

    rows = [
        {"messages": [
            {"role": "system", "content": "be brief"},
            {"role": "user", "content": "hi"},
            {"role": "assistant", "content": "hello"},
            {"role": "user", "content": "more"},
            {"role": "assistant", "content": "ok"},
        ]},
    ]
    path = tmp_path / "chat.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")

    docs = load_token_documents(str(path))
    toks, flags = docs[0]
    assert len(toks) == len(flags)
    # byte-level template: assistant bodies are "hello\n" and "ok\n"
    assert sum(flags) == len(b"hello\n") + len(b"ok\n")
    # the masked-in bytes are exactly the assistant content
    masked = bytes(t for t, fl in zip(toks, flags) if fl)
    assert masked == b"hello\nok\n"
    # headers are masked out
    unmasked = bytes(t for t, fl in zip(toks, flags) if not fl)
    assert b"<|assistant|>" in unmasked and b"<|user|>" in unmasked

    # and through the batch pipeline: loss_mask present and sparse
    batches = jsonl_token_batches(str(path), batch_size=2, seq_len=32, seed=0)
    batch = next(batches)
    assert "loss_mask" in batch
    assert 0 < np.sum(batch["loss_mask"]) < batch["loss_mask"].size


def test_chat_messages_with_real_tokenizer_no_special_token_litter(tmp_path):
    """Fragments must encode WITHOUT special tokens: a tokenizer whose
    post-processor adds BOS per call must not litter BOS mid-sequence."""
    import json

    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace
    from tokenizers.processors import TemplateProcessing

    from finetune_controller_tpu.data.loader import load_token_documents

    vocab = {"<s>": 0, "hi": 1, "hello": 2, "<|user|>": 3, "<|assistant|>": 4,
             "[UNK]": 5}
    tok = Tokenizer(WordLevel(vocab, unk_token="[UNK]"))
    tok.pre_tokenizer = Whitespace()
    tok.post_processor = TemplateProcessing(
        single="<s> $A", special_tokens=[("<s>", 0)]
    )
    tok_file = tmp_path / "tok.json"
    tok.save(str(tok_file))

    path = tmp_path / "chat.jsonl"
    path.write_text(json.dumps({"messages": [
        {"role": "user", "content": "hi"},
        {"role": "assistant", "content": "hello"},
    ]}) + "\n")
    docs = load_token_documents(str(path), tokenizer_file=str(tok_file))
    toks, flags = docs[0]
    assert toks.count(0) == 0, toks  # no BOS anywhere in the fragments
    # assistant body is exactly "hello"
    assert [t for t, fl in zip(toks, flags) if fl] == [2]

    # malformed messages fail with the loader's ValueError contract
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"messages": "hi"}) + "\n")
    import pytest

    with pytest.raises(ValueError, match="messages"):
        load_token_documents(str(bad))


def test_chat_rows_without_assistant_role_fail_loudly(tmp_path):
    """An all-masked chat corpus (wrong role name) must error, not silently
    train on nothing."""
    import json

    import pytest

    from finetune_controller_tpu.data.loader import load_token_documents

    path = tmp_path / "model_role.jsonl"
    path.write_text(json.dumps({"messages": [
        {"role": "user", "content": "hi"},
        {"role": "model", "content": "hello"},  # Gemini-style role name
    ]}) + "\n")
    with pytest.raises(ValueError, match="assistant"):
        load_token_documents(str(path))


def test_image_decode_paths(tmp_path):
    """data/images.py reference forms: npy path, grayscale promotion, bare
    base64, data URI, and the loud failure for junk refs."""
    import base64

    import pytest

    from finetune_controller_tpu.data.images import (
        CLIP_MEAN,
        CLIP_STD,
        decode_image,
        preprocess_image,
    )

    # float .npy in [0,1] passes through; grayscale (H, W) promotes to 3ch
    arr = np.random.default_rng(0).uniform(0, 1, (6, 5)).astype(np.float32)
    np.save(tmp_path / "g.npy", arr)
    img = decode_image(str(tmp_path / "g.npy"))
    assert img.shape == (6, 5, 3)
    np.testing.assert_allclose(img[..., 0], arr, atol=1e-6)

    # uint8 .npy rescales to [0,1]
    np.save(tmp_path / "u.npy", (arr * 255).astype(np.uint8)[..., None].repeat(3, -1))
    assert decode_image(str(tmp_path / "u.npy")).max() <= 1.0

    # bare base64 of an npy payload
    import io

    buf = io.BytesIO()
    np.save(buf, arr)
    b64 = base64.b64encode(buf.getvalue()).decode()
    assert decode_image(b64).shape == (6, 5, 3)
    assert decode_image("data:application/npy;base64," + b64).shape == (6, 5, 3)

    # normalization: "clip" centers, "none" keeps [0,1]
    raw = preprocess_image(str(tmp_path / "g.npy"), 4, normalize="none")
    assert raw.shape == (4, 4, 3) and raw.min() >= 0.0
    cl = preprocess_image(str(tmp_path / "g.npy"), 4, normalize="clip")
    np.testing.assert_allclose(cl, (raw - CLIP_MEAN) / CLIP_STD, atol=1e-5)

    with pytest.raises(FileNotFoundError, match="neither"):
        decode_image("no/such/file.png!!")
    # a typo'd EXTENSIONLESS path can be valid base64 of garbage bytes —
    # that must surface as the intended error, not an uncaught decode
    # failure from inside the image decoder (PIL's UnidentifiedImageError)
    with pytest.raises(FileNotFoundError, match="neither"):
        decode_image("imahetypo+00")
    # a file suffix rules the base64 fallback out entirely: a missing
    # "cat0.png" is a missing FILE, never a base64 payload
    with pytest.raises(FileNotFoundError, match="suffix"):
        decode_image("cat0.png")
    with pytest.raises(ValueError, match="normalize"):
        preprocess_image(str(tmp_path / "g.npy"), 4, normalize="bogus")
