import json

import numpy as np

from finetune_controller_tpu.data.loader import (
    batches_from_tokens,
    jsonl_token_batches,
    load_token_documents,
    pack_documents,
)


def test_pack_documents_segments():
    docs = [[1, 2, 3], [4, 5, 6, 7, 8]]
    tokens, segs = pack_documents(docs, seq_len=4)
    assert tokens.shape == (2, 4)
    assert segs.tolist() == [[1, 1, 1, 2], [2, 2, 2, 2]]


def test_pack_pads_tiny_dataset():
    tokens, segs = pack_documents([[9, 9]], seq_len=8)
    assert tokens.shape == (1, 8)
    assert segs[0, :2].tolist() == [1, 1]
    assert segs[0, 2:].sum() == 0


def test_jsonl_loading_and_sharding(tmp_path):
    path = tmp_path / "data.jsonl"
    with open(path, "w") as f:
        for i in range(50):
            f.write(json.dumps({"tokens": list(range(i, i + 20))}) + "\n")
    docs = load_token_documents(str(path))
    assert len(docs) == 50

    it0 = jsonl_token_batches(str(path), batch_size=2, seq_len=16, shard_index=0, shard_count=2)
    it1 = jsonl_token_batches(str(path), batch_size=2, seq_len=16, shard_index=1, shard_count=2)
    b0, b1 = next(it0), next(it1)
    assert b0["tokens"].shape == (2, 16)
    # different shards see different blocks
    assert not np.array_equal(b0["tokens"], b1["tokens"])


def test_text_rows_byte_fallback(tmp_path):
    path = tmp_path / "text.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"text": "hello"}) + "\n")
    docs = load_token_documents(str(path))
    assert docs[0] == list(b"hello")


def test_batches_have_loss_mask_and_segments():
    tokens, segs = pack_documents([list(range(100))], seq_len=10)
    b = next(batches_from_tokens(tokens, segs, batch_size=2))
    assert set(b) >= {"tokens", "loss_mask", "segment_ids"}
    assert b["loss_mask"].dtype == np.float32
