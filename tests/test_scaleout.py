"""Tests for scale-out compute: Pallas flash attention, ring attention (SP),
MoE expert parallelism — the strategies SURVEY.md §2.3 lists as greenfield
obligations (SP/CP, EP) plus the hand-written kernel path.

All run on the 8-virtual-device CPU mesh (Pallas in interpreter mode).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.ops.attention import causal_attention, xla_causal_attention
from finetune_controller_tpu.ops.pallas.flash_attention import flash_attention
from finetune_controller_tpu.parallel.mesh import MeshSpec
from finetune_controller_tpu.parallel.ring import ring_attention_sharded, ring_mesh
from finetune_controller_tpu.parallel.sharding import LLAMA_RULES


def _qkv(b=2, s=64, h=4, hkv=2, d=16, dtype=jnp.float32):
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d), dtype)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d), dtype)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d), dtype)
    return q, k, v


# ---------------------------------------------------------------------------
# Pallas flash attention
# ---------------------------------------------------------------------------


def test_flash_attention_matches_xla():
    q, k, v = _qkv()
    seg = (jnp.arange(64)[None, :] // 32).astype(jnp.int32).repeat(2, 0)
    ref = xla_causal_attention(q, k, v, segment_ids=seg)
    out = flash_attention(q, k, v, segment_ids=seg, block_q=16, block_k=16)
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_flash_attention_grads_match_xla():
    q, k, v = _qkv(s=32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=8, block_k=8) ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_causal_attention(q, k, v) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-4)


def test_flash_attention_grads_match_xla_gqa_segments_uneven():
    """Pallas backward (dQ + dK/dV kernels) vs XLA autodiff with everything
    turned on at once: GQA group reduction, segment masks, ragged tail block."""
    q, k, v = _qkv(s=40)
    seg = (jnp.arange(40)[None, :] // 20).astype(jnp.int32).repeat(2, 0)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, segment_ids=seg, block_q=16, block_k=16)
        return (out ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_causal_attention(q, k, v, segment_ids=seg) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-4)


def test_flash_attention_uneven_blocks():
    # S=48 with block 32: remainder block exercises the causal frontier math
    q, k, v = _qkv(s=48)
    ref = xla_causal_attention(q, k, v)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_flash_tuning_defaults_resolution():
    """Unset knobs resolve to the measured TPU winners (block 1024; exp
    dtype following the input dtype — tpu_session.jsonl kernel A/B)."""
    from finetune_controller_tpu.ops.pallas.flash_attention import (
        DEFAULT_BLOCK,
        _resolve_tuning,
    )

    q_bf16 = jnp.zeros((1, 8, 1, 4), jnp.bfloat16)
    q_f32 = jnp.zeros((1, 8, 1, 4), jnp.float32)
    assert DEFAULT_BLOCK == 1024
    assert _resolve_tuning(q_bf16, None, None, None) == (
        DEFAULT_BLOCK, DEFAULT_BLOCK, "bfloat16")
    assert _resolve_tuning(q_f32, None, None, None) == (
        DEFAULT_BLOCK, DEFAULT_BLOCK, "float32")
    # explicit values always win over the defaults
    assert _resolve_tuning(q_bf16, 256, 128, "float32") == (256, 128, "float32")


def test_flash_tuning_spec_and_env_precedence(monkeypatch):
    """Round-5: the job's typed kernel config (LlamaConfig.kernel_tuning())
    seeds the flash knobs; FTC_* env vars override per knob."""
    from finetune_controller_tpu.models.llama import LlamaConfig
    from finetune_controller_tpu.ops.attention import flash_tuning_kwargs

    for var in ("FTC_FLASH_BLOCK_Q", "FTC_FLASH_BLOCK_K",
                "FTC_FLASH_EXP_DTYPE"):
        monkeypatch.delenv(var, raising=False)

    cfg = LlamaConfig(
        flash_block_q=256, flash_block_k=512, flash_exp_dtype="bfloat16",
        ulysses_inner="pallas", ring_inner="flash",
    )
    tuning = cfg.kernel_tuning()
    assert tuning == {
        "block_q": 256, "block_k": 512, "exp_dtype": "bfloat16",
        "ring_inner": "flash", "ulysses_inner": "pallas",
    }
    assert flash_tuning_kwargs(tuning) == {
        "block_q": 256, "block_k": 512, "exp_dtype": "bfloat16"
    }
    # env overrides spec, knob by knob
    monkeypatch.setenv("FTC_FLASH_BLOCK_Q", "1024")
    monkeypatch.setenv("FTC_FLASH_EXP_DTYPE", "float32")
    assert flash_tuning_kwargs(tuning) == {
        "block_q": 1024, "block_k": 512, "exp_dtype": "float32"
    }
    # defaults stay empty; invalid spec values fail loudly
    assert LlamaConfig().kernel_tuning() == {}
    import pytest

    with pytest.raises(ValueError, match="multiple of 128"):
        flash_tuning_kwargs({"block_q": 100})
    with pytest.raises(ValueError, match="float32 or bfloat16"):
        flash_tuning_kwargs({"exp_dtype": "fp8"})


def test_kernel_tuning_flows_from_job_spec():
    """model_overrides on a job spec land in the resolved LlamaConfig — the
    API path for shipping measured kernel winners (round-3 weak #5)."""
    from finetune_controller_tpu.controller.examples import (
        LoRASFTArguments,
        TinyTestLoRA,
    )
    from finetune_controller_tpu.train.cli import build_model_config

    class TunedTiny(TinyTestLoRA):
        model_name = "tiny-tuned-lora"
        model_overrides = {"flash_block_q": 256, "ulysses_inner": "pallas"}

    spec = TunedTiny(
        training_arguments=LoRASFTArguments()
    ).build_trainer_spec("j1", "/tmp/a")
    assert spec["model"]["overrides"] == {
        "flash_block_q": 256, "ulysses_inner": "pallas"
    }
    cfg = build_model_config(spec)
    assert cfg.flash_block_q == 256 and cfg.ulysses_inner == "pallas"
    assert cfg.kernel_tuning() == {
        "block_q": 256, "ulysses_inner": "pallas"
    }


def test_flash_attention_bf16_default_exp_matches_xla():
    """bf16 inputs take the bf16-exp path by default; parity vs the f32-exp
    XLA oracle stays within bf16 rounding noise."""
    q, k, v = _qkv(dtype=jnp.bfloat16)
    ref = xla_causal_attention(q, k, v)
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    np.testing.assert_allclose(
        out.astype(np.float32), ref.astype(np.float32), atol=3e-2)


def test_dispatcher_pallas_path():
    q, k, v = _qkv(s=32)
    out = causal_attention(q, k, v, impl="pallas")
    ref = causal_attention(q, k, v, impl="xla")
    np.testing.assert_allclose(out, ref, atol=1e-5)


# ---------------------------------------------------------------------------
# Ring attention (sequence/context parallelism)
# ---------------------------------------------------------------------------


def test_ring_attention_matches_xla(devices8):
    mesh = MeshSpec(dp=2, fsdp=1, sp=4).build(devices8)
    q, k, v = _qkv(b=4, s=64)
    seg = (jnp.arange(64)[None, :] // 16).astype(jnp.int32).repeat(4, 0)
    ref = xla_causal_attention(q, k, v, segment_ids=seg)
    out = ring_attention_sharded(q, k, v, segment_ids=seg, mesh=mesh)
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_ring_attention_grads(devices8):
    mesh = MeshSpec(dp=1, fsdp=2, sp=4).build(devices8)
    q, k, v = _qkv(b=2, s=32)

    g1 = jax.grad(
        lambda q, k, v: (ring_attention_sharded(q, k, v, mesh=mesh) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: (xla_causal_attention(q, k, v) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-4)


def test_ring_dispatch_through_model_config(devices8):
    """attention_impl='ring' + installed mesh flows through a full model."""
    mesh = MeshSpec(dp=1, fsdp=2, sp=4).build(devices8)
    cfg = PRESETS["tiny-test"].replace(attention_impl="ring", remat=False)
    model = LlamaForCausalLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0, cfg.vocab_size)
    variables = model.init({"params": jax.random.PRNGKey(1)}, tokens)
    with ring_mesh(mesh):
        logits_ring = model.apply(variables, tokens)
    logits_ref = model.apply(
        variables, tokens,
    )  # without mesh installed the ring impl falls back to plain attention
    # bf16 compute: ring and dense paths differ by accumulation order only
    np.testing.assert_allclose(logits_ring, logits_ref, atol=0.15)


# ---------------------------------------------------------------------------
# MoE expert parallelism
# ---------------------------------------------------------------------------


def test_moe_model_forward_and_aux():
    cfg = PRESETS["tiny-moe-test"].replace(remat=False)
    model = LlamaForCausalLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, cfg.vocab_size)
    variables = model.init({"params": jax.random.PRNGKey(1)}, tokens)
    logits, collections = model.apply(tokens=tokens, variables=variables, mutable=("moe_aux",))
    assert logits.shape == (2, 16, cfg.vocab_size)
    from finetune_controller_tpu.models.moe import moe_aux_loss

    aux = moe_aux_loss(collections)
    # Switch aux loss is >= 1 (equals 1 at perfectly uniform routing)
    assert float(aux) >= 0.9 * cfg.n_layers


def test_moe_params_have_expert_axis_sharding(devices8):
    mesh = MeshSpec(dp=1, fsdp=2, ep=4).build(devices8)
    cfg = PRESETS["tiny-moe-test"].replace(remat=False)
    model = LlamaForCausalLM(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    shapes = jax.eval_shape(lambda: model.init({"params": jax.random.PRNGKey(0)}, tokens))
    shardings = LLAMA_RULES.tree_specs(shapes)
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in kp): spec
        for kp, spec in jax.tree_util.tree_flatten_with_path(
            shardings, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
        )[0]
    }
    gate_specs = [s for p, s in flat.items() if "experts_gate" in p]
    assert gate_specs, flat.keys()
    # leading layer-scan axis is None, then experts over 'ep'
    assert all(s[1] == "ep" or s[0] == "ep" for s in gate_specs), gate_specs


def test_moe_trains_end_to_end(devices8):
    """Full trainer loop on the MoE preset over an ep mesh — loss decreases."""
    from finetune_controller_tpu.data.synthetic import synthetic_batches
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

    mesh = MeshSpec(dp=1, fsdp=2, ep=4).build(devices8)
    cfg = PRESETS["tiny-moe-test"]
    tcfg = TrainConfig(
        mode="full", learning_rate=5e-2, warmup_steps=2, total_steps=12,
        batch_size=8, seq_len=16, log_every=4, checkpoint_every=1000,
    )
    trainer = Trainer(cfg.replace(lora=cfg.lora), tcfg, mesh=mesh)
    batches = synthetic_batches(
        batch_size=tcfg.batch_size, seq_len=tcfg.seq_len,
        vocab_size=cfg.vocab_size, task="increment", seed=0,
    )
    state = trainer.init_state()
    losses = []
    it = iter(batches)
    for _ in range(tcfg.total_steps):
        state, metrics = trainer.step(state, next(it))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses
    assert "moe_aux" in metrics

# ---------------------------------------------------------------------------
# int4 QLoRA
# ---------------------------------------------------------------------------


def test_int4_quantization_roundtrip():
    from finetune_controller_tpu.models.quant import dequantize_int4, quantize_int4

    w = jax.random.normal(jax.random.PRNGKey(0), (128, 32), jnp.float32) * 0.1
    packed, scales = quantize_int4(w, block_size=64)
    assert packed.shape == (64, 32) and packed.dtype == jnp.uint8
    assert scales.shape == (2, 32)
    deq = dequantize_int4(packed, scales, dtype=jnp.float32)
    # int4 with blockwise scales: relative error bounded by scale/2 per element
    err = np.abs(np.asarray(deq - w))
    bound = np.asarray(scales, np.float32).repeat(64, axis=0) * 0.51
    assert (err <= bound + 1e-6).all()
    # memory: ~4.25 bits/weight
    nbytes = packed.nbytes + scales.nbytes
    assert nbytes < w.nbytes / 6


def test_qlora_model_trains_and_shrinks_memory(devices8):
    from finetune_controller_tpu.data.synthetic import synthetic_batches
    from finetune_controller_tpu.train.trainer import TrainConfig, Trainer
    from finetune_controller_tpu.models.lora import LoRAConfig

    cfg = PRESETS["tiny-test"].replace(
        quantize_base=True, lora=LoRAConfig(rank=8), remat=False
    )
    tcfg = TrainConfig(
        mode="lora", learning_rate=1e-1, warmup_steps=2, total_steps=25,
        batch_size=8, seq_len=16, log_every=5, checkpoint_every=1000,
    )
    mesh = MeshSpec(dp=1, fsdp=2, tp=2).build(devices8[:4])
    trainer = Trainer(cfg, tcfg, mesh=mesh)
    state = trainer.init_state()
    # frozen projection kernels are stored packed uint8
    flat = jax.tree_util.tree_flatten_with_path(state.frozen)[0]
    packed = [v for kp, v in flat if "kernel_packed" in str(kp)]
    assert packed and all(v.dtype == jnp.uint8 for v in packed)
    assert not [kp for kp, _ in flat
                if str(kp).endswith("q_proj'], key='kernel')")]
    batches = synthetic_batches(
        batch_size=8, seq_len=16, vocab_size=cfg.vocab_size, task="increment",
        seed=0,
    )
    it = iter(batches)
    losses = []
    for _ in range(25):
        state, metrics = trainer.step(state, next(it))
        losses.append(float(metrics["loss"]))
    # compare window means: single steps are noisy at toy scale, and rank-8
    # adapters on a frozen random base move the loss slowly
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.1, losses


def test_ring_flash_inner_matches_xla_inner(devices8):
    """The Pallas flash ring inner (per-hop streaming kernel + logsumexp
    merge) matches both the XLA ring inner and the unsharded oracle,
    forward and gradients, with packed-document segments."""
    mesh = MeshSpec(dp=2, fsdp=1, sp=4).build(devices8)
    q, k, v = _qkv(b=2, s=64)
    seg = (jnp.arange(64)[None, :] // 24).astype(jnp.int32).repeat(2, 0)

    ref = xla_causal_attention(q, k, v, segment_ids=seg)
    out = ring_attention_sharded(
        q, k, v, segment_ids=seg, mesh=mesh, inner="flash")
    np.testing.assert_allclose(out, ref, atol=2e-5)

    g_flash = jax.grad(
        lambda q, k, v: (ring_attention_sharded(
            q, k, v, segment_ids=seg, mesh=mesh, inner="flash") ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (xla_causal_attention(
            q, k, v, segment_ids=seg) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=1e-4)


def test_flash_with_lse_full_attention_mode():
    """causal=False kernel mode: full attention + differentiable lse."""
    from finetune_controller_tpu.ops.pallas.flash_attention import (
        flash_attention_with_lse,
    )

    q, k, v = _qkv(b=1, s=48)
    out, lse = flash_attention_with_lse(
        q, k, v, causal=False, block_q=16, block_k=16)
    # full softmax reference
    h, hkv = q.shape[2], k.shape[2]
    g = h // hkv
    qr = q.reshape(1, 48, hkv, g, -1) * q.shape[-1] ** -0.5
    sc = jnp.einsum("bskgd,btkd->bkgst", qr, k).astype(jnp.float32)
    ref_lse = jax.nn.logsumexp(sc, axis=-1, keepdims=True)
    p = jnp.exp(sc - ref_lse)
    ref = jnp.einsum("bkgst,btkd->bskgd", p, v).reshape(q.shape)
    np.testing.assert_allclose(out, ref, atol=2e-5)
    np.testing.assert_allclose(
        lse, ref_lse.squeeze(-1).reshape(1, h, 48)[..., None], atol=2e-5)


def test_ulysses_attention_matches_xla(devices8):
    """Ulysses SP (all-to-all head sharding) is bit-exact vs the unsharded
    oracle — the local kernel computes the same full-sequence attention."""
    from finetune_controller_tpu.parallel.ulysses import (
        ulysses_attention_sharded,
    )

    mesh = MeshSpec(dp=2, fsdp=1, sp=2).build(devices8[:4])
    q, k, v = _qkv(b=2, s=64)
    seg = (jnp.arange(64)[None, :] // 24).astype(jnp.int32).repeat(2, 0)

    ref = xla_causal_attention(q, k, v, segment_ids=seg)
    out = ulysses_attention_sharded(q, k, v, segment_ids=seg, mesh=mesh)
    np.testing.assert_allclose(out, ref, atol=1e-6)

    g_u = jax.grad(
        lambda q, k, v: (ulysses_attention_sharded(
            q, k, v, segment_ids=seg, mesh=mesh) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (xla_causal_attention(
            q, k, v, segment_ids=seg) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_u, g_ref):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_ulysses_requires_kv_head_divisibility(devices8):
    from finetune_controller_tpu.parallel.ulysses import (
        ulysses_attention_sharded,
    )

    mesh = MeshSpec(dp=1, fsdp=2, sp=4).build(devices8)
    q, k, v = _qkv(b=2, s=64)  # hkv=2 < sp=4
    with pytest.raises(ValueError, match="divide n_kv_heads"):
        ulysses_attention_sharded(q, k, v, mesh=mesh)


def test_ulysses_dispatch_through_model_config(devices8):
    """attention_impl='ulysses' trains through the full model on an sp mesh
    and matches the XLA attention reference."""
    mesh = MeshSpec(dp=1, fsdp=2, sp=2).build(devices8[:4])
    cfg = PRESETS["tiny-test"].replace(attention_impl="ulysses", remat=False)
    model = LlamaForCausalLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0, cfg.vocab_size)
    variables = model.init({"params": jax.random.PRNGKey(1)}, tokens)
    with ring_mesh(mesh):
        logits_u = model.apply(variables, tokens)
    logits_ref = model.apply(
        variables, tokens,
        deterministic=True,
    )
    np.testing.assert_allclose(
        np.asarray(logits_u), np.asarray(logits_ref), atol=2e-4)


def test_ring_unknown_inner_rejected(devices8):
    mesh = MeshSpec(dp=2, fsdp=1, sp=4).build(devices8)
    q, k, v = _qkv(b=2, s=64)
    with pytest.raises(ValueError, match="unknown ring inner"):
        ring_attention_sharded(q, k, v, mesh=mesh, inner="vulkan")


def test_ulysses_unknown_local_kernel_rejected(devices8):
    from finetune_controller_tpu.parallel.ulysses import (
        ulysses_attention_sharded,
    )

    mesh = MeshSpec(dp=2, fsdp=1, sp=2).build(devices8[:4])
    q, k, v = _qkv(b=2, s=64)
    with pytest.raises(ValueError, match="unknown ulysses local kernel"):
        ulysses_attention_sharded(q, k, v, mesh=mesh, impl="ring")
