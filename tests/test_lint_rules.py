"""Per-rule fixture tests for ftc-lint (analysis/engine.py + rules).

Each rule gets the same treatment: it fires on a known-bad snippet, stays
quiet on the clean rewrite, and honors an inline suppression.
"""

import json
import textwrap

import pytest

from finetune_controller_tpu.analysis import lint_source
from finetune_controller_tpu.analysis.engine import all_rules, lint_paths, main


def _lint(src: str, rule: str | None = None):
    rules = all_rules()
    if rule is not None:
        rules = {rule: rules[rule]}
    return lint_source(textwrap.dedent(src), "<fixture>", rules)


def _active(src: str, rule: str | None = None):
    return [f for f in _lint(src, rule) if not f.suppressed]


def _ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# host-sync-in-jit
# ---------------------------------------------------------------------------


def test_host_sync_in_jit_fires_on_item_and_print():
    src = """
        import jax

        @jax.jit
        def step(state, batch):
            loss = compute(state, batch)
            print(loss)
            return loss.item()
    """
    found = _active(src, "host-sync-in-jit")
    assert len(found) == 2
    assert {"print", ".item()"} <= {
        "print" if "print" in f.message else ".item()" for f in found
    }


def test_host_sync_detects_jit_by_reference_and_np_asarray():
    src = """
        import jax
        import numpy as np

        def train_step(state, batch):
            return np.asarray(batch["x"])

        fn = jax.jit(train_step, donate_argnums=(0,))
    """
    found = _active(src, "host-sync-in-jit")
    assert len(found) == 1
    assert "np.asarray" in found[0].message


def test_host_sync_quiet_on_clean_jit_and_host_code():
    src = """
        import jax
        import numpy as np

        @jax.jit
        def step(state, batch):
            return state + batch["x"].sum()

        def host_loop(metrics):
            # host-side float()/print are fine — not a traced body
            print(float(np.asarray(metrics)))
    """
    assert _active(src, "host-sync-in-jit") == []


def test_host_sync_suppression_honored():
    src = """
        import jax

        @jax.jit
        def step(state):
            print(state)  # ftc: ignore[host-sync-in-jit] -- trace-time banner, prints once per compile
            return state
    """
    findings = _lint(src, "host-sync-in-jit")
    assert len(findings) == 1 and findings[0].suppressed


# ---------------------------------------------------------------------------
# prng-key-reuse
# ---------------------------------------------------------------------------


def test_key_reuse_fires_on_double_consumption():
    src = """
        import jax

        def sample(shape):
            key = jax.random.PRNGKey(0)
            a = jax.random.normal(key, shape)
            b = jax.random.uniform(key, shape)
            return a, b
    """
    found = _active(src, "prng-key-reuse")
    assert len(found) == 1
    assert "`key`" in found[0].message


def test_key_reuse_quiet_with_split_and_rebind():
    src = """
        import jax

        def sample(shape):
            key = jax.random.PRNGKey(0)
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, shape)
            b = jax.random.uniform(k2, shape)
            key = jax.random.fold_in(k1, 7)
            c = jax.random.normal(key, shape)
            return a, b, c
    """
    assert _active(src, "prng-key-reuse") == []


def test_key_reuse_suppression_honored():
    src = """
        import jax

        def sample(shape):
            key = jax.random.PRNGKey(0)
            a = jax.random.normal(key, shape)
            # ftc: ignore[prng-key-reuse] -- correlated draws are intentional here
            b = jax.random.uniform(key, shape)
            return a, b
    """
    findings = _lint(src, "prng-key-reuse")
    assert len(findings) == 1 and findings[0].suppressed


# ---------------------------------------------------------------------------
# recompile-jit-in-loop / recompile-fresh-callable
# ---------------------------------------------------------------------------


def test_jit_in_loop_fires():
    src = """
        import jax

        def run(fns, x):
            for f in fns:
                x = jax.jit(f)(x)
            return x
    """
    assert len(_active(src, "recompile-jit-in-loop")) == 1


def test_jit_in_loop_quiet_when_hoisted_or_deferred():
    src = """
        import jax

        jitted = jax.jit(lambda x: x + 1)

        def run(xs):
            out = [jitted(x) for x in xs]
            for x in xs:
                # a def inside the loop defers the jit to call time
                def make(f):
                    return jax.jit(f)
            return out
    """
    assert _active(src, "recompile-jit-in-loop") == []


def test_fresh_callable_fires_inside_function_not_module_level():
    src = """
        import jax
        import functools

        module_level = jax.jit(functools.partial(max))  # once at import: fine

        def bench(f, x):
            g = jax.jit(jax.grad(f))
            return g(x)
    """
    found = _active(src, "recompile-fresh-callable")
    assert len(found) == 1
    assert found[0].line > 6  # the one inside bench(), not the module-level one


def test_recompile_suppressions_honored():
    src = """
        import jax

        def run(fns, x):
            for f in fns:
                # ftc: ignore[recompile-jit-in-loop] -- one compile per impl is the point
                x = jax.jit(f)(x)
            return x
    """
    findings = _lint(src, "recompile-jit-in-loop")
    assert len(findings) == 1 and findings[0].suppressed


# ---------------------------------------------------------------------------
# missing-donation
# ---------------------------------------------------------------------------


def test_missing_donation_fires_on_call_and_decorator_forms():
    src = """
        import jax
        from functools import partial

        def train_step(state, batch):
            return state

        fn = jax.jit(train_step)  # no donate_argnums

        @partial(jax.jit)
        def update_step(state, grads):
            return state
    """
    found = _active(src, "missing-donation")
    assert len(found) == 2


def test_missing_donation_quiet_when_donated_or_eval():
    src = """
        import jax
        from functools import partial

        def train_step(state, batch):
            return state

        fn = jax.jit(train_step, donate_argnums=(0,))

        @partial(jax.jit, donate_argnames=("state",))
        def update_step(state, grads):
            return state

        def eval_step(state, batch):
            return state

        efn = jax.jit(eval_step)  # eval reuses state: donation would be wrong
    """
    assert _active(src, "missing-donation") == []


def test_missing_donation_suppression_honored():
    src = """
        import jax

        def train_step(state, batch):
            return state

        # ftc: ignore[missing-donation] -- state aliasing measured irrelevant here
        fn = jax.jit(train_step)
    """
    findings = _lint(src, "missing-donation")
    assert len(findings) == 1 and findings[0].suppressed


# ---------------------------------------------------------------------------
# silent-except
# ---------------------------------------------------------------------------


def test_silent_except_fires_on_broad_pass():
    src = """
        def tick():
            try:
                work()
            except Exception:
                pass
    """
    assert len(_active(src, "silent-except")) == 1


def test_silent_except_fires_on_bare_except():
    src = """
        def tick():
            try:
                work()
            except:
                result = None
    """
    assert len(_active(src, "silent-except")) == 1


def test_silent_except_quiet_when_logged_narrowed_or_reraised():
    src = """
        import logging

        logger = logging.getLogger(__name__)

        def tick():
            try:
                work()
            except Exception:
                logger.exception("tick failed")
            try:
                work()
            except Exception as exc:
                raise RuntimeError("wrapped") from exc
            try:
                work()
            except (OSError, ValueError):
                pass  # narrow types may stay silent
    """
    assert _active(src, "silent-except") == []


def test_silent_except_suppression_honored():
    src = """
        def tick():
            try:
                work()
            except Exception:  # ftc: ignore[silent-except] -- probe failure means feature off
                pass
    """
    findings = _lint(src, "silent-except")
    assert len(findings) == 1 and findings[0].suppressed


# ---------------------------------------------------------------------------
# shared-mutable-without-lock
# ---------------------------------------------------------------------------


def test_shared_mutable_fires_on_unlocked_thread_target():
    src = """
        import threading

        class Counter:
            def __init__(self):
                self.n = 0
                self.items = []
                self._thread = threading.Thread(target=self._work)

            def _work(self):
                self.n += 1
                self.items.append(1)
    """
    found = _active(src, "shared-mutable-without-lock")
    assert len(found) == 2


def test_shared_mutable_quiet_under_lock_and_off_thread():
    src = """
        import threading

        class Counter:
            def __init__(self):
                self.n = 0
                self._lock = threading.Lock()
                self._thread = threading.Thread(target=self._work)

            def _work(self):
                with self._lock:
                    self.n += 1
                self.done = True  # plain rebind: atomic, unflagged

            def not_a_thread_target(self):
                self.n += 1
    """
    assert _active(src, "shared-mutable-without-lock") == []


def test_shared_mutable_suppression_honored():
    src = """
        import threading

        class Counter:
            def __init__(self):
                self._thread = threading.Thread(target=self._work)

            def _work(self):
                # ftc: ignore[shared-mutable-without-lock] -- single writer; drained after join
                self.errors.append(1)
    """
    findings = _lint(src, "shared-mutable-without-lock")
    assert len(findings) == 1 and findings[0].suppressed


# ---------------------------------------------------------------------------
# blocking-io-in-async
# ---------------------------------------------------------------------------


def test_blocking_io_fires_on_sleep_requests_open():
    src = """
        import time
        import requests

        async def handler(path):
            time.sleep(1)
            r = requests.get("http://x")
            with open(path) as f:
                return f, r
    """
    found = _active(src, "blocking-io-in-async")
    assert len(found) == 3


def test_blocking_io_quiet_on_async_idioms_and_sync_defs():
    src = """
        import asyncio
        import time

        async def handler(path):
            await asyncio.sleep(1)
            data = await asyncio.to_thread(_read, path)
            return data

        def _read(path):
            # sync helper: runs via to_thread, off the loop
            time.sleep(0.1)
            with open(path) as f:
                return f.read()
    """
    assert _active(src, "blocking-io-in-async") == []


def test_blocking_io_suppression_honored():
    src = """
        async def handler(path):
            with open(path) as f:  # ftc: ignore[blocking-io-in-async] -- local tmpfile, metadata-only open
                return f.name
    """
    findings = _lint(src, "blocking-io-in-async")
    assert len(findings) == 1 and findings[0].suppressed


# ---------------------------------------------------------------------------
# unbounded-retry
# ---------------------------------------------------------------------------


def test_unbounded_retry_fires_on_exitless_sleep_loop():
    src = """
        import time

        def poll_forever():
            while True:
                check()
                time.sleep(5)
    """
    found = _active(src, "unbounded-retry")
    assert len(found) == 1
    assert "no break/return/raise" in found[0].message


def test_unbounded_retry_fires_on_unbounded_except_sleep():
    src = """
        import time

        def fetch(url):
            while True:
                try:
                    return request(url)
                except Exception:
                    log_failure()
                    time.sleep(1)
    """
    found = _active(src, "unbounded-retry")
    assert len(found) == 1
    assert "except handler" in found[0].message


def test_unbounded_retry_quiet_on_bounded_and_conditioned_loops():
    src = """
        import asyncio
        import time

        def bounded(url):
            # for-range with a final raise: the house pattern
            for attempt in range(5):
                try:
                    return request(url)
                except Exception:
                    time.sleep(1)
            raise RuntimeError("exhausted")

        def counted(url):
            attempt = 0
            while True:
                try:
                    return request(url)
                except Exception:
                    attempt += 1
                    if attempt >= 5:
                        raise
                    time.sleep(1)

        async def daemon(self):
            # condition-tested loop (reconciler shape): not while-True
            while not self.stop.is_set():
                await self.tick()
                await asyncio.sleep(2)

        def tail(f):
            # while True WITH an exit and no except-sleep: fine
            while True:
                line = f.readline()
                if not line:
                    return
                time.sleep(0.1)

        def deadline_bounded(url):
            # the bound lives in the loop body, outside the try: still bounded
            deadline = time.monotonic() + 60
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError("gave up")
                try:
                    return request(url)
                except Exception:
                    time.sleep(1)
    """
    assert _active(src, "unbounded-retry") == []


def test_unbounded_retry_ignores_nested_def_return():
    # a return inside a nested def does NOT exit the outer loop
    src = """
        import time

        def outer():
            while True:
                def cb():
                    return 1
                time.sleep(5)
    """
    found = _active(src, "unbounded-retry")
    assert len(found) == 1


def test_unbounded_retry_suppression_honored():
    src = """
        import time

        def daemon():
            while True:  # ftc: ignore[unbounded-retry] -- intentional forever daemon
                work()
                time.sleep(5)
    """
    findings = _lint(src, "unbounded-retry")
    assert len(findings) == 1 and findings[0].suppressed


# ---------------------------------------------------------------------------
# engine behavior
# ---------------------------------------------------------------------------


def test_suppression_matches_line_above_and_multiple_ids():
    src = """
        import jax

        def train_step(state):
            return state

        # ftc: ignore[missing-donation,recompile-jit-in-loop] -- fixture
        fn = jax.jit(train_step)
    """
    findings = _lint(src, "missing-donation")
    assert len(findings) == 1 and findings[0].suppressed


def test_unrelated_suppression_does_not_silence():
    src = """
        def tick():
            try:
                work()
            except Exception:  # ftc: ignore[host-sync-in-jit] -- wrong id
                pass
    """
    found = _active(src, "silent-except")
    assert len(found) == 1


def test_rule_registry_has_both_planes():
    rules = all_rules()
    planes = {r.plane for r in rules.values()}
    assert planes == {"compute", "controller"}
    assert len(rules) >= 8


def test_cli_json_format_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    rc = main([str(bad), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["counts"]["active"] == 1
    assert out["findings"][0]["rule"] == "silent-except"

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert main([str(clean)]) == 0

    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    assert main([str(broken)]) == 2


def test_cli_select_and_ignore(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    assert main([str(bad), "--select", "host-sync-in-jit"]) == 0
    capsys.readouterr()
    assert main([str(bad), "--ignore", "silent-except"]) == 0
    with pytest.raises(SystemExit):
        main([str(bad), "--select", "no-such-rule"])


def test_lint_paths_walks_directories(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text("x = 1\n")
    (pkg / "b.py").write_text(
        "async def h():\n    import time\n    time.sleep(1)\n"
    )
    result = lint_paths([str(pkg)])
    assert [f.rule for f in result.active] == ["blocking-io-in-async"]
    assert result.exit_code == 1
