"""Per-spec mesh policy: job specs declare intra-slice parallelism, the
controller resolves it against the device flavor at submit time.

Reference anchor: per-model declaration pattern (``finetuning.py:51-104``) —
the reference could declare resources but never parallelism (SURVEY.md §2.3);
this is the TPU-native extension that lets a MoE spec request expert
parallelism (BASELINE config #4) without touching trainer code.
"""

import asyncio
import json

import pytest

from conftest import run_async as run
from finetune_controller_tpu.controller.backends.local import LocalProcessBackend
from finetune_controller_tpu.controller.devices import (
    DeviceCatalog,
    DeviceFlavor,
    FlavorQuota,
    default_catalog,
    default_mesh_for,
)
from finetune_controller_tpu.controller.examples import (
    LoRASFTArguments,
    Mixtral8x7B_MoE_LoRA,
    TinyMoETestLoRA,
)
from finetune_controller_tpu.controller.monitor import JobMonitor
from finetune_controller_tpu.controller.objectstore import LocalObjectStore
from finetune_controller_tpu.controller.schemas import DatabaseStatus, JobInput
from finetune_controller_tpu.controller.statestore import StateStore
from finetune_controller_tpu.controller.task_builder import DatasetInput, task_builder


def _active(mesh: dict) -> dict:
    return {a: v for a, v in mesh.items() if v != 1}


def test_default_policy_is_fsdp_over_slice():
    cat = default_catalog()
    v5e16 = cat.get("v5e-16")
    assert _active(default_mesh_for(v5e16)) == {"fsdp": 16}
    assert _active(default_mesh_for(v5e16, num_slices=2)) == {"dp": 2, "fsdp": 16}


def test_moe_policy_resolution():
    cat = default_catalog()
    v5p64 = cat.get("v5p-64")
    mesh = default_mesh_for(v5p64, policy=Mixtral8x7B_MoE_LoRA.mesh_policy)
    # 8 experts on ep, remaining 8 chips FSDP — Mixtral's BASELINE #4 layout
    assert _active(mesh) == {"ep": 8, "fsdp": 8}
    # every axis is pinned explicitly so the trainer's -1 defaults can't kick in
    assert mesh["fsdp"] == 8 and mesh["tp"] == 1 and mesh["sp"] == 1


def test_policy_validation_errors():
    flavor = DeviceFlavor(name="v5e-4", generation="v5e", topology="2x2",
                          hosts=1, chips_per_host=4)
    with pytest.raises(ValueError, match="not divisible"):
        default_mesh_for(flavor, policy={"ep": 3, "fsdp": -1})
    with pytest.raises(ValueError, match="at most one"):
        default_mesh_for(flavor, policy={"ep": -1, "fsdp": -1})
    with pytest.raises(ValueError, match="not in"):
        default_mesh_for(flavor, policy={"dp": 2})
    with pytest.raises(ValueError, match="cannot satisfy"):
        default_mesh_for(flavor, policy={"tp": 2})  # covers 2 of 4 chips, no fill
    # exact coverage without a fill axis is fine
    assert _active(default_mesh_for(flavor, policy={"tp": 4})) == {"tp": 4}


def _two_chip_catalog():
    return DeviceCatalog(
        flavors=[DeviceFlavor(name="cpu-2", generation="cpu", hosts=1,
                              chips_per_host=2, runtime="cpu", queue="q")],
        quotas=[FlavorQuota(flavor="cpu-2", nominal_chips=4)],
        default_flavor="cpu-2",
    )


def test_moe_job_trains_expert_parallel_e2e(tmp_path):
    """Submit the tiny MoE spec → the launched training run actually uses an
    ep>1 mesh (resolved_config.json proves it) and SUCCEEDS with metrics."""

    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        catalog = _two_chip_catalog()
        backend = LocalProcessBackend(
            tmp_path / "sandboxes", store, catalog, sync_interval_s=0.2
        )
        monitor = JobMonitor(state, store, backend, interval_s=0.1)
        await state.connect()

        spec = TinyMoETestLoRA(
            training_arguments=LoRASFTArguments(
                total_steps=3, warmup_steps=1, batch_size=2, seq_len=16, lora_rank=2
            )
        )
        job = JobInput(job_id="moe-e2e-1", user_id="u",
                       model_name="tiny-moe-test-lora", device="cpu-2",
                       arguments={"total_steps": 3})
        await task_builder(
            job, spec, DatasetInput(),
            state=state, store=store, backend=backend, catalog=catalog,
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )

        deadline = asyncio.get_event_loop().time() + 180
        while True:
            await monitor.tick()
            rec = await state.get_job("moe-e2e-1")
            if rec.status.is_final:
                break
            assert asyncio.get_event_loop().time() < deadline, rec
            await asyncio.sleep(0.3)
        assert rec.status is DatabaseStatus.SUCCEEDED, rec

        # the run's resolved config proves the ep axis was active
        resolved = json.loads(
            await store.get_bytes(rec.artifacts_uri + "/resolved_config.json")
        )
        assert resolved["mesh"]["ep"] == 2, resolved["mesh"]
        assert resolved["model"]["preset"] == "tiny-moe-test"

        metrics = await state.get_metrics("moe-e2e-1")
        assert metrics is not None and "loss" in metrics.records[0]
        await backend.close()
        await state.close()

    run(main())
