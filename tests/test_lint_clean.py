"""The repo-clean gate: `ftc-lint finetune_controller_tpu/` must exit 0.

Every finding in the package is either fixed or carries an explicit
``# ftc: ignore[rule-id] -- reason`` suppression.  A new hazard introduced
by any PR fails here, with the offending file:line in the assertion message.
"""

from pathlib import Path

from finetune_controller_tpu.analysis.engine import lint_paths

PACKAGE = Path(__file__).resolve().parent.parent / "finetune_controller_tpu"


def test_package_is_lint_clean():
    result = lint_paths([str(PACKAGE)])
    assert result.errors == [], f"unparseable files: {result.errors}"
    rendered = "\n".join(f.render() for f in result.active)
    assert result.active == [], (
        f"ftc-lint found {len(result.active)} unsuppressed finding(s) — fix "
        f"them or add a justified '# ftc: ignore[rule-id] -- reason':\n{rendered}"
    )
    assert result.exit_code == 0


def test_suppressions_all_carry_reasons():
    """CI policy (docs/static_analysis.md): a bare ignore with no
    ``-- reason`` tail is a finding hidden, not explained."""
    import re

    bare = []
    for path in sorted(PACKAGE.rglob("*.py")):
        if path.parent.name == "analysis":
            continue  # the linter's own sources DOCUMENT the syntax in prose
        for i, line in enumerate(path.read_text().splitlines(), start=1):
            m = re.search(r"#\s*ftc:\s*ignore\[[^\]]+\]\s*(.*)", line)
            if m and not m.group(1).strip().startswith("--"):
                bare.append(f"{path}:{i}")
    assert bare == [], f"suppressions without a -- reason: {bare}"
