"""Pipeline-parallel (GPipe over the ``pp`` mesh axis) tests — closes the one
parallelism row SURVEY.md §2.3 still listed as absent.

All on the 8-virtual-device CPU mesh: numerical equivalence against the
non-pipelined forward (f32, where rounding order cannot hide bugs), gradient
equivalence through the differentiated schedule, an end-to-end Trainer run on
a dp×pp mesh, and the composition guards.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finetune_controller_tpu.data import synthetic_batches
from finetune_controller_tpu.models.llama import (
    PRESETS,
    LlamaForCausalLM,
    pipelined_causal_lm_logits,
)
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.parallel.mesh import MeshSpec
from finetune_controller_tpu.parallel.pipeline import validate_pp_mesh
from finetune_controller_tpu.train import Trainer, TrainConfig


def _setup(devices8, dtype=jnp.float32, n_layers=4):
    cfg = PRESETS["tiny-test"].replace(
        lora=LoRAConfig(rank=4), n_layers=n_layers, dtype=dtype
    )
    model = LlamaForCausalLM(cfg)
    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (8, 32)
    ).astype(np.int32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, jnp.asarray(tokens))
    mesh = MeshSpec(dp=2, fsdp=1, pp=4).build(devices8)
    return cfg, model, dict(variables), jnp.asarray(tokens), mesh


def test_pipeline_forward_matches_reference(devices8):
    cfg, model, variables, tokens, mesh = _setup(devices8)
    ref = model.apply(variables, tokens)
    with mesh:
        out = pipelined_causal_lm_logits(
            cfg, variables, tokens, mesh=mesh, n_micro=4
        )
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_pipeline_uneven_microbatches_and_segments(devices8):
    cfg, model, variables, tokens, mesh = _setup(devices8)
    seg = (jnp.arange(32)[None, :] // 16).astype(jnp.int32).repeat(8, 0)
    ref = model.apply(variables, tokens, segment_ids=seg)
    with mesh:
        # M=2 < P=4: more bubble, same numbers
        out = pipelined_causal_lm_logits(
            cfg, variables, tokens, mesh=mesh, n_micro=2, segment_ids=seg
        )
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_pipeline_grads_match_reference(devices8):
    cfg, model, variables, tokens, mesh = _setup(devices8)

    def loss_pp(lora):
        v = {**variables, "lora": lora}
        with mesh:
            lg = pipelined_causal_lm_logits(cfg, v, tokens, mesh=mesh, n_micro=4)
        return (lg.astype(jnp.float32) ** 2).mean()

    def loss_ref(lora):
        v = {**variables, "lora": lora}
        return (model.apply(v, tokens).astype(jnp.float32) ** 2).mean()

    g1 = jax.grad(loss_pp)(variables["lora"])
    g2 = jax.grad(loss_ref)(variables["lora"])
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(a, b, atol=1e-4)


def test_trainer_trains_on_dp_pp_mesh(devices8, tmp_path):
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4))
    train_cfg = TrainConfig(
        mode="lora", learning_rate=2e-2, warmup_steps=2, total_steps=40,
        batch_size=8, seq_len=32, log_every=5, checkpoint_every=1000,
    )
    mesh = MeshSpec(dp=2, fsdp=1, pp=2, tp=1).build(devices8[:4])
    trainer = Trainer(cfg, train_cfg, mesh=mesh)
    batches = synthetic_batches(8, 32, cfg.vocab_size, task="increment")
    losses = []
    trainer.fit(
        batches, str(tmp_path), on_metrics=lambda s, m: losses.append(m["loss"])
    )
    assert losses[-1] < losses[0] * 0.7, f"loss did not drop: {losses}"


def test_pp_composition_guards(devices8):
    mesh = MeshSpec(dp=1, fsdp=1, pp=4, tp=2).build(devices8)
    with pytest.raises(ValueError, match="composes with dp only"):
        validate_pp_mesh(mesh)

    moe_cfg = PRESETS["tiny-moe-test"].replace(lora=LoRAConfig(rank=4))
    pp_mesh = MeshSpec(dp=2, fsdp=1, pp=4).build(devices8)
    with pytest.raises(ValueError, match="dense text"):
        Trainer(moe_cfg, TrainConfig(mode="lora"), mesh=pp_mesh)

    odd_cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=4), n_layers=3)
    with pytest.raises(ValueError, match="not divisible by pp"):
        Trainer(odd_cfg, TrainConfig(mode="lora"), mesh=pp_mesh)
